// tpu-acx: MPI compat shim over SocketTransport.
//
// Implements the MPI slice in include/compat/mpi.h so programs written
// against MPI-ACX (including the reference's own tests) run on the tpu-acx
// data plane with no MPI library present. Matches the role reference
// init.cpp:164-181 assumes of its MPI (THREAD_MULTIPLE, world comm).

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <vector>
#include <sched.h>

#include "acx/api_internal.h"
#include "acx/flightrec.h"
#include "acx/net.h"
#include "acx/trace.h"
#include "acx/tseries.h"
#include "compat/mpi.h"

namespace acx {

ApiState& GS() {
  static ApiState s;
  return s;
}

void EnsureTransport() {
  ApiState& g = GS();
  if (g.transport == nullptr) {
    g.transport = CreateTransportFromEnv();
    // Crash-path trace/flight flushes need the rank as early as possible.
    trace::SetRank(g.transport->rank());
    flight::SetRank(g.transport->rank());
  }
}

size_t DatatypeSize(int datatype) {
  switch (datatype) {
    case MPI_CHAR:
    case MPI_BYTE: return 1;
    case MPI_INT:
    case MPI_FLOAT: return 4;
    case MPI_DOUBLE:
    case MPI_INT64_T: return 8;
    default:
      std::fprintf(stderr, "tpu-acx: unknown datatype %d\n", datatype);
      std::exit(13);
  }
}

}  // namespace acx

using acx::GS;

extern "C" {

int MPI_Init_thread(int*, char***, int required, int* provided) {
  (void)required;
  acx::EnsureTransport();
  GS().mpi_inited = true;
  if (provided) *provided = MPI_THREAD_MULTIPLE;
  return MPI_SUCCESS;
}

int MPI_Init(int* argc, char*** argv) {
  int provided;
  return MPI_Init_thread(argc, argv, MPI_THREAD_SINGLE, &provided);
}

int MPI_Initialized(int* flag) {
  *flag = GS().mpi_inited ? 1 : 0;
  return MPI_SUCCESS;
}

int MPI_Finalize(void) {
  acx::ApiState& g = GS();
  if (g.transport != nullptr) {
    g.transport->Barrier(0);
    // The transport is deleted only if MPIX_Finalize already ran (it owns
    // nothing else at this point); otherwise leave it for process exit.
    if (!g.mpix_inited) {
      // The tseries atexit flusher holds a cached pointer for its tail
      // sample — detach it before the delete or it samples a dangling
      // transport.
      acx::tseries::DetachTransport();
      delete g.transport;
      g.transport = nullptr;
    }
  }
  g.mpi_inited = false;
  g.mpi_finalized = true;
  return MPI_SUCCESS;
}

int MPI_Finalized(int* flag) {
  *flag = GS().mpi_finalized ? 1 : 0;
  return MPI_SUCCESS;
}

int MPI_Query_thread(int* provided) {
  *provided = MPI_THREAD_MULTIPLE;
  return MPI_SUCCESS;
}

int MPI_Abort(MPI_Comm, int errorcode) {
  if (GS().transport != nullptr) GS().transport->Abort(errorcode);
  std::exit(errorcode);
}

int MPI_Comm_rank(MPI_Comm, int* rank) {
  acx::EnsureTransport();
  *rank = GS().transport->rank();
  return MPI_SUCCESS;
}

int MPI_Comm_size(MPI_Comm, int* size) {
  acx::EnsureTransport();
  *size = GS().transport->size();
  return MPI_SUCCESS;
}

int MPI_Type_size(MPI_Datatype datatype, int* size) {
  *size = static_cast<int>(acx::DatatypeSize(datatype));
  return MPI_SUCCESS;
}

int MPI_Barrier(MPI_Comm comm) {
  acx::EnsureTransport();
  // barrier_enter/exit instants are the cross-rank clock anchors
  // tools/acx_trace_merge.py aligns per-rank steady clocks on: every rank
  // leaves the same barrier at (nearly) the same wall instant.
  ACX_TRACE_EVENT("barrier_enter", -1);
  ACX_FLIGHT(kBarrierEnter, -1, -1, comm, 0, 0);
  GS().transport->Barrier(comm);
  ACX_TRACE_EVENT("barrier_exit", -1);
  ACX_FLIGHT(kBarrierExit, -1, -1, comm, 0, 0);
  return MPI_SUCCESS;
}

}  // extern "C"

namespace {

// Reserved matching context for shim-level collectives so their frames can
// never collide with user point-to-point tags (the transport reserves -2
// for its own control frames and -3 for rendezvous fallback).
constexpr int kCollCtx = -4;

void BlockingSend(const void* buf, size_t bytes, int dst, int tag) {
  std::unique_ptr<acx::Ticket> t(
      GS().transport->Isend(buf, bytes, dst, tag, kCollCtx));
  acx::Status st;
  while (!t->Test(&st)) sched_yield();
}

void BlockingRecv(void* buf, size_t bytes, int src, int tag) {
  std::unique_ptr<acx::Ticket> t(
      GS().transport->Irecv(buf, bytes, src, tag, kCollCtx));
  acx::Status st;
  while (!t->Test(&st)) sched_yield();
}

template <typename T>
void ReduceInto(T* acc, const T* in, int count, MPI_Op op) {
  for (int i = 0; i < count; i++) {
    switch (op) {
      case MPI_MAX: acc[i] = acc[i] > in[i] ? acc[i] : in[i]; break;
      case MPI_MIN: acc[i] = acc[i] < in[i] ? acc[i] : in[i]; break;
      default: acc[i] += in[i]; break;
    }
  }
}

// Gather-to-0 / reduce / broadcast over the reserved collective context —
// the same scheme as the transport's AllreduceInt, typed over T.
template <typename T>
void AllreduceT(T* data, int count, MPI_Op op) {
  acx::Transport* tr = GS().transport;
  const size_t nb = sizeof(T) * static_cast<size_t>(count);
  if (tr->rank() == 0) {
    std::vector<T> tmp(count);
    for (int p = 1; p < tr->size(); p++) {
      BlockingRecv(tmp.data(), nb, p, 0);
      ReduceInto(data, tmp.data(), count, op);
    }
    for (int p = 1; p < tr->size(); p++) BlockingSend(data, nb, p, 1);
  } else {
    BlockingSend(data, nb, 0, 0);
    BlockingRecv(data, nb, 0, 1);
  }
}

}  // namespace

extern "C" {

int MPI_Allreduce(const void* sendbuf, void* recvbuf, int count,
                  MPI_Datatype datatype, MPI_Op op, MPI_Comm comm) {
  acx::EnsureTransport();
  switch (datatype) {  // validate BEFORE DatatypeSize (which exits on bad ids)
    case MPI_INT: case MPI_CHAR: case MPI_BYTE:
    case MPI_INT64_T: case MPI_FLOAT: case MPI_DOUBLE:
      break;
    default:
      std::fprintf(stderr, "tpu-acx MPI shim: Allreduce datatype %d\n",
                   datatype);
      return MPI_ERR_OTHER;
  }
  if (sendbuf != MPI_IN_PLACE)
    std::memcpy(recvbuf, sendbuf, acx::DatatypeSize(datatype) * count);
  switch (datatype) {
    case MPI_INT:  // transport-native fast path
      GS().transport->AllreduceInt(static_cast<int32_t*>(recvbuf), count, op,
                                   comm);
      break;
    case MPI_CHAR:
      AllreduceT(static_cast<int8_t*>(recvbuf), count, op);
      break;
    case MPI_BYTE:
      AllreduceT(static_cast<uint8_t*>(recvbuf), count, op);
      break;
    case MPI_INT64_T:
      AllreduceT(static_cast<int64_t*>(recvbuf), count, op);
      break;
    case MPI_FLOAT:
      AllreduceT(static_cast<float*>(recvbuf), count, op);
      break;
    case MPI_DOUBLE:
      AllreduceT(static_cast<double*>(recvbuf), count, op);
      break;
  }
  return MPI_SUCCESS;
}

int MPI_Send(const void* buf, int count, MPI_Datatype datatype, int dest,
             int tag, MPI_Comm comm) {
  acx::EnsureTransport();
  std::unique_ptr<acx::Ticket> t(GS().transport->Isend(
      buf, acx::DatatypeSize(datatype) * count, dest, tag, comm));
  acx::Status st;
  while (!t->Test(&st)) sched_yield();
  return MPI_SUCCESS;
}

int MPI_Recv(void* buf, int count, MPI_Datatype datatype, int source, int tag,
             MPI_Comm comm, MPI_Status* status) {
  acx::EnsureTransport();
  std::unique_ptr<acx::Ticket> t(GS().transport->Irecv(
      buf, acx::DatatypeSize(datatype) * count, source, tag, comm));
  acx::Status st;
  while (!t->Test(&st)) sched_yield();
  acx::CopyStatus(st, status);
  return MPI_SUCCESS;
}

}  // extern "C"
