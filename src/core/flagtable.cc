#include "acx/state.h"

#include <cstdlib>

#include "acx/transport.h"

namespace acx {

const char* FlagName(int32_t f) {
  switch (f) {
    case kAvailable: return "AVAILABLE";
    case kReserved: return "RESERVED";
    case kPending: return "PENDING";
    case kIssued: return "ISSUED";
    case kCompleted: return "COMPLETED";
    case kCleanup: return "CLEANUP";
    case kRecovering: return "RECOVERING";
    default: return "<invalid>";
  }
}

FlagTable::FlagTable(size_t n)
    : n_(n),
      flags_(new std::atomic<int32_t>[n]),
      ops_(new Op[n]) {
  for (size_t i = 0; i < n_; i++)
    flags_[i].store(kAvailable, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
}

FlagTable::~FlagTable() {
  // Tickets and owners on still-live slots (teardown with in-flight ops) are
  // reclaimed here so destruction is leak-safe. `owner` is malloc'd by the
  // API layer by contract (see Op::owner in state.h).
  for (size_t i = 0; i < n_; i++) {
    delete ops_[i].ticket;
    std::free(ops_[i].owner);
  }
}

int FlagTable::Allocate() {
  // Lowest-free-slot allocation (not a rotating hint): keeps live slots
  // packed at the bottom of the table so the proxy's sweep only has to walk
  // [0, watermark) — with K concurrent ops that's a K-entry sweep instead of
  // O(nflags), which is what makes caller-driven inline progress cheap
  // enough to run on the enqueue path. CAS arbitrates concurrent allocators
  // (fixes the reference's single-thread-only FIXME, triggered.cpp:40-44).
  for (size_t i = 0; i < n_; i++) {
    int32_t expect = kAvailable;
    if (flags_[i].compare_exchange_strong(expect, kReserved,
                                          std::memory_order_acq_rel,
                                          std::memory_order_relaxed)) {
      active.fetch_add(1, std::memory_order_relaxed);
      // Raise the sweep watermark to cover this slot (monotonic max).
      size_t w = watermark_.load(std::memory_order_relaxed);
      while (w < i + 1 &&
             !watermark_.compare_exchange_weak(w, i + 1,
                                               std::memory_order_release,
                                               std::memory_order_relaxed)) {
      }
      return static_cast<int>(i);
    }
  }
  return -1;
}

void FlagTable::Free(int idx) {
  // Release any completion ticket still attached to the op so that Free is
  // leak-safe from every path (proxy CLEANUP, host Wait, graph teardown).
  // `owner` (the public request object) is deliberately NOT released here:
  // its lifetime belongs to whichever side consumed the op.
  delete ops_[idx].ticket;
  ops_[idx].Reset();
  flags_[idx].store(kAvailable, std::memory_order_release);
  active.fetch_sub(1, std::memory_order_relaxed);
  // Decay the sweep bound when the top of the live range frees, so sweep
  // cost returns to O(live ops) after a burst drains instead of staying at
  // O(peak concurrency) forever.
  size_t w = watermark_.load(std::memory_order_acquire);
  if (static_cast<size_t>(idx) + 1 == w) {
    size_t nw = static_cast<size_t>(idx);
    while (nw > 0 &&
           flags_[nw - 1].load(std::memory_order_acquire) == kAvailable)
      nw--;
    if (watermark_.compare_exchange_strong(w, nw, std::memory_order_acq_rel,
                                           std::memory_order_relaxed)) {
      // Close the decay/Allocate race: a concurrent Allocate may have
      // reserved a slot in [nw, w) after our downward scan read it as
      // AVAILABLE but before the CAS — and, having seen the old watermark
      // cover it, skipped its own raise. Re-verify the range and CAS-max
      // the watermark back over any live slot found.
      for (size_t j = w; j > nw; j--) {
        if (flags_[j - 1].load(std::memory_order_acquire) != kAvailable) {
          size_t cur = watermark_.load(std::memory_order_relaxed);
          while (cur < j &&
                 !watermark_.compare_exchange_weak(
                     cur, j, std::memory_order_release,
                     std::memory_order_relaxed)) {
          }
          break;
        }
      }
    }
  }
}

}  // namespace acx
