// tpu-acx: fleet membership table (DESIGN.md §12). See acx/membership.h for
// the model; this file is deliberately boring — a mutex-guarded state vector
// plus an atomic epoch, so the transport can feed it from under its own lock
// and the C API can snapshot it from any thread.

#include "acx/membership.h"

namespace acx {

Membership& Fleet() {
  static Membership m;
  return m;
}

void Membership::Reset(int size, int self_rank) {
  std::lock_guard<std::mutex> lk(mu_);
  state_.assign(size < 0 ? 0 : static_cast<size_t>(size),
                MemberState::kMemberActive);
  self_ = self_rank;
  joins_ = leaves_ = deaths_ = 0;
  epoch_.store(1, std::memory_order_release);
}

int Membership::size() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int>(state_.size());
}

MemberState Membership::state(int rank) const {
  std::lock_guard<std::mutex> lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size()))
    return MemberState::kMemberUnknown;
  return state_[rank];
}

uint64_t Membership::BumpLocked() {
  // fetch_add under mu_ keeps the bump atomic with the state write while
  // epoch() stays a lock-free read for pollers.
  return epoch_.fetch_add(1, std::memory_order_acq_rel) + 1;
}

uint64_t Membership::OnJoin(int rank) {
  std::lock_guard<std::mutex> lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size()))
    return epoch_.load(std::memory_order_relaxed);
  if (state_[rank] == MemberState::kMemberActive)
    return epoch_.load(std::memory_order_relaxed);
  state_[rank] = MemberState::kMemberActive;
  joins_++;
  return BumpLocked();
}

uint64_t Membership::OnLeave(int rank) {
  std::lock_guard<std::mutex> lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size()))
    return epoch_.load(std::memory_order_relaxed);
  if (state_[rank] == MemberState::kMemberLeft ||
      state_[rank] == MemberState::kMemberDead)
    return epoch_.load(std::memory_order_relaxed);
  state_[rank] = MemberState::kMemberLeft;
  leaves_++;
  return BumpLocked();
}

uint64_t Membership::OnDeath(int rank) {
  std::lock_guard<std::mutex> lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size()))
    return epoch_.load(std::memory_order_relaxed);
  // A graceful LEFT verdict is final: the EOF that trails a clean leave
  // must not re-classify the slot as crashed.
  if (state_[rank] == MemberState::kMemberLeft ||
      state_[rank] == MemberState::kMemberDead)
    return epoch_.load(std::memory_order_relaxed);
  state_[rank] = MemberState::kMemberDead;
  deaths_++;
  return BumpLocked();
}

void Membership::OnDraining(int rank) {
  std::lock_guard<std::mutex> lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size())) return;
  if (state_[rank] == MemberState::kMemberActive)
    state_[rank] = MemberState::kMemberDraining;
}

void Membership::AdoptEpoch(uint64_t remote_epoch) {
  uint64_t cur = epoch_.load(std::memory_order_acquire);
  while (remote_epoch > cur &&
         !epoch_.compare_exchange_weak(cur, remote_epoch,
                                       std::memory_order_acq_rel,
                                       std::memory_order_acquire)) {
  }
}

uint64_t Membership::AdoptView(int rank, MemberState st,
                               uint64_t remote_epoch) {
  AdoptEpoch(remote_epoch);
  switch (st) {
    case MemberState::kMemberActive:
      return OnJoin(rank);
    case MemberState::kMemberLeft:
      return OnLeave(rank);
    case MemberState::kMemberDead:
      return OnDeath(rank);
    case MemberState::kMemberDraining:
      OnDraining(rank);
      return epoch_.load(std::memory_order_acquire);
    default:
      return epoch_.load(std::memory_order_acquire);
  }
}

FleetStats Membership::stats() const {
  std::lock_guard<std::mutex> lk(mu_);
  FleetStats s;
  s.epoch = epoch_.load(std::memory_order_relaxed);
  s.joins = joins_;
  s.leaves = leaves_;
  s.deaths = deaths_;
  for (MemberState st : state_)
    if (st == MemberState::kMemberActive) s.active++;
  return s;
}

int Membership::View(int32_t* out, int cap) const {
  std::lock_guard<std::mutex> lk(mu_);
  const int n = static_cast<int>(state_.size());
  for (int i = 0; i < n && i < cap; i++)
    out[i] = static_cast<int32_t>(state_[i]);
  return n;
}

}  // namespace acx
