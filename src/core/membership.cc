// tpu-acx: fleet membership table (DESIGN.md §12). See acx/membership.h for
// the model; this file is deliberately boring — a mutex-guarded state vector
// plus an atomic epoch, so the transport can feed it from under its own lock
// and the C API can snapshot it from any thread. The join/leave/death
// tallies and the active count are atomic mirrors of the vector, written
// only under mu_, so stats()/size() stay lock-free for the crash-flush path
// (DESIGN.md §18, signal-path contract).

#include "acx/membership.h"

namespace acx {

Membership& Fleet() {
  static Membership m;
  return m;
}

void Membership::Reset(int size, int self_rank) {
  MutexLock lk(mu_);
  const size_t n = size < 0 ? 0 : static_cast<size_t>(size);
  state_.assign(n, MemberState::kMemberActive);
  self_ = self_rank;
  nslots_.store(static_cast<int>(n), std::memory_order_relaxed);
  joins_.store(0, std::memory_order_relaxed);
  leaves_.store(0, std::memory_order_relaxed);
  deaths_.store(0, std::memory_order_relaxed);
  active_.store(n, std::memory_order_relaxed);
  epoch_.store(1, std::memory_order_release);
}

int Membership::size() const {
  return nslots_.load(std::memory_order_relaxed);
}

MemberState Membership::state(int rank) const {
  MutexLock lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size()))
    return MemberState::kMemberUnknown;
  return state_[rank];
}

uint64_t Membership::BumpLocked() {
  // fetch_add under mu_ keeps the bump atomic with the state write while
  // epoch() stays a lock-free read for pollers.
  return epoch_.fetch_add(1, std::memory_order_acq_rel) + 1;
}

uint64_t Membership::OnJoin(int rank) {
  MutexLock lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size()))
    return epoch_.load(std::memory_order_relaxed);
  if (state_[rank] == MemberState::kMemberActive)
    return epoch_.load(std::memory_order_relaxed);
  state_[rank] = MemberState::kMemberActive;
  joins_.fetch_add(1, std::memory_order_relaxed);
  active_.fetch_add(1, std::memory_order_relaxed);
  return BumpLocked();
}

uint64_t Membership::OnLeave(int rank) {
  MutexLock lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size()))
    return epoch_.load(std::memory_order_relaxed);
  if (state_[rank] == MemberState::kMemberLeft ||
      state_[rank] == MemberState::kMemberDead)
    return epoch_.load(std::memory_order_relaxed);
  if (state_[rank] == MemberState::kMemberActive)
    active_.fetch_sub(1, std::memory_order_relaxed);
  state_[rank] = MemberState::kMemberLeft;
  leaves_.fetch_add(1, std::memory_order_relaxed);
  return BumpLocked();
}

uint64_t Membership::OnDeath(int rank) {
  MutexLock lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size()))
    return epoch_.load(std::memory_order_relaxed);
  // A graceful LEFT verdict is final: the EOF that trails a clean leave
  // must not re-classify the slot as crashed.
  if (state_[rank] == MemberState::kMemberLeft ||
      state_[rank] == MemberState::kMemberDead)
    return epoch_.load(std::memory_order_relaxed);
  if (state_[rank] == MemberState::kMemberActive)
    active_.fetch_sub(1, std::memory_order_relaxed);
  state_[rank] = MemberState::kMemberDead;
  deaths_.fetch_add(1, std::memory_order_relaxed);
  return BumpLocked();
}

void Membership::OnDraining(int rank) {
  MutexLock lk(mu_);
  if (rank < 0 || rank >= static_cast<int>(state_.size())) return;
  if (state_[rank] == MemberState::kMemberActive) {
    state_[rank] = MemberState::kMemberDraining;
    active_.fetch_sub(1, std::memory_order_relaxed);
  }
}

void Membership::AdoptEpoch(uint64_t remote_epoch) {
  uint64_t cur = epoch_.load(std::memory_order_acquire);
  while (remote_epoch > cur &&
         !epoch_.compare_exchange_weak(cur, remote_epoch,
                                       std::memory_order_acq_rel,
                                       std::memory_order_acquire)) {
  }
}

uint64_t Membership::AdoptView(int rank, MemberState st,
                               uint64_t remote_epoch) {
  AdoptEpoch(remote_epoch);
  switch (st) {
    case MemberState::kMemberActive:
      return OnJoin(rank);
    case MemberState::kMemberLeft:
      return OnLeave(rank);
    case MemberState::kMemberDead:
      return OnDeath(rank);
    case MemberState::kMemberDraining:
      OnDraining(rank);
      return epoch_.load(std::memory_order_acquire);
    default:
      return epoch_.load(std::memory_order_acquire);
  }
}

FleetStats Membership::stats() const {
  // Lock-free by contract: reachable from the crash-flush tail via the
  // tseries refresh hook. The mirrors are each individually consistent;
  // a snapshot racing a transition may be one transition stale, which is
  // fine for an observability read.
  FleetStats s;
  s.epoch = epoch_.load(std::memory_order_relaxed);
  s.joins = joins_.load(std::memory_order_relaxed);
  s.leaves = leaves_.load(std::memory_order_relaxed);
  s.deaths = deaths_.load(std::memory_order_relaxed);
  s.active = active_.load(std::memory_order_relaxed);
  return s;
}

int Membership::View(int32_t* out, int cap) const {
  MutexLock lk(mu_);
  const int n = static_cast<int>(state_.size());
  for (int i = 0; i < n && i < cap; i++)
    out[i] = static_cast<int32_t>(state_[i]);
  return n;
}

}  // namespace acx
