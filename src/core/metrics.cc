// Thread-safety: the registry is mutex-free by design — every mutation is
// a relaxed atomic (acx/metrics.h) — so the clang thread-safety pass
// (acx/thread_annotations.h, DESIGN.md §18) has nothing to annotate here;
// this note is the annotation. Keep it that way: the crash-flush tail
// (tseries FlushBestEffort) reads every counter and histogram, and the
// signal-path audit (tools/acx_audit.py, rule 5) will flag any lock or
// allocation a future change introduces on that path.

#include "acx/metrics.h"

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "acx/fault.h"  // NowNs

namespace acx {
namespace metrics {
namespace {

// Keep in sync with enum Counter / enum Hist (acx/metrics.h). The arrays
// are deliberately unsized: the static_asserts below turn a counter added
// without a name (or vice versa) into a build error instead of an
// out-of-bounds read at snapshot time.
const char* const kCounterName[] = {
    "triggers",        "waits",          "ops_isend",      "ops_irecv",
    "ops_pready",      "ops_parrived",   "bytes_sent",     "bytes_recv",
    "retries",         "timeouts",       "faults_injected", "faults_wire",
    "hb_sent",         "hb_recv",        "hb_misses",      "peers_dead",
    "slot_hwm",
    "proxy_sweeps",    "ops_issued",     "ops_completed",  "slots_reclaimed",
    "proxy_busy_ns",   "proxy_idle_ns",  "reconnects",     "frames_replayed",
    "crc_rejects",     "naks_sent",      "drained_slots",  "fleet_epoch",
    "fleet_joins",     "fleet_leaves",   "fleet_deaths",
    "preadys_published", "parriveds_observed",
    "pages_free",      "pages_shared",   "prefix_hits",
    "prefix_evictions", "preemptions",
};

const char* const kHistName[] = {
    "trigger_to_issue_ns",
    "issue_to_complete_ns",
    "complete_to_wait_ns",
    "proxy_sweep_ns",
    "wire_queue_ns",
    "wire_transit_ns",
};

static_assert(sizeof(kCounterName) / sizeof(kCounterName[0]) == kNumCounters,
              "kCounterName out of sync with enum Counter (acx/metrics.h)");
static_assert(sizeof(kHistName) / sizeof(kHistName[0]) == kNumHists,
              "kHistName out of sync with enum Hist (acx/metrics.h)");

struct HistData {
  std::atomic<uint64_t> count{0};
  std::atomic<uint64_t> sum{0};
  std::atomic<uint64_t> buckets[kNumBuckets] = {};
};

// Per-slot lifecycle stamps. Stamp writes are relaxed: the flag-table
// protocol's release/release stores already order the enqueuer's trigger
// stamp before the proxy's issue read (same contract as Op fields).
struct Stamp {
  std::atomic<uint64_t> trigger{0};
  std::atomic<uint64_t> issue{0};
  std::atomic<uint64_t> complete{0};
};

struct State {
  std::atomic<uint64_t> counters[kNumCounters] = {};
  HistData hists[kNumHists];
  Stamp* stamps = nullptr;
  size_t nstamps = 0;
  const char* dump_path = nullptr;  // nullptr = snapshot-only (ACX_METRICS=1)
};

State& S() {
  static State* s = [] {
    State* st = new State;
    // Stamp capacity mirrors the flag table size knob (MPIX_Init).
    size_t n = 4096;
    const char* e = std::getenv("ACX_NFLAGS");
    if (e == nullptr) e = std::getenv("MPIACX_NFLAGS");
    if (e != nullptr) {
      const long v = std::atol(e);
      if (v > 0) n = static_cast<size_t>(v);
    }
    st->nstamps = n;
    st->stamps = new Stamp[n];
    const char* p = std::getenv("ACX_METRICS");
    if (p != nullptr && p[0] != '\0' && std::strcmp(p, "1") != 0 &&
        std::strcmp(p, "0") != 0)
      st->dump_path = p;
    return st;
  }();
  return *s;
}

// Bucket i>0 holds [2^(i-1), 2^i) ns; bucket 0 holds exactly 0.
int BucketOf(uint64_t ns) {
  int b = 0;
  while (ns != 0 && b < kNumBuckets - 1) {
    ns >>= 1;
    b++;
  }
  return b;
}

Stamp* StampFor(int64_t slot) {
  State& s = S();
  if (slot < 0 || static_cast<size_t>(slot) >= s.nstamps) return nullptr;
  return &s.stamps[slot];
}

std::string SnapshotString() {
  State& s = S();
  std::string out;
  out.reserve(4096);
  out += "{\"enabled\":";
  out += Enabled() ? "true" : "false";
  out += ",\"counters\":{";
  char buf[64];
  for (int c = 0; c < kNumCounters; c++) {
    std::snprintf(buf, sizeof buf, "%s\"%s\":%llu", c ? "," : "",
                  kCounterName[c],
                  (unsigned long long)s.counters[c].load(
                      std::memory_order_relaxed));
    out += buf;
  }
  out += "},\"histograms\":{";
  for (int h = 0; h < kNumHists; h++) {
    const HistData& hd = s.hists[h];
    std::snprintf(buf, sizeof buf, "%s\"%s\":{\"unit\":\"ns\",", h ? "," : "",
                  kHistName[h]);
    out += buf;
    std::snprintf(buf, sizeof buf, "\"count\":%llu,\"sum\":%llu,\"buckets\":[",
                  (unsigned long long)hd.count.load(std::memory_order_relaxed),
                  (unsigned long long)hd.sum.load(std::memory_order_relaxed));
    out += buf;
    for (int b = 0; b < kNumBuckets; b++) {
      std::snprintf(buf, sizeof buf, "%s%llu", b ? "," : "",
                    (unsigned long long)hd.buckets[b].load(
                        std::memory_order_relaxed));
      out += buf;
    }
    out += "]}";
  }
  // Schema tail: which counter entries are gauges (absolute readings —
  // never summed or differenced), plus run-lifetime derived rates.
  out += "},\"gauges\":[\"fleet_epoch\",\"slot_hwm\",\"pages_free\","
         "\"pages_shared\"],\"derived\":{";
  const uint64_t busy =
      s.counters[kProxyBusyNs].load(std::memory_order_relaxed);
  const uint64_t idle =
      s.counters[kProxyIdleNs].load(std::memory_order_relaxed);
  std::snprintf(buf, sizeof buf, "\"proxy_util_pct\":%.2f",
                busy + idle > 0
                    ? 100.0 * static_cast<double>(busy) /
                          static_cast<double>(busy + idle)
                    : 0.0);
  out += buf;
  out += "}}";
  return out;
}

// Prometheus text exposition (version 0.0.4) of the same registry the
// JSON snapshot serializes, so a standard scraper can read the fleet
// without bespoke tooling (docs/DESIGN.md §20). Every counter/gauge name
// round-trips as "acx_<name>"; gauges (IsGauge) get TYPE gauge, the rest
// TYPE counter. Histograms become the native cumulative-bucket series:
// bucket 0 holds exactly 0 ns (le="0") and bucket i>0 holds
// [2^(i-1), 2^i) ns — values are integer nanoseconds, so the inclusive
// Prometheus upper bound is 2^i - 1 — with the saturating top bucket
// mapped to le="+Inf".
std::string PromString() {
  State& s = S();
  std::string out;
  out.reserve(16384);
  char buf[96];
  for (int c = 0; c < kNumCounters; c++) {
    const bool g = IsGauge(static_cast<Counter>(c));
    std::snprintf(buf, sizeof buf, "# TYPE acx_%s %s\n", kCounterName[c],
                  g ? "gauge" : "counter");
    out += buf;
    std::snprintf(buf, sizeof buf, "acx_%s %llu\n", kCounterName[c],
                  (unsigned long long)s.counters[c].load(
                      std::memory_order_relaxed));
    out += buf;
  }
  for (int h = 0; h < kNumHists; h++) {
    const HistData& hd = s.hists[h];
    std::snprintf(buf, sizeof buf, "# TYPE acx_%s histogram\n", kHistName[h]);
    out += buf;
    uint64_t cum = 0;
    for (int b = 0; b < kNumBuckets; b++) {
      cum += hd.buckets[b].load(std::memory_order_relaxed);
      if (b == kNumBuckets - 1) {
        std::snprintf(buf, sizeof buf, "acx_%s_bucket{le=\"+Inf\"} %llu\n",
                      kHistName[h], (unsigned long long)cum);
      } else if (b == 0) {
        std::snprintf(buf, sizeof buf, "acx_%s_bucket{le=\"0\"} %llu\n",
                      kHistName[h], (unsigned long long)cum);
      } else {
        std::snprintf(buf, sizeof buf, "acx_%s_bucket{le=\"%llu\"} %llu\n",
                      kHistName[h],
                      (unsigned long long)((uint64_t{1} << b) - 1),
                      (unsigned long long)cum);
      }
      out += buf;
    }
    std::snprintf(buf, sizeof buf, "acx_%s_sum %llu\nacx_%s_count %llu\n",
                  kHistName[h],
                  (unsigned long long)hd.sum.load(std::memory_order_relaxed),
                  kHistName[h],
                  (unsigned long long)hd.count.load(std::memory_order_relaxed));
    out += buf;
  }
  const uint64_t busy =
      s.counters[kProxyBusyNs].load(std::memory_order_relaxed);
  const uint64_t idle =
      s.counters[kProxyIdleNs].load(std::memory_order_relaxed);
  std::snprintf(buf, sizeof buf,
                "# TYPE acx_proxy_util_pct gauge\nacx_proxy_util_pct %.2f\n",
                busy + idle > 0 ? 100.0 * static_cast<double>(busy) /
                                      static_cast<double>(busy + idle)
                                : 0.0);
  out += buf;
  return out;
}

}  // namespace

bool Enabled() {
  static const bool on = [] {
    const auto set = [](const char* name) {
      const char* p = std::getenv(name);
      return p != nullptr && p[0] != '\0' && std::strcmp(p, "0") != 0;
    };
    // ACX_TSERIES implies collection: the periodic sampler (acx/tseries.h)
    // reads this registry, so arming it without ACX_METRICS must still
    // turn the counters on. The finalize dump stays ACX_METRICS-gated.
    return set("ACX_METRICS") || set("ACX_TSERIES");
  }();
  return on;
}

const char* CounterName(Counter c) {
  return c >= 0 && c < kNumCounters ? kCounterName[c] : "?";
}

const char* HistName(Hist h) {
  return h >= 0 && h < kNumHists ? kHistName[h] : "?";
}

uint64_t Value(Counter c) {
  return S().counters[c].load(std::memory_order_relaxed);
}

void HistRead(Hist h, uint64_t* count, uint64_t* sum, uint64_t* buckets) {
  const HistData& hd = S().hists[h];
  if (count != nullptr) *count = hd.count.load(std::memory_order_relaxed);
  if (sum != nullptr) *sum = hd.sum.load(std::memory_order_relaxed);
  if (buckets != nullptr)
    for (int b = 0; b < kNumBuckets; b++)
      buckets[b] = hd.buckets[b].load(std::memory_order_relaxed);
}

bool IsGauge(Counter c) {
  return c == kFleetEpoch || c == kSlotHighWater || c == kPagesFree ||
         c == kPagesShared;
}

void Add(Counter c, uint64_t v) {
  S().counters[c].fetch_add(v, std::memory_order_relaxed);
}

void Set(Counter c, uint64_t v) {
  S().counters[c].store(v, std::memory_order_relaxed);
}

void MaxGauge(Counter c, uint64_t v) {
  std::atomic<uint64_t>& g = S().counters[c];
  uint64_t cur = g.load(std::memory_order_relaxed);
  while (v > cur &&
         !g.compare_exchange_weak(cur, v, std::memory_order_relaxed)) {
  }
}

void Observe(Hist h, uint64_t ns) {
  HistData& hd = S().hists[h];
  hd.count.fetch_add(1, std::memory_order_relaxed);
  hd.sum.fetch_add(ns, std::memory_order_relaxed);
  hd.buckets[BucketOf(ns)].fetch_add(1, std::memory_order_relaxed);
}

void MarkTrigger(int64_t slot) {
  Add(kTriggers, 1);
  if (Stamp* st = StampFor(slot))
    st->trigger.store(NowNs(), std::memory_order_relaxed);
}

void MarkIssue(int64_t slot, bool is_send, uint64_t bytes) {
  Add(is_send ? kOpsIsend : kOpsIrecv, 1);
  Add(is_send ? kBytesSent : kBytesRecv, bytes);
  const uint64_t now = NowNs();
  if (Stamp* st = StampFor(slot)) {
    // exchange(0): a retry re-issues the same slot — the trigger segment
    // must be recorded once, against the first post.
    const uint64_t t = st->trigger.exchange(0, std::memory_order_relaxed);
    if (t != 0 && now > t) Observe(kTriggerToIssue, now - t);
    st->issue.store(now, std::memory_order_relaxed);
  }
}

void MarkComplete(int64_t slot) {
  const uint64_t now = NowNs();
  if (Stamp* st = StampFor(slot)) {
    const uint64_t t = st->issue.exchange(0, std::memory_order_relaxed);
    if (t != 0 && now > t) Observe(kIssueToComplete, now - t);
    st->complete.store(now, std::memory_order_relaxed);
  }
}

void MarkWait(int64_t slot) {
  Add(kWaits, 1);
  const uint64_t now = NowNs();
  if (Stamp* st = StampFor(slot)) {
    const uint64_t t = st->complete.exchange(0, std::memory_order_relaxed);
    if (t != 0 && now > t) Observe(kCompleteToWait, now - t);
  }
}

int SnapshotJson(char* buf, int cap) {
  const std::string s = SnapshotString();
  if (buf != nullptr && cap > 0) {
    const size_t n =
        s.size() < static_cast<size_t>(cap) - 1 ? s.size() : cap - 1;
    std::memcpy(buf, s.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int>(s.size());
}

int PromText(char* buf, int cap) {
  const std::string s = PromString();
  if (buf != nullptr && cap > 0) {
    const size_t n =
        s.size() < static_cast<size_t>(cap) - 1 ? s.size() : cap - 1;
    std::memcpy(buf, s.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int>(s.size());
}

int DumpJson(const char* path) {
  FILE* f = std::fopen(path, "w");
  if (f == nullptr) return 1;
  const std::string s = SnapshotString();
  std::fwrite(s.data(), 1, s.size(), f);
  std::fputc('\n', f);
  std::fclose(f);
  return 0;
}

void FlushAtFinalize(int rank) {
  State& s = S();
  if (!Enabled() || s.dump_path == nullptr) return;
  const std::string fn = std::string(s.dump_path) + ".rank" +
                         std::to_string(rank) + ".metrics.json";
  if (DumpJson(fn.c_str()) != 0)
    std::fprintf(stderr, "tpu-acx: ACX_METRICS: cannot write %s\n",
                 fn.c_str());
}

}  // namespace metrics
}  // namespace acx
