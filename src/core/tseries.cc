#include "acx/tseries.h"

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

#include "acx/fault.h"  // NowNs
#include "acx/membership.h"
#include "acx/metrics.h"
#include "acx/thread_annotations.h"
#include "acx/trace.h"
#include "acx/transport.h"

namespace acx {
namespace tseries {
namespace {

struct Config {
  bool on = false;
  const char* prefix = nullptr;
  uint64_t interval_ns = 0;
};

const Config& cfg() {
  static const Config c = [] {
    Config c;
    const char* p = std::getenv("ACX_TSERIES");
    if (p == nullptr || p[0] == '\0' || std::strcmp(p, "0") == 0) return c;
    uint64_t ms = 250;
    const char* iv = std::getenv("ACX_TSERIES_INTERVAL_MS");
    if (iv != nullptr && iv[0] != '\0') {
      char* end = nullptr;
      const unsigned long long v = std::strtoull(iv, &end, 10);
      // strtoull silently wraps a leading '-' into a huge value; a
      // negative interval is a config error like any other.
      if (end == iv || *end != '\0' || v == 0 ||
          std::strchr(iv, '-') != nullptr) {
        // A zero or unparseable interval is a config error, not a "sample
        // as fast as possible" request — refuse loudly rather than spin.
        std::fprintf(stderr,
                     "tpu-acx: ACX_TSERIES_INTERVAL_MS=\"%s\" invalid; "
                     "sampling disabled\n",
                     iv);
        return c;
      }
      ms = static_cast<uint64_t>(v);
    }
    c.on = true;
    c.prefix = p;
    c.interval_ns = ms * 1000000ull;
    return c;
  }();
  return c;
}

struct State {
  Mutex mu;  // serializes sampling + file writes
  FILE* f ACX_GUARDED_BY(mu) = nullptr;
  // Latch: don't retry / re-warn every interval.
  bool open_failed ACX_GUARDED_BY(mu) = false;
  // Delta samples written (init line is seq "0").
  uint64_t seq ACX_GUARDED_BY(mu) = 0;
  uint64_t prev_counters[metrics::kNumCounters] ACX_GUARDED_BY(mu) = {};
  uint64_t prev_hcount[metrics::kNumHists] ACX_GUARDED_BY(mu) = {};
  uint64_t prev_hsum[metrics::kNumHists] ACX_GUARDED_BY(mu) = {};
  uint64_t prev_hbuckets[metrics::kNumHists][metrics::kNumBuckets]
      ACX_GUARDED_BY(mu) = {};
  // Most recent full sample line, for LiveJson.
  std::string live ACX_GUARDED_BY(mu);

  Mutex ann_mu;
  // Last Annotate fragment, "" = none.
  std::string annotation ACX_GUARDED_BY(ann_mu);
};

State& S() {
  static State* s = new State;
  return *s;
}

std::atomic<int> g_rank{-1};
std::atomic<uint64_t> g_next_due{0};
std::atomic<uint64_t> g_samples{0};
std::atomic<Transport*> g_transport{nullptr};
std::atomic<void (*)()> g_refresh{nullptr};

int RankForFile() {
  int r = g_rank.load(std::memory_order_relaxed);
  if (r >= 0) return r;
  return trace::EnvRankOr(0);
}

uint64_t WallMs() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::milliseconds>(
          std::chrono::system_clock::now().time_since_epoch())
          .count());
}

void AppendU64(std::string* out, const char* key, uint64_t v) {
  char buf[64];
  std::snprintf(buf, sizeof buf, "\"%s\":%llu", key,
                (unsigned long long)v);
  *out += buf;
}

// Links section: cumulative absolute wire-scope counters per peer. Best
// effort — a peer whose scope can't be snapped without blocking is simply
// absent from this sample (same contract as link_clock).
void AppendLinks(std::string* out, Transport* t) {
  *out += "\"links\":[";
  bool first = true;
  if (t != nullptr) {
    const int self = t->rank();
    const int n = t->size();
    for (int p = 0; p < n; p++) {
      if (p == self) continue;
      LinkScope sc;
      if (!t->link_scope(p, &sc)) continue;
      if (!first) *out += ",";
      first = false;
      char buf[640];
      std::snprintf(
          buf, sizeof buf,
          "{\"peer\":%d,\"state\":%d,\"epoch\":%u,\"sf\":%u,\"sf_up\":%u,"
          "\"tx_pb\":%llu,"
          "\"tx_wb\":%llu,\"rx_pb\":%llu,\"rx_wb\":%llu,\"tx_fr\":%llu,"
          "\"rx_fr\":%llu,\"naks\":%llu,\"crc\":%llu,\"replayed\":%llu,"
          "\"txq_ns\":%llu,\"txq_fr\":%llu,\"rxt_ns\":%llu,"
          "\"rxt_fr\":%llu,\"pif\":%llu}",
          p, sc.state, sc.epoch, sc.subflows, sc.subflows_up,
          (unsigned long long)sc.tx_payload_bytes,
          (unsigned long long)sc.tx_wire_bytes,
          (unsigned long long)sc.rx_payload_bytes,
          (unsigned long long)sc.rx_wire_bytes,
          (unsigned long long)sc.tx_frames,
          (unsigned long long)sc.rx_frames, (unsigned long long)sc.naks,
          (unsigned long long)sc.crc_rejects,
          (unsigned long long)sc.replayed,
          (unsigned long long)sc.tx_queue_ns_sum,
          (unsigned long long)sc.tx_queue_frames,
          (unsigned long long)sc.rx_transit_ns_sum,
          (unsigned long long)sc.rx_transit_frames,
          // Gauge, like "state": absolute per sample, never delta-decoded.
          (unsigned long long)sc.part_inflight);
      *out += buf;
    }
  }
  *out += "]";
}

void SampleLocked(State& s, Transport* t) ACX_REQUIRES(s.mu) {
  if (s.open_failed) return;
  if (s.f == nullptr) {
    // Filename on the stack and the warning over raw write(2): this body
    // also runs on the crash-flush tail (FlushBestEffort), where
    // std::string construction and fprintf on stderr are off-contract
    // (DESIGN.md §18, rule 5).
    char fn[512];
    std::snprintf(fn, sizeof fn, "%s.rank%d.tseries.jsonl", cfg().prefix,
                  RankForFile());
    s.f = std::fopen(fn, "w");
    if (s.f == nullptr) {
      s.open_failed = true;
      trace::WriteErrNote("tpu-acx: ACX_TSERIES: cannot write ", fn);
      return;
    }
  }

  const uint64_t mono = trace::NowSinceStartNs();
  const uint64_t wall = WallMs();
  const uint64_t epoch = Fleet().epoch();

  uint64_t cur[metrics::kNumCounters];
  for (int c = 0; c < metrics::kNumCounters; c++)
    cur[c] = metrics::Value(static_cast<metrics::Counter>(c));

  std::string line;
  line.reserve(1024);
  char buf[96];

  if (s.seq == 0) {
    // Baseline: every counter absolute, so a reader reconstructs the
    // cumulative series from init + deltas alone.
    std::snprintf(buf, sizeof buf,
                  "{\"init\":true,\"rank\":%d,\"interval_ms\":%llu,",
                  RankForFile(),
                  (unsigned long long)(cfg().interval_ns / 1000000ull));
    line += buf;
    AppendU64(&line, "t_mono_ns", mono);
    line += ",";
    AppendU64(&line, "t_wall_ms", wall);
    line += ",";
    AppendU64(&line, "epoch", epoch);
    line += ",\"counters\":{";
    for (int c = 0; c < metrics::kNumCounters; c++) {
      if (c) line += ",";
      AppendU64(&line, metrics::CounterName(static_cast<metrics::Counter>(c)),
                cur[c]);
    }
    line += "},";
    AppendLinks(&line, t);
    // An "app" fragment published before the first sample (a shim-only
    // program with no proxy forcing one via sample_now) must not be
    // dropped: the init line carries it like any other sample.
    {
      // Try-lock, not lock: this body runs on the crash-flush tail, and an
      // Annotate call interrupted mid-assign must not deadlock the dying
      // rank. A contended regular sample just drops the app fragment once.
      TryMutexLock alk(s.ann_mu);
      if (alk.owns() && !s.annotation.empty()) {
        line += ",\"app\":";
        line += s.annotation;
      }
    }
    line += "}";
  } else {
    std::snprintf(buf, sizeof buf, "{\"seq\":%llu,",
                  (unsigned long long)s.seq);
    line += buf;
    AppendU64(&line, "t_mono_ns", mono);
    line += ",";
    AppendU64(&line, "t_wall_ms", wall);
    line += ",";
    AppendU64(&line, "epoch", epoch);
    // Changed non-gauge counters, delta-encoded. Quiet intervals cost a
    // few dozen bytes, busy ones stay proportional to what moved.
    line += ",\"d\":{";
    bool first = true;
    for (int c = 0; c < metrics::kNumCounters; c++) {
      const metrics::Counter cc = static_cast<metrics::Counter>(c);
      if (metrics::IsGauge(cc) || cur[c] == s.prev_counters[c]) continue;
      if (!first) line += ",";
      first = false;
      AppendU64(&line, metrics::CounterName(cc), cur[c] - s.prev_counters[c]);
    }
    // Gauges: absolute every sample (delta of an epoch or a watermark is
    // meaningless).
    line += "},\"g\":{";
    AppendU64(&line, "fleet_epoch", cur[metrics::kFleetEpoch]);
    line += ",";
    AppendU64(&line, "slot_hwm", cur[metrics::kSlotHighWater]);
    line += ",";
    AppendU64(&line, "pages_free", cur[metrics::kPagesFree]);
    line += ",";
    AppendU64(&line, "pages_shared", cur[metrics::kPagesShared]);
    line += "},";
    // Interval-local proxy utilization, from the busy/idle ns deltas.
    const uint64_t db =
        cur[metrics::kProxyBusyNs] - s.prev_counters[metrics::kProxyBusyNs];
    const uint64_t di =
        cur[metrics::kProxyIdleNs] - s.prev_counters[metrics::kProxyIdleNs];
    std::snprintf(buf, sizeof buf, "\"proxy_util_pct\":%.2f,",
                  db + di > 0 ? 100.0 * static_cast<double>(db) /
                                    static_cast<double>(db + di)
                              : 0.0);
    line += buf;
    // Histogram deltas, sparse buckets: only hists that moved, only
    // buckets that moved.
    line += "\"h\":{";
    first = true;
    for (int h = 0; h < metrics::kNumHists; h++) {
      const metrics::Hist hh = static_cast<metrics::Hist>(h);
      uint64_t count = 0, sum = 0, buckets[metrics::kNumBuckets];
      metrics::HistRead(hh, &count, &sum, buckets);
      if (count == s.prev_hcount[h]) {
        s.prev_hsum[h] = sum;
        continue;
      }
      if (!first) line += ",";
      first = false;
      line += "\"";
      line += metrics::HistName(hh);
      line += "\":{";
      AppendU64(&line, "count", count - s.prev_hcount[h]);
      line += ",";
      AppendU64(&line, "sum", sum - s.prev_hsum[h]);
      line += ",\"b\":[";
      bool bfirst = true;
      for (int b = 0; b < metrics::kNumBuckets; b++) {
        if (buckets[b] == s.prev_hbuckets[h][b]) continue;
        if (!bfirst) line += ",";
        bfirst = false;
        std::snprintf(buf, sizeof buf, "[%d,%llu]", b,
                      (unsigned long long)(buckets[b] -
                                           s.prev_hbuckets[h][b]));
        line += buf;
      }
      line += "]}";
      s.prev_hcount[h] = count;
      s.prev_hsum[h] = sum;
      std::memcpy(s.prev_hbuckets[h], buckets, sizeof buckets);
    }
    line += "},";
    AppendLinks(&line, t);
    {
      // Try-lock, not lock: this body runs on the crash-flush tail, and an
      // Annotate call interrupted mid-assign must not deadlock the dying
      // rank. A contended regular sample just drops the app fragment once.
      TryMutexLock alk(s.ann_mu);
      if (alk.owns() && !s.annotation.empty()) {
        line += ",\"app\":";
        line += s.annotation;
      }
    }
    line += "}";
  }

  std::memcpy(s.prev_counters, cur, sizeof cur);
  s.seq++;
  std::fwrite(line.data(), 1, line.size(), s.f);
  std::fputc('\n', s.f);
  std::fflush(s.f);  // per-line: the tail must be on disk when we die
  s.live = line;
  g_samples.fetch_add(1, std::memory_order_relaxed);
}

void Refresh() {
  void (*fn)() = g_refresh.load(std::memory_order_acquire);
  if (fn != nullptr) fn();
}

// Crash/exit flusher: one last best-effort sample. try_lock — if the
// sampler itself crashed mid-write we must not deadlock the signal path.
void FlushBestEffort() {
  if (!Enabled()) return;
  State& s = S();
  TryMutexLock lk(s.mu);
  if (!lk.owns()) return;
  Refresh();
  SampleLocked(s, g_transport.load(std::memory_order_acquire));
}

}  // namespace

bool Enabled() {
  static const bool on = [] {
    const bool v = cfg().on;
    if (v) trace::RegisterCrashFlusher(FlushBestEffort, /*on_exit=*/true);
    return v;
  }();
  return on;
}

uint64_t IntervalNs() { return cfg().interval_ns; }

void SetRank(int rank) {
  g_rank.store(rank, std::memory_order_relaxed);
  (void)Enabled();  // arm the crash flusher as soon as the rank is known
}

void SetRefreshHook(void (*fn)()) {
  g_refresh.store(fn, std::memory_order_release);
}

void MaybeSample(Transport* t) {
  const uint64_t now = NowNs();
  const uint64_t due = g_next_due.load(std::memory_order_relaxed);
  if (now < due) return;
  // Single writer (the proxy sweep) in steady state; a plain store is
  // fine, racing SampleNow callers just take an extra sample.
  g_next_due.store(now + IntervalNs(), std::memory_order_relaxed);
  SampleNow(t);
}

void SampleNow(Transport* t) {
  if (!Enabled()) return;
  if (t != nullptr) g_transport.store(t, std::memory_order_release);
  Refresh();
  State& s = S();
  MutexLock lk(s.mu);
  SampleLocked(s, t != nullptr
                      ? t
                      : g_transport.load(std::memory_order_acquire));
}

void DetachTransport() {
  g_transport.store(nullptr, std::memory_order_release);
}

void Annotate(const char* json) {
  if (!Enabled() || json == nullptr) return;
  const size_t n = std::strlen(json);
  if (n == 0 || n > 8192 || json[0] != '{') return;
  State& s = S();
  MutexLock lk(s.ann_mu);
  s.annotation.assign(json, n);
}

int LiveJson(char* buf, int cap) {
  State& s = S();
  MutexLock lk(s.mu);
  const std::string& l = s.live;
  if (buf != nullptr && cap > 0) {
    const size_t n =
        l.size() < static_cast<size_t>(cap) - 1 ? l.size() : cap - 1;
    std::memcpy(buf, l.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int>(l.size());
}

uint64_t SamplesWritten() {
  return g_samples.load(std::memory_order_relaxed);
}

}  // namespace tseries
}  // namespace acx
