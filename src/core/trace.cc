#include "acx/trace.h"

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace acx {
namespace trace {
namespace {

using Clock = std::chrono::steady_clock;

struct Event {
  uint64_t ts_ns;   // nanoseconds: µs ticks are too coarse to attribute
                    // a ~2.5 µs enqueue-latency budget segment-by-segment
  const char* name;
  int64_t slot;
};

struct Ring {
  std::mutex mu;
  std::vector<Event> events;
  size_t cap = 65536;
  uint64_t dropped = 0;
  Clock::time_point t0 = Clock::now();
};

Ring& ring() {
  static Ring* r = [] {
    Ring* r = new Ring;
    const char* c = std::getenv("ACX_TRACE_CAP");
    if (c != nullptr) {
      const unsigned long long v = strtoull(c, nullptr, 10);
      if (v > 0) r->cap = static_cast<size_t>(v);
    }
    r->events.reserve(r->cap < 4096 ? r->cap : 4096);
    return r;
  }();
  return *r;
}

const char* path() {
  static const char* p = std::getenv("ACX_TRACE");
  return p;
}

}  // namespace

bool Enabled() {
  static const bool on = path() != nullptr && path()[0] != '\0';
  return on;
}

void Emit(const char* name, int64_t slot) {
  Ring& r = ring();
  // Timestamp under the lock: emitters race (app, trigger, proxy, and
  // waiter threads), and the file must be time-ordered.
  std::lock_guard<std::mutex> lk(r.mu);
  if (r.events.size() >= r.cap) {
    r.dropped++;
    return;
  }
  const uint64_t ts = static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(Clock::now() -
                                                           r.t0)
          .count());
  r.events.push_back(Event{ts, name, slot});
}

void Flush(int rank) {
  if (!Enabled()) return;
  Ring& r = ring();
  std::vector<Event> events;
  uint64_t dropped;
  {
    std::lock_guard<std::mutex> lk(r.mu);
    events.swap(r.events);
    dropped = r.dropped;
    r.dropped = 0;
  }
  std::string fn = std::string(path()) + ".rank" + std::to_string(rank) +
                   ".trace.json";
  FILE* f = std::fopen(fn.c_str(), "w");
  if (f == nullptr) {
    std::fprintf(stderr, "tpu-acx: ACX_TRACE: cannot write %s\n", fn.c_str());
    return;
  }
  // Chrome trace-event JSON: instant events, one tid per slot so each
  // op slot gets its own track in the viewer.
  std::fprintf(f, "{\"traceEvents\":[\n");
  for (size_t i = 0; i < events.size(); i++) {
    const Event& e = events[i];
    // Chrome/Perfetto "ts" is in µs and accepts decimals — keep the ns
    // precision as fractional µs.
    std::fprintf(f,
                 "{\"name\":\"%s\",\"ph\":\"i\",\"s\":\"t\",\"ts\":%llu.%03llu,"
                 "\"pid\":%d,\"tid\":%lld}%s\n",
                 e.name, (unsigned long long)(e.ts_ns / 1000),
                 (unsigned long long)(e.ts_ns % 1000), rank,
                 (long long)e.slot, i + 1 < events.size() ? "," : "");
  }
  std::fprintf(f, "],\"displayTimeUnit\":\"ms\",\"otherData\":{"
                  "\"dropped\":%llu,\"events\":%zu}}\n",
               (unsigned long long)dropped, events.size());
  std::fclose(f);
}

}  // namespace trace
}  // namespace acx
