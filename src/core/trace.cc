#include "acx/trace.h"

#include <signal.h>
#include <unistd.h>

#include <algorithm>
#include <climits>
#include <csignal>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "acx/thread_annotations.h"

namespace acx {
namespace trace {
namespace {

using Clock = std::chrono::steady_clock;

struct Event {
  uint64_t ts_ns;   // nanoseconds: µs ticks are too coarse to attribute
                    // a ~2.5 µs enqueue-latency budget segment-by-segment
  const char* name;
  int64_t slot;
  uint64_t span;    // causal span id (acx/span.h); 0 = untagged
};

struct Ring {
  Mutex mu;
  std::vector<Event> events ACX_GUARDED_BY(mu);
  size_t cap = 65536;
  uint64_t dropped ACX_GUARDED_BY(mu) = 0;
  Clock::time_point t0 = Clock::now();
};

Ring& ring() {
  static Ring* r = [] {
    Ring* r = new Ring;
    const char* c = std::getenv("ACX_TRACE_CAP");
    if (c != nullptr) {
      const unsigned long long v = strtoull(c, nullptr, 10);
      if (v > 0) r->cap = static_cast<size_t>(v);
    }
    MutexLock lk(r->mu);  // satisfies the guard; uncontended at init
    r->events.reserve(r->cap < 4096 ? r->cap : 4096);
    return r;
  }();
  return *r;
}

const char* path() {
  static const char* p = std::getenv("ACX_TRACE");
  return p;
}

std::atomic<int> g_rank{-1};
std::atomic<bool> g_flushing{false};

int RankForFlush() {
  int r = g_rank.load(std::memory_order_relaxed);
  if (r >= 0) return r;
  return EnvRankOr(0);
}

// Snapshot the ring without draining it (a later flush rewrites a
// superset; an abnormal-exit flush after a normal finalize flush never
// truncates the finalize file down to a tail). Two entry points instead of
// a best_effort flag: the signal-path contract (DESIGN.md §18, rule 5) is
// per-function, and the crash flusher must reach a body that contains no
// blocking acquire at all. The best-effort form refuses to block on the
// ring mutex and skips empty rings.
bool SnapshotBestEffort(std::vector<Event>* events, uint64_t* dropped) {
  Ring& r = ring();
  TryMutexLock lk(r.mu);
  if (!lk.owns()) return false;
  if (r.events.empty()) return false;
  *events = r.events;
  *dropped = r.dropped;
  return true;
}

void SnapshotBlocking(std::vector<Event>* events, uint64_t* dropped) {
  Ring& r = ring();
  MutexLock lk(r.mu);
  *events = r.events;
  *dropped = r.dropped;
}

void WriteFile(const std::vector<Event>& events, uint64_t dropped, int rank);

void FlushBestEffort() {
  if (!Enabled()) return;
  std::vector<Event> events;
  uint64_t dropped = 0;
  if (!SnapshotBestEffort(&events, &dropped)) return;
  WriteFile(events, dropped, RankForFlush());
}

// Crash-flush registry shared with the flight recorder (acx/flightrec.h):
// one set of signal/atexit hooks, N best-effort flushers. Small fixed
// array — registration happens a handful of times at startup, the signal
// path just walks it.
constexpr int kMaxFlushers = 8;
void (*g_flushers[kMaxFlushers])() = {};
bool g_flusher_on_exit[kMaxFlushers] = {};
std::atomic<int> g_nflushers{0};

void RunFlushersAtExit() {
  const int n = g_nflushers.load(std::memory_order_acquire);
  for (int i = 0; i < n; i++)
    if (g_flusher_on_exit[i]) g_flushers[i]();
}

void OnFatalSignal(int sig) {
  // One flushing pass only; fopen/fprintf are not async-signal-safe, but a
  // best-effort black box from a dying rank beats a guaranteed empty one.
  if (!g_flushing.exchange(true)) {
    const int n = g_nflushers.load(std::memory_order_acquire);
    for (int i = 0; i < n; i++) g_flushers[i]();
  }
  std::signal(sig, SIG_DFL);
  std::raise(sig);
}

void InstallCrashHooks() {
  std::atexit(RunFlushersAtExit);
  const int sigs[] = {SIGTERM, SIGINT, SIGABRT, SIGSEGV, SIGBUS};
  for (int sig : sigs) {
    // Only claim default dispositions — never stomp a runtime's (e.g.
    // Python's SIGINT) installed handler.
    struct sigaction old {};
    if (sigaction(sig, nullptr, &old) != 0) continue;
    if (old.sa_handler != SIG_DFL || (old.sa_flags & SA_SIGINFO)) continue;
    struct sigaction sa {};
    sa.sa_handler = OnFatalSignal;
    sigemptyset(&sa.sa_mask);
    sigaction(sig, &sa, nullptr);
  }
}

// One output record (instant, span begin, or span end), sortable by
// timestamp so the written stream stays time-ordered with spans inline.
struct Record {
  uint64_t ts_ns;
  std::string json;  // everything but the "ts" field
};

void AppendTs(std::string* out, uint64_t ts_ns) {
  char buf[48];
  // Chrome/Perfetto "ts" is in µs and accepts decimals — keep the ns
  // precision as fractional µs.
  std::snprintf(buf, sizeof buf, "\"ts\":%llu.%03llu",
                (unsigned long long)(ts_ns / 1000),
                (unsigned long long)(ts_ns % 1000));
  *out += buf;
}

// Synthesize duration spans from the instant stream: for each slot the
// lifecycle transitions pair up into segments. An end-name arriving with
// its begin stamp set emits one async "b"/"e" pair (name+cat+id matched,
// the Perfetto async-span contract) and the chain advances.
struct SpanRule {
  const char* begin1;
  const char* begin2;  // alternate begin (send/recv flavor), or nullptr
  const char* end;
  const char* span;
};

const SpanRule kSpanRules[] = {
    {"trigger_fired", nullptr, "isend_issued", "proxy_pickup"},
    {"trigger_fired", nullptr, "irecv_issued", "proxy_pickup"},
    {"isend_issued", "irecv_issued", "op_completed", "wire"},
    {"op_completed", nullptr, "wait_observed", "wait_pickup"},
    {"pready_marked", nullptr, "pready_wire", "pready_push"},
};

size_t SynthesizeSpans(const std::vector<Event>& events, int rank,
                       std::vector<Record>* out) {
  // last[slot][name] = ts of the most recent instant with that name.
  std::unordered_map<int64_t, std::unordered_map<std::string, uint64_t>> last;
  uint64_t next_id = 0;
  size_t spans = 0;
  for (const Event& e : events) {
    auto& slot_last = last[e.slot];
    for (const SpanRule& rule : kSpanRules) {
      if (std::strcmp(e.name, rule.end) != 0) continue;
      uint64_t b_ts = 0;
      auto it = slot_last.find(rule.begin1);
      if (it != slot_last.end()) {
        b_ts = it->second;
        slot_last.erase(it);
      } else if (rule.begin2 != nullptr &&
                 (it = slot_last.find(rule.begin2)) != slot_last.end()) {
        b_ts = it->second;
        slot_last.erase(it);
      } else {
        continue;
      }
      if (e.ts_ns < b_ts) continue;
      char buf[256];
      char args[64] = "";
      // The end instant carries the op's causal span id (the begin side
      // always has the same id — both come from the same Op); propagate it
      // so synthesized lifecycle bars stay joinable with the wire events.
      if (e.span != 0)
        std::snprintf(args, sizeof args, "\"args\":{\"span\":%llu},",
                      (unsigned long long)e.span);
      const uint64_t id = next_id++;
      std::snprintf(buf, sizeof buf,
                    "{\"name\":\"%s\",\"cat\":\"acx\",\"ph\":\"b\","
                    "\"id\":%llu,\"pid\":%d,\"tid\":%lld,%s",
                    rule.span, (unsigned long long)id, rank,
                    (long long)e.slot, args);
      out->push_back(Record{b_ts, buf});
      std::snprintf(buf, sizeof buf,
                    "{\"name\":\"%s\",\"cat\":\"acx\",\"ph\":\"e\","
                    "\"id\":%llu,\"pid\":%d,\"tid\":%lld,%s",
                    rule.span, (unsigned long long)id, rank,
                    (long long)e.slot, args);
      out->push_back(Record{e.ts_ns, buf});
      spans++;
    }
    slot_last[e.name] = e.ts_ns;
  }
  return spans;
}

void WriteFile(const std::vector<Event>& events, uint64_t dropped, int rank) {
  // Filename on the stack — the crash path must not construct std::string.
  char fn[512];
  std::snprintf(fn, sizeof fn, "%s.rank%d.trace.json", path(), rank);
  FILE* f = std::fopen(fn, "w");
  if (f == nullptr) {
    WriteErrNote("tpu-acx: ACX_TRACE: cannot write ", fn);
    return;
  }
  // Chrome trace-event JSON: instant events (one tid per slot, so each op
  // slot gets its own track) plus synthesized lifecycle spans.
  std::vector<Record> records;
  records.reserve(events.size() * 2);
  for (const Event& e : events) {
    char buf[224];
    if (e.span != 0) {
      std::snprintf(buf, sizeof buf,
                    "{\"name\":\"%s\",\"ph\":\"i\",\"s\":\"t\","
                    "\"pid\":%d,\"tid\":%lld,"
                    "\"args\":{\"span\":%llu},",
                    e.name, rank, (long long)e.slot,
                    (unsigned long long)e.span);
    } else {
      std::snprintf(buf, sizeof buf,
                    "{\"name\":\"%s\",\"ph\":\"i\",\"s\":\"t\","
                    "\"pid\":%d,\"tid\":%lld,",
                    e.name, rank, (long long)e.slot);
    }
    records.push_back(Record{e.ts_ns, buf});
  }
  const size_t spans = SynthesizeSpans(events, rank, &records);
  // Stable sort keeps the stream time-ordered with span boundaries
  // interleaved at their instants' timestamps (begin records sort back to
  // their begin instant).
  std::stable_sort(records.begin(), records.end(),
                   [](const Record& a, const Record& b) {
                     return a.ts_ns < b.ts_ns;
                   });
  std::fprintf(f, "{\"traceEvents\":[\n");
  for (size_t i = 0; i < records.size(); i++) {
    std::string line = records[i].json;
    AppendTs(&line, records[i].ts_ns);
    line += "}";
    std::fprintf(f, "%s%s\n", line.c_str(),
                 i + 1 < records.size() ? "," : "");
  }
  std::fprintf(f, "],\"displayTimeUnit\":\"ms\",\"otherData\":{"
                  "\"dropped\":%llu,\"events\":%zu,\"spans\":%zu}}\n",
               (unsigned long long)dropped, events.size(), spans);
  std::fclose(f);
}

}  // namespace

bool Enabled() {
  static const bool on = [] {
    const bool v = path() != nullptr && path()[0] != '\0';
    if (v) RegisterCrashFlusher(FlushBestEffort, /*on_exit=*/true);
    return v;
  }();
  return on;
}

// See trace.h: raw write(2) on stderr, usable from signal context. Kept
// deliberately free of stdio, allocation, and locks — the signal-path
// audit (tools/acx_audit.py, rule 5) walks every function reachable from
// the crash flushers and would flag any of those here.
void WriteErrNote(const char* what, const char* name) {
  char buf[512];
  size_t n = 0;
  for (const char* p = what; *p != '\0' && n < sizeof buf - 1; p++)
    buf[n++] = *p;
  for (const char* p = name; *p != '\0' && n < sizeof buf - 1; p++)
    buf[n++] = *p;
  buf[n++] = '\n';
  const ssize_t rc = write(STDERR_FILENO, buf, n);
  (void)rc;
}

void RegisterCrashFlusher(void (*fn)(), bool on_exit) {
  static std::once_flag once;
  std::call_once(once, InstallCrashHooks);
  static std::mutex mu;
  std::lock_guard<std::mutex> lk(mu);
  const int n = g_nflushers.load(std::memory_order_relaxed);
  if (n >= kMaxFlushers) return;
  for (int i = 0; i < n; i++)
    if (g_flushers[i] == fn) return;  // idempotent
  g_flushers[n] = fn;
  g_flusher_on_exit[n] = on_exit;
  g_nflushers.store(n + 1, std::memory_order_release);
}

void Emit(const char* name, int64_t slot) { Emit(name, slot, 0); }

void Emit(const char* name, int64_t slot, uint64_t span) {
  Ring& r = ring();
  // Timestamp under the lock: emitters race (app, trigger, proxy, and
  // waiter threads), and the file must be time-ordered.
  MutexLock lk(r.mu);
  if (r.events.size() >= r.cap) {
    r.dropped++;
    return;
  }
  const uint64_t ts = static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(Clock::now() -
                                                           r.t0)
          .count());
  r.events.push_back(Event{ts, name, slot, span});
}

int EnvRankOr(int fallback) {
  const char* e = std::getenv("ACX_RANK");
  if (e == nullptr || e[0] == '\0') return fallback;
  // strtol alone would accept leading whitespace and '+'; the contract is
  // a full bare decimal string, so the first byte must already be a digit.
  if (e[0] < '0' || e[0] > '9') return fallback;
  char* end = nullptr;
  const long v = std::strtol(e, &end, 10);
  if (end == e || *end != '\0' || v < 0 || v > INT_MAX) return fallback;
  return static_cast<int>(v);
}

uint64_t NowSinceStartNs() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(Clock::now() -
                                                           ring().t0)
          .count());
}

void SetRank(int rank) {
  g_rank.store(rank, std::memory_order_relaxed);
  (void)Enabled();  // arm the crash hooks as soon as the rank is known
}

void Flush(int rank) {
  if (!Enabled()) return;
  std::vector<Event> events;
  uint64_t dropped = 0;
  SnapshotBlocking(&events, &dropped);
  WriteFile(events, dropped, rank);
}

}  // namespace trace
}  // namespace acx
