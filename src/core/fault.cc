// tpu-acx: fault-injection + retry-policy state (see include/acx/fault.h).

#include "acx/fault.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "acx/state.h"

namespace acx {

RetryPolicy& Policy() {
  // Leaked on purpose (process-lifetime; atexit-ordering-proof, same
  // pattern as the trace ring).
  static RetryPolicy* p = [] {
    auto* pp = new RetryPolicy();
    if (const char* e = getenv("ACX_OP_TIMEOUT_MS")) {
      const double ms = atof(e);
      if (ms > 0) pp->timeout_ns.store(static_cast<uint64_t>(ms * 1e6));
    }
    if (const char* e = getenv("ACX_RETRY_BACKOFF_US")) {
      const unsigned long long us = strtoull(e, nullptr, 10);
      if (us > 0) pp->backoff_us.store(us);
    }
    if (const char* e = getenv("ACX_MAX_RETRIES"))
      pp->max_retries.store(static_cast<uint32_t>(atoi(e)));
    if (const char* e = getenv("ACX_RECONNECT_MAX")) {
      const int v = atoi(e);
      if (v >= 0) pp->reconnect_max.store(static_cast<uint32_t>(v));
    }
    if (const char* e = getenv("ACX_RECONNECT_BACKOFF_MS")) {
      const unsigned long long ms = strtoull(e, nullptr, 10);
      if (ms > 0) pp->reconnect_backoff_ms.store(ms);
    }
    return pp;
  }();
  return *p;
}

namespace fault {
namespace {

struct State {
  Config cfg;
  std::atomic<bool> enabled{false};
  std::atomic<uint64_t> matched{0};
  std::atomic<uint64_t> drops{0};
  std::atomic<uint64_t> delays{0};
  std::atomic<uint64_t> fails{0};
  std::atomic<uint64_t> frame_drops{0};
  std::atomic<uint64_t> frame_corrupts{0};
  std::atomic<uint64_t> link_stalls{0};
  std::atomic<uint64_t> link_closes{0};
};

State& S() {
  static State* s = [] {
    auto* st = new State();
    if (const char* e = getenv("ACX_FAULT")) {
      Config c;
      if (ParseSpec(e, &c)) {
        st->cfg = c;
        st->enabled.store(c.action != Action::kNone,
                          std::memory_order_release);
      } else {
        std::fprintf(stderr, "tpu-acx: bad ACX_FAULT spec '%s' (ignored)\n",
                     e);
      }
    }
    return st;
  }();
  return *s;
}

}  // namespace

bool Enabled() { return S().enabled.load(std::memory_order_acquire); }

bool ParseSpec(const char* spec, Config* out) {
  if (spec == nullptr || *spec == '\0') return false;
  Config c;
  const char* p = spec;
  char tok[64];
  const auto next = [&p](char* buf, size_t cap) -> bool {
    if (*p == '\0') return false;
    size_t i = 0;
    while (*p != '\0' && *p != ':') {
      if (i + 1 >= cap) return false;
      buf[i++] = *p++;
    }
    buf[i] = '\0';
    if (*p == ':') p++;
    return i > 0;
  };
  if (!next(tok, sizeof tok)) return false;
  if (strcmp(tok, "drop") == 0) c.action = Action::kDrop;
  else if (strcmp(tok, "delay") == 0) c.action = Action::kDelay;
  else if (strcmp(tok, "fail") == 0) c.action = Action::kFail;
  else if (strcmp(tok, "drop_frame") == 0) c.action = Action::kDropFrame;
  else if (strcmp(tok, "corrupt_frame") == 0) c.action = Action::kCorruptFrame;
  else if (strcmp(tok, "stall_link_ms") == 0) c.action = Action::kStallLink;
  else if (strcmp(tok, "close_link_once") == 0) c.action = Action::kCloseLink;
  else if (strcmp(tok, "none") == 0) c.action = Action::kNone;
  else return false;
  while (*p != '\0') {
    if (!next(tok, sizeof tok)) return false;
    char* eq = strchr(tok, '=');
    if (eq == nullptr) return false;
    *eq = '\0';
    const char* val = eq + 1;
    if (strcmp(tok, "rank") == 0) c.rank = atoi(val);
    else if (strcmp(tok, "peer") == 0) c.peer = atoi(val);
    else if (strcmp(tok, "subflow") == 0) c.subflow = atoi(val);
    else if (strcmp(tok, "nth") == 0) c.nth = atoi(val);
    else if (strcmp(tok, "count") == 0) c.count = atoi(val);
    else if (strcmp(tok, "us") == 0) c.delay_us = strtoull(val, nullptr, 10);
    else if (strcmp(tok, "ms") == 0) c.stall_ms = strtoull(val, nullptr, 10);
    else if (strcmp(tok, "err") == 0) c.err = atoi(val);
    else if (strcmp(tok, "kind") == 0) {
      if (strcmp(val, "send") == 0) c.kind = 1;
      else if (strcmp(val, "recv") == 0) c.kind = 2;
      else if (strcmp(val, "any") == 0) c.kind = 0;
      else return false;
    } else {
      return false;
    }
  }
  if (c.nth < 1 || c.count < 1) return false;
  // A zero-length stall is a typo, not a fault: reject like nth=0.
  if (c.action == Action::kStallLink && c.stall_ms < 1) return false;
  *out = c;
  return true;
}

void Configure(const Config& cfg) {
  State& s = S();
  s.cfg = cfg;
  s.matched.store(0, std::memory_order_relaxed);
  s.enabled.store(cfg.action != Action::kNone, std::memory_order_release);
}

Action OnIssue(int rank, bool is_send, int peer, uint64_t* delay_us,
               int* err) {
  State& s = S();
  const Config& c = s.cfg;
  // Frame actions never fire (or consume a match) at the issue level; the
  // shared matched counter stays consistent because exactly one action is
  // armed at a time and the other consult site early-returns symmetrically.
  if (c.action == Action::kNone || c.action >= Action::kDropFrame)
    return Action::kNone;
  if (c.rank >= 0 && rank != c.rank) return Action::kNone;
  if (c.kind == 1 && !is_send) return Action::kNone;
  if (c.kind == 2 && is_send) return Action::kNone;
  if (c.peer >= 0 && peer != c.peer) return Action::kNone;
  const uint64_t m = s.matched.fetch_add(1, std::memory_order_relaxed) + 1;
  if (m < static_cast<uint64_t>(c.nth) ||
      m >= static_cast<uint64_t>(c.nth) + static_cast<uint64_t>(c.count))
    return Action::kNone;
  switch (c.action) {
    case Action::kDrop:
      s.drops.fetch_add(1, std::memory_order_relaxed);
      break;
    case Action::kDelay:
      s.delays.fetch_add(1, std::memory_order_relaxed);
      if (delay_us != nullptr) *delay_us = c.delay_us;
      break;
    case Action::kFail:
      s.fails.fetch_add(1, std::memory_order_relaxed);
      if (err != nullptr) *err = c.err != 0 ? c.err : kErrInjected;
      break;
    default:
      break;
  }
  return c.action;
}

Action OnFrame(int rank, int peer, int subflow, uint64_t* stall_us) {
  State& s = S();
  const Config& c = s.cfg;
  if (c.action < Action::kDropFrame) return Action::kNone;
  if (c.rank >= 0 && rank != c.rank) return Action::kNone;
  if (c.peer >= 0 && peer != c.peer) return Action::kNone;
  // Subflow filter sits with rank/peer, BEFORE the matched counter: a
  // `subflow=` spec counts only that lane's frames, so nth= stays a stable
  // coordinate regardless of how the other lanes interleave.
  if (c.subflow >= 0 && subflow != c.subflow) return Action::kNone;
  const uint64_t m = s.matched.fetch_add(1, std::memory_order_relaxed) + 1;
  if (m < static_cast<uint64_t>(c.nth) ||
      m >= static_cast<uint64_t>(c.nth) + static_cast<uint64_t>(c.count))
    return Action::kNone;
  switch (c.action) {
    case Action::kDropFrame:
      s.frame_drops.fetch_add(1, std::memory_order_relaxed);
      break;
    case Action::kCorruptFrame:
      s.frame_corrupts.fetch_add(1, std::memory_order_relaxed);
      break;
    case Action::kStallLink:
      s.link_stalls.fetch_add(1, std::memory_order_relaxed);
      if (stall_us != nullptr) *stall_us = c.stall_ms * 1000;
      break;
    case Action::kCloseLink:
      s.link_closes.fetch_add(1, std::memory_order_relaxed);
      break;
    default:
      break;
  }
  return c.action;
}

Stats stats() {
  State& s = S();
  Stats out;
  out.drops = s.drops.load(std::memory_order_relaxed);
  out.delays = s.delays.load(std::memory_order_relaxed);
  out.fails = s.fails.load(std::memory_order_relaxed);
  out.frame_drops = s.frame_drops.load(std::memory_order_relaxed);
  out.frame_corrupts = s.frame_corrupts.load(std::memory_order_relaxed);
  out.link_stalls = s.link_stalls.load(std::memory_order_relaxed);
  out.link_closes = s.link_closes.load(std::memory_order_relaxed);
  return out;
}

}  // namespace fault
}  // namespace acx
