// tpu-acx: fault-injection + retry-policy state (see include/acx/fault.h).

#include "acx/fault.h"

#include <unistd.h>

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "acx/state.h"

namespace acx {

namespace {

// Strict unsigned parse: whole string, base 10, no sign. The lenient
// atof/strtoull parses these knobs used before PR 11 turned "ACX_MAX_
// RETRIES=8x" into 8 and "abc" into 0 — a typo'd chaos leg would then run
// with a policy nobody asked for. Same convention as tseries.cc.
bool StrictU64(const char* s, uint64_t* out) {
  if (s == nullptr || *s == '\0' || strchr(s, '-') != nullptr) return false;
  char* end = nullptr;
  errno = 0;
  const unsigned long long v = strtoull(s, &end, 10);
  if (errno != 0 || end == s || *end != '\0') return false;
  *out = static_cast<uint64_t>(v);
  return true;
}

// Strict non-negative decimal (ACX_OP_TIMEOUT_MS accepts fractions).
bool StrictMs(const char* s, double* out) {
  if (s == nullptr || *s == '\0' || strchr(s, '-') != nullptr) return false;
  char* end = nullptr;
  const double v = strtod(s, &end);
  if (end == s || *end != '\0' || !(v >= 0)) return false;
  *out = v;
  return true;
}

void RefuseEnv(const char* name, const char* val, const char* why) {
  std::fprintf(stderr, "tpu-acx: %s=\"%s\" invalid (%s); keeping default\n",
               name, val, why);
}

}  // namespace

RetryPolicy& Policy() {
  // Leaked on purpose (process-lifetime; atexit-ordering-proof, same
  // pattern as the trace ring).
  static RetryPolicy* p = [] {
    auto* pp = new RetryPolicy();
    if (const char* e = getenv("ACX_OP_TIMEOUT_MS")) {
      double ms = 0;
      if (!StrictMs(e, &ms))
        RefuseEnv("ACX_OP_TIMEOUT_MS", e, "want a non-negative number");
      else if (ms > 0)
        pp->timeout_ns.store(static_cast<uint64_t>(ms * 1e6));
    }
    if (const char* e = getenv("ACX_RETRY_BACKOFF_US")) {
      uint64_t us = 0;
      if (!StrictU64(e, &us) || us == 0)
        RefuseEnv("ACX_RETRY_BACKOFF_US", e, "want an integer >= 1");
      else
        pp->backoff_us.store(us);
    }
    if (const char* e = getenv("ACX_MAX_RETRIES")) {
      uint64_t v = 0;
      if (!StrictU64(e, &v) || v > 1000000000ull)
        RefuseEnv("ACX_MAX_RETRIES", e, "want an integer 0..1e9");
      else
        pp->max_retries.store(static_cast<uint32_t>(v));
    }
    if (const char* e = getenv("ACX_RECONNECT_MAX")) {
      uint64_t v = 0;
      if (!StrictU64(e, &v) || v > 1000000000ull)
        RefuseEnv("ACX_RECONNECT_MAX", e, "want an integer 0..1e9");
      else
        pp->reconnect_max.store(static_cast<uint32_t>(v));
    }
    if (const char* e = getenv("ACX_RECONNECT_BACKOFF_MS")) {
      uint64_t ms = 0;
      if (!StrictU64(e, &ms) || ms == 0)
        RefuseEnv("ACX_RECONNECT_BACKOFF_MS", e, "want an integer >= 1");
      else
        pp->reconnect_backoff_ms.store(ms);
    }
    return pp;
  }();
  return *p;
}

namespace fault {
namespace {

// One schedule entry: the parsed spec plus ITS OWN trigger state. Per-spec
// counters keep `nth=` a stable coordinate in a multi-spec schedule — spec
// B's window cannot be burned by attempts only spec A matched.
struct SpecState {
  Config cfg;
  std::atomic<uint64_t> matched{0};
  std::atomic<uint64_t> fired{0};
};

struct State {
  SpecState specs[kMaxSpecs];
  std::atomic<int> nspecs{0};
  std::atomic<bool> enabled{false};
  std::atomic<uint64_t> drops{0};
  std::atomic<uint64_t> delays{0};
  std::atomic<uint64_t> fails{0};
  std::atomic<uint64_t> kills{0};
  std::atomic<uint64_t> frame_drops{0};
  std::atomic<uint64_t> frame_corrupts{0};
  std::atomic<uint64_t> link_stalls{0};
  std::atomic<uint64_t> link_closes{0};
};

void Install(State* st, const Config* cfgs, int n) {
  if (n < 0) n = 0;
  if (n > kMaxSpecs) n = kMaxSpecs;
  bool any = false;
  for (int i = 0; i < n; i++) {
    st->specs[i].cfg = cfgs[i];
    st->specs[i].matched.store(0, std::memory_order_relaxed);
    st->specs[i].fired.store(0, std::memory_order_relaxed);
    if (cfgs[i].action != Action::kNone) any = true;
  }
  st->nspecs.store(n, std::memory_order_relaxed);
  st->enabled.store(any, std::memory_order_release);
}

State& S() {
  static State* s = [] {
    auto* st = new State();
    Config cfgs[kMaxSpecs];
    int n = 0;
    if (const char* e = getenv("ACX_FAULT")) {
      // A typo'd spec must never let a CI chaos leg silently run
      // fault-free: fail the rank the way `acxrun -fault` fails the
      // launch (satellite of DESIGN.md §16).
      if (!ParseSchedule(e, cfgs, kMaxSpecs, &n)) {
        std::fprintf(stderr,
                     "tpu-acx: bad ACX_FAULT spec '%s' (fatal: refusing to "
                     "run fault-free)\n",
                     e);
        std::fflush(stderr);
        abort();
      }
    }
    if (const char* e = getenv("ACX_CHAOS")) {
      const char* np_s = getenv("ACX_SIZE");
      const int np = np_s != nullptr && atoi(np_s) > 0 ? atoi(np_s) : 2;
      char expanded[2048];
      int m = 0;
      if (!ExpandChaos(e, np, expanded, sizeof expanded) ||
          !ParseSchedule(expanded, cfgs + n, kMaxSpecs - n, &m)) {
        std::fprintf(stderr,
                     "tpu-acx: bad ACX_CHAOS spec '%s' (fatal: refusing to "
                     "run fault-free)\n",
                     e);
        std::fflush(stderr);
        abort();
      }
      n += m;
    }
    Install(st, cfgs, n);
    return st;
  }();
  return *s;
}

// `want_part` selects the match domain: plain issue attempts (OnIssue,
// op=0 specs) vs partitioned pushes (OnPartIssue, op=part specs). The two
// domains never cross-match — see the op=part note in acx/fault.h.
bool PassesIssueFilters(const Config& c, int rank, bool is_send, int peer,
                        bool want_part) {
  if (c.action == Action::kNone || IsFrameAction(c.action)) return false;
  if ((c.op != 0) != want_part) return false;
  if (c.rank >= 0 && rank != c.rank) return false;
  if (c.kind == 1 && !is_send) return false;
  if (c.kind == 2 && is_send) return false;
  if (c.peer >= 0 && peer != c.peer) return false;
  return true;
}

bool PassesFrameFilters(const Config& c, int rank, int peer, int subflow) {
  if (!IsFrameAction(c.action)) return false;
  if (c.rank >= 0 && rank != c.rank) return false;
  if (c.peer >= 0 && peer != c.peer) return false;
  // Subflow filter sits with rank/peer, BEFORE the matched counter: a
  // `subflow=` spec counts only that lane's frames, so nth= stays a stable
  // coordinate regardless of how the other lanes interleave.
  if (c.subflow >= 0 && subflow != c.subflow) return false;
  return true;
}

bool InWindow(const Config& c, uint64_t m) {
  return m >= static_cast<uint64_t>(c.nth) &&
         m < static_cast<uint64_t>(c.nth) + static_cast<uint64_t>(c.count);
}

}  // namespace

bool Enabled() { return S().enabled.load(std::memory_order_acquire); }

const char* ActionName(Action a) {
  switch (a) {
    case Action::kDrop: return "drop";
    case Action::kDelay: return "delay";
    case Action::kFail: return "fail";
    case Action::kDropFrame: return "drop_frame";
    case Action::kCorruptFrame: return "corrupt_frame";
    case Action::kStallLink: return "stall_link_ms";
    case Action::kCloseLink: return "close_link_once";
    case Action::kKill: return "kill";
    default: return "none";
  }
}

bool ParseSpec(const char* spec, Config* out) {
  if (spec == nullptr || *spec == '\0') return false;
  // ';' belongs to the schedule grammar (ParseSchedule); inside a single
  // spec it can only be a typo half-swallowed by atoi.
  if (strchr(spec, ';') != nullptr) return false;
  Config c;
  const char* p = spec;
  char tok[64];
  const auto next = [&p](char* buf, size_t cap) -> bool {
    if (*p == '\0') return false;
    size_t i = 0;
    while (*p != '\0' && *p != ':') {
      if (i + 1 >= cap) return false;
      buf[i++] = *p++;
    }
    buf[i] = '\0';
    if (*p == ':') p++;
    return i > 0;
  };
  if (!next(tok, sizeof tok)) return false;
  if (strcmp(tok, "drop") == 0) c.action = Action::kDrop;
  else if (strcmp(tok, "delay") == 0) c.action = Action::kDelay;
  else if (strcmp(tok, "fail") == 0) c.action = Action::kFail;
  else if (strcmp(tok, "drop_frame") == 0) c.action = Action::kDropFrame;
  else if (strcmp(tok, "corrupt_frame") == 0) c.action = Action::kCorruptFrame;
  else if (strcmp(tok, "stall_link_ms") == 0) c.action = Action::kStallLink;
  else if (strcmp(tok, "close_link_once") == 0) c.action = Action::kCloseLink;
  else if (strcmp(tok, "kill") == 0) c.action = Action::kKill;
  else if (strcmp(tok, "none") == 0) c.action = Action::kNone;
  else return false;
  while (*p != '\0') {
    if (!next(tok, sizeof tok)) return false;
    char* eq = strchr(tok, '=');
    if (eq == nullptr) return false;
    *eq = '\0';
    const char* val = eq + 1;
    if (strcmp(tok, "rank") == 0) c.rank = atoi(val);
    else if (strcmp(tok, "peer") == 0) c.peer = atoi(val);
    else if (strcmp(tok, "subflow") == 0) c.subflow = atoi(val);
    else if (strcmp(tok, "nth") == 0) c.nth = atoi(val);
    else if (strcmp(tok, "count") == 0) c.count = atoi(val);
    else if (strcmp(tok, "us") == 0) c.delay_us = strtoull(val, nullptr, 10);
    else if (strcmp(tok, "ms") == 0) c.stall_ms = strtoull(val, nullptr, 10);
    else if (strcmp(tok, "err") == 0) c.err = atoi(val);
    else if (strcmp(tok, "kind") == 0) {
      if (strcmp(val, "send") == 0) c.kind = 1;
      else if (strcmp(val, "recv") == 0) c.kind = 2;
      else if (strcmp(val, "any") == 0) c.kind = 0;
      else return false;
    } else if (strcmp(tok, "op") == 0) {
      if (strcmp(val, "part") == 0) c.op = 1;
      else if (strcmp(val, "plain") == 0) c.op = 0;
      else return false;
    } else {
      return false;
    }
  }
  if (c.nth < 1 || c.count < 1) return false;
  // A zero-length stall is a typo, not a fault: reject like nth=0.
  if (c.action == Action::kStallLink && c.stall_ms < 1) return false;
  // op=part names the partitioned-push domain, which only issue-level
  // actions (OnPartIssue) ever consult — on a frame action it is a typo.
  if (c.op != 0 && (IsFrameAction(c.action) || c.action == Action::kNone))
    return false;
  *out = c;
  return true;
}

bool ParseSchedule(const char* spec, Config* out, int cap, int* n) {
  if (spec == nullptr || *spec == '\0' || out == nullptr || n == nullptr)
    return false;
  Config parsed[kMaxSpecs];
  int k = 0;
  const char* p = spec;
  while (*p != '\0') {
    const char* semi = strchr(p, ';');
    const size_t len = semi != nullptr ? static_cast<size_t>(semi - p)
                                       : strlen(p);
    char seg[256];
    if (len == 0 || len >= sizeof seg) return false;
    memcpy(seg, p, len);
    seg[len] = '\0';
    if (k >= cap || k >= kMaxSpecs) return false;
    if (!ParseSpec(seg, &parsed[k])) return false;
    k++;
    p = semi != nullptr ? semi + 1 : p + len;
    // A trailing ';' means a segment is MISSING (half a schedule survived
    // shell quoting) — refuse rather than arm a truncated experiment.
    if (semi != nullptr && *p == '\0') return false;
  }
  if (k == 0) return false;
  for (int i = 0; i < k; i++) out[i] = parsed[i];
  *n = k;
  return true;
}

int FormatSpec(const Config& c, char* buf, size_t cap) {
  size_t off = 0;
  const auto puts_ = [&](const char* s) -> bool {
    const size_t len = strlen(s);
    if (off + len + 1 > cap) return false;
    memcpy(buf + off, s, len + 1);
    off += len;
    return true;
  };
  const auto put = [&](const char* key, long long v) -> bool {
    char kv[48];
    snprintf(kv, sizeof kv, ":%s=%lld", key, v);
    return puts_(kv);
  };
  if (!puts_(ActionName(c.action))) return -1;
  if (c.rank >= 0 && !put("rank", c.rank)) return -1;
  if (c.kind == 1 && !puts_(":kind=send")) return -1;
  if (c.kind == 2 && !puts_(":kind=recv")) return -1;
  if (c.op == 1 && !puts_(":op=part")) return -1;
  if (c.peer >= 0 && !put("peer", c.peer)) return -1;
  if (c.subflow >= 0 && !put("subflow", c.subflow)) return -1;
  if (c.nth != 1 && !put("nth", c.nth)) return -1;
  if (c.count != 1 && !put("count", c.count)) return -1;
  if (c.action == Action::kDelay && c.delay_us != 1000 &&
      !put("us", static_cast<long long>(c.delay_us)))
    return -1;
  if (c.action == Action::kStallLink && c.stall_ms != 10 &&
      !put("ms", static_cast<long long>(c.stall_ms)))
    return -1;
  if (c.err != 0 && !put("err", c.err)) return -1;
  return static_cast<int>(off);
}

bool ExpandChaos(const char* spec, int np, char* out, size_t cap) {
  if (spec == nullptr || *spec == '\0' || out == nullptr || np < 1)
    return false;
  uint64_t seed = 0;
  bool have_seed = false;
  int faults = 3;
  bool mix_issue = false, mix_wire = false, mix_kill = false,
       mix_part = false, have_mix = false;
  const char* p = spec;
  char tok[96];
  while (*p != '\0') {
    size_t i = 0;
    while (*p != '\0' && *p != ':') {
      if (i + 1 >= sizeof tok) return false;
      tok[i++] = *p++;
    }
    tok[i] = '\0';
    if (*p == ':') p++;
    if (i == 0) return false;
    char* eq = strchr(tok, '=');
    if (eq == nullptr) return false;
    *eq = '\0';
    const char* val = eq + 1;
    if (strcmp(tok, "seed") == 0) {
      if (!StrictU64(val, &seed)) return false;
      have_seed = true;
    } else if (strcmp(tok, "faults") == 0) {
      uint64_t f = 0;
      if (!StrictU64(val, &f) || f < 1 ||
          f > static_cast<uint64_t>(kMaxSpecs))
        return false;
      faults = static_cast<int>(f);
    } else if (strcmp(tok, "mix") == 0) {
      have_mix = true;
      const char* q = val;
      while (*q != '\0') {
        const char* comma = strchr(q, ',');
        const size_t len =
            comma != nullptr ? static_cast<size_t>(comma - q) : strlen(q);
        if (len == 5 && strncmp(q, "issue", 5) == 0) mix_issue = true;
        else if (len == 4 && strncmp(q, "wire", 4) == 0) mix_wire = true;
        else if (len == 4 && strncmp(q, "kill", 4) == 0) mix_kill = true;
        else if (len == 4 && strncmp(q, "part", 4) == 0) mix_part = true;
        else return false;
        q = comma != nullptr ? comma + 1 : q + len;
      }
    } else {
      return false;
    }
  }
  if (!have_seed) return false;
  if (!have_mix) mix_issue = mix_wire = true;
  if (!mix_issue && !mix_wire && !mix_kill && !mix_part) return false;

  // splitmix64: fixed-width, overflow-defined, identical on every
  // platform — the whole point is `acxrun -print-chaos` and every rank
  // agreeing on the schedule forever.
  uint64_t x = seed ^ 0x9e3779b97f4a7c15ull;
  const auto rnd = [&x]() {
    uint64_t z = (x += 0x9e3779b97f4a7c15ull);
    z ^= z >> 30;
    z *= 0xbf58476d1ce4e5b9ull;
    z ^= z >> 27;
    z *= 0x94d049bb133111ebull;
    z ^= z >> 31;
    return z;
  };

  int classes[4];
  int ncls = 0;
  if (mix_issue) classes[ncls++] = 0;
  if (mix_wire) classes[ncls++] = 1;
  if (mix_kill) classes[ncls++] = 2;
  if (mix_part) classes[ncls++] = 3;
  bool kill_used = false;
  size_t off = 0;
  // Trigger windows already handed out, per (rank, match domain). Two
  // same-rank specs of the same domain (issue-level vs wire-level — each
  // has its own matched counter) with overlapping [nth, nth+count) windows
  // would SHADOW each other: the first in-window spec in schedule order
  // wins every attempt, the later spec can never fire, and the oracle
  // rightly calls a scheduled-but-impossible fault a failed experiment.
  struct Win {
    int rank, domain, lo, hi;
  };
  Win wins[kMaxSpecs];
  int nwins = 0;
  const auto overlaps = [&](int rank, int domain, int lo, int hi) {
    for (int w = 0; w < nwins; w++)
      if (wins[w].rank == rank && wins[w].domain == domain &&
          lo < wins[w].hi && wins[w].lo < hi)
        return true;
    return false;
  };
  for (int i = 0; i < faults; i++) {
    int cls = classes[i % ncls];
    // At most ONE abrupt death per schedule: a second kill would race the
    // first victim's respawn and make the run order-dependent.
    if (cls == 2 && kill_used)
      cls = mix_wire ? 1 : (mix_issue ? 0 : (mix_part ? 3 : 1));
    Config c;
    c.rank = static_cast<int>(rnd() % static_cast<uint64_t>(np));
    c.nth = 2 + static_cast<int>(rnd() % 10);
    c.count = 1 + static_cast<int>(rnd() % 2);
    if (cls == 0) {
      // Recoverable by construction: drop (retried) or delay (waited out)
      // — never `fail`, which would make the seeded workload fail by
      // design instead of surviving.
      const uint64_t pick = rnd() % 3;
      c.action = pick < 2 ? Action::kDrop : Action::kDelay;
      if (c.action == Action::kDelay) c.delay_us = 500 + rnd() % 4500;
    } else if (cls == 1) {
      static const Action kWire[4] = {Action::kDropFrame,
                                      Action::kCorruptFrame,
                                      Action::kStallLink, Action::kCloseLink};
      c.action = kWire[rnd() % 4];
      if (c.action == Action::kStallLink) c.stall_ms = 10 + rnd() % 40;
      if (c.action == Action::kCloseLink) c.count = 1;
    } else if (cls == 3) {
      // Partitioned-push domain (op=part): recoverable by the same
      // construction as `issue` — a dropped Pready push is re-pushed
      // after the policy backoff, a delayed one is merely late.
      const uint64_t pick = rnd() % 3;
      c.action = pick < 2 ? Action::kDrop : Action::kDelay;
      if (c.action == Action::kDelay) c.delay_us = 500 + rnd() % 4500;
      c.op = 1;
    } else {
      c.action = Action::kKill;
      c.count = 1;
      c.nth = 4 + static_cast<int>(rnd() % 8);
      kill_used = true;
    }
    // De-shadow: re-roll the window until it is disjoint from every prior
    // same-(rank, domain) window; as a deterministic last resort place it
    // right after the occupied region. All rolls come from the seeded
    // stream, so the schedule stays identical for a given (seed, np).
    {
      // Three disjoint match domains, three independent window spaces:
      // issue-level (OnIssue), wire-level (OnFrame), partitioned
      // (OnPartIssue, op=part).
      const int domain = IsFrameAction(c.action) ? 1 : (c.op != 0 ? 2 : 0);
      const int base = c.action == Action::kKill ? 4 : 2;
      const int range = c.action == Action::kKill ? 8 : 10;
      for (int t = 0; t < 16 && overlaps(c.rank, domain, c.nth,
                                         c.nth + c.count); t++)
        c.nth = base + static_cast<int>(rnd() % range);
      if (overlaps(c.rank, domain, c.nth, c.nth + c.count)) {
        int hi = base;
        for (int w = 0; w < nwins; w++)
          if (wins[w].rank == c.rank && wins[w].domain == domain &&
              wins[w].hi > hi)
            hi = wins[w].hi;
        c.nth = hi;
      }
      if (nwins < kMaxSpecs)
        wins[nwins++] = Win{c.rank, domain, c.nth, c.nth + c.count};
    }
    char sbuf[128];
    if (FormatSpec(c, sbuf, sizeof sbuf) < 0) return false;
    const size_t need = strlen(sbuf) + (i > 0 ? 1 : 0);
    if (off + need + 1 > cap) return false;
    if (i > 0) out[off++] = ';';
    memcpy(out + off, sbuf, strlen(sbuf) + 1);
    off += strlen(sbuf);
  }
  return true;
}

void Configure(const Config& cfg) { ConfigureSchedule(&cfg, 1); }

void ConfigureSchedule(const Config* cfgs, int n) { Install(&S(), cfgs, n); }

namespace {

// Shared firing tail of OnIssue/OnPartIssue: the two entry points differ
// only in which match domain their filter admits.
Action FireIssueWinner(State& s, int winner, int rank, uint64_t* delay_us,
                       int* err) {
  SpecState& sp = s.specs[winner];
  const Config& c = sp.cfg;
  sp.fired.fetch_add(1, std::memory_order_relaxed);
  switch (c.action) {
    case Action::kDrop:
      s.drops.fetch_add(1, std::memory_order_relaxed);
      break;
    case Action::kDelay:
      s.delays.fetch_add(1, std::memory_order_relaxed);
      if (delay_us != nullptr) *delay_us = c.delay_us;
      break;
    case Action::kFail:
      s.fails.fetch_add(1, std::memory_order_relaxed);
      if (err != nullptr) *err = c.err != 0 ? c.err : kErrInjected;
      break;
    case Action::kKill:
      // Abrupt death, by design indistinguishable from the OOM-killer:
      // no dump, no finalize, no graceful LEFT. The note below is the
      // only trace (SIGKILL cannot be caught) — acxrun -chaos and the
      // oracle key on the supervisor's SIGKILL observation, not on this.
      s.kills.fetch_add(1, std::memory_order_relaxed);
      std::fprintf(stderr, "tpu-acx[%d]: fault kill: raising SIGKILL\n",
                   rank);
      std::fflush(stderr);
      raise(SIGKILL);
      for (;;) pause();  // unreachable; SIGKILL cannot be handled
    default:
      break;
  }
  return c.action;
}

}  // namespace

Action OnIssue(int rank, bool is_send, int peer, uint64_t* delay_us,
               int* err) {
  State& s = S();
  const int n = s.nspecs.load(std::memory_order_relaxed);
  int winner = -1;
  for (int i = 0; i < n; i++) {
    SpecState& sp = s.specs[i];
    if (!PassesIssueFilters(sp.cfg, rank, is_send, peer, false)) continue;
    const uint64_t m = sp.matched.fetch_add(1, std::memory_order_relaxed) + 1;
    // Every matching spec counts this attempt (its nth= coordinate must
    // advance even while another spec fires); the FIRST in-window spec in
    // schedule order supplies the action.
    if (winner < 0 && InWindow(sp.cfg, m)) winner = i;
  }
  if (winner < 0) return Action::kNone;
  return FireIssueWinner(s, winner, rank, delay_us, err);
}

Action OnPartIssue(int rank, bool is_send, int peer, uint64_t* delay_us,
                   int* err) {
  State& s = S();
  const int n = s.nspecs.load(std::memory_order_relaxed);
  int winner = -1;
  for (int i = 0; i < n; i++) {
    SpecState& sp = s.specs[i];
    if (!PassesIssueFilters(sp.cfg, rank, is_send, peer, true)) continue;
    const uint64_t m = sp.matched.fetch_add(1, std::memory_order_relaxed) + 1;
    if (winner < 0 && InWindow(sp.cfg, m)) winner = i;
  }
  if (winner < 0) return Action::kNone;
  return FireIssueWinner(s, winner, rank, delay_us, err);
}

Action OnFrame(int rank, int peer, int subflow, uint64_t* stall_us) {
  State& s = S();
  const int n = s.nspecs.load(std::memory_order_relaxed);
  int winner = -1;
  for (int i = 0; i < n; i++) {
    SpecState& sp = s.specs[i];
    if (!PassesFrameFilters(sp.cfg, rank, peer, subflow)) continue;
    const uint64_t m = sp.matched.fetch_add(1, std::memory_order_relaxed) + 1;
    if (winner < 0 && InWindow(sp.cfg, m)) winner = i;
  }
  if (winner < 0) return Action::kNone;
  SpecState& sp = s.specs[winner];
  const Config& c = sp.cfg;
  sp.fired.fetch_add(1, std::memory_order_relaxed);
  switch (c.action) {
    case Action::kDropFrame:
      s.frame_drops.fetch_add(1, std::memory_order_relaxed);
      break;
    case Action::kCorruptFrame:
      s.frame_corrupts.fetch_add(1, std::memory_order_relaxed);
      break;
    case Action::kStallLink:
      s.link_stalls.fetch_add(1, std::memory_order_relaxed);
      if (stall_us != nullptr) *stall_us = c.stall_ms * 1000;
      break;
    case Action::kCloseLink:
      s.link_closes.fetch_add(1, std::memory_order_relaxed);
      break;
    default:
      break;
  }
  return c.action;
}

Stats stats() {
  State& s = S();
  Stats out;
  out.drops = s.drops.load(std::memory_order_relaxed);
  out.delays = s.delays.load(std::memory_order_relaxed);
  out.fails = s.fails.load(std::memory_order_relaxed);
  out.kills = s.kills.load(std::memory_order_relaxed);
  out.frame_drops = s.frame_drops.load(std::memory_order_relaxed);
  out.frame_corrupts = s.frame_corrupts.load(std::memory_order_relaxed);
  out.link_stalls = s.link_stalls.load(std::memory_order_relaxed);
  out.link_closes = s.link_closes.load(std::memory_order_relaxed);
  return out;
}

int ScheduleSize() { return S().nspecs.load(std::memory_order_relaxed); }

uint64_t SpecMatched(int i) {
  State& s = S();
  if (i < 0 || i >= s.nspecs.load(std::memory_order_relaxed)) return 0;
  return s.specs[i].matched.load(std::memory_order_relaxed);
}

uint64_t SpecFired(int i) {
  State& s = S();
  if (i < 0 || i >= s.nspecs.load(std::memory_order_relaxed)) return 0;
  return s.specs[i].fired.load(std::memory_order_relaxed);
}

int WriteReport(int rank) {
  const char* prefix = getenv("ACX_FAULT_REPORT");
  if (prefix == nullptr || prefix[0] == '\0') return 1;
  State& s = S();
  const std::string fn = std::string(prefix) + ".rank" +
                         std::to_string(rank) + ".fault.json";
  FILE* f = std::fopen(fn.c_str(), "w");
  if (f == nullptr) {
    std::fprintf(stderr, "tpu-acx: fault: cannot write %s\n", fn.c_str());
    return -1;
  }
  std::fprintf(f, "{\"rank\":%d,\"specs\":[", rank);
  const int n = s.nspecs.load(std::memory_order_relaxed);
  for (int i = 0; i < n; i++) {
    const Config& c = s.specs[i].cfg;
    char sbuf[192];
    if (FormatSpec(c, sbuf, sizeof sbuf) < 0) sbuf[0] = '\0';
    std::fprintf(f,
                 "%s\n {\"spec\":\"%s\",\"action\":\"%s\",\"rank\":%d,"
                 "\"kind\":%d,\"op\":%d,\"peer\":%d,\"subflow\":%d,\"nth\":%d,"
                 "\"count\":%d,\"matched\":%llu,\"fired\":%llu}",
                 i > 0 ? "," : "", sbuf, ActionName(c.action), c.rank,
                 c.kind, c.op, c.peer, c.subflow, c.nth, c.count,
                 (unsigned long long)s.specs[i].matched.load(
                     std::memory_order_relaxed),
                 (unsigned long long)s.specs[i].fired.load(
                     std::memory_order_relaxed));
  }
  const Stats st = stats();
  std::fprintf(f,
               "],\n\"stats\":{\"drops\":%llu,\"delays\":%llu,"
               "\"fails\":%llu,\"kills\":%llu,\"frame_drops\":%llu,"
               "\"frame_corrupts\":%llu,\"link_stalls\":%llu,"
               "\"link_closes\":%llu}}\n",
               (unsigned long long)st.drops, (unsigned long long)st.delays,
               (unsigned long long)st.fails, (unsigned long long)st.kills,
               (unsigned long long)st.frame_drops,
               (unsigned long long)st.frame_corrupts,
               (unsigned long long)st.link_stalls,
               (unsigned long long)st.link_closes);
  std::fclose(f);
  return 0;
}

}  // namespace fault
}  // namespace acx
