#include "acx/flightrec.h"

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "acx/api_internal.h"
#include "acx/fault.h"
#include "acx/state.h"
#include "acx/trace.h"
#include "acx/transport.h"

// The ring is deliberately racy (torn records are tolerated diagnostics,
// see acx/flightrec.h); teach TSAN builds not to flag the by-design races
// in the writer and the dump reader.
#if defined(__SANITIZE_THREAD__)
#define ACX_NO_TSAN __attribute__((no_sanitize("thread")))
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define ACX_NO_TSAN __attribute__((no_sanitize("thread")))
#else
#define ACX_NO_TSAN
#endif
#else
#define ACX_NO_TSAN
#endif

namespace acx {
namespace flight {
namespace {

// Ring storage. Sized once from ACX_FLIGHT_EVENTS (rounded up to a power
// of two so the index wrap is a mask, not a modulo). Writers bump `head`
// with one relaxed fetch_add and then fill the claimed record with plain
// stores — no locks, no fences. A dump that races a writer reads at most
// one torn record per writer thread; the reader treats events as
// diagnostic, not authoritative.
struct Ring {
  Event* buf = nullptr;
  uint64_t mask = 0;
  uint64_t cap = 0;
  std::atomic<uint64_t> head{0};
};

Ring& ring() {
  static Ring* r = [] {
    Ring* r = new Ring;
    uint64_t cap = 8192;
    const char* e = std::getenv("ACX_FLIGHT_EVENTS");
    if (e != nullptr) cap = strtoull(e, nullptr, 10);
    if (cap > 0) {
      uint64_t p2 = 1;
      while (p2 < cap && p2 < (1ull << 24)) p2 <<= 1;
      r->buf = static_cast<Event*>(std::calloc(p2, sizeof(Event)));
      if (r->buf != nullptr) {
        r->cap = p2;
        r->mask = p2 - 1;
      }
    }
    return r;
  }();
  return *r;
}

std::atomic<int> g_rank{-1};
std::atomic<uint64_t> g_stall_warns{0};
std::atomic<uint64_t> g_hang_dumps{0};
std::atomic<uint64_t> g_dumps_written{0};

int RankForDump() {
  int r = g_rank.load(std::memory_order_relaxed);
  if (r >= 0) return r;
  return trace::EnvRankOr(0);
}

uint64_t EnvMsToNs(const char* name, uint64_t def_ms) {
  const char* e = std::getenv(name);
  uint64_t ms = def_ms;
  if (e != nullptr) ms = strtoull(e, nullptr, 10);
  return ms * 1000000ull;
}

const char* kKindNames[] = {
    "none",
    "isend_enqueue", "irecv_enqueue", "trigger_fired", "isend_issued",
    "irecv_issued", "op_completed", "wait_observed", "op_timeout",
    "op_retry", "op_parked", "op_resumed", "op_drained", "slot_reclaimed",
    "op_fault",
    "psend_slot", "precv_slot", "pready_mark", "pready_wire", "parrived",
    "tx_data", "tx_rts", "tx_ack", "tx_seqack", "tx_nak",
    "rx_data", "rx_frame", "rx_seqack", "rx_nak",
    "link_recovering", "link_up", "peer_dead",
    "barrier_enter", "barrier_exit", "stall_warn", "hang_dump",
    "init", "finalize",
};
static_assert(sizeof(kKindNames) / sizeof(kKindNames[0]) == kKindCount,
              "kind-name table out of sync with flight::Kind");

const char* OpKindName(OpKind k) {
  switch (k) {
    case OpKind::kIsend: return "isend";
    case OpKind::kIrecv: return "irecv";
    case OpKind::kPready: return "pready";
    case OpKind::kParrived: return "parrived";
    default: return "none";
  }
}

const char* HealthName(PeerHealth h) {
  switch (h) {
    case PeerHealth::kRecovering: return "recovering";
    case PeerHealth::kDead: return "dead";
    default: return "healthy";
  }
}

// Fatal-signal flusher (registered with trace.cc's crash registry). Gated
// on $ACX_FLIGHT being set: a crash dump to an implicit cwd path would
// litter test runs that deliberately kill ranks; when the operator asked
// for flight files by naming a prefix, the dying rank writes one.
void DumpOnCrash() {
  if (std::getenv("ACX_FLIGHT") != nullptr) Dump(nullptr, "fatal-signal");
}

}  // namespace

const char* KindName(uint16_t k) {
  return k < kKindCount ? kKindNames[k] : "unknown";
}

bool Enabled() {
  static const bool on = [] {
    const bool v = ring().cap > 0;
    if (v) trace::RegisterCrashFlusher(DumpOnCrash, /*on_exit=*/false);
    return v;
  }();
  return on;
}

ACX_NO_TSAN
void Record(uint16_t kind, int32_t slot, int32_t peer, int32_t tag,
            uint64_t seq, int16_t aux, uint64_t span) {
  Ring& r = ring();
  if (r.cap == 0) return;
  const uint64_t i = r.head.fetch_add(1, std::memory_order_relaxed) & r.mask;
  Event& e = r.buf[i];
  e.t_ns = NowNs();
  e.seq = seq;
  e.span = span;
  e.slot = slot;
  e.peer = peer;
  e.tag = tag;
  e.kind = kind;
  e.aux = aux;
}

void SetRank(int rank) {
  g_rank.store(rank, std::memory_order_relaxed);
  (void)Enabled();  // size the ring + arm the crash hook up front
}

uint64_t StallWarnNs() {
  static const uint64_t ns = EnvMsToNs("ACX_STALL_WARN_MS", 10000);
  return ns;
}

uint64_t HangDumpNs() {
  static const uint64_t ns = EnvMsToNs("ACX_HANG_DUMP_MS", 30000);
  return ns;
}

void NoteStallWarn() {
  g_stall_warns.fetch_add(1, std::memory_order_relaxed);
}

void NoteHangDump() { g_hang_dumps.fetch_add(1, std::memory_order_relaxed); }

Stats stats() {
  Stats s;
  Ring& r = ring();
  s.recorded = r.head.load(std::memory_order_relaxed);
  s.capacity = r.cap;
  s.stall_warns = g_stall_warns.load(std::memory_order_relaxed);
  s.hang_dumps = g_hang_dumps.load(std::memory_order_relaxed);
  s.dumps_written = g_dumps_written.load(std::memory_order_relaxed);
  return s;
}

ACX_NO_TSAN
int Dump(const char* prefix, const char* reason) {
  if (prefix == nullptr) prefix = std::getenv("ACX_FLIGHT");
  if (prefix == nullptr || prefix[0] == '\0') prefix = "acx";
  const int rank = RankForDump();
  // Stack filename + raw-write warning: this body runs from the
  // fatal-signal flusher (DumpOnCrash), where std::string construction and
  // fprintf on a shared stream are off-contract (DESIGN.md §18, rule 5).
  char fn[512];
  std::snprintf(fn, sizeof fn, "%s.rank%d.flight.json", prefix, rank);
  FILE* f = std::fopen(fn, "w");
  if (f == nullptr) {
    trace::WriteErrNote("tpu-acx: flight: cannot write ", fn);
    return -1;
  }
  const uint64_t now = NowNs();
  ApiState& g = GS();
  const int size = g.transport != nullptr ? g.transport->size() : 0;

  std::fprintf(f,
               "{\"rank\":%d,\"size\":%d,\"reason\":\"%s\",\"now_ns\":%llu,\n",
               rank, size, reason != nullptr ? reason : "explicit",
               (unsigned long long)now);
  std::fprintf(f,
               "\"config\":{\"events_cap\":%llu,\"stall_warn_ms\":%llu,"
               "\"hang_dump_ms\":%llu},\n",
               (unsigned long long)ring().cap,
               (unsigned long long)(StallWarnNs() / 1000000ull),
               (unsigned long long)(HangDumpNs() / 1000000ull));
  {
    const Stats s = stats();
    std::fprintf(f,
                 "\"stats\":{\"recorded\":%llu,\"stall_warns\":%llu,"
                 "\"hang_dumps\":%llu,\"dumps_written\":%llu},\n",
                 (unsigned long long)s.recorded,
                 (unsigned long long)s.stall_warns,
                 (unsigned long long)s.hang_dumps,
                 (unsigned long long)(s.dumps_written + 1));
  }

  // Live slot table: point-in-time, read racily (the proxy may transition
  // slots mid-snapshot; a dump must never take its locks — this path runs
  // from signal context). Every non-AVAILABLE slot below the watermark.
  std::fprintf(f, "\"slots\":[");
  bool first = true;
  if (g.table != nullptr) {
    const size_t wm = g.table->watermark();
    for (size_t i = 0; i < wm; i++) {
      const int32_t st = g.table->Load((int)i, std::memory_order_relaxed);
      if (st == kAvailable) continue;
      const Op& op = g.table->op((int)i);
      const uint64_t since = op.watch_since_ns;
      const double age_ms =
          (since != 0 && now > since) ? (now - since) / 1e6 : 0.0;
      std::fprintf(f,
                   "%s\n {\"slot\":%zu,\"state\":\"%s\",\"kind\":\"%s\","
                   "\"peer\":%d,\"tag\":%d,\"bytes\":%zu,\"partition\":%d,"
                   "\"attempts\":%u,\"error\":%d,\"age_ms\":%.1f,"
                   "\"span\":%llu}",
                   first ? "" : ",", i, FlagName(st), OpKindName(op.kind),
                   op.peer, op.tag, op.bytes, op.partition, op.attempts,
                   op.status.error, age_ms,
                   (unsigned long long)op.span);
      first = false;
    }
  }
  std::fprintf(f, "],\n");

  // Per-peer link clocks: health plus the wire's epoch/seq/ack counters
  // (best-effort — the transport refuses to block for them).
  std::fprintf(f, "\"peers\":[");
  first = true;
  if (g.transport != nullptr) {
    const int self = g.transport->rank();
    for (int r = 0; r < size; r++) {
      if (r == self) continue;
      // Relaxed form: the dump must never block on the transport mutex
      // (this body can run from a fatal-signal handler).
      const PeerHealth h = g.transport->peer_health_relaxed(r);
      LinkClock lc;
      const bool have = g.transport->link_clock(r, &lc);
      std::fprintf(f,
                   "%s\n {\"rank\":%d,\"health\":\"%s\",\"have_clock\":%s,"
                   "\"epoch\":%u,\"tx_seq\":%llu,\"rx_seq\":%llu,"
                   "\"acked_rx\":%llu,\"replay_bytes\":%llu}",
                   first ? "" : ",", r, HealthName(h),
                   have ? "true" : "false", lc.epoch,
                   (unsigned long long)lc.tx_seq,
                   (unsigned long long)lc.rx_seq,
                   (unsigned long long)lc.acked_rx,
                   (unsigned long long)lc.replay_bytes);
      first = false;
    }
  }
  std::fprintf(f, "],\n");

  // The ring, oldest-first. Snapshot the head once; records written after
  // that by racing threads show up as at most one torn event each.
  std::fprintf(f, "\"events\":[");
  first = true;
  {
    Ring& r = ring();
    const uint64_t head = r.head.load(std::memory_order_relaxed);
    const uint64_t n = head < r.cap ? head : r.cap;
    for (uint64_t k = 0; k < n; k++) {
      const Event e = r.buf[(head - n + k) & r.mask];
      std::fprintf(f,
                   "%s\n {\"t_ns\":%llu,\"kind\":\"%s\",\"slot\":%d,"
                   "\"peer\":%d,\"tag\":%d,\"seq\":%llu,\"aux\":%d,"
                   "\"span\":%llu}",
                   first ? "" : ",", (unsigned long long)e.t_ns,
                   KindName(e.kind), e.slot, e.peer, e.tag,
                   (unsigned long long)e.seq, (int)e.aux,
                   (unsigned long long)e.span);
      first = false;
    }
  }
  std::fprintf(f, "]}\n");
  std::fclose(f);
  g_dumps_written.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

}  // namespace flight
}  // namespace acx
