#include "acx/proxy.h"

#include <chrono>
#include <cstdio>
#include <cstdlib>

#include "acx/debug.h"
#include "acx/fault.h"
#include "acx/flightrec.h"
#include "acx/membership.h"
#include "acx/metrics.h"
#include "acx/trace.h"
#include "acx/tseries.h"

namespace acx {

Proxy::Proxy(FlagTable* table, Transport* transport)
    : table_(table), transport_(transport) {}

Proxy::~Proxy() { Stop(); }

void Proxy::Start() {
  if (running_.exchange(true)) return;
  exit_.store(false, std::memory_order_release);
  thread_ = std::thread([this] { Run(); });
}

void Proxy::Stop() {
  if (!running_.exchange(false)) return;
  exit_.store(true, std::memory_order_release);
  Kick();
  if (thread_.joinable()) thread_.join();
}

void Proxy::Kick() {
  kicks_.fetch_add(1, std::memory_order_release);
  std::lock_guard<std::mutex> lk(idle_mu_);
  idle_cv_.notify_all();
}

bool Proxy::TryProgress() {
  TryMutexLock lk(sweep_mu_);
  if (!lk.owns()) return false;  // another thread is already sweeping
  const bool progressed = Sweep();
  if (progressed) sweeps_.fetch_add(1, std::memory_order_relaxed);
  return progressed;
}

int Proxy::CancelInflight() {
  // Exclusive sweep: no concurrent Sweep may race the flag stores below.
  MutexLock lk(sweep_mu_);
  int count = 0;
  const size_t n = table_->watermark();
  for (size_t i = 0; i < n; i++) {
    const int32_t f = table_->Load(i);
    if (f != kPending && f != kIssued && f != kRecovering) continue;
    Op& op = table_->op(i);
    int err = kErrTimeout;
    if (op.peer >= 0 &&
        transport_->peer_health(op.peer) != PeerHealth::kHealthy)
      err = kErrPeerDead;
    op.status = Status{op.peer, op.tag, err, 0};
    // Flight events that read op fields must be recorded BEFORE the release
    // store of COMPLETED: once the waiter observes it, it may Free() the
    // slot and Op::Reset() races with any later read of the op.
    ACX_FLIGHT_SPAN(kOpDrained, i, op.peer, op.tag, op.attempts, err,
                    op.span);
    table_->Store(i, kCompleted);
    ACX_TRACE_SPAN("op_drained", i, op.span);
    if (metrics::Enabled()) metrics::MarkComplete(i);
    count++;
  }
  if (count != 0)
    ops_completed_.fetch_add(static_cast<uint64_t>(count),
                             std::memory_order_relaxed);
  return count;
}

Proxy::Stats Proxy::stats() const {
  Stats s;
  s.sweeps = sweeps_.load(std::memory_order_relaxed);
  s.ops_issued = ops_issued_.load(std::memory_order_relaxed);
  s.ops_completed = ops_completed_.load(std::memory_order_relaxed);
  s.slots_reclaimed = slots_reclaimed_.load(std::memory_order_relaxed);
  s.retries = retries_.load(std::memory_order_relaxed);
  s.timeouts = timeouts_.load(std::memory_order_relaxed);
  return s;
}

namespace {

// Arm the per-op deadline once, at the FIRST issue attempt — including a
// dropped one, so an op whose every post is swallowed still times out.
void ArmDeadlineFirstAttempt(Op& op) {
  if (op.attempts != 0 || op.deadline_ns != 0) return;
  const uint64_t t = Policy().timeout_ns.load(std::memory_order_relaxed);
  if (t != 0) op.deadline_ns = NowNs() + t;
}

// Exponential backoff: policy seed on the first retry, doubling after,
// capped at 1s per step.
void ArmRetryBackoff(Op& op) {
  constexpr uint32_t kCapUs = 1000000;
  if (op.backoff_us == 0) {
    uint64_t b = Policy().backoff_us.load(std::memory_order_relaxed);
    if (b < 1) b = 1;
    if (b > kCapUs) b = kCapUs;
    op.backoff_us = static_cast<uint32_t>(b);
  } else if (op.backoff_us < kCapUs) {
    op.backoff_us = op.backoff_us * 2 < kCapUs ? op.backoff_us * 2 : kCapUs;
  }
  op.retry_at_ns = NowNs() + static_cast<uint64_t>(op.backoff_us) * 1000;
}

}  // namespace

bool Proxy::IssueOp(size_t i, Op& op, Stats& local, bool from_pending) {
  const bool is_send = op.kind == OpKind::kIsend;
  bool consult = true;
  if (from_pending) {
    if (op.not_before_ns != 0) {
      // Injected-delay gate: hold the op in PENDING until it opens, then
      // post WITHOUT re-consulting the fault plane (one fault, one delay).
      if (NowNs() < op.not_before_ns) return false;
      op.not_before_ns = 0;
      consult = false;
    } else {
      // Fresh trigger (first launch or graph re-fire): reset bookkeeping so
      // a re-fired graph op gets a fresh deadline and retry budget (and a
      // fresh watchdog clock — a re-fire is not a stall).
      op.attempts = 0;
      op.deadline_ns = 0;
      op.retry_at_ns = 0;
      op.backoff_us = 0;
      op.watch_since_ns = 0;
      op.watch_stage = 0;
    }
  }
  if (consult && fault::Enabled()) {
    uint64_t delay_us = 0;
    int err = 0;
    switch (fault::OnIssue(transport_->rank(), is_send, op.peer, &delay_us,
                           &err)) {
      case fault::Action::kDelay:
        if (from_pending)
          op.not_before_ns = NowNs() + delay_us * 1000;
        else
          op.retry_at_ns = NowNs() + delay_us * 1000;
        ACX_TRACE_EVENT("fault_delay", i);
        ACX_FLIGHT(kOpFault, i, op.peer, op.tag, op.attempts,
                   (int16_t)fault::Action::kDelay);
        return true;
      case fault::Action::kFail:
        op.status = Status{op.peer, op.tag, err, 0};
        ACX_FLIGHT(kOpFault, i, op.peer, op.tag, op.attempts,
                   (int16_t)fault::Action::kFail);
        ACX_FLIGHT_SPAN(kOpCompleted, i, op.peer, op.tag, op.attempts, err,
                        op.span);
        table_->Store(i, kCompleted);
        ACX_TRACE_SPAN("fault_fail", i, op.span);
        if (metrics::Enabled()) metrics::MarkComplete(i);
        local.ops_completed++;
        return true;
      case fault::Action::kDrop:
        // The post is swallowed: the op sits ISSUED with no ticket until
        // CheckStalled's backoff timer re-posts it. Not counted in
        // ops_issued — nothing reached the wire.
        ArmDeadlineFirstAttempt(op);
        op.attempts++;
        ArmRetryBackoff(op);
        delete op.ticket;
        op.ticket = nullptr;
        if (from_pending) table_->Store(i, kIssued);
        ACX_TRACE_EVENT("fault_drop", i);
        ACX_FLIGHT(kOpFault, i, op.peer, op.tag, op.attempts,
                   (int16_t)fault::Action::kDrop);
        return true;
      default:
        break;
    }
  }
  ArmDeadlineFirstAttempt(op);
  op.attempts++;
  // Graph re-fire: a relaunch moves COMPLETED->PENDING with the previous
  // launch's ticket still attached; reclaim it first.
  delete op.ticket;
  if (is_send) {
    ACX_DLOG("slot %zu: isend %zuB -> peer %d tag %d", i, op.bytes, op.peer,
             op.tag);
    op.ticket = transport_->Isend(op.sbuf, op.bytes, op.peer, op.tag, op.ctx,
                                  op.span);
    if (from_pending) table_->Store(i, kIssued);
    ACX_TRACE_SPAN("isend_issued", i, op.span);
    ACX_FLIGHT_SPAN(kIsendIssued, i, op.peer, op.tag, op.attempts,
                    op.partition, op.span);
    if (metrics::Enabled()) metrics::MarkIssue(i, true, op.bytes);
  } else {
    ACX_DLOG("slot %zu: irecv %zuB <- peer %d tag %d", i, op.bytes, op.peer,
             op.tag);
    op.ticket = transport_->Irecv(op.rbuf, op.bytes, op.peer, op.tag, op.ctx,
                                  op.span);
    if (from_pending) table_->Store(i, kIssued);
    ACX_TRACE_SPAN("irecv_issued", i, op.span);
    ACX_FLIGHT_SPAN(kIrecvIssued, i, op.peer, op.tag, op.attempts,
                    op.partition, op.span);
    if (metrics::Enabled()) metrics::MarkIssue(i, false, op.bytes);
  }
  local.ops_issued++;
  return true;
}

bool Proxy::CheckStalled(size_t i, Op& op, Stats& local) {
  // Hot path: a posted op with no deadline has nothing to police — return
  // without reading the clock.
  const bool unposted = op.ticket == nullptr;
  if (!unposted && op.deadline_ns == 0) return false;
  const uint64_t now = NowNs();
  if (op.deadline_ns != 0 && now >= op.deadline_ns) {
    op.status = Status{op.peer, op.tag, kErrTimeout, 0};
    ACX_FLIGHT_SPAN(kOpTimeout, i, op.peer, op.tag, op.attempts, kErrTimeout,
                    op.span);
    table_->Store(i, kCompleted);
    ACX_TRACE_SPAN("op_timeout", i, op.span);
    if (metrics::Enabled()) metrics::MarkComplete(i);
    local.timeouts++;
    local.ops_completed++;
    return true;
  }
  // Only an op whose post was LOST (no ticket) may be re-issued; a posted
  // op is already live on a reliable transport — re-posting would
  // double-send. Posted ops are governed by the deadline alone.
  if (!unposted || now < op.retry_at_ns) return false;
  if (op.attempts > Policy().max_retries.load(std::memory_order_relaxed)) {
    op.status = Status{op.peer, op.tag, kErrTimeout, 0};
    ACX_FLIGHT_SPAN(kOpTimeout, i, op.peer, op.tag, op.attempts, kErrTimeout,
                    op.span);
    table_->Store(i, kCompleted);
    ACX_TRACE_SPAN("op_timeout", i, op.span);
    if (metrics::Enabled()) metrics::MarkComplete(i);
    local.timeouts++;
    local.ops_completed++;
    return true;
  }
  local.retries++;
  ACX_TRACE_SPAN("op_retry", i, op.span);
  ACX_FLIGHT_SPAN(kOpRetry, i, op.peer, op.tag, op.attempts, 0, op.span);
  return IssueOp(i, op, local, false);
}

bool Proxy::Sweep() {
  bool progressed = false;
  Stats local{};
  // Only [0, watermark) can hold live slots (lowest-free-slot allocation);
  // with K concurrent ops this is a K-entry walk, not O(nflags).
  const size_t n = table_->watermark();
  if (metrics::Enabled() && n > 0)
    metrics::MaxGauge(metrics::kSlotHighWater, n);
  for (size_t i = 0; i < n; i++) {
    const int32_t f = table_->Load(i);
    Op& op = table_->op(i);
    switch (f) {
      case kPending: {
        switch (op.kind) {
          case OpKind::kIsend:
          case OpKind::kIrecv:
            progressed |= IssueOp(i, op, local, /*from_pending=*/true);
            break;
          case OpKind::kPready: {
            // Send-side partition became ready (host call or device-mirrored
            // flag write): push it to the wire and complete the slot.
            //
            // Partition-push fault gate (op=part specs, acx/fault.h): a
            // DELAYED push is held in PENDING until the gate opens; a
            // DROPPED push is swallowed and held for the policy backoff
            // (Pready has no ticket, so the plain retry ladder never
            // polices it — the hold IS its re-push path, and the late
            // partition exercises the receiver's arrival deadline). The
            // reopened push goes out WITHOUT re-consulting: one fault, one
            // hold. FAIL error-completes the partition slot; the waiter
            // surfaces it from HostWaitPartitioned.
            bool push = true;
            if (op.not_before_ns != 0) {
              if (NowNs() < op.not_before_ns) {
                push = false;
              } else {
                op.not_before_ns = 0;
              }
            } else if (fault::Enabled()) {
              uint64_t delay_us = 0;
              int err = 0;
              const fault::Action a = fault::OnPartIssue(
                  transport_->rank(), /*is_send=*/true, op.peer, &delay_us,
                  &err);
              if (a == fault::Action::kDelay) {
                op.not_before_ns = NowNs() + delay_us * 1000;
                push = false;
                progressed = true;
                ACX_TRACE_EVENT("fault_delay", i);
                ACX_FLIGHT(kOpFault, i, op.peer, op.tag, 0,
                           (int16_t)fault::Action::kDelay);
              } else if (a == fault::Action::kDrop) {
                uint64_t b =
                    Policy().backoff_us.load(std::memory_order_relaxed);
                if (b < 1) b = 1;
                op.not_before_ns = NowNs() + b * 1000;
                push = false;
                progressed = true;
                ACX_TRACE_EVENT("fault_drop", i);
                ACX_FLIGHT(kOpFault, i, op.peer, op.tag, 0,
                           (int16_t)fault::Action::kDrop);
              } else if (a == fault::Action::kFail) {
                op.status = Status{op.peer, op.tag, err, 0};
                ACX_FLIGHT(kOpFault, i, op.peer, op.tag, 0,
                           (int16_t)fault::Action::kFail);
                ACX_FLIGHT_SPAN(kOpCompleted, i, op.peer, op.tag, 0, err,
                                op.span);
                table_->Store(i, kCompleted);
                ACX_TRACE_SPAN("fault_fail", i, op.span);
                if (metrics::Enabled()) metrics::MarkComplete(i);
                local.ops_completed++;
                push = false;
                progressed = true;
              }
            }
            if (push) {
              op.chan->Pready(op.partition);
              ACX_FLIGHT_SPAN(kPreadyWire, i, op.peer, op.tag, 0,
                              op.partition, op.span);
              table_->Store(i, kCompleted);
              ACX_TRACE_SPAN("pready_wire", i, op.span);
              if (metrics::Enabled())
                metrics::Add(metrics::kOpsPready, 1);
              local.ops_completed++;
              progressed = true;
            }
            break;
          }
          default:
            std::fprintf(stderr,
                         "tpu-acx proxy: invalid PENDING op kind %d slot %zu\n",
                         static_cast<int>(op.kind), i);
            transport_->Abort(13);
        }
        break;
      }
      case kIssued: {
        switch (op.kind) {
          case OpKind::kIsend:
          case OpKind::kIrecv: {
            // op.status is written before the release store of COMPLETED, so
            // any thread that acquires COMPLETED sees a coherent status (the
            // reference needed a mutex here; see its init.cpp:119-141).
            if (op.ticket != nullptr && op.ticket->Test(&op.status)) {
              ACX_FLIGHT_SPAN(kOpCompleted, i, op.peer, op.tag, op.attempts,
                              op.status.error, op.span);
              table_->Store(i, kCompleted);
              ACX_TRACE_SPAN("op_completed", i, op.span);
              if (metrics::Enabled()) metrics::MarkComplete(i);
              local.ops_completed++;
              progressed = true;
            } else if (op.peer >= 0 &&
                       transport_->peer_health(op.peer) ==
                           PeerHealth::kRecovering) {
              // Peer's link is reconnecting (DESIGN.md §9): park the op so
              // the deadline/retry police don't fail it for the outage.
              // Parked time is credited back when the op resumes.
              op.parked_at_ns = NowNs();
              table_->Store(i, kRecovering);
              ACX_TRACE_SPAN("op_parked", i, op.span);
              ACX_FLIGHT_SPAN(kOpParked, i, op.peer, op.tag, op.attempts, 0,
                              op.span);
              progressed = true;
            } else if (CheckStalled(i, op, local)) {
              progressed = true;
            }
            break;
          }
          case OpKind::kParrived: {
            if (op.chan->Parrived(op.partition)) {
              ACX_FLIGHT_SPAN(kParrived, i, op.peer, op.tag, 0, op.partition,
                              op.span);
              table_->Store(i, kCompleted);
              ACX_TRACE_SPAN("parrived", i, op.span);
              if (metrics::Enabled())
                metrics::Add(metrics::kOpsParrived, 1);
              local.ops_completed++;
              progressed = true;
            } else if (op.deadline_ns != 0 && NowNs() >= op.deadline_ns) {
              // An abandoned partition never arrives (its sender died, or
              // healed past this round and will not redo it); without a
              // deadline here the waiter spins forever — these slots have
              // no ticket, so CheckStalled never polices them.
              op.status = Status{op.peer, op.tag, kErrTimeout, 0};
              ACX_FLIGHT_SPAN(kOpTimeout, i, op.peer, op.tag, 0, kErrTimeout,
                              op.span);
              table_->Store(i, kCompleted);
              ACX_TRACE_SPAN("op_timeout", i, op.span);
              if (metrics::Enabled()) metrics::MarkComplete(i);
              local.timeouts++;
              local.ops_completed++;
              progressed = true;
            }
            break;
          }
          default:
            break;  // kPready never sits in ISSUED
        }
        break;
      }
      case kRecovering: {
        // Parked on a reconnecting link. Test first: the replay machinery
        // can complete the op mid-recovery, and a failed recovery completes
        // the ticket with kErrPeerDead — both surface here.
        if (op.ticket != nullptr && op.ticket->Test(&op.status)) {
          ACX_FLIGHT_SPAN(kOpCompleted, i, op.peer, op.tag, op.attempts,
                          op.status.error, op.span);
          table_->Store(i, kCompleted);
          ACX_TRACE_SPAN("op_completed", i, op.span);
          if (metrics::Enabled()) metrics::MarkComplete(i);
          local.ops_completed++;
          progressed = true;
        } else if (op.peer < 0 || transport_->peer_health(op.peer) !=
                                      PeerHealth::kRecovering) {
          // Link healed (or the verdict is in and the ticket will report
          // it next pass). Credit the parked time against the deadline.
          if (op.deadline_ns != 0 && op.parked_at_ns != 0)
            op.deadline_ns += NowNs() - op.parked_at_ns;
          op.parked_at_ns = 0;
          table_->Store(i, kIssued);
          ACX_TRACE_SPAN("op_resumed", i, op.span);
          ACX_FLIGHT_SPAN(kOpResumed, i, op.peer, op.tag, op.attempts, 0,
                          op.span);
          progressed = true;
        }
        break;
      }
      case kCleanup: {
        // First-class reclaim state (fixes the reference's slot leak).
        // op.ticket is deleted inside FlagTable::Free. Capture the span
        // first — Free resets the Op.
        const uint64_t reclaimed_span = op.span;
        std::free(op.owner);
        op.owner = nullptr;
        table_->Free(static_cast<int>(i));
        ACX_TRACE_SPAN("slot_reclaimed", i, reclaimed_span);
        ACX_FLIGHT_SPAN(kSlotReclaimed, i, -1, -1, 0, 0, reclaimed_span);
        local.slots_reclaimed++;
        progressed = true;
        break;
      }
      default:
        break;  // AVAILABLE / RESERVED / COMPLETED need no proxy action
    }
  }
  if (local.ops_issued) ops_issued_.fetch_add(local.ops_issued, std::memory_order_relaxed);
  if (local.ops_completed) ops_completed_.fetch_add(local.ops_completed, std::memory_order_relaxed);
  if (local.slots_reclaimed) slots_reclaimed_.fetch_add(local.slots_reclaimed, std::memory_order_relaxed);
  if (local.retries) retries_.fetch_add(local.retries, std::memory_order_relaxed);
  if (local.timeouts) timeouts_.fetch_add(local.timeouts, std::memory_order_relaxed);
  return progressed;
}

bool Proxy::WatchdogScan(uint64_t now) {
  const uint64_t warn_ns = flight::StallWarnNs();
  const uint64_t dump_ns = flight::HangDumpNs();
  bool do_dump = false;
  const size_t n = table_->watermark();
  for (size_t i = 0; i < n; i++) {
    const int32_t f = table_->Load(i);
    Op& op = table_->op(i);
    if (f != kPending && f != kIssued && f != kRecovering) {
      // Not in flight: hands off. Writing the watch fields here would race
      // with Op::Reset() on Free from the consuming thread. Slots freed
      // through Free() come back zeroed; persistent partitioned slots are
      // re-armed by MPIX_Start / MPIX_Pready while the app thread owns them.
      continue;
    }
    if (op.watch_since_ns == 0) {
      op.watch_since_ns = now;
      op.watch_stage = 0;
      continue;
    }
    const uint64_t age = now - op.watch_since_ns;
    if (op.watch_stage == 0 && warn_ns != 0 && age >= warn_ns) {
      op.watch_stage = 1;
      flight::NoteStallWarn();
      ACX_FLIGHT(kStallWarn, i, op.peer, op.tag, op.attempts,
                 op.partition);
      // Structured one-line stall report: enough to attribute the wait
      // without a dump — slot identity, peer link clocks, replay state.
      const PeerHealth h = op.peer >= 0 ? transport_->peer_health(op.peer)
                                        : PeerHealth::kHealthy;
      LinkClock lc;
      const bool have_lc =
          op.peer >= 0 && transport_->link_clock(op.peer, &lc);
      std::fprintf(
          stderr,
          "tpu-acx: stall: rank=%d slot=%zu state=%s kind=%d peer=%d "
          "tag=%d part=%d age_ms=%llu attempts=%u peer_health=%d "
          "epoch=%u tx_seq=%llu rx_seq=%llu acked_rx=%llu "
          "replay_bytes=%llu (warn at ACX_STALL_WARN_MS=%llu)\n",
          transport_->rank(), i, FlagName(f), (int)op.kind, op.peer,
          op.tag, op.partition, (unsigned long long)(age / 1000000ull),
          op.attempts, (int)h, have_lc ? lc.epoch : 0,
          (unsigned long long)(have_lc ? lc.tx_seq : 0),
          (unsigned long long)(have_lc ? lc.rx_seq : 0),
          (unsigned long long)(have_lc ? lc.acked_rx : 0),
          (unsigned long long)(have_lc ? lc.replay_bytes : 0),
          (unsigned long long)(warn_ns / 1000000ull));
    }
    if (op.watch_stage <= 1 && dump_ns != 0 && age >= dump_ns) {
      op.watch_stage = 2;
      flight::NoteHangDump();
      ACX_FLIGHT(kHangDump, i, op.peer, op.tag, op.attempts, op.partition);
      do_dump = true;
    }
  }
  return do_dump;
}

void Proxy::Run() {
  // Backoff ladder: spin a few sweeps, then yield, then sleep with
  // exponential growth capped at 200us; park on the condvar when the table
  // is fully idle. Kick() wakes us immediately in all cases.
  int idle_sweeps = 0;
  // Stall watchdog cadence: thresholds are env-latched once; when armed,
  // the clock is read only every 64 loop iterations (the sweep itself must
  // not pay a clock read per pass) and the scan runs at quarter-threshold
  // granularity, clamped to [10ms, 1s].
  const uint64_t wd_warn = flight::StallWarnNs();
  const uint64_t wd_dump = flight::HangDumpNs();
  const bool wd_armed =
      (wd_warn != 0 || wd_dump != 0) && flight::Enabled();
  uint64_t wd_interval = 0;
  if (wd_armed) {
    uint64_t base = wd_warn != 0 && (wd_dump == 0 || wd_warn < wd_dump)
                        ? wd_warn
                        : wd_dump;
    wd_interval = base / 4;
    if (wd_interval < 10000000ull) wd_interval = 10000000ull;
    if (wd_interval > 1000000000ull) wd_interval = 1000000000ull;
  }
  uint64_t wd_next = wd_armed ? NowNs() + wd_interval : 0;
  unsigned wd_tick = 0;
  // Busy/idle split for the metrics plane ("proxy idle fraction"): clocks
  // are only read when ACX_METRICS is armed.
  const bool mx = metrics::Enabled();
  // Live telemetry plane (DESIGN.md §13): the sweep loop is the sampler's
  // clock. Disabled costs this one latched bool; enabled, the off-interval
  // cost is one clock read + compare per pass inside MaybeSample.
  const bool ts = tseries::Enabled();
  while (!exit_.load(std::memory_order_acquire)) {
    const uint64_t kicks_before = kicks_.load(std::memory_order_acquire);
    bool progressed;
    const uint64_t t_sweep = mx ? NowNs() : 0;
    {
      MutexLock lk(sweep_mu_);
      progressed = Sweep();
    }
    if (mx) {
      const uint64_t dt = NowNs() - t_sweep;
      metrics::Add(metrics::kProxyBusyNs, dt);
      metrics::Observe(metrics::kProxySweepNs, dt);
    }
    if (ts) tseries::MaybeSample(transport_);
    sweeps_.fetch_add(1, std::memory_order_relaxed);
    // Watchdog: cheap modular tick so the hot sweep loop reads the clock
    // at most once per 64 iterations; the slow idle branches below nap
    // long enough that 64 ticks still bounds detection latency well under
    // any sane threshold.
    if (wd_armed && (++wd_tick & 63u) == 0) {
      const uint64_t now = NowNs();
      if (now >= wd_next) {
        wd_next = now + wd_interval;
        bool do_dump;
        {
          MutexLock lk(sweep_mu_);
          do_dump = WatchdogScan(now);
        }
        if (do_dump) {
          // Dump outside sweep_mu_: Dump reads the table racily by design
          // and must never extend the lock hold time of the hot path.
          std::fprintf(stderr,
                       "tpu-acx: hang: rank=%d in-flight op(s) exceeded "
                       "ACX_HANG_DUMP_MS=%llu — writing flight dump\n",
                       transport_->rank(),
                       (unsigned long long)(wd_dump / 1000000ull));
          flight::Dump(nullptr, "watchdog");
        }
      }
    }
    if (progressed) {
      idle_sweeps = 0;
      continue;
    }
    idle_sweeps++;
    // Membership plane (DESIGN.md §12): a fleet-epoch bump means a peer
    // joined, left, or was declared dead since the last pass — resweep
    // right away so parked ops see the new verdict (e.g. a RECOVERING op
    // whose peer's slot was taken over by a joining incarnation) instead
    // of napping through it.
    {
      const uint64_t fe = Fleet().epoch();
      if (fe != fleet_epoch_seen_) {
        const bool first = fleet_epoch_seen_ == 0;
        fleet_epoch_seen_ = fe;
        if (!first) {
          ACX_TRACE_EVENT("fleet_epoch", static_cast<size_t>(fe));
          idle_sweeps = 0;
          continue;
        }
      }
    }
    if (table_->active.load(std::memory_order_relaxed) == 0) {
      // Nothing in flight: keep the transport's background protocol alive
      // (heartbeats, dead-peer checks), then park until work arrives. The
      // 50ms wait bound doubles as the heartbeat cadence floor.
      transport_->Tick();
      const uint64_t t_idle = mx ? NowNs() : 0;
      std::unique_lock<std::mutex> lk(idle_mu_);
      // wait_until on system_clock, not wait_for: libstdc++'s wait_for
      // takes the pthread_cond_clockwait path, which the GCC-10 libtsan
      // does not intercept — TSAN then never sees the mutex released
      // inside the wait and flags every later Kick() as a double lock.
      // Wall-clock jumps only perturb the 50ms nap; the predicate and
      // the outer loop re-check regardless.
      idle_cv_.wait_until(
          lk, std::chrono::system_clock::now() + std::chrono::milliseconds(50),
          [&] {
            return exit_.load(std::memory_order_acquire) ||
                   kicks_.load(std::memory_order_acquire) != kicks_before ||
                   table_->active.load(std::memory_order_relaxed) != 0;
          });
      if (mx) metrics::Add(metrics::kProxyIdleNs, NowNs() - t_idle);
      idle_sweeps = 0;
    } else if (idle_sweeps < 64) {
      std::this_thread::yield();
    } else {
      transport_->Tick();
      const int exp = idle_sweeps - 64 < 8 ? idle_sweeps - 64 : 8;
      const uint64_t t_idle = mx ? NowNs() : 0;
      std::this_thread::sleep_for(std::chrono::microseconds(1 << exp));
      if (mx) metrics::Add(metrics::kProxyIdleNs, NowNs() - t_idle);
    }
  }
}

}  // namespace acx
