#include "acx/proxy.h"

#include <chrono>
#include <cstdio>
#include <cstdlib>

#include "acx/debug.h"
#include "acx/trace.h"

namespace acx {

Proxy::Proxy(FlagTable* table, Transport* transport)
    : table_(table), transport_(transport) {}

Proxy::~Proxy() { Stop(); }

void Proxy::Start() {
  if (running_.exchange(true)) return;
  exit_.store(false, std::memory_order_release);
  thread_ = std::thread([this] { Run(); });
}

void Proxy::Stop() {
  if (!running_.exchange(false)) return;
  exit_.store(true, std::memory_order_release);
  Kick();
  if (thread_.joinable()) thread_.join();
}

void Proxy::Kick() {
  kicks_.fetch_add(1, std::memory_order_release);
  std::lock_guard<std::mutex> lk(idle_mu_);
  idle_cv_.notify_all();
}

bool Proxy::TryProgress() {
  std::unique_lock<std::mutex> lk(sweep_mu_, std::try_to_lock);
  if (!lk.owns_lock()) return false;  // another thread is already sweeping
  const bool progressed = Sweep();
  if (progressed) sweeps_.fetch_add(1, std::memory_order_relaxed);
  return progressed;
}

Proxy::Stats Proxy::stats() const {
  Stats s;
  s.sweeps = sweeps_.load(std::memory_order_relaxed);
  s.ops_issued = ops_issued_.load(std::memory_order_relaxed);
  s.ops_completed = ops_completed_.load(std::memory_order_relaxed);
  s.slots_reclaimed = slots_reclaimed_.load(std::memory_order_relaxed);
  return s;
}

bool Proxy::Sweep() {
  bool progressed = false;
  Stats local{};
  // Only [0, watermark) can hold live slots (lowest-free-slot allocation);
  // with K concurrent ops this is a K-entry walk, not O(nflags).
  const size_t n = table_->watermark();
  for (size_t i = 0; i < n; i++) {
    const int32_t f = table_->Load(i);
    Op& op = table_->op(i);
    switch (f) {
      case kPending: {
        switch (op.kind) {
          case OpKind::kIsend:
            ACX_DLOG("slot %zu: isend %zuB -> peer %d tag %d", i, op.bytes,
                     op.peer, op.tag);
            // Graph re-fire: a relaunch moves COMPLETED->PENDING with the
            // previous launch's ticket still attached; reclaim it first.
            delete op.ticket;
            op.ticket = transport_->Isend(op.sbuf, op.bytes, op.peer, op.tag,
                                          op.ctx);
            table_->Store(i, kIssued);
            ACX_TRACE_EVENT("isend_issued", i);
            local.ops_issued++;
            progressed = true;
            break;
          case OpKind::kIrecv:
            ACX_DLOG("slot %zu: irecv %zuB <- peer %d tag %d", i, op.bytes,
                     op.peer, op.tag);
            delete op.ticket;
            op.ticket = transport_->Irecv(op.rbuf, op.bytes, op.peer, op.tag,
                                          op.ctx);
            table_->Store(i, kIssued);
            ACX_TRACE_EVENT("irecv_issued", i);
            local.ops_issued++;
            progressed = true;
            break;
          case OpKind::kPready:
            // Send-side partition became ready (host call or device-mirrored
            // flag write): push it to the wire and complete the slot.
            op.chan->Pready(op.partition);
            table_->Store(i, kCompleted);
            ACX_TRACE_EVENT("pready_wire", i);
            local.ops_completed++;
            progressed = true;
            break;
          default:
            std::fprintf(stderr,
                         "tpu-acx proxy: invalid PENDING op kind %d slot %zu\n",
                         static_cast<int>(op.kind), i);
            transport_->Abort(13);
        }
        break;
      }
      case kIssued: {
        switch (op.kind) {
          case OpKind::kIsend:
          case OpKind::kIrecv: {
            // op.status is written before the release store of COMPLETED, so
            // any thread that acquires COMPLETED sees a coherent status (the
            // reference needed a mutex here; see its init.cpp:119-141).
            if (op.ticket != nullptr && op.ticket->Test(&op.status)) {
              table_->Store(i, kCompleted);
              ACX_TRACE_EVENT("op_completed", i);
              local.ops_completed++;
              progressed = true;
            }
            break;
          }
          case OpKind::kParrived: {
            if (op.chan->Parrived(op.partition)) {
              table_->Store(i, kCompleted);
              ACX_TRACE_EVENT("parrived", i);
              local.ops_completed++;
              progressed = true;
            }
            break;
          }
          default:
            break;  // kPready never sits in ISSUED
        }
        break;
      }
      case kCleanup: {
        // First-class reclaim state (fixes the reference's slot leak).
        // op.ticket is deleted inside FlagTable::Free.
        std::free(op.owner);
        op.owner = nullptr;
        table_->Free(static_cast<int>(i));
        ACX_TRACE_EVENT("slot_reclaimed", i);
        local.slots_reclaimed++;
        progressed = true;
        break;
      }
      default:
        break;  // AVAILABLE / RESERVED / COMPLETED need no proxy action
    }
  }
  if (local.ops_issued) ops_issued_.fetch_add(local.ops_issued, std::memory_order_relaxed);
  if (local.ops_completed) ops_completed_.fetch_add(local.ops_completed, std::memory_order_relaxed);
  if (local.slots_reclaimed) slots_reclaimed_.fetch_add(local.slots_reclaimed, std::memory_order_relaxed);
  return progressed;
}

void Proxy::Run() {
  // Backoff ladder: spin a few sweeps, then yield, then sleep with
  // exponential growth capped at 200us; park on the condvar when the table
  // is fully idle. Kick() wakes us immediately in all cases.
  int idle_sweeps = 0;
  while (!exit_.load(std::memory_order_acquire)) {
    const uint64_t kicks_before = kicks_.load(std::memory_order_acquire);
    bool progressed;
    {
      std::lock_guard<std::mutex> lk(sweep_mu_);
      progressed = Sweep();
    }
    sweeps_.fetch_add(1, std::memory_order_relaxed);
    if (progressed) {
      idle_sweeps = 0;
      continue;
    }
    idle_sweeps++;
    if (table_->active.load(std::memory_order_relaxed) == 0) {
      // Nothing in flight: park until someone enqueues work.
      std::unique_lock<std::mutex> lk(idle_mu_);
      idle_cv_.wait_for(lk, std::chrono::milliseconds(50), [&] {
        return exit_.load(std::memory_order_acquire) ||
               kicks_.load(std::memory_order_acquire) != kicks_before ||
               table_->active.load(std::memory_order_relaxed) != 0;
      });
      idle_sweeps = 0;
    } else if (idle_sweeps < 64) {
      std::this_thread::yield();
    } else {
      const int exp = idle_sweeps - 64 < 8 ? idle_sweeps - 64 : 8;
      std::this_thread::sleep_for(std::chrono::microseconds(1 << exp));
    }
  }
}

}  // namespace acx
