// tpu-acx: CUDA runtime compat shim over the host execution-queue runtime.
//
// Maps the cuda* names the reference's tests call (include/compat/
// cuda_runtime.h) onto acx::Stream / acx::Graph / acx::GraphExec
// (include/acx/runtime.h). "Device" memory is host memory on this path —
// on-TPU buffers belong to the Python/JAX layer, and the host-plane tests
// exchange host buffers exactly like reference test/src/ring.c does.

#include <cstdlib>
#include <cstring>

#include "acx/runtime.h"
#include "compat/cuda_runtime.h"

namespace {

inline acx::Stream* S(cudaStream_t s) {
  return s == nullptr ? acx::Stream::Default()
                      : reinterpret_cast<acx::Stream*>(s);
}
inline acx::Graph* G(cudaGraph_t g) { return reinterpret_cast<acx::Graph*>(g); }

}  // namespace

extern "C" {

const char* cudaGetErrorName(cudaError_t err) {
  return err == cudaSuccess ? "cudaSuccess" : "acxError";
}

int cudaGetDeviceCount(int* count) {
  // One logical device per rank on the host plane (the proxy path). TPU
  // chip enumeration is the Python layer's job.
  *count = 1;
  return cudaSuccess;
}

int cudaSetDevice(int) { return cudaSuccess; }

int cudaStreamCreate(cudaStream_t* stream) {
  *stream = reinterpret_cast<cudaStream_t>(new acx::Stream());
  return cudaSuccess;
}

int cudaStreamDestroy(cudaStream_t stream) {
  if (stream != nullptr) delete reinterpret_cast<acx::Stream*>(stream);
  return cudaSuccess;
}

int cudaStreamSynchronize(cudaStream_t stream) {
  S(stream)->Sync();
  return cudaSuccess;
}

int cudaStreamBeginCapture(cudaStream_t stream, enum cudaStreamCaptureMode) {
  S(stream)->BeginCapture();
  return cudaSuccess;
}

int cudaStreamEndCapture(cudaStream_t stream, cudaGraph_t* graph) {
  *graph = reinterpret_cast<cudaGraph_t>(S(stream)->EndCapture());
  return cudaSuccess;
}

int cudaGraphCreate(cudaGraph_t* graph, unsigned int) {
  *graph = reinterpret_cast<cudaGraph_t>(new acx::Graph());
  return cudaSuccess;
}

int cudaGraphDestroy(cudaGraph_t graph) {
  delete G(graph);
  return cudaSuccess;
}

int cudaGraphAddChildGraphNode(cudaGraphNode_t* node, cudaGraph_t graph,
                               const cudaGraphNode_t* deps, size_t ndeps,
                               cudaGraph_t child) {
  std::vector<acx::GraphNode*> d;
  for (size_t i = 0; i < ndeps; i++)
    d.push_back(reinterpret_cast<acx::GraphNode*>(deps[i]));
  acx::GraphNode* tail = G(graph)->AddChildGraph(G(child), d);
  if (node) *node = reinterpret_cast<cudaGraphNode_t>(tail);
  return cudaSuccess;
}

int cudaGraphInstantiate(cudaGraphExec_t* exec, cudaGraph_t graph,
                         cudaGraphNode_t* error_node, char* log,
                         size_t log_size) {
  if (error_node) *error_node = nullptr;
  if (log && log_size) log[0] = '\0';
  *exec = reinterpret_cast<cudaGraphExec_t>(new acx::GraphExec(G(graph)));
  return cudaSuccess;
}

int cudaGraphLaunch(cudaGraphExec_t exec, cudaStream_t stream) {
  reinterpret_cast<acx::GraphExec*>(exec)->Launch(S(stream));
  return cudaSuccess;
}

int cudaGraphExecDestroy(cudaGraphExec_t exec) {
  delete reinterpret_cast<acx::GraphExec*>(exec);
  return cudaSuccess;
}

int cudaMemcpy(void* dst, const void* src, size_t count, enum cudaMemcpyKind) {
  std::memcpy(dst, src, count);
  return cudaSuccess;
}

int cudaMemcpyAsync(void* dst, const void* src, size_t count,
                    enum cudaMemcpyKind, cudaStream_t stream) {
  S(stream)->Enqueue([dst, src, count] { std::memcpy(dst, src, count); });
  return cudaSuccess;
}

int cudaMalloc(void** ptr, size_t size) {
  *ptr = std::malloc(size);
  return *ptr != nullptr || size == 0 ? cudaSuccess : cudaErrorInvalidValue;
}

int cudaFree(void* ptr) {
  std::free(ptr);
  return cudaSuccess;
}

int cudaLaunchHostFunc(cudaStream_t stream, cudaHostFn_t fn, void* userData) {
  S(stream)->Enqueue([fn, userData] { fn(userData); });
  return cudaSuccess;
}

}  // extern "C"
