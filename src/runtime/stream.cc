// tpu-acx: stream/graph runtime implementation. See include/acx/runtime.h.

#include "acx/runtime.h"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <unordered_map>

namespace acx {

// ---- Stream -------------------------------------------------------------

Stream::Stream() {
  worker_ = std::thread([this] { Run(); });
}

Stream::~Stream() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    exit_ = true;
  }
  cv_.notify_all();
  if (worker_.joinable()) worker_.join();
}

void Stream::Run() {
  std::unique_lock<std::mutex> lk(mu_);
  for (;;) {
    // !busy_ matters: an EnqueueInstant may be running an item inline with
    // the lock released; popping the next item before it finishes would
    // break in-order execution.
    cv_.wait(lk, [&] { return (exit_ || !q_.empty()) && !busy_; });
    if (exit_ && q_.empty()) return;
    auto fn = std::move(q_.front());
    q_.pop_front();
    busy_ = true;
    lk.unlock();
    fn();
    lk.lock();
    busy_ = false;
    if (q_.empty()) done_cv_.notify_all();
  }
}

// Capture mode: record fn as a graph node chained after the capture tail so
// replay preserves enqueue order. Returns false when not capturing. Caller
// holds mu_.
bool Stream::RecordIfCapturingLocked(std::function<void()>& fn) {
  if (capture_ == nullptr) return false;
  std::vector<GraphNode*> deps;
  if (capture_tail_ != nullptr)
    deps.push_back(static_cast<GraphNode*>(capture_tail_));
  capture_tail_ = capture_->AddNode(std::move(fn), deps);
  return true;
}

void Stream::Enqueue(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (RecordIfCapturingLocked(fn)) return;
    q_.push_back(std::move(fn));
  }
  cv_.notify_all();
}

void Stream::EnqueueInstant(std::function<void()> fn) {
  std::unique_lock<std::mutex> lk(mu_);
  if (RecordIfCapturingLocked(fn)) return;
  if (!q_.empty() || busy_) {
    q_.push_back(std::move(fn));
    lk.unlock();
    cv_.notify_all();
    return;
  }
  // Queue idle: an in-order queue has already "reached" this point, so the
  // item may run right here — saves the worker-thread context switch (the
  // dominant enqueue cost on shared-core hosts). Run it as the worker
  // would: busy_ held, lock released (fn may drive transport progress, so
  // it must not hold mu_ and stall concurrent Enqueue/Sync). busy_ keeps
  // the worker and other EnqueueInstant callers ordered behind us.
  busy_ = true;
  lk.unlock();
  fn();
  lk.lock();
  busy_ = false;
  // Wake the worker only if it has something to act on (items queued while
  // fn ran, or a pending exit) — an unconditional notify would futex-wake
  // the idle worker on every inline op, costing a context switch on
  // shared-core hosts. Sync waiters are gated on busy_ too, so tell them
  // when the stream drains.
  const bool wake_worker = !q_.empty() || exit_;
  const bool drained = q_.empty();
  lk.unlock();
  if (wake_worker) cv_.notify_all();
  if (drained) done_cv_.notify_all();
}

void Stream::Sync() {
  std::unique_lock<std::mutex> lk(mu_);
  done_cv_.wait(lk, [&] { return q_.empty() && !busy_; });
}

void Stream::BeginCapture() {
  std::lock_guard<std::mutex> lk(mu_);
  if (capture_ != nullptr) {
    std::fprintf(stderr, "tpu-acx: nested stream capture not supported\n");
    std::abort();
  }
  capture_ = new Graph();
  capture_tail_ = nullptr;
}

Graph* Stream::EndCapture() {
  std::lock_guard<std::mutex> lk(mu_);
  Graph* g = capture_;
  capture_ = nullptr;
  capture_tail_ = nullptr;
  return g;
}

Stream* Stream::Default() {
  // Leaked intentionally: the default stream lives for the process, like
  // CUDA's stream 0.
  static Stream* s = new Stream();
  return s;
}

// ---- Graph --------------------------------------------------------------

Graph::Graph() : cleanup_(std::make_shared<CleanupSet>()) {}

Graph::~Graph() = default;

GraphNode* Graph::AddNode(std::function<void()> fn,
                          const std::vector<GraphNode*>& deps) {
  nodes_.push_back(std::make_unique<GraphNode>());
  GraphNode* n = nodes_.back().get();
  n->fn = std::move(fn);
  n->deps = deps;
  return n;
}

GraphNode* Graph::AddChildGraph(Graph* child,
                                const std::vector<GraphNode*>& deps) {
  // Copy the child's node closures in, remapping intra-child dependencies;
  // child roots additionally depend on `deps`.
  std::unordered_map<const GraphNode*, GraphNode*> remap;
  GraphNode* tail = nullptr;
  for (const auto& cn : child->nodes_) {
    std::vector<GraphNode*> nd;
    for (GraphNode* d : cn->deps) {
      auto it = remap.find(d);
      if (it != remap.end()) nd.push_back(it->second);
    }
    if (cn->deps.empty()) nd.insert(nd.end(), deps.begin(), deps.end());
    GraphNode* nn = AddNode(cn->fn, nd);
    remap[cn.get()] = nn;
    tail = nn;
  }
  child_cleanups_.push_back(child->cleanup_);
  return tail;
}

void Graph::AddCleanup(std::function<void()> hook) {
  cleanup_->hooks.push_back(std::move(hook));
}

// ---- GraphExec ----------------------------------------------------------

GraphExec::GraphExec(Graph* g) {
  // Kahn topological sort, stable w.r.t. insertion order so capture replays
  // in enqueue order.
  const auto& nodes = g->nodes();
  std::unordered_map<const GraphNode*, size_t> indeg;
  for (const auto& n : nodes) indeg[n.get()] = n->deps.size();
  std::vector<const GraphNode*> ready, order;
  order.reserve(nodes.size());
  for (const auto& n : nodes)
    if (n->deps.empty()) ready.push_back(n.get());
  size_t cursor = 0;
  while (cursor < ready.size()) {
    const GraphNode* n = ready[cursor++];
    order.push_back(n);
    for (const auto& m : nodes) {
      if (std::find(m->deps.begin(), m->deps.end(), n) != m->deps.end()) {
        if (--indeg[m.get()] == 0) ready.push_back(m.get());
      }
    }
  }
  if (order.size() != nodes.size()) {
    std::fprintf(stderr, "tpu-acx: graph has a dependency cycle\n");
    std::abort();
  }
  for (const GraphNode* n : order) seq_.push_back(n->fn);
  cleanups_.push_back(g->cleanup());
  for (auto& c : g->child_cleanups_) cleanups_.push_back(c);
}

void GraphExec::Launch(Stream* s) {
  // Hold the cleanup sets for the duration of this launch so resources
  // outlive in-flight work even if the exec is destroyed immediately after.
  auto keep = cleanups_;
  for (auto& fn : seq_) {
    s->Enqueue([fn, keep] { fn(); });
  }
}

}  // namespace acx
