// tpu-acx: per-subflow wire clocks + the reconnect ladder arithmetic — the
// middle layer of the three-layer net split (DESIGN.md §15). framing.h
// defines what a frame is; this file defines what a link (and each of its
// striped subflows) is OVER TIME: the epoch/seq/ack clock that survives
// reconnects, and the deterministic backoff/deadline math both ends of an
// outage use to agree on how long recovery may take. socket_transport.cc
// owns the sockets and applies these.
//
// Everything here is lock-free plain data + arithmetic (except IoFullTimed,
// which blocks on ONE fd with a deadline) — unit-testable in isolation.
//
// Thread-safety: these structs carry no mutex of their own. Instances live
// inside socket_transport's Peer/Subflow state, which is ACX_GUARDED_BY the
// transport mutex (acx/thread_annotations.h) — the *Locked methods that
// mutate wire clocks run with that capability held, and the analysis checks
// it there, at the owner, not here.
#pragma once

#include <stdint.h>

#include "include/acx/fault.h"

namespace acx {
namespace link_state {

// The wire clock of ONE subflow of one link: epoch names the incarnation,
// tx_seq/rx_seq the per-direction high-waters, acked_rx what we've told the
// peer we have. With striping every subflow runs its own independent clock
// (its own seq space, its own replay buffer) so each heals independently.
struct WireClock {
  uint32_t epoch = 1;
  uint64_t tx_seq = 0;        // last sequence number stamped on a tx frame
  uint64_t rx_seq = 0;        // last in-order sequence number received
  uint64_t acked_rx = 0;      // rx high-water last advertised via SeqAck
  uint32_t rx_since_ack = 0;  // sequenced frames since the last SeqAck
  uint64_t last_nak_ns = 0;   // NAK rate limiter (1ms)
};

// Nominal ladder value for dial attempt `attempt` (1-based):
// `backoff_ms` doubling per attempt, 2s cap. The wait actually scheduled
// is jittered (below); this nominal value is what deadline budgets are
// computed from, so both ends of an outage agree on the total budget.
inline uint64_t DialBackoffMs(uint64_t backoff_ms, int attempt) {
  uint64_t ms = backoff_ms;
  if (ms == 0) ms = 1;
  for (int i = 1; i < attempt && ms < 2000; i++) ms *= 2;
  return ms < 2000 ? ms : 2000;
}

// ±25% jitter on a backoff wait. After a shared fault (a switch blip, a
// rank replaced under rolling restart) every surviving dialer otherwise
// redials on the identical deterministic schedule, thundering-herding the
// victim's rendezvous listener. Cheap LCG on caller-owned state; NOT the
// ladder itself, so budget math (AcceptDeadlineNs) stays deterministic.
inline uint64_t JitteredWaitNs(uint64_t* state, uint64_t nominal_ms) {
  *state = *state * 6364136223846793005ull + 1442695040888963407ull;
  const uint64_t nominal_ns = nominal_ms * 1000000ull;
  const uint64_t span = nominal_ns / 2;  // [0.75x, 1.25x)
  if (span == 0) return nominal_ns;
  return nominal_ns - span / 2 + (*state >> 33) % span;
}

// Total time an acceptor waits for the dialer's ladder to reach it before
// declaring the peer dead: the sum of every nominal backoff plus handshake
// margin plus 25% jitter headroom.
inline uint64_t AcceptDeadlineNs(uint64_t backoff_ms, uint32_t max_attempts) {
  uint64_t total_ms = 1000;  // handshake + scheduling margin
  for (uint32_t a = 1; a <= max_attempts; a++)
    total_ms += DialBackoffMs(backoff_ms, a);
  total_ms += total_ms / 4;
  return total_ms * 1000000ull;
}

// Exact-length IO with a poll-based deadline, for the header-sized
// handshake on a fresh (blocking) reconnect socket. Safe under the
// transport lock: the peer's handshake side runs under its OWN lock, so
// there is no circular wait — worst case is the bounded timeout.
bool IoFullTimed(int fd, void* buf, size_t n, int timeout_ms, bool wr);

}  // namespace link_state
}  // namespace acx
