#include "src/net/stripe.h"

#include <stdlib.h>

namespace acx {
namespace stripe {

Config ConfigFromEnv() {
  Config cfg;
  if (const char* e = getenv("ACX_STRIPES")) {
    const long v = strtol(e, nullptr, 10);
    if (v < 1)
      cfg.stripes = 1;
    else if (v > kMaxStripes)
      cfg.stripes = kMaxStripes;
    else
      cfg.stripes = static_cast<int>(v);
  }
  if (const char* e = getenv("ACX_STRIPE_MIN_BYTES")) {
    const long long v = strtoll(e, nullptr, 10);
    if (v > 0) cfg.min_bytes = static_cast<size_t>(v);
  }
  return cfg;
}

bool ShouldStripe(size_t bytes, int live_subflows, const Config& cfg) {
  if (cfg.stripes <= 1 || live_subflows <= 1) return false;
  if (bytes < cfg.min_bytes) return false;
  // Need at least two chunks for striping to mean anything.
  size_t chunk = bytes / static_cast<size_t>(live_subflows);
  if (chunk > kChunkCap) chunk = kChunkCap;
  if (chunk < kMinChunk) chunk = kMinChunk;
  return bytes > chunk;
}

std::vector<ChunkSpan> PlanChunks(size_t bytes, int live_subflows) {
  if (live_subflows < 1) live_subflows = 1;
  // Even split across lanes, rounded up so the last chunk is the short one.
  size_t chunk =
      (bytes + static_cast<size_t>(live_subflows) - 1) /
      static_cast<size_t>(live_subflows);
  if (chunk > kChunkCap) chunk = kChunkCap;
  if (chunk < kMinChunk) chunk = kMinChunk;
  std::vector<ChunkSpan> out;
  out.reserve(bytes / chunk + 1);
  for (uint64_t off = 0; off < bytes; off += chunk) {
    const uint64_t len =
        (bytes - off < chunk) ? (bytes - off) : static_cast<uint64_t>(chunk);
    out.push_back({off, len});
  }
  return out;
}

}  // namespace stripe
}  // namespace acx
