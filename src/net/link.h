// tpu-acx: byte-stream links — the wire under the stream transport.
//
// The transport's framing/matching engine (stream_transport logic in
// socket_transport.cc) is wire-agnostic; a Link is one full-duplex byte
// stream to one peer with nonblocking semantics. Two implementations:
//   * SockLink — an AF_UNIX stream socket fd (cross-host-capable shape;
//     the role of the reference's network MPI path).
//   * ShmLink — a pair of single-producer/single-consumer byte rings in a
//     shared-memory segment, one ring per direction. This is the same-host
//     fast path, the role MPI's shm BTL plays for the reference's
//     `mpiexec -np N` single-node runs: no syscalls on the data path, just
//     two memcpys and acquire/release counters.
#pragma once

#include <errno.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstdlib>

namespace acx {

class Link {
 public:
  virtual ~Link() = default;
  // Nonblocking; return bytes moved (0 = would block / nothing available).
  // Fatal wire errors terminate the process (matching the abort-style error
  // handling of the reference library, its internal.h CHECK macros) —
  // EXCEPT peer-death errors (EOF, EPIPE, ECONNRESET), which latch
  // alive()=false so the transport can fail the peer's ops in bounded time
  // instead of the whole job dying.
  virtual size_t WriteSome(const char* p, size_t n) = 0;
  virtual size_t ReadSome(char* p, size_t n) = 0;
  // False once the wire itself reported the peer gone. Shm rings have no
  // EOF concept, so ShmLink stays alive forever — dead-peer detection there
  // rides on heartbeats (ACX_HEARTBEAT_MS) instead.
  virtual bool alive() const { return true; }
  // Tear down the wire under the transport (fault injection, desync
  // recovery). The next Read/WriteSome observes the failure and latches
  // alive()=false; links without a teardown concept ignore it.
  virtual void ForceClose() {}
  // Nonblocking gather write: move bytes from up to `n` iovecs in order,
  // returning total bytes moved. Lets the transport put header + borrowed
  // user payload on the wire in one syscall with zero intermediate copies
  // (DESIGN.md §15). Default loops WriteSome per iovec, stopping at the
  // first short write — semantically identical, just more calls.
  virtual size_t WriteVec(const struct iovec* iov, int n) {
    size_t total = 0;
    for (int i = 0; i < n; i++) {
      const size_t w = WriteSome(static_cast<const char*>(iov[i].iov_base),
                                 iov[i].iov_len);
      total += w;
      if (w < iov[i].iov_len) break;
    }
    return total;
  }
};

class SockLink : public Link {
 public:
  explicit SockLink(int fd, int rank, int peer)
      : fd_(fd), rank_(rank), peer_(peer) {}
  ~SockLink() override {
    if (fd_ >= 0) close(fd_);
  }

  size_t WriteSome(const char* p, size_t n) override {
    if (!alive_) return 0;
    // MSG_NOSIGNAL: a write to a closed peer must surface as EPIPE, not a
    // process-killing SIGPIPE — peer death is a recoverable event here.
    ssize_t r = send(fd_, p, n, MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
      if (errno == EPIPE || errno == ECONNRESET) {
        alive_ = false;
        return 0;
      }
      std::fprintf(stderr, "tpu-acx[%d]: write to %d failed: %s\n", rank_,
                   peer_, strerror(errno));
      _exit(14);
    }
    return static_cast<size_t>(r);
  }

  size_t ReadSome(char* p, size_t n) override {
    if (!alive_) return 0;
    ssize_t r = read(fd_, p, n);
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
      if (errno == ECONNRESET) {
        alive_ = false;
        return 0;
      }
      std::fprintf(stderr, "tpu-acx[%d]: read from %d failed: %s\n", rank_,
                   peer_, strerror(errno));
      _exit(14);
    }
    if (r == 0 && n > 0) {
      // EOF: the peer closed its end. Any data it sent before exiting was
      // already drained by earlier reads; latch so the transport can fail
      // this peer's pending ops instead of waiting forever.
      alive_ = false;
      return 0;
    }
    return static_cast<size_t>(r);
  }

  size_t WriteVec(const struct iovec* iov, int n) override {
    if (!alive_ || n <= 0) return 0;
    struct msghdr mh;
    memset(&mh, 0, sizeof mh);
    mh.msg_iov = const_cast<struct iovec*>(iov);
    mh.msg_iovlen = static_cast<size_t>(n);
    ssize_t r = sendmsg(fd_, &mh, MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
      if (errno == EPIPE || errno == ECONNRESET) {
        alive_ = false;
        return 0;
      }
      std::fprintf(stderr, "tpu-acx[%d]: writev to %d failed: %s\n", rank_,
                   peer_, strerror(errno));
      _exit(14);
    }
    return static_cast<size_t>(r);
  }

  bool alive() const override { return alive_; }

  void ForceClose() override {
    // shutdown (not close): the fd number stays reserved until the dtor, so
    // a concurrent accept can't recycle it while the transport still holds
    // this Link. Both directions die; reads see EOF, writes see EPIPE.
    if (fd_ >= 0) shutdown(fd_, SHUT_RDWR);
  }

 private:
  int fd_;
  int rank_, peer_;
  bool alive_ = true;
};

// -- Shared-memory SPSC ring ------------------------------------------------
//
// head/tail are free-running 64-bit byte counters on separate cache lines
// (no false sharing between the producer's and consumer's hot words).
// Producer owns tail, consumer owns head; cross-reads use acquire so payload
// bytes written before the release store of tail are visible to the reader.

struct alignas(64) ShmRingHdr {
  std::atomic<uint64_t> tail{0};  // bytes produced
  char pad0[64 - sizeof(std::atomic<uint64_t>)];
  std::atomic<uint64_t> head{0};  // bytes consumed
  char pad1[64 - sizeof(std::atomic<uint64_t>)];
};
static_assert(sizeof(ShmRingHdr) == 128, "two cache lines");

inline size_t ShmRingWrite(ShmRingHdr* h, char* data, size_t cap,
                           const char* src, size_t n) {
  const uint64_t head = h->head.load(std::memory_order_acquire);
  const uint64_t tail = h->tail.load(std::memory_order_relaxed);
  const size_t space = cap - static_cast<size_t>(tail - head);
  if (n > space) n = space;
  if (n == 0) return 0;
  const size_t pos = static_cast<size_t>(tail % cap);
  const size_t first = n < cap - pos ? n : cap - pos;
  memcpy(data + pos, src, first);
  memcpy(data, src + first, n - first);
  h->tail.store(tail + n, std::memory_order_release);
  return n;
}

inline size_t ShmRingRead(ShmRingHdr* h, char* data, size_t cap, char* dst,
                          size_t n) {
  const uint64_t tail = h->tail.load(std::memory_order_acquire);
  const uint64_t head = h->head.load(std::memory_order_relaxed);
  const size_t avail = static_cast<size_t>(tail - head);
  if (n > avail) n = avail;
  if (n == 0) return 0;
  const size_t pos = static_cast<size_t>(head % cap);
  const size_t first = n < cap - pos ? n : cap - pos;
  memcpy(dst, data + pos, first);
  memcpy(dst + first, data, n - first);
  h->head.store(head + n, std::memory_order_release);
  return n;
}

// Ring capacity sanitizer: a zero ring would wedge every send (WriteSome
// forever returns 0), and a stride not a multiple of 64 would misalign the
// alignas(64) ShmRingHdr atomics of higher slots (UB). Clamp to >= 4 KiB
// and round up to a cache line. acxrun and the env path share this so the
// segment the launcher sizes and the one ranks map always agree.
inline size_t ShmSanitizeRingBytes(uint64_t v) {
  if (v < 4096) v = 4096;
  return static_cast<size_t>((v + 63) & ~uint64_t{63});
}

// Default per-direction ring capacity when ACX_SHM_RING_BYTES is unset.
// Segment length is derived from ring size with no metadata block, so the
// launcher (which sizes the memfd) and every rank (which maps it) must use
// the same default — keep this the single definition.
inline constexpr size_t kShmDefaultRingBytes = 1u << 18;

// Segment geometry: np*(np-1) directed rings, one per ordered rank pair,
// laid out densely. Ring for (i -> j), j != i, lives at slot
// i*(np-1) + (j<i ? j : j-1). Derived identically by acxrun (which sizes the
// segment) and every rank (which maps it) — no metadata block needed.
inline size_t ShmRingSlotBytes(size_t ring_bytes) {
  return sizeof(ShmRingHdr) + ring_bytes;
}
inline size_t ShmSegmentBytes(int np, size_t ring_bytes) {
  return static_cast<size_t>(np) * (np - 1) * ShmRingSlotBytes(ring_bytes);
}
inline char* ShmRingAt(char* base, int np, size_t ring_bytes, int src,
                       int dst) {
  const int slot = src * (np - 1) + (dst < src ? dst : dst - 1);
  return base + static_cast<size_t>(slot) * ShmRingSlotBytes(ring_bytes);
}

class ShmLink : public Link {
 public:
  // base: mapped segment; rank -> peer is the out ring, peer -> rank the in.
  ShmLink(char* base, int np, size_t ring_bytes, int rank, int peer)
      : cap_(ring_bytes) {
    char* out = ShmRingAt(base, np, ring_bytes, rank, peer);
    char* in = ShmRingAt(base, np, ring_bytes, peer, rank);
    out_hdr_ = reinterpret_cast<ShmRingHdr*>(out);
    out_data_ = out + sizeof(ShmRingHdr);
    in_hdr_ = reinterpret_cast<ShmRingHdr*>(in);
    in_data_ = in + sizeof(ShmRingHdr);
  }

  size_t WriteSome(const char* p, size_t n) override {
    return ShmRingWrite(out_hdr_, out_data_, cap_, p, n);
  }
  size_t ReadSome(char* p, size_t n) override {
    return ShmRingRead(in_hdr_, in_data_, cap_, p, n);
  }

 private:
  ShmRingHdr* out_hdr_;
  char* out_data_;
  ShmRingHdr* in_hdr_;
  char* in_data_;
  size_t cap_;
};

}  // namespace acx
