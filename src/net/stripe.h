// tpu-acx: striping policy — pure arithmetic deciding WHETHER a message
// stripes across subflows and HOW it is cut into chunks (DESIGN.md §15).
// No sockets, no locks; socket_transport.cc applies the plan this file
// produces. Unit-tested in ctests/test_framing.cc.
#pragma once

#include <stddef.h>
#include <stdint.h>

#include <vector>

namespace acx {
namespace stripe {

// Hard cap on subflows per peer: 8 lanes is already past the point of
// diminishing returns for socket-buffer aggregation, and the subflow index
// must fit the 8-bit hello ctx field (wire::HelloSubflowCtx).
constexpr int kMaxStripes = 8;

// Chunk sizing bounds. The cap keeps any single chunk's blocking writev
// short enough that round-robin actually interleaves lanes; the floor keeps
// per-chunk header overhead (56B header + 24B ChunkHdr) under ~2%.
constexpr size_t kChunkCap = 1u << 20;   // 1 MiB
constexpr size_t kMinChunk = 4096;

struct Config {
  int stripes = 1;                   // ACX_STRIPES, clamped [1, kMaxStripes]
  size_t min_bytes = 64u << 10;      // ACX_STRIPE_MIN_BYTES
};

// Parse ACX_STRIPES / ACX_STRIPE_MIN_BYTES. Defaults keep the transport
// byte-identical to the single-flow protocol.
Config ConfigFromEnv();

struct ChunkSpan {
  uint64_t offset;
  uint64_t len;
};

// A message stripes iff it meets the size threshold (inclusive: a message
// of exactly min_bytes stripes), more than one lane is live, and the plan
// yields at least two chunks (a single-chunk "stripe" would just be the
// eager path with extra headers).
bool ShouldStripe(size_t bytes, int live_subflows, const Config& cfg);

// Cut `bytes` into chunks for `live_subflows` lanes. Chunk size targets an
// even split across lanes, clamped to [kMinChunk, kChunkCap] — the cap, not
// the lane count, bounds chunk size, so large messages produce MORE chunks
// than lanes and round-robin keeps every lane busy for the whole message.
std::vector<ChunkSpan> PlanChunks(size_t bytes, int live_subflows);

}  // namespace stripe
}  // namespace acx
