// tpu-acx: frame encode/decode + replay records — the bottom layer of the
// three-layer net split (DESIGN.md §15):
//
//   framing   (this file)  — what a frame IS: header construction/sealing,
//                            wire payload lengths, the rendezvous/stripe
//                            descriptor shapes, CRC policy, and the bounded
//                            per-subflow replay buffer of byte-exact frames.
//   link_state             — what a LINK is over time: per-subflow wire
//                            clocks (epoch/seq/ack), the reconnect ladder
//                            arithmetic, hello construction.
//   socket_transport.cc    — who OWNS the sockets: matching queues, the
//                            progress engine, striping policy application.
//
// Nothing here takes the transport lock or touches an fd; everything is
// plain data + arithmetic so it is unit-testable in isolation
// (ctests/test_framing.cc).
#pragma once

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#include <deque>
#include <vector>

#include "src/net/wire.h"

namespace acx {
namespace framing {

// -- wire payload shapes ----------------------------------------------------

#pragma pack(push, 1)
// kMagicRts wire payload: the sender advertises its buffer for a
// process_vm_readv pull (rendezvous, DESIGN.md §5).
struct RvDesc {
  uint64_t addr;
  uint32_t seq;
  int32_t pid;
};
// kMagicAck wire payload.
struct RvAck {
  uint32_t seq;
  int32_t ok;
};
// kMagicStripe wire payload: one per striped message, always on subflow 0.
// The envelope is what occupies the message's position in the per-
// (src,tag,ctx) FIFO matching order; chunks carry the bytes.
struct StripeDesc {
  uint32_t msg_id;      // per-peer-direction message id (chunk pairing key)
  uint32_t nchunks;     // total chunk frames this message was split into
  uint64_t total_bytes; // == envelope hdr.bytes; self-describing on replay
};
// kMagicChunk leading wire payload: 24 bytes of placement metadata, then
// `len` payload bytes. Offset travels explicitly (not derived from index)
// so chunks are self-contained: they reassemble correctly whatever subflow
// they arrive on, in whatever order, including after a lane migration.
struct ChunkHdr {
  uint32_t msg_id;
  uint32_t idx;     // chunk index in [0, nchunks)
  uint64_t offset;  // byte offset of this slice in the full message
  uint64_t len;     // slice length (== frame hdr.bytes)
};
#pragma pack(pop)
static_assert(sizeof(RvDesc) == 16, "wire shape");
static_assert(sizeof(RvAck) == 8, "wire shape");
static_assert(sizeof(StripeDesc) == 16, "wire shape");
static_assert(sizeof(ChunkHdr) == 24, "wire shape");

inline wire::WireHeader MakeHdr(uint32_t magic, int tag, int ctx,
                                uint64_t bytes) {
  wire::WireHeader h{};
  h.magic = magic;
  h.tag = tag;
  h.ctx = ctx;
  h.bytes = bytes;
  return h;
}

// Actual on-wire payload length of a frame. NOT hdr.bytes for RTS/ACK: an
// RTS advertises the full message length in bytes while carrying only the
// 16-byte descriptor, and an ACK advertises 0 while carrying 8. A chunk
// frame advertises its slice length and carries ChunkHdr + slice.
inline size_t WirePayloadLen(const wire::WireHeader& h) {
  switch (h.magic) {
    case wire::kMagicRts: return sizeof(RvDesc);
    case wire::kMagicAck: return sizeof(RvAck);
    case wire::kMagic: return static_cast<size_t>(h.bytes);
    case wire::kMagicStripe: return sizeof(StripeDesc);
    case wire::kMagicChunk: return sizeof(ChunkHdr) +
                                   static_cast<size_t>(h.bytes);
    default: return 0;
  }
}

inline bool KnownMagic(uint32_t m) {
  return m == wire::kMagic || m == wire::kMagicRts || m == wire::kMagicAck ||
         m == wire::kMagicHb || m == wire::kMagicSeqAck ||
         m == wire::kMagicNak || m == wire::kMagicHello ||
         m == wire::kMagicView || m == wire::kMagicStripe ||
         m == wire::kMagicChunk;
}

// Restamp a recorded frame blob ([header|payload]) in place with a new link
// epoch — and, when `new_seq` is non-null, a new sequence number — then
// reseal the header CRC. This is how reconnect adoption re-targets replay
// records at the agreed post-outage epoch, and how lane degradation
// migrates a dead subflow's unacked frames into a survivor's seq space.
inline void RestampFrame(char* blob, uint32_t epoch,
                         const uint64_t* new_seq = nullptr) {
  memcpy(blob + offsetof(wire::WireHeader, epoch), &epoch, sizeof epoch);
  if (new_seq != nullptr)
    memcpy(blob + offsetof(wire::WireHeader, seq), new_seq, sizeof *new_seq);
  const uint32_t hcrc =
      wire::Crc32c(0, blob, offsetof(wire::WireHeader, hcrc));
  memcpy(blob + offsetof(wire::WireHeader, hcrc), &hcrc, sizeof hcrc);
}

inline uint64_t FrameSeq(const char* blob) {
  uint64_t seq;
  memcpy(&seq, blob + offsetof(wire::WireHeader, seq), sizeof seq);
  return seq;
}

// -- replay buffer ----------------------------------------------------------

// One fully-written-but-unacked frame, byte-exact as it went on the wire
// ([header|payload]). `queued` marks a record currently re-enqueued on an
// outq as a raw frame (its blob is borrowed — the record must not be
// popped or evicted until the write completes).
struct ReplayRec {
  uint64_t seq = 0;
  std::vector<char> frame;
  bool queued = false;
};

// Bounded FIFO of replay records for ONE subflow's seq space. Eviction of
// an unacked record breaks replayability — latched in `broken` so a future
// recovery fails loudly instead of replaying a gapped stream.
struct ReplayBuffer {
  std::deque<ReplayRec> recs;
  size_t bytes = 0;
  bool broken = false;

  // Copy a frame in at full-write time. `hdr` is the header as the RECORD
  // should remember it (the caller restores pristine CRCs a corrupt_frame
  // fault poisoned on the wire copy). The payload may be two wire segments
  // (a chunk frame's ChunkHdr + borrowed slice); either may be empty. This
  // copy is the one place the zero-copy send path intentionally copies —
  // replay must outlive the user's buffer. Returns true when the append
  // evicted an unacked record (the broken latch just flipped or
  // re-confirmed).
  bool Record(const wire::WireHeader& hdr, const char* head,
              size_t head_bytes, const char* payload, size_t payload_bytes,
              size_t budget) {
    ReplayRec rec;
    rec.seq = hdr.seq;
    rec.frame.resize(sizeof hdr + head_bytes + payload_bytes);
    memcpy(rec.frame.data(), &hdr, sizeof hdr);
    if (head_bytes != 0)
      memcpy(rec.frame.data() + sizeof hdr, head, head_bytes);
    if (payload_bytes != 0)
      memcpy(rec.frame.data() + sizeof hdr + head_bytes, payload,
             payload_bytes);
    bytes += rec.frame.size();
    recs.push_back(std::move(rec));
    bool evicted = false;
    // A record whose blob is borrowed by an in-flight raw frame pins
    // everything behind it.
    while (bytes > budget && !recs.empty() && !recs.front().queued) {
      bytes -= recs.front().frame.size();
      recs.pop_front();
      broken = true;
      evicted = true;
    }
    return evicted;
  }

  // Peer acknowledged delivery of everything up to `acked`: trim records.
  void AckThrough(uint64_t acked) {
    while (!recs.empty() && !recs.front().queued &&
           recs.front().seq <= acked) {
      bytes -= recs.front().frame.size();
      recs.pop_front();
    }
  }

  // A raw (replay) frame finished writing: release its record's blob.
  void ClearQueued(uint64_t seq) {
    for (auto& rec : recs) {
      if (rec.seq == seq) {
        rec.queued = false;
        return;
      }
    }
  }

  void Clear() {
    recs.clear();
    bytes = 0;
  }
};

}  // namespace framing
}  // namespace acx
