// tpu-acx: SocketTransport — the multi-process data plane.
//
// Plays the role the MPI library plays for the reference (SURVEY.md §2 L0;
// reference src/init.cpp:66-141 posts MPI_Isend/Irecv/Test): nonblocking
// point-to-point with FIFO matching per (src, tag, ctx), partitioned
// channels, and the two control collectives (Barrier, AllreduceInt) the
// runtime and compat layer need.
//
// Wires are AF_UNIX stream socketpairs pre-connected by `acxrun`
// (tools/acxrun.cc), one per peer, passed down via ACX_FDS. All sockets are
// nonblocking; Progress() flushes pending writes and drains arrivals, and is
// driven from Ticket::Test so the proxy's sweep loop is also the transport's
// progress engine. A single mutex serializes the proxy thread and app
// threads — the message-rate ceiling of this backend is host-side anyway
// (on-TPU traffic rides ICI via XLA collectives, not this path).

#include "acx/net.h"

#include <errno.h>
#include <fcntl.h>
#include <sched.h>
#include <string.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <memory>
#include <mutex>
#include <vector>

namespace acx {
namespace {

constexpr uint32_t kMagic = 0xAC0C0101u;

// Internal context ids. User contexts are >= 0; the control plane and the
// partitioned layer get their own namespaces so they can never match user
// point-to-point traffic.
constexpr int kCtrlCtx = -2;
inline int PartCtx(int ctx) { return -1000 - ctx; }
// Partition p of a tag-tagged partitioned channel travels as its own
// message; 4096 partitions per channel (the reference's whole slot table is
// 4096, mpi-acx-internal.h:141, so this bounds nothing in practice).
inline int PartTag(int tag, int p) { return tag * 4096 + p; }

#pragma pack(push, 1)
struct WireHeader {
  uint32_t magic;
  int32_t tag;
  int32_t ctx;
  uint64_t bytes;
};
#pragma pack(pop)

struct SendReq {
  std::vector<char> data;  // header + payload
  size_t off = 0;
  bool done = false;
  Status st;
};

struct RecvReq {
  void* buf = nullptr;
  size_t bytes = 0;
  int src = -1, tag = 0, ctx = 0;
  bool done = false;
  Status st;
};

struct Msg {
  int tag = 0, ctx = 0;
  std::vector<char> payload;
};

// Incoming-byte-stream assembly state for one peer socket.
struct InState {
  WireHeader hdr{};
  size_t hdr_got = 0;
  std::vector<char> payload;
  size_t payload_got = 0;
};

class SocketTransport;

class SockTicket : public Ticket {
 public:
  SockTicket(SocketTransport* t, std::shared_ptr<SendReq> s)
      : t_(t), send_(std::move(s)) {}
  SockTicket(SocketTransport* t, std::shared_ptr<RecvReq> r)
      : t_(t), recv_(std::move(r)) {}
  bool Test(Status* st) override;

 private:
  SocketTransport* t_;
  std::shared_ptr<SendReq> send_;
  std::shared_ptr<RecvReq> recv_;
};

class SocketTransport : public Transport {
 public:
  SocketTransport(int rank, int size, std::vector<int> fds)
      : rank_(rank), size_(size), fds_(std::move(fds)), peers_(size) {
    for (int i = 0; i < size_; i++) {
      if (i == rank_ || fds_[i] < 0) continue;
      const int fl = fcntl(fds_[i], F_GETFL, 0);
      fcntl(fds_[i], F_SETFL, fl | O_NONBLOCK);
    }
  }

  ~SocketTransport() override {
    for (int i = 0; i < size_; i++)
      if (i != rank_ && fds_[i] >= 0) close(fds_[i]);
  }

  int rank() const override { return rank_; }
  int size() const override { return size_; }

  Ticket* Isend(const void* buf, size_t bytes, int dst, int tag,
                int ctx) override {
    std::lock_guard<std::mutex> lk(mu_);
    return IsendLocked(buf, bytes, dst, tag, ctx);
  }

  Ticket* Irecv(void* buf, size_t bytes, int src, int tag, int ctx) override {
    std::lock_guard<std::mutex> lk(mu_);
    return IrecvLocked(buf, bytes, src, tag, ctx);
  }

  PartitionedChan* PsendInit(const void* buf, int partitions,
                             size_t part_bytes, int dst, int tag,
                             int ctx) override;
  PartitionedChan* PrecvInit(void* buf, int partitions, size_t part_bytes,
                             int src, int tag, int ctx) override;

  // Fan-in/fan-out barrier through rank 0 on the control context. The
  // reference gets this from MPI_Barrier for free; sufficient at host-plane
  // process counts.
  void Barrier(int /*ctx*/) override {
    if (rank_ == 0) {
      int token = 0;
      for (int p = 1; p < size_; p++) RecvB(&token, sizeof token, p, 1);
      for (int p = 1; p < size_; p++) SendB(&token, sizeof token, p, 2);
    } else {
      int token = rank_;
      SendB(&token, sizeof token, 0, 1);
      RecvB(&token, sizeof token, 0, 2);
    }
  }

  void AllreduceInt(int32_t* data, int count, int op, int /*ctx*/) override {
    const size_t nb = sizeof(int32_t) * static_cast<size_t>(count);
    if (rank_ == 0) {
      std::vector<int32_t> tmp(count);
      for (int p = 1; p < size_; p++) {
        RecvB(tmp.data(), nb, p, 3);
        for (int i = 0; i < count; i++) {
          switch (op) {
            case 0: data[i] = data[i] > tmp[i] ? data[i] : tmp[i]; break;
            case 1: data[i] = data[i] < tmp[i] ? data[i] : tmp[i]; break;
            default: data[i] += tmp[i]; break;
          }
        }
      }
      for (int p = 1; p < size_; p++) SendB(data, nb, p, 4);
    } else {
      SendB(data, nb, 0, 3);
      RecvB(data, nb, 0, 4);
    }
  }

  void Abort(int code) override {
    std::fprintf(stderr, "tpu-acx[%d]: abort(%d)\n", rank_, code);
    _exit(code);
  }

  // Called from SockTicket::Test.
  bool TestReq(const std::shared_ptr<SendReq>& s,
               const std::shared_ptr<RecvReq>& r, Status* st) {
    std::lock_guard<std::mutex> lk(mu_);
    ProgressLocked();
    if (s) {
      if (s->done && st) *st = s->st;
      return s->done;
    }
    if (r->done && st) *st = r->st;
    return r->done;
  }

 private:
  friend class SockPsendChan;
  friend class SockPrecvChan;

  struct Peer {
    std::deque<std::shared_ptr<SendReq>> outq;
    InState in;
    std::deque<Msg> arrived;                     // unmatched arrivals, FIFO
    std::deque<std::shared_ptr<RecvReq>> posted; // unmatched recvs, FIFO
  };

  Ticket* IsendLocked(const void* buf, size_t bytes, int dst, int tag,
                      int ctx) {
    auto s = std::make_shared<SendReq>();
    s->st = Status{rank_, tag, 0, bytes};
    if (dst == rank_) {
      // Self-send: loop straight back through the matching queues.
      Msg m;
      m.tag = tag;
      m.ctx = ctx;
      m.payload.assign(static_cast<const char*>(buf),
                       static_cast<const char*>(buf) + bytes);
      DeliverLocked(rank_, std::move(m));
      s->done = true;
      return new SockTicket(this, s);
    }
    WireHeader h{kMagic, tag, ctx, bytes};
    s->data.resize(sizeof h + bytes);
    memcpy(s->data.data(), &h, sizeof h);
    memcpy(s->data.data() + sizeof h, buf, bytes);
    peers_[dst].outq.push_back(s);
    FlushOutLocked(dst);
    return new SockTicket(this, s);
  }

  Ticket* IrecvLocked(void* buf, size_t bytes, int src, int tag, int ctx) {
    auto r = std::make_shared<RecvReq>();
    r->buf = buf;
    r->bytes = bytes;
    r->src = src;
    r->tag = tag;
    r->ctx = ctx;
    // Try the unexpected queue first (FIFO per (src, tag, ctx)).
    auto& q = peers_[src].arrived;
    for (auto it = q.begin(); it != q.end(); ++it) {
      if (it->tag == tag && it->ctx == ctx) {
        CompleteRecv(r.get(), src, *it);
        q.erase(it);
        return new SockTicket(this, r);
      }
    }
    peers_[src].posted.push_back(r);
    return new SockTicket(this, r);
  }

  static void CompleteRecv(RecvReq* r, int src, const Msg& m) {
    const size_t n = m.payload.size() < r->bytes ? m.payload.size() : r->bytes;
    memcpy(r->buf, m.payload.data(), n);
    r->st = Status{src, m.tag, 0, n};
    r->done = true;
  }

  void DeliverLocked(int src, Msg&& m) {
    auto& posted = peers_[src].posted;
    for (auto it = posted.begin(); it != posted.end(); ++it) {
      if ((*it)->tag == m.tag && (*it)->ctx == m.ctx) {
        CompleteRecv(it->get(), src, m);
        posted.erase(it);
        return;
      }
    }
    peers_[src].arrived.push_back(std::move(m));
  }

  void FlushOutLocked(int p) {
    auto& q = peers_[p].outq;
    while (!q.empty()) {
      auto& s = q.front();
      ssize_t n = write(fds_[p], s->data.data() + s->off,
                        s->data.size() - s->off);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        std::fprintf(stderr, "tpu-acx[%d]: write to %d failed: %s\n", rank_,
                     p, strerror(errno));
        _exit(14);
      }
      s->off += static_cast<size_t>(n);
      if (s->off == s->data.size()) {
        s->done = true;
        s->data.clear();
        q.pop_front();
      }
    }
  }

  void DrainInLocked(int p) {
    InState& in = peers_[p].in;
    for (;;) {
      if (in.hdr_got < sizeof(WireHeader)) {
        ssize_t n = read(fds_[p], reinterpret_cast<char*>(&in.hdr) + in.hdr_got,
                         sizeof(WireHeader) - in.hdr_got);
        if (n <= 0) {
          if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
          if (n == 0) return;  // peer exited; pending data already drained
          std::fprintf(stderr, "tpu-acx[%d]: read from %d failed: %s\n",
                       rank_, p, strerror(errno));
          _exit(14);
        }
        in.hdr_got += static_cast<size_t>(n);
        if (in.hdr_got < sizeof(WireHeader)) return;
        if (in.hdr.magic != kMagic) {
          std::fprintf(stderr, "tpu-acx[%d]: bad wire magic from %d\n", rank_,
                       p);
          _exit(14);
        }
        in.payload.resize(in.hdr.bytes);
        in.payload_got = 0;
      }
      while (in.payload_got < in.payload.size()) {
        ssize_t n = read(fds_[p], in.payload.data() + in.payload_got,
                         in.payload.size() - in.payload_got);
        if (n <= 0) {
          if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
          if (n == 0) return;
          std::fprintf(stderr, "tpu-acx[%d]: read from %d failed: %s\n",
                       rank_, p, strerror(errno));
          _exit(14);
        }
        in.payload_got += static_cast<size_t>(n);
      }
      Msg m;
      m.tag = in.hdr.tag;
      m.ctx = in.hdr.ctx;
      m.payload = std::move(in.payload);
      in.payload.clear();
      in.hdr_got = 0;
      DeliverLocked(p, std::move(m));
    }
  }

  void ProgressLocked() {
    for (int p = 0; p < size_; p++) {
      if (p == rank_) continue;
      FlushOutLocked(p);
      DrainInLocked(p);
    }
  }

  // Blocking control-plane helpers (used by Barrier/AllreduceInt only).
  void SendB(const void* buf, size_t bytes, int dst, int tag) {
    std::unique_ptr<Ticket> t(Isend(buf, bytes, dst, tag, kCtrlCtx));
    Status st;
    while (!t->Test(&st)) sched_yield();
  }
  void RecvB(void* buf, size_t bytes, int src, int tag) {
    std::unique_ptr<Ticket> t(Irecv(buf, bytes, src, tag, kCtrlCtx));
    Status st;
    while (!t->Test(&st)) sched_yield();
  }

  int rank_, size_;
  std::vector<int> fds_;
  std::vector<Peer> peers_;
  std::mutex mu_;
};

bool SockTicket::Test(Status* st) { return t_->TestReq(send_, recv_, st); }

// -- Partitioned channels -------------------------------------------------
//
// One logical N-partition message per round (reference MPI_Psend_init /
// MPI_Precv_init, partitioned.cu:36-123); each partition travels as an
// independent point-to-point message on (PartTag(tag,p), PartCtx(ctx)), so
// out-of-order Pready works and per-partition arrival is observable — the
// property ring-partitioned.cu's device polling depends on.
//
// Thread-safety contract: Pready/Parrived are called by the proxy while the
// round is in flight; StartRound/FinishRound are called by the app thread
// only when every partition's flag has been observed RESERVED/COMPLETED
// (acquire), which happens-after the proxy's last touch (release) — so no
// extra locking is needed here beyond the transport's own mutex.

class SockPsendChan : public PartitionedChan {
 public:
  SockPsendChan(SocketTransport* t, const void* buf, int parts, size_t pb,
                int dst, int tag, int ctx)
      : t_(t), buf_(static_cast<const char*>(buf)), dst_(dst), tag_(tag),
        ctx_(ctx) {
    partitions = parts;
    part_bytes = pb;
    is_send = true;
    inflight_.reserve(parts);
  }

  void Pready(int p) override {
    inflight_.emplace_back(t_->Isend(buf_ + static_cast<size_t>(p) * part_bytes,
                                     part_bytes, dst_, PartTag(tag_, p),
                                     PartCtx(ctx_)));
  }
  bool Parrived(int) override { return false; }  // send side has no arrivals
  void StartRound() override { inflight_.clear(); }
  void FinishRound(Status* st) override {
    Status tmp;
    for (auto& tk : inflight_) {
      while (!tk->Test(&tmp)) sched_yield();
    }
    if (st) *st = Status{t_->rank(), tag_, 0,
                         part_bytes * static_cast<size_t>(partitions)};
    inflight_.clear();
  }

 private:
  SocketTransport* t_;
  const char* buf_;
  int dst_, tag_, ctx_;
  std::vector<std::unique_ptr<Ticket>> inflight_;
};

class SockPrecvChan : public PartitionedChan {
 public:
  SockPrecvChan(SocketTransport* t, void* buf, int parts, size_t pb, int src,
                int tag, int ctx)
      : t_(t), buf_(static_cast<char*>(buf)), src_(src), tag_(tag), ctx_(ctx),
        tickets_(parts), done_(parts, false) {
    partitions = parts;
    part_bytes = pb;
    is_send = false;
  }

  void Pready(int) override {}
  bool Parrived(int p) override {
    if (done_[p]) return true;
    Status st;
    if (tickets_[p] && tickets_[p]->Test(&st)) {
      done_[p] = true;
      return true;
    }
    return false;
  }
  void StartRound() override {
    for (int p = 0; p < partitions; p++) {
      done_[p] = false;
      tickets_[p].reset(
          t_->Irecv(buf_ + static_cast<size_t>(p) * part_bytes, part_bytes,
                    src_, PartTag(tag_, p), PartCtx(ctx_)));
    }
  }
  void FinishRound(Status* st) override {
    for (int p = 0; p < partitions; p++) {
      while (!Parrived(p)) sched_yield();
      tickets_[p].reset();
    }
    if (st) *st = Status{src_, tag_, 0,
                         part_bytes * static_cast<size_t>(partitions)};
  }

 private:
  SocketTransport* t_;
  char* buf_;
  int src_, tag_, ctx_;
  std::vector<std::unique_ptr<Ticket>> tickets_;
  std::vector<bool> done_;
};

PartitionedChan* SocketTransport::PsendInit(const void* buf, int partitions,
                                            size_t part_bytes, int dst,
                                            int tag, int ctx) {
  return new SockPsendChan(this, buf, partitions, part_bytes, dst, tag, ctx);
}

PartitionedChan* SocketTransport::PrecvInit(void* buf, int partitions,
                                            size_t part_bytes, int src,
                                            int tag, int ctx) {
  return new SockPrecvChan(this, buf, partitions, part_bytes, src, tag, ctx);
}

}  // namespace

Transport* CreateSocketTransport(int rank, int size,
                                 const std::vector<int>& fds) {
  return new SocketTransport(rank, size, fds);
}

Transport* CreateSelfTransport() {
  // A SocketTransport of size 1 is pure loopback: every send routes through
  // DeliverLocked and never touches a socket.
  return new SocketTransport(0, 1, {-1});
}

Transport* CreateTransportFromEnv() {
  const char* size_s = getenv("ACX_SIZE");
  const int size = size_s ? atoi(size_s) : 1;
  if (size <= 1) return CreateSelfTransport();
  const char* rank_s = getenv("ACX_RANK");
  const char* fds_s = getenv("ACX_FDS");
  if (!rank_s || !fds_s) {
    std::fprintf(stderr,
                 "tpu-acx: ACX_SIZE=%d but ACX_RANK/ACX_FDS unset "
                 "(run under acxrun)\n",
                 size);
    exit(13);
  }
  std::vector<int> fds;
  const char* s = fds_s;
  while (*s) {
    fds.push_back(atoi(s));
    const char* c = strchr(s, ',');
    if (!c) break;
    s = c + 1;
  }
  if (static_cast<int>(fds.size()) != size) {
    std::fprintf(stderr, "tpu-acx: ACX_FDS has %zu entries, want %d\n",
                 fds.size(), size);
    exit(13);
  }
  return CreateSocketTransport(atoi(rank_s), size, fds);
}

}  // namespace acx
