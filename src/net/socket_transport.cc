// tpu-acx: StreamTransport — the multi-process data plane.
//
// Plays the role the MPI library plays for the reference (SURVEY.md §2 L0;
// reference src/init.cpp:66-141 posts MPI_Isend/Irecv/Test): nonblocking
// point-to-point with FIFO matching per (src, tag, ctx), partitioned
// channels, and the two control collectives (Barrier, AllreduceInt) the
// runtime and compat layer need.
//
// The framing/matching engine is wire-agnostic over Link (src/net/link.h):
//   * socket plane — AF_UNIX stream socketpairs pre-connected by `acxrun`
//     (tools/acxrun.cc), one per peer, passed down via ACX_FDS;
//   * shm plane — SPSC byte rings in a memfd segment created by acxrun
//     (ACX_SHM_FD), the same-host fast path (no syscalls per message).
// Progress() flushes pending writes and drains arrivals, and is driven from
// Ticket::Test so the proxy's sweep loop is also the transport's progress
// engine. A single mutex serializes the proxy thread and app threads — the
// message-rate ceiling of this backend is host-side anyway (on-TPU traffic
// rides ICI via XLA collectives, not this path).
//
// This file is the TOP layer of the three-layer net split (DESIGN.md §15):
// src/net/framing.h owns frame shapes/CRC/replay records, src/net/
// link_state.h owns the per-subflow wire clocks and reconnect arithmetic,
// src/net/stripe.h owns the striping policy. This file owns sockets,
// matching queues, and the progress engine that applies all three.
//
// Multi-path striping (DESIGN.md §15): with ACX_STRIPES=N > 1, each peer
// link grows N-1 extra "subflow" sockets (lane 0 is the original link;
// lanes 1..N-1 are dialed lazily against the peer's rendezvous listener).
// Every lane runs its own epoch/seq/replay clock and heals independently;
// a lane that cannot be revived degrades the link to the survivors instead
// of killing it. Messages >= ACX_STRIPE_MIN_BYTES travel as a kMagicStripe
// envelope on lane 0 (holding the message's FIFO matching slot) plus
// kMagicChunk slices round-robin across all live lanes, reassembled by
// explicit offset on the receive side. Everything below the threshold — and
// everything at ACX_STRIPES=1, the default — is byte-identical to the
// single-flow protocol.

#include "acx/net.h"

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <sched.h>
#include <stddef.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/prctl.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include <climits>
#include <set>
#include <unordered_map>
#include <unordered_set>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "acx/fault.h"
#include "acx/flightrec.h"
#include "acx/membership.h"
#include "acx/metrics.h"
#include "acx/thread_annotations.h"
#include "acx/trace.h"
#include "src/net/framing.h"
#include "src/net/link.h"
#include "src/net/link_state.h"
#include "src/net/stripe.h"
#include "src/net/wire.h"

namespace acx {
namespace {

// Frame format lives in src/net/wire.h (56-byte header); frame payload
// shapes and the replay buffer live in src/net/framing.h. The aliases keep
// this file's protocol code readable.
using wire::WireHeader;
using framing::ChunkHdr;
using framing::MakeHdr;
using framing::KnownMagic;
using framing::RvAck;
using framing::RvDesc;
using framing::StripeDesc;
using framing::WirePayloadLen;
constexpr uint32_t kMagic = wire::kMagic;
// Rendezvous frames (large-message single-copy path, same host only):
// an RTS frame advertises {addr, seq, pid} of the sender's buffer; the
// receiver pulls the payload with one process_vm_readv straight into the
// destination (the copy-through-the-ring path costs two copies) and acks.
// A nack (ok=0, e.g. pvread denied by a hardened kernel) makes the sender
// re-send the payload as a normal copy frame on a private (seq, ctx) key.
constexpr uint32_t kMagicRts = wire::kMagicRts;
constexpr uint32_t kMagicAck = wire::kMagicAck;
// Heartbeat: a zero-payload keepalive frame. Any inbound bytes refresh the
// peer's liveness clock, so heartbeats only need to flow when the wire is
// otherwise quiet. Essential on the shm plane, which has no EOF concept.
// Its seq field carries the sender's tx high-water mark so a receiver can
// NAK tail loss (a dropped final frame with no traffic behind it).
constexpr uint32_t kMagicHb = wire::kMagicHb;
constexpr uint32_t kMagicSeqAck = wire::kMagicSeqAck;
constexpr uint32_t kMagicNak = wire::kMagicNak;
constexpr uint32_t kMagicStripe = wire::kMagicStripe;
constexpr uint32_t kMagicChunk = wire::kMagicChunk;

// Internal context ids. User contexts are >= 0; the control plane and the
// partitioned layer get their own namespaces so they can never match user
// point-to-point traffic.
constexpr int kCtrlCtx = -2;
constexpr int kRvDataCtx = -3;  // rendezvous-fallback payload frames
constexpr size_t kRvDefaultThreshold = 256u << 10;
inline int PartCtx(int ctx) { return -1000 - ctx; }
// Partition p of a tag-tagged partitioned channel travels as its own
// message; 4096 partitions per channel (the reference's whole slot table is
// 4096, mpi-acx-internal.h:141, so this bounds nothing in practice).
inline int PartTag(int tag, int p) { return tag * 4096 + p; }

// Zero-copy send: the wire is fed straight from the user buffer (legal —
// the caller may not touch it until the ticket completes), so large
// messages cost exactly one gather-write into the ring / socket.
struct SendReq {
  WireHeader hdr{};
  const char* payload = nullptr;  // user buffer, borrowed until done
  size_t bytes = 0;               // user message length (== hdr.bytes)
  const char* wire_payload = nullptr;  // what actually goes on the wire
  size_t wire_bytes = 0;               // (== payload/bytes except RTS/ACK)
  // Chunk frames carry TWO wire segments after the header: the 24-byte
  // ChunkHdr (stored in desc, pointed at by wire_head) and the borrowed
  // payload slice. Zero/null on every other frame class.
  const char* wire_head = nullptr;
  size_t wire_head_bytes = 0;
  size_t off = 0;  // progress over [header | wire head | wire payload]
  bool rv = false;  // rendezvous: wire completion != user completion
  bool done = false;
  // Replay frame: wire_payload is a complete [header|payload] blob borrowed
  // from the lane's replay buffer; no separate header is written and no new
  // record is made (hdr.seq identifies the record to un-queue on write).
  bool raw = false;
  bool fault_checked = false;  // OnFrame consulted once per frame
  // Deferred payload CRC (chunks only): computed at first write attempt of
  // THIS frame, i.e. right after the previous chunk's writev returned —
  // the CRC of chunk k+1 overlaps the kernel's handling of chunk k.
  bool crc_deferred = false;
  // corrupt_frame poisons the on-wire crc field; the pristine values are
  // kept so the replay record (and any post-reconnect resend) is clean.
  bool corrupted = false;
  uint32_t good_crc = 0, good_hcrc = 0;
  // Enqueue stamp on the trace timeline (trace::NowSinceStartNs), for the
  // per-link tx-queue histogram; 0 on control frames (not measured).
  uint64_t enq_ns = 0;
  int dst = -1;   // destination rank (dead-peer teardown scans rv_pending_)
  char desc[24];  // storage for RTS/ACK/StripeDesc/ChunkHdr wire payloads
  // Striped parent: the user-visible SendReq of a striped message. The
  // parent itself is never queued; it completes when its `pending` child
  // frames (envelope + chunks) have all fully written.
  std::shared_ptr<SendReq> parent;
  uint32_t pending = 0;
  Status st;
};

struct RecvReq {
  void* buf = nullptr;
  size_t bytes = 0;
  int src = -1, tag = 0, ctx = 0;
  // Rendezvous fallback rewrites the matching key to (seq, kRvDataCtx);
  // report_tag preserves the user-visible tag for the Status.
  int report_tag = INT_MIN;
  bool done = false;
  uint64_t span = 0;  // the LOCAL recv op's causal span id (acx/span.h)
  Status st;
};

struct Msg {
  int tag = 0, ctx = 0;
  std::vector<char> payload;
  bool rv = false;  // unexpected RTS: payload empty, fields below valid
  RvDesc rv_desc{};
  uint64_t rv_bytes = 0;  // full message length advertised by the RTS
  uint64_t span = 0;      // the SENDER op's span id, off the wire header
  // Unexpected stripe envelope: a PLACEHOLDER holding the message's FIFO
  // matching slot while chunks land in the reassembly map. payload empty;
  // stripe_id keys peers_[src].stripes. 0 = plain message.
  uint32_t stripe_id = 0;
};

// Incoming-byte-stream assembly state for ONE subflow of one peer link.
// When the header matches an already-posted recv, payload bytes stream
// directly into the recv buffer (`direct`); otherwise they assemble into
// `payload` and queue as an unexpected message.
struct InState {
  WireHeader hdr{};
  size_t hdr_got = 0;
  std::vector<char> payload;
  size_t payload_got = 0;
  std::shared_ptr<RecvReq> direct;
  uint32_t run_crc = 0;    // incremental CRC32C over the streamed payload
  bool discard = false;    // stale/duplicate/out-of-order frame: drain+drop
  bool nak_after = false;  // sequence gap: re-pull once the frame is drained
  // Chunk-frame assembly: the leading 24-byte ChunkHdr, read before the
  // slice bytes are routed to their destination by explicit offset.
  ChunkHdr chdr{};
  size_t chdr_got = 0;
};

// Receive-side reassembly of one striped message. Chunks may arrive before
// the envelope (lanes are independent streams), so the entry is created by
// whichever lands first; `have_env` gates completion. Once a recv matches
// (`direct`), further slices stream straight into the user buffer.
struct StripeRx {
  bool have_env = false;
  int tag = 0, ctx = 0;
  uint64_t total = 0;     // full message length (envelope hdr.bytes)
  uint32_t nchunks = 0;
  uint64_t span = 0;      // sender op's span id, off the envelope
  std::shared_ptr<RecvReq> direct;
  std::vector<char> assembly;           // pre-match landing zone
  std::unordered_set<uint32_t> got;     // chunk indices received
};

class StreamTransport;

class SockTicket : public Ticket {
 public:
  SockTicket(StreamTransport* t, std::shared_ptr<SendReq> s)
      : t_(t), send_(std::move(s)) {}
  SockTicket(StreamTransport* t, std::shared_ptr<RecvReq> r)
      : t_(t), recv_(std::move(r)) {}
  bool Test(Status* st) override;
  const std::shared_ptr<RecvReq>& recv() const { return recv_; }

 private:
  StreamTransport* t_;
  std::shared_ptr<SendReq> send_;
  std::shared_ptr<RecvReq> recv_;
};

class StreamTransport : public Transport {
 public:
  // links[i] is the wire to rank i (null at i == rank). shm_base/shm_len, if
  // set, is a mapping to munmap at teardown.
  StreamTransport(int rank, int size, std::vector<std::unique_ptr<Link>> links,
                  void* shm_base = nullptr, size_t shm_len = 0,
                  bool sock_plane = false)
      : rank_(rank), size_(size), peers_(size),
        shm_base_(shm_base), shm_len_(shm_len) {
    const char* e = getenv("ACX_RV_THRESHOLD");
    if (e != nullptr) {
      const unsigned long long v = strtoull(e, nullptr, 10);
      rv_threshold_ = v == 0 ? SIZE_MAX : static_cast<size_t>(v);
    }
    // Test hook: pretend every pvread fails so the nack/copy-fallback
    // path (the behavior on ptrace-hardened kernels) gets exercised.
    const char* ff = getenv("ACX_RV_FORCE_FALLBACK");
    rv_force_fallback_ = ff != nullptr && atoi(ff) != 0;
    // Resilience: heartbeats are opt-in (ACX_HEARTBEAT_MS > 0); EOF-based
    // dead-peer detection on socket links is always on. The grace window
    // keeps slow-starting peers (module import, JIT warmup) from being
    // declared dead before they ever speak.
    last_rx_ns_.assign(size_, 0);
    peer_dead_.assign(size_, false);
    if (size_ > 1) {
      if (const char* hb = getenv("ACX_HEARTBEAT_MS")) {
        const double ms = atof(hb);
        if (ms > 0) hb_interval_ns_ = static_cast<uint64_t>(ms * 1e6);
      }
      if (hb_interval_ns_ != 0) {
        double to_ms = 0;
        if (const char* t = getenv("ACX_PEER_TIMEOUT_MS")) to_ms = atof(t);
        peer_timeout_ns_ = to_ms > 0 ? static_cast<uint64_t>(to_ms * 1e6)
                                     : 5 * hb_interval_ns_;
        double grace_ms = 5000;
        if (const char* g = getenv("ACX_PEER_GRACE_MS")) grace_ms = atof(g);
        grace_deadline_ns_ = NowNs() + static_cast<uint64_t>(grace_ms * 1e6);
      }
    }
    // Survivable links (DESIGN.md §9). Payload CRC stamping is on by
    // default (ACX_CRC=0 disables); the recovery machinery (sequencing
    // checks, replay, NAK, epoch-bumped reconnect) arms only on the socket
    // plane inside an acxrun-managed job (ACX_JOB_ID names the rendezvous
    // namespace for the reconnect listeners). The shm plane has no EOF or
    // reconnect concept, and standalone unit tests keep PR-1 semantics.
    if (const char* c = getenv("ACX_CRC")) crc_on_ = atoi(c) != 0;
    if (const char* rb = getenv("ACX_REPLAY_BUF_BYTES")) {
      const unsigned long long v = strtoull(rb, nullptr, 10);
      if (v > 0) replay_budget_ = static_cast<size_t>(v);
    }
    // Fleet membership (DESIGN.md §12): the transport is the authority on
    // fleet shape — every construction (re)seats the table at epoch 1 with
    // every slot ACTIVE. Joiners and verdicts adjust it from there.
    Fleet().Reset(size_, rank_);
    const char* job = getenv("ACX_JOB_ID");
    recovery_armed_ = sock_plane && size_ > 1 && job != nullptr;
    if (recovery_armed_) {
      job_id_ = job;
      // Jitter seed for the reconnect/redial backoff ladder (cheap LCG; no
      // cryptographic needs — just decorrelating sibling ranks' redials).
      jitter_state_ = NowNs() ^ (static_cast<uint64_t>(rank_) << 32) ^
                      static_cast<uint64_t>(getpid());
      // Abstract-namespace AF_UNIX listener: reconnecting peers dial
      // "\0acx-<job>-<rank>". Abstract names need no filesystem cleanup and
      // vanish with the process — a dead rank's name can't be dialed.
      // CLOEXEC so a rank that execs a replacement of itself (rolling
      // restart) releases the name for the replacement's own bind.
      listen_fd_ = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC,
                          0);
      if (listen_fd_ >= 0) {
        struct sockaddr_un sa;
        memset(&sa, 0, sizeof sa);
        sa.sun_family = AF_UNIX;
        const int n = snprintf(sa.sun_path + 1, sizeof(sa.sun_path) - 1,
                               "acx-%s-%d", job_id_.c_str(), rank_);
        const socklen_t slen = static_cast<socklen_t>(
            offsetof(struct sockaddr_un, sun_path) + 1 + n);
        if (bind(listen_fd_, reinterpret_cast<struct sockaddr*>(&sa), slen) !=
                0 ||
            listen(listen_fd_, size_) != 0) {
          close(listen_fd_);
          listen_fd_ = -1;
        }
      }
      // Without a listener nobody can reconnect TO us; fall back to the
      // PR-1 fail-stop behavior rather than promise recovery we can't do.
      if (listen_fd_ < 0) recovery_armed_ = false;
    }
    // Striping (DESIGN.md §15): subflows ride the same rendezvous listener
    // the reconnect ladder uses, so lanes need recovery armed. Forcing
    // stripes_ = 1 otherwise keeps shm/self/unmanaged runs on the proven
    // single-flow path — loudly when the user explicitly asked for lanes.
    stripe_cfg_ = stripe::ConfigFromEnv();
    stripes_ = stripe_cfg_.stripes;
    if (stripes_ > 1 && !recovery_armed_) {
      if (sock_plane && size_ > 1)
        std::fprintf(stderr,
                     "tpu-acx[%d]: ACX_STRIPES=%d ignored (no ACX_JOB_ID "
                     "rendezvous listener to dial subflows on)\n",
                     rank_, stripes_);
      stripes_ = 1;
    }
    // Seat every peer's lane array: lane 0 is the inherited link; lanes
    // 1..N-1 start linkless and are dialed lazily by the lower rank.
    for (int p = 0; p < size_; p++) {
      Peer& peer = peers_[p];
      peer.sf.resize(p == rank_ ? 1 : stripes_ < 1 ? 1 : stripes_);
      if (p != rank_ && static_cast<size_t>(p) < links.size())
        peer.sf[0].link = std::move(links[p]);
    }
#ifdef PR_SET_PTRACER
    // Let sibling ranks process_vm_readv our send buffers even under
    // Yama ptrace_scope=1 (no-op where Yama is absent; nack path covers
    // kernels where this still isn't enough). SCOPE WARNING: PTRACER_ANY
    // relaxes Yama for the whole process against ANY same-UID process,
    // not just sibling ranks — so it is armed only inside an
    // acxrun-managed job (ACX_FDS set: every same-UID peer is part of
    // this job's trust domain) or when explicitly requested with
    // ACX_RV_PTRACER=1; ACX_RV_PTRACER=0 always disables it, and the
    // rendezvous path stays correct either way via the nack->copy
    // fallback. Also skipped when rendezvous is off (ACX_RV_THRESHOLD=0).
    const char* pt = getenv("ACX_RV_PTRACER");
    const bool want_ptracer =
        pt != nullptr ? atoi(pt) != 0 : getenv("ACX_FDS") != nullptr;
    if (size_ > 1 && rv_threshold_ != SIZE_MAX && want_ptracer)
      prctl(PR_SET_PTRACER, PR_SET_PTRACER_ANY, 0, 0, 0);
#endif
  }

  ~StreamTransport() override {
    if (listen_fd_ >= 0) close(listen_fd_);
    peers_.clear();
    if (shm_base_ != nullptr) munmap(shm_base_, shm_len_);
  }

  int rank() const override { return rank_; }
  int size() const override { return size_; }

  Ticket* Isend(const void* buf, size_t bytes, int dst, int tag, int ctx,
                uint64_t span = 0) override {
    MutexLock lk(mu_);
    return IsendLocked(buf, bytes, dst, tag, ctx, span);
  }

  Ticket* Irecv(void* buf, size_t bytes, int src, int tag, int ctx,
                uint64_t span = 0) override {
    MutexLock lk(mu_);
    return IrecvLocked(buf, bytes, src, tag, ctx, span);
  }

  PartitionedChan* PsendInit(const void* buf, int partitions,
                             size_t part_bytes, int dst, int tag,
                             int ctx) override;
  PartitionedChan* PrecvInit(void* buf, int partitions, size_t part_bytes,
                             int src, int tag, int ctx) override;

  // Fan-in/fan-out barrier through rank 0 on the control context. The
  // reference gets this from MPI_Barrier for free; sufficient at host-plane
  // process counts.
  void Barrier(int /*ctx*/) override {
    if (rank_ == 0) {
      int token = 0;
      for (int p = 1; p < size_; p++) RecvB(&token, sizeof token, p, 1);
      for (int p = 1; p < size_; p++) SendB(&token, sizeof token, p, 2);
    } else {
      int token = rank_;
      SendB(&token, sizeof token, 0, 1);
      RecvB(&token, sizeof token, 0, 2);
    }
  }

  void AllreduceInt(int32_t* data, int count, int op, int /*ctx*/) override {
    const size_t nb = sizeof(int32_t) * static_cast<size_t>(count);
    if (rank_ == 0) {
      std::vector<int32_t> tmp(count);
      for (int p = 1; p < size_; p++) {
        RecvB(tmp.data(), nb, p, 3);
        for (int i = 0; i < count; i++) {
          switch (op) {
            case 0: data[i] = data[i] > tmp[i] ? data[i] : tmp[i]; break;
            case 1: data[i] = data[i] < tmp[i] ? data[i] : tmp[i]; break;
            default: data[i] += tmp[i]; break;
          }
        }
      }
      for (int p = 1; p < size_; p++) SendB(data, nb, p, 4);
    } else {
      SendB(data, nb, 0, 3);
      RecvB(data, nb, 0, 4);
    }
  }

  void Abort(int code) override {
    std::fprintf(stderr, "tpu-acx[%d]: abort(%d)\n", rank_, code);
    _exit(code);
  }

  // Background protocol work (heartbeats, dead-peer checks) when no
  // Ticket::Test is pumping progress; called from the proxy's idle branches.
  void Tick() override {
    if (size_ <= 1) return;
    MutexLock lk(mu_);
    ProgressLocked();
  }

  NetStats net_stats() const override {
    NetStats ns;
    ns.hb_sent = hb_sent_.load(std::memory_order_relaxed);
    ns.hb_recv = hb_recv_.load(std::memory_order_relaxed);
    ns.peers_dead = peers_dead_n_.load(std::memory_order_relaxed);
    ns.failed_ops = failed_ops_.load(std::memory_order_relaxed);
    ns.reconnects = reconnects_.load(std::memory_order_relaxed);
    ns.replayed_frames = frames_replayed_.load(std::memory_order_relaxed);
    ns.crc_rejects = crc_rejects_.load(std::memory_order_relaxed);
    ns.naks_sent = naks_sent_.load(std::memory_order_relaxed);
    ns.links_recovering = recovering_count_.load(std::memory_order_relaxed);
    ns.replay_broken_links =
        replay_broken_links_.load(std::memory_order_relaxed);
    return ns;
  }

  PeerHealth peer_health(int r) override {
    if (r < 0 || r >= size_ || r == rank_) return PeerHealth::kHealthy;
    // Lock-free fast path: nothing recovering, nobody dead — the common
    // state for the whole life of a healthy job, and the proxy consults
    // this for every not-yet-complete op.
    if (recovering_count_.load(std::memory_order_relaxed) == 0 &&
        peers_dead_n_.load(std::memory_order_relaxed) == 0)
      return PeerHealth::kHealthy;
    MutexLock lk(mu_);
    if (peer_dead_[r]) return PeerHealth::kDead;
    return peers_[r].health != 0 ? PeerHealth::kRecovering
                                 : PeerHealth::kHealthy;
  }

  // Crash-path form (flight dumps): identical fast path, but a bounded
  // try-lock instead of blocking on mu_. On a miss the honest answer is
  // kRecovering — the fast path already said something is in flux, and a
  // dump annotation must not wedge a dying rank for an exact verdict.
  PeerHealth peer_health_relaxed(int r) override {
    if (r < 0 || r >= size_ || r == rank_) return PeerHealth::kHealthy;
    if (recovering_count_.load(std::memory_order_relaxed) == 0 &&
        peers_dead_n_.load(std::memory_order_relaxed) == 0)
      return PeerHealth::kHealthy;
    TryMutexLock lk(mu_, /*spins=*/4);
    if (!lk.owns()) return PeerHealth::kRecovering;
    if (peer_dead_[r]) return PeerHealth::kDead;
    return peers_[r].health != 0 ? PeerHealth::kRecovering
                                 : PeerHealth::kHealthy;
  }

  bool link_clock(int r, LinkClock* out) override {
    if (r < 0 || r >= size_ || r == rank_) return false;
    // Best-effort contract (acx/transport.h): callers include the stall
    // watchdog and the flight-recorder dump path, which may run from a
    // fatal-signal handler — never block on mu_, just try a few times.
    TryMutexLock lk(mu_, /*spins=*/4);
    if (!lk.owns()) return false;
    const Peer& p = peers_[r];
    // Lane 0 is the link's identity clock; replay backlog is the SUM over
    // lanes (the number a stall report cares about is total unacked bytes).
    out->epoch = p.sf[0].clk.epoch;
    out->tx_seq = p.sf[0].clk.tx_seq;
    out->rx_seq = p.sf[0].clk.rx_seq;
    out->acked_rx = p.sf[0].clk.acked_rx;
    uint64_t rb = 0;
    for (const Subflow& sf : p.sf) rb += sf.replay.bytes;
    out->replay_bytes = rb;
    return true;
  }

  bool link_scope(int r, LinkScope* out) override {
    if (r < 0 || r >= size_ || r == rank_) return false;
    // Same best-effort contract as link_clock: the tseries sampler and the
    // crash flusher must never block on mu_.
    TryMutexLock lk(mu_, /*spins=*/4);
    if (!lk.owns()) return false;
    const Peer& p = peers_[r];
    out->state = peer_dead_[r] ? 2 : (p.health != 0 ? 1 : 0);
    out->epoch = p.sf[0].clk.epoch;
    out->tx_payload_bytes = p.sc_tx_payload;
    out->tx_wire_bytes = p.sc_tx_wire;
    out->rx_payload_bytes = p.sc_rx_payload;
    out->rx_wire_bytes = p.sc_rx_wire;
    out->tx_frames = p.sc_tx_frames;
    out->rx_frames = p.sc_rx_frames;
    out->naks = p.sc_naks;
    out->crc_rejects = p.sc_crc_rejects;
    out->replayed = p.sc_replayed;
    out->subflows = static_cast<uint32_t>(p.sf.size());
    uint32_t up = 0;
    for (const Subflow& sf : p.sf)
      if (sf.link && !sf.down) up++;
    out->subflows_up = up;
    out->tx_queue_ns_sum = p.sc_tx_queue_ns;
    out->tx_queue_frames = p.sc_tx_queue_frames;
    out->rx_transit_ns_sum = p.sc_rx_transit_ns;
    out->rx_transit_frames = p.sc_rx_transit_frames;
    out->part_inflight = p.sc_part_inflight > 0
                             ? static_cast<uint64_t>(p.sc_part_inflight)
                             : 0;
    return true;
  }

  // Partitioned-round gauge bookkeeping (the channels below are friends).
  void PartInflightAdd(int r, int delta) {
    if (r < 0 || r >= size_) return;
    MutexLock lk(mu_);
    peers_[r].sc_part_inflight += delta;
  }

  // Voluntary departure (MPIX_Fleet_leave, DESIGN.md §12). The caller has
  // already drained; here we record LEFT locally, tell every healthy peer
  // with an explicit VIEW frame — so their verdict is graceful-leave, not
  // the crash the trailing EOF would otherwise suggest — and surrender the
  // rendezvous listener so a replacement process can bind the abstract
  // name while we are still alive (e.g. a supervisor parent waiting on the
  // replacement it forked).
  void FleetLeave() override {
    if (size_ <= 1) return;
    MutexLock lk(mu_);
    const uint64_t fepoch = Fleet().OnLeave(rank_);
    for (int q = 0; q < size_; q++) {
      if (q == rank_ || !peers_[q].sf[0].link || peer_dead_[q]) continue;
      if (peers_[q].health != 0) continue;
      SendViewLocked(q, rank_, MemberState::kMemberLeft, fepoch);
    }
    ACX_TRACE_EVENT("fleet_leave", static_cast<size_t>(rank_));
    if (listen_fd_ >= 0) {
      close(listen_fd_);
      listen_fd_ = -1;
    }
  }

  // Late-joiner bootstrap (ACX_JOIN=1, DESIGN.md §12): we came up with
  // every link null and dial each peer's rendezvous listener with a JOIN
  // hello. Sweeps repeat on a jittered, growing pause until every slot is
  // either linked or — only at budget expiry — latched dead: a peer may
  // itself be mid-replacement, so "unreachable right now" is not a verdict
  // until the deadline. Returns the number of live links established.
  int JoinFleet(int budget_ms) {
    // Explicit lock()/unlock() (not a scoped guard): the dial loop drops
    // the lock across its jittered naps, and the annotated acquire/release
    // pair is the form the thread-safety analysis can follow.
    mu_.lock();
    const uint64_t deadline =
        NowNs() + static_cast<uint64_t>(budget_ms) * 1000000ull;
    uint64_t pause_ms = 20;
    for (;;) {
      int missing = 0;
      for (int p = 0; p < size_; p++) {
        if (p == rank_ || peers_[p].sf[0].link || peer_dead_[p]) continue;
        if (!DialJoinLocked(p)) missing++;
      }
      if (missing == 0) break;
      if (NowNs() >= deadline) {
        for (int p = 0; p < size_; p++) {
          if (p == rank_ || peers_[p].sf[0].link || peer_dead_[p]) continue;
          MarkPeerDeadLocked(p, "unreachable at join", /*hb_detected=*/true);
        }
        break;
      }
      const uint64_t wait_ns = JitteredWaitNs(pause_ms);
      mu_.unlock();
      poll(nullptr, 0, static_cast<int>(wait_ns / 1000000ull) + 1);
      mu_.lock();
      if (pause_ms < 200) pause_ms *= 2;
    }
    Fleet().OnJoin(rank_);  // no-op bump-wise if Reset left us ACTIVE
    int linked = 0;
    for (int p = 0; p < size_; p++)
      if (p != rank_ && peers_[p].sf[0].link) linked++;
    mu_.unlock();
    std::fprintf(stderr,
                 "tpu-acx[%d]: joined fleet (%d/%d peer link(s), fleet "
                 "epoch %llu)\n",
                 rank_, linked, size_ - 1,
                 static_cast<unsigned long long>(Fleet().epoch()));
    return linked;
  }

  // Called from SockTicket::Test.
  bool TestReq(const std::shared_ptr<SendReq>& s,
               const std::shared_ptr<RecvReq>& r, Status* st) {
    MutexLock lk(mu_);
    ProgressLocked();
    if (s) {
      if (s->done && st) *st = s->st;
      return s->done;
    }
    if (r->done && st) *st = r->st;
    return r->done;
  }

  // Called from SockPrecvChan::FinishRound for partitions the proxy gave up
  // on (arrival deadline / drain): un-post the recv so a REDONE round's
  // frame is matched by the redo's fresh request instead of this stale one.
  // If the frame arrives anyway it lands on the unexpected queue for its
  // (tag, ctx) — the round's tags are never reused, so it sits inert.
  // Returns false when the req already matched (a frame is mid-stream into
  // its buffer) or went rendezvous; those cases can't be un-posted.
  bool CancelPostedRecv(const std::shared_ptr<RecvReq>& r) {
    if (!r) return false;
    MutexLock lk(mu_);
    if (r->done || r->src < 0 || r->src >= size_) return false;
    auto& q = peers_[r->src].posted;
    for (auto it = q.begin(); it != q.end(); ++it) {
      if (it->get() == r.get()) {
        q.erase(it);
        r->st = Status{r->src, r->tag, kErrTimeout, 0};
        r->done = true;
        return true;
      }
    }
    return false;
  }

 private:
  friend class SockPsendChan;
  friend class SockPrecvChan;

  // One lane of a peer link (DESIGN.md §15). Lane 0 is the link itself (the
  // acxrun-inherited socket or shm ring); lanes >= 1 are striping subflows
  // dialed lazily against the peer's rendezvous listener. Each lane is a
  // full independent stream: its own outq, inbound assembly, wire clock,
  // and replay buffer — so CRC rejects, NAK re-pulls, and epoch-bumped
  // reconnects heal per lane without touching the others.
  struct Subflow {
    std::unique_ptr<Link> link;             // null: not (yet) established
    std::deque<std::shared_ptr<SendReq>> outq;
    InState in;
    link_state::WireClock clk;
    framing::ReplayBuffer replay;
    uint64_t stall_until_ns = 0;  // stall_link_ms fault gate
    // Lane lifecycle. `down` latches a lane the link DEGRADED away from
    // (redial ladder exhausted / acceptor deadline expired): the link keeps
    // moving on the survivors and never retries a down lane. Dial state is
    // for lanes >= 1 only; lane 0 uses the peer-level recovery ladder.
    bool down = false;
    uint64_t next_dial_ns = 0;  // dialer: earliest next connect attempt
    int dial_attempts = 0;      // dialer: attempts since lane was last up
    uint64_t give_up_ns = 0;    // acceptor: degrade if no subflow hello by
  };

  struct Peer {
    std::vector<Subflow> sf;                     // lanes; sf[0] = the link
    std::deque<Msg> arrived;                     // unmatched arrivals, FIFO
    std::deque<std::shared_ptr<RecvReq>> posted; // unmatched recvs, FIFO

    // -- striped reassembly (DESIGN.md §15) --
    std::unordered_map<uint32_t, StripeRx> stripes;
    // Recently-completed stripe ids. A lane degradation migrates unacked
    // chunk frames into a survivor's seq space with FRESH seqs, so a chunk
    // for an already-delivered message passes the per-lane duplicate gate;
    // this set is what recognizes (and drains) it instead of resurrecting
    // a never-completing map entry. Bounded: pruned to the newest 1024.
    std::set<uint32_t> done_stripes;
    uint32_t next_stripe_id = 1;  // tx side: per-peer-direction id counter
    int rr_cursor = 0;            // tx side: round-robin lane cursor
    bool replay_broken_noted = false;  // this link counted in the gauge

    int health = 0;                // 0 healthy, 1 recovering (lane 0)
    int rec_attempts = 0;          // dialer: connects attempted this outage
    uint64_t rec_next_ns = 0;      // dialer: next connect attempt
    uint64_t rec_deadline_ns = 0;  // acceptor: give up waiting for a dial

    // -- wire scope (DESIGN.md §13) -- cumulative per-link accounting,
    // written under mu_, exported via link_scope(). Aggregated over lanes
    // (the per-link goodput/overhead split is what tseries/acx_top read);
    // Peer objects persist across reconnects, so these stay cumulative for
    // the life of the process.
    uint64_t sc_tx_payload = 0;  // app bytes queued in eager data frames
    uint64_t sc_tx_wire = 0;     // every byte write(2) accepted for this link
    uint64_t sc_rx_payload = 0;  // app bytes delivered from data frames
    uint64_t sc_rx_wire = 0;     // every byte read(2) returned from this link
    uint64_t sc_tx_frames = 0;   // frames fully written (incl. control)
    uint64_t sc_rx_frames = 0;   // data frames fully delivered
    uint64_t sc_naks = 0;        // re-pulls sent on this link
    uint64_t sc_crc_rejects = 0; // frames from this peer dropped on CRC
    uint64_t sc_replayed = 0;    // frames re-sent to this peer

    // -- causal timing (DESIGN.md §14) -- cumulative, same lifecycle as the
    // scope counters above. Transit is the RAW clock delta (includes
    // inter-process timeline offset, clamped at 0); skew correction is an
    // offline concern (tools/acx_trace_merge.py).
    uint64_t sc_tx_queue_ns = 0;      // enqueue -> fully-on-wire, sequenced
    uint64_t sc_tx_queue_frames = 0;
    uint64_t sc_rx_transit_ns = 0;    // sender tx_ns -> delivery, clamped
    uint64_t sc_rx_transit_frames = 0;

    // Partitions in flight on this link (gauge, DESIGN.md §17): maintained
    // by the partitioned channels (SockPsendChan/SockPrecvChan) under mu_,
    // exported via link_scope(). Signed so a transient over-decrement can
    // never wrap the exported value to 2^64-ish.
    int64_t sc_part_inflight = 0;
  };

  // Count of lanes currently usable for fresh traffic.
  int LiveLanesLocked(const Peer& peer) const ACX_REQUIRES(mu_) {
    int n = 0;
    for (const Subflow& sf : peer.sf)
      if (sf.link && !sf.down) n++;
    return n;
  }

  // Next live lane at or after peer.rr_cursor, advancing the cursor. Lane 0
  // is always live when this is called (the link would be recovering/dead
  // otherwise), so the loop terminates.
  int NextLiveLaneLocked(Peer& peer) ACX_REQUIRES(mu_) {
    const int n = static_cast<int>(peer.sf.size());
    for (int i = 0; i < n; i++) {
      const int k = peer.rr_cursor;
      peer.rr_cursor = (peer.rr_cursor + 1) % n;
      if (peer.sf[k].link && !peer.sf[k].down) return k;
    }
    return 0;
  }

  Ticket* IsendLocked(const void* buf, size_t bytes, int dst, int tag,
                      int ctx, uint64_t span = 0) ACX_REQUIRES(mu_) {
    if (dst != rank_ && (dst < 0 || dst >= size_)) {
      std::fprintf(stderr, "tpu-acx[%d]: no wire to peer %d\n", rank_, dst);
      _exit(14);
    }
    // Dead-check before the link check: a joiner that could not reach some
    // peer has a dead latch and NO link for it — that is an error ticket,
    // not a malformed environment.
    if (dst != rank_ && peer_dead_[dst]) {
      // Immediate-error ticket: blocking helpers and barriers that touch a
      // dead peer stay bounded instead of wedging.
      auto s = std::make_shared<SendReq>();
      s->st = Status{rank_, tag, kErrPeerDead, 0};
      s->done = true;
      return new SockTicket(this, s);
    }
    if (dst != rank_ && !peers_[dst].sf[0].link) {
      std::fprintf(stderr, "tpu-acx[%d]: no wire to peer %d\n", rank_, dst);
      _exit(14);
    }
    auto s = std::make_shared<SendReq>();
    s->st = Status{rank_, tag, 0, bytes};
    s->dst = dst;
    if (dst == rank_) {
      // Self-send: loop straight back through the matching queues.
      Msg m;
      m.tag = tag;
      m.ctx = ctx;
      m.span = span;
      m.payload.assign(static_cast<const char*>(buf),
                       static_cast<const char*>(buf) + bytes);
      DeliverLocked(rank_, std::move(m));
      s->done = true;
      return new SockTicket(this, s);
    }
    s->payload = static_cast<const char*>(buf);
    s->bytes = bytes;
    s->enq_ns = trace::NowSinceStartNs();
    if (bytes >= rv_threshold_) {
      // Rendezvous: put a 16-byte RTS on the wire instead of the payload;
      // completion comes from the receiver's ACK (HandleAckLocked).
      const uint32_t seq = rv_next_seq_++;
      s->hdr = MakeHdr(kMagicRts, tag, ctx, bytes);
      RvDesc d{reinterpret_cast<uint64_t>(buf), seq, getpid()};
      static_assert(sizeof d <= sizeof s->desc, "desc too small");
      memcpy(s->desc, &d, sizeof d);
      s->wire_payload = s->desc;
      s->wire_bytes = sizeof d;
      s->rv = true;
      rv_pending_[seq] = s;
      s->hdr.span = span;
      s->hdr.crc = PayloadCrc(s->wire_payload, s->wire_bytes);
      StampSeqLocked(dst, 0, &s->hdr);
      peers_[dst].sf[0].outq.push_back(s);
      FlushOutLocked(dst, 0);
      return new SockTicket(this, s);
    }
    EnqueueEagerLocked(dst, s, tag, ctx, span);
    return new SockTicket(this, s);
  }

  // Eager path shared by IsendLocked and the rendezvous nack fallback: put
  // the payload on the wire as one kMagic frame — or, when the striping
  // policy says so, as a kMagicStripe envelope on lane 0 plus kMagicChunk
  // slices round-robin over every live lane. The caller owns s->payload/
  // s->bytes and has reset off/rv/fault state.
  void EnqueueEagerLocked(int p, const std::shared_ptr<SendReq>& s, int tag,
                          int ctx, uint64_t span) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    const int nlive = LiveLanesLocked(peer);
    if (stripe::ShouldStripe(s->bytes, nlive, stripe_cfg_)) {
      // 31-bit id (it travels in the chunk header's int32 tag field too);
      // skip 0, which means "not a stripe" in Msg::stripe_id.
      uint32_t msg_id = peer.next_stripe_id++ & 0x7fffffffu;
      if (msg_id == 0) msg_id = peer.next_stripe_id++ & 0x7fffffffu;
      const std::vector<stripe::ChunkSpan> plan =
          stripe::PlanChunks(s->bytes, nlive);
      // The parent never touches the wire; it completes when the envelope
      // and every chunk have fully written.
      s->pending = static_cast<uint32_t>(plan.size()) + 1;
      // Envelope: holds the message's FIFO slot on lane 0.
      auto env = std::make_shared<SendReq>();
      env->hdr = MakeHdr(kMagicStripe, tag, ctx, s->bytes);
      env->hdr.span = span;
      StripeDesc sd{msg_id, static_cast<uint32_t>(plan.size()), s->bytes};
      static_assert(sizeof sd <= sizeof env->desc, "desc too small");
      memcpy(env->desc, &sd, sizeof sd);
      env->wire_payload = env->desc;
      env->wire_bytes = sizeof sd;
      env->dst = p;
      env->enq_ns = s->enq_ns;
      env->parent = s;
      env->hdr.crc = PayloadCrc(env->wire_payload, env->wire_bytes);
      StampSeqLocked(p, 0, &env->hdr);
      peer.sf[0].outq.push_back(std::move(env));
      for (size_t i = 0; i < plan.size(); i++) {
        auto c = std::make_shared<SendReq>();
        c->hdr = MakeHdr(kMagicChunk, static_cast<int>(msg_id),
                         static_cast<int>(i), plan[i].len);
        c->hdr.span = span;
        ChunkHdr ch{msg_id, static_cast<uint32_t>(i), plan[i].offset,
                    plan[i].len};
        static_assert(sizeof ch <= sizeof c->desc, "desc too small");
        memcpy(c->desc, &ch, sizeof ch);
        c->wire_head = c->desc;
        c->wire_head_bytes = sizeof ch;
        c->wire_payload = s->payload + plan[i].offset;
        c->wire_bytes = static_cast<size_t>(plan[i].len);
        c->dst = p;
        c->enq_ns = s->enq_ns;
        c->parent = s;
        // CRC deferred to the first write attempt: chunk k+1's checksum
        // computes while the kernel is still moving chunk k (FlushOut).
        c->crc_deferred = true;
        const int lane = NextLiveLaneLocked(peer);
        StampSeqLocked(p, lane, &c->hdr);
        peer.sf[lane].outq.push_back(std::move(c));
      }
      for (size_t k = 0; k < peer.sf.size(); k++) FlushOutLocked(p, k);
      return;
    }
    s->hdr = MakeHdr(kMagic, tag, ctx, s->bytes);
    s->hdr.span = span;
    s->wire_payload = s->payload;
    s->wire_bytes = s->bytes;
    s->hdr.crc = PayloadCrc(s->wire_payload, s->wire_bytes);
    StampSeqLocked(p, 0, &s->hdr);
    peer.sf[0].outq.push_back(s);
    FlushOutLocked(p, 0);
  }

  // -- wire stamping ---------------------------------------------------------
  // Sequence numbers are assigned at ENQUEUE time (all enqueues push_back and
  // each lane's outq drains front-to-back) so wire order equals sequence
  // order within every lane.

  uint32_t PayloadCrc(const char* p, size_t n) const {
    return (crc_on_ && n != 0) ? wire::Crc32c(0, p, n) : 0;
  }

  // Epoch + header CRC for an unsequenced frame whose seq field the caller
  // already filled (heartbeat high-water, SeqAck/NAK cumulative rx).
  void SealHdrLocked(int dst, size_t lane, WireHeader* h) ACX_REQUIRES(mu_) {
    h->epoch = peers_[dst].sf[lane].clk.epoch;
    h->hcrc = wire::HeaderCrc(*h);
  }

  void StampSeqLocked(int dst, size_t lane, WireHeader* h) ACX_REQUIRES(mu_) {
    h->seq = ++peers_[dst].sf[lane].clk.tx_seq;
    SealHdrLocked(dst, lane, h);
  }

  Ticket* IrecvLocked(void* buf, size_t bytes, int src, int tag, int ctx,
                      uint64_t span = 0) ACX_REQUIRES(mu_) {
    // Same loud failure as IsendLocked: a recv from a wireless peer would
    // otherwise sit in `posted` forever (ProgressLocked skips null links).
    if (src != rank_ && (src < 0 || src >= size_)) {
      std::fprintf(stderr, "tpu-acx[%d]: no wire to peer %d\n", rank_, src);
      _exit(14);
    }
    auto r = std::make_shared<RecvReq>();
    r->buf = buf;
    r->bytes = bytes;
    r->src = src;
    r->tag = tag;
    r->ctx = ctx;
    r->span = span;
    // Try the unexpected queue first (FIFO per (src, tag, ctx)) — and
    // BEFORE any dead-peer verdict: a graceful leave (DESIGN.md §12)
    // drains and then announces LEFT, so eager data it delivered ahead of
    // the marker is still valid and must remain consumable after the
    // latch. A rendezvous arrival is the exception — completing it needs
    // the (possibly gone) sender's address space and its ack/fallback
    // path, so a dead peer's RTS fails like any other post against it.
    auto& q = peers_[src].arrived;
    for (auto it = q.begin(); it != q.end(); ++it) {
      if (it->tag == tag && it->ctx == ctx) {
        if (it->rv && src != rank_ && peer_dead_[src]) break;
        NoteMatchLocked(it->span, r->span);
        if (it->rv) {
          CompleteRvLocked(src, r, it->tag, it->rv_bytes, it->rv_desc,
                           it->span);
        } else if (it->stripe_id != 0) {
          // Stripe placeholder: attach the recv to the in-progress
          // reassembly (completing it if every chunk already landed).
          AttachStripeLocked(src, it->stripe_id, r);
        } else {
          CompleteRecv(r.get(), src, *it);
        }
        q.erase(it);
        return new SockTicket(this, r);
      }
    }
    if (src != rank_ && peer_dead_[src]) {
      r->st = Status{src, tag, kErrPeerDead, 0};
      r->done = true;
      return new SockTicket(this, r);
    }
    if (src != rank_ && !peers_[src].sf[0].link) {
      std::fprintf(stderr, "tpu-acx[%d]: no wire to peer %d\n", rank_, src);
      _exit(14);
    }
    peers_[src].posted.push_back(r);
    return new SockTicket(this, r);
  }

  static void CompleteRecv(RecvReq* r, int src, const Msg& m) {
    const size_t n = m.payload.size() < r->bytes ? m.payload.size() : r->bytes;
    memcpy(r->buf, m.payload.data(), n);
    const int err = m.payload.size() > r->bytes ? kErrTruncate : 0;
    r->st =
        Status{src, r->report_tag != INT_MIN ? r->report_tag : m.tag, err, n};
    r->done = true;
  }

  // -- striped receive (DESIGN.md §15) ---------------------------------------

  // A stripe envelope arrived on lane 0: create/complete the reassembly
  // entry and give the message its slot in FIFO matching order — matching a
  // posted recv directly, or queueing a placeholder Msg.
  void HandleStripeEnvLocked(int p, const WireHeader& h, const StripeDesc& d) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    const uint32_t msg_id = d.msg_id;  // packed member: copy before binding
    StripeRx& srx = peer.stripes[msg_id];  // chunks may have preceded us
    srx.have_env = true;
    srx.tag = h.tag;
    srx.ctx = h.ctx;
    srx.total = d.total_bytes;
    srx.nchunks = d.nchunks;
    srx.span = h.span;
    auto& posted = peer.posted;
    for (auto it = posted.begin(); it != posted.end(); ++it) {
      if ((*it)->tag == h.tag && (*it)->ctx == h.ctx) {
        std::shared_ptr<RecvReq> r = *it;
        posted.erase(it);
        NoteMatchLocked(h.span, r->span);
        srx.direct = r;
        if (!srx.assembly.empty()) {
          // Chunks that landed pre-match copied into the assembly buffer;
          // flush them into the user buffer and stream the rest direct.
          const size_t n =
              srx.assembly.size() < r->bytes ? srx.assembly.size() : r->bytes;
          memcpy(r->buf, srx.assembly.data(), n);
          srx.assembly.clear();
          srx.assembly.shrink_to_fit();
        }
        if (srx.got.size() == srx.nchunks) CompleteStripeLocked(p, d.msg_id);
        return;
      }
    }
    Msg m;
    m.tag = h.tag;
    m.ctx = h.ctx;
    m.span = h.span;
    m.stripe_id = d.msg_id;
    peer.arrived.push_back(std::move(m));
  }

  // A posted/late recv matched a stripe placeholder from the arrived queue.
  void AttachStripeLocked(int p, uint32_t msg_id,
                          const std::shared_ptr<RecvReq>& r) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    auto it = peer.stripes.find(msg_id);
    if (it == peer.stripes.end()) {
      // The reassembly was torn down (peer died mid-stripe) but the
      // placeholder outlived it: fail like any other post against the gap.
      r->st = Status{p, r->tag, kErrPeerDead, 0};
      r->done = true;
      return;
    }
    StripeRx& srx = it->second;
    srx.direct = r;
    if (!srx.assembly.empty()) {
      const size_t n =
          srx.assembly.size() < r->bytes ? srx.assembly.size() : r->bytes;
      memcpy(r->buf, srx.assembly.data(), n);
      srx.assembly.clear();
      srx.assembly.shrink_to_fit();
    }
    if (srx.have_env && srx.got.size() == srx.nchunks)
      CompleteStripeLocked(p, msg_id);
  }

  // Every chunk landed AND the envelope matched a recv: complete it and
  // retire the reassembly entry into the done-set.
  void CompleteStripeLocked(int p, uint32_t msg_id) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    auto it = peer.stripes.find(msg_id);
    if (it == peer.stripes.end() || !it->second.direct) return;
    StripeRx& srx = it->second;
    RecvReq* r = srx.direct.get();
    const size_t deliver =
        srx.total < r->bytes ? static_cast<size_t>(srx.total) : r->bytes;
    peer.sc_rx_payload += deliver;  // wire scope: goodput on completion
    peer.sc_rx_frames++;
    r->st = Status{p, r->report_tag != INT_MIN ? r->report_tag : srx.tag,
                   srx.total > r->bytes ? kErrTruncate : 0, deliver};
    r->done = true;
    peer.stripes.erase(it);
    peer.done_stripes.insert(msg_id);
    while (peer.done_stripes.size() > 1024)
      peer.done_stripes.erase(peer.done_stripes.begin());
  }

  // Pull an RTS-advertised payload straight out of the sender's address
  // space (one copy), then ack. On pvread failure, nack and repost the recv
  // on the private fallback key the sender will use for the copy re-send.
  // `span` is the sender op's span id off the RTS frame; it rides the ACK
  // back so the sender's completion stays causally attributable.
  void CompleteRvLocked(int src, const std::shared_ptr<RecvReq>& r, int tag,
                        uint64_t full_bytes, const RvDesc& d,
                        uint64_t span = 0) ACX_REQUIRES(mu_) {
    const size_t deliver = r->bytes < full_bytes ? r->bytes : full_bytes;
    size_t got = 0;
    if (!rv_force_fallback_) {
      // Loop: one process_vm_readv call moves at most MAX_RW_COUNT
      // (~2 GiB), so giant messages take several calls.
      while (got < deliver) {
        struct iovec liov{static_cast<char*>(r->buf) + got, deliver - got};
        struct iovec riov{reinterpret_cast<void*>(d.addr + got),
                          deliver - got};
        const ssize_t n = process_vm_readv(d.pid, &liov, 1, &riov, 1, 0);
        if (n <= 0) break;
        got += static_cast<size_t>(n);
      }
    }
    const bool ok = !rv_force_fallback_ && got == deliver;
    if (ok) {
      r->st = Status{src, tag, full_bytes > r->bytes ? kErrTruncate : 0,
                     deliver};
      r->done = true;
    } else {
      r->report_tag = tag;
      r->tag = static_cast<int>(d.seq & 0x7fffffff);
      r->ctx = kRvDataCtx;
      peers_[src].posted.push_back(r);
    }
    SendAckLocked(src, d.seq, ok, span);
  }

  void SendAckLocked(int dst, uint32_t seq, bool ok, uint64_t span = 0) ACX_REQUIRES(mu_) {
    auto s = std::make_shared<SendReq>();
    s->hdr = MakeHdr(kMagicAck, 0, 0, 0);
    RvAck a{seq, ok ? 1 : 0};
    memcpy(s->desc, &a, sizeof a);
    s->wire_payload = s->desc;
    s->wire_bytes = sizeof a;
    s->dst = dst;
    s->hdr.span = span;
    s->enq_ns = trace::NowSinceStartNs();
    s->hdr.crc = PayloadCrc(s->wire_payload, s->wire_bytes);
    StampSeqLocked(dst, 0, &s->hdr);
    peers_[dst].sf[0].outq.push_back(std::move(s));
    FlushOutLocked(dst, 0);
  }

  void HandleAckLocked(int src, const RvAck& a) ACX_REQUIRES(mu_) {
    auto it = rv_pending_.find(a.seq);
    if (it == rv_pending_.end()) return;  // duplicate/stale ack
    std::shared_ptr<SendReq> s = it->second;
    rv_pending_.erase(it);
    if (a.ok) {
      s->done = true;
      return;
    }
    // Receiver couldn't pvread: re-send as a normal copy frame on the
    // fallback key it just posted. Goes through the shared eager path, so
    // a big fallback payload stripes exactly like a first-try eager send.
    s->rv = false;
    const uint64_t span = s->hdr.span;  // survives the header rebuild
    s->off = 0;
    s->fault_checked = false;
    s->enq_ns = trace::NowSinceStartNs();
    EnqueueEagerLocked(src, s, static_cast<int>(a.seq & 0x7fffffff),
                       kRvDataCtx, span);
  }

  void DeliverLocked(int src, Msg&& m) ACX_REQUIRES(mu_) {
    auto& posted = peers_[src].posted;
    for (auto it = posted.begin(); it != posted.end(); ++it) {
      if ((*it)->tag == m.tag && (*it)->ctx == m.ctx) {
        std::shared_ptr<RecvReq> r = *it;
        posted.erase(it);
        NoteMatchLocked(m.span, r->span);
        if (m.rv) {
          CompleteRvLocked(src, r, m.tag, m.rv_bytes, m.rv_desc, m.span);
        } else {
          CompleteRecv(r.get(), src, m);
        }
        return;
      }
    }
    peers_[src].arrived.push_back(std::move(m));
  }

  // -- causal tracing hooks (DESIGN.md §14) ----------------------------------

  // A message matched a local recv. Emits the rx_from/rx_match instant PAIR
  // back-to-back under mu_ — rx_from carries the SENDER op's span (off the
  // wire), rx_match the LOCAL recv op's span — so offline tools can bridge
  // the sender's causal chain into the receiver's without heuristics: an
  // rx_match always follows its rx_from immediately in this rank's ring.
  void NoteMatchLocked(uint64_t wire_span, uint64_t recv_span) ACX_REQUIRES(mu_) {
    if (wire_span != 0) ACX_TRACE_SPAN("rx_from", -1, wire_span);
    if (recv_span != 0) ACX_TRACE_SPAN("rx_match", -1, recv_span);
  }

  // A sequenced frame from p was fully received (not a discard): account
  // one-way transit off the sender's tx stamp and emit the wire_rx instant
  // under the sender's span. The transit figure is a RAW cross-process
  // clock delta — both timelines are per-rank trace origins, so it embeds
  // a constant offset; live consumers (tseries/acx_top) present it as raw,
  // and acx_trace_merge/acx_critpath subtract the barrier-anchored skew.
  void NoteFrameRxLocked(int p, int lane, const WireHeader& h) ACX_REQUIRES(mu_) {
    if (h.span != 0) {
      ACX_TRACE_SPAN("wire_rx", -1, h.span);
      // aux = lane: seq spaces are per-subflow (each lane has its own wire
      // clock), so offline seq audits need the lane to scope the monotone
      // check — without it striped traffic looks like seq regressions.
      ACX_FLIGHT_SPAN(kRxFrame, -1, p, h.tag, h.seq, lane, h.span);
    }
    if (h.tx_ns != 0) {
      const uint64_t now = trace::NowSinceStartNs();
      const uint64_t transit = now > h.tx_ns ? now - h.tx_ns : 0;
      Peer& peer = peers_[p];
      peer.sc_rx_transit_ns += transit;
      peer.sc_rx_transit_frames++;
      if (metrics::Enabled())
        metrics::Observe(metrics::kWireTransitNs, transit);
    }
  }

  // Handshake version gate: a hello whose magic is a coherent v1 value is
  // an old-protocol peer, not line noise — say so before dropping the
  // socket. Mixed wire versions can never interoperate (the header grew
  // when the span id landed, §14); every rank must upgrade together.
  void WarnIfLegacyHello(int p, uint32_t magic) {
    if (!wire::KnownLegacyMagic(magic)) return;
    char who[32];
    if (p >= 0)
      std::snprintf(who, sizeof who, "rank %d", p);
    else
      std::snprintf(who, sizeof who, "an unidentified peer");
    std::fprintf(stderr,
                 "tpu-acx: rank %d: hello from %s carries wire protocol v1 "
                 "magic 0x%08x; this build is v2 (56-byte spanned header) — "
                 "refusing the link, upgrade all ranks together\n",
                 rank_, who, magic);
  }

  // Copy a fully-written frame into the lane's bounded replay buffer.
  // Called at full-write time (the payload is still borrowed, so the copy
  // is legal); a corrupt_frame-poisoned header is recorded with its
  // pristine CRCs so a replay heals rather than re-injects.
  void RecordFrameLocked(int p, size_t lane, SendReq* s) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    WireHeader h = s->hdr;
    if (s->corrupted) {
      h.crc = s->good_crc;
      h.hcrc = s->good_hcrc;
    }
    const bool evicted = peer.sf[lane].replay.Record(
        h, s->wire_head, s->wire_head_bytes, s->wire_payload, s->wire_bytes,
        replay_budget_);
    if (evicted && !peer.replay_broken_noted) {
      // First eviction on this link: count it in the fleet-visible gauge
      // (NetStats.replay_broken_links) and say so once — the link still
      // moves data, but its next loss is terminal (DESIGN.md §9).
      peer.replay_broken_noted = true;
      replay_broken_links_.fetch_add(1, std::memory_order_relaxed);
      std::fprintf(stderr,
                   "tpu-acx[%d]: replay buffer for peer %d overran "
                   "ACX_REPLAY_BUF_BYTES; link can no longer survive a "
                   "reconnect\n",
                   rank_, p);
    }
  }

  // A raw (replay) frame finished writing: release its record's blob.
  void ClearQueuedLocked(int p, size_t lane, uint64_t seq) ACX_REQUIRES(mu_) {
    peers_[p].sf[lane].replay.ClearQueued(seq);
  }

  // Peer acknowledged delivery of everything up to `acked` on this lane.
  void HandleSeqAckLocked(int p, size_t lane, uint64_t acked) ACX_REQUIRES(mu_) {
    peers_[p].sf[lane].replay.AckThrough(acked);
  }

  // Header-only cumulative ack of our delivered-in-order high water on one
  // lane (acks travel on the lane they acknowledge — each lane is its own
  // seq space).
  void SendSeqAckLocked(int p, size_t lane) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    Subflow& sf = peer.sf[lane];
    auto s = std::make_shared<SendReq>();
    s->hdr = MakeHdr(kMagicSeqAck, 0, 0, 0);
    s->hdr.seq = sf.clk.rx_seq;
    SealHdrLocked(p, lane, &s->hdr);
    s->wire_payload = s->desc;
    s->wire_bytes = 0;
    s->dst = p;
    sf.clk.acked_rx = sf.clk.rx_seq;
    sf.clk.rx_since_ack = 0;
    sf.outq.push_back(std::move(s));
    FlushOutLocked(p, lane);
  }

  // Rate-limited re-pull: "I have everything through rx_seq; resend from
  // rx_seq+1" — per lane. Fired on a sequence gap, a CRC reject, or a
  // heartbeat whose tx high-water is ahead of us (tail loss).
  void MaybeNakLocked(int p, size_t lane) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    Subflow& sf = peer.sf[lane];
    const uint64_t now = NowNs();
    if (now - sf.clk.last_nak_ns < 1000000) return;  // 1ms
    sf.clk.last_nak_ns = now;
    auto s = std::make_shared<SendReq>();
    s->hdr = MakeHdr(kMagicNak, 0, 0, 0);
    s->hdr.seq = sf.clk.rx_seq;
    SealHdrLocked(p, lane, &s->hdr);
    s->wire_payload = s->desc;
    s->wire_bytes = 0;
    s->dst = p;
    sf.outq.push_back(std::move(s));
    naks_sent_.fetch_add(1, std::memory_order_relaxed);
    peer.sc_naks++;  // wire scope
    FlushOutLocked(p, lane);
  }

  // Peer asked for a resend from r+1 on this lane. Requeue every unacked,
  // not-already-queued record as a raw frame ahead of the unwritten tail of
  // the lane's outq (replayed seqs are lower than anything not yet written,
  // so wire order stays sequence order). Duplicates are skip-consumed by
  // the receiver.
  void HandleNakLocked(int p, size_t lane, uint64_t r) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    Subflow& sf = peer.sf[lane];
    HandleSeqAckLocked(p, lane, r);  // everything <= r is implicitly acked
    if (sf.replay.recs.empty()) return;  // raced with a covering ack
    if (sf.replay.recs.front().seq != r + 1) {
      // Lost frames we no longer hold are unrecoverable on ANY lane — the
      // message stream has a permanent gap, so the whole link dies.
      MarkPeerDeadLocked(p, "replay buffer exhausted", /*hb_detected=*/true);
      return;
    }
    auto& q = sf.outq;
    auto ins = q.begin();
    if (!q.empty() && q.front()->off > 0) ++ins;  // never tear a mid-write
    uint64_t count = 0;
    for (auto& rec : sf.replay.recs) {
      if (rec.queued) continue;
      rec.queued = true;
      auto s = std::make_shared<SendReq>();
      s->raw = true;
      s->dst = p;
      s->hdr.seq = rec.seq;
      s->wire_payload = rec.frame.data();
      s->wire_bytes = rec.frame.size();
      ins = q.insert(ins, std::move(s));
      ++ins;
      count++;
    }
    if (count != 0) {
      frames_replayed_.fetch_add(count, std::memory_order_relaxed);
      peer.sc_replayed += count;  // wire scope
    }
    FlushOutLocked(p, lane);
  }

  void FlushOutLocked(int p, size_t lane) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    if (peer.health != 0) return;  // reconnecting: no wire to write to
    Subflow& sf = peer.sf[lane];
    if (!sf.link || sf.down) return;
    if (sf.stall_until_ns != 0) {
      if (NowNs() < sf.stall_until_ns) return;  // stall_link_ms fault
      sf.stall_until_ns = 0;
    }
    auto& q = sf.outq;
    while (!q.empty()) {
      auto& s = q.front();
      if (s->off == 0 && !s->raw && s->crc_deferred) {
        // Deferred chunk CRC (DESIGN.md §15): computed at the FIRST write
        // attempt, not at enqueue — so chunk k+1's checksum runs while the
        // kernel is still draining chunk k's sendmsg. Covers the 24-byte
        // placement header plus the borrowed payload slice, exactly what
        // the receiver's running CRC will see.
        if (crc_on_)
          s->hdr.crc = wire::Crc32c(
              wire::Crc32c(0, s->wire_head, s->wire_head_bytes),
              s->wire_payload, s->wire_bytes);
        s->crc_deferred = false;
        s->hdr.hcrc = wire::HeaderCrc(s->hdr);
      }
      if (s->off == 0 && !s->raw && s->hdr.tx_ns == 0 &&
          wire::Sequenced(s->hdr.magic)) {
        // Stamp the tx timestamp at the first write attempt and reseal the
        // header CRC. Done BEFORE the fault consult so corrupt_frame's
        // pristine-CRC capture sees the final header bytes; never redone
        // (tx_ns != 0 guard), so the replay record stays byte-exact. A
        // replayed frame therefore keeps its ORIGINAL stamp — transit
        // measured across a loss/replay window is genuinely that long.
        s->hdr.tx_ns = trace::NowSinceStartNs();
        s->hdr.hcrc = wire::HeaderCrc(s->hdr);
      }
      if (s->off == 0 && !s->raw && !s->fault_checked && recovery_armed_ &&
          fault::Enabled() && wire::Sequenced(s->hdr.magic)) {
        s->fault_checked = true;  // one consult per frame, whatever happens
        uint64_t stall_us = 0;
        switch (fault::OnFrame(rank_, p, static_cast<int>(lane), &stall_us)) {
          case fault::Action::kDropFrame:
            // Swallowed — but recorded, so the receiver's NAK heals it.
            RecordFrameLocked(p, lane, s.get());
            if (!s->rv) {
              if (s->parent && --s->parent->pending == 0) {
                s->parent->done = true;
                s->parent->payload = nullptr;
              }
              s->done = true;
              s->payload = nullptr;
            }
            q.pop_front();
            continue;
          case fault::Action::kCorruptFrame:
            s->good_crc = s->hdr.crc;
            s->good_hcrc = s->hdr.hcrc;
            s->hdr.crc ^= 0xDEADBEEFu;
            s->hdr.hcrc = wire::HeaderCrc(s->hdr);  // header itself stays valid
            s->corrupted = true;
            break;
          case fault::Action::kStallLink:
            sf.stall_until_ns = NowNs() + stall_us * 1000;
            return;
          case fault::Action::kCloseLink:
            sf.link->ForceClose();
            return;  // next Progress pass sees !alive and heals the lane
          default:
            break;
        }
      }
      // Scatter/gather write: header, placement head (chunk frames), and
      // the BORROWED user payload go to the kernel in one sendmsg — the
      // partitioned/eager send path never stages payload bytes through an
      // intermediate buffer (the replay record, taken at full write below,
      // is the one deliberate copy).
      const size_t hdr_len = s->raw ? 0 : sizeof(WireHeader);
      const size_t head_end = hdr_len + s->wire_head_bytes;
      const size_t total = head_end + s->wire_bytes;
      while (s->off < total) {
        struct iovec iov[3];
        int niov = 0;
        size_t off = s->off;
        if (off < hdr_len) {
          iov[niov].iov_base = reinterpret_cast<char*>(&s->hdr) + off;
          iov[niov].iov_len = hdr_len - off;
          niov++;
          off = hdr_len;
        }
        if (off < head_end) {
          iov[niov].iov_base =
              const_cast<char*>(s->wire_head) + (off - hdr_len);
          iov[niov].iov_len = head_end - off;
          niov++;
          off = head_end;
        }
        if (off < total) {
          iov[niov].iov_base =
              const_cast<char*>(s->wire_payload) + (off - head_end);
          iov[niov].iov_len = total - off;
          niov++;
        }
        const size_t n = sf.link->WriteVec(iov, niov);
        if (n == 0) return;  // wire full
        s->off += n;
        peer.sc_tx_wire += n;  // wire scope: all bytes, framing included
      }
      // Wire scope: frame fully written. Goodput (payload) is only the app
      // bytes inside eager data frames — a chunk's hdr.bytes is its slice
      // of user payload; the stripe envelope is pure overhead. Raw replays
      // count as wire bytes + replayed frames, never as fresh payload.
      peer.sc_tx_frames++;
      if (!s->raw && (s->hdr.magic == kMagic || s->hdr.magic == kMagicChunk))
        peer.sc_tx_payload += s->hdr.bytes;
      // Causal tracing (§14): queue time = enqueue -> fully on the wire,
      // attributed per link and to the wire_queue_ns histogram; wire_tx
      // marks the spanned frame's departure on this rank's trace timeline.
      if (!s->raw && s->enq_ns != 0 && wire::Sequenced(s->hdr.magic)) {
        const uint64_t now = trace::NowSinceStartNs();
        const uint64_t queued = now > s->enq_ns ? now - s->enq_ns : 0;
        peer.sc_tx_queue_ns += queued;
        peer.sc_tx_queue_frames++;
        if (metrics::Enabled())
          metrics::Observe(metrics::kWireQueueNs, queued);
      }
      if (!s->raw && s->hdr.span != 0)
        ACX_TRACE_SPAN("wire_tx", -1, s->hdr.span);
      if (s->raw) {
        ClearQueuedLocked(p, lane, s->hdr.seq);
      } else if (recovery_armed_ && wire::Sequenced(s->hdr.magic)) {
        RecordFrameLocked(p, lane, s.get());
      }
      // Flight-record the frame at its full-write point — the moment it is
      // irrevocably on the wire (raw replays are already counted in
      // frames_replayed_; heartbeats/hellos are protocol noise).
      if (!s->raw) {
        switch (s->hdr.magic) {
          case kMagic:
          case kMagicStripe:
          case kMagicChunk:
            ACX_FLIGHT_SPAN(kTxData, -1, p, s->hdr.tag, s->hdr.seq, 0,
                            s->hdr.span);
            break;
          case kMagicRts:
            ACX_FLIGHT_SPAN(kTxRts, -1, p, s->hdr.tag, s->hdr.seq, 0,
                            s->hdr.span);
            break;
          case kMagicAck:
            ACX_FLIGHT_SPAN(kTxAck, -1, p, s->hdr.tag, s->hdr.seq, 0,
                            s->hdr.span);
            break;
          case kMagicSeqAck:
            ACX_FLIGHT(kTxSeqAck, -1, p, -1, s->hdr.seq, 0);
            break;
          case kMagicNak:
            ACX_FLIGHT(kTxNak, -1, p, -1, s->hdr.seq, 0);
            break;
          default:
            break;
        }
      }
      if (!s->rv) {
        // Rendezvous sends stay pending (and keep borrowing the user
        // buffer) until the receiver's ACK arrives. A striped message's
        // parent stays pending (and keeps borrowing) until the envelope
        // and every chunk are fully on the wire, whatever lane each took.
        if (s->parent && --s->parent->pending == 0) {
          s->parent->done = true;
          s->parent->payload = nullptr;
        }
        s->done = true;
        s->payload = nullptr;
      }
      q.pop_front();
    }
  }

  // The byte stream from p desynced (header CRC or magic check failed): a
  // torn frame means nothing downstream can be trusted. With recovery armed
  // the link is torn down and rebuilt — the epoch/seq/replay machinery
  // restores exactly-once delivery. Disarmed, this stays PR-1 fail-stop.
  // Desync on a SUBFLOW lane heals through the same lane-0 recovery: the
  // whole link tears down and the dialer re-establishes every lane.
  void StreamDesyncLocked(int p) ACX_REQUIRES(mu_) {
    std::fprintf(stderr, "tpu-acx[%d]: wire desync from %d (bad header)\n",
                 rank_, p);
    if (!recovery_armed_) _exit(14);
    peers_[p].sf[0].link->ForceClose();
    StartRecoveryLocked(p, "wire desync");
  }

  // A sequenced frame was delivered in order on this lane: advance its rx
  // clock and ack every 16 frames (the idle flush in ProgressLocked covers
  // quiet tails).
  void BumpRxLocked(int p, size_t lane, uint64_t seq) ACX_REQUIRES(mu_) {
    Subflow& sf = peers_[p].sf[lane];
    sf.clk.rx_seq = seq;
    ACX_FLIGHT(kRxData, -1, p, -1, seq, 0);
    if (++sf.clk.rx_since_ack >= 16) SendSeqAckLocked(p, lane);
  }

  void DrainInLocked(int p, size_t lane) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    Subflow& sf = peer.sf[lane];
    InState& in = sf.in;
    for (;;) {
      // A NAK/desync handled below can flip the peer into recovery (or
      // dead) mid-drain; stop touching the link the moment that happens.
      if (peer_dead_[p] || peer.health != 0) return;
      if (in.hdr_got < sizeof(WireHeader)) {
        size_t n =
            sf.link->ReadSome(reinterpret_cast<char*>(&in.hdr) + in.hdr_got,
                              sizeof(WireHeader) - in.hdr_got);
        if (n == 0) return;
        NoteRx(p, n);
        in.hdr_got += n;
        if (in.hdr_got < sizeof(WireHeader)) return;
        // Header integrity gate: magic and header-CRC must both hold
        // before ANY field is trusted.
        if (!KnownMagic(in.hdr.magic) ||
            in.hdr.hcrc != wire::HeaderCrc(in.hdr)) {
          // A v1 magic is a coherent OLD-protocol frame, not line noise:
          // fail loudly with the version story instead of the generic
          // desync path's "torn frame" framing. The link still tears down
          // — mixed-version links can never resync (§14).
          if (wire::KnownLegacyMagic(in.hdr.magic))
            std::fprintf(stderr,
                         "tpu-acx: rank %d: peer %d speaks wire protocol v1 "
                         "(magic 0x%08x); this build is v2 (56-byte spanned "
                         "header) — upgrade all ranks together\n",
                         rank_, p, in.hdr.magic);
          StreamDesyncLocked(p);
          return;
        }
        in.payload_got = 0;
        in.run_crc = 0;
        in.discard = false;
        in.nak_after = false;
        in.chdr_got = 0;
        // -- unsequenced control frames (header-only) --
        if (in.hdr.magic == kMagicHb) {
          hb_recv_.fetch_add(1, std::memory_order_relaxed);
          // Tail loss: the sender's tx high-water FOR THIS LANE is ahead
          // of what we've delivered and nothing behind the gap is coming
          // (heartbeats are FIFO behind data, so everything written
          // earlier was read).
          if (recovery_armed_ && in.hdr.epoch == sf.clk.epoch &&
              in.hdr.seq > sf.clk.rx_seq)
            MaybeNakLocked(p, lane);
          in.hdr_got = 0;
          continue;
        }
        if (in.hdr.magic == kMagicSeqAck) {
          ACX_FLIGHT(kRxSeqAck, -1, p, -1, in.hdr.seq, 0);
          HandleSeqAckLocked(p, lane, in.hdr.seq);
          in.hdr_got = 0;
          continue;
        }
        if (in.hdr.magic == kMagicNak) {
          ACX_FLIGHT(kRxNak, -1, p, -1, in.hdr.seq, 0);
          HandleNakLocked(p, lane, in.hdr.seq);
          in.hdr_got = 0;
          continue;
        }
        if (in.hdr.magic == wire::kMagicView) {
          // Fleet view update (DESIGN.md §12): tag = subject rank, ctx = its
          // new MemberState, bytes = sender's fleet epoch. Unsequenced so
          // membership keeps converging while a data stream is stalled.
          const int subject = in.hdr.tag;
          const auto st = static_cast<MemberState>(in.hdr.ctx);
          if (subject >= 0 && subject < size_)
            Fleet().AdoptView(subject, st, in.hdr.bytes);
          if (subject == p && st == MemberState::kMemberLeft) {
            // The peer itself announced a graceful leave: it drained before
            // sending this, so the quiet dead-latch retires its slots
            // without failing work. A later JOIN re-arms the slot.
            in.hdr_got = 0;
            MarkPeerDeadLocked(p, "peer left", /*hb_detected=*/false);
            return;
          }
          in.hdr_got = 0;
          continue;
        }
        if (in.hdr.magic == wire::kMagicHello) {
          // Handshake frames only ever travel on a fresh reconnect socket.
          StreamDesyncLocked(p);
          return;
        }
        // -- sequenced data frames (gated per LANE: each lane is its own
        // epoch/seq space) --
        if (recovery_armed_) {
          if (in.hdr.epoch != sf.clk.epoch || in.hdr.seq <= sf.clk.rx_seq) {
            // Stale epoch or duplicate (replay overshoot): consume quietly.
            in.discard = true;
          } else if (in.hdr.seq > sf.clk.rx_seq + 1) {
            // Gap: something was lost ahead of this frame. Consume it (the
            // replay will re-deliver it in order) and ask for a resend.
            in.discard = true;
            in.nak_after = true;
          }
        }
        if (!in.discard) {
          if (in.hdr.magic == kMagicRts) {
            in.direct.reset();
            in.payload.resize(sizeof(RvDesc));
          } else if (in.hdr.magic == kMagicAck) {
            in.direct.reset();
            in.payload.resize(sizeof(RvAck));
          } else if (in.hdr.magic == kMagicStripe) {
            in.direct.reset();
            in.payload.resize(sizeof(StripeDesc));
          } else if (in.hdr.magic == kMagicChunk) {
            // Chunk frames have their own placement-directed read path
            // below; in.direct is never used for them.
            in.direct.reset();
            in.payload.clear();
          } else {
            // Direct delivery: if a matching recv is already posted, stream
            // the payload straight into its buffer (one memcpy off the
            // wire). Only unexpected messages pay the assembly-buffer copy.
            auto& posted = peer.posted;
            for (auto it = posted.begin(); it != posted.end(); ++it) {
              if ((*it)->tag == in.hdr.tag && (*it)->ctx == in.hdr.ctx) {
                in.direct = *it;
                posted.erase(it);
                break;
              }
            }
            if (in.direct == nullptr) in.payload.resize(in.hdr.bytes);
          }
        }
      }
      const size_t wire_len = WirePayloadLen(in.hdr);
      if (in.discard) {
        while (in.payload_got < wire_len) {
          char scratch[4096];
          size_t want = wire_len - in.payload_got;
          if (want > sizeof scratch) want = sizeof scratch;
          size_t n = sf.link->ReadSome(scratch, want);
          if (n == 0) return;
          NoteRx(p, n);
          in.payload_got += n;
        }
        if (in.nak_after) MaybeNakLocked(p, lane);
        in.hdr_got = 0;
        continue;
      }
      if (in.hdr.magic == kMagicChunk) {
        // -- chunk frame: [ChunkHdr][slice], placement-directed -----------
        while (in.chdr_got < sizeof(ChunkHdr)) {
          size_t n = sf.link->ReadSome(
              reinterpret_cast<char*>(&in.chdr) + in.chdr_got,
              sizeof(ChunkHdr) - in.chdr_got);
          if (n == 0) return;
          NoteRx(p, n);
          in.chdr_got += n;
          if (in.chdr_got == sizeof(ChunkHdr)) {
            // The sender's CRC runs over ChunkHdr + slice as one stream.
            if (in.hdr.crc != 0)
              in.run_crc = wire::Crc32c(0, &in.chdr, sizeof in.chdr);
            if (in.chdr.len != in.hdr.bytes) {
              // Frame header and placement header disagree: torn stream.
              StreamDesyncLocked(p);
              return;
            }
          }
        }
        // Destination resolution happens per drain call, not per frame:
        // the recv can attach (envelope match, late Irecv) while a chunk
        // is mid-read, and the remainder then streams into the user
        // buffer. Three cases: message already delivered (a degraded
        // lane's migrated duplicate) -> drain; recv attached -> write in
        // place at the chunk's offset; else -> assembly buffer.
        // ChunkHdr is packed (alignment 1): copy the key fields into
        // aligned locals before any container call binds a reference.
        const uint32_t ck_msg_id = in.chdr.msg_id;
        const uint32_t ck_idx = in.chdr.idx;
        const bool seen = peer.done_stripes.count(ck_msg_id) != 0;
        StripeRx* srx = nullptr;
        RecvReq* r = nullptr;
        if (!seen) {
          srx = &peer.stripes[ck_msg_id];  // chunks may precede the env
          r = srx->direct ? srx->direct.get() : nullptr;
          if (r == nullptr) {
            const size_t need =
                static_cast<size_t>(in.chdr.offset + in.chdr.len);
            if (srx->assembly.size() < need) srx->assembly.resize(need);
          }
        }
        while (in.payload_got < in.hdr.bytes) {
          char scratch[4096];
          const uint64_t pos = in.chdr.offset + in.payload_got;
          size_t want = static_cast<size_t>(in.hdr.bytes - in.payload_got);
          char* dst;
          if (seen) {
            dst = scratch;
            if (want > sizeof scratch) want = sizeof scratch;
          } else if (r != nullptr) {
            if (pos < r->bytes) {
              dst = static_cast<char*>(r->buf) + pos;
              if (want > r->bytes - pos)
                want = static_cast<size_t>(r->bytes - pos);
            } else {
              // Oversized tail (recv buffer smaller than the message):
              // drain + drop, still CRC'd — the sender's checksum covers
              // the whole slice.
              dst = scratch;
              if (want > sizeof scratch) want = sizeof scratch;
            }
          } else {
            dst = srx->assembly.data() + pos;
          }
          size_t n = sf.link->ReadSome(dst, want);
          if (n == 0) return;
          NoteRx(p, n);
          if (in.hdr.crc != 0) in.run_crc = wire::Crc32c(in.run_crc, dst, n);
          in.payload_got += n;
        }
        if (in.hdr.crc != 0 && in.run_crc != in.hdr.crc) {
          crc_rejects_.fetch_add(1, std::memory_order_relaxed);
          peer.sc_crc_rejects++;  // wire scope
          if (!recovery_armed_) {
            std::fprintf(stderr, "tpu-acx[%d]: payload CRC mismatch from %d\n",
                         rank_, p);
            _exit(14);
          }
          // Do NOT mark the chunk received or advance this lane's rx_seq:
          // the replayed copy overwrites the same placement range.
          in.hdr_got = 0;
          MaybeNakLocked(p, lane);
          continue;
        }
        if (recovery_armed_) BumpRxLocked(p, lane, in.hdr.seq);
        NoteFrameRxLocked(p, lane, in.hdr);
        if (!seen && srx->got.insert(ck_idx).second) {
          if (srx->have_env && srx->got.size() == srx->nchunks)
            CompleteStripeLocked(p, ck_msg_id);
        }
        in.hdr_got = 0;
        continue;
      }
      if (in.direct != nullptr) {
        RecvReq* r = in.direct.get();
        const size_t deliver =
            r->bytes < in.hdr.bytes ? r->bytes : in.hdr.bytes;
        while (in.payload_got < deliver) {
          char* dst = static_cast<char*>(r->buf) + in.payload_got;
          size_t n = sf.link->ReadSome(dst, deliver - in.payload_got);
          if (n == 0) return;
          NoteRx(p, n);
          if (in.hdr.crc != 0) in.run_crc = wire::Crc32c(in.run_crc, dst, n);
          in.payload_got += n;
        }
        // Oversized tail (recv buffer smaller than message): drain + drop.
        // Still CRC'd — the sender's checksum covers the whole payload.
        while (in.payload_got < in.hdr.bytes) {
          char scratch[4096];
          size_t want = in.hdr.bytes - in.payload_got;
          if (want > sizeof scratch) want = sizeof scratch;
          size_t n = sf.link->ReadSome(scratch, want);
          if (n == 0) return;
          NoteRx(p, n);
          if (in.hdr.crc != 0)
            in.run_crc = wire::Crc32c(in.run_crc, scratch, n);
          in.payload_got += n;
        }
        if (in.hdr.crc != 0 && in.run_crc != in.hdr.crc) {
          crc_rejects_.fetch_add(1, std::memory_order_relaxed);
          peer.sc_crc_rejects++;  // wire scope
          if (!recovery_armed_) {
            std::fprintf(stderr, "tpu-acx[%d]: payload CRC mismatch from %d\n",
                         rank_, p);
            _exit(14);
          }
          // Do NOT complete the recv or advance rx_seq: re-arm the recv at
          // the head of the posted queue (it must match first again) and
          // pull a clean copy from the sender's replay buffer.
          peer.posted.push_front(in.direct);
          in.direct.reset();
          in.hdr_got = 0;
          MaybeNakLocked(p, lane);
          continue;
        }
        if (recovery_armed_) BumpRxLocked(p, lane, in.hdr.seq);
        NoteFrameRxLocked(p, lane, in.hdr);
        NoteMatchLocked(in.hdr.span, r->span);
        // Wire scope: goodput is what the app receives (delivered bytes,
        // truncation excluded), not what crossed the wire.
        peer.sc_rx_payload += deliver;
        peer.sc_rx_frames++;
        r->st = Status{
            p, r->report_tag != INT_MIN ? r->report_tag : in.hdr.tag,
            in.hdr.bytes > r->bytes ? kErrTruncate : 0, deliver};
        r->done = true;
        in.direct.reset();
        in.hdr_got = 0;
        continue;
      }
      while (in.payload_got < in.payload.size()) {
        size_t n = sf.link->ReadSome(in.payload.data() + in.payload_got,
                                     in.payload.size() - in.payload_got);
        if (n == 0) return;
        NoteRx(p, n);
        in.payload_got += n;
      }
      if (in.hdr.crc != 0 &&
          wire::Crc32c(0, in.payload.data(), in.payload.size()) !=
              in.hdr.crc) {
        crc_rejects_.fetch_add(1, std::memory_order_relaxed);
        peer.sc_crc_rejects++;  // wire scope
        if (!recovery_armed_) {
          std::fprintf(stderr, "tpu-acx[%d]: payload CRC mismatch from %d\n",
                       rank_, p);
          _exit(14);
        }
        in.payload.clear();
        in.hdr_got = 0;
        MaybeNakLocked(p, lane);
        continue;
      }
      if (recovery_armed_) BumpRxLocked(p, lane, in.hdr.seq);
      NoteFrameRxLocked(p, lane, in.hdr);
      if (in.hdr.magic == kMagicRts) {
        Msg m;
        m.tag = in.hdr.tag;
        m.ctx = in.hdr.ctx;
        m.span = in.hdr.span;
        m.rv = true;
        memcpy(&m.rv_desc, in.payload.data(), sizeof m.rv_desc);
        m.rv_bytes = in.hdr.bytes;
        in.payload.clear();
        in.hdr_got = 0;
        DeliverLocked(p, std::move(m));
      } else if (in.hdr.magic == kMagicAck) {
        RvAck a;
        memcpy(&a, in.payload.data(), sizeof a);
        in.payload.clear();
        in.hdr_got = 0;
        HandleAckLocked(p, a);
      } else if (in.hdr.magic == kMagicStripe) {
        StripeDesc d;
        memcpy(&d, in.payload.data(), sizeof d);
        in.payload.clear();
        in.hdr_got = 0;
        // A migrated duplicate envelope for a delivered message (lane
        // degradation window) must not resurrect a reassembly entry.
        if (peer.done_stripes.count(uint32_t{d.msg_id}) == 0)
          HandleStripeEnvLocked(p, in.hdr, d);
      } else {
        Msg m;
        m.tag = in.hdr.tag;
        m.ctx = in.hdr.ctx;
        m.span = in.hdr.span;
        m.payload = std::move(in.payload);
        peer.sc_rx_payload += m.payload.size();  // wire scope
        peer.sc_rx_frames++;
        in.payload.clear();
        in.hdr_got = 0;
        DeliverLocked(p, std::move(m));
      }
    }
  }

  void ProgressLocked() ACX_REQUIRES(mu_) {
    if (hb_interval_ns_ != 0) HeartbeatLocked();
    if (recovery_armed_) {
      PollRecoveryLocked();
      // Idle SeqAck flush: without traffic the sender's replay buffer would
      // never trim. Coarse 5ms timer — one NowNs per pass is the only cost.
      const uint64_t now = NowNs();
      if (now - last_ack_flush_ns_ >= 5000000) {
        last_ack_flush_ns_ = now;
        for (int p = 0; p < size_; p++) {
          if (p == rank_ || peer_dead_[p]) continue;
          Peer& peer = peers_[p];
          if (peer.health != 0) continue;
          for (size_t k = 0; k < peer.sf.size(); k++) {
            Subflow& sf = peer.sf[k];
            if (!sf.link || sf.down) continue;
            if (sf.clk.rx_seq > sf.clk.acked_rx) SendSeqAckLocked(p, k);
          }
        }
      }
    }
    for (int p = 0; p < size_; p++) {
      Peer& peer = peers_[p];
      if (p == rank_ || !peer.sf[0].link) continue;  // no wire (malformed env)
      if (peer_dead_[p]) continue;
      if (peer.health != 0) continue;  // reconnecting: leave the link be
      // Lane establishment: the LOWER rank dials every subflow (same no-race
      // DAG as reconnects); lanes redial lazily after a loss.
      if (recovery_armed_ && rank_ < p) EnsureSubflowsLocked(p);
      for (size_t k = 0; k < peer.sf.size(); k++) {
        Subflow& sf = peer.sf[k];
        if (!sf.link || sf.down) continue;
        FlushOutLocked(p, k);
        DrainInLocked(p, k);
        if (peer.health != 0 || peer_dead_[p]) break;
        if (!sf.link->alive()) {
          if (k == 0)
            StartRecoveryLocked(p, "connection closed");
          else
            SubflowLostLocked(p, k);
          if (peer.health != 0 || peer_dead_[p]) break;
        }
      }
      // Acceptor side of a lost subflow: if the dialer's redial ladder
      // never reaches us, stop waiting and degrade to the survivors.
      if (rank_ > p && peer.sf.size() > 1 && !peer_dead_[p] &&
          peer.health == 0) {
        const uint64_t now = NowNs();
        for (size_t k = 1; k < peer.sf.size(); k++) {
          Subflow& sf = peer.sf[k];
          if (!sf.link && !sf.down && sf.give_up_ns != 0 &&
              now >= sf.give_up_ns)
            DegradeSubflowLocked(p, k);
        }
      }
    }
  }

  // Liveness clock: ANY inbound bytes from p count (a multi-second bulk
  // transfer holds heartbeat frames behind it in the FIFO outq, so payload
  // bytes must refresh the clock or large messages would false-positive).
  // Doubles as the rx side of the wire scope: every byte read off any of
  // the peer's lanes passes through here (caller holds mu_).
  void NoteRx(int p, size_t n) {
    if (hb_interval_ns_ != 0) last_rx_ns_[p] = NowNs();
    peers_[p].sc_rx_wire += n;
  }

  void HeartbeatLocked() ACX_REQUIRES(mu_) {
    const uint64_t now = NowNs();
    if (now - last_hb_send_ns_ >= hb_interval_ns_) {
      last_hb_send_ns_ = now;
      for (int p = 0; p < size_; p++) {
        if (p == rank_ || !peers_[p].sf[0].link || peer_dead_[p]) continue;
        if (peers_[p].health != 0) continue;  // reconnecting: nothing to send on
        // One heartbeat per LIVE LANE: each lane's seq field carries that
        // lane's tx high-water (without consuming a number), so the
        // receiver's tail-loss detection works per subflow.
        for (size_t k = 0; k < peers_[p].sf.size(); k++) {
          Subflow& sf = peers_[p].sf[k];
          if (!sf.link || sf.down) continue;
          auto s = std::make_shared<SendReq>();
          s->hdr = MakeHdr(kMagicHb, 0, 0, 0);
          s->hdr.seq = sf.clk.tx_seq;
          SealHdrLocked(p, k, &s->hdr);
          s->wire_payload = s->desc;
          s->wire_bytes = 0;
          s->dst = p;
          sf.outq.push_back(std::move(s));
          hb_sent_.fetch_add(1, std::memory_order_relaxed);
        }
      }
    }
    if (now < grace_deadline_ns_) return;
    for (int p = 0; p < size_; p++) {
      if (p == rank_ || !peers_[p].sf[0].link || peer_dead_[p]) continue;
      // A reconnecting peer is by definition not speaking; the reconnect
      // ladder's own deadline is the liveness verdict for it (satellite:
      // heartbeat monitor must not declare reconnecting links dead).
      if (peers_[p].health != 0) {
        last_rx_ns_[p] = now;
        continue;
      }
      // A peer that never spoke starts its clock at the end of the grace
      // window, not at process start.
      if (last_rx_ns_[p] == 0) last_rx_ns_[p] = now;
      if (now - last_rx_ns_[p] > peer_timeout_ns_)
        MarkPeerDeadLocked(p, "heartbeat timeout", /*hb_detected=*/true);
    }
  }

  // Latch peer p dead and fail everything in flight against it with
  // kErrPeerDead, so every waiter (tickets, barriers, blocking helpers)
  // unblocks in bounded time instead of wedging — the reference's failure
  // mode (SURVEY.md §5.3).
  void MarkPeerDeadLocked(int p, const char* why, bool hb_detected) ACX_REQUIRES(mu_) {
    if (peer_dead_[p]) return;
    peer_dead_[p] = true;
    peers_dead_n_.fetch_add(1, std::memory_order_relaxed);
    ACX_TRACE_EVENT("peer_dead", static_cast<size_t>(p));
    Peer& peer = peers_[p];
    ACX_FLIGHT(kPeerDead, -1, p, -1, peer.sf[0].clk.rx_seq,
               peer.sf[0].clk.epoch);
    uint64_t failed = 0;
    if (peer.health == 1) {
      peer.health = 0;
      recovering_count_.fetch_sub(1, std::memory_order_relaxed);
    }
    if (peer.replay_broken_noted) {
      // The link is gone; it no longer belongs in the "moving but fragile"
      // gauge.
      peer.replay_broken_noted = false;
      replay_broken_links_.fetch_sub(1, std::memory_order_relaxed);
    }
    for (Subflow& sf : peer.sf) {
      sf.replay.Clear();
      if (sf.in.direct) {
        RecvReq* r = sf.in.direct.get();
        r->st = Status{p, r->report_tag != INT_MIN ? r->report_tag : r->tag,
                       kErrPeerDead, 0};
        r->done = true;
        sf.in.direct.reset();
        failed++;
      }
    }
    for (auto& r : peer.posted) {
      r->st = Status{p, r->report_tag != INT_MIN ? r->report_tag : r->tag,
                     kErrPeerDead, 0};
      r->done = true;
      failed++;
    }
    peer.posted.clear();
    for (Subflow& sf : peer.sf) {
      for (auto& s : sf.outq) {
        if (s->done) continue;
        if (s->parent) {
          // Envelope/chunk frames of one striped message: fail the PARENT
          // once, whatever lanes its pieces were queued on.
          if (!s->parent->done) {
            s->parent->st.error = kErrPeerDead;
            s->parent->st.bytes = 0;
            s->parent->done = true;
            failed++;
          }
          s->done = true;
          continue;
        }
        s->st.error = kErrPeerDead;
        s->st.bytes = 0;
        s->done = true;
        // Only user-visible ops count as failed work: raw replay frames and
        // SeqAck/NAK/heartbeat control frames are protocol-internal.
        if (!s->raw && (s->hdr.magic == kMagic || s->hdr.magic == kMagicRts))
          failed++;
      }
      sf.outq.clear();
    }
    // In-progress striped receives: a reassembly with a recv attached fails
    // that recv; one without loses its placeholder too (a recv posted later
    // fails on the dead latch instead). Completed stripes already left the
    // map and stay delivered.
    for (auto it = peer.stripes.begin(); it != peer.stripes.end();) {
      StripeRx& srx = it->second;
      if (srx.direct) {
        RecvReq* r = srx.direct.get();
        r->st = Status{p,
                       r->report_tag != INT_MIN ? r->report_tag : srx.tag,
                       kErrPeerDead, 0};
        r->done = true;
        failed++;
      } else {
        const uint32_t id = it->first;
        for (auto a = peer.arrived.begin(); a != peer.arrived.end();) {
          a = a->stripe_id == id ? peer.arrived.erase(a) : std::next(a);
        }
      }
      it = peer.stripes.erase(it);
    }
    for (auto it = rv_pending_.begin(); it != rv_pending_.end();) {
      if (it->second->dst == p) {
        it->second->st.error = kErrPeerDead;
        it->second->st.bytes = 0;
        it->second->done = true;
        failed++;
        it = rv_pending_.erase(it);
      } else {
        ++it;
      }
    }
    if (failed != 0) failed_ops_.fetch_add(failed, std::memory_order_relaxed);
    // Membership verdict (DESIGN.md §12): a quiet latch (clean EOF, no
    // heartbeat verdict, nothing in flight) is a graceful departure;
    // anything loud is a crash. Both land in the same state machine — an
    // explicit VIEW(left) recorded LEFT first and OnDeath never overrides
    // it, so crash-leave and graceful-leave converge.
    if (failed == 0 && !hb_detected)
      Fleet().OnLeave(p);
    else
      Fleet().OnDeath(p);
    // Quiet latch on a clean EOF with nothing in flight: normal teardown
    // can observe a peer's close after the final barrier, and that is not
    // worth a scary message. Loud when real work was killed.
    if (failed != 0 || hb_detected)
      std::fprintf(stderr,
                   "tpu-acx[%d]: peer %d declared dead (%s); failed %llu "
                   "in-flight op(s)\n",
                   rank_, p, why, static_cast<unsigned long long>(failed));
  }

  // -- survivable-link recovery engine (DESIGN.md §9) ------------------------
  //
  // Roles are fixed by rank order: the LOWER rank dials the HIGHER rank's
  // abstract-namespace listener, so the two sides of an outage never race
  // each other's connect. The dialer walks a bounded exponential ladder
  // (ACX_RECONNECT_MAX attempts, ACX_RECONNECT_BACKOFF_MS base, 2s cap);
  // the acceptor waits out the whole ladder plus margin before giving up.
  // The hello is a WireHeader (magic=kMagicHello): tag = sender's rank,
  // seq = sender's delivered-in-order high water for this peer, epoch =
  // proposed / agreed link epoch. The acceptor's reply is authoritative:
  // agreed = max(proposal, own epoch + 1). Subflow lanes (ctx carries
  // kHelloSubflow | index<<8) ride the SAME listener and the same ladder
  // arithmetic, but heal per lane: only the lane's own clock and replay
  // are touched.

  // True when nothing user-visible is pending against p — dying peers at
  // clean teardown then take the quiet dead-latch fast path instead of a
  // pointless reconnect storm. Replay contents deliberately do NOT count:
  // fully-delivered-but-unacked frames are not in-flight work.
  bool NothingInFlightLocked(int p) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    if (!peer.posted.empty()) return false;
    for (const Subflow& sf : peer.sf) {
      if (sf.in.direct) return false;
      for (const auto& s : sf.outq)
        if (!s->raw && !s->done && wire::Sequenced(s->hdr.magic))
          return false;
    }
    for (const auto& kv : peer.stripes)
      if (kv.second.direct) return false;
    for (auto& kv : rv_pending_)
      if (kv.second->dst == p) return false;
    return true;
  }

  // Ladder arithmetic lives in link_state (unit-tested in isolation); these
  // wrappers bind it to the policy knobs and the per-process jitter state.
  uint64_t DialBackoffMs(int attempt) const {
    return link_state::DialBackoffMs(
        Policy().reconnect_backoff_ms.load(std::memory_order_relaxed),
        attempt);
  }

  uint64_t JitteredWaitNs(uint64_t nominal_ms) {
    return link_state::JitteredWaitNs(&jitter_state_, nominal_ms);
  }

  uint64_t AcceptDeadlineNs() const {
    return link_state::AcceptDeadlineNs(
        Policy().reconnect_backoff_ms.load(std::memory_order_relaxed),
        Policy().reconnect_max.load(std::memory_order_relaxed));
  }

  // The link to p failed (EOF, desync, forced close). Either park the peer
  // in RECOVERING and start the reconnect ladder, or — when recovery can't
  // help (disarmed, replay gapped) or isn't needed (nothing in flight) —
  // fall through to the PR-1 dead-latch.
  void StartRecoveryLocked(int p, const char* why) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    if (peer_dead_[p] || peer.health != 0) return;
    if (NothingInFlightLocked(p)) {
      MarkPeerDeadLocked(p, why, /*hb_detected=*/false);
      return;
    }
    if (!recovery_armed_ || peer.sf[0].replay.broken) {
      MarkPeerDeadLocked(p, why, /*hb_detected=*/true);
      return;
    }
    peer.health = 1;
    recovering_count_.fetch_add(1, std::memory_order_relaxed);
    peer.rec_attempts = 0;
    const uint64_t now = NowNs();
    if (rank_ < p)
      peer.rec_next_ns = now;  // dial immediately; ladder spaces retries
    else
      peer.rec_deadline_ns = now + AcceptDeadlineNs();
    ACX_TRACE_EVENT("link_recovering", static_cast<size_t>(p));
    ACX_FLIGHT(kLinkRecovering, -1, p, -1, peer.sf[0].clk.rx_seq,
               peer.sf[0].clk.epoch);
    std::fprintf(stderr,
                 "tpu-acx[%d]: link to %d lost (%s); attempting reconnect\n",
                 rank_, p, why);
  }

  // Pump every in-progress recovery: accept incoming dials, fire due
  // outgoing dials, expire acceptor deadlines. With an outage in progress
  // (something recovering or dead) the listener is polled every pass; on a
  // fully healthy fleet it is still polled at a coarse 10ms cadence so a
  // late JOINER (DESIGN.md §12) is never stuck waiting on a failure we
  // haven't noticed — at ~100 cheap EAGAIN accepts/sec, not per-sweep.
  void PollRecoveryLocked() ACX_REQUIRES(mu_) {
    const bool urgent =
        recovering_count_.load(std::memory_order_relaxed) != 0 ||
        peers_dead_n_.load(std::memory_order_relaxed) != 0;
    const uint64_t now = NowNs();
    if (!urgent) {
      if (now - last_accept_poll_ns_ < 10000000ull) return;
      last_accept_poll_ns_ = now;
      HandleDialLocked();
      return;
    }
    last_accept_poll_ns_ = now;
    HandleDialLocked();
    if (recovering_count_.load(std::memory_order_relaxed) == 0) return;
    for (int p = 0; p < size_; p++) {
      if (p == rank_ || peer_dead_[p] || peers_[p].health == 0) continue;
      if (rank_ < p) {
        if (now >= peers_[p].rec_next_ns) DialPeerLocked(p);
      } else if (now >= peers_[p].rec_deadline_ns) {
        MarkPeerDeadLocked(p, "reconnect wait expired", /*hb_detected=*/true);
      }
    }
  }

  // One connect() against peer p's abstract-namespace rendezvous listener.
  // Returns the connected fd, or -1 (not listening / no socket).
  int ConnectListenerLocked(int p) ACX_REQUIRES(mu_) {
    int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    struct sockaddr_un sa;
    memset(&sa, 0, sizeof sa);
    sa.sun_family = AF_UNIX;
    const int n = snprintf(sa.sun_path + 1, sizeof(sa.sun_path) - 1,
                           "acx-%s-%d", job_id_.c_str(), p);
    const socklen_t slen = static_cast<socklen_t>(
        offsetof(struct sockaddr_un, sun_path) + 1 + n);
    if (connect(fd, reinterpret_cast<struct sockaddr*>(&sa), slen) != 0) {
      close(fd);
      return -1;
    }
    return fd;
  }

  void DialPeerLocked(int p) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    const uint32_t maxa =
        Policy().reconnect_max.load(std::memory_order_relaxed);
    if (peer.rec_attempts >= static_cast<int>(maxa)) {
      MarkPeerDeadLocked(p, "reconnect attempts exhausted",
                         /*hb_detected=*/true);
      return;
    }
    peer.rec_attempts++;
    peer.rec_next_ns =
        NowNs() + JitteredWaitNs(DialBackoffMs(peer.rec_attempts));
    const int fd = ConnectListenerLocked(p);
    if (fd < 0) return;  // peer not listening (yet, or ever) — ladder retries
    WireHeader hello = MakeHdr(wire::kMagicHello, rank_, 0, 0);
    hello.seq = peer.sf[0].clk.rx_seq;
    hello.epoch = peer.sf[0].clk.epoch + 1;  // proposal; reply authoritative
    hello.hcrc = wire::HeaderCrc(hello);
    WireHeader reply{};
    if (!link_state::IoFullTimed(fd, &hello, sizeof hello, 1000,
                                 /*wr=*/true) ||
        !link_state::IoFullTimed(fd, &reply, sizeof reply, 1000,
                                 /*wr=*/false) ||
        reply.magic != wire::kMagicHello ||
        reply.hcrc != wire::HeaderCrc(reply) || reply.tag != p ||
        reply.epoch < hello.epoch) {
      WarnIfLegacyHello(p, reply.magic);
      close(fd);
      return;
    }
    AdoptLinkLocked(p, fd, reply.seq, reply.epoch);
  }

  // One JOIN dial to peer p's listener (JoinFleet only). Unlike
  // DialPeerLocked this proposes a FRESH incarnation: seq 0, kHelloJoin
  // set, our fleet epoch riding in bytes; the reply carries the acceptor's
  // post-join fleet epoch the same way.
  bool DialJoinLocked(int p) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    const int fd = ConnectListenerLocked(p);
    if (fd < 0) return false;  // peer not listening (yet) — sweeps again
    WireHeader hello = MakeHdr(wire::kMagicHello, rank_, wire::kHelloJoin, 0);
    hello.bytes = Fleet().epoch();
    hello.seq = 0;
    hello.epoch = peer.sf[0].clk.epoch + 1;  // proposal; reply authoritative
    hello.hcrc = wire::HeaderCrc(hello);
    WireHeader reply{};
    if (!link_state::IoFullTimed(fd, &hello, sizeof hello, 1000,
                                 /*wr=*/true) ||
        !link_state::IoFullTimed(fd, &reply, sizeof reply, 2000,
                                 /*wr=*/false) ||
        reply.magic != wire::kMagicHello ||
        reply.hcrc != wire::HeaderCrc(reply) || reply.tag != p ||
        (reply.ctx & wire::kHelloJoin) == 0) {
      WarnIfLegacyHello(p, reply.magic);
      close(fd);
      return false;
    }
    peer.sf[0].clk.epoch = reply.epoch;
    const int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    peer.sf[0].link = std::make_unique<SockLink>(fd, rank_, p);
    last_rx_ns_[p] = NowNs();
    Fleet().AdoptEpoch(reply.bytes);
    ACX_TRACE_EVENT("fleet_join_link", static_cast<size_t>(p));
    ACX_FLIGHT(kLinkUp, -1, p, -1, 0, reply.epoch);
    return true;
  }

  void HandleDialLocked() ACX_REQUIRES(mu_) {
    if (listen_fd_ < 0) return;
    for (;;) {
      const int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_CLOEXEC);
      if (fd < 0) return;  // EAGAIN: no (more) pending dials
      WireHeader hello{};
      if (!link_state::IoFullTimed(fd, &hello, sizeof hello, 1000,
                                   /*wr=*/false) ||
          hello.magic != wire::kMagicHello ||
          hello.hcrc != wire::HeaderCrc(hello) || hello.tag < 0 ||
          hello.tag >= size_ || hello.tag == rank_) {
        WarnIfLegacyHello(-1, hello.magic);
        close(fd);
        continue;
      }
      const int p = hello.tag;
      // Subflow hello (DESIGN.md §15): establish/re-establish ONE striping
      // lane of an otherwise healthy link. Same dial DAG as reconnects
      // (only the lower rank dials), same epoch agreement, scoped to the
      // lane's own clock.
      if ((hello.ctx & wire::kHelloSubflow) != 0) {
        const int k = wire::HelloSubflowIndex(hello.ctx);
        Peer& peer = peers_[p];
        if (k < 1 || k >= stripe::kMaxStripes || hello.tag >= rank_ ||
            peer_dead_[p] || !recovery_armed_ ||
            (static_cast<size_t>(k) < peer.sf.size() && peer.sf[k].down)) {
          close(fd);
          continue;
        }
        if (static_cast<size_t>(k) >= peer.sf.size())
          peer.sf.resize(static_cast<size_t>(k) + 1);
        Subflow& sf = peer.sf[k];
        const uint32_t own = sf.clk.epoch + 1;
        const uint32_t agreed = hello.epoch > own ? hello.epoch : own;
        WireHeader reply =
            MakeHdr(wire::kMagicHello, rank_, wire::HelloSubflowCtx(k), 0);
        reply.seq = sf.clk.rx_seq;
        reply.epoch = agreed;
        reply.hcrc = wire::HeaderCrc(reply);
        if (!link_state::IoFullTimed(fd, &reply, sizeof reply, 1000,
                                     /*wr=*/true)) {
          close(fd);
          continue;
        }
        AdoptSubflowLocked(p, k, fd, hello.seq, agreed);
        continue;
      }
      const bool join = (hello.ctx & wire::kHelloJoin) != 0;
      // Plain reconnects RESUME an incarnation: only LOWER ranks dial us
      // (no connect race) and a dead peer cannot resume. JOIN hellos
      // announce a FRESH incarnation re-occupying the slot: only the joiner
      // dials (still no race), from any rank, dead latch or not.
      if (!join && (hello.tag >= rank_ || peer_dead_[p])) {
        close(fd);
        continue;
      }
      const uint32_t own = peers_[p].sf[0].clk.epoch + 1;
      const uint32_t agreed = hello.epoch > own ? hello.epoch : own;
      if (join) {
        // Adopt FIRST so the reply can carry the post-join fleet epoch. If
        // the reply write then fails, the joiner retries and OnJoin is
        // idempotent; the half-installed link heals through the normal
        // EOF -> quiet-latch -> rejoin path.
        AdoptJoinLocked(p, fd, agreed);
        WireHeader reply = MakeHdr(wire::kMagicHello, rank_,
                                   wire::kHelloJoin, 0);
        reply.bytes = Fleet().epoch();
        reply.seq = 0;
        reply.epoch = agreed;
        reply.hcrc = wire::HeaderCrc(reply);
        if (!link_state::IoFullTimed(fd, &reply, sizeof reply, 1000,
                                     /*wr=*/true))
          peers_[p].sf[0].link->ForceClose();
        continue;
      }
      WireHeader reply = MakeHdr(wire::kMagicHello, rank_, 0, 0);
      reply.seq = peers_[p].sf[0].clk.rx_seq;
      reply.epoch = agreed;
      reply.hcrc = wire::HeaderCrc(reply);
      if (!link_state::IoFullTimed(fd, &reply, sizeof reply, 1000,
                                   /*wr=*/true)) {
        close(fd);
        continue;
      }
      // Adopt even if our side of the link still looked healthy — the
      // incoming hello IS the failure signal (the dialer saw something we
      // haven't read yet).
      AdoptLinkLocked(p, fd, hello.seq, agreed);
    }
  }

  // A fresh incarnation of rank p re-occupies its slot (DESIGN.md §12):
  // retire whatever the old incarnation left behind through the PR-3
  // dead-latch (its in-flight work can never complete), then install the
  // new socket with zeroed wire clocks, clear the dead latch, bump the
  // fleet epoch, and fan the new view over the existing links.
  void AdoptJoinLocked(int p, int fd, uint32_t agreed) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    if (!peer_dead_[p])
      MarkPeerDeadLocked(p, "superseded by joining incarnation",
                         /*hb_detected=*/false);
    peer_dead_[p] = false;
    peers_dead_n_.fetch_sub(1, std::memory_order_relaxed);
    // Fresh wire state: the new incarnation never saw the old stream, so
    // no WIRE state carries over — not the replay buffers, not a
    // half-assembled inbound frame, not the stripe id spaces. The whole
    // lane array rebuilds: lane 0 gets the join socket at the agreed
    // epoch; lanes >= 1 start linkless at epoch 1 and the lower rank
    // redials them lazily. Fully-delivered eager payloads in the
    // unexpected queue DO survive: the old incarnation drained before it
    // left, so data it landed ahead of its departure is valid app traffic
    // a late recv must still match. Rendezvous arrivals cannot — their
    // descriptors point into the dead incarnation's address space.
    peer.sf.clear();
    peer.sf.resize(stripes_ < 1 ? 1 : stripes_);
    peer.sf[0].clk.epoch = agreed;
    peer.next_stripe_id = 1;
    peer.rr_cursor = 0;
    peer.done_stripes.clear();
    peer.replay_broken_noted = false;  // gauge already settled by dead-latch
    for (auto it = peer.arrived.begin(); it != peer.arrived.end();)
      it = it->rv ? peer.arrived.erase(it) : std::next(it);
    peer.rec_attempts = 0;
    peer.rec_next_ns = 0;
    peer.rec_deadline_ns = 0;
    const int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    peer.sf[0].link = std::make_unique<SockLink>(fd, rank_, p);
    last_rx_ns_[p] = NowNs();
    const uint64_t fepoch = Fleet().OnJoin(p);
    ACX_TRACE_EVENT("fleet_join", static_cast<size_t>(p));
    ACX_FLIGHT(kLinkUp, -1, p, -1, 0, agreed);
    std::fprintf(stderr,
                 "tpu-acx[%d]: rank %d joined (link epoch %u, fleet epoch "
                 "%llu)\n",
                 rank_, p, agreed, static_cast<unsigned long long>(fepoch));
    for (int q = 0; q < size_; q++) {
      if (q == rank_ || q == p || !peers_[q].sf[0].link || peer_dead_[q])
        continue;
      if (peers_[q].health != 0) continue;
      SendViewLocked(q, p, MemberState::kMemberActive, fepoch);
    }
    // Catch the joiner up on everyone we already know to be gone — it came
    // up assuming a full fleet and can only discover departures by dial
    // timeout otherwise.
    for (int q = 0; q < size_; q++) {
      if (q == rank_ || q == p) continue;
      const MemberState st = Fleet().state(q);
      if (st == MemberState::kMemberLeft || st == MemberState::kMemberDead)
        SendViewLocked(p, q, st, fepoch);
    }
  }

  // Header-only unsequenced membership frame: tag = subject rank, ctx =
  // its new state, bytes = our fleet epoch (see DrainInLocked's receive
  // side). Rides outside the sequence space like heartbeats; always lane 0.
  void SendViewLocked(int q, int subject, MemberState st, uint64_t fepoch) ACX_REQUIRES(mu_) {
    auto s = std::make_shared<SendReq>();
    s->hdr = MakeHdr(wire::kMagicView, subject, static_cast<int>(st), 0);
    s->hdr.bytes = fepoch;
    SealHdrLocked(q, 0, &s->hdr);
    s->wire_payload = s->desc;
    s->wire_bytes = 0;
    s->dst = q;
    peers_[q].sf[0].outq.push_back(std::move(s));
    FlushOutLocked(q, 0);
  }

  // Install the reconnected socket as the live LANE-0 link to p and restore
  // exactly-once delivery on it: rewind the lane's outq, replay every frame
  // the peer hasn't delivered (epoch re-stamped in place), reset inbound
  // assembly. Subflow lanes are untouched — each heals through its own
  // AdoptSubflowLocked.
  void AdoptLinkLocked(int p, int fd, uint64_t peer_rx, uint32_t agreed) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    Subflow& sf = peer.sf[0];
    const int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    sf.link = std::make_unique<SockLink>(fd, rank_, p);  // old fd closes
    sf.clk.epoch = agreed;
    // Purge the outq: raw replay frames are regenerated from the replay
    // buffer below; unsequenced control frames (HB/SeqAck/NAK) are stale
    // and cheap to regenerate; sequenced survivors rewind to byte 0 with
    // pristine CRCs and the new epoch.
    for (auto it = sf.outq.begin(); it != sf.outq.end();) {
      auto& s = *it;
      if (s->raw) {
        ClearQueuedLocked(p, 0, s->hdr.seq);
        it = sf.outq.erase(it);
      } else if (!wire::Sequenced(s->hdr.magic)) {
        it = sf.outq.erase(it);
      } else {
        s->off = 0;
        if (s->corrupted) {
          s->hdr.crc = s->good_crc;
          s->corrupted = false;
        }
        SealHdrLocked(p, 0, &s->hdr);
        ++it;
      }
    }
    HandleSeqAckLocked(p, 0, peer_rx);  // peer holds everything thru peer_rx
    if (!sf.replay.recs.empty() && sf.replay.recs.front().seq != peer_rx + 1) {
      // The peer needs a frame we no longer hold: recovery can't be
      // lossless, and a silent gap is worse than a dead link.
      MarkPeerDeadLocked(p, "replay buffer exhausted", /*hb_detected=*/true);
      return;
    }
    uint64_t count = 0;
    auto ins = sf.outq.begin();
    for (auto& rec : sf.replay.recs) {
      rec.queued = true;
      framing::RestampFrame(rec.frame.data(), agreed);
      auto s = std::make_shared<SendReq>();
      s->raw = true;
      s->dst = p;
      s->hdr.seq = rec.seq;
      s->wire_payload = rec.frame.data();
      s->wire_bytes = rec.frame.size();
      ins = sf.outq.insert(ins, std::move(s));
      ++ins;
      count++;
    }
    if (count != 0) {
      frames_replayed_.fetch_add(count, std::memory_order_relaxed);
      peer.sc_replayed += count;  // wire scope
    }
    // Inbound assembly state is a torn frame from the dead link: rewind.
    // A half-filled direct recv re-arms at the head of the posted queue;
    // the replayed copy will match it again and overwrite from byte 0.
    InState& in = sf.in;
    if (in.direct) {
      peer.posted.push_front(in.direct);
      in.direct.reset();
    }
    in = InState{};
    if (peer.health == 1) {
      peer.health = 0;
      recovering_count_.fetch_sub(1, std::memory_order_relaxed);
    }
    peer.rec_attempts = 0;
    peer.rec_next_ns = 0;
    peer.rec_deadline_ns = 0;
    sf.stall_until_ns = 0;
    sf.clk.last_nak_ns = 0;
    last_rx_ns_[p] = NowNs();
    reconnects_.fetch_add(1, std::memory_order_relaxed);
    ACX_TRACE_EVENT("link_reconnected", static_cast<size_t>(p));
    ACX_FLIGHT(kLinkUp, -1, p, -1, sf.clk.rx_seq, agreed);
    std::fprintf(stderr,
                 "tpu-acx[%d]: link to %d re-established (epoch %u, "
                 "replaying %llu frame(s))\n",
                 rank_, p, agreed, static_cast<unsigned long long>(count));
    FlushOutLocked(p, 0);
  }

  // -- striping subflow lifecycle (DESIGN.md §15) ----------------------------

  // Dialer side: fire any due subflow dials for an otherwise healthy link.
  void EnsureSubflowsLocked(int p) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    if (peer.sf.size() <= 1) return;
    const uint64_t now = NowNs();
    for (size_t k = 1; k < peer.sf.size(); k++) {
      Subflow& sf = peer.sf[k];
      if (sf.link || sf.down) continue;
      if (now < sf.next_dial_ns) continue;
      DialSubflowLocked(p, static_cast<int>(k));
    }
  }

  // One connect attempt for lane k. Initial establishment (lane epoch still
  // 1) retries forever at the capped backoff — the peer may simply not
  // have its listener up yet, and the link is fully functional on lane 0
  // meanwhile. A REDIAL (lane died after being up) walks the same bounded
  // ladder as lane-0 recovery and then DEGRADES the lane instead of
  // killing the link.
  void DialSubflowLocked(int p, int k) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    Subflow& sf = peer.sf[k];
    const bool redial = sf.clk.epoch > 1;
    sf.dial_attempts++;
    if (redial) {
      const uint32_t maxa =
          Policy().reconnect_max.load(std::memory_order_relaxed);
      if (sf.dial_attempts > static_cast<int>(maxa)) {
        DegradeSubflowLocked(p, static_cast<size_t>(k));
        return;
      }
    }
    sf.next_dial_ns = NowNs() + JitteredWaitNs(DialBackoffMs(
                                    sf.dial_attempts < 16 ? sf.dial_attempts
                                                          : 16));
    const int fd = ConnectListenerLocked(p);
    if (fd < 0) return;  // not listening yet — ladder retries
    WireHeader hello =
        MakeHdr(wire::kMagicHello, rank_, wire::HelloSubflowCtx(k), 0);
    hello.seq = sf.clk.rx_seq;
    hello.epoch = sf.clk.epoch + 1;  // proposal; the reply is authoritative
    hello.hcrc = wire::HeaderCrc(hello);
    WireHeader reply{};
    if (!link_state::IoFullTimed(fd, &hello, sizeof hello, 500,
                                 /*wr=*/true) ||
        !link_state::IoFullTimed(fd, &reply, sizeof reply, 500,
                                 /*wr=*/false) ||
        reply.magic != wire::kMagicHello ||
        reply.hcrc != wire::HeaderCrc(reply) || reply.tag != p ||
        (reply.ctx & wire::kHelloSubflow) == 0 ||
        wire::HelloSubflowIndex(reply.ctx) != k ||
        reply.epoch < hello.epoch) {
      WarnIfLegacyHello(p, reply.magic);
      close(fd);
      return;
    }
    AdoptSubflowLocked(p, static_cast<size_t>(k), fd, reply.seq, reply.epoch);
  }

  // Install a handshaken socket as lane k, replaying the lane's unacked
  // frames — the per-lane mirror of AdoptLinkLocked, touching only this
  // lane's clock/replay/assembly.
  void AdoptSubflowLocked(int p, size_t k, int fd, uint64_t peer_rx,
                          uint32_t agreed) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    Subflow& sf = peer.sf[k];
    const bool redial = sf.clk.epoch > 1;
    const int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    fcntl(fd, F_SETFD, FD_CLOEXEC);
    sf.link = std::make_unique<SockLink>(fd, rank_, p);
    sf.clk.epoch = agreed;
    for (auto it = sf.outq.begin(); it != sf.outq.end();) {
      auto& s = *it;
      if (s->raw) {
        sf.replay.ClearQueued(s->hdr.seq);
        it = sf.outq.erase(it);
      } else if (!wire::Sequenced(s->hdr.magic)) {
        it = sf.outq.erase(it);
      } else {
        s->off = 0;
        if (s->corrupted) {
          s->hdr.crc = s->good_crc;
          s->corrupted = false;
        }
        SealHdrLocked(p, k, &s->hdr);
        ++it;
      }
    }
    sf.replay.AckThrough(peer_rx);
    if (!sf.replay.recs.empty() && sf.replay.recs.front().seq != peer_rx + 1) {
      MarkPeerDeadLocked(p, "subflow replay exhausted", /*hb_detected=*/true);
      return;
    }
    uint64_t count = 0;
    auto ins = sf.outq.begin();
    for (auto& rec : sf.replay.recs) {
      rec.queued = true;
      framing::RestampFrame(rec.frame.data(), agreed);
      auto s = std::make_shared<SendReq>();
      s->raw = true;
      s->dst = p;
      s->hdr.seq = rec.seq;
      s->wire_payload = rec.frame.data();
      s->wire_bytes = rec.frame.size();
      ins = sf.outq.insert(ins, std::move(s));
      ++ins;
      count++;
    }
    if (count != 0) {
      frames_replayed_.fetch_add(count, std::memory_order_relaxed);
      peer.sc_replayed += count;  // wire scope
    }
    if (sf.in.direct) {
      peer.posted.push_front(sf.in.direct);
      sf.in.direct.reset();
    }
    sf.in = InState{};
    sf.stall_until_ns = 0;
    sf.down = false;
    sf.dial_attempts = 0;
    sf.next_dial_ns = 0;
    sf.give_up_ns = 0;
    last_rx_ns_[p] = NowNs();
    // First establishment agrees epoch 2 (both sides proposed 1+1); any
    // higher agreement means the lane was up before — a true reconnect.
    if (redial || agreed > 2) {
      reconnects_.fetch_add(1, std::memory_order_relaxed);
      std::fprintf(stderr,
                   "tpu-acx[%d]: subflow %zu to %d re-established (epoch "
                   "%u, replaying %llu frame(s))\n",
                   rank_, k, p, agreed,
                   static_cast<unsigned long long>(count));
    }
    ACX_FLIGHT(kLinkUp, -1, p, -1, sf.clk.rx_seq, agreed);
    FlushOutLocked(p, k);
  }

  // Lane k's socket died (EOF / forced close) on an otherwise healthy link:
  // drop the fd and schedule the redial ladder (dialer) or the give-up
  // deadline (acceptor). Traffic keeps flowing on the other lanes; the
  // lane's unacked frames sit in its replay buffer until the redial
  // resolves — replayed on success, migrated by DegradeSubflowLocked on
  // failure.
  void SubflowLostLocked(int p, size_t k) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    if (peer_dead_[p] || peer.health != 0) return;
    Subflow& sf = peer.sf[k];
    std::fprintf(stderr,
                 "tpu-acx[%d]: subflow %zu to %d lost; %s\n", rank_, k, p,
                 rank_ < p ? "redialing" : "awaiting redial");
    sf.link.reset();
    if (rank_ < p) {
      sf.dial_attempts = 0;
      sf.next_dial_ns = NowNs();
    } else {
      sf.give_up_ns = NowNs() + AcceptDeadlineNs();
    }
  }

  // The redial ladder for lane k exhausted (or the acceptor's deadline
  // expired): permanently fold the lane into the survivors. Its unacked
  // frames migrate into lane 0's sequence space with FRESH seq numbers —
  // the receiver's per-stripe got-set and done_stripes dedup absorb any
  // frames that had actually been delivered but not yet acked.
  void DegradeSubflowLocked(int p, size_t k) ACX_REQUIRES(mu_) {
    Peer& peer = peers_[p];
    Subflow& sf = peer.sf[k];
    Subflow& sf0 = peer.sf[0];
    if (sf.replay.broken) {
      // The lane evicted unacked frames: migration would leave a
      // permanent gap in some striped message. Same verdict as a gapped
      // lane-0 replay.
      MarkPeerDeadLocked(p, "subflow replay exhausted", /*hb_detected=*/true);
      return;
    }
    // (1) Unwritten frames waiting on the dead lane: carry the sequenced
    // non-raw ones over (they get fresh lane-0 seqs below); raw frames are
    // regenerated from the replay records; control frames are stale.
    std::vector<std::shared_ptr<SendReq>> carry;
    for (auto& s : sf.outq) {
      if (s->raw) {
        sf.replay.ClearQueued(s->hdr.seq);
        continue;
      }
      if (!wire::Sequenced(s->hdr.magic)) continue;
      carry.push_back(s);
    }
    sf.outq.clear();
    // (2) Unacked-but-written frames FIRST (they precede the unwritten ones
    // in message order), restamped into lane 0's epoch/seq space and
    // appended — records move into lane 0's replay so a later lane-0
    // reconnect can still replay them.
    uint64_t moved = 0;
    for (auto& rec : sf.replay.recs) {
      const uint64_t newseq = ++sf0.clk.tx_seq;
      char* blob = rec.frame.data();
      framing::RestampFrame(blob, sf0.clk.epoch, &newseq);
      rec.seq = newseq;
      rec.queued = true;
      auto s = std::make_shared<SendReq>();
      s->raw = true;
      s->dst = p;
      s->hdr.seq = newseq;
      s->wire_payload = blob;
      s->wire_bytes = rec.frame.size();
      sf0.replay.bytes += rec.frame.size();
      sf0.replay.recs.push_back(std::move(rec));
      sf0.outq.push_back(std::move(s));
      moved++;
    }
    sf.replay.recs.clear();
    sf.replay.bytes = 0;
    // (3) Then the never-written carries, stamped after the migrated raws
    // so lane-0 wire order stays sequence order.
    for (auto& s : carry) {
      s->off = 0;
      if (s->corrupted) {
        s->hdr.crc = s->good_crc;
        s->corrupted = false;
      }
      StampSeqLocked(p, 0, &s->hdr);  // fresh lane-0 seq; tx_ns preserved
      sf0.outq.push_back(s);
    }
    if (moved != 0) {
      frames_replayed_.fetch_add(moved, std::memory_order_relaxed);
      peer.sc_replayed += moved;
    }
    if (sf.in.direct) {
      peer.posted.push_front(sf.in.direct);
      sf.in.direct.reset();
    }
    sf.in = InState{};
    sf.link.reset();
    sf.down = true;
    sf.next_dial_ns = 0;
    sf.give_up_ns = 0;
    std::fprintf(stderr,
                 "tpu-acx[%d]: subflow %zu to %d degraded (%llu frame(s) "
                 "migrated); continuing on %d lane(s)\n",
                 rank_, k, p, static_cast<unsigned long long>(moved),
                 LiveLanesLocked(peer));
    FlushOutLocked(p, 0);
  }

  // Blocking control-plane helpers (used by Barrier/AllreduceInt only).
  void SendB(const void* buf, size_t bytes, int dst, int tag) {
    std::unique_ptr<Ticket> t(Isend(buf, bytes, dst, tag, kCtrlCtx));
    Status st;
    while (!t->Test(&st)) sched_yield();
  }
  void RecvB(void* buf, size_t bytes, int src, int tag) {
    std::unique_ptr<Ticket> t(Irecv(buf, bytes, src, tag, kCtrlCtx));
    Status st;
    while (!t->Test(&st)) sched_yield();
  }

  int rank_, size_;
  Mutex mu_;
  std::vector<Peer> peers_ ACX_GUARDED_BY(mu_);
  void* shm_base_;
  size_t shm_len_;
  size_t rv_threshold_ = kRvDefaultThreshold;
  bool rv_force_fallback_ = false;
  uint32_t rv_next_seq_ ACX_GUARDED_BY(mu_) = 1;
  std::unordered_map<uint32_t, std::shared_ptr<SendReq>> rv_pending_
      ACX_GUARDED_BY(mu_);

  // -- resilience state (all guarded by mu_ except the atomic counters) --
  uint64_t hb_interval_ns_ = 0;  // 0 = heartbeats off (EOF detection stays on)
  uint64_t peer_timeout_ns_ = 0;
  uint64_t grace_deadline_ns_ ACX_GUARDED_BY(mu_) = 0;
  uint64_t last_hb_send_ns_ ACX_GUARDED_BY(mu_) = 0;
  std::vector<uint64_t> last_rx_ns_ ACX_GUARDED_BY(mu_);
  std::vector<bool> peer_dead_ ACX_GUARDED_BY(mu_);
  std::atomic<uint64_t> hb_sent_{0};
  std::atomic<uint64_t> hb_recv_{0};
  std::atomic<uint64_t> peers_dead_n_{0};
  std::atomic<uint64_t> failed_ops_{0};

  // -- survivable-link state (DESIGN.md §9) --
  bool recovery_armed_ = false;  // socket plane + ACX_JOB_ID + live listener
  bool crc_on_ = true;           // ACX_CRC (payload CRC32C stamping)
  size_t replay_budget_ = 4u << 20;  // ACX_REPLAY_BUF_BYTES, per subflow
  std::string job_id_;
  int listen_fd_ = -1;
  uint64_t last_ack_flush_ns_ = 0;  // idle SeqAck flush timer
  uint64_t last_accept_poll_ns_ = 0;  // coarse listener poll when healthy
  uint64_t jitter_state_ = 0;  // backoff-jitter LCG state (JitteredWaitNs)
  std::atomic<uint64_t> reconnects_{0};
  std::atomic<uint64_t> frames_replayed_{0};
  std::atomic<uint64_t> crc_rejects_{0};
  std::atomic<uint64_t> naks_sent_{0};
  std::atomic<uint64_t> recovering_count_{0};
  std::atomic<uint64_t> replay_broken_links_{0};

  // -- striping (DESIGN.md §15) --
  stripe::Config stripe_cfg_;  // ACX_STRIPES / ACX_STRIPE_MIN_BYTES
  int stripes_ = 1;            // effective lane count (1 unless armed)
};

bool SockTicket::Test(Status* st) { return t_->TestReq(send_, recv_, st); }

// -- Partitioned channels -------------------------------------------------
//
// One logical N-partition message per round (reference MPI_Psend_init /
// MPI_Precv_init, partitioned.cu:36-123); each partition travels as an
// independent point-to-point message on (PartTag(tag,p), PartCtx(ctx)), so
// out-of-order Pready works and per-partition arrival is observable — the
// property ring-partitioned.cu's device polling depends on.
//
// Thread-safety contract: Pready/Parrived are called by the proxy while the
// round is in flight; StartRound/FinishRound are called by the app thread
// only when every partition's flag has been observed RESERVED/COMPLETED
// (acquire), which happens-after the proxy's last touch (release) — so no
// extra locking is needed here beyond the transport's own mutex.

class SockPsendChan : public PartitionedChan {
 public:
  SockPsendChan(StreamTransport* t, const void* buf, int parts, size_t pb,
                int dst, int tag, int ctx)
      : t_(t), buf_(static_cast<const char*>(buf)), dst_(dst), tag_(tag),
        ctx_(ctx) {
    partitions = parts;
    part_bytes = pb;
    is_send = true;
    inflight_.reserve(parts);
  }

  void Pready(int p) override {
    inflight_.emplace_back(t_->Isend(buf_ + static_cast<size_t>(p) * part_bytes,
                                     part_bytes, dst_, PartTag(tag_, p),
                                     PartCtx(ctx_)));
    t_->PartInflightAdd(dst_, 1);
  }
  bool Parrived(int) override { return false; }  // send side has no arrivals
  void StartRound() override { inflight_.clear(); }
  void FinishRound(Status* st) override {
    // Sends resolve in bounded time — a live peer's link drains the outq
    // and a dead peer's teardown error-completes it — but the error must
    // not be swallowed: a partition that never reached the peer is the
    // receiver's missing round.
    Status out{t_->rank(), tag_, 0,
               part_bytes * static_cast<size_t>(partitions)};
    Status tmp;
    for (auto& tk : inflight_) {
      while (!tk->Test(&tmp)) sched_yield();
      if (tmp.error != 0 && out.error == 0)
        out = Status{dst_, tag_, tmp.error, 0};
      t_->PartInflightAdd(dst_, -1);
    }
    if (st) *st = out;
    inflight_.clear();
  }

 private:
  StreamTransport* t_;
  const char* buf_;
  int dst_, tag_, ctx_;
  std::vector<std::unique_ptr<Ticket>> inflight_;
};

class SockPrecvChan : public PartitionedChan {
 public:
  SockPrecvChan(StreamTransport* t, void* buf, int parts, size_t pb, int src,
                int tag, int ctx)
      : t_(t), buf_(static_cast<char*>(buf)), src_(src), tag_(tag), ctx_(ctx),
        tickets_(parts), done_(parts, false) {
    partitions = parts;
    part_bytes = pb;
    is_send = false;
  }

  void Pready(int) override {}
  bool Parrived(int p) override {
    if (done_[p]) return true;
    Status st;
    if (tickets_[p] && tickets_[p]->Test(&st)) {
      done_[p] = true;
      t_->PartInflightAdd(src_, -1);
      // Completed WITH an error (peer died mid-round) means "resolved",
      // not "arrived" — keep the status so FinishRound reports it instead
      // of handing the caller silent stale bytes.
      if (st.error != 0 && err_.error == 0) err_ = st;
      return true;
    }
    return false;
  }
  void StartRound() override {
    err_ = Status{};
    for (int p = 0; p < partitions; p++) {
      done_[p] = false;
      tickets_[p].reset(
          t_->Irecv(buf_ + static_cast<size_t>(p) * part_bytes, part_bytes,
                    src_, PartTag(tag_, p), PartCtx(ctx_)));
    }
    t_->PartInflightAdd(src_, partitions);
  }
  void FinishRound(Status* st) override {
    // By the wait contract every partition slot has already completed —
    // either arrived, or failed by the proxy (arrival deadline / drain).
    // NEVER spin here: a failed partition's frame may never come. Un-post
    // abandoned recvs so a redone round can't be half-matched against
    // this round's stale requests.
    Status out{src_, tag_, 0, part_bytes * static_cast<size_t>(partitions)};
    for (int p = 0; p < partitions; p++) {
      if (!Parrived(p)) {
        if (tickets_[p])
          t_->CancelPostedRecv(
              static_cast<SockTicket*>(tickets_[p].get())->recv());
        if (out.error == 0) out = Status{src_, tag_, kErrTimeout, 0};
        t_->PartInflightAdd(src_, -1);  // abandoned, never arrived
      }
      tickets_[p].reset();
    }
    if (err_.error != 0 && out.error == 0) out = err_;
    err_ = Status{};
    if (st) *st = out;
  }

 private:
  StreamTransport* t_;
  char* buf_;
  int src_, tag_, ctx_;
  std::vector<std::unique_ptr<Ticket>> tickets_;
  std::vector<bool> done_;
  Status err_{};
};

PartitionedChan* StreamTransport::PsendInit(const void* buf, int partitions,
                                            size_t part_bytes, int dst,
                                            int tag, int ctx) {
  return new SockPsendChan(this, buf, partitions, part_bytes, dst, tag, ctx);
}

PartitionedChan* StreamTransport::PrecvInit(void* buf, int partitions,
                                            size_t part_bytes, int src,
                                            int tag, int ctx) {
  return new SockPrecvChan(this, buf, partitions, part_bytes, src, tag, ctx);
}

}  // namespace

Transport* CreateSocketTransport(int rank, int size,
                                 const std::vector<int>& fds) {
  std::vector<std::unique_ptr<Link>> links(size);
  for (int i = 0; i < size; i++) {
    if (i == rank || fds[i] < 0) continue;
    const int fl = fcntl(fds[i], F_GETFL, 0);
    fcntl(fds[i], F_SETFL, fl | O_NONBLOCK);
    // CLOEXEC: a rank that fork+execs its replacement (rolling restart)
    // must not leak link fds into it — peers would never see EOF.
    fcntl(fds[i], F_SETFD, FD_CLOEXEC);
    links[i] = std::make_unique<SockLink>(fds[i], rank, i);
  }
  return new StreamTransport(rank, size, std::move(links), nullptr, 0,
                             /*sock_plane=*/true);
}

Transport* CreateShmTransport(int rank, int size, void* base,
                              size_t ring_bytes, size_t owned_len) {
  std::vector<std::unique_ptr<Link>> links(size);
  for (int i = 0; i < size; i++) {
    if (i == rank) continue;
    links[i] = std::make_unique<ShmLink>(static_cast<char*>(base), size,
                                         ring_bytes, rank, i);
  }
  return new StreamTransport(rank, size, std::move(links),
                             owned_len != 0 ? base : nullptr, owned_len);
}

Transport* CreateSelfTransport() {
  // A size-1 StreamTransport is pure loopback: every send routes through
  // DeliverLocked and never touches a wire.
  std::vector<std::unique_ptr<Link>> links(1);
  return new StreamTransport(0, 1, std::move(links));
}

Transport* CreateTransportFromEnv() {
  const char* size_s = getenv("ACX_SIZE");
  const int size = size_s ? atoi(size_s) : 1;
  if (size <= 1) return CreateSelfTransport();
  const char* rank_s = getenv("ACX_RANK");
  if (!rank_s) {
    std::fprintf(stderr,
                 "tpu-acx: ACX_SIZE=%d but ACX_RANK unset (run under acxrun)\n",
                 size);
    exit(13);
  }
  const int rank = atoi(rank_s);

  // Late joiner (DESIGN.md §12): no inherited fds at all — bootstrap every
  // link through the peers' ACX_JOB_ID rendezvous listeners with a JOIN
  // handshake. Used by a replacement process in a rolling restart.
  const char* join_s = getenv("ACX_JOIN");
  if (join_s != nullptr && atoi(join_s) != 0) {
    if (getenv("ACX_JOB_ID") == nullptr) {
      std::fprintf(stderr,
                   "tpu-acx: ACX_JOIN=1 but ACX_JOB_ID unset (nothing to "
                   "rendezvous on)\n");
      exit(13);
    }
    const char* bud_s = getenv("ACX_FLEET_JOIN_TIMEOUT_MS");
    const int budget_ms = bud_s != nullptr ? atoi(bud_s) : 10000;
    std::vector<std::unique_ptr<Link>> links(size);
    auto* t = new StreamTransport(rank, size, std::move(links), nullptr, 0,
                                  /*sock_plane=*/true);
    if (t->JoinFleet(budget_ms) == 0) {
      std::fprintf(stderr,
                   "tpu-acx[%d]: join failed: no peer reachable within "
                   "%d ms\n",
                   rank, budget_ms);
      exit(13);
    }
    return t;
  }

  // Same-host fast path: the memfd segment acxrun created, unless the user
  // forces the socket plane with ACX_TRANSPORT=socket.
  const char* want = getenv("ACX_TRANSPORT");
  const char* shm_fd_s = getenv("ACX_SHM_FD");
  if (want != nullptr && strcmp(want, "shm") == 0 && shm_fd_s == nullptr) {
    // shm requested by name but no segment exists: fail loudly rather than
    // silently running (and benchmarking) the socket plane.
    std::fprintf(stderr,
                 "tpu-acx: ACX_TRANSPORT=shm but no ACX_SHM_FD (launcher "
                 "could not create the shm segment?)\n");
    exit(13);
  }
  if (shm_fd_s != nullptr && (want == nullptr || strcmp(want, "socket") != 0)) {
    const int fd = atoi(shm_fd_s);
    const char* ring_s = getenv("ACX_SHM_RING_BYTES");
    const size_t ring_bytes = ShmSanitizeRingBytes(
        ring_s ? strtoull(ring_s, nullptr, 10) : kShmDefaultRingBytes);
    const size_t len = ShmSegmentBytes(size, ring_bytes);
    void* base = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (base == MAP_FAILED) {
      std::fprintf(stderr, "tpu-acx: mmap of ACX_SHM_FD failed: %s\n",
                   strerror(errno));
      exit(13);
    }
    close(fd);
    return CreateShmTransport(rank, size, base, ring_bytes, len);
  }

  const char* fds_s = getenv("ACX_FDS");
  if (!fds_s) {
    std::fprintf(stderr,
                 "tpu-acx: ACX_SIZE=%d but ACX_FDS unset (run under acxrun)\n",
                 size);
    exit(13);
  }
  std::vector<int> fds;
  const char* s = fds_s;
  while (*s) {
    fds.push_back(atoi(s));
    const char* c = strchr(s, ',');
    if (!c) break;
    s = c + 1;
  }
  if (static_cast<int>(fds.size()) != size) {
    std::fprintf(stderr, "tpu-acx: ACX_FDS has %zu entries, want %d\n",
                 fds.size(), size);
    exit(13);
  }
  return CreateSocketTransport(rank, size, fds);
}

}  // namespace acx
