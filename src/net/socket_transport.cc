// tpu-acx: StreamTransport — the multi-process data plane.
//
// Plays the role the MPI library plays for the reference (SURVEY.md §2 L0;
// reference src/init.cpp:66-141 posts MPI_Isend/Irecv/Test): nonblocking
// point-to-point with FIFO matching per (src, tag, ctx), partitioned
// channels, and the two control collectives (Barrier, AllreduceInt) the
// runtime and compat layer need.
//
// The framing/matching engine is wire-agnostic over Link (src/net/link.h):
//   * socket plane — AF_UNIX stream socketpairs pre-connected by `acxrun`
//     (tools/acxrun.cc), one per peer, passed down via ACX_FDS;
//   * shm plane — SPSC byte rings in a memfd segment created by acxrun
//     (ACX_SHM_FD), the same-host fast path (no syscalls per message).
// Progress() flushes pending writes and drains arrivals, and is driven from
// Ticket::Test so the proxy's sweep loop is also the transport's progress
// engine. A single mutex serializes the proxy thread and app threads — the
// message-rate ceiling of this backend is host-side anyway (on-TPU traffic
// rides ICI via XLA collectives, not this path).

#include "acx/net.h"

#include <errno.h>
#include <fcntl.h>
#include <sched.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/prctl.h>
#include <sys/uio.h>
#include <unistd.h>

#include <climits>
#include <unordered_map>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <memory>
#include <mutex>
#include <vector>

#include "acx/fault.h"
#include "acx/trace.h"
#include "src/net/link.h"

namespace acx {
namespace {

constexpr uint32_t kMagic = 0xAC0C0101u;
// Rendezvous frames (large-message single-copy path, same host only):
// an RTS frame advertises {addr, seq, pid} of the sender's buffer; the
// receiver pulls the payload with one process_vm_readv straight into the
// destination (the copy-through-the-ring path costs two copies) and acks.
// A nack (ok=0, e.g. pvread denied by a hardened kernel) makes the sender
// re-send the payload as a normal copy frame on a private (seq, ctx) key.
constexpr uint32_t kMagicRts = 0xAC0C0102u;
constexpr uint32_t kMagicAck = 0xAC0C0103u;
// Heartbeat: a zero-payload keepalive frame. Any inbound bytes refresh the
// peer's liveness clock, so heartbeats only need to flow when the wire is
// otherwise quiet. Essential on the shm plane, which has no EOF concept.
constexpr uint32_t kMagicHb = 0xAC0C0104u;

// Internal context ids. User contexts are >= 0; the control plane and the
// partitioned layer get their own namespaces so they can never match user
// point-to-point traffic.
constexpr int kCtrlCtx = -2;
constexpr int kRvDataCtx = -3;  // rendezvous-fallback payload frames
constexpr size_t kRvDefaultThreshold = 256u << 10;
inline int PartCtx(int ctx) { return -1000 - ctx; }
// Partition p of a tag-tagged partitioned channel travels as its own
// message; 4096 partitions per channel (the reference's whole slot table is
// 4096, mpi-acx-internal.h:141, so this bounds nothing in practice).
inline int PartTag(int tag, int p) { return tag * 4096 + p; }

#pragma pack(push, 1)
struct WireHeader {
  uint32_t magic;
  int32_t tag;
  int32_t ctx;
  uint64_t bytes;
};
struct RvDesc {  // RTS wire payload
  uint64_t addr;
  uint32_t seq;
  int32_t pid;
};
struct RvAck {  // ACK wire payload
  uint32_t seq;
  int32_t ok;
};
#pragma pack(pop)

// Zero-copy send: the wire is fed straight from the user buffer (legal —
// the caller may not touch it until the ticket completes), so large
// messages cost exactly one memcpy into the ring / socket.
struct SendReq {
  WireHeader hdr{};
  const char* payload = nullptr;  // user buffer, borrowed until done
  size_t bytes = 0;               // user message length (== hdr.bytes)
  const char* wire_payload = nullptr;  // what actually goes on the wire
  size_t wire_bytes = 0;               // (== payload/bytes except RTS/ACK)
  size_t off = 0;  // progress over [header | wire payload]
  bool rv = false;  // rendezvous: wire completion != user completion
  bool done = false;
  int dst = -1;   // destination rank (dead-peer teardown scans rv_pending_)
  char desc[16];  // storage for RTS/ACK wire payloads
  Status st;
};

struct RecvReq {
  void* buf = nullptr;
  size_t bytes = 0;
  int src = -1, tag = 0, ctx = 0;
  // Rendezvous fallback rewrites the matching key to (seq, kRvDataCtx);
  // report_tag preserves the user-visible tag for the Status.
  int report_tag = INT_MIN;
  bool done = false;
  Status st;
};

struct Msg {
  int tag = 0, ctx = 0;
  std::vector<char> payload;
  bool rv = false;  // unexpected RTS: payload empty, fields below valid
  RvDesc rv_desc{};
  uint64_t rv_bytes = 0;  // full message length advertised by the RTS
};

// Incoming-byte-stream assembly state for one peer link. When the header
// matches an already-posted recv, payload bytes stream directly into the
// recv buffer (`direct`); otherwise they assemble into `payload` and queue
// as an unexpected message.
struct InState {
  WireHeader hdr{};
  size_t hdr_got = 0;
  std::vector<char> payload;
  size_t payload_got = 0;
  std::shared_ptr<RecvReq> direct;
};

class StreamTransport;

class SockTicket : public Ticket {
 public:
  SockTicket(StreamTransport* t, std::shared_ptr<SendReq> s)
      : t_(t), send_(std::move(s)) {}
  SockTicket(StreamTransport* t, std::shared_ptr<RecvReq> r)
      : t_(t), recv_(std::move(r)) {}
  bool Test(Status* st) override;

 private:
  StreamTransport* t_;
  std::shared_ptr<SendReq> send_;
  std::shared_ptr<RecvReq> recv_;
};

class StreamTransport : public Transport {
 public:
  // links[i] is the wire to rank i (null at i == rank). shm_base/shm_len, if
  // set, is a mapping to munmap at teardown.
  StreamTransport(int rank, int size, std::vector<std::unique_ptr<Link>> links,
                  void* shm_base = nullptr, size_t shm_len = 0)
      : rank_(rank), size_(size), links_(std::move(links)), peers_(size),
        shm_base_(shm_base), shm_len_(shm_len) {
    const char* e = getenv("ACX_RV_THRESHOLD");
    if (e != nullptr) {
      const unsigned long long v = strtoull(e, nullptr, 10);
      rv_threshold_ = v == 0 ? SIZE_MAX : static_cast<size_t>(v);
    }
    // Test hook: pretend every pvread fails so the nack/copy-fallback
    // path (the behavior on ptrace-hardened kernels) gets exercised.
    const char* ff = getenv("ACX_RV_FORCE_FALLBACK");
    rv_force_fallback_ = ff != nullptr && atoi(ff) != 0;
    // Resilience: heartbeats are opt-in (ACX_HEARTBEAT_MS > 0); EOF-based
    // dead-peer detection on socket links is always on. The grace window
    // keeps slow-starting peers (module import, JIT warmup) from being
    // declared dead before they ever speak.
    last_rx_ns_.assign(size_, 0);
    peer_dead_.assign(size_, false);
    if (size_ > 1) {
      if (const char* hb = getenv("ACX_HEARTBEAT_MS")) {
        const double ms = atof(hb);
        if (ms > 0) hb_interval_ns_ = static_cast<uint64_t>(ms * 1e6);
      }
      if (hb_interval_ns_ != 0) {
        double to_ms = 0;
        if (const char* t = getenv("ACX_PEER_TIMEOUT_MS")) to_ms = atof(t);
        peer_timeout_ns_ = to_ms > 0 ? static_cast<uint64_t>(to_ms * 1e6)
                                     : 5 * hb_interval_ns_;
        double grace_ms = 5000;
        if (const char* g = getenv("ACX_PEER_GRACE_MS")) grace_ms = atof(g);
        grace_deadline_ns_ = NowNs() + static_cast<uint64_t>(grace_ms * 1e6);
      }
    }
#ifdef PR_SET_PTRACER
    // Let sibling ranks process_vm_readv our send buffers even under
    // Yama ptrace_scope=1 (no-op where Yama is absent; nack path covers
    // kernels where this still isn't enough). SCOPE WARNING: PTRACER_ANY
    // relaxes Yama for the whole process against ANY same-UID process,
    // not just sibling ranks — so it is armed only inside an
    // acxrun-managed job (ACX_FDS set: every same-UID peer is part of
    // this job's trust domain) or when explicitly requested with
    // ACX_RV_PTRACER=1; ACX_RV_PTRACER=0 always disables it, and the
    // rendezvous path stays correct either way via the nack->copy
    // fallback. Also skipped when rendezvous is off (ACX_RV_THRESHOLD=0).
    const char* pt = getenv("ACX_RV_PTRACER");
    const bool want_ptracer =
        pt != nullptr ? atoi(pt) != 0 : getenv("ACX_FDS") != nullptr;
    if (size_ > 1 && rv_threshold_ != SIZE_MAX && want_ptracer)
      prctl(PR_SET_PTRACER, PR_SET_PTRACER_ANY, 0, 0, 0);
#endif
  }

  ~StreamTransport() override {
    links_.clear();
    if (shm_base_ != nullptr) munmap(shm_base_, shm_len_);
  }

  int rank() const override { return rank_; }
  int size() const override { return size_; }

  Ticket* Isend(const void* buf, size_t bytes, int dst, int tag,
                int ctx) override {
    std::lock_guard<std::mutex> lk(mu_);
    return IsendLocked(buf, bytes, dst, tag, ctx);
  }

  Ticket* Irecv(void* buf, size_t bytes, int src, int tag, int ctx) override {
    std::lock_guard<std::mutex> lk(mu_);
    return IrecvLocked(buf, bytes, src, tag, ctx);
  }

  PartitionedChan* PsendInit(const void* buf, int partitions,
                             size_t part_bytes, int dst, int tag,
                             int ctx) override;
  PartitionedChan* PrecvInit(void* buf, int partitions, size_t part_bytes,
                             int src, int tag, int ctx) override;

  // Fan-in/fan-out barrier through rank 0 on the control context. The
  // reference gets this from MPI_Barrier for free; sufficient at host-plane
  // process counts.
  void Barrier(int /*ctx*/) override {
    if (rank_ == 0) {
      int token = 0;
      for (int p = 1; p < size_; p++) RecvB(&token, sizeof token, p, 1);
      for (int p = 1; p < size_; p++) SendB(&token, sizeof token, p, 2);
    } else {
      int token = rank_;
      SendB(&token, sizeof token, 0, 1);
      RecvB(&token, sizeof token, 0, 2);
    }
  }

  void AllreduceInt(int32_t* data, int count, int op, int /*ctx*/) override {
    const size_t nb = sizeof(int32_t) * static_cast<size_t>(count);
    if (rank_ == 0) {
      std::vector<int32_t> tmp(count);
      for (int p = 1; p < size_; p++) {
        RecvB(tmp.data(), nb, p, 3);
        for (int i = 0; i < count; i++) {
          switch (op) {
            case 0: data[i] = data[i] > tmp[i] ? data[i] : tmp[i]; break;
            case 1: data[i] = data[i] < tmp[i] ? data[i] : tmp[i]; break;
            default: data[i] += tmp[i]; break;
          }
        }
      }
      for (int p = 1; p < size_; p++) SendB(data, nb, p, 4);
    } else {
      SendB(data, nb, 0, 3);
      RecvB(data, nb, 0, 4);
    }
  }

  void Abort(int code) override {
    std::fprintf(stderr, "tpu-acx[%d]: abort(%d)\n", rank_, code);
    _exit(code);
  }

  // Background protocol work (heartbeats, dead-peer checks) when no
  // Ticket::Test is pumping progress; called from the proxy's idle branches.
  void Tick() override {
    if (size_ <= 1) return;
    std::lock_guard<std::mutex> lk(mu_);
    ProgressLocked();
  }

  NetStats net_stats() const override {
    NetStats ns;
    ns.hb_sent = hb_sent_.load(std::memory_order_relaxed);
    ns.hb_recv = hb_recv_.load(std::memory_order_relaxed);
    ns.peers_dead = peers_dead_n_.load(std::memory_order_relaxed);
    ns.failed_ops = failed_ops_.load(std::memory_order_relaxed);
    return ns;
  }

  // Called from SockTicket::Test.
  bool TestReq(const std::shared_ptr<SendReq>& s,
               const std::shared_ptr<RecvReq>& r, Status* st) {
    std::lock_guard<std::mutex> lk(mu_);
    ProgressLocked();
    if (s) {
      if (s->done && st) *st = s->st;
      return s->done;
    }
    if (r->done && st) *st = r->st;
    return r->done;
  }

 private:
  friend class SockPsendChan;
  friend class SockPrecvChan;

  struct Peer {
    std::deque<std::shared_ptr<SendReq>> outq;
    InState in;
    std::deque<Msg> arrived;                     // unmatched arrivals, FIFO
    std::deque<std::shared_ptr<RecvReq>> posted; // unmatched recvs, FIFO
  };

  Ticket* IsendLocked(const void* buf, size_t bytes, int dst, int tag,
                      int ctx) {
    if (dst != rank_ && (dst < 0 || dst >= size_ || !links_[dst])) {
      std::fprintf(stderr, "tpu-acx[%d]: no wire to peer %d\n", rank_, dst);
      _exit(14);
    }
    if (dst != rank_ && peer_dead_[dst]) {
      // Immediate-error ticket: blocking helpers and barriers that touch a
      // dead peer stay bounded instead of wedging.
      auto s = std::make_shared<SendReq>();
      s->st = Status{rank_, tag, kErrPeerDead, 0};
      s->done = true;
      return new SockTicket(this, s);
    }
    auto s = std::make_shared<SendReq>();
    s->st = Status{rank_, tag, 0, bytes};
    s->dst = dst;
    if (dst == rank_) {
      // Self-send: loop straight back through the matching queues.
      Msg m;
      m.tag = tag;
      m.ctx = ctx;
      m.payload.assign(static_cast<const char*>(buf),
                       static_cast<const char*>(buf) + bytes);
      DeliverLocked(rank_, std::move(m));
      s->done = true;
      return new SockTicket(this, s);
    }
    s->payload = static_cast<const char*>(buf);
    s->bytes = bytes;
    if (bytes >= rv_threshold_) {
      // Rendezvous: put a 16-byte RTS on the wire instead of the payload;
      // completion comes from the receiver's ACK (HandleAckLocked).
      const uint32_t seq = rv_next_seq_++;
      s->hdr = WireHeader{kMagicRts, tag, ctx, bytes};
      RvDesc d{reinterpret_cast<uint64_t>(buf), seq, getpid()};
      static_assert(sizeof d <= sizeof s->desc, "desc too small");
      memcpy(s->desc, &d, sizeof d);
      s->wire_payload = s->desc;
      s->wire_bytes = sizeof d;
      s->rv = true;
      rv_pending_[seq] = s;
    } else {
      s->hdr = WireHeader{kMagic, tag, ctx, bytes};
      s->wire_payload = s->payload;
      s->wire_bytes = bytes;
    }
    peers_[dst].outq.push_back(s);
    FlushOutLocked(dst);
    return new SockTicket(this, s);
  }

  Ticket* IrecvLocked(void* buf, size_t bytes, int src, int tag, int ctx) {
    // Same loud failure as IsendLocked: a recv from a wireless peer would
    // otherwise sit in `posted` forever (ProgressLocked skips null links).
    if (src != rank_ && (src < 0 || src >= size_ || !links_[src])) {
      std::fprintf(stderr, "tpu-acx[%d]: no wire to peer %d\n", rank_, src);
      _exit(14);
    }
    if (src != rank_ && peer_dead_[src]) {
      auto r = std::make_shared<RecvReq>();
      r->st = Status{src, tag, kErrPeerDead, 0};
      r->done = true;
      return new SockTicket(this, r);
    }
    auto r = std::make_shared<RecvReq>();
    r->buf = buf;
    r->bytes = bytes;
    r->src = src;
    r->tag = tag;
    r->ctx = ctx;
    // Try the unexpected queue first (FIFO per (src, tag, ctx)).
    auto& q = peers_[src].arrived;
    for (auto it = q.begin(); it != q.end(); ++it) {
      if (it->tag == tag && it->ctx == ctx) {
        if (it->rv) {
          CompleteRvLocked(src, r, it->tag, it->rv_bytes, it->rv_desc);
        } else {
          CompleteRecv(r.get(), src, *it);
        }
        q.erase(it);
        return new SockTicket(this, r);
      }
    }
    peers_[src].posted.push_back(r);
    return new SockTicket(this, r);
  }

  static void CompleteRecv(RecvReq* r, int src, const Msg& m) {
    const size_t n = m.payload.size() < r->bytes ? m.payload.size() : r->bytes;
    memcpy(r->buf, m.payload.data(), n);
    const int err = m.payload.size() > r->bytes ? kErrTruncate : 0;
    r->st =
        Status{src, r->report_tag != INT_MIN ? r->report_tag : m.tag, err, n};
    r->done = true;
  }

  // Pull an RTS-advertised payload straight out of the sender's address
  // space (one copy), then ack. On pvread failure, nack and repost the recv
  // on the private fallback key the sender will use for the copy re-send.
  void CompleteRvLocked(int src, const std::shared_ptr<RecvReq>& r, int tag,
                        uint64_t full_bytes, const RvDesc& d) {
    const size_t deliver = r->bytes < full_bytes ? r->bytes : full_bytes;
    size_t got = 0;
    if (!rv_force_fallback_) {
      // Loop: one process_vm_readv call moves at most MAX_RW_COUNT
      // (~2 GiB), so giant messages take several calls.
      while (got < deliver) {
        struct iovec liov{static_cast<char*>(r->buf) + got, deliver - got};
        struct iovec riov{reinterpret_cast<void*>(d.addr + got),
                          deliver - got};
        const ssize_t n = process_vm_readv(d.pid, &liov, 1, &riov, 1, 0);
        if (n <= 0) break;
        got += static_cast<size_t>(n);
      }
    }
    const bool ok = !rv_force_fallback_ && got == deliver;
    if (ok) {
      r->st = Status{src, tag, full_bytes > r->bytes ? kErrTruncate : 0,
                     deliver};
      r->done = true;
    } else {
      r->report_tag = tag;
      r->tag = static_cast<int>(d.seq & 0x7fffffff);
      r->ctx = kRvDataCtx;
      peers_[src].posted.push_back(r);
    }
    SendAckLocked(src, d.seq, ok);
  }

  void SendAckLocked(int dst, uint32_t seq, bool ok) {
    auto s = std::make_shared<SendReq>();
    s->hdr = WireHeader{kMagicAck, 0, 0, 0};
    RvAck a{seq, ok ? 1 : 0};
    memcpy(s->desc, &a, sizeof a);
    s->wire_payload = s->desc;
    s->wire_bytes = sizeof a;
    peers_[dst].outq.push_back(std::move(s));
    FlushOutLocked(dst);
  }

  void HandleAckLocked(int src, const RvAck& a) {
    auto it = rv_pending_.find(a.seq);
    if (it == rv_pending_.end()) return;  // duplicate/stale ack
    std::shared_ptr<SendReq> s = it->second;
    rv_pending_.erase(it);
    if (a.ok) {
      s->done = true;
      return;
    }
    // Receiver couldn't pvread: re-send as a normal copy frame on the
    // fallback key it just posted.
    s->rv = false;
    s->hdr = WireHeader{kMagic, static_cast<int>(a.seq & 0x7fffffff),
                        kRvDataCtx, s->bytes};
    s->wire_payload = s->payload;
    s->wire_bytes = s->bytes;
    s->off = 0;
    peers_[src].outq.push_back(std::move(s));
    FlushOutLocked(src);
  }

  void DeliverLocked(int src, Msg&& m) {
    auto& posted = peers_[src].posted;
    for (auto it = posted.begin(); it != posted.end(); ++it) {
      if ((*it)->tag == m.tag && (*it)->ctx == m.ctx) {
        std::shared_ptr<RecvReq> r = *it;
        posted.erase(it);
        if (m.rv) {
          CompleteRvLocked(src, r, m.tag, m.rv_bytes, m.rv_desc);
        } else {
          CompleteRecv(r.get(), src, m);
        }
        return;
      }
    }
    peers_[src].arrived.push_back(std::move(m));
  }

  void FlushOutLocked(int p) {
    auto& q = peers_[p].outq;
    while (!q.empty()) {
      auto& s = q.front();
      while (s->off < sizeof(WireHeader)) {
        size_t n = links_[p]->WriteSome(
            reinterpret_cast<const char*>(&s->hdr) + s->off,
            sizeof(WireHeader) - s->off);
        if (n == 0) return;  // wire full
        s->off += n;
      }
      const size_t total = sizeof(WireHeader) + s->wire_bytes;
      while (s->off < total) {
        size_t n = links_[p]->WriteSome(
            s->wire_payload + (s->off - sizeof(WireHeader)), total - s->off);
        if (n == 0) return;
        s->off += n;
      }
      if (!s->rv) {
        // Rendezvous sends stay pending (and keep borrowing the user
        // buffer) until the receiver's ACK arrives.
        s->done = true;
        s->payload = nullptr;
      }
      q.pop_front();
    }
  }

  void DrainInLocked(int p) {
    InState& in = peers_[p].in;
    for (;;) {
      if (in.hdr_got < sizeof(WireHeader)) {
        size_t n =
            links_[p]->ReadSome(reinterpret_cast<char*>(&in.hdr) + in.hdr_got,
                                sizeof(WireHeader) - in.hdr_got);
        if (n == 0) return;
        NoteRx(p);
        in.hdr_got += n;
        if (in.hdr_got < sizeof(WireHeader)) return;
        in.payload_got = 0;
        if (in.hdr.magic == kMagicHb) {
          hb_recv_.fetch_add(1, std::memory_order_relaxed);
          in.hdr_got = 0;
          continue;
        }
        if (in.hdr.magic == kMagicRts) {
          in.direct.reset();
          in.payload.resize(sizeof(RvDesc));
        } else if (in.hdr.magic == kMagicAck) {
          in.direct.reset();
          in.payload.resize(sizeof(RvAck));
        } else if (in.hdr.magic == kMagic) {
          // Direct delivery: if a matching recv is already posted, stream
          // the payload straight into its buffer (one memcpy off the wire).
          // Only unexpected messages pay the assembly-buffer copy.
          auto& posted = peers_[p].posted;
          for (auto it = posted.begin(); it != posted.end(); ++it) {
            if ((*it)->tag == in.hdr.tag && (*it)->ctx == in.hdr.ctx) {
              in.direct = *it;
              posted.erase(it);
              break;
            }
          }
          if (in.direct == nullptr) in.payload.resize(in.hdr.bytes);
        } else {
          std::fprintf(stderr, "tpu-acx[%d]: bad wire magic from %d\n", rank_,
                       p);
          _exit(14);
        }
      }
      if (in.direct != nullptr) {
        RecvReq* r = in.direct.get();
        const size_t deliver =
            r->bytes < in.hdr.bytes ? r->bytes : in.hdr.bytes;
        while (in.payload_got < deliver) {
          size_t n = links_[p]->ReadSome(
              static_cast<char*>(r->buf) + in.payload_got,
              deliver - in.payload_got);
          if (n == 0) return;
          NoteRx(p);
          in.payload_got += n;
        }
        // Oversized tail (recv buffer smaller than message): drain + drop.
        while (in.payload_got < in.hdr.bytes) {
          char scratch[4096];
          size_t want = in.hdr.bytes - in.payload_got;
          if (want > sizeof scratch) want = sizeof scratch;
          size_t n = links_[p]->ReadSome(scratch, want);
          if (n == 0) return;
          NoteRx(p);
          in.payload_got += n;
        }
        r->st = Status{
            p, r->report_tag != INT_MIN ? r->report_tag : in.hdr.tag,
            in.hdr.bytes > r->bytes ? kErrTruncate : 0, deliver};
        r->done = true;
        in.direct.reset();
        in.hdr_got = 0;
        continue;
      }
      while (in.payload_got < in.payload.size()) {
        size_t n = links_[p]->ReadSome(in.payload.data() + in.payload_got,
                                       in.payload.size() - in.payload_got);
        if (n == 0) return;
        NoteRx(p);
        in.payload_got += n;
      }
      if (in.hdr.magic == kMagicRts) {
        Msg m;
        m.tag = in.hdr.tag;
        m.ctx = in.hdr.ctx;
        m.rv = true;
        memcpy(&m.rv_desc, in.payload.data(), sizeof m.rv_desc);
        m.rv_bytes = in.hdr.bytes;
        in.payload.clear();
        in.hdr_got = 0;
        DeliverLocked(p, std::move(m));
      } else if (in.hdr.magic == kMagicAck) {
        RvAck a;
        memcpy(&a, in.payload.data(), sizeof a);
        in.payload.clear();
        in.hdr_got = 0;
        HandleAckLocked(p, a);
      } else {
        Msg m;
        m.tag = in.hdr.tag;
        m.ctx = in.hdr.ctx;
        m.payload = std::move(in.payload);
        in.payload.clear();
        in.hdr_got = 0;
        DeliverLocked(p, std::move(m));
      }
    }
  }

  void ProgressLocked() {
    if (hb_interval_ns_ != 0) HeartbeatLocked();
    for (int p = 0; p < size_; p++) {
      if (p == rank_ || !links_[p]) continue;  // no wire (malformed env)
      if (peer_dead_[p]) continue;
      FlushOutLocked(p);
      DrainInLocked(p);
      if (!links_[p]->alive())
        MarkPeerDeadLocked(p, "connection closed", /*hb_detected=*/false);
    }
  }

  // Liveness clock: ANY inbound bytes from p count (a multi-second bulk
  // transfer holds heartbeat frames behind it in the FIFO outq, so payload
  // bytes must refresh the clock or large messages would false-positive).
  void NoteRx(int p) {
    if (hb_interval_ns_ != 0) last_rx_ns_[p] = NowNs();
  }

  void HeartbeatLocked() {
    const uint64_t now = NowNs();
    if (now - last_hb_send_ns_ >= hb_interval_ns_) {
      last_hb_send_ns_ = now;
      for (int p = 0; p < size_; p++) {
        if (p == rank_ || !links_[p] || peer_dead_[p]) continue;
        auto s = std::make_shared<SendReq>();
        s->hdr = WireHeader{kMagicHb, 0, 0, 0};
        s->wire_payload = s->desc;
        s->wire_bytes = 0;
        s->dst = p;
        peers_[p].outq.push_back(std::move(s));
        hb_sent_.fetch_add(1, std::memory_order_relaxed);
      }
    }
    if (now < grace_deadline_ns_) return;
    for (int p = 0; p < size_; p++) {
      if (p == rank_ || !links_[p] || peer_dead_[p]) continue;
      // A peer that never spoke starts its clock at the end of the grace
      // window, not at process start.
      if (last_rx_ns_[p] == 0) last_rx_ns_[p] = now;
      if (now - last_rx_ns_[p] > peer_timeout_ns_)
        MarkPeerDeadLocked(p, "heartbeat timeout", /*hb_detected=*/true);
    }
  }

  // Latch peer p dead and fail everything in flight against it with
  // kErrPeerDead, so every waiter (tickets, barriers, blocking helpers)
  // unblocks in bounded time instead of wedging — the reference's failure
  // mode (SURVEY.md §5.3).
  void MarkPeerDeadLocked(int p, const char* why, bool hb_detected) {
    if (peer_dead_[p]) return;
    peer_dead_[p] = true;
    peers_dead_n_.fetch_add(1, std::memory_order_relaxed);
    ACX_TRACE_EVENT("peer_dead", static_cast<size_t>(p));
    uint64_t failed = 0;
    Peer& peer = peers_[p];
    if (peer.in.direct) {
      RecvReq* r = peer.in.direct.get();
      r->st = Status{p, r->report_tag != INT_MIN ? r->report_tag : r->tag,
                     kErrPeerDead, 0};
      r->done = true;
      peer.in.direct.reset();
      failed++;
    }
    for (auto& r : peer.posted) {
      r->st = Status{p, r->report_tag != INT_MIN ? r->report_tag : r->tag,
                     kErrPeerDead, 0};
      r->done = true;
      failed++;
    }
    peer.posted.clear();
    for (auto& s : peer.outq) {
      if (s->done) continue;
      s->st.error = kErrPeerDead;
      s->st.bytes = 0;
      s->done = true;
      if (s->hdr.magic != kMagicHb && s->hdr.magic != kMagicAck) failed++;
    }
    peer.outq.clear();
    for (auto it = rv_pending_.begin(); it != rv_pending_.end();) {
      if (it->second->dst == p) {
        it->second->st.error = kErrPeerDead;
        it->second->st.bytes = 0;
        it->second->done = true;
        failed++;
        it = rv_pending_.erase(it);
      } else {
        ++it;
      }
    }
    if (failed != 0) failed_ops_.fetch_add(failed, std::memory_order_relaxed);
    // Quiet latch on a clean EOF with nothing in flight: normal teardown
    // can observe a peer's close after the final barrier, and that is not
    // worth a scary message. Loud when real work was killed.
    if (failed != 0 || hb_detected)
      std::fprintf(stderr,
                   "tpu-acx[%d]: peer %d declared dead (%s); failed %llu "
                   "in-flight op(s)\n",
                   rank_, p, why, static_cast<unsigned long long>(failed));
  }

  // Blocking control-plane helpers (used by Barrier/AllreduceInt only).
  void SendB(const void* buf, size_t bytes, int dst, int tag) {
    std::unique_ptr<Ticket> t(Isend(buf, bytes, dst, tag, kCtrlCtx));
    Status st;
    while (!t->Test(&st)) sched_yield();
  }
  void RecvB(void* buf, size_t bytes, int src, int tag) {
    std::unique_ptr<Ticket> t(Irecv(buf, bytes, src, tag, kCtrlCtx));
    Status st;
    while (!t->Test(&st)) sched_yield();
  }

  int rank_, size_;
  std::vector<std::unique_ptr<Link>> links_;
  std::vector<Peer> peers_;
  std::mutex mu_;
  void* shm_base_;
  size_t shm_len_;
  size_t rv_threshold_ = kRvDefaultThreshold;
  bool rv_force_fallback_ = false;
  uint32_t rv_next_seq_ = 1;
  std::unordered_map<uint32_t, std::shared_ptr<SendReq>> rv_pending_;

  // -- resilience state (all guarded by mu_ except the atomic counters) --
  uint64_t hb_interval_ns_ = 0;  // 0 = heartbeats off (EOF detection stays on)
  uint64_t peer_timeout_ns_ = 0;
  uint64_t grace_deadline_ns_ = 0;
  uint64_t last_hb_send_ns_ = 0;
  std::vector<uint64_t> last_rx_ns_;
  std::vector<bool> peer_dead_;
  std::atomic<uint64_t> hb_sent_{0};
  std::atomic<uint64_t> hb_recv_{0};
  std::atomic<uint64_t> peers_dead_n_{0};
  std::atomic<uint64_t> failed_ops_{0};
};

bool SockTicket::Test(Status* st) { return t_->TestReq(send_, recv_, st); }

// -- Partitioned channels -------------------------------------------------
//
// One logical N-partition message per round (reference MPI_Psend_init /
// MPI_Precv_init, partitioned.cu:36-123); each partition travels as an
// independent point-to-point message on (PartTag(tag,p), PartCtx(ctx)), so
// out-of-order Pready works and per-partition arrival is observable — the
// property ring-partitioned.cu's device polling depends on.
//
// Thread-safety contract: Pready/Parrived are called by the proxy while the
// round is in flight; StartRound/FinishRound are called by the app thread
// only when every partition's flag has been observed RESERVED/COMPLETED
// (acquire), which happens-after the proxy's last touch (release) — so no
// extra locking is needed here beyond the transport's own mutex.

class SockPsendChan : public PartitionedChan {
 public:
  SockPsendChan(StreamTransport* t, const void* buf, int parts, size_t pb,
                int dst, int tag, int ctx)
      : t_(t), buf_(static_cast<const char*>(buf)), dst_(dst), tag_(tag),
        ctx_(ctx) {
    partitions = parts;
    part_bytes = pb;
    is_send = true;
    inflight_.reserve(parts);
  }

  void Pready(int p) override {
    inflight_.emplace_back(t_->Isend(buf_ + static_cast<size_t>(p) * part_bytes,
                                     part_bytes, dst_, PartTag(tag_, p),
                                     PartCtx(ctx_)));
  }
  bool Parrived(int) override { return false; }  // send side has no arrivals
  void StartRound() override { inflight_.clear(); }
  void FinishRound(Status* st) override {
    Status tmp;
    for (auto& tk : inflight_) {
      while (!tk->Test(&tmp)) sched_yield();
    }
    if (st) *st = Status{t_->rank(), tag_, 0,
                         part_bytes * static_cast<size_t>(partitions)};
    inflight_.clear();
  }

 private:
  StreamTransport* t_;
  const char* buf_;
  int dst_, tag_, ctx_;
  std::vector<std::unique_ptr<Ticket>> inflight_;
};

class SockPrecvChan : public PartitionedChan {
 public:
  SockPrecvChan(StreamTransport* t, void* buf, int parts, size_t pb, int src,
                int tag, int ctx)
      : t_(t), buf_(static_cast<char*>(buf)), src_(src), tag_(tag), ctx_(ctx),
        tickets_(parts), done_(parts, false) {
    partitions = parts;
    part_bytes = pb;
    is_send = false;
  }

  void Pready(int) override {}
  bool Parrived(int p) override {
    if (done_[p]) return true;
    Status st;
    if (tickets_[p] && tickets_[p]->Test(&st)) {
      done_[p] = true;
      return true;
    }
    return false;
  }
  void StartRound() override {
    for (int p = 0; p < partitions; p++) {
      done_[p] = false;
      tickets_[p].reset(
          t_->Irecv(buf_ + static_cast<size_t>(p) * part_bytes, part_bytes,
                    src_, PartTag(tag_, p), PartCtx(ctx_)));
    }
  }
  void FinishRound(Status* st) override {
    for (int p = 0; p < partitions; p++) {
      while (!Parrived(p)) sched_yield();
      tickets_[p].reset();
    }
    if (st) *st = Status{src_, tag_, 0,
                         part_bytes * static_cast<size_t>(partitions)};
  }

 private:
  StreamTransport* t_;
  char* buf_;
  int src_, tag_, ctx_;
  std::vector<std::unique_ptr<Ticket>> tickets_;
  std::vector<bool> done_;
};

PartitionedChan* StreamTransport::PsendInit(const void* buf, int partitions,
                                            size_t part_bytes, int dst,
                                            int tag, int ctx) {
  return new SockPsendChan(this, buf, partitions, part_bytes, dst, tag, ctx);
}

PartitionedChan* StreamTransport::PrecvInit(void* buf, int partitions,
                                            size_t part_bytes, int src,
                                            int tag, int ctx) {
  return new SockPrecvChan(this, buf, partitions, part_bytes, src, tag, ctx);
}

}  // namespace

Transport* CreateSocketTransport(int rank, int size,
                                 const std::vector<int>& fds) {
  std::vector<std::unique_ptr<Link>> links(size);
  for (int i = 0; i < size; i++) {
    if (i == rank || fds[i] < 0) continue;
    const int fl = fcntl(fds[i], F_GETFL, 0);
    fcntl(fds[i], F_SETFL, fl | O_NONBLOCK);
    links[i] = std::make_unique<SockLink>(fds[i], rank, i);
  }
  return new StreamTransport(rank, size, std::move(links));
}

Transport* CreateShmTransport(int rank, int size, void* base,
                              size_t ring_bytes, size_t owned_len) {
  std::vector<std::unique_ptr<Link>> links(size);
  for (int i = 0; i < size; i++) {
    if (i == rank) continue;
    links[i] = std::make_unique<ShmLink>(static_cast<char*>(base), size,
                                         ring_bytes, rank, i);
  }
  return new StreamTransport(rank, size, std::move(links),
                             owned_len != 0 ? base : nullptr, owned_len);
}

Transport* CreateSelfTransport() {
  // A size-1 StreamTransport is pure loopback: every send routes through
  // DeliverLocked and never touches a wire.
  std::vector<std::unique_ptr<Link>> links(1);
  return new StreamTransport(0, 1, std::move(links));
}

Transport* CreateTransportFromEnv() {
  const char* size_s = getenv("ACX_SIZE");
  const int size = size_s ? atoi(size_s) : 1;
  if (size <= 1) return CreateSelfTransport();
  const char* rank_s = getenv("ACX_RANK");
  if (!rank_s) {
    std::fprintf(stderr,
                 "tpu-acx: ACX_SIZE=%d but ACX_RANK unset (run under acxrun)\n",
                 size);
    exit(13);
  }
  const int rank = atoi(rank_s);

  // Same-host fast path: the memfd segment acxrun created, unless the user
  // forces the socket plane with ACX_TRANSPORT=socket.
  const char* want = getenv("ACX_TRANSPORT");
  const char* shm_fd_s = getenv("ACX_SHM_FD");
  if (want != nullptr && strcmp(want, "shm") == 0 && shm_fd_s == nullptr) {
    // shm requested by name but no segment exists: fail loudly rather than
    // silently running (and benchmarking) the socket plane.
    std::fprintf(stderr,
                 "tpu-acx: ACX_TRANSPORT=shm but no ACX_SHM_FD (launcher "
                 "could not create the shm segment?)\n");
    exit(13);
  }
  if (shm_fd_s != nullptr && (want == nullptr || strcmp(want, "socket") != 0)) {
    const int fd = atoi(shm_fd_s);
    const char* ring_s = getenv("ACX_SHM_RING_BYTES");
    const size_t ring_bytes = ShmSanitizeRingBytes(
        ring_s ? strtoull(ring_s, nullptr, 10) : kShmDefaultRingBytes);
    const size_t len = ShmSegmentBytes(size, ring_bytes);
    void* base = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (base == MAP_FAILED) {
      std::fprintf(stderr, "tpu-acx: mmap of ACX_SHM_FD failed: %s\n",
                   strerror(errno));
      exit(13);
    }
    close(fd);
    return CreateShmTransport(rank, size, base, ring_bytes, len);
  }

  const char* fds_s = getenv("ACX_FDS");
  if (!fds_s) {
    std::fprintf(stderr,
                 "tpu-acx: ACX_SIZE=%d but ACX_FDS unset (run under acxrun)\n",
                 size);
    exit(13);
  }
  std::vector<int> fds;
  const char* s = fds_s;
  while (*s) {
    fds.push_back(atoi(s));
    const char* c = strchr(s, ',');
    if (!c) break;
    s = c + 1;
  }
  if (static_cast<int>(fds.size()) != size) {
    std::fprintf(stderr, "tpu-acx: ACX_FDS has %zu entries, want %d\n",
                 fds.size(), size);
    exit(13);
  }
  return CreateSocketTransport(rank, size, fds);
}

}  // namespace acx
