#include "src/net/wire.h"

namespace acx {
namespace wire {

namespace {

// Software fallback: classic byte-at-a-time table for the reflected
// Castagnoli polynomial. Built once at static-init time.
struct Table {
  uint32_t t[256];
  Table() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : (c >> 1);
      t[i] = c;
    }
  }
};
const Table kTable;

uint32_t SwUpdate(uint32_t state, const unsigned char* p, size_t n) {
  while (n--) state = kTable.t[(state ^ *p++) & 0xFF] ^ (state >> 8);
  return state;
}

#if defined(__x86_64__) || defined(__i386__)
__attribute__((target("sse4.2")))
uint32_t HwUpdate(uint32_t state, const unsigned char* p, size_t n) {
#if defined(__x86_64__)
  uint64_t s64 = state;
  while (n >= 8) {
    uint64_t chunk;
    __builtin_memcpy(&chunk, p, 8);
    s64 = __builtin_ia32_crc32di(s64, chunk);
    p += 8;
    n -= 8;
  }
  state = (uint32_t)s64;
#endif
  while (n--) state = __builtin_ia32_crc32qi(state, *p++);
  return state;
}
#endif

}  // namespace

uint32_t Crc32c(uint32_t crc, const void* data, size_t n) {
  const unsigned char* p = static_cast<const unsigned char*>(data);
  uint32_t state = crc ^ 0xFFFFFFFFu;
#if defined(__x86_64__) || defined(__i386__)
  static const bool hw = __builtin_cpu_supports("sse4.2");
  state = hw ? HwUpdate(state, p, n) : SwUpdate(state, p, n);
#else
  state = SwUpdate(state, p, n);
#endif
  return state ^ 0xFFFFFFFFu;
}

uint32_t Crc32cSw(uint32_t crc, const void* data, size_t n) {
  const unsigned char* p = static_cast<const unsigned char*>(data);
  return SwUpdate(crc ^ 0xFFFFFFFFu, p, n) ^ 0xFFFFFFFFu;
}

}  // namespace wire
}  // namespace acx
