#include "src/net/link_state.h"

#include <errno.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

namespace acx {
namespace link_state {

bool IoFullTimed(int fd, void* buf, size_t n, int timeout_ms, bool wr) {
  char* pbuf = static_cast<char*>(buf);
  size_t got = 0;
  const uint64_t deadline =
      NowNs() + static_cast<uint64_t>(timeout_ms) * 1000000ull;
  while (got < n) {
    const uint64_t now = NowNs();
    if (now >= deadline) return false;
    struct pollfd pf;
    pf.fd = fd;
    pf.events = wr ? POLLOUT : POLLIN;
    pf.revents = 0;
    const int pr =
        poll(&pf, 1, static_cast<int>((deadline - now) / 1000000ull) + 1);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (pr == 0) return false;
    const ssize_t r = wr ? send(fd, pbuf + got, n - got, MSG_NOSIGNAL)
                         : read(fd, pbuf + got, n - got);
    if (r > 0) {
      got += static_cast<size_t>(r);
      continue;
    }
    if (r < 0 && (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK))
      continue;
    return false;  // EOF or hard error
  }
  return true;
}

}  // namespace link_state
}  // namespace acx
