// Wire framing for the stream transport: a fixed 40-byte header carrying a
// per-link epoch, a monotonic per-link sequence number, a CRC32C of the
// payload, and a CRC32C of the header itself. The header CRC catches stream
// desync (a torn read lands mid-frame); the payload CRC catches payload
// corruption; epoch+seq drive the replay/reconnect protocol in
// socket_transport.cc (DESIGN.md §9).
#pragma once

#include <stddef.h>
#include <stdint.h>

namespace acx {
namespace wire {

// Frame classes. The low byte distinguishes them; the upper bytes are a
// transport signature so a desynced stream is overwhelmingly likely to fail
// the magic check even before the header CRC is consulted. The third byte is
// the wire protocol VERSION: v2 (0xAC0C02xx) added the causal span id and tx
// timestamp fields to the header (DESIGN.md §14). A v1 peer's frames still
// pass the signature sieve — KnownLegacyMagic below — so version skew is
// diagnosed loudly at the handshake/stream gate instead of desyncing.
constexpr uint32_t kMagic       = 0xAC0C0201;  // eager copy: header + payload
constexpr uint32_t kMagicRts    = 0xAC0C0202;  // rendezvous RTS: header + RvDesc
constexpr uint32_t kMagicAck    = 0xAC0C0203;  // rendezvous ACK: header + RvAck
constexpr uint32_t kMagicHb     = 0xAC0C0204;  // heartbeat: header only
constexpr uint32_t kMagicSeqAck = 0xAC0C0205;  // cumulative receive ack: header only
constexpr uint32_t kMagicNak    = 0xAC0C0206;  // negative ack / re-pull: header only
constexpr uint32_t kMagicHello  = 0xAC0C0207;  // reconnect/join handshake: header only
constexpr uint32_t kMagicView   = 0xAC0C0208;  // fleet membership view: header only
// Multi-path striping (DESIGN.md §15). A message above ACX_STRIPE_MIN_BYTES
// travels as one kMagicStripe envelope on subflow 0 (it occupies the
// message's slot in the per-(src,tag,ctx) FIFO matching order) plus
// kMagicChunk frames carrying disjoint payload slices round-robin across
// every live subflow. Both are sequenced in their own subflow's seq space.
constexpr uint32_t kMagicStripe = 0xAC0C0209;  // stripe envelope: header + StripeDesc
constexpr uint32_t kMagicChunk  = 0xAC0C020A;  // stripe chunk: header + ChunkHdr + slice

// A frame class from the pre-span 40-byte protocol (v1, 0xAC0C01xx). Never
// accepted — recognized only so the mismatch error can say "old peer"
// instead of "stream desync".
inline bool KnownLegacyMagic(uint32_t m) {
  return (m & 0xFFFFFF00u) == 0xAC0C0100u && (m & 0xFFu) >= 0x01u &&
         (m & 0xFFu) <= 0x08u;
}

// kMagicHello ctx bits. A plain reconnect hello (ctx == 0) resumes the
// existing link incarnation; a JOIN hello announces a FRESH incarnation of
// the rank (a replacement process re-occupying the slot): the acceptor
// resets the peer's wire state instead of resuming it, bumps the fleet
// epoch, and fans the new view out (DESIGN.md §12).
constexpr int32_t kHelloJoin = 0x1;
// A SUBFLOW hello establishes (or resumes after a lane loss) one striped
// subflow of an existing link: bits [8,16) of ctx carry the subflow index
// (>= 1; subflow 0 is the primary link itself and is never dialed this
// way). seq/epoch carry the dialer's per-SUBFLOW rx high-water and epoch
// proposal, exactly like a plain resume hello does for the primary.
constexpr int32_t kHelloSubflow = 0x2;
inline int32_t HelloSubflowCtx(int subflow) {
  return kHelloSubflow | (subflow << 8);
}
inline int HelloSubflowIndex(int32_t ctx) { return (ctx >> 8) & 0xFF; }

#pragma pack(push, 1)
struct WireHeader {
  uint32_t magic;  // frame class, above
  int32_t  tag;    // message tag (kMagicHello: dialer's rank;
                   //   kMagicView: the rank the view update is about)
  int32_t  ctx;    // context id (kCtrlCtx, kRvDataCtx, PartCtx(...);
                   //   kMagicHello: kHelloJoin flags; kMagicView: the
                   //   subject rank's new MemberState)
  uint32_t crc;    // CRC32C of the payload; 0 = unchecked (ACX_CRC=0 / empty)
  uint64_t bytes;  // payload length following the header (kMagicHello with
                   //   kHelloJoin, and kMagicView: sender's fleet epoch —
                   //   hello/view frames are header-only either way)
  uint64_t seq;    // per-link monotonic sequence (kMagicHb: tx high-water;
                   //   kMagicSeqAck/kMagicNak: cumulative rx; kMagicHello:
                   //   sender's rx high-water for resume)
  uint64_t span;   // causal span id of the op this frame serves (DESIGN.md
                   //   §14): origin rank << 48 | slot << 32 | incarnation.
                   //   0 = unspanned (control traffic, protocol internals)
  uint64_t tx_ns;  // sender's trace::NowSinceStartNs() at the moment the
                   //   frame's first byte went on the wire; 0 = unstamped
  uint32_t epoch;  // link incarnation (kMagicHello: proposed/agreed epoch)
  uint32_t hcrc;   // CRC32C of bytes [0, offsetof(hcrc)) of this header
};
#pragma pack(pop)
static_assert(sizeof(WireHeader) == 56, "wire header is part of the protocol");

// Incremental CRC32C (Castagnoli, reflected poly 0x82F63B78). Start with
// crc=0; feeding a buffer in pieces gives the same result as one shot.
// Hardware SSE4.2 path when available, software table otherwise.
uint32_t Crc32c(uint32_t crc, const void* data, size_t n);

// The software table path, always — never dispatches to SSE4.2. Same
// incremental contract and the same answer as Crc32c; exists so tests can
// pin the fallback against the hardware path on hosts where the hardware
// path is what Crc32c actually runs (ctests/test_framing.cc).
uint32_t Crc32cSw(uint32_t crc, const void* data, size_t n);

inline uint32_t HeaderCrc(const WireHeader& h) {
  return Crc32c(0, &h, offsetof(WireHeader, hcrc));
}

// Frames that consume a sequence number and are recorded for replay.
// Control frames (hb/seqack/nak/hello) ride outside the sequence space so
// they can flow while the data stream is stalled or being replayed.
// Stripe envelopes and chunks are sequenced in their OWN subflow's space.
inline bool Sequenced(uint32_t magic) {
  return magic == kMagic || magic == kMagicRts || magic == kMagicAck ||
         magic == kMagicStripe || magic == kMagicChunk;
}

}  // namespace wire
}  // namespace acx
