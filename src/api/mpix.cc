// tpu-acx: the public MPIX_* API (all 17 entry points of reference
// include/mpi-acx.h:48-104), implemented over the atomic FlagTable + Proxy +
// Transport + Stream/Graph runtime.
//
// Layer map (SURVEY.md §1): this file is L3+L4 — the counterpart of
// reference src/sendrecv.cu (enqueued ops, waits, request lifecycle),
// src/partitioned.cu (partitioned init/start/signaling) and the
// MPIX_Init/Finalize halves of src/init.cpp. Deliberate redesigns:
//   * Graph waits observe COMPLETED (reference's graph-path MPIX_Wait_enqueue
//     waits for PENDING — the latent bug at sendrecv.cu:411 — fixed here by
//     construction; Waitall at :548 already did it right).
//   * Graph-owned ops re-fire on every launch; their slot + request are
//     reclaimed through the graph's refcounted cleanup set (the
//     cudaUserObject pattern, sendrecv.cu:106-127) via the proxy's
//     first-class CLEANUP state, so nothing leaks if the graph never ran.
//   * No completion mutex: COMPLETED is published with release ordering and
//     consumers arbitrate COMPLETED->CLEANUP by CAS (reference needed
//     mpiacx_op_completion_mutex, init.cpp:119-141).

#include <sched.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "acx/api_internal.h"
#include "acx/fault.h"
#include "acx/span.h"
#include "acx/debug.h"
#include "acx/flightrec.h"
#include "acx/metrics.h"
#include "acx/trace.h"
#include "acx/tseries.h"
#include "acx/net.h"
#include "acx/runtime.h"
#include "mpi-acx.h"

namespace acx {
namespace {

constexpr int kErr = 1;

// Causal tracing (DESIGN.md §14): every enqueued op gets one process-unique
// incarnation number; span::Make folds it with rank + slot into the 64-bit
// span id that rides the op's wire frames and tags its lifecycle events.
// Starts at 1 so a span is never the reserved 0 ("unspanned").
std::atomic<uint32_t> g_span_incarnation{0};

// Application span bracket (see api_internal.h). Relaxed: the serving layer
// sets it on the thread that enqueues, and a racy read from another
// enqueuer only mislabels the request attribution of one op, never the
// op's own span.
std::atomic<uint64_t> g_app_span{0};

// Spin until the slot reaches `want` (host- and node-side waits). The
// waiting thread drives the progress engine itself (Proxy::TryProgress) so
// completion doesn't require a context switch to the proxy thread; yield
// only when another thread already holds the sweep.
void SpinUntil(FlagTable* t, Proxy* proxy, int idx, int32_t want) {
  while (t->Load(idx) != want) {
    if (proxy == nullptr || !proxy->TryProgress()) sched_yield();
  }
}

Stream* StreamFromQueue(void* queue) {
  // queue is a cudaStream_t* (reference sendrecv.cu dereferences the same
  // way); NULL handle = default stream.
  void* h = *static_cast<void**>(queue);
  return h == nullptr ? Stream::Default() : static_cast<Stream*>(h);
}

// Resolve the void* of MPIX_Pready/Parrived into a request or prequest
// (see include/mpi-acx.h: host passes MPIX_Request*, device-mirror style
// passes the MPIX_Prequest handle itself).
struct Resolved {
  MpixRequest* req = nullptr;
  MpixPrequest* preq = nullptr;
};

Resolved ResolveHandle(void* r) {
  Resolved out;
  if (r == nullptr) return out;
  uint32_t m;
  std::memcpy(&m, r, sizeof m);
  void* h = r;
  if (m != kReqMagic && m != kPreqMagic) {
    h = *static_cast<void**>(r);
    if (h == nullptr) return out;
    std::memcpy(&m, h, sizeof m);
  }
  if (m == kReqMagic) out.req = static_cast<MpixRequest*>(h);
  if (m == kPreqMagic) out.preq = static_cast<MpixPrequest*>(h);
  return out;
}

// Register the graph-lifetime reclaim hook for a graph-owned op: when the
// last of {graph, execs} dies, push the slot to CLEANUP (spinning out any
// in-flight transfer first) and let the proxy free ticket + request.
// The hook re-reads the global state at run time: if MPIX_Finalize already
// tore the table down (graphs may legally outlive finalize), there is
// nothing left to reclaim and the hook is a no-op.
void ArmGraphCleanup(Graph* g, int idx) {
  FlagTable* expect_table = GS().table;
  g->AddCleanup([expect_table, idx] {
    ApiState& g2 = GS();
    // lifecycle_mu makes the liveness check and the reclaim atomic with
    // respect to MPIX_Finalize's teardown (a concurrent finalize would
    // otherwise free the table under us). The spin is lock-free safe: the
    // proxy never takes this mutex, so it keeps making progress.
    std::lock_guard<std::mutex> lk(g2.lifecycle_mu);
    if (g2.table == nullptr || g2.table != expect_table) return;
    int32_t f = g2.table->Load(idx);
    while ((f == kPending || f == kIssued || f == kRecovering) &&
           g2.proxy != nullptr) {
      sched_yield();
      f = g2.table->Load(idx);
    }
    // RESERVED (never launched) or COMPLETED: either way, reclaim.
    g2.table->Store(idx, kCleanup);
    if (g2.proxy != nullptr) g2.proxy->Kick();
  });
}

int EnqueueSendRecv(bool is_send, void* buf, int count, MPI_Datatype datatype,
                    int peer, int tag, MPI_Comm comm, MPIX_Request* request,
                    int qtype, void* queue) {
  ApiState& g = GS();
  if (!g.mpix_inited || request == nullptr || queue == nullptr) return kErr;
  // Error paths hand back MPIX_REQUEST_NULL so a caller that ignores the
  // return code fails loudly on its next MPIX call instead of dereferencing
  // an uninitialized handle.
  *request = MPIX_REQUEST_NULL;

  const int idx = g.table->Allocate();
  if (idx < 0) {
    std::fprintf(stderr, "tpu-acx: flag table exhausted (ACX_NFLAGS=%zu)\n",
                 g.table->size());
    return kErr;
  }
  Op& op = g.table->op(idx);
  op.kind = is_send ? OpKind::kIsend : OpKind::kIrecv;
  if (is_send)
    op.sbuf = buf;
  else
    op.rbuf = buf;
  op.bytes = DatatypeSize(datatype) * static_cast<size_t>(count);
  op.peer = peer;
  op.tag = tag;
  op.ctx = comm;
  op.span = span::Make(
      g.transport->rank(), idx,
      g_span_incarnation.fetch_add(1, std::memory_order_relaxed) + 1);

  auto* req = static_cast<MpixRequest*>(std::calloc(1, sizeof(MpixRequest)));
  req->magic = kReqMagic;
  req->kind = ReqKind::kBasic;
  req->flag_idx = idx;
  op.owner = req;  // proxy frees it at CLEANUP (malloc contract, state.h)

  FlagTable* table = g.table;
  Proxy* proxy = g.proxy;
  const uint64_t op_span = op.span;
  // The trigger: "the queue reached this point". First firing moves
  // RESERVED->PENDING; graph relaunches re-fire COMPLETED->PENDING
  // (reference state doc, mpi-acx-internal.h:176-189).
  auto trigger = [table, proxy, idx, op_span] {
    table->Store(idx, kPending);
    ACX_TRACE_SPAN("trigger_fired", idx, op_span);
    ACX_FLIGHT_SPAN(kTriggerFired, idx, -1, -1, 0, 0, op_span);
    if (metrics::Enabled()) metrics::MarkTrigger(idx);
    // Post the transfer inline if no one else is sweeping (saves the
    // proxy-thread handoff); Kick still wakes a parked proxy to poll the
    // ISSUED op in case no host thread ever waits on it.
    proxy->TryProgress();
    proxy->Kick();
  };

  if (qtype == MPIX_QUEUE_CUDA_STREAM) {
    Stream* s = StreamFromQueue(queue);
    req->graph_owned = s->capturing();
    s->EnqueueInstant(trigger);  // records a node instead when capturing
    if (req->graph_owned) ArmGraphCleanup(s->capture_graph(), idx);
  } else if (qtype == MPIX_QUEUE_CUDA_GRAPH) {
    // Explicit-construction mode: hand back a single-node graph the app
    // composes (reference sendrecv.cu:186-208).
    auto* gr = new Graph();
    gr->AddNode(trigger);
    req->graph_owned = true;
    ArmGraphCleanup(gr, idx);
    *static_cast<void**>(queue) = gr;
  } else {
    table->Free(idx);
    std::free(req);
    return kErr;
  }
  // "req_op" ties this op's span to the application request bracket (if
  // one is open): slot-keyed, span = the app's request id. Offline tools
  // pair it with the enqueue event below (same slot, next in the ring).
  const uint64_t app = AppSpan();
  if (app != 0) ACX_TRACE_SPAN("req_op", idx, app);
  ACX_TRACE_SPAN(is_send ? "isend_enqueue" : "irecv_enqueue", idx, op_span);
  if (is_send)
    ACX_FLIGHT_SPAN(kIsendEnqueue, idx, peer, tag, op.bytes, 0, op_span);
  else
    ACX_FLIGHT_SPAN(kIrecvEnqueue, idx, peer, tag, op.bytes, 0, op_span);
  *request = req;
  return MPI_SUCCESS;
}

// The wait work item: spin to COMPLETED, deliver status, and for
// stream-owned ops advance to CLEANUP (graph-owned ops only observe, so the
// op can re-fire on the next launch).
std::function<void()> MakeWaiter(int idx, MPI_Status* status,
                                 bool graph_owned) {
  FlagTable* table = GS().table;
  Proxy* proxy = GS().proxy;
  return [table, proxy, idx, status, graph_owned] {
    SpinUntil(table, proxy, idx, kCompleted);
    // Safe to read the op here: the slot is COMPLETED and this waiter owns
    // the transition to CLEANUP (graph waiters only observe).
    const uint64_t span = table->op(idx).span;
    ACX_TRACE_SPAN("wait_observed", idx, span);
    ACX_FLIGHT_SPAN(kWaitObserved, idx, -1, -1, 0, 0, span);
    if (metrics::Enabled()) metrics::MarkWait(idx);
    CopyStatus(table->op(idx).status, status);
    if (!graph_owned) {
      table->Store(idx, kCleanup);
      proxy->Kick();
    }
  };
}

int EnqueueWait(MPIX_Request* reqp, MPI_Status* status, int qtype,
                void* queue, Graph* shared_graph) {
  ApiState& g = GS();
  if (!g.mpix_inited || reqp == nullptr) return kErr;
  auto* req = static_cast<MpixRequest*>(*reqp);
  if (req == nullptr || req->kind != ReqKind::kBasic) return kErr;
  const int idx = req->flag_idx;
  const bool graph_owned = req->graph_owned;

  if (qtype == MPIX_QUEUE_CUDA_STREAM) {
    Stream* s = StreamFromQueue(queue);
    if (!s->capturing() && !graph_owned &&
        g.table->Load(idx) == kCompleted) {
      // Fast path (reference try_complete_wait_op, sendrecv.cu:82-104):
      // already complete — consume inline, no queue hop.
      ACX_TRACE_SPAN("wait_observed", idx, g.table->op(idx).span);
      ACX_FLIGHT_SPAN(kWaitObserved, idx, -1, -1, 0, 0,
                      g.table->op(idx).span);
      if (metrics::Enabled()) metrics::MarkWait(idx);
      CopyStatus(g.table->op(idx).status, status);
      g.table->Store(idx, kCleanup);
      g.proxy->Kick();
      *reqp = MPIX_REQUEST_NULL;
      return MPI_SUCCESS;
    }
    // A wait recorded while capturing becomes a graph node and must only
    // OBSERVE completion (a cleanup-consuming node would free the slot on
    // the first launch and hang every relaunch). If the op itself was
    // enqueued pre-capture, the capture graph also takes over reclaim.
    const bool wait_in_graph = graph_owned || s->capturing();
    if (s->capturing() && !graph_owned)
      ArmGraphCleanup(s->capture_graph(), idx);
    s->Enqueue(MakeWaiter(idx, status, wait_in_graph));
  } else if (qtype == MPIX_QUEUE_CUDA_GRAPH) {
    // Graph wait observes COMPLETED — deliberately NOT the reference's
    // buggy PENDING wait (sendrecv.cu:411).
    Graph* gr = shared_graph;
    if (gr == nullptr) {
      gr = new Graph();
      *static_cast<void**>(queue) = gr;
    }
    gr->AddNode(MakeWaiter(idx, status, /*graph_owned=*/true));
  } else {
    return kErr;
  }
  *reqp = MPIX_REQUEST_NULL;
  return MPI_SUCCESS;
}

int HostWaitBasic(MpixRequest* req, MPI_Status* status) {
  ApiState& g = GS();
  const int idx = req->flag_idx;
  if (req->graph_owned) {
    std::fprintf(stderr,
                 "tpu-acx: host MPIX_Wait on a graph-owned op is not "
                 "supported (reference README limitation)\n");
    return kErr;
  }
  SpinUntil(g.table, g.proxy, idx, kCompleted);
  const uint64_t span = g.table->op(idx).span;
  ACX_TRACE_SPAN("wait_observed", idx, span);
  ACX_FLIGHT_SPAN(kWaitObserved, idx, -1, -1, 0, 0, span);
  if (metrics::Enabled()) metrics::MarkWait(idx);
  CopyStatus(g.table->op(idx).status, status);
  g.table->Store(idx, kCleanup);  // proxy frees request + ticket + slot
  g.proxy->Kick();
  return MPI_SUCCESS;
}

// Host wait on a partitioned request: per-partition COMPLETED->RESERVED
// reset for restart, then close the transport round (reference
// sendrecv.cu:607-632).
int HostWaitPartitioned(MpixRequest* req, MPI_Status* status) {
  ApiState& g = GS();
  if (!req->started) {
    // Wait on an inactive persistent request returns immediately with an
    // empty status (MPI persistent-request semantics).
    CopyStatus(Status{}, status);
    return MPI_SUCCESS;
  }
  Status part_err{};
  for (int p = 0; p < req->partitions; p++) {
    SpinUntil(g.table, g.proxy, req->part_idx[p], kCompleted);
    // A partition the proxy failed (arrival deadline, drain) carries its
    // error in the slot status; the transport round below may still close
    // cleanly, so the slot error must win or the caller sees silent
    // short/stale bytes.
    const Status& ps = g.table->op(req->part_idx[p]).status;
    if (ps.error != 0 && part_err.error == 0) part_err = ps;
    g.table->Store(req->part_idx[p], kReserved);
  }
  Status st;
  req->chan->FinishRound(&st);
  if (st.error == 0 && part_err.error != 0) st = part_err;
  CopyStatus(st, status);
  req->started = false;
  return MPI_SUCCESS;
}

int PartitionedInit(bool is_send, void* buf, int partitions, MPI_Count count,
                    MPI_Datatype datatype, int peer, int tag, MPI_Comm comm,
                    MPIX_Request* request) {
  ApiState& g = GS();
  if (!g.mpix_inited || request == nullptr || partitions <= 0) return kErr;
  *request = MPIX_REQUEST_NULL;  // see EnqueueSendRecv
  const size_t part_bytes =
      DatatypeSize(datatype) * static_cast<size_t>(count);

  PartitionedChan* chan =
      is_send ? g.transport->PsendInit(buf, partitions, part_bytes, peer, tag,
                                       comm)
              : g.transport->PrecvInit(buf, partitions, part_bytes, peer, tag,
                                       comm);

  auto* req = static_cast<MpixRequest*>(std::calloc(1, sizeof(MpixRequest)));
  req->magic = kReqMagic;
  req->kind = is_send ? ReqKind::kPsend : ReqKind::kPrecv;
  req->chan = chan;
  req->partitions = partitions;
  req->part_idx =
      static_cast<int*>(std::malloc(sizeof(int) * partitions));
  if (!is_send)
    req->part_seen =
        static_cast<uint8_t*>(std::calloc(partitions, sizeof(uint8_t)));
  // One flag slot per partition (reference partitioned.cu:61-68,105-112).
  for (int p = 0; p < partitions; p++) {
    const int idx = g.table->Allocate();
    if (idx < 0) {
      for (int q = 0; q < p; q++) g.table->Free(req->part_idx[q]);
      std::free(req->part_idx);
      std::free(req->part_seen);
      std::free(req);
      delete chan;
      return kErr;
    }
    Op& op = g.table->op(idx);
    op.kind = is_send ? OpKind::kPready : OpKind::kParrived;
    op.chan = chan;
    op.partition = p;
    // Identity stamps so observability (flight dumps, stall reports) and
    // drain error-typing can attribute the partition slot to its peer.
    op.peer = peer;
    op.tag = tag;
    op.bytes = part_bytes;
    req->part_idx[p] = idx;
  }
  if (trace::Enabled()) {
    for (int p = 0; p < partitions; p++)
      trace::Emit(is_send ? "psend_slot" : "precv_slot", req->part_idx[p]);
  }
  for (int p = 0; p < partitions; p++) {
    if (is_send)
      ACX_FLIGHT(kPsendSlot, req->part_idx[p], peer, tag, part_bytes, p);
    else
      ACX_FLIGHT(kPrecvSlot, req->part_idx[p], peer, tag, part_bytes, p);
  }
  *request = req;
  return MPI_SUCCESS;
}

}  // namespace

void SetAppSpan(uint64_t id) {
  g_app_span.store(id, std::memory_order_relaxed);
}

uint64_t AppSpan() { return g_app_span.load(std::memory_order_relaxed); }

}  // namespace acx

using namespace acx;

extern "C" {

int MPIX_Init(void) {
  ApiState& g = GS();
  if (g.mpix_inited) return kErr;
  // Arm (and validate) any env fault schedule BEFORE the transport dials:
  // a typo'd ACX_FAULT/ACX_CHAOS must abort the rank at init, not be
  // discovered (or worse, silently skipped) mid-run.
  (void)fault::Enabled();
  EnsureTransport();
  // Table size from env; both the tpu-acx and the reference spelling work
  // (reference MPIACX_NFLAGS, init.cpp:205-216; default 4096,
  // mpi-acx-internal.h:141).
  size_t nflags = 4096;
  const char* e = std::getenv("ACX_NFLAGS");
  if (e == nullptr) e = std::getenv("MPIACX_NFLAGS");
  if (e != nullptr) {
    long v = std::atol(e);
    if (v <= 0) {
      std::fprintf(stderr, "tpu-acx: invalid ACX_NFLAGS '%s'\n", e);
      return kErr;
    }
    nflags = static_cast<size_t>(v);
  }
  g.table = new FlagTable(nflags);
  g.proxy = new Proxy(g.table, g.transport);
  g.proxy->Start();
  trace::SetRank(g.transport->rank());
  flight::SetRank(g.transport->rank());
  SetDebugRank(g.transport->rank());
  tseries::SetRank(g.transport->rank());
  // The sampler folds proxy/net/fleet stats into the registry before each
  // sample; the hook keeps src/core free of this layer.
  tseries::SetRefreshHook(&RefreshRuntimeMetrics);
  ACX_FLIGHT(kInit, -1, g.transport->rank(), g.transport->size(), 0, 0);
  g.mpix_inited = true;
  ACX_DLOG("MPIX_Init: rank %d/%d, %zu flag slots", g.transport->rank(),
           g.transport->size(), nflags);
  return MPI_SUCCESS;
}

int MPIX_Finalize(void) {
  ApiState& g = GS();
  // Serialize against graph cleanup hooks (see ArmGraphCleanup).
  std::lock_guard<std::mutex> lk(g.lifecycle_mu);
  if (!g.mpix_inited) return kErr;
  ACX_FLIGHT(kFinalize, -1, g.transport->rank(), g.transport->size(), 0, 0);
  // Leaked-slot diagnostics (reference init.cpp:262-266).
  size_t leaked = 0;
  for (size_t i = 0; i < g.table->size(); i++) {
    const int32_t f = g.table->Load(i);
    if (f != kAvailable && f != kCleanup) {
      if (leaked < 8)
        std::fprintf(stderr, "tpu-acx: finalize: slot %zu leaked in state %s\n",
                     i, FlagName(f));
      leaked++;
    }
  }
  if (leaked > 0)
    std::fprintf(stderr, "tpu-acx: finalize: %zu leaked slot(s)\n", leaked);
  Proxy::Stats st = g.proxy->stats();
  ACX_DLOG("MPIX_Finalize: sweeps=%llu issued=%llu completed=%llu reclaimed=%llu",
           (unsigned long long)st.sweeps, (unsigned long long)st.ops_issued,
           (unsigned long long)st.ops_completed,
           (unsigned long long)st.slots_reclaimed);
  g.proxy->Stop();
  // After Stop: the proxy thread's tail events (final completions and
  // slot reclaims) are in the ring before the file is written.
  trace::Flush(g.transport->rank());
  // Metrics dump while proxy/table/transport are still alive: the
  // refresh folds their cumulative stats into the registry first.
  if (metrics::Enabled()) {
    RefreshRuntimeMetrics();
    metrics::FlushAtFinalize(g.transport->rank());
  }
  // Per-spec fault ledger (ACX_FAULT_REPORT): the chaos oracle's proof
  // that every scheduled fault actually fired (DESIGN.md §16).
  fault::WriteReport(g.transport->rank());
  // Final tseries sample: guarantees the series tail (and, with the init
  // baseline, >= 2 samples) even for runs shorter than one interval. The
  // transport outlives finalize, so the link section stays valid.
  if (tseries::Enabled()) tseries::SampleNow(g.transport);
  delete g.proxy;
  g.proxy = nullptr;
  delete g.table;
  g.table = nullptr;
  g.mpix_inited = false;
  return MPI_SUCCESS;
}

int MPIX_Isend_enqueue(const void* buf, int count, MPI_Datatype datatype,
                       int dest, int tag, MPI_Comm comm, MPIX_Request* request,
                       int qtype, void* queue) {
  return EnqueueSendRecv(true, const_cast<void*>(buf), count, datatype, dest,
                         tag, comm, request, qtype, queue);
}

int MPIX_Irecv_enqueue(void* buf, int count, MPI_Datatype datatype, int source,
                       int tag, MPI_Comm comm, MPIX_Request* request,
                       int qtype, void* queue) {
  return EnqueueSendRecv(false, buf, count, datatype, source, tag, comm,
                         request, qtype, queue);
}

int MPIX_Wait_enqueue(MPIX_Request* req, MPI_Status* status, int qtype,
                      void* queue) {
  return EnqueueWait(req, status, qtype, queue, nullptr);
}

int MPIX_Waitall_enqueue(int count, MPIX_Request* reqs, MPI_Status* statuses,
                         int qtype, void* queue) {
  // One node/work-item per request; for the graph flavor all waits share a
  // single returned graph (reference returns one graph from
  // Waitall_enqueue too, sendrecv.cu:544-566).
  Graph* shared = nullptr;
  if (qtype == MPIX_QUEUE_CUDA_GRAPH) {
    shared = new Graph();
    *static_cast<void**>(queue) = shared;
  }
  for (int i = 0; i < count; i++) {
    MPI_Status* st =
        statuses == MPI_STATUSES_IGNORE ? MPI_STATUS_IGNORE : &statuses[i];
    int rc = EnqueueWait(&reqs[i], st, qtype, queue, shared);
    if (rc != MPI_SUCCESS) return rc;
  }
  return MPI_SUCCESS;
}

int MPIX_Psend_init(const void* buf, int partitions, MPI_Count count,
                    MPI_Datatype datatype, int dest, int tag, MPI_Comm comm,
                    MPI_Info, MPIX_Request* request) {
  return PartitionedInit(true, const_cast<void*>(buf), partitions, count,
                         datatype, dest, tag, comm, request);
}

int MPIX_Precv_init(void* buf, int partitions, MPI_Count count,
                    MPI_Datatype datatype, int source, int tag, MPI_Comm comm,
                    MPI_Info, MPIX_Request* request) {
  return PartitionedInit(false, buf, partitions, count, datatype, source, tag,
                         comm, request);
}

int MPIX_Prequest_create(MPIX_Request request, MPIX_Prequest* prequest) {
  auto* req = static_cast<MpixRequest*>(request);
  if (prequest == nullptr) return kErr;
  *prequest = MPIX_PREQUEST_NULL;
  if (req == nullptr || req->magic != kReqMagic ||
      req->kind == ReqKind::kBasic)
    return kErr;
  auto* preq =
      static_cast<MpixPrequest*>(std::calloc(1, sizeof(MpixPrequest)));
  preq->magic = kPreqMagic;
  preq->kind = req->kind;
  preq->partitions = req->partitions;
  preq->part_idx = req->part_idx;  // borrowed
  preq->part_seen = req->part_seen;  // borrowed
  preq->chan = req->chan;
  *prequest = preq;
  return MPI_SUCCESS;
}

int MPIX_Prequest_free(MPIX_Prequest* request) {
  if (request == nullptr || *request == nullptr) return kErr;
  std::free(*request);
  *request = MPIX_PREQUEST_NULL;
  return MPI_SUCCESS;
}

int MPIX_Start(MPIX_Request* request) {
  ApiState& g = GS();
  auto* req = static_cast<MpixRequest*>(*request);
  if (req == nullptr || req->kind == ReqKind::kBasic || req->started)
    return kErr;
  req->chan->StartRound();
  if (req->kind == ReqKind::kPrecv) {
    // Receive partitions go straight to ISSUED so the proxy polls arrival
    // (reference partitioned.cu:133-136); send partitions stay RESERVED
    // until Pready. Re-arm the watchdog clock while we still own the slot
    // (RESERVED): persistent requests reuse slots across rounds without
    // Free/Reset, and the proxy must never write non-inflight slots.
    for (int p = 0; p < req->partitions; p++) {
      Op& op = g.table->op(req->part_idx[p]);
      op.watch_since_ns = 0;
      op.watch_stage = 0;
      // Arm a FRESH arrival deadline per round. Partition slots are reused
      // across rounds without Reset, so a stale deadline from round k would
      // instantly fail round k+1 — and with no deadline at all an abandoned
      // round (sender died, or healed past it) pins the waiter forever.
      const uint64_t t = Policy().timeout_ns.load(std::memory_order_relaxed);
      op.deadline_ns = t != 0 ? NowNs() + t : 0;
      op.status = Status{};
      if (req->part_seen != nullptr) req->part_seen[p] = 0;
      g.table->Store(req->part_idx[p], kIssued);
    }
    g.proxy->Kick();
  }
  req->started = true;
  return MPI_SUCCESS;
}

int MPIX_Startall(int count, MPIX_Request* request) {
  for (int i = 0; i < count; i++) {
    int rc = MPIX_Start(&request[i]);
    if (rc != MPI_SUCCESS) return rc;
  }
  return MPI_SUCCESS;
}

int MPIX_Wait(MPIX_Request* req, MPI_Status* status) {
  ApiState& g = GS();
  if (!g.mpix_inited || req == nullptr) return kErr;
  auto* r = static_cast<MpixRequest*>(*req);
  if (r == nullptr) return kErr;
  // Cache the kind: HostWaitBasic hands the request to the proxy for
  // freeing, so r must not be dereferenced after it returns.
  const bool basic = r->kind == ReqKind::kBasic;
  int rc = basic ? HostWaitBasic(r, status) : HostWaitPartitioned(r, status);
  if (rc == MPI_SUCCESS && basic)
    *req = MPIX_REQUEST_NULL;  // partitioned requests persist across rounds
  return rc;
}

int MPIX_Waitall(int count, MPIX_Request* reqs, MPI_Status* statuses) {
  for (int i = 0; i < count; i++) {
    MPI_Status* st =
        statuses == MPI_STATUSES_IGNORE ? MPI_STATUS_IGNORE : &statuses[i];
    int rc = MPIX_Wait(&reqs[i], st);
    if (rc != MPI_SUCCESS) return rc;
  }
  return MPI_SUCCESS;
}

int MPIX_Request_free(MPIX_Request* request) {
  // Partitioned-only, like the reference (sendrecv.cu:654-682): basic
  // requests are consumed by their wait.
  ApiState& g = GS();
  auto* req = static_cast<MpixRequest*>(*request);
  if (req == nullptr || req->kind == ReqKind::kBasic) return kErr;
  for (int p = 0; p < req->partitions; p++) g.table->Free(req->part_idx[p]);
  delete req->chan;
  std::free(req->part_idx);
  std::free(req->part_seen);
  std::free(req);
  *request = MPIX_REQUEST_NULL;
  return MPI_SUCCESS;
}

int MPIX_Pready(int partition, void* request) {
  ApiState& g = GS();
  Resolved h = ResolveHandle(request);
  int* part_idx = nullptr;
  int partitions = 0;
  if (h.req != nullptr && h.req->kind == ReqKind::kPsend) {
    part_idx = h.req->part_idx;
    partitions = h.req->partitions;
  } else if (h.preq != nullptr && h.preq->kind == ReqKind::kPsend) {
    part_idx = h.preq->part_idx;
    partitions = h.preq->partitions;
  } else {
    return kErr;
  }
  if (partition < 0 || partition >= partitions) return kErr;
  {
    // Re-arm the watchdog clock before publishing (slot is RESERVED and
    // app-owned here; see MPIX_Start for why the proxy can't do this).
    Op& op = g.table->op(part_idx[partition]);
    op.watch_since_ns = 0;
    op.watch_stage = 0;
  }
  g.table->Store(part_idx[partition], kPending);
  if (metrics::Enabled()) metrics::Add(metrics::kPreadysPublished, 1);
  ACX_TRACE_EVENT("pready_marked", part_idx[partition]);
  {
    const Op& op = g.table->op(part_idx[partition]);
    ACX_FLIGHT(kPreadyMark, part_idx[partition], op.peer, op.tag, 0,
               partition);
  }
  g.proxy->Kick();
  return MPI_SUCCESS;
}

int MPIX_Parrived(void* request, int partition, int* flag) {
  ApiState& g = GS();
  Resolved h = ResolveHandle(request);
  int* part_idx = nullptr;
  uint8_t* seen = nullptr;
  int partitions = 0;
  if (h.req != nullptr && h.req->kind == ReqKind::kPrecv) {
    part_idx = h.req->part_idx;
    seen = h.req->part_seen;
    partitions = h.req->partitions;
  } else if (h.preq != nullptr && h.preq->kind == ReqKind::kPrecv) {
    part_idx = h.preq->part_idx;
    seen = h.preq->part_seen;
    partitions = h.preq->partitions;
  } else {
    return kErr;
  }
  if (partition < 0 || partition >= partitions || flag == nullptr) return kErr;
  *flag = g.table->Load(part_idx[partition]) == kCompleted ? 1 : 0;
  if (*flag != 0 && seen != nullptr && seen[partition] == 0) {
    seen[partition] = 1;
    if (metrics::Enabled()) metrics::Add(metrics::kParrivedsObserved, 1);
  }
  return MPI_SUCCESS;
}

}  // extern "C"
