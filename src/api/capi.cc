// tpu-acx: small C exports beyond the MPIX surface, for the Python ctypes
// bindings (mpi_acx_tpu/runtime.py) — observability the reference lacks
// (SURVEY.md §5.5), plus the device<->proxy flag bridge (SURVEY.md §2 C6):
// the TPU-native counterpart of the reference's host-mapped flag page that
// a running kernel stores into and the proxy polls
// (reference partitioned.cu:200-212 -> init.cpp:82-115).

#include <sched.h>

#include <atomic>
#include <cstdint>

#include "acx/api_internal.h"
#include "acx/fault.h"
#include "acx/flightrec.h"
#include "acx/membership.h"
#include "acx/metrics.h"
#include "acx/trace.h"
#include "acx/tseries.h"

namespace acx {

// Cumulative count of ops cancelled by acx_drain (process lifetime; the
// proxy's own counters don't distinguish drained from completed).
static std::atomic<uint64_t> g_drained{0};

// Fold cumulative runtime stats into the metrics registry. Set (not Add):
// every source here is itself a monotonic cumulative counter, so the
// registry mirrors it instead of re-accumulating.
void RefreshRuntimeMetrics() {
  if (!metrics::Enabled()) return;
  ApiState& g = GS();
  if (g.proxy != nullptr) {
    const Proxy::Stats s = g.proxy->stats();
    metrics::Set(metrics::kProxySweeps, s.sweeps);
    metrics::Set(metrics::kOpsIssued, s.ops_issued);
    metrics::Set(metrics::kOpsCompleted, s.ops_completed);
    metrics::Set(metrics::kSlotsReclaimed, s.slots_reclaimed);
    metrics::Set(metrics::kRetries, s.retries);
    metrics::Set(metrics::kTimeouts, s.timeouts);
  }
  const fault::Stats f = fault::stats();
  metrics::Set(metrics::kFaultsInjected, f.drops + f.delays + f.fails);
  metrics::Set(metrics::kFaultsWire, f.frame_drops + f.frame_corrupts +
                                         f.link_stalls + f.link_closes);
  if (g.transport != nullptr) {
    const NetStats n = g.transport->net_stats();
    metrics::Set(metrics::kHbSent, n.hb_sent);
    metrics::Set(metrics::kHbRecv, n.hb_recv);
    metrics::Set(metrics::kPeersDead, n.peers_dead);
    metrics::Set(metrics::kHbMisses, n.failed_ops);
    metrics::Set(metrics::kReconnects, n.reconnects);
    metrics::Set(metrics::kFramesReplayed, n.replayed_frames);
    metrics::Set(metrics::kCrcRejects, n.crc_rejects);
    metrics::Set(metrics::kNaksSent, n.naks_sent);
  }
  metrics::Set(metrics::kDrainedSlots,
               g_drained.load(std::memory_order_relaxed));
  const FleetStats fs = Fleet().stats();
  metrics::Set(metrics::kFleetEpoch, fs.epoch);
  metrics::Set(metrics::kFleetJoins, fs.joins);
  metrics::Set(metrics::kFleetLeaves, fs.leaves);
  metrics::Set(metrics::kFleetDeaths, fs.deaths);
  if (g.table != nullptr)
    metrics::MaxGauge(metrics::kSlotHighWater, g.table->watermark());
}

}  // namespace acx

extern "C" {

// ---- metrics plane -------------------------------------------------------

// 1 iff ACX_METRICS is set (any non-"0" value).
int acx_metrics_enabled(void) { return acx::metrics::Enabled() ? 1 : 0; }

// Writes the registry snapshot as JSON into buf (NUL-terminated, truncated
// at cap). Returns the full length needed excluding the NUL — call with
// (NULL, 0) to size the buffer. Refreshes runtime-derived counters first.
int acx_metrics_snapshot(char* buf, int cap) {
  acx::RefreshRuntimeMetrics();
  return acx::metrics::SnapshotJson(buf, cap);
}

// Writes the registry in Prometheus text exposition format (0.0.4) into
// buf: every counter/gauge as "acx_<name>" with a TYPE line, histograms
// as cumulative _bucket{le=...}/_sum/_count series. Same sizing contract
// as acx_metrics_snapshot. Refreshes runtime-derived counters first so a
// scrape sees live proxy/fault/transport/fleet state (DESIGN.md §20).
int acx_metrics_prom(char* buf, int cap) {
  acx::RefreshRuntimeMetrics();
  return acx::metrics::PromText(buf, cap);
}

// Nanoseconds on this rank's shared observability timeline
// (trace::NowSinceStartNs) — the clock trace events and tseries samples
// stamp, exported so the Python request-journey log (mpi_acx_tpu/
// reqlog.py) lands on the same per-rank axis and the barrier-anchored
// skew correction of tools/acx_trace_merge.py applies to journeys too.
uint64_t acx_now_since_start_ns(void) {
  return acx::trace::NowSinceStartNs();
}

// Dumps the registry snapshot to `path`. Returns 0 on success.
int acx_metrics_dump_json(const char* path) {
  if (path == nullptr) return 1;
  acx::RefreshRuntimeMetrics();
  return acx::metrics::DumpJson(path);
}

// ---- live telemetry plane (DESIGN.md §13) --------------------------------

// 1 iff ACX_TSERIES sampling is armed (prefix set, interval valid).
int acx_tseries_enabled(void) { return acx::tseries::Enabled() ? 1 : 0; }

// Take a sample immediately (outside the periodic cadence) so a subsequent
// acx_tseries_live_json reads fresh state. Returns the total samples
// written, or -1 when sampling is disabled.
int acx_tseries_sample_now(void) {
  if (!acx::tseries::Enabled()) return -1;
  acx::RefreshRuntimeMetrics();
  acx::tseries::SampleNow(acx::GS().transport);
  return static_cast<int>(acx::tseries::SamplesWritten());
}

// Copies the most recent sample line (one JSON object, same schema as the
// .tseries.jsonl rows) into buf. Sizing contract of acx_metrics_snapshot;
// returns 0 when no sample exists yet.
int acx_tseries_live_json(char* buf, int cap) {
  return acx::tseries::LiveJson(buf, cap);
}

// Attach an application JSON fragment (a complete object, <= 8 KiB) to
// subsequent samples under "app" — the serving layer publishes rolling
// TTFT/ITL percentiles and queue depth this way. Invalid input is ignored.
void acx_tseries_annotate(const char* json) { acx::tseries::Annotate(json); }

// Fold the serving layer's paged-KV pool stats into the registry
// (models/kvpage.py publishes once per scheduler iteration). pages_free
// and pages_shared are gauges (absolute pool occupancy right now);
// prefix_hits / prefix_evictions / preemptions arrive as host-side
// CUMULATIVE values, so Set (not Add) mirrors them — the same fold
// convention RefreshRuntimeMetrics uses for proxy stats.
void acx_serving_page_stats(uint64_t pages_free, uint64_t pages_shared,
                            uint64_t prefix_hits, uint64_t prefix_evictions,
                            uint64_t preemptions) {
  if (!acx::metrics::Enabled()) return;
  acx::metrics::Set(acx::metrics::kPagesFree, pages_free);
  acx::metrics::Set(acx::metrics::kPagesShared, pages_shared);
  acx::metrics::Set(acx::metrics::kPrefixHits, prefix_hits);
  acx::metrics::Set(acx::metrics::kPrefixEvictions, prefix_evictions);
  acx::metrics::Set(acx::metrics::kPreemptions, preemptions);
}

// Fills out[4] = {sweeps, ops_issued, ops_completed, slots_reclaimed}.
void acx_proxy_stats(uint64_t* out) {
  acx::ApiState& g = acx::GS();
  if (g.proxy == nullptr) {
    out[0] = out[1] = out[2] = out[3] = 0;
    return;
  }
  acx::Proxy::Stats s = g.proxy->stats();
  out[0] = s.sweeps;
  out[1] = s.ops_issued;
  out[2] = s.ops_completed;
  out[3] = s.slots_reclaimed;
}

// ---- resilience plane ----------------------------------------------------

// Fills out[8] = {retries, timeouts, fault_drops, fault_delays, fault_fails,
// hb_sent, hb_recv, peers_dead}. Safe before init (zeros).
void acx_resilience_stats(uint64_t* out) {
  acx::ApiState& g = acx::GS();
  if (g.proxy != nullptr) {
    acx::Proxy::Stats s = g.proxy->stats();
    out[0] = s.retries;
    out[1] = s.timeouts;
  } else {
    out[0] = out[1] = 0;
  }
  acx::fault::Stats f = acx::fault::stats();
  out[2] = f.drops;
  out[3] = f.delays;
  out[4] = f.fails;
  if (g.transport != nullptr) {
    acx::NetStats n = g.transport->net_stats();
    out[5] = n.hb_sent;
    out[6] = n.hb_recv;
    out[7] = n.peers_dead;
  } else {
    out[5] = out[6] = out[7] = 0;
  }
}

// Fills out[7] = {reconnects, replayed_frames, crc_rejects, naks_sent,
// drained_slots, links_recovering, replay_broken_links} — the
// survivable-link counters (DESIGN.md §9). replay_broken_links is the
// early-warning gauge: links still moving data whose replay buffer evicted
// an unacked frame, so their NEXT loss is terminal. Safe before init
// (zeros).
void acx_recovery_stats(uint64_t* out) {
  acx::ApiState& g = acx::GS();
  if (g.transport != nullptr) {
    acx::NetStats n = g.transport->net_stats();
    out[0] = n.reconnects;
    out[1] = n.replayed_frames;
    out[2] = n.crc_rejects;
    out[3] = n.naks_sent;
    out[5] = n.links_recovering;
    out[6] = n.replay_broken_links;
  } else {
    out[0] = out[1] = out[2] = out[3] = out[5] = out[6] = 0;
  }
  out[4] = acx::g_drained.load(std::memory_order_relaxed);
}

// Graceful drain (DESIGN.md §9): give everything in flight — including ops
// parked on a reconnecting link — `timeout_ms` to finish under caller-driven
// progress, then cancel the stragglers with typed errors (kErrPeerDead when
// the op's peer is unhealthy, kErrTimeout otherwise). Returns the number of
// ops cancelled (0 = everything finished), or -1 before MPIX_Init. Waiters
// on cancelled requests unblock immediately with the op's error status.
int acx_drain(double timeout_ms) {
  acx::ApiState& g = acx::GS();
  if (g.table == nullptr || g.proxy == nullptr) return -1;
  const uint64_t deadline =
      acx::NowNs() +
      static_cast<uint64_t>(timeout_ms < 0 ? 0 : timeout_ms * 1e6);
  const auto any_inflight = [&g] {
    const size_t n = g.table->watermark();
    for (size_t i = 0; i < n; i++) {
      const int32_t f = g.table->Load(i);
      if (f == acx::kPending || f == acx::kIssued || f == acx::kRecovering)
        return true;
    }
    return false;
  };
  while (any_inflight() && acx::NowNs() < deadline) {
    if (!g.proxy->TryProgress()) sched_yield();
  }
  const int n = g.proxy->CancelInflight();
  if (n > 0)
    acx::g_drained.fetch_add(static_cast<uint64_t>(n),
                             std::memory_order_relaxed);
  return n;
}

int MPIX_Drain(double timeout_ms) { return acx_drain(timeout_ms); }

// ---- fleet membership (DESIGN.md §12) ------------------------------------

// Current fleet epoch: starts at 1 when the transport comes up, bumps on
// every membership transition (join / leave / death), max-merges with peer
// views. Safe before init (0: no fleet yet).
uint64_t MPIX_Fleet_epoch(void) { return acx::Fleet().epoch(); }

// Copies up to `cap` per-rank MemberState values (acx/membership.h: 2 =
// ACTIVE, 3 = DRAINING, 4 = LEFT, 5 = DEAD) into `states` and returns the
// fleet size — call with (NULL, 0) to size the buffer. 0 before init.
int MPIX_Fleet_view(int32_t* states, int cap) {
  return acx::Fleet().View(states, states == nullptr ? 0 : cap);
}

// Graceful departure: mark self DRAINING, give in-flight work `timeout_ms`
// to finish (acx_drain), then announce LEFT to every peer and surrender the
// rendezvous listener so a replacement can take the slot. Returns the
// number of ops the drain had to cancel (0 = clean), or -1 before init.
// The process may keep the library loaded afterwards (e.g. a supervisor
// parent waiting on the replacement it forked) but must not post new ops.
int MPIX_Fleet_leave(double timeout_ms) {
  acx::ApiState& g = acx::GS();
  if (g.transport == nullptr) return -1;
  acx::Fleet().OnDraining(g.transport->rank());
  const int cancelled = acx_drain(timeout_ms);
  g.transport->FleetLeave();
  return cancelled < 0 ? 0 : cancelled;
}

// Fills out[5] = {epoch, joins, leaves, deaths, active}. Safe before init
// (all zeros — a fleet of size 0).
void acx_fleet_stats(uint64_t* out) {
  const acx::FleetStats s = acx::Fleet().stats();
  out[0] = s.epoch;
  out[1] = s.joins;
  out[2] = s.leaves;
  out[3] = s.deaths;
  out[4] = s.active;
}

// ---- causal tracing (DESIGN.md §14) --------------------------------------

// Open an application span bracket: every MPIX op enqueued on any thread
// until the matching acx_span_app_end() emits a "req_op" trace event tying
// its native causal span to this request id, so offline tools can split a
// request's latency into queue vs compute vs wire. Nesting is not
// supported — the latest begin wins. `id` must be nonzero (0 is reserved
// for "no bracket open").
void acx_span_app_begin(uint64_t id) { acx::SetAppSpan(id); }

// Close the application span bracket opened by acx_span_app_begin.
void acx_span_app_end(void) { acx::SetAppSpan(0); }

// ---- flight recorder -----------------------------------------------------

// Writes this rank's flight dump to <prefix>.rank<r>.flight.json. A NULL
// (or empty) prefix falls back to $ACX_FLIGHT, then "acx". Returns 0 on
// success, -1 if the file could not be written. Safe at any time, including
// before MPIX_Init (the dump just has empty slot/peer sections).
int acx_flight_dump(const char* prefix) {
  return acx::flight::Dump(prefix, "explicit");
}

// Fills out[5] = {recorded, capacity, stall_warns, hang_dumps,
// dumps_written}. `recorded` is the lifetime event count (may exceed
// `capacity`, the ring size; capacity == 0 means the recorder is disabled
// via ACX_FLIGHT_EVENTS=0).
void acx_flight_stats(uint64_t* out) {
  const acx::flight::Stats s = acx::flight::stats();
  out[0] = s.recorded;
  out[1] = s.capacity;
  out[2] = s.stall_warns;
  out[3] = s.hang_dumps;
  out[4] = s.dumps_written;
}

// MPIX-surface alias: dump runtime state (currently the flight recording)
// for post-mortem analysis by tools/acx_doctor.py.
int MPIX_Dump_state(void) { return acx_flight_dump(nullptr); }

int MPIX_Set_deadline(double timeout_ms) {
  if (timeout_ms < 0) return 1;
  acx::Policy().timeout_ns.store(
      static_cast<uint64_t>(timeout_ms * 1e6), std::memory_order_relaxed);
  return 0;
}

int MPIX_Get_deadline(double* timeout_ms) {
  if (timeout_ms == nullptr) return 1;
  *timeout_ms =
      static_cast<double>(
          acx::Policy().timeout_ns.load(std::memory_order_relaxed)) /
      1e6;
  return 0;
}

int MPIX_Op_status(void* request, int* state, int* error, int* attempts) {
  auto* req = static_cast<acx::MpixRequest*>(request);
  acx::ApiState& g = acx::GS();
  if (req == nullptr || req->magic != acx::kReqMagic || g.table == nullptr)
    return 1;
  const auto probe = [&g](int idx, int* st, int* err, int* att) {
    *st = static_cast<int>(g.table->Load(idx));
    const acx::Op& op = g.table->op(idx);
    // The op's status is only coherent once the proxy's release store of
    // COMPLETED has been acquired (same contract as the wait paths).
    *err = *st >= acx::kCompleted ? op.status.error : 0;
    *att = static_cast<int>(op.attempts);
  };
  int st = 0, err = 0, att = 0;
  if (req->kind == acx::ReqKind::kBasic) {
    if (req->flag_idx < 0) return 1;
    probe(req->flag_idx, &st, &err, &att);
  } else {
    if (req->partitions <= 0 || req->part_idx == nullptr) return 1;
    st = acx::kCleanup;
    for (int p = 0; p < req->partitions; p++) {
      int pst = 0, perr = 0, patt = 0;
      probe(req->part_idx[p], &pst, &perr, &patt);
      if (pst < st) st = pst;
      if (err == 0 && perr != 0) err = perr;
      if (patt > att) att = patt;
    }
  }
  if (state != nullptr) *state = st;
  if (error != nullptr) *error = err;
  if (attempts != nullptr) *attempts = att;
  return 0;
}

int acx_rank(void) {
  acx::EnsureTransport();
  return acx::GS().transport->rank();
}

int acx_size(void) {
  acx::EnsureTransport();
  return acx::GS().transport->size();
}

uint64_t acx_nflags(void) {
  acx::ApiState& g = acx::GS();
  return g.table == nullptr ? 0 : g.table->size();
}

// ---- device<->proxy flag bridge -----------------------------------------
//
// On the reference, a running CUDA kernel writes PENDING directly into the
// host-mapped flag word the proxy busy-polls (partitioned.cu:204). A TPU
// kernel cannot dereference host memory, so the TPU-native path is: the
// Pallas pready kernel mutates an HBM flag buffer using the SAME protocol
// constants (mpi_acx_tpu/ops/flags.py), and the Python layer hands that
// buffer's words here to be mirrored into the proxy-polled native table.

// Device->host direction. For each i whose device-side word is PENDING,
// CAS the native slot RESERVED->PENDING (exactly what host MPIX_Pready
// publishes, mpix.cc) — the CAS makes re-mirroring the same buffer
// idempotent and never regresses ISSUED/COMPLETED slots. Kicks the proxy
// once if anything was published. Returns the publish count, or -1 before
// MPIX_Init.
int acx_flags_publish(const int64_t* slots, const int32_t* vals, int n) {
  acx::ApiState& g = acx::GS();
  if (g.table == nullptr || g.proxy == nullptr) return -1;
  std::atomic<int32_t>* raw = g.table->raw();
  const int64_t nflags = static_cast<int64_t>(g.table->size());
  int published = 0;
  for (int i = 0; i < n; i++) {
    if (vals[i] != acx::kPending) continue;
    if (slots[i] < 0 || slots[i] >= nflags) return -1;
    int32_t expect = acx::kReserved;
    if (raw[slots[i]].compare_exchange_strong(expect, acx::kPending,
                                              std::memory_order_acq_rel,
                                              std::memory_order_acquire))
      published++;
  }
  if (published > 0) g.proxy->Kick();
  return published;
}

// Host->device direction: snapshot native flag words (e.g. COMPLETED set
// by the proxy after a partition arrived, init.cpp:104-115 in the
// reference) so the Python layer can lift them into the device flag
// buffer a Pallas parrived kernel polls. Returns 0, or -1 before init /
// on a bad slot.
int acx_flags_fetch(const int64_t* slots, int32_t* out, int n) {
  acx::ApiState& g = acx::GS();
  if (g.table == nullptr) return -1;
  std::atomic<int32_t>* raw = g.table->raw();
  const int64_t nflags = static_cast<int64_t>(g.table->size());
  for (int i = 0; i < n; i++) {
    if (slots[i] < 0 || slots[i] >= nflags) return -1;
    out[i] = raw[slots[i]].load(std::memory_order_acquire);
  }
  return 0;
}

// Partition -> native-slot mapping of a partitioned request: what the
// reference's MPIX_Prequest_create copies into the device mirror
// (partitioned.cu:167-184). Returns the partition count (writing up to
// `cap` entries), or -1 for a non-partitioned/invalid handle.
int acx_request_partition_slots(void* request, int64_t* out, int cap) {
  auto* req = static_cast<acx::MpixRequest*>(request);
  if (req == nullptr || req->magic != acx::kReqMagic ||
      req->kind == acx::ReqKind::kBasic)
    return -1;
  for (int p = 0; p < req->partitions && p < cap; p++) out[p] = req->part_idx[p];
  return req->partitions;
}

}  // extern "C"
