// tpu-acx: small C exports beyond the MPIX surface, for the Python ctypes
// bindings (mpi_acx_tpu/runtime.py) — observability the reference lacks
// (SURVEY.md §5.5).

#include <cstdint>

#include "acx/api_internal.h"

extern "C" {

// Fills out[4] = {sweeps, ops_issued, ops_completed, slots_reclaimed}.
void acx_proxy_stats(uint64_t* out) {
  acx::ApiState& g = acx::GS();
  if (g.proxy == nullptr) {
    out[0] = out[1] = out[2] = out[3] = 0;
    return;
  }
  acx::Proxy::Stats s = g.proxy->stats();
  out[0] = s.sweeps;
  out[1] = s.ops_issued;
  out[2] = s.ops_completed;
  out[3] = s.slots_reclaimed;
}

int acx_rank(void) {
  acx::EnsureTransport();
  return acx::GS().transport->rank();
}

int acx_size(void) {
  acx::EnsureTransport();
  return acx::GS().transport->size();
}

uint64_t acx_nflags(void) {
  acx::ApiState& g = acx::GS();
  return g.table == nullptr ? 0 : g.table->size();
}

}  // extern "C"
