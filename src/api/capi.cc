// tpu-acx: small C exports beyond the MPIX surface, for the Python ctypes
// bindings (mpi_acx_tpu/runtime.py) — observability the reference lacks
// (SURVEY.md §5.5), plus the device<->proxy flag bridge (SURVEY.md §2 C6):
// the TPU-native counterpart of the reference's host-mapped flag page that
// a running kernel stores into and the proxy polls
// (reference partitioned.cu:200-212 -> init.cpp:82-115).

#include <atomic>
#include <cstdint>

#include "acx/api_internal.h"

extern "C" {

// Fills out[4] = {sweeps, ops_issued, ops_completed, slots_reclaimed}.
void acx_proxy_stats(uint64_t* out) {
  acx::ApiState& g = acx::GS();
  if (g.proxy == nullptr) {
    out[0] = out[1] = out[2] = out[3] = 0;
    return;
  }
  acx::Proxy::Stats s = g.proxy->stats();
  out[0] = s.sweeps;
  out[1] = s.ops_issued;
  out[2] = s.ops_completed;
  out[3] = s.slots_reclaimed;
}

int acx_rank(void) {
  acx::EnsureTransport();
  return acx::GS().transport->rank();
}

int acx_size(void) {
  acx::EnsureTransport();
  return acx::GS().transport->size();
}

uint64_t acx_nflags(void) {
  acx::ApiState& g = acx::GS();
  return g.table == nullptr ? 0 : g.table->size();
}

// ---- device<->proxy flag bridge -----------------------------------------
//
// On the reference, a running CUDA kernel writes PENDING directly into the
// host-mapped flag word the proxy busy-polls (partitioned.cu:204). A TPU
// kernel cannot dereference host memory, so the TPU-native path is: the
// Pallas pready kernel mutates an HBM flag buffer using the SAME protocol
// constants (mpi_acx_tpu/ops/flags.py), and the Python layer hands that
// buffer's words here to be mirrored into the proxy-polled native table.

// Device->host direction. For each i whose device-side word is PENDING,
// CAS the native slot RESERVED->PENDING (exactly what host MPIX_Pready
// publishes, mpix.cc) — the CAS makes re-mirroring the same buffer
// idempotent and never regresses ISSUED/COMPLETED slots. Kicks the proxy
// once if anything was published. Returns the publish count, or -1 before
// MPIX_Init.
int acx_flags_publish(const int64_t* slots, const int32_t* vals, int n) {
  acx::ApiState& g = acx::GS();
  if (g.table == nullptr || g.proxy == nullptr) return -1;
  std::atomic<int32_t>* raw = g.table->raw();
  const int64_t nflags = static_cast<int64_t>(g.table->size());
  int published = 0;
  for (int i = 0; i < n; i++) {
    if (vals[i] != acx::kPending) continue;
    if (slots[i] < 0 || slots[i] >= nflags) return -1;
    int32_t expect = acx::kReserved;
    if (raw[slots[i]].compare_exchange_strong(expect, acx::kPending,
                                              std::memory_order_acq_rel,
                                              std::memory_order_acquire))
      published++;
  }
  if (published > 0) g.proxy->Kick();
  return published;
}

// Host->device direction: snapshot native flag words (e.g. COMPLETED set
// by the proxy after a partition arrived, init.cpp:104-115 in the
// reference) so the Python layer can lift them into the device flag
// buffer a Pallas parrived kernel polls. Returns 0, or -1 before init /
// on a bad slot.
int acx_flags_fetch(const int64_t* slots, int32_t* out, int n) {
  acx::ApiState& g = acx::GS();
  if (g.table == nullptr) return -1;
  std::atomic<int32_t>* raw = g.table->raw();
  const int64_t nflags = static_cast<int64_t>(g.table->size());
  for (int i = 0; i < n; i++) {
    if (slots[i] < 0 || slots[i] >= nflags) return -1;
    out[i] = raw[slots[i]].load(std::memory_order_acquire);
  }
  return 0;
}

// Partition -> native-slot mapping of a partitioned request: what the
// reference's MPIX_Prequest_create copies into the device mirror
// (partitioned.cu:167-184). Returns the partition count (writing up to
// `cap` entries), or -1 for a non-partitioned/invalid handle.
int acx_request_partition_slots(void* request, int64_t* out, int cap) {
  auto* req = static_cast<acx::MpixRequest*>(request);
  if (req == nullptr || req->magic != acx::kReqMagic ||
      req->kind == acx::ReqKind::kBasic)
    return -1;
  for (int p = 0; p < req->partitions && p < cap; p++) out[p] = req->part_idx[p];
  return req->partitions;
}

}  // extern "C"
