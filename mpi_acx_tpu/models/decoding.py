"""Shared greedy-decode scaffold for the model families.

Both transformer.generate and llama.generate are this loop closed over
their own prefill/decode_step; keeping the scaffold in one place keeps
the max_seq position-clamp guard and the scan wiring from drifting.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax


def greedy_generate(prefill_fn: Callable, decode_fn: Callable,
                    prompt, n_new: int, max_seq: int,
                    max_len: Optional[int] = None):
    """prompt [B, S] -> [B, S + n_new] by greedy argmax.

    prefill_fn(tokens, max_len, last_only) -> (logits [B, *, vocab], cache)
    decode_fn(cache, token [B]) -> (logits [B, vocab], cache)
    """
    B, S = prompt.shape
    if max_len is None:
        max_len = S + n_new
    assert S + n_new <= max_len, (S, n_new, max_len)
    # The position table/rope ceiling is hard: past it, position lookups
    # clamp silently and every token reuses the last row.
    assert S + n_new <= max_seq, (S, n_new, max_seq)
    logits, cache = prefill_fn(prompt, max_len, True)
    first = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)

    def step(carry, _):
        cache, tok = carry
        logits, cache = decode_fn(cache, tok)
        nxt = jnp.argmax(logits, axis=-1).astype(tok.dtype)
        return (cache, nxt), tok

    (_, _), toks = lax.scan(step, (cache, first), None, length=n_new)
    return jnp.concatenate([prompt, jnp.moveaxis(toks, 0, 1)], axis=1)
