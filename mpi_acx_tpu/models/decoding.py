"""Shared decode scaffolds for the model families.

Both transformer.generate and llama.generate are this loop closed over
their own prefill/decode_step; keeping the scaffold in one place keeps
the max_seq position-clamp guard and the scan wiring from drifting.
``sample_generate`` is the stochastic sibling (temperature / top-k /
top-p nucleus), all inside one ``lax.scan`` — fixed shapes, one compile.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def grouped_decode_attend(q, kc, vc, pos, max_len, n_rep, flash=None):
    """W-token grouped-query attention against an UN-REPEATED KV cache:
    q [B, W, Hq, D] occupying positions pos..pos+W-1, kc/vc
    [B, max_len, Hkv, D] with Hq = Hkv*n_rep -> o [B, W, Hq*D]. Query
    head g*n_rep + r reads K/V group g directly — no [B, L, Hq, D]
    materialization, preserving GQA's cache-bandwidth win; window row w
    attends cache entries <= pos+w. With n_rep=1 this IS plain
    multi-head attention and with W=1 the ordinary decode step, so every
    decode path — the three families' steps, the tensor-parallel loops,
    and the speculative window passes — shares this single definition of
    the scale/mask/softmax math.

    ``flash`` is the ``decode_flash`` config knob, dispatched through
    :func:`mpi_acx_tpu.ops.flash_decode.select_decode_attend` (the
    ``select_attention`` idiom): ``None`` -> auto (the length-aware
    Pallas decode kernel on TPU when max_len is big and 128-divisible,
    dense otherwise), ``True`` -> always the kernel (interpret mode off
    TPU, so CPU tests run the same code path), ``False`` -> the dense
    reference below."""
    from mpi_acx_tpu.ops.flash_decode import select_decode_attend

    return select_decode_attend(flash)(q, kc, vc, pos, max_len, n_rep)


def dense_decode_attend(q, kc, vc, pos, max_len, n_rep):
    """Dense-einsum reference for :func:`grouped_decode_attend` — reads
    the whole [B, max_len, Hkv, D] cache every step (the flash kernel's
    parity ground truth; also the dispatch target below the kernel's
    crossover and on non-TPU backends).

    ``kc``/``vc`` may each be an ``(int8 codes, f32 scales [B, max_len,
    Hkv, 1])`` tuple (ops/kvquant.py layout). The per-position scales
    are then applied to the SMALL tensors — K's to the logits, V's to
    the probabilities — never to the cache itself: the r05 chip A/B
    showed the obvious dequantize-then-attend path at 0.73x the bf16
    baseline because XLA materializes the dequantized [B, max_len, H,
    D] tensor in HBM (int8 read + bf16 write + bf16 read — MORE
    traffic than the bf16 cache the codes were meant to halve). With
    the factoring, the full-cache operands stay int8 end-to-end.
    Algebraically identical: sum_d q_d*(K_kd*s_k) == (sum_d q_d*K_kd)
    * s_k, and the f32 logits/probs multiply is if anything MORE
    precise than rounding each dequantized element to bf16."""
    ks = vs = None
    if isinstance(kc, tuple):
        kc, ks = kc
    if isinstance(vc, tuple):
        vc, vs = vc
    B, W = q.shape[:2]
    Hkv, Dh = kc.shape[2], kc.shape[3]
    qg = q.reshape(B, W, Hkv, n_rep, Dh)
    # Pre-scale q by 1/sqrt(Dh) (W*Hq*Dh elements) instead of dividing
    # the [B, g, r, W, max_len] f32 logits — same trick as _flash_kernel.
    qg = (qg.astype(jnp.float32) * (1.0 / Dh ** 0.5)).astype(q.dtype)
    kin = kc if ks is None else kc.astype(q.dtype)  # int8 exact in bf16
    logits = jnp.einsum("bqgrd,bkgd->bgrqk", qg, kin).astype(jnp.float32)
    if ks is not None:
        # [B, max_len, Hkv, 1] -> [B, g, 1, 1, k] against bgrqk.
        logits = logits * ks[..., 0].transpose(0, 2, 1)[:, :, None, None]
    pos = jnp.asarray(pos)
    if pos.ndim == 0:
        rows = pos + jnp.arange(W)[:, None]            # [W, 1]
        cols = jnp.arange(max_len)[None, :]            # [1, max_len]
        mask = (cols <= rows)[None, None, None]        # [1,1,1,W,max_len]
    else:
        # Per-slot positions (continuous-batching serving): slot b's
        # window row w attends cache entries <= pos[b] + w.
        rows = pos[:, None, None] + jnp.arange(W)[None, :, None]
        cols = jnp.arange(max_len)[None, None, :]
        mask = (cols <= rows)[:, None, None]       # [B,1,1,W,max_len]
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(logits, axis=-1)
    if vs is not None:
        p = p * vs[..., 0].transpose(0, 2, 1)[:, :, None, None]
    p = p.astype(q.dtype)
    vin = vc if vs is None else vc.astype(q.dtype)
    return jnp.einsum("bgrqk,bkgd->bqgrd", p, vin).reshape(
        B, W, Hkv * n_rep * Dh)


def decode_layer_scan(layers, x, kc_all, vc_all, pos, qkv_fn, attend_fn,
                      ksc_all=None, vsc_all=None):
    """The carry-scan decode layer loop shared by every decode path
    (transformer/llama decode_step, the TP generation loop).

    The KV cache rides the scan's CARRY with ONE in-place
    dynamic_update_slice per layer. Passing it as scan xs/ys instead (the
    obvious structure) makes XLA re-materialize the whole
    [L, B, max_len, H, D] buffer every step — measured 1.9x slower
    end-to-end GPT-2 decode on v5e (the copies, not attention math,
    dominated).

    qkv_fn(lp, x, pos) -> (q, k [B,1,H,D], v); attend_fn(lp, x, q, kc_l,
    vc_l, pos) -> x consumes the layer's UPDATED cache slices. Returns
    (x, kc_all, vc_all). ``pos`` may be a scalar (every row at the same
    position — the generate paths) or [B] (each slot at its own
    position — continuous-batching serving, models/serving.py), in
    which case the cache writes vmap per slot.

    With ``ksc_all``/``vsc_all`` ([L, B, max_len, H, 1] f32) the cache
    is INT8 (ops/kvquant.py): the fresh K/V vectors are quantized on
    write, the scale buffers ride the carry beside the code buffers,
    and attend_fn receives ``(codes, scales)`` tuples that
    :func:`grouped_decode_attend` consumes without ever materializing
    a dequantized cache (scale-on-scores factoring — see its
    docstring for the r05 chip A/B that killed the dequant-first
    design). Returns (x, kc, vc, ksc, vsc) then.
    """
    from mpi_acx_tpu.ops.kvquant import kv_quant

    n_layers = jax.tree.leaves(layers)[0].shape[0]
    quant = ksc_all is not None
    pos = jnp.asarray(pos)
    slotwise = pos.ndim == 1   # per-slot positions (serving.py)

    def write(cache, fresh, i):
        """Land fresh [B, 1, H, D] at this layer's write position(s):
        one slice write at scalar pos, a vmapped per-slot write when
        each slot sits at its own position."""
        if not slotwise:
            return lax.dynamic_update_slice(cache, fresh[None],
                                            (i, 0, pos, 0, 0))
        layer = lax.dynamic_index_in_dim(cache, i, 0, keepdims=False)
        layer = jax.vmap(
            lambda c, f, p: lax.dynamic_update_slice(c, f, (p, 0, 0)))(
            layer, fresh, pos)
        return lax.dynamic_update_index_in_dim(cache, layer, i, 0)

    def body(carry, i):
        if quant:
            x, kc, vc, ksc, vsc = carry
        else:
            x, kc, vc = carry
        lp = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            layers)
        q, k, v = qkv_fn(lp, x, pos)
        if quant:
            k, ks = kv_quant(k)
            v, vs = kv_quant(v)
            ksc = write(ksc, ks, i)
            vsc = write(vsc, vs, i)
        kc = write(kc, k, i)
        vc = write(vc, v, i)
        kc_l = lax.dynamic_index_in_dim(kc, i, 0, keepdims=False)
        vc_l = lax.dynamic_index_in_dim(vc, i, 0, keepdims=False)
        if quant:
            kc_l = (kc_l, lax.dynamic_index_in_dim(ksc, i, 0,
                                                   keepdims=False))
            vc_l = (vc_l, lax.dynamic_index_in_dim(vsc, i, 0,
                                                   keepdims=False))
        x = attend_fn(lp, x, q, kc_l, vc_l, pos)
        if quant:
            return (x, kc, vc, ksc, vsc), None
        return (x, kc, vc), None

    if quant:
        (x, kc_all, vc_all, ksc_all, vsc_all), _ = lax.scan(
            body, (x, kc_all, vc_all, ksc_all, vsc_all),
            jnp.arange(n_layers))
        return x, kc_all, vc_all, ksc_all, vsc_all
    (x, kc_all, vc_all), _ = lax.scan(body, (x, kc_all, vc_all),
                                      jnp.arange(n_layers))
    return x, kc_all, vc_all


def fill_kv_cache(cache, ks, vs, pos):
    """Land the prefill K/V ([L, B, S, H, D], compute dtype) into a
    fresh cache from a family's ``init_kv_cache`` and set ``pos`` —
    quantizing when the cache is int8 ('ks' present). The ONE
    definition of the fill, so the int8 layout can't drift between
    families."""
    from mpi_acx_tpu.ops.kvquant import kv_quant
    if "ks" in cache:
        ks, kscale = kv_quant(ks)
        vs, vscale = kv_quant(vs)
        cache["ks"] = lax.dynamic_update_slice(cache["ks"], kscale,
                                               (0,) * 5)
        cache["vs"] = lax.dynamic_update_slice(cache["vs"], vscale,
                                               (0,) * 5)
    cache["k"] = lax.dynamic_update_slice(cache["k"], ks, (0,) * 5)
    cache["v"] = lax.dynamic_update_slice(cache["v"], vs, (0,) * 5)
    cache["pos"] = jnp.asarray(pos, jnp.int32)
    return cache


def run_decode_layers(layers, x, cache, qkv_fn, attend_fn):
    """:func:`decode_layer_scan` dispatched on the cache layout (bf16
    vs int8 — the ONE place 'ks' selects the quantized path), returning
    ``(x, updated cache)`` with ``pos`` advanced by the one decoded
    token."""
    pos = cache["pos"]
    if "ks" in cache:
        x, kc, vc, ksc, vsc = decode_layer_scan(
            layers, x, cache["k"], cache["v"], pos, qkv_fn, attend_fn,
            ksc_all=cache["ks"], vsc_all=cache["vs"])
        return x, {"k": kc, "v": vc, "ks": ksc, "vs": vsc,
                   "pos": pos + 1}
    x, kc, vc = decode_layer_scan(layers, x, cache["k"], cache["v"],
                                  pos, qkv_fn, attend_fn)
    return x, {"k": kc, "v": vc, "pos": pos + 1}


def greedy_generate(prefill_fn: Callable, decode_fn: Callable,
                    prompt, n_new: int, max_seq: int,
                    max_len: Optional[int] = None):
    """prompt [B, S] -> [B, S + n_new] by greedy argmax.

    prefill_fn(tokens, max_len, last_only) -> (logits [B, *, vocab], cache)
    decode_fn(cache, token [B]) -> (logits [B, vocab], cache)
    """
    B, S = prompt.shape
    if max_len is None:
        max_len = S + n_new
    assert S + n_new <= max_len, (S, n_new, max_len)
    # The position table/rope ceiling is hard: past it, position lookups
    # clamp silently and every token reuses the last row.
    assert S + n_new <= max_seq, (S, n_new, max_seq)
    logits, cache = prefill_fn(prompt, max_len, True)
    first = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)

    def step(carry, _):
        cache, tok = carry
        logits, cache = decode_fn(cache, tok)
        nxt = jnp.argmax(logits, axis=-1).astype(tok.dtype)
        return (cache, nxt), tok

    (_, _), toks = lax.scan(step, (cache, first), None, length=n_new)
    return jnp.concatenate([prompt, jnp.moveaxis(toks, 0, 1)], axis=1)


def sample_logits(logits, key, temperature: float = 1.0,
                  top_k: Optional[int] = None, top_p: Optional[float] = None):
    """Sample token ids from [B, vocab] f32 logits.

    Filters compose in the standard order: top-k first, then top-p
    (nucleus) over the surviving mass, then a Gumbel draw at the given
    temperature. ``temperature=0`` degenerates to argmax. Static-shaped
    (masking, not gathering), so it jits and scans cleanly.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    neg = jnp.finfo(logits.dtype).min
    V = logits.shape[-1]
    want_k = top_k is not None and top_k < V
    want_p = top_p is not None and top_p < 1.0
    if want_k or want_p:
        # ONE descending sort serves both filters (a second full-vocab
        # sort per decode step would dominate the filter cost).
        srt = jnp.sort(logits, axis=-1)[:, ::-1]         # [B, V] desc
        if want_k:
            kth = srt[:, top_k - 1][:, None]
            logits = jnp.where(logits < kth, neg, logits)
            # Nucleus below operates on the top-k-FILTERED distribution
            # (sequential composition, the standard order).
            srt = jnp.where(jnp.arange(V)[None, :] >= top_k, neg, srt)
        if want_p:
            # Keep the smallest prefix of descending-prob tokens whose
            # mass reaches top_p; the top-1 token always survives.
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep = (cum - probs < top_p).at[:, 0].set(True)
            thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)
            logits = jnp.where(logits < thresh[:, None], neg, logits)
    # Gumbel-max draw == categorical sample over the filtered softmax.
    g = jax.random.gumbel(key, logits.shape, logits.dtype)
    return jnp.argmax(logits + g, axis=-1)


def sample_generate(prefill_fn: Callable, decode_fn: Callable,
                    prompt, n_new: int, max_seq: int, key,
                    temperature: float = 1.0, top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    max_len: Optional[int] = None):
    """prompt [B, S] -> [B, S + n_new] by stochastic sampling (temperature
    / top-k / top-p); same contract as :func:`greedy_generate` plus a PRNG
    key. One jittable program: the whole decode is a lax.scan."""
    B, S = prompt.shape
    if max_len is None:
        max_len = S + n_new
    assert S + n_new <= max_len, (S, n_new, max_len)
    assert S + n_new <= max_seq, (S, n_new, max_seq)
    logits, cache = prefill_fn(prompt, max_len, True)
    key, sub = jax.random.split(key)
    first = sample_logits(logits[:, -1].astype(jnp.float32), sub,
                          temperature, top_k, top_p).astype(prompt.dtype)

    def step(carry, _):
        cache, tok, key = carry
        logits, cache = decode_fn(cache, tok)
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits.astype(jnp.float32), sub, temperature,
                            top_k, top_p).astype(tok.dtype)
        return (cache, nxt, key), tok

    (_, _, _), toks = lax.scan(step, (cache, first, key), None, length=n_new)
    return jnp.concatenate([prompt, jnp.moveaxis(toks, 0, 1)], axis=1)
