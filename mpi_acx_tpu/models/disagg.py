"""Disaggregated prefill/decode serving: per-layer KV handoff.

Monolithic continuous batching (models/serving.py) runs prefill and
decode on the same rank, so every prompt pass stalls the decode batch
behind it — the interference disaggregation exists to remove. Here the
fleet splits by role (``ACX_ROLE``): prefill ranks run the prompt pass
and ship each layer's KV block THE MOMENT that layer finishes — one
partitioned send per request, one partition per layer, MPIX_Pready
fired from inside the layer loop while later layers still run — and
decode ranks poll MPIX_Parrived, splice arriving pages into their slot
caches through the same ``scatter_fn`` the monolithic server uses, and
own token generation. The wire mechanics (packing, persistent
channels, tags) live in parallel/kv_ship.py.

Wire form (the EQuARX rule): int8 codes + f32 scales are the ONLY form
KV takes on the wire, so decode slot caches are always the int8
variant and a disagg serve is bit-equal to the monolithic
``_serve(kv_int8=True)`` — for BOTH prefill-side cache variants
(``prefill_kv_int8``): quantize-at-compute and quantize-at-wire
produce identical bytes because prefill attention runs on the exact
bf16 K/V either way and ops/kvquant.py is deterministic. Pinned by
tests/test_disagg.py.

Handoff protocol, per request (descriptor + one partitioned round):

  prefill                           decode
  -------                           ------
  HDR isend {rid, prompt_len,
             bucket}         ---->  irecv HDR; pick channel(peer,
                                    bucket); MPIX_Start recv round
  MPIX_Start send round
  layer 0 compute; quant;
  pack; Pready(0)            ---->  Parrived(0) -> splice layer 0
  layer 1 ...                ---->  ... (arrival overlaps prefill
  ...                               compute of later layers)
  head -> first token
  FIN isend {rid, first,
             prefill_us}     ---->  irecv FIN; all layers arrived;
  wait round                        wait round; scatter_fn -> slot
                                    armed; decode takes over

Failure semantics: a handoff that dies mid-round (peer loss, injected
fault) is REQUEUED — the decode side discards the partial splice and
re-arms for the re-shipped handoff; peer loss does not charge the
request's retry budget (serving.py's ``_peer_dead`` rule). A respawned
prefill rank re-ships every handoff it owns from scratch; the decode
side discards duplicates of already-completed requests by rid, which
makes the re-ship idempotent. The send side completes an aborted round
by publishing its remaining partitions with stale staging bytes
(``abort_fill``) so the persistent channel stays restartable.

Telemetry: every handoff records the TTFT split — prefill-compute vs
ship (publish -> last arrival) vs decode-pickup (unpack + scatter) —
as ``HandoffTelemetry`` rows on ``DisaggMetrics.handoffs``;
``overlap=False`` (ship only after the full prompt pass) is the
baseline the bench compares against (bench.py disagg rows).
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mpi_acx_tpu import reqlog
from mpi_acx_tpu.models.serving import (
    RollingSLO, RequestTelemetry, ServedBatch, ServingMetrics, _bucket,
    _flight_dump_best_effort, _pct, _peer_dead,
    _span_app_begin_best_effort, _span_app_end_best_effort,
    _tseries_annotate_best_effort, make_server_fns)
from mpi_acx_tpu.parallel.kv_ship import (
    DESC_FIN_TAG, DESC_HDR_TAG, KvReceiver, KvShipper)

# Descriptor magics ("ACXH"/"ACXF"): a handoff stream that desyncs
# (protocol bug, stale message from a dead incarnation) fails loudly at
# the magic check instead of splicing garbage into a slot cache.
_HDR_MAGIC = 0x41435848
_FIN_MAGIC = 0x41435846


def _hdr_wire(rid: int, prompt_len: int, bucket: int) -> np.ndarray:
    return np.array([_HDR_MAGIC, rid, prompt_len, bucket], np.int64)


def _fin_wire(rid: int, first_token: int, prefill_us: int,
              expose_us: int) -> np.ndarray:
    return np.array([_FIN_MAGIC, rid, first_token, prefill_us,
                     expose_us], np.int64)


def fleet_roles(size: int) -> List[str]:
    """Role of every rank, from $ACX_ROLE.

    Accepted forms (README knob table): a comma list mapping every rank
    (``prefill,decode,decode`` — the form acxrun propagates, since all
    ranks share one environment), a single role token (this rank's
    role; the fleet map defaults to rank 0 = prefill, rest = decode and
    the token must agree with it), or unset (loopback single-process
    serving — no fleet)."""
    spec = os.environ.get("ACX_ROLE", "").strip()
    default = ["prefill"] + ["decode"] * max(size - 1, 0)
    if not spec:
        return default
    if "," in spec:
        roles = [t.strip() for t in spec.split(",") if t.strip()]
        if len(roles) != size or any(r not in ("prefill", "decode")
                                     for r in roles):
            raise ValueError(
                f"ACX_ROLE={spec!r}: need one prefill|decode per rank "
                f"({size})")
        if "prefill" not in roles or "decode" not in roles:
            raise ValueError(
                f"ACX_ROLE={spec!r}: need at least one prefill and one "
                "decode rank")
        return roles
    if spec not in ("prefill", "decode"):
        raise ValueError(f"ACX_ROLE={spec!r}: prefill|decode|comma-list")
    return default


@dataclass
class HandoffTelemetry:
    """One handoff's TTFT split (DisaggMetrics.handoffs row)."""

    rid: int
    layers: int
    wire_bytes: int      # partitioned payload (codes + scales)
    prefill_s: float     # embed -> first token (incl. per-layer publish)
    ship_s: float        # FIN observed -> last partition arrived + round
    pickup_s: float      # unpack/assemble -> scatter -> slot armed
    overlap: bool        # per-layer Pready (True) vs ship-after-prefill
    expose_s: float = 0.0  # publish time EXPOSED after the head — the
    #                        wire cost overlap hides under compute (~0
    #                        with per-layer Pready; the full serialized
    #                        pack+publish without it)


@dataclass
class DisaggMetrics(ServingMetrics):
    """ServingMetrics grown by the handoff rows of a disagg serve."""

    handoffs: List[HandoffTelemetry] = field(default_factory=list)
    handoff_prefill_p50_s: float = 0.0
    handoff_ship_p50_s: float = 0.0
    handoff_pickup_p50_s: float = 0.0


def _finish_handoff_metrics(m: DisaggMetrics) -> DisaggMetrics:
    m.handoff_prefill_p50_s = _pct([h.prefill_s for h in m.handoffs], 0.5)
    m.handoff_ship_p50_s = _pct([h.ship_s for h in m.handoffs], 0.5)
    m.handoff_pickup_p50_s = _pct([h.pickup_s for h in m.handoffs], 0.5)
    return m


def make_layerwise_prefill_fns(params, cfg, family=None):
    """Per-layer prefill closures: (embed_fn, layer_fn, head_fn,
    quant_fn). The layer loop is hoisted to the host so the caller can
    publish layer l's KV the moment ``layer_fn`` returns — the
    per-layer Pready the monolithic scan prefill structurally cannot
    express. Each closure reuses the dense family's exact block pieces
    (_qkv/_attend/_mlp, same primitive sequence as the scan body), so
    the hoisted loop is bit-identical to ``family.prefill`` — logits,
    codes, and scales (pinned by tests/test_disagg.py).

    Only the dense transformer scaffold is supported (the layer
    internals are family-specific; llama/MoE would need their own
    block closures)."""
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.ops.kvquant import kv_quant
    from mpi_acx_tpu.ops.wquant import wread
    if family is not None and family is not tfm:
        raise NotImplementedError(
            "layerwise prefill: dense transformer family only")

    @jax.jit
    def embed_fn(tokens):
        S = tokens.shape[1]
        return (params["embed"][tokens]
                + params["pos"][:S]).astype(cfg.dtype)

    @jax.jit
    def layer_fn(x, layer):
        lp = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, layer, 0,
                                               keepdims=False),
            params["layers"])
        q, k, v = tfm._qkv(cfg, lp, x)
        x = x + tfm._attend(cfg, q, k, v) @ wread(lp, "wo", x.dtype)
        return tfm._mlp(cfg, lp, x), k, v

    @jax.jit
    def head_fn(x, last_index):
        x = tfm.layernorm(x, params["lnf_g"], params["lnf_b"])
        x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
        return jnp.einsum("bsd,vd->bsv", x,
                          params["embed"].astype(x.dtype),
                          preferred_element_type=jnp.float32)

    @jax.jit
    def quant_fn(k, v):
        kq, ks = kv_quant(k)
        vq, vs = kv_quant(v)
        return kq, ks, vq, vs

    return embed_fn, layer_fn, head_fn, quant_fn


def _prefill_ship(ch, pfns, cfg, padded, last_index, overlap,
                  prefill_kv_int8, ship_fault=None, rid=0):
    """Run the layerwise prompt pass, publishing layer l's partition as
    it completes (``overlap``) or all partitions after the head
    (the ship-after-full-prefill baseline). Returns (first_token,
    prefill_s). The caller has already begun the channel round.

    ``prefill_kv_int8`` picks the prefill-side cache variant:
    quantize-at-compute (the prefill holds int8 codes, as a
    kv_int8-serving prefill rank would) vs quantize-at-wire (bf16
    staging, codes produced at pack time). Same wire bytes either way
    — prefill attention uses the exact bf16 K/V in both, and the
    quantizer is the single ops/kvquant.py definition.

    ``ship_fault(rid, layer)`` is a test hook called before layer
    ``layer``'s publish — raising from it models a prefill rank dying
    mid-handoff (tests/test_disagg.py).

    Returns (first_token, prefill_s, expose_s) — ``expose_s`` is the
    publish time left EXPOSED after the head finished: ~0 with per-layer
    overlap (everything already shipped under compute), the full
    serialized pack+publish cost without it. The bench's overlap A/B
    reads this off the FIN descriptor."""
    embed_fn, layer_fn, head_fn, quant_fn = pfns
    t0 = time.perf_counter()
    x = embed_fn(padded)
    staged = []
    for layer in range(cfg.n_layers):
        x, k, v = layer_fn(x, layer)
        reqlog.emit("prefill_layer", rid, layer=layer)
        if prefill_kv_int8:
            # quantize-at-compute: codes are the prefill's cache form.
            kq, ks, vq, vs = (np.asarray(a) for a in quant_fn(k, v))
        else:
            # quantize-at-wire: bf16 staging until the pack.
            kq = ks = vq = vs = None
        if ship_fault is not None:
            ship_fault(rid, layer)
        if overlap:
            if kq is None:
                kq, ks, vq, vs = (np.asarray(a) for a in quant_fn(k, v))
            ch.publish(layer, kq[0], ks[0], vq[0], vs[0])
            reqlog.emit("ship_pready", rid, part=layer, overlap=True)
        else:
            staged.append((kq, ks, vq, vs) if kq is not None else (k, v))
    logits = head_fn(x, last_index)
    first = int(jnp.argmax(logits[0, 0]))
    t_head = time.perf_counter()
    if not overlap:
        for layer, st in enumerate(staged):
            if len(st) == 2:
                kq, ks, vq, vs = (np.asarray(a) for a in quant_fn(*st))
            else:
                kq, ks, vq, vs = st
            ch.publish(layer, kq[0], ks[0], vq[0], vs[0])
            reqlog.emit("ship_pready", rid, part=layer, overlap=False)
    t1 = time.perf_counter()
    return first, t1 - t0, t1 - t_head


def _splice_poll(ch, bucket, heads, head_dim, timeout_s=30.0):
    """Poll every layer partition, splicing arrivals into the assembled
    [L, 1, bucket, ...] host cache as they land (arrival order, not
    layer order). Raises AcxTimeoutError past ``timeout_s`` — the
    bound that keeps a decode rank from spinning forever on a prefill
    rank that died before heartbeat detection."""
    from mpi_acx_tpu.runtime import ERR_TIMEOUT, AcxTimeoutError
    L = ch.geom.n_layers
    kq = np.zeros((L, 1, bucket, heads, head_dim), np.int8)
    vq = np.zeros_like(kq)
    ks = np.zeros((L, 1, bucket, heads, 1), np.float32)
    vs = np.zeros_like(ks)
    pending = set(range(L))
    deadline = time.monotonic() + timeout_s
    while pending:
        for layer in sorted(pending):
            if ch.poll(layer):
                lkq, lks, lvq, lvs = ch.take(layer)
                kq[layer, 0] = lkq
                ks[layer, 0] = lks
                vq[layer, 0] = lvq
                vs[layer, 0] = lvs
                pending.discard(layer)
        if pending and time.monotonic() > deadline:
            raise AcxTimeoutError(
                f"handoff: {len(pending)} layer partition(s) never "
                f"arrived within {timeout_s}s", ERR_TIMEOUT, ch.geom.peer,
                -1)
    return {"k": kq, "ks": ks, "v": vq, "vs": vs}


def _abort_rounds(send_ch, recv_ch, drain_s: float = 5.0) -> None:
    """Close both ends of a failed handoff round so the persistent
    channels stay restartable: the send side publishes its remaining
    partitions with stale staging bytes, the recv side drains arrivals
    (error-completed partitions read arrived too) and closes. Never
    raises — this runs on the requeue path, where the original
    exception is the one that matters."""
    try:
        if send_ch is not None and send_ch.open_round:
            send_ch.abort_fill()
            send_ch.finish()
    except Exception:  # noqa: BLE001 — cleanup must not mask the cause
        send_ch.open_round = False
    try:
        if recv_ch is not None and recv_ch.open_round:
            deadline = time.monotonic() + drain_s
            while (not all(recv_ch.poll(p)
                           for p in range(recv_ch.geom.n_layers))
                   and time.monotonic() < deadline):
                pass
            recv_ch.finish()
    except Exception:  # noqa: BLE001 — cleanup must not mask the cause
        recv_ch.open_round = False


_loopback_runtime = None


def _loopback_rt():
    """Process-singleton loopback Runtime (rank 0 of 1) for the
    single-process disagg mode; finalized at interpreter exit. A
    caller running under acxrun passes its own Runtime instead."""
    global _loopback_runtime
    if _loopback_runtime is None:
        import atexit

        from mpi_acx_tpu.runtime import Runtime
        _loopback_runtime = Runtime()
        atexit.register(_loopback_runtime.finalize)
    return _loopback_runtime


def serve_disagg_greedy(params, cfg, prompts: Sequence[np.ndarray], n_new,
                        n_slots: int, max_len: int, family=None,
                        eos: Optional[int] = None, chunk: int = 1,
                        server_fns=None, prefill_kv_int8: bool = False,
                        max_request_retries: int = 2, rt=None,
                        overlap: bool = True,
                        ship_fault: Optional[Callable] = None,
                        poll_timeout_s: float = 30.0) -> ServedBatch:
    """Disaggregated greedy serve. With $ACX_ROLE unset: loopback mode
    — this process plays both roles against a self-channel, so the
    full wire path (descriptors, partitioned round, per-layer Pready /
    Parrived, splice) runs single-process; outputs are bit-equal to
    the monolithic ``serve_greedy(..., kv_int8=True)``. With $ACX_ROLE
    set (under acxrun): dispatches to this rank's role worker —
    prefill ranks return an empty batch, decode ranks return their
    requests' outputs (None rows elsewhere).

    ``server_fns`` must be a ``make_server_fns(..., kv_int8=True)``
    tuple — decode slots are always int8, the wire form.
    ``prefill_kv_int8`` picks the prefill-side variant (see
    ``_prefill_ship``); ``overlap=False`` ships only after the full
    prompt pass (the bench baseline). ``ship_fault(rid, layer)`` is a
    failure-injection hook (see ``_prefill_ship``)."""
    roles = None
    if os.environ.get("ACX_ROLE", "").strip():
        if rt is None:
            raise ValueError("fleet mode needs an explicit Runtime")
        roles = fleet_roles(rt.size)
        if roles[rt.rank] == "prefill":
            run_prefill_worker(rt, params, cfg, prompts, max_len,
                               family=family, overlap=overlap,
                               prefill_kv_int8=prefill_kv_int8)
            return ServedBatch([None] * len(prompts),
                               _finish_handoff_metrics(DisaggMetrics()))
        return run_decode_worker(
            rt, params, cfg, prompts, n_new, n_slots, max_len,
            family=family, eos=eos, chunk=chunk, server_fns=server_fns,
            max_request_retries=max_request_retries,
            poll_timeout_s=poll_timeout_s)
    return _serve_disagg_loopback(
        params, cfg, prompts, n_new, n_slots, max_len, family, eos,
        chunk, server_fns, prefill_kv_int8, max_request_retries,
        rt if rt is not None else _loopback_rt(), overlap, ship_fault,
        poll_timeout_s)


def _serve_disagg_loopback(params, cfg, prompts, n_new, n_slots, max_len,
                           family, eos, chunk, server_fns,
                           prefill_kv_int8, max_request_retries, rt,
                           overlap, ship_fault, poll_timeout_s):
    """Single-process disagg scheduler: models/serving.py's ``_serve``
    with the refill path replaced by a real wire handoff (descriptor
    exchange + partitioned round against the loopback transport). The
    decode loop is byte-for-byte the monolithic one — that, plus the
    wire carrying the exact int8 codes the monolithic fill would have
    produced, is the bit-equality argument."""
    if family is None:
        from mpi_acx_tpu.models import transformer as family  # noqa: N813
    assert prompts, "no requests"
    assert all(len(p) > 0 for p in prompts), "zero-length prompt"
    n_new = ([int(n_new)] * len(prompts) if np.ndim(n_new) == 0
             else [int(n) for n in n_new])
    assert len(n_new) == len(prompts), (len(n_new), len(prompts))
    assert all(n >= 1 for n in n_new), "n_new >= 1 per request"
    assert all(len(p) + n + chunk <= max_len
               for p, n in zip(prompts, n_new)), "request exceeds max_len"
    assert all(len(p) + n + chunk <= cfg.max_seq
               for p, n in zip(prompts, n_new)), "request exceeds max_seq"

    if server_fns is None:
        server_fns = make_server_fns(params, cfg, family, chunk=chunk,
                                     kv_int8=True)
    (_, step_fn, scatter_fn, fns_chunk, fns_int8, fns_sample) = server_fns
    assert fns_chunk == chunk, (fns_chunk, chunk)
    assert fns_int8, "disagg decode slots are int8 (the wire form)"
    assert fns_sample is None, "disagg serving is greedy-only for now"

    pfns = make_layerwise_prefill_fns(params, cfg, family)
    shipper = KvShipper(rt, cfg.n_layers, cfg.n_heads, cfg.head_dim)
    receiver = KvReceiver(rt, cfg.n_layers, cfg.n_heads, cfg.head_dim)

    slots = family.init_kv_cache(cfg, n_slots, max_len, kv_int8=True)
    slots["pos"] = jnp.zeros((n_slots,), jnp.int32)
    queue = deque(enumerate(np.asarray(p, np.int32) for p in prompts))
    for depth, (rid, p) in enumerate(queue):
        reqlog.emit("admit", rid, prompt_len=len(p), n_new=n_new[rid])
        reqlog.emit("queue", rid, depth=depth)
    owner = [-1] * n_slots
    emitted: List[List[int]] = [[] for _ in prompts]
    done: List[Optional[np.ndarray]] = [None] * len(prompts)
    last_tok = np.zeros((n_slots,), np.int32)
    keys = jax.random.split(jax.random.key(0), n_slots)  # greedy dummies
    attempts = [0] * len(prompts)

    t0 = time.perf_counter()
    ttft = [None] * len(prompts)
    finish = [None] * len(prompts)
    slo = RollingSLO()
    itl_samples: List[float] = []
    qd_samples: List[int] = []
    occ_samples: List[float] = []
    handoffs: List[HandoffTelemetry] = []
    n_steps = n_prefills = n_requeues = n_peer_requeues = 0
    n_hang_dumps = 0

    def _requeue(rid, prompt, exc, charge=True):
        nonlocal n_requeues, n_peer_requeues
        if charge:
            attempts[rid] += 1
            if attempts[rid] > max_request_retries:
                raise RuntimeError(
                    f"request {rid} failed {attempts[rid]} time(s), past "
                    f"max_request_retries={max_request_retries}") from exc
        else:
            n_peer_requeues += 1
        emitted[rid] = []
        ttft[rid] = None
        n_requeues += 1
        reqlog.emit("requeue", rid, charged=bool(charge))
        queue.append((rid, prompt))

    def refill(b):
        """Handoff-refill: prefill-side layer loop publishes into the
        loopback self-channel, decode side splices and scatters —
        the wire path the role-split fleet runs, serialized in one
        process."""
        nonlocal slots, n_prefills
        rid, prompt = queue.popleft()
        S = len(prompt)
        bucket = min(_bucket(S), max_len, cfg.max_seq)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :S] = prompt
        send_ch = shipper.channel(rt.rank, bucket)
        recv_ch = receiver.channel(rt.rank, bucket)
        spanned = _span_app_begin_best_effort(rid)
        reqlog.emit("prefill_start", rid, prompt_len=S, bucket=bucket)
        try:
            # Descriptor header: recv posted first, both waited — the
            # exchange is atomic, so a later handoff failure can never
            # leave a dangling descriptor in the loopback stream.
            hdr = np.zeros(4, np.int64)
            hr = rt.irecv_enqueue(hdr, source=rt.rank, tag=DESC_HDR_TAG)
            rt.wait(rt.isend_enqueue(_hdr_wire(rid, S, bucket),
                                     dest=rt.rank, tag=DESC_HDR_TAG))
            rt.wait(hr)
            assert int(hdr[0]) == _HDR_MAGIC and int(hdr[1]) == rid, hdr
            reqlog.emit("ship_hdr", rid, side="loopback", bucket=bucket)
            recv_ch.begin()
            send_ch.begin()
            first, prefill_s, expose_s = _prefill_ship(
                send_ch, pfns, cfg, jnp.asarray(padded), S - 1, overlap,
                prefill_kv_int8, ship_fault=ship_fault, rid=rid)
            reqlog.emit("prefill_end", rid, first_token=first,
                        prefill_s=prefill_s)
            fin = np.zeros(5, np.int64)
            fr = rt.irecv_enqueue(fin, source=rt.rank, tag=DESC_FIN_TAG)
            rt.wait(rt.isend_enqueue(
                _fin_wire(rid, first, int(prefill_s * 1e6),
                          int(expose_s * 1e6)),
                dest=rt.rank, tag=DESC_FIN_TAG))
            rt.wait(fr)
            assert int(fin[0]) == _FIN_MAGIC and int(fin[1]) == rid, fin
            reqlog.emit("ship_fin", rid, side="loopback")
            t_ship = time.perf_counter()
            one = _splice_poll(recv_ch, bucket, cfg.n_heads,
                               cfg.head_dim, timeout_s=poll_timeout_s)
            send_ch.finish()
            recv_ch.finish()
            ship_s = time.perf_counter() - t_ship
            t_pick = time.perf_counter()
            one = {k: jnp.asarray(v) for k, v in one.items()}
            slots = scatter_fn(slots, one, b, S)
            pickup_s = time.perf_counter() - t_pick
        except Exception as exc:  # noqa: BLE001 — any handoff failure
            _abort_rounds(send_ch, recv_ch)
            _requeue(rid, prompt, exc, charge=not _peer_dead(exc))
            return False
        finally:
            if spanned:
                _span_app_end_best_effort()
        owner[b] = rid
        reqlog.emit("seat", rid, slot=b, pos=S)
        emitted[rid].append(int(fin[2]))
        last_tok[b] = int(fin[2])
        n_prefills += 1
        ttft[rid] = time.perf_counter() - t0
        slo.note_ttft(ttft[rid])
        reqlog.emit("stream", rid, n=1, ttft_s=ttft[rid])
        handoffs.append(HandoffTelemetry(
            rid=rid, layers=cfg.n_layers,
            wire_bytes=cfg.n_layers * send_ch.geom.part_bytes,
            prefill_s=prefill_s, ship_s=ship_s, pickup_s=pickup_s,
            overlap=overlap, expose_s=expose_s))
        return True

    def retire(b):
        nonlocal slots
        rid = owner[b]
        done[rid] = np.concatenate(
            [np.asarray(prompts[rid], np.int32),
             np.asarray(emitted[rid], np.int32)])
        finish[rid] = time.perf_counter() - t0
        reqlog.emit("finish", rid, new_tokens=len(emitted[rid]),
                    latency_s=finish[rid])
        owner[b] = -1
        slots["pos"] = slots["pos"].at[b].set(0)

    def slot_finished(b):
        rid = owner[b]
        return (len(emitted[rid]) >= n_new[rid]
                or (eos is not None and emitted[rid]
                    and emitted[rid][-1] == eos))

    qd_samples.append(len(queue))
    while queue and any(o == -1 for o in owner):
        b = owner.index(-1)
        if refill(b) and slot_finished(b):
            retire(b)

    while any(o >= 0 for o in owner) or queue:
        qd_samples.append(len(queue))
        occ_samples.append(sum(o >= 0 for o in owner) / n_slots)
        slo.note_gauges(qd_samples[-1], occ_samples[-1])
        _tseries_annotate_best_effort(slo.live_slos())
        if not any(o >= 0 for o in owner):
            while queue and any(o == -1 for o in owner):
                b = owner.index(-1)
                if refill(b) and slot_finished(b):
                    retire(b)
            continue
        step_t0 = time.perf_counter()
        try:
            slots, toks, keys = step_fn(slots, jnp.asarray(last_tok), keys)
        except Exception as exc:  # noqa: BLE001 — any device failure
            lost_peer = _peer_dead(exc)
            if _flight_dump_best_effort():
                n_hang_dumps += 1
            for b in range(n_slots):
                if owner[b] >= 0:
                    rid = owner[b]
                    owner[b] = -1
                    _requeue(rid, np.asarray(prompts[rid], np.int32),
                             exc, charge=not lost_peer)
            slots = family.init_kv_cache(cfg, n_slots, max_len,
                                         kv_int8=True)
            slots["pos"] = jnp.zeros((n_slots,), jnp.int32)
            keys = jax.random.split(jax.random.key(0), n_slots)
            last_tok = np.zeros((n_slots,), np.int32)
            continue
        block = np.asarray(toks, np.int32)
        step_dt = time.perf_counter() - step_t0
        n_steps += 1
        reqlog.emit("decode_step", step=n_steps, dt_s=step_dt,
                    active=sum(o >= 0 for o in owner))
        for b in range(n_slots):
            last_tok[b] = block[-1, b]
            if owner[b] < 0:
                continue
            got = 0
            for c in range(block.shape[0]):
                if slot_finished(b):
                    break
                emitted[owner[b]].append(int(block[c, b]))
                itl_samples.append(step_dt / chunk)
                slo.note_itl(step_dt / chunk)
                got += 1
            if got:
                reqlog.emit("stream", owner[b], n=got, itl_s=step_dt / chunk)
        for b in range(n_slots):
            while owner[b] >= 0 and slot_finished(b):
                retire(b)
                if queue:
                    refill(b)

    assert all(d is not None for d in done)
    shipper.close()
    receiver.close()
    wall = time.perf_counter() - t0
    per_request = []
    total_new = 0
    for rid in range(len(prompts)):
        nt = len(emitted[rid])
        total_new += nt
        lat = finish[rid] if finish[rid] is not None else wall
        per_request.append(RequestTelemetry(
            rid=rid,
            ttft_s=ttft[rid] if ttft[rid] is not None else lat,
            latency_s=lat, new_tokens=nt,
            tokens_per_s=nt / lat if lat > 0 else 0.0,
            retries=attempts[rid]))
    metrics = DisaggMetrics(
        requests=len(prompts), wall_s=wall, new_tokens=total_new,
        tokens_per_s=total_new / wall if wall > 0 else 0.0,
        steps=n_steps, prefills=n_prefills, requeues=n_requeues,
        peer_requeues=n_peer_requeues, hang_dumps=n_hang_dumps,
        ttft_p50_s=_pct([r.ttft_s for r in per_request], 0.50),
        ttft_p99_s=_pct([r.ttft_s for r in per_request], 0.99),
        itl_p50_s=_pct(itl_samples, 0.50),
        itl_p99_s=_pct(itl_samples, 0.99),
        queue_depth_max=max(qd_samples) if qd_samples else 0,
        queue_depth_mean=(sum(qd_samples) / len(qd_samples)
                          if qd_samples else 0.0),
        slot_occupancy_mean=(sum(occ_samples) / len(occ_samples)
                             if occ_samples else 1.0),
        per_request=per_request, handoffs=handoffs)
    return ServedBatch(done, _finish_handoff_metrics(metrics))


# -- fleet-mode role workers (under acxrun, $ACX_ROLE set) -----------------


def run_prefill_worker(rt, params, cfg, prompts, max_len, family=None,
                       overlap: bool = True,
                       prefill_kv_int8: bool = False) -> int:
    """Prefill rank's loop: for every owned request (static map: rid ->
    prefill rank ``rid % n_prefill``, decode rank ``rid % n_decode``),
    run the layerwise prompt pass and ship it. A respawned incarnation
    of this rank simply reruns the loop from rid 0 — re-shipping is
    idempotent because the decode side discards duplicates by rid.
    Returns the number of handoffs shipped."""
    roles = fleet_roles(rt.size)
    prefill_ranks = [r for r, ro in enumerate(roles) if ro == "prefill"]
    decode_ranks = [r for r, ro in enumerate(roles) if ro == "decode"]
    me = prefill_ranks.index(rt.rank)
    pfns = make_layerwise_prefill_fns(params, cfg, family)
    shipper = KvShipper(rt, cfg.n_layers, cfg.n_heads, cfg.head_dim)
    my_rids = [rid for rid in range(len(prompts))
               if rid % len(prefill_ranks) == me]
    for depth, rid in enumerate(my_rids):
        # The prefill rank is the fleet's request entry point: its
        # admit/queue events open every journey the decode rank's
        # finish will close (tools/acx_request.py joins them by rid).
        reqlog.emit("admit", rid, prompt_len=len(prompts[rid]))
        reqlog.emit("queue", rid, depth=depth)
    shipped = 0
    for rid in my_rids:
        dst = decode_ranks[rid % len(decode_ranks)]
        prompt = np.asarray(prompts[rid], np.int32)
        S = len(prompt)
        bucket = min(_bucket(S), max_len, cfg.max_seq)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :S] = prompt
        ch = shipper.channel(dst, bucket)
        spanned = _span_app_begin_best_effort(rid)
        reqlog.emit("prefill_start", rid, prompt_len=S, bucket=bucket)
        try:
            rt.wait(rt.isend_enqueue(_hdr_wire(rid, S, bucket), dest=dst,
                                     tag=DESC_HDR_TAG))
            reqlog.emit("ship_hdr", rid, side="send", bucket=bucket,
                        dst=dst)
            ch.begin()
            first, prefill_s, expose_s = _prefill_ship(
                ch, pfns, cfg, jnp.asarray(padded), S - 1, overlap,
                prefill_kv_int8, rid=rid)
            reqlog.emit("prefill_end", rid, first_token=first,
                        prefill_s=prefill_s)
            rt.wait(rt.isend_enqueue(
                _fin_wire(rid, first, int(prefill_s * 1e6),
                          int(expose_s * 1e6)), dest=dst,
                tag=DESC_FIN_TAG))
            reqlog.emit("ship_fin", rid, side="send", dst=dst)
            ch.finish()
            shipped += 1
        finally:
            if spanned:
                _span_app_end_best_effort()
    shipper.close()
    return shipped


def run_decode_worker(rt, params, cfg, prompts, n_new, n_slots, max_len,
                      family=None, eos=None, chunk: int = 1,
                      server_fns=None, max_request_retries: int = 2,
                      poll_timeout_s: float = 30.0,
                      page_tokens: int = None,
                      n_pages: int = None) -> ServedBatch:
    """Decode rank's loop: consume handoffs from the prefill rank,
    splice them into slot caches, and generate. Returns a ServedBatch
    with this rank's requests filled in (None rows elsewhere).

    ``page_tokens`` switches the decode cache from fixed per-slot rows
    to the paged pool (models/kvpage.py): an inbound handoff's bucket
    rows land in freshly allocated pages (the wire already carries
    int8 codes + f32 scales — exactly the page-resident form, so the
    splice is a page scatter, no re-quantization) and the request's
    FULL page budget (prompt + n_new + chunk) is reserved at seat
    time — a seated request can never be starved mid-decode by a later
    arrival. Outputs stay bit-equal to the fixed-slot worker's.

    Failure semantics: a handoff that dies mid-flight (prefill rank
    killed) raises out of the intake; the request is requeued —
    UNCHARGED when the failure is peer-loss shaped — and satisfied by
    the respawned prefill rank's re-ship. Handoffs for already-retired
    rids (the re-ship's duplicates) are drained and discarded."""
    if family is None:
        from mpi_acx_tpu.models import transformer as family  # noqa: N813
    roles = fleet_roles(rt.size)
    prefill_ranks = [r for r, ro in enumerate(roles) if ro == "prefill"]
    decode_ranks = [r for r, ro in enumerate(roles) if ro == "decode"]
    assert len(prefill_ranks) == 1, \
        "decode worker handles a single prefill rank for now"
    src = prefill_ranks[0]
    n_new = ([int(n_new)] * len(prompts) if np.ndim(n_new) == 0
             else [int(n) for n in n_new])
    my_rids = [rid for rid in range(len(prompts))
               if decode_ranks[rid % len(decode_ranks)] == rt.rank]

    paged = page_tokens is not None
    if paged:
        from mpi_acx_tpu.models import kvpage
        pt = int(page_tokens)
        assert max_len % pt == 0, (max_len, pt)
        if n_pages is None:
            n_pages = n_slots * (max_len // pt)
        pkv = kvpage.PagedKV(cfg, family, n_slots, max_len, pt, n_pages,
                             kv_int8=True)
        step_fn = kvpage.make_paged_step_fn(params, cfg, family, chunk,
                                            pt)
        scatter_fn = None
    else:
        if server_fns is None:
            server_fns = make_server_fns(params, cfg, family, chunk=chunk,
                                         kv_int8=True)
        (_, step_fn, scatter_fn, fns_chunk, fns_int8,
         fns_sample) = server_fns
        assert fns_chunk == chunk and fns_int8 and fns_sample is None

    receiver = KvReceiver(rt, cfg.n_layers, cfg.n_heads, cfg.head_dim)
    slots = family.init_kv_cache(cfg, n_slots, max_len, kv_int8=True) \
        if not paged else None
    if not paged:
        slots["pos"] = jnp.zeros((n_slots,), jnp.int32)
    owner = [-1] * n_slots
    emitted = {rid: [] for rid in my_rids}
    done: List[Optional[np.ndarray]] = [None] * len(prompts)
    last_tok = np.zeros((n_slots,), np.int32)
    keys = jax.random.split(jax.random.key(0), n_slots)
    attempts = {rid: 0 for rid in my_rids}
    pending = set(my_rids)       # not yet retired
    seated = set()               # currently owning a slot

    t0 = time.perf_counter()
    ttft = {rid: None for rid in my_rids}
    finish = {rid: None for rid in my_rids}
    handoffs: List[HandoffTelemetry] = []
    itl_samples: List[float] = []
    n_steps = n_prefills = n_requeues = n_peer_requeues = 0
    n_hang_dumps = 0

    def _note_failure(rid, exc):
        nonlocal n_requeues, n_peer_requeues
        charge = not _peer_dead(exc)
        if charge:
            attempts[rid] += 1
            if attempts[rid] > max_request_retries:
                raise RuntimeError(
                    f"request {rid} failed {attempts[rid]} time(s), past "
                    f"max_request_retries={max_request_retries}") from exc
        else:
            n_peer_requeues += 1
        emitted[rid] = []
        ttft[rid] = None
        n_requeues += 1
        reqlog.emit("requeue", rid, charged=bool(charge))

    def intake(b) -> bool:
        """Consume the next inbound handoff. Seats it in slot ``b`` and
        returns True; returns False for a discarded duplicate or a
        failed handoff (requeued — the re-ship will satisfy it)."""
        nonlocal slots, n_prefills
        hdr = np.zeros(4, np.int64)
        recv_ch = None
        rid = -1
        try:
            rt.wait(rt.irecv_enqueue(hdr, source=src, tag=DESC_HDR_TAG))
            assert int(hdr[0]) == _HDR_MAGIC, hdr
            rid, S, bucket = int(hdr[1]), int(hdr[2]), int(hdr[3])
            reqlog.emit("ship_hdr", rid, side="recv", bucket=bucket,
                        src=src)
            recv_ch = receiver.channel(src, bucket)
            recv_ch.begin()
            one = _splice_poll(recv_ch, bucket, cfg.n_heads,
                               cfg.head_dim, timeout_s=poll_timeout_s)
            fin = np.zeros(5, np.int64)
            rt.wait(rt.irecv_enqueue(fin, source=src, tag=DESC_FIN_TAG))
            assert (int(fin[0]) == _FIN_MAGIC
                    and int(fin[1]) == rid), (fin, rid)
            reqlog.emit("ship_fin", rid, side="recv", src=src)
            recv_ch.finish()
            if rid not in pending or rid in seated:
                return False      # re-ship duplicate: drained, dropped
            t_pick = time.perf_counter()
            one = {k: jnp.asarray(v) for k, v in one.items()}
            if paged:
                # Reserve the request's FULL page budget up front (no
                # growth path in this loop) and splice the wire's
                # int8+scales bucket rows — already the page-resident
                # form — straight into the prompt pages.
                need = kvpage.pages_needed(S + n_new[rid] + chunk, pt)
                pages = pkv.alloc_evicting(need)
                if pages is None:
                    raise RuntimeError(
                        f"decode rank {rt.rank}: page pool dry seating "
                        f"rid={rid} (need {need} pages, "
                        f"{pkv.alloc.free_count} free) — size n_pages "
                        "to n_slots*max_len/page_tokens")
                try:
                    pkv.scatter_prompt(
                        {k: v for k, v in one.items() if k != "pos"},
                        pages[:kvpage.pages_needed(S, pt)])
                    pkv.seat(b, [], pages, S, rid=rid)
                except Exception:
                    for p in pages:
                        pkv.alloc.decref(p)
                    raise
            else:
                slots = scatter_fn(slots, one, b, S)
                reqlog.emit("seat", rid, slot=b, pos=S)
            pickup_s = time.perf_counter() - t_pick
        except Exception as exc:  # noqa: BLE001 — any handoff failure
            nonlocal n_hang_dumps
            # Snapshot the comm plane before healing: the flight dump
            # is the evidence trail acx_doctor (and the chaos oracle's
            # doctor_verdict audit) attributes the dead link from.
            if n_hang_dumps == 0 and _flight_dump_best_effort():
                n_hang_dumps += 1
            _abort_rounds(None, recv_ch)
            if rid in pending and rid not in seated:
                _note_failure(rid, exc)
            elif rid < 0 and not _peer_dead(exc):
                raise
            return False
        owner[b] = rid
        seated.add(rid)
        first = int(fin[2])
        emitted[rid].append(first)
        last_tok[b] = first
        n_prefills += 1
        ttft[rid] = time.perf_counter() - t0
        reqlog.emit("stream", rid, n=1, ttft_s=ttft[rid])
        handoffs.append(HandoffTelemetry(
            rid=rid, layers=cfg.n_layers,
            wire_bytes=cfg.n_layers * recv_ch.geom.part_bytes,
            prefill_s=int(fin[3]) / 1e6, ship_s=0.0, pickup_s=pickup_s,
            overlap=True, expose_s=int(fin[4]) / 1e6))
        return True

    def retire(b):
        nonlocal slots
        rid = owner[b]
        done[rid] = np.concatenate(
            [np.asarray(prompts[rid], np.int32),
             np.asarray(emitted[rid], np.int32)])
        finish[rid] = time.perf_counter() - t0
        reqlog.emit("finish", rid, new_tokens=len(emitted[rid]),
                    latency_s=finish[rid])
        pending.discard(rid)
        seated.discard(rid)
        owner[b] = -1
        if paged:
            pkv.release(b)        # pages back to the pool, slot parked
        else:
            slots["pos"] = slots["pos"].at[b].set(0)

    def slot_finished(b):
        rid = owner[b]
        return (len(emitted[rid]) >= n_new[rid]
                or (eos is not None and emitted[rid]
                    and emitted[rid][-1] == eos))

    while pending:
        # Seat inbound handoffs on every free slot before stepping.
        while (len(seated) < len(pending)
               and any(o == -1 for o in owner)):
            b = owner.index(-1)
            if intake(b) and slot_finished(b):
                retire(b)
        if not any(o >= 0 for o in owner):
            continue
        step_t0 = time.perf_counter()
        if paged:
            state = pkv.device_state()
            state, toks, keys = step_fn(state, jnp.asarray(last_tok),
                                        keys)
            pkv.absorb(state)
            kvpage.publish_page_stats_best_effort(
                pkv.alloc.free_count, pkv.alloc.shared_count(), 0, 0, 0)
        else:
            slots, toks, keys = step_fn(slots, jnp.asarray(last_tok),
                                        keys)
        block = np.asarray(toks, np.int32)
        step_dt = time.perf_counter() - step_t0
        n_steps += 1
        reqlog.emit("decode_step", step=n_steps, dt_s=step_dt,
                    active=sum(o >= 0 for o in owner))
        for b in range(n_slots):
            last_tok[b] = block[-1, b]
            if owner[b] < 0:
                continue
            got = 0
            for c in range(block.shape[0]):
                if slot_finished(b):
                    break
                emitted[owner[b]].append(int(block[c, b]))
                itl_samples.append(step_dt / chunk)
                got += 1
            if got:
                reqlog.emit("stream", owner[b], n=got,
                            itl_s=step_dt / chunk)
        for b in range(n_slots):
            if owner[b] >= 0 and slot_finished(b):
                retire(b)

    receiver.close()
    wall = time.perf_counter() - t0
    per_request = []
    total_new = 0
    for rid in my_rids:
        nt = len(emitted[rid])
        total_new += nt
        lat = finish[rid] if finish[rid] is not None else wall
        per_request.append(RequestTelemetry(
            rid=rid, ttft_s=ttft[rid] if ttft[rid] is not None else lat,
            latency_s=lat, new_tokens=nt,
            tokens_per_s=nt / lat if lat > 0 else 0.0,
            retries=attempts[rid]))
    metrics = DisaggMetrics(
        requests=len(my_rids), wall_s=wall, new_tokens=total_new,
        tokens_per_s=total_new / wall if wall > 0 else 0.0,
        steps=n_steps, prefills=n_prefills, requeues=n_requeues,
        peer_requeues=n_peer_requeues, hang_dumps=n_hang_dumps,
        ttft_p50_s=_pct([r.ttft_s for r in per_request], 0.50),
        ttft_p99_s=_pct([r.ttft_s for r in per_request], 0.99),
        itl_p50_s=_pct(itl_samples, 0.50),
        itl_p99_s=_pct(itl_samples, 0.99),
        per_request=per_request, handoffs=handoffs)
    return ServedBatch(done, _finish_handoff_metrics(metrics))
