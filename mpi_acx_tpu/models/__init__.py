"""Model families built on the parallel substrate.

The reference ships no models (SURVEY.md §0: "it is not a training
framework") — but its driver-defined target configs are model workloads
(BASELINE.json configs[3,4]: GPT-2 125M and Llama-style pipeline
exchanges). These are those workloads, TPU-native: MXU-shaped matmuls in
bfloat16, static shapes, and parallelism expressed through the
mpi_acx_tpu.parallel primitives.
"""

from mpi_acx_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    gpt2_small,
    tiny_config,
    init_params,
    forward,
    loss_fn,
    init_kv_cache,
    prefill,
    decode_step,
    generate,
    cast_params,
)
from mpi_acx_tpu.models.moe import (  # noqa: F401
    MoeConfig,
    init_moe_params,
    load_balance_loss,
    make_moe_train_step,
    moe_layer,
    moe_layer_and_aux,
    router_z_loss,
)
from mpi_acx_tpu.models import llama  # noqa: F401  (namespaced: llama.forward, ...)
from mpi_acx_tpu.models import moe_transformer  # noqa: F401  (namespaced)
from mpi_acx_tpu.models.speculative import (  # noqa: F401
    speculative_generate,
    speculative_sample,
)
from mpi_acx_tpu.models.serving import (  # noqa: F401
    serve_greedy,
    serve_sample,
)
