"""Mixture-of-experts layer with expert parallelism over a mesh axis.

Top-k (switch-style k=1 / GShard-style k=2) routing with a static capacity
factor: dispatch and combine are einsums against a one-hot dispatch tensor,
so the whole layer is static-shaped for XLA. Expert parallelism shards the
expert dimension over a mesh axis inside shard_map: tokens travel to their
expert's device through ``lax.all_to_all`` (the EP collective), are
transformed by the local experts, and return the same way.

Training support: :func:`load_balance_loss` (the Switch-Transformer
auxiliary loss that keeps routing uniform) and :func:`router_z_loss`
(logit-magnitude regularizer), both exposed together with the layer output
by :func:`moe_layer_and_aux`, and :func:`make_moe_train_step` — a jitted
expert-parallel SGD step over a 1D 'ep' mesh whose loss and gradients are
validated exactly against the single-device layer (tests/test_moe_train.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    d_model: int = 128
    d_ff: int = 256
    n_experts: int = 8
    capacity_factor: float = 2.0
    top_k: int = 1     # experts per token (1 = Switch, 2 = GShard-style)


def init_moe_params(key: jax.Array, cfg: MoeConfig) -> Dict[str, Any]:
    k1, k2, k3 = jax.random.split(key, 3)
    E, d, ff = cfg.n_experts, cfg.d_model, cfg.d_ff
    return {
        "gate": jax.random.normal(k1, (d, E), jnp.float32) * 0.02,
        "w1": jax.random.normal(k2, (E, d, ff), jnp.float32) * 0.02,
        "w2": jax.random.normal(k3, (E, ff, d), jnp.float32) * 0.02,
    }


def _dispatch_tensors(gates: jax.Array, capacity: int, k: int = 1):
    """gates [T, E] -> (dispatch [T, E, C] one-hot, combine [T, E, C]).

    Top-k routing with per-expert capacity C: choice rank 0 (every token's
    best expert) claims queue positions first, then rank 1, etc. — the
    standard priority order, so adding second choices never evicts a
    token's first choice. Tokens past an expert's capacity are dropped
    from that expert (their dispatch/combine rows are zero). Combine
    weights are the router's softmax probabilities of the SURVIVING
    choices (not renormalized — the Switch/GShard convention, which also
    keeps the k=1 path bit-identical to a pure argmax router).
    """
    T, E = gates.shape
    probs = jax.nn.softmax(gates, axis=-1)                    # [T, E]
    _, idx = lax.top_k(gates, k)                              # [T, k]
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    counts = jnp.zeros((E,), jnp.float32)   # queue fill from earlier ranks
    for c in range(k):                      # k is static and tiny
        onehot = jax.nn.one_hot(idx[:, c], E, dtype=jnp.float32)  # [T, E]
        # Position of each token within its expert's queue, after the
        # tokens already enqueued by higher-priority choice ranks.
        pos = ((jnp.cumsum(onehot, axis=0) - 1.0) + counts) * onehot
        keep = pos < capacity
        onehot = onehot * keep
        posc = jax.nn.one_hot(
            pos.sum(-1).astype(jnp.int32), capacity)          # [T, C]
        d_c = onehot[:, :, None] * posc[:, None, :]           # [T, E, C]
        prob = jnp.sum(probs * onehot, -1)                    # [T]
        dispatch = dispatch + d_c
        combine = combine + d_c * prob[:, None, None]
        counts = counts + onehot.sum(0)
    return dispatch, combine


def load_balance_loss(gates: jax.Array, k: int = 1) -> jax.Array:
    """Switch-Transformer auxiliary load-balancing loss on router logits
    [T, E]: ``E * sum_e f_e * p_e`` where f_e is the fraction of (token,
    choice) assignments routed to expert e (pre-capacity) and p_e the mean
    router probability. Equals 1.0 at perfectly uniform routing (its
    minimum over f for fixed uniform p), grows as routing collapses."""
    T, E = gates.shape
    probs = jax.nn.softmax(gates, axis=-1)
    _, idx = lax.top_k(gates, k)                              # [T, k]
    f = jax.nn.one_hot(idx, E, dtype=jnp.float32).sum(1).mean(0)  # [E]
    return E * jnp.sum(f / k * probs.mean(0))


def router_z_loss(gates: jax.Array) -> jax.Array:
    """Mean squared router logsumexp ([T, E] logits) — keeps gate logits
    small so the routing softmax stays in its well-conditioned range
    (the ST-MoE z-loss)."""
    return jnp.mean(jax.nn.logsumexp(gates.astype(jnp.float32), -1) ** 2)


def _route(params, x, cfg: MoeConfig, E: int):
    """Shared routing prologue: (gates [T,E] f32, dispatch, combine, cap).
    THE single source of the capacity formula and dispatch convention —
    every MoE execution path (single-device, sharded-token all_to_all EP,
    replicated-token EP) routes through here, which is what the
    bit-equal-routing guarantees in their docstrings rest on."""
    gates = x.astype(jnp.float32) @ params["gate"]
    cap = int(cfg.capacity_factor * x.shape[0] / E + 1)
    dispatch, combine = _dispatch_tensors(gates, cap, cfg.top_k)
    return gates, dispatch, combine, cap


def _expert_ffn(xin, params):
    """The expert MLP body on [..., E?, C, d] queues (leading axes ride
    einsum ellipses); one definition for every path."""
    h = jax.nn.gelu(jnp.einsum("...ecd,edf->...ecf", xin, params["w1"]))
    return jnp.einsum("...ecf,efd->...ecd", h, params["w2"])


def _moe_forward(params, x, cfg: MoeConfig, ep_axis):
    """Shared forward: returns (y [T, d], gates [T, E] f32 logits)."""
    T, d = x.shape
    e_local = params["w1"].shape[0]
    if ep_axis is None:
        E = e_local
        gates, dispatch, combine, _ = _route(params, x, cfg, E)
        xin = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), dispatch)
        out = _expert_ffn(xin, params)
        return (jnp.einsum("ecd,tec->td", out, combine).astype(x.dtype),
                gates)

    ep = lax.axis_size(ep_axis)
    E = e_local * ep
    # Capacity is per dispatch group (this rank's T tokens) — the GShard
    # convention; with tokens sharded over ep, T here is the local count.
    gates, dispatch, combine, cap = _route(params, x, cfg, E)
    xin = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), dispatch)
    # [E, C, d] -> [ep, E_local, C, d]; all_to_all swaps the ep axis with
    # the device axis so device j holds every sender's slice for ITS
    # experts: afterwards [ep(senders), E_local, C, d].
    xin = xin.reshape(ep, e_local, cap, d)
    xin = lax.all_to_all(xin, ep_axis, split_axis=0, concat_axis=0,
                         tiled=False)
    out = _expert_ffn(xin, params)
    # Route results back to their senders.
    out = lax.all_to_all(out, ep_axis, split_axis=0, concat_axis=0,
                         tiled=False)
    out = out.reshape(E, cap, d)
    return jnp.einsum("ecd,tec->td", out, combine).astype(x.dtype), gates


def moe_layer(params: Dict[str, Any], x: jax.Array, cfg: MoeConfig,
              ep_axis: str | None = None) -> jax.Array:
    """x [T, d] -> [T, d].

    With ep_axis set (inside shard_map), the expert dim of params is the
    LOCAL slice [E/ep, d, ff] and tokens are exchanged by all_to_all:
    the dispatched activations [E, C, d] regroup to [ep, E_local, C, d]
    and all_to_all over the leading axis gives each device every sender's
    slice for ITS experts (BASELINE-style EP). x may be the
    rank's exclusive token shard (standard EP: all_to_all then moves real
    token data between devices) or replicated (each rank redundantly
    routes the same tokens).
    """
    y, _ = _moe_forward(params, x, cfg, ep_axis)
    return y


def _moe_forward_replicated(params, x, cfg: MoeConfig, ep_axis):
    """Replicated-token EP forward: returns (y [T, d], gates [T, E] f32)
    — the shared body of :func:`moe_layer_replicated_ep` and its
    aux-returning twin."""
    T, d = x.shape
    e_local = params["w1"].shape[0]
    ep = lax.axis_size(ep_axis)
    E = e_local * ep
    gates, dispatch, combine, _ = _route(params, x, cfg, E)  # [T, E, C]
    e0 = lax.axis_index(ep_axis) * e_local
    disp_l = lax.dynamic_slice_in_dim(dispatch, e0, e_local, axis=1)
    comb_l = lax.dynamic_slice_in_dim(combine, e0, e_local, axis=1)
    xin = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), disp_l)
    out = _expert_ffn(xin, params)
    part = jnp.einsum("ecd,tec->td", out, comb_l)
    return lax.psum(part, ep_axis).astype(x.dtype), gates


def moe_layer_replicated_ep(params: Dict[str, Any], x: jax.Array,
                            cfg: MoeConfig, ep_axis: str) -> jax.Array:
    """Expert parallelism for REPLICATED tokens (per-shard function).

    When every rank already holds the same x [T, d] (the tensor-parallel
    serving path and the flagship train step's blocks), the all_to_all
    exchange is pure overhead: each rank can route all T tokens itself,
    run only its LOCAL expert block, and let ONE psum assemble the
    combined output — 1/ep the expert FLOPs per rank and one collective
    per layer instead of two all_to_alls over redundant copies. The
    dispatch/combine tensors are computed identically to the
    single-device path, so routing (capacity, drops) is bit-equal.

    Use :func:`moe_layer` with ``ep_axis`` when tokens are SHARDED (the
    dp+ep training layout) — there the all_to_all moves real data.
    """
    y, _ = _moe_forward_replicated(params, x, cfg, ep_axis)
    return y


def moe_layer_replicated_ep_and_aux(params: Dict[str, Any], x: jax.Array,
                                    cfg: MoeConfig, ep_axis: str):
    """:func:`moe_layer_replicated_ep` plus the training auxiliaries
    (computed from the full replicated gates, so every rank holds the
    same aux values — gate/contribute them on ONE rank per replication
    group when assembling an exclusive-path loss)."""
    y, gates = _moe_forward_replicated(params, x, cfg, ep_axis)
    return y, {"load_balance": load_balance_loss(gates, cfg.top_k),
               "router_z": router_z_loss(gates)}


def moe_layer_sharded_dispatch(params: Dict[str, Any], x: jax.Array,
                               cfg: MoeConfig, ep_axis: str) -> jax.Array:
    """REAL expert-parallel dispatch for REPLICATED tokens (per-shard
    function): the serving-side counterpart of the training EP path.

    Where :func:`moe_layer_replicated_ep` has every rank route and
    dispatch ALL T tokens (only the expert FLOPs shard), here each rank
    takes its EXCLUSIVE T/ep token slice, routes just those, and the
    capacity-bounded ``all_to_all`` machinery of :func:`moe_layer`
    carries them to their expert's rank and back — per-rank routed token
    counts genuinely shard (router + dispatch/combine einsums drop from
    T to T/ep tokens per rank). One ``all_gather`` re-replicates the
    outputs for the next attention block.

    Capacity is per dispatch group (each rank's T/ep tokens), so in the
    drop-free regime (``capacity_factor >= n_experts``, the serving
    guard) outputs are token-identical to the single-device layer; with
    tight capacity the drop pattern is per-group, exactly like the dp+ep
    training layout. Requires ``T % ep == 0`` (shapes are static — this
    raises at trace time).
    """
    ep = lax.axis_size(ep_axis)
    T, d = x.shape
    if T % ep != 0:
        raise ValueError(
            f"sharded EP dispatch needs tokens ({T}) % ep ({ep}) == 0; "
            f"use moe_layer_replicated_ep for indivisible shapes")
    Tl = T // ep
    r = lax.axis_index(ep_axis)
    xl = lax.dynamic_slice_in_dim(x, r * Tl, Tl, axis=0)
    yl = moe_layer(params, xl, cfg, ep_axis=ep_axis)
    return lax.all_gather(yl, ep_axis, axis=0, tiled=True)


def moe_layer_and_aux(params: Dict[str, Any], x: jax.Array, cfg: MoeConfig,
                      ep_axis: str | None = None):
    """Like :func:`moe_layer` but also returns the training auxiliaries
    computed from this rank's router logits:
    ``(y, {"load_balance": .., "router_z": ..})``."""
    y, gates = _moe_forward(params, x, cfg, ep_axis)
    return y, {"load_balance": load_balance_loss(gates, cfg.top_k),
               "router_z": router_z_loss(gates)}


def make_moe_train_step(cfg: MoeConfig, mesh, ep_axis: str = "ep",
                        lr: float = 0.1, aux_weight: float = 1e-2,
                        z_weight: float = 1e-3):
    """Expert-parallel SGD train step over a 1D ``ep`` mesh.

    Returns a jitted ``step(params, x, targets) -> (loss, new_params)``
    with x/targets [T, d] sharded over ``ep`` (each device routes its own
    token shard; all_to_all carries tokens to their expert's device and
    back), gate replicated, expert weights sharded. Loss = global mean
    squared error + aux_weight * load-balance + z_weight * router-z.

    Gradient construction mirrors mpi_acx_tpu.train.make_loss_and_grads:
    every rank's loss terms cover only its EXCLUSIVE token shard and the
    scalar is assembled by psum, so each parameter cotangent path is
    unique; under check_vma=False the psum transpose uniformly scales all
    cotangents by ep (undone explicitly), after which the replicated gate
    needs one psum and the expert-sharded leaves none.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    ep_n = mesh.shape[ep_axis]
    assert cfg.n_experts % ep_n == 0, (
        f"n_experts ({cfg.n_experts}) must divide by the {ep_axis!r} mesh "
        f"axis ({ep_n})")

    def per_shard(params, x, tgt):
        def loss_fn(params):
            y, aux = moe_layer_and_aux(params, x, cfg, ep_axis=ep_axis)
            se = jnp.sum((y.astype(jnp.float32) -
                          tgt.astype(jnp.float32)) ** 2)
            raw = (se / (x.shape[1] * x.shape[0] * ep_n)
                   + (aux_weight * aux["load_balance"]
                      + z_weight * aux["router_z"]) / ep_n)
            return lax.psum(raw, ep_axis)

        loss, g = jax.value_and_grad(loss_fn)(params)
        g = jax.tree.map(lambda t: t / ep_n, g)   # undo psum seed scaling
        g = dict(g, gate=lax.psum(g["gate"], ep_axis))
        return loss, g

    pspecs = {"gate": P(), "w1": P(ep_axis), "w2": P(ep_axis)}
    grad_fn = shard_map(per_shard, mesh=mesh,
                        in_specs=(pspecs, P(ep_axis), P(ep_axis)),
                        out_specs=(P(), pspecs), check_vma=False)

    @jax.jit
    def step(params, x, tgt):
        loss, g = grad_fn(params, x, tgt)
        return loss, jax.tree.map(lambda p, gg: p - lr * gg, params, g)

    return step
