"""Mixture-of-experts layer with expert parallelism over a mesh axis.

Top-1 (switch-style) routing with a static capacity factor: dispatch and
combine are einsums against a one-hot dispatch tensor, so the whole layer
is static-shaped for XLA. Expert parallelism shards the expert dimension
over a mesh axis inside shard_map: tokens travel to their expert's device
through ``lax.all_to_all`` (the EP collective), are transformed by the
local experts, and return the same way.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    d_model: int = 128
    d_ff: int = 256
    n_experts: int = 8
    capacity_factor: float = 2.0


def init_moe_params(key: jax.Array, cfg: MoeConfig) -> Dict[str, Any]:
    k1, k2, k3 = jax.random.split(key, 3)
    E, d, ff = cfg.n_experts, cfg.d_model, cfg.d_ff
    return {
        "gate": jax.random.normal(k1, (d, E), jnp.float32) * 0.02,
        "w1": jax.random.normal(k2, (E, d, ff), jnp.float32) * 0.02,
        "w2": jax.random.normal(k3, (E, ff, d), jnp.float32) * 0.02,
    }


def _dispatch_tensors(gates: jax.Array, capacity: int):
    """gates [T, E] -> (dispatch [T, E, C] one-hot, combine [T, E, C])."""
    T, E = gates.shape
    expert = jnp.argmax(gates, axis=-1)                       # [T]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)     # [T, E]
    # Position of each token within its expert's queue.
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot         # [T, E]
    keep = pos < capacity
    onehot = onehot * keep
    posc = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), capacity)  # [T, C]
    dispatch = onehot[:, :, None] * posc[:, None, :]          # [T, E, C]
    prob = jnp.sum(jax.nn.softmax(gates, axis=-1) * onehot, -1)  # [T]
    combine = dispatch * prob[:, None, None]
    return dispatch, combine


def moe_layer(params: Dict[str, Any], x: jax.Array, cfg: MoeConfig,
              ep_axis: str | None = None) -> jax.Array:
    """x [T, d] -> [T, d].

    With ep_axis set (inside shard_map), the expert dim of params is the
    LOCAL slice [E/ep, d, ff] and tokens are exchanged by all_to_all:
    dispatch [T, E_local*ep, C] -> regroup to [ep, T, E_local, C] ->
    all_to_all over the leading axis, so each device receives every
    device's tokens for ITS experts (BASELINE-style EP).
    """
    T, d = x.shape
    gates = x.astype(jnp.float32) @ params["gate"]
    e_local = params["w1"].shape[0]
    if ep_axis is None:
        E = e_local
        cap = int(cfg.capacity_factor * T / E + 1)
        dispatch, combine = _dispatch_tensors(gates, cap)
        xin = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), dispatch)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin, params["w1"]))
        out = jnp.einsum("ecf,efd->ecd", h, params["w2"])
        return jnp.einsum("ecd,tec->td", out, combine).astype(x.dtype)

    ep = lax.axis_size(ep_axis)
    E = e_local * ep
    cap = int(cfg.capacity_factor * T / E + 1)
    dispatch, combine = _dispatch_tensors(gates, cap)          # [T, E, C]
    xin = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), dispatch)
    # [E, C, d] -> [ep, E_local, C, d]; all_to_all swaps the ep axis with
    # the device axis so device j holds every sender's slice for ITS
    # experts: afterwards [ep(senders), E_local, C, d].
    xin = xin.reshape(ep, e_local, cap, d)
    xin = lax.all_to_all(xin, ep_axis, split_axis=0, concat_axis=0,
                         tiled=False)
    h = jax.nn.gelu(jnp.einsum("secd,edf->secf", xin, params["w1"]))
    out = jnp.einsum("secf,efd->secd", h, params["w2"])
    # Route results back to their senders.
    out = lax.all_to_all(out, ep_axis, split_axis=0, concat_axis=0,
                         tiled=False)
    out = out.reshape(E, cap, d)
    return jnp.einsum("ecd,tec->td", out, combine).astype(x.dtype)
