"""Paged KV cache: block-table allocator + radix prefix sharing.

The serving stack (models/serving.py) and the disagg decode workers
(models/disagg.py) historically gave every slot a private
``[max_len]`` cache row, so a request at position 40 of a 4096-token
cache owned 4096 positions of HBM even though the flash-decode kernel
no longer *reads* the dead tail — at scale the server is
memory-capacity-bound, not compute-bound. This module replaces the
per-slot rows with a shared pool of fixed-size PAGES (default 128
tokens, matching flash-decode's block granularity) and a per-slot
block table:

* **Pool** — ``{'k','v': [L, P, page_tokens, H, Dh]}`` device buffers
  (plus ``'ks','vs'`` f32 scale pages when the cache is int8 —
  ops/kvquant.py codes + scales stay the only page-resident form, the
  same EQuARX rule the wire plane enforces). The trailing ``n_slots``
  pages of P are per-slot PARKING pages: an idle slot's table points
  every entry at its own parking page, so the lockstep decode step's
  writes for idle slots land somewhere harmless instead of corrupting
  pages a live request owns.
* **Block tables** — host-side ``[n_slots, max_pages]`` int32 rows
  (mirrored to the device per step) mapping token position
  ``t`` of slot ``b`` to pool page ``table[b, t // page_tokens]``.
* **Allocator** — :class:`PageAllocator`: a free list plus per-page
  refcounts; pages are shared by refcount and reclaimed at zero.
* **Radix prefix cache** — :class:`RadixPrefixCache`: a trie over
  full-page token chunks, so requests sharing a system prompt store
  the shared pages ONCE; a prefix hit seats the cached pages and
  prefill runs only on the suffix (:func:`prefill_with_history`).
  Shared pages are never written (the matched depth is capped so the
  suffix always starts at a page boundary with >= 1 fresh token);
  copy-on-write (:meth:`PagedKV.ensure_writable`) guards the
  invariant defensively.

Bit-equality contract: the paged dense attend gathers the slot's
pages into the SAME ``[B, max_len, H, Dh]`` shape the fixed-slot path
attends (mpi_acx_tpu/ops/flash_decode.py:paged_gather_attend), so on
a cold (no-prefix-hit) schedule paged greedy serving is bit-equal to
fixed-slot ``serve_greedy`` — dead gathered positions contribute
exactly 0.0 through the masked softmax (finite garbage, never NaN).
Prefix-HIT prefills compute the suffix against the stored pages with
different tensor shapes than the cold full-prompt pass, so hit-path
outputs are deterministic per backend but not bitwise-pinned to the
cold path (docs/DESIGN.md §19).

The paged decode step is transformer-family-scoped (the
``make_layerwise_prefill_fns`` precedent in models/disagg.py): it
closes over the GPT-2 block internals. Other families raise loudly.
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mpi_acx_tpu import reqlog


def default_page_tokens(max_len: int) -> int:
    """Default page size: ``$ACX_KV_PAGE_TOKENS`` (128 unset — the
    flash-decode block granularity), stepped down to the largest
    divisor of ``max_len`` so the table tiles the cache exactly."""
    want = int(os.environ.get("ACX_KV_PAGE_TOKENS", "128") or "128")
    want = max(1, min(want, max_len))
    while max_len % want:
        want -= 1
    return want


def pages_needed(tokens: int, page_tokens: int) -> int:
    return -(-int(tokens) // page_tokens)            # ceil div


# --------------------------------------------------------------------------
# Allocator


class PageAllocator:
    """Host-side page bookkeeping: a deterministic (lowest-id-first)
    free list plus per-page refcounts. All-or-nothing allocation; a
    page is reclaimed exactly when its refcount reaches zero."""

    def __init__(self, n_pages: int):
        assert n_pages >= 1, n_pages
        self.n_pages = int(n_pages)
        # pop() takes from the end; storing descending ids hands out
        # page 0 first — deterministic layouts for reproducible tests.
        self._free = list(range(self.n_pages - 1, -1, -1))
        self._ref = [0] * self.n_pages

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.n_pages - len(self._free)

    def shared_count(self) -> int:
        """Pages referenced by more than one owner (slot or trie)."""
        return sum(1 for r in self._ref if r > 1)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages at refcount 1, or None (nothing allocated)
        when fewer than n are free."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def incref(self, page: int) -> None:
        assert self._ref[page] > 0, (page, "incref of a free page")
        self._ref[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one reference; True iff the page was reclaimed."""
        assert self._ref[page] > 0, (page, "decref of a free page")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)
            # Keep the free list sorted descending so reclaimed pages
            # re-issue lowest-first too (determinism under churn).
            self._free.sort(reverse=True)
            return True
        return False


# --------------------------------------------------------------------------
# Radix prefix cache


class _TrieNode:
    __slots__ = ("children", "page", "stamp")

    def __init__(self, page: int = -1):
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.page = page
        self.stamp = 0


class RadixPrefixCache:
    """Trie over FULL-PAGE token chunks. ``match`` walks the prompt's
    complete pages and increfs every page on the matched path (the
    caller owns those references until it releases the slot);
    ``insert`` adopts a served request's prompt pages into the trie
    (incref — the trie is an owner like any slot). Eviction removes
    least-recently-matched LEAVES only, so an interior page can never
    outlive a cached extension of it.

    Invariant (why shared pages are never written): ``match`` caps the
    hit depth at ``(S - 1) // page_tokens`` — the suffix keeps >= 1
    token and starts exactly at a page boundary, so every position a
    prefill or decode write touches lands in a freshly allocated page.
    """

    def __init__(self, alloc: PageAllocator, page_tokens: int):
        self.alloc = alloc
        self.page_tokens = page_tokens
        self.root = _TrieNode()
        self._clock = 0
        self.hits = 0            # matches with depth >= 1 page
        self.evictions = 0       # pages evicted (LRU leaves)
        self.pages_reused = 0    # cumulative pages handed out by match

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, prompt: np.ndarray) -> List[int]:
        """Longest cached full-page prefix of ``prompt``; increfs and
        returns its pages (possibly empty). Depth capped so at least
        one suffix token remains (see class docstring)."""
        max_depth = (len(prompt) - 1) // self.page_tokens
        node, pages = self.root, []
        stamp = self._tick()
        for d in range(max_depth):
            chunk = tuple(
                int(t) for t in
                prompt[d * self.page_tokens:(d + 1) * self.page_tokens])
            nxt = node.children.get(chunk)
            if nxt is None:
                break
            nxt.stamp = stamp
            pages.append(nxt.page)
            node = nxt
        for p in pages:
            self.alloc.incref(p)
        if pages:
            self.hits += 1
            self.pages_reused += len(pages)
        return pages

    def insert(self, prompt: np.ndarray, pages: List[int]) -> int:
        """Adopt the prompt's full pages (``pages[d]`` backs chunk d)
        into the trie; returns how many pages were newly adopted."""
        node, adopted = self.root, 0
        stamp = self._tick()
        n_full = len(prompt) // self.page_tokens
        for d in range(min(n_full, len(pages))):
            chunk = tuple(
                int(t) for t in
                prompt[d * self.page_tokens:(d + 1) * self.page_tokens])
            nxt = node.children.get(chunk)
            if nxt is None:
                nxt = _TrieNode(pages[d])
                node.children[chunk] = nxt
                self.alloc.incref(pages[d])
                adopted += 1
            nxt.stamp = stamp
            node = nxt
        return adopted

    def evict_one(self) -> bool:
        """Drop the least-recently-matched leaf (decref its page).
        Returns False when the trie is empty."""
        best = None  # (stamp, parent, key, node)
        stack = [(self.root, None, None)]
        while stack:
            node, parent, key = stack.pop()
            if parent is not None and not node.children:
                if best is None or node.stamp < best[0]:
                    best = (node.stamp, parent, key, node)
            for k, ch in node.children.items():
                stack.append((ch, node, k))
        if best is None:
            return False
        _, parent, key, node = best
        del parent.children[key]
        self.alloc.decref(node.page)
        self.evictions += 1
        return True


# --------------------------------------------------------------------------
# Device pool


def init_page_pool(cfg, n_pages: int, page_tokens: int, n_slots: int,
                   kv_int8: bool = False):
    """Zeroed page pool: ``{'k','v': [L, P, page_tokens, H, Dh]}``
    (+ ``'ks','vs'`` f32 scale pages when int8) with
    ``P = n_pages + n_slots`` — the trailing ``n_slots`` pages are the
    per-slot parking pages (module docstring), outside the allocator."""
    P = n_pages + n_slots
    shape = (cfg.n_layers, P, page_tokens, cfg.n_heads, cfg.head_dim)
    pool = {
        "k": jnp.zeros(shape, jnp.int8 if kv_int8 else cfg.dtype),
        "v": jnp.zeros(shape, jnp.int8 if kv_int8 else cfg.dtype),
    }
    if kv_int8:
        pool["ks"] = jnp.zeros(shape[:-1] + (1,), jnp.float32)
        pool["vs"] = jnp.zeros(shape[:-1] + (1,), jnp.float32)
    return pool


_POOL_KEYS = ("k", "v", "ks", "vs")


def _check_family(family) -> None:
    name = getattr(family, "__name__", "").rsplit(".", 1)[-1]
    if family is not None and name != "transformer":
        raise NotImplementedError(
            "paged KV serving closes over the GPT-2 block internals "
            "(the make_layerwise_prefill_fns precedent); family "
            f"{name!r} is not wired yet — use models.transformer")


# --------------------------------------------------------------------------
# Paged decode step (transformer family)


def paged_decode_step(params, cfg, state, token, page_tokens: int,
                      ffn=None):
    """One autoregressive step against the page pool; mirrors
    ``transformer.decode_step`` exactly (same _qkv/attend/ffn math, so
    active slots are bit-equal to the fixed-slot step) with the cache
    writes routed through the block table: layer i's fresh K/V for
    slot b lands at ``pool[i, table[b, pos_b // pt], pos_b % pt]``.
    ``state`` = pool keys + ``'table'`` [B, max_pages] + ``'pos'``
    [B]. Idle slots write their parking page (their table rows point
    nowhere else) and the page index is clipped so a long-idle slot's
    walking pos can never index past its table row."""
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.ops.flash_decode import select_paged_decode_attend
    from mpi_acx_tpu.ops.kvquant import kv_quant
    from mpi_acx_tpu.ops.wquant import wread

    ffn = ffn or tfm._mlp
    table, pos = state["table"], state["pos"]
    B, max_pages = table.shape
    quant = "ks" in state
    pe = params["pos"][pos][:, None, :]
    x = (params["embed"][token][:, None, :] + pe).astype(cfg.dtype)

    write_page = jnp.take_along_axis(
        table, jnp.minimum(pos // page_tokens, max_pages - 1)[:, None],
        axis=1)[:, 0]                                  # [B]
    off = pos % page_tokens

    def write(pool, fresh, i):
        """pool [L, P, pt, H, *]; fresh [B, 1, H, *] -> slot b's row
        (write_page[b], off[b]). Distinct pages per slot (each slot
        owns its pages; idle slots own their parking page), so the
        scatter never collides."""
        layer = lax.dynamic_index_in_dim(pool, i, 0, keepdims=False)
        layer = layer.at[write_page, off].set(
            fresh[:, 0].astype(pool.dtype))
        return lax.dynamic_update_index_in_dim(pool, layer, i, 0)

    attend = select_paged_decode_attend(cfg.decode_flash)

    def body(carry, i):
        if quant:
            x, kp, vp, ksp, vsp = carry
        else:
            x, kp, vp = carry
        lp = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            params["layers"])
        q, k, v = tfm._qkv(cfg, lp, x)
        if quant:
            k, ks = kv_quant(k)
            v, vs = kv_quant(v)
            ksp = write(ksp, ks, i)
            vsp = write(vsp, vs, i)
        kp = write(kp, k, i)
        vp = write(vp, v, i)
        kl = lax.dynamic_index_in_dim(kp, i, 0, keepdims=False)
        vl = lax.dynamic_index_in_dim(vp, i, 0, keepdims=False)
        if quant:
            kl = (kl, lax.dynamic_index_in_dim(ksp, i, 0, keepdims=False))
            vl = (vl, lax.dynamic_index_in_dim(vsp, i, 0, keepdims=False))
        o = attend(q, kl, vl, table, pos, page_tokens, 1)
        x = ffn(cfg, lp, x + o @ wread(lp, "wo", x.dtype))
        if quant:
            return (x, kp, vp, ksp, vsp), None
        return (x, kp, vp), None

    n_layers = jax.tree.leaves(params["layers"])[0].shape[0]
    if quant:
        carry = (x, state["k"], state["v"], state["ks"], state["vs"])
        (x, kp, vp, ksp, vsp), _ = lax.scan(body, carry,
                                            jnp.arange(n_layers))
        out = {"k": kp, "v": vp, "ks": ksp, "vs": vsp}
    else:
        (x, kp, vp), _ = lax.scan(body, (x, state["k"], state["v"]),
                                  jnp.arange(n_layers))
        out = {"k": kp, "v": vp}
    out["table"] = table
    out["pos"] = pos + 1
    x = tfm.layernorm(x, params["lnf_g"], params["lnf_b"])
    logits = jnp.einsum("bsd,vd->bsv", x,
                        params["embed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)[:, 0]
    return logits, out


def make_paged_step_fn(params, cfg, family, chunk: int,
                       page_tokens: int):
    """Jitted chunked decode step over the paged state (the paged
    sibling of make_server_fns' step_fn — greedy only; the state is
    donated so XLA updates the pool in place)."""
    _check_family(family)

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, tok, keys):
        def one(carry, _):
            state, tok, keys = carry
            logits, state = paged_decode_step(params, cfg, state, tok,
                                              page_tokens)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (state, nxt, keys), nxt
        (state, _, keys), toks = lax.scan(one, (state, tok, keys), None,
                                          length=chunk)
        return state, toks, keys

    return step_fn


# --------------------------------------------------------------------------
# Prefix-hit suffix prefill


def prefill_with_history(params, cfg, suffix, hk, hv, last_index,
                         ffn=None):
    """Prefill ONLY the suffix of a prompt whose first ``P`` tokens'
    K/V are already paged in (a radix prefix hit): ``suffix``
    [1, S_suf] tokens occupying absolute positions ``P..P+S_suf-1``,
    ``hk``/``hv`` [L, P, H, Dh] the gathered (dequantized) history.
    Per layer the suffix queries attend ``concat(history, suffix)``
    through the shared :func:`dense_decode_attend` definition (pos=P
    scalar — row w sees cols <= P + w, full history + causal suffix).
    Returns (logits [1, 1, vocab] at ``last_index``, suffix K/V
    [L, 1, S_suf, H, Dh] in compute dtype, ready for page scatter).

    The compute skipped is the point: a hit at depth P runs S_suf
    rows through the trunk instead of P + S_suf. The cost is bitwise
    freedom — the concat shapes differ from the cold full-prompt
    pass, so hit-path logits match cold only to numerics (docs/
    DESIGN.md §19)."""
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.models.decoding import dense_decode_attend
    from mpi_acx_tpu.ops.wquant import wread

    ffn = ffn or tfm._mlp
    B, Sb = suffix.shape
    P = hk.shape[1]
    x = (params["embed"][suffix]
         + params["pos"][P:P + Sb]).astype(cfg.dtype)

    def body(x, xs):
        lp, hkl, hvl = xs
        q, k, v = tfm._qkv(cfg, lp, x)
        kcat = jnp.concatenate([hkl[None].astype(x.dtype), k], axis=1)
        vcat = jnp.concatenate([hvl[None].astype(x.dtype), v], axis=1)
        o = dense_decode_attend(q, kcat, vcat, P, P + Sb, 1)
        x = x + o @ wread(lp, "wo", x.dtype)
        return ffn(cfg, lp, x), (k, v)

    x, (ks, vs) = lax.scan(body, x, (params["layers"], hk, hv))
    x = tfm.layernorm(x, params["lnf_g"], params["lnf_b"])
    x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
    logits = jnp.einsum("bsd,vd->bsv", x,
                        params["embed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
    return logits, ks, vs


# --------------------------------------------------------------------------
# Host-side paged state manager


class PagedKV:
    """The serving scheduler's view of the page plane: device pool +
    host block tables + allocator + (optional) radix prefix cache.
    The scheduler calls the seat/grow/release methods; the jitted step
    consumes :meth:`device_state` and hands the donated result back
    through :meth:`absorb`."""

    def __init__(self, cfg, family, n_slots: int, max_len: int,
                 page_tokens: int, n_pages: int, kv_int8: bool = False,
                 prefix_cache: bool = False):
        assert max_len % page_tokens == 0, \
            (f"max_len={max_len} must be a multiple of "
             f"page_tokens={page_tokens} (the block table tiles the "
             "cache exactly)")
        _check_family(family)
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.max_len = int(max_len)
        self.page_tokens = int(page_tokens)
        self.n_pages = int(n_pages)
        self.max_pages = max_len // page_tokens
        self.kv_int8 = bool(kv_int8)
        self.alloc = PageAllocator(n_pages)
        self.prefix = (RadixPrefixCache(self.alloc, page_tokens)
                       if prefix_cache else None)
        self.pool = init_page_pool(cfg, n_pages, page_tokens, n_slots,
                                   kv_int8=kv_int8)
        # Slot b's parking page sits past the allocator's range.
        self._park = [n_pages + b for b in range(n_slots)]
        self.pages: List[List[int]] = [[] for _ in range(n_slots)]
        self.pos = np.zeros((n_slots,), np.int32)
        self.table = np.asarray(
            [[self._park[b]] * self.max_pages
             for b in range(n_slots)], np.int32)
        self._dev_table = None
        self.pages_hwm = 0
        self.preemptions = 0
        self._scatter_cache: Dict = {}
        self._gather_cache: Dict = {}
        self._copy_fn = None

    # -- device state ------------------------------------------------------

    def device_state(self):
        if self._dev_table is None:
            self._dev_table = jnp.asarray(self.table)
        state = dict(self.pool)
        state["table"] = self._dev_table
        state["pos"] = jnp.asarray(self.pos)
        return state

    def absorb(self, state) -> None:
        self.pool = {k: state[k] for k in _POOL_KEYS if k in state}
        self._dev_table = state["table"]
        # np.array (copy): np.asarray of a device array is a read-only
        # view, and the host mirror gets written by seat/release.
        self.pos = np.array(state["pos"], np.int32)

    def reset_pool(self) -> None:
        """Rebuild the device pool from zeros (after a failed donated
        step the buffers can't be trusted) and drop every reference —
        allocator, tables, and the prefix cache start over."""
        self.pool = init_page_pool(self.cfg, self.n_pages,
                                   self.page_tokens, self.n_slots,
                                   kv_int8=self.kv_int8)
        self.alloc = PageAllocator(self.n_pages)
        if self.prefix is not None:
            hits, ev, reused = (self.prefix.hits, self.prefix.evictions,
                                self.prefix.pages_reused)
            self.prefix = RadixPrefixCache(self.alloc, self.page_tokens)
            self.prefix.hits, self.prefix.evictions = hits, ev
            self.prefix.pages_reused = reused
        self.pages = [[] for _ in range(self.n_slots)]
        self.pos = np.zeros((self.n_slots,), np.int32)
        self.table = np.asarray(
            [[self._park[b]] * self.max_pages
             for b in range(self.n_slots)], np.int32)
        self._dev_table = None

    # -- table bookkeeping -------------------------------------------------

    def _sync_row(self, b: int) -> None:
        row = self.pages[b] + [self._park[b]] * (self.max_pages
                                                 - len(self.pages[b]))
        self.table[b] = np.asarray(row, np.int32)
        self._dev_table = None

    def _note_hwm(self) -> None:
        self.pages_hwm = max(self.pages_hwm, self.alloc.used_count)

    def alloc_evicting(self, n: int) -> Optional[List[int]]:
        """Allocate n pages, evicting prefix-cache LRU leaves to make
        room; None when the pool can't cover n even fully drained."""
        while self.alloc.free_count < n:
            if self.prefix is None or not self.prefix.evict_one():
                return None
        got = self.alloc.alloc(n)
        if got is not None:
            self._note_hwm()
        return got

    def seat(self, b: int, prompt_pages: List[int],
             fresh_pages: List[int], new_pos: int, rid: int = -1) -> None:
        """Slot b takes ownership of ``prompt_pages + fresh_pages``
        (references already held by the caller) at position
        ``new_pos``. ``rid`` only labels the journey event (ACX_REQLOG,
        docs/DESIGN.md §20) — the allocator itself is request-blind."""
        assert not self.pages[b], (b, "seat of an occupied slot")
        self.pages[b] = list(prompt_pages) + list(fresh_pages)
        assert len(self.pages[b]) <= self.max_pages, \
            (b, len(self.pages[b]), self.max_pages)
        self.pos[b] = new_pos
        reqlog.emit("seat", rid, slot=b, pages=len(self.pages[b]),
                    shared=len(prompt_pages), pos=new_pos)
        self._sync_row(b)

    def release(self, b: int) -> None:
        """Drop slot b's page references (shared prefix pages survive
        through the trie's reference) and park the slot."""
        for p in self.pages[b]:
            self.alloc.decref(p)
        self.pages[b] = []
        self.pos[b] = 0
        self._sync_row(b)

    def grow(self, b: int, need_pages: int) -> bool:
        """Extend slot b's page list to ``need_pages``; False when the
        pool is dry even after prefix eviction (caller preempts)."""
        need_pages = min(need_pages, self.max_pages)
        short = need_pages - len(self.pages[b])
        if short <= 0:
            return True
        got = self.alloc_evicting(short)
        if got is None:
            return False
        self.pages[b].extend(got)
        self._sync_row(b)
        return True

    def ensure_writable(self, b: int, j: int) -> bool:
        """Copy-on-write: if slot b's page j is shared (refcount > 1),
        give the slot a private copy. Unreachable under the default
        policy (RadixPrefixCache docstring) — kept as the defensive
        guard the scheduler runs before decode writes. Returns True
        iff a copy was made."""
        page = self.pages[b][j]
        if self.alloc.refcount(page) <= 1:
            return False
        got = self.alloc_evicting(1)
        if got is None:
            raise RuntimeError(
                "copy-on-write with a dry pool (admission should have "
                "bounded the request)")
        if self._copy_fn is None:
            @partial(jax.jit, donate_argnums=(0,))
            def _copy(pool, src, dst):
                out = {}
                for key in pool:
                    page_data = lax.dynamic_index_in_dim(
                        pool[key], src, 1, keepdims=True)
                    out[key] = lax.dynamic_update_slice(
                        pool[key], page_data, (0, dst, 0, 0, 0))
                return out
            self._copy_fn = _copy
        self.pool = self._copy_fn(self.pool, jnp.int32(page),
                                  jnp.int32(got[0]))
        self.pages[b][j] = got[0]
        self.alloc.decref(page)
        self._sync_row(b)
        return True

    # -- prompt scatter / history gather -----------------------------------

    def scatter_prompt(self, one, pages: List[int], start_page: int = 0
                       ) -> None:
        """Write a prefilled cache (``one`` = {'k','v'[,'ks','vs']:
        [L, 1, S_bucket, H, *]}) into ``pages`` — page d takes bucket
        rows [d*pt, (d+1)*pt) (zero-padded rows past the prompt are
        never attended). ``start_page`` offsets the SOURCE rows only
        (0 for a cold full-prompt scatter; unused pages cost
        nothing — only ``len(pages)`` pages are written)."""
        pt = self.page_tokens
        bucket = one["k"].shape[2]
        keys = tuple(k for k in _POOL_KEYS if k in one and k in self.pool)
        ck = (bucket, len(pages), keys)
        if ck not in self._scatter_cache:
            @partial(jax.jit, donate_argnums=(0,))
            def _scatter(pool, one, pages_arr, n_pg=len(pages),
                         bucket=bucket, keys=keys):
                for j in range(n_pg):
                    n = min(pt, bucket - j * pt)
                    if n <= 0:
                        break
                    for key in keys:
                        src = one[key][:, 0, j * pt:j * pt + n]
                        pool[key] = lax.dynamic_update_slice(
                            pool[key], src[:, None].astype(
                                pool[key].dtype),
                            (0, pages_arr[j], 0, 0, 0))
                return pool
            self._scatter_cache[ck] = _scatter
        if pages:
            self.pool = self._scatter_cache[ck](
                self.pool, one, jnp.asarray(pages, jnp.int32))

    def gather_history(self, pages: List[int]):
        """Gather ``pages`` into contiguous [L, n*pt, H, Dh] history
        K/V in compute dtype (dequantizing int8 pages — the only
        page-resident form — through their f32 scales)."""
        ck = len(pages)
        if ck not in self._gather_cache:
            @jax.jit
            def _gather(pool, pages_arr):
                def grab(key):
                    return jnp.take(pool[key], pages_arr, axis=1)
                k, v = grab("k"), grab("v")
                if "ks" in pool:
                    k = k.astype(jnp.float32) * grab("ks")
                    v = v.astype(jnp.float32) * grab("vs")
                L = k.shape[0]
                shp = (L, ck * self.page_tokens) + k.shape[3:]
                return (k.reshape(shp).astype(self.cfg.dtype),
                        v.reshape(shp).astype(self.cfg.dtype))
            self._gather_cache[ck] = _gather
        return self._gather_cache[ck](
            self.pool, jnp.asarray(pages, jnp.int32))


# --------------------------------------------------------------------------
# Native-metrics publication (no-build/no-load discipline)


def publish_page_stats_best_effort(pages_free: int, pages_shared: int,
                                   prefix_hits: int,
                                   prefix_evictions: int,
                                   preemptions: int) -> bool:
    """Mirror the page plane into the native registry gauges/counters
    (src/core/metrics.cc: pages_free, pages_shared, prefix_hits,
    prefix_evictions, preemptions) — but only when the native runtime
    is already loaded; never build or load the library for telemetry
    (the ``_flight_dump_best_effort`` discipline)."""
    try:
        import ctypes
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None:
            return False
        _rt._lib.acx_serving_page_stats(
            ctypes.c_uint64(pages_free), ctypes.c_uint64(pages_shared),
            ctypes.c_uint64(prefix_hits),
            ctypes.c_uint64(prefix_evictions),
            ctypes.c_uint64(preemptions))
        return True
    except Exception:  # pragma: no cover — diagnostics must never raise
        return False
