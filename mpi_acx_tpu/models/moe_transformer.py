"""Switch-style MoE transformer: GPT-2 attention, mixture-of-experts FFN.

The third model family (after GPT-2 and Llama), built from the same
primitives: every block is causal self-attention (flash/dense via the
shared ops.attention policy) followed by a top-k routed expert FFN
(models.moe). The layer stack is a ``lax.scan`` over stacked layer
parameters — one compiled block body — with the router auxiliary losses
(load-balance, router-z) accumulated through the scan carry.

Distributed training uses the classic DP+EP layout: ONE mesh axis carries
both the batch shard and the expert shard (experts live across the
data-parallel ranks; ``lax.all_to_all`` moves tokens to their expert's
rank and back inside each block). :func:`make_moe_transformer_train_step`
builds the jitted step; tests/test_moe_train.py validates its loss and
every updated parameter exactly against the identical math on one device.

The reference ships no models at all (SURVEY.md §0) — model families are
this framework's application layer over the communication substrate, the
workloads its BASELINE configs describe.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from mpi_acx_tpu.ops.wquant import wread

from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.moe import (MoeConfig, moe_layer,
                                    moe_layer_and_aux,
                                    moe_layer_replicated_ep)


@dataclasses.dataclass(frozen=True)
class MoeTransformerConfig:
    vocab: int = 50257
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072          # per-expert FFN width
    n_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 2.0
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16
    use_flash: Optional[bool] = None
    decode_flash: Optional[bool] = None  # decode kernel; None = auto

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def moe(self) -> MoeConfig:
        return MoeConfig(d_model=self.d_model, d_ff=self.d_ff,
                         n_experts=self.n_experts,
                         capacity_factor=self.capacity_factor,
                         top_k=self.top_k)


def tiny_moe_config(vocab: int = 256, d_model: int = 32, n_heads: int = 2,
                    n_layers: int = 2, d_ff: int = 64, n_experts: int = 8,
                    top_k: int = 1, capacity_factor: float = 2.0,
                    max_seq: int = 64) -> MoeTransformerConfig:
    return MoeTransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        d_ff=d_ff, n_experts=n_experts, top_k=top_k,
        capacity_factor=capacity_factor, max_seq=max_seq)


def init_params(key: jax.Array, cfg: MoeTransformerConfig) -> Dict[str, Any]:
    """Stacked-layer pytree like transformer.init_params: every per-layer
    tensor has a leading [n_layers] axis; expert tensors additionally
    carry the [n_experts] axis EP shards."""
    k = jax.random.split(key, 7)
    L, d, ff, E = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.n_experts
    s = 0.02

    def nrm(key, *shape, scale=s):
        return jax.random.normal(key, shape, jnp.float32) * scale

    return {
        "embed": nrm(k[0], cfg.vocab, d),
        "pos": nrm(k[1], cfg.max_seq, d),
        "lnf_g": jnp.ones((d,)), "lnf_b": jnp.zeros((d,)),
        "layers": {
            "ln1_g": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
            "wqkv": nrm(k[2], L, d, 3 * d),
            "wo": nrm(k[3], L, d, d),
            "ln2_g": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
            "gate": nrm(k[4], L, d, E),
            "w1": nrm(k[5], L, E, d, ff),
            "w2": nrm(k[6], L, E, ff, d),
        },
    }


def _reject_quantized_experts(lp: Dict[str, Any]):
    """The expert einsums read w1/w2 directly (no ops.wquant.wread path
    yet) — refuse int8 weight-only checkpoints LOUDLY at every MoE FFN
    entry (block() and _moe_ffn) rather than multiply raw codes without
    their scales. A raise, not an assert: python -O must not strip it."""
    if "w1_scale" in lp or "w2_scale" in lp:
        raise ValueError(
            "MoE expert weights do not support int8 weight-only "
            "quantization (ops/wquant.py is the dense serving path)")


def block(cfg: MoeTransformerConfig, lp: Dict[str, Any], h: jax.Array,
          ep_axis: str | None = None):
    """One MoE-transformer block on h [B, S, d]; returns (h, aux) where
    aux = (load_balance, router_z) from this block's router. With ep_axis
    set (inside shard_map), lp's gate stays replicated and w1/w2 are the
    LOCAL expert slices; tokens flow through all_to_all."""
    B, S, d = h.shape
    _reject_quantized_experts(lp)

    # The attention half IS a GPT-2 block half — share its single
    # definition (qkv packing + flash/dense policy) with the dense family.
    q, k, v = tfm._qkv(cfg, lp, h)
    h = h + tfm._attend(cfg, q, k, v) @ wread(lp, "wo", h.dtype)

    hn = tfm.layernorm(h, lp["ln2_g"], lp["ln2_b"])
    mp = {"gate": lp["gate"], "w1": lp["w1"], "w2": lp["w2"]}
    y, aux = moe_layer_and_aux(mp, hn.reshape(B * S, d), cfg.moe,
                               ep_axis=ep_axis)
    return h + y.reshape(B, S, d), (aux["load_balance"], aux["router_z"])


def _hidden(params: Dict[str, Any], cfg: MoeTransformerConfig,
            tokens: jax.Array, ep_axis: str | None = None):
    """Shared trunk: tokens -> (final-normed hidden states [B, S, d],
    aux dict of per-layer MEAN router losses)."""
    B, S = tokens.shape
    h = (params["embed"][tokens] + params["pos"][:S]).astype(cfg.dtype)

    def body(carry, lp):
        h, lb, rz = carry
        h, (b_lb, b_rz) = block(cfg, lp, h, ep_axis=ep_axis)
        return (h, lb + b_lb, rz + b_rz), None

    zero = jnp.zeros((), jnp.float32)
    (h, lb, rz), _ = lax.scan(body, (h, zero, zero), params["layers"])
    h = tfm.layernorm(h, params["lnf_g"], params["lnf_b"])
    L = cfg.n_layers
    return h, {"load_balance": lb / L, "router_z": rz / L}


def forward(params: Dict[str, Any], cfg: MoeTransformerConfig,
            tokens: jax.Array, ep_axis: str | None = None):
    """tokens [B, S] -> (logits [B, S, vocab] f32, aux) where aux is the
    dict of per-layer MEAN router losses."""
    h, aux = _hidden(params, cfg, tokens, ep_axis=ep_axis)
    # bf16 operands, f32 accumulation — the unembed convention the dense
    # family measured 1.45x whole-model latency for getting wrong.
    logits = jnp.einsum("bsd,vd->bsv", h, params["embed"].astype(h.dtype),
                        preferred_element_type=jnp.float32)
    return logits, aux


def loss_fn(params, cfg: MoeTransformerConfig, tokens, targets,
            aux_weight: float = 1e-2, z_weight: float = 1e-3,
            ep_axis: str | None = None, xent_chunk: int | None = None):
    """Mean token cross-entropy + weighted router auxiliaries;
    ``xent_chunk`` selects the memory-bounded chunked-vocab CE
    (ops/xent.py — no logits materialization)."""
    if xent_chunk is not None:
        from mpi_acx_tpu.ops.xent import chunked_xent_ll
        B, S = tokens.shape
        h, aux = _hidden(params, cfg, tokens, ep_axis=ep_axis)
        ll = chunked_xent_ll(h.reshape(B * S, -1), params["embed"],
                             targets.reshape(-1), xent_chunk)
        return (-jnp.mean(ll) + aux_weight * aux["load_balance"]
                + z_weight * aux["router_z"])
    logits, aux = forward(params, cfg, tokens, ep_axis=ep_axis)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    return (-jnp.mean(ll) + aux_weight * aux["load_balance"]
            + z_weight * aux["router_z"])


# -- KV-cache decode -------------------------------------------------------


def _moe_ffn(cfg: MoeTransformerConfig, lp: Dict[str, Any], h: jax.Array,
             ep_axis: str | None = None, replicated: bool = False,
             sharded_dispatch: bool = False, with_aux: bool = False):
    """The block's routed FFN on h [B, S, d] (token axis flattened for
    the router) — one wrapper for every caller: single-device inference
    (ep_axis None), and with ``ep_axis`` set the expert-parallel paths:
    ``replicated=True`` when h is replicated over the axis and every
    rank should route all tokens (the flagship train blocks — local
    expert block + one psum, 1/ep the FLOPs); ``sharded_dispatch=True``
    when h is replicated but each rank should route only its exclusive
    1/ep token slice through the training path's all_to_all (the TP
    serving default, moe.moe_layer_sharded_dispatch); neither when
    tokens are already sharded (all_to_all moves real data).
    ``with_aux=True`` additionally returns this router's
    ``(load_balance, router_z)`` pair for training losses."""
    from mpi_acx_tpu.models.moe import moe_layer_and_aux, \
        moe_layer_replicated_ep_and_aux, moe_layer_sharded_dispatch
    assert not (replicated and sharded_dispatch)
    _reject_quantized_experts(lp)
    B, S, d = h.shape
    hn = tfm.layernorm(h, lp["ln2_g"], lp["ln2_b"])
    mp = {"gate": lp["gate"], "w1": lp["w1"], "w2": lp["w2"]}
    flat = hn.reshape(B * S, d)
    if ep_axis is not None and sharded_dispatch:
        assert not with_aux, "aux needs full gates; use replicated"
        y = moe_layer_sharded_dispatch(mp, flat, cfg.moe, ep_axis)
    elif ep_axis is not None and replicated:
        if with_aux:
            y, aux = moe_layer_replicated_ep_and_aux(mp, flat, cfg.moe,
                                                     ep_axis)
        else:
            y = moe_layer_replicated_ep(mp, flat, cfg.moe, ep_axis)
    elif with_aux:
        y, aux = moe_layer_and_aux(mp, flat, cfg.moe, ep_axis=ep_axis)
    else:
        y = moe_layer(mp, flat, cfg.moe, ep_axis=ep_axis)
    out = h + y.reshape(B, S, d)
    if with_aux:
        return out, (aux["load_balance"], aux["router_z"])
    return out


def init_kv_cache(cfg: MoeTransformerConfig, batch: int, max_len: int,
                  kv_int8: bool = False):
    """Same cache layout as the dense family (cfg duck-types),
    including the int8 variant."""
    return tfm.init_kv_cache(cfg, batch, max_len, kv_int8=kv_int8)


def prefill(params: Dict[str, Any], cfg: MoeTransformerConfig,
            tokens: jax.Array, max_len: int, last_only: bool = False,
            kv_int8: bool = False, last_index=None):
    """Prompt pass filling a fresh KV cache — the dense family's scaffold
    with the routed FFN plugged in (tfm.prefill's ``ffn`` hook). Routing
    capacity during prefill is per (B*S)-token batch, exactly as in
    forward."""
    return tfm.prefill(params, cfg, tokens, max_len, last_only,
                       ffn=_moe_ffn, kv_int8=kv_int8,
                       last_index=last_index)


def decode_step(params: Dict[str, Any], cfg: MoeTransformerConfig, cache,
                token: jax.Array):
    """One autoregressive step via the dense family's scaffold. The
    router sees the B decode tokens as its dispatch group (capacity =
    cf*B/E+1), which differs from the dense forward's (B*S)-token group:
    cached decode reproduces the dense computation only in the drop-free
    regime — keep ``capacity_factor >= n_experts`` when serving (with
    cf < E a popular expert can drop tokens the dense pass would seat,
    silently diverging)."""
    return tfm.decode_step(params, cfg, cache, token, ffn=_moe_ffn)


def generate(params: Dict[str, Any], cfg: MoeTransformerConfig,
             prompt: jax.Array, n_new: int,
             max_len: Optional[int] = None,
             kv_int8: bool = False) -> jax.Array:
    """Greedy decode: prompt [B, S] -> [B, S + n_new]. ``kv_int8``
    selects the quantized KV cache (shared scaffold; experts
    untouched)."""
    from mpi_acx_tpu.models.decoding import greedy_generate
    return greedy_generate(
        lambda t, ml, lo: prefill(params, cfg, t, ml, last_only=lo,
                                  kv_int8=kv_int8),
        lambda c, t: decode_step(params, cfg, c, t),
        prompt, n_new, cfg.max_seq, max_len)


def generate_sample(params: Dict[str, Any], cfg: MoeTransformerConfig,
                    prompt: jax.Array, n_new: int, key: jax.Array,
                    temperature: float = 1.0, top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    max_len: Optional[int] = None,
                    kv_int8: bool = False) -> jax.Array:
    """Stochastic decode (temperature / top-k / top-p nucleus)."""
    from mpi_acx_tpu.models.decoding import sample_generate
    return sample_generate(
        lambda t, ml, lo: prefill(params, cfg, t, ml, last_only=lo,
                                  kv_int8=kv_int8),
        lambda c, t: decode_step(params, cfg, c, t),
        prompt, n_new, cfg.max_seq, key, temperature, top_k, top_p, max_len)


def param_specs(ep_axis: str = "dp") -> Dict[str, Any]:
    """PartitionSpecs: expert tensors shard their [n_experts] dim over the
    DP+EP mesh axis; everything else replicates."""
    from jax.sharding import PartitionSpec as P
    return {
        "embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
        "layers": {
            "ln1_g": P(), "ln1_b": P(), "wqkv": P(), "wo": P(),
            "ln2_g": P(), "ln2_b": P(), "gate": P(),
            "w1": P(None, ep_axis), "w2": P(None, ep_axis),
        },
    }


def make_moe_transformer_train_step(cfg: MoeTransformerConfig, mesh,
                                    axis: str = "dp", lr: float = 0.1,
                                    aux_weight: float = 1e-2,
                                    z_weight: float = 1e-3):
    """DP+EP train step: ONE mesh axis shards both the batch and the
    experts (the classic data-parallel MoE layout — each rank runs the
    dense parts on its batch shard while hosting E/dp experts that serve
    every rank's tokens via all_to_all).

    Returns a jitted ``step(params, tokens, targets) -> (loss,
    new_params)``; tokens/targets [B, S] with B sharded over ``axis``.
    Gradient construction follows the framework rule (train.py): per-rank
    loss terms cover only the rank's EXCLUSIVE batch shard, the scalar is
    psum-assembled (transpose scaling undone), replicated leaves psum
    their gradients, expert-sharded leaves already accumulate cross-rank
    token contributions through the all_to_all transpose.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    assert cfg.n_experts % n == 0, (
        f"n_experts ({cfg.n_experts}) must divide by the {axis!r} mesh "
        f"axis ({n})")
    specs = param_specs(axis)

    def per_shard(params, tokens, targets):
        def lf(params):
            return lax.psum(
                loss_fn(params, cfg, tokens, targets, aux_weight, z_weight,
                        ep_axis=axis) / n, axis)

        loss, g = jax.value_and_grad(lf)(params)
        g = jax.tree.map(lambda t: t / n, g)      # undo psum seed scaling

        def reduce(path, t):
            name = path[-1].key if hasattr(path[-1], "key") else None
            if name in ("w1", "w2"):
                return t                           # expert-sharded leaf
            return lax.psum(t, axis)
        g = jax.tree_util.tree_map_with_path(reduce, g)
        return loss, g

    grad_fn = shard_map(per_shard, mesh=mesh,
                        in_specs=(specs, P(axis), P(axis)),
                        out_specs=(P(), specs), check_vma=False)

    @jax.jit
    def step(params, tokens, targets):
        loss, g = grad_fn(params, tokens, targets)
        return loss, jax.tree.map(lambda p, gg: p - lr * gg, params, g)

    return step
