"""Continuous-batching serving loop (single device).

The reference has no serving stack at all (SURVEY.md §0); this module
is the framework-goal tier above models/decoding.py. A static-batch
server leaves slots idle from the moment their request finishes until
the whole batch drains — at B slots and mixed output lengths that is a
bubble of up to (B-1)/B of the work. Here B cache slots decode in
lockstep as ONE jitted step while a host-side scheduler swaps finished
requests out and queued prompts in mid-stream, so the device never
waits for the slowest request.

The mechanism is per-slot positions: decode_layer_scan's vector-pos
mode writes each slot's fresh K/V at its own ``pos[b]`` and
grouped_decode_attend masks each slot at ``cols <= pos[b]`` — every
slot's math is exactly its solo run's (no left-padding, no shared
clock), so greedy outputs are bit-equal to per-request generate()
(tested). Prompts are right-padded to a power-of-two bucket for the
prefill compile cache; pad rows are never attended (they sit past
``pos[b]`` until overwritten by decode writes).

Static shapes throughout: one compiled prefill per bucket length, one
compiled decode step, one compiled slot-scatter — the host loop only
schedules.
"""

from __future__ import annotations

import ctypes
import math
import os
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mpi_acx_tpu import reqlog


def _pct(samples: List[float], p: float) -> float:
    """Nearest-rank percentile, StepTimer's convention (profiling.py):
    the ceil(p*n)-th smallest sample, no interpolation."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[max(0, math.ceil(p * len(s)) - 1)]


@dataclass
class RequestTelemetry:
    """Per-request serving telemetry (times from the batch's arrival at
    _serve entry, so queue wait is included — the number a caller of a
    serving system actually experiences)."""

    rid: int
    ttft_s: float        # time to first token (prefill emits it)
    latency_s: float     # arrival -> retire
    new_tokens: int
    tokens_per_s: float  # new_tokens / latency_s
    retries: int         # failed attempts that re-queued this request


@dataclass
class ServingMetrics:
    """Batch-level serving telemetry returned on ServedBatch.metrics."""

    requests: int = 0
    wall_s: float = 0.0
    new_tokens: int = 0
    tokens_per_s: float = 0.0     # aggregate: new_tokens / wall_s
    steps: int = 0                # decode step_fn dispatches
    prefills: int = 0             # successful refills
    requeues: int = 0             # failure-path restarts
    peer_requeues: int = 0        # requeues from peer loss (uncharged)
    slots_shed: int = 0           # slots retired to match lost capacity
    slots_revived: int = 0        # shed slots returned after a fleet join
    hang_dumps: int = 0           # flight dumps written on step failure
    rejections: int = 0           # typed admission rejections
    rejection_reasons: Dict[str, int] = field(default_factory=dict)
    preemptions: int = 0          # paged: page-pressure evictions (uncharged)
    prefix_hits: int = 0          # paged: radix-cache prompt matches
    prefix_evictions: int = 0     # paged: trie pages evicted under pressure
    prefix_pages_reused: int = 0  # paged: prompt pages seated from the trie
    pages_hwm: int = 0            # paged: pool pages-in-use high-water mark
    slo_deferrals: int = 0        # paged: refills deferred by the SLO gate
    ttft_p50_s: float = 0.0
    ttft_p99_s: float = 0.0
    itl_p50_s: float = 0.0        # inter-token latency (per decoded token)
    itl_p99_s: float = 0.0
    queue_depth_max: int = 0
    queue_depth_mean: float = 0.0
    slot_occupancy_mean: float = 0.0  # fraction of slots owned per step
    per_request: List[RequestTelemetry] = field(default_factory=list)


@dataclass
class RequestRejected:
    """Typed admission rejection: an oversized (or otherwise
    unservable) request degrades to this marker at its index in the
    ServedBatch instead of an assert killing the whole server. Check
    with ``isinstance(out[i], RequestRejected)``; ``reason`` is a
    stable token (``exceeds_max_len``, ``exceeds_model_ceiling``,
    ``exceeds_page_budget``), ``detail`` the human-readable arithmetic.
    Counted in ``ServingMetrics.rejections`` / ``rejection_reasons``."""

    rid: int
    reason: str
    detail: str = ""


def _admission_check(rid, prompt, n, chunk, max_len, max_seq,
                     page_budget=None, page_tokens=None
                     ) -> Optional[RequestRejected]:
    """The serving admission rule: a request needs ``len(prompt) + n +
    chunk`` cache positions (the chunk overrun is real — a slot
    finishing mid-chunk keeps writing until the boundary). Returns a
    RequestRejected or None; the paged path adds the pool-budget bound
    (``page_budget`` in pages of ``page_tokens``)."""
    total = len(prompt) + n + chunk
    if total > max_len:
        return RequestRejected(
            rid, "exceeds_max_len",
            f"len(prompt)={len(prompt)} + n_new={n} + chunk={chunk} "
            f"= {total} > max_len={max_len}")
    if total > max_seq:
        return RequestRejected(
            rid, "exceeds_model_ceiling",
            f"len(prompt)={len(prompt)} + n_new={n} + chunk={chunk} "
            f"= {total} > cfg.max_seq={max_seq}")
    if page_budget is not None:
        need = -(-total // page_tokens)
        if need > page_budget:
            return RequestRejected(
                rid, "exceeds_page_budget",
                f"ceil({total} / {page_tokens}) = {need} pages > "
                f"pool n_pages={page_budget}")
    return None


def _count_reasons(rejections) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for rej in rejections:
        out[rej.reason] = out.get(rej.reason, 0) + 1
    return out


class ServedBatch(list):
    """serve_greedy/serve_sample result: a plain list of per-request
    ``prompt + generated`` arrays (full backward compatibility — index,
    iterate, len as before) carrying the batch telemetry as
    ``.metrics``."""

    def __init__(self, outputs, metrics: ServingMetrics):
        super().__init__(outputs)
        self.metrics = metrics


def _peer_dead(exc: BaseException) -> bool:
    """True iff ``exc`` is peer-loss shaped: the runtime's typed
    AcxPeerDeadError, anything carrying ``error == ERR_PEER_DEAD``
    (a multi-host collective that failed on a dead rank), or an error
    message naming the condition. Peer loss is an infrastructure event,
    not the request's fault — the scheduler requeues its victims without
    charging their retry budget (docs/DESIGN.md "Survivable links")."""
    try:
        from mpi_acx_tpu.runtime import ERR_PEER_DEAD, AcxPeerDeadError
    except Exception:  # pragma: no cover — runtime layer unavailable
        AcxPeerDeadError, ERR_PEER_DEAD = (), 20
    if isinstance(exc, AcxPeerDeadError):
        return True
    if getattr(exc, "error", None) == ERR_PEER_DEAD:
        return True
    msg = str(exc).lower()
    return "peer dead" in msg or "peer_dead" in msg


def _fleet_active() -> Optional[int]:
    """Best-effort count of ACTIVE rank slots in this process's fleet view
    (docs/DESIGN.md §12), or None when the native runtime isn't loaded —
    same no-build/no-load discipline as ``_flight_dump_best_effort``. The
    serving loop polls this to notice capacity RETURNING: a replacement
    rank joining raises the count, and shed slots come back."""
    try:
        import ctypes
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None:
            return None
        out = (ctypes.c_uint64 * 5)()
        _rt._lib.acx_fleet_stats(out)
        return int(out[4])
    except Exception:  # pragma: no cover — diagnostics must never raise
        return None


def _flight_dump_best_effort() -> bool:
    """Write this rank's flight-recorder dump if the operator opted in
    ($ACX_FLIGHT names a prefix — same gate as the fatal-signal dump, so
    deliberate failure-path tests don't litter the cwd) and the native
    runtime is already loaded (never build or load the library just for a
    dump — the serving loop must keep making progress). A failed step
    usually means a comm op wedged underneath XLA; the dump plus
    tools/acx_doctor.py turns 'the batch hung' into 'rank R never sent
    tag T'. Returns True iff a dump file was written."""
    if not os.environ.get("ACX_FLIGHT"):
        return False
    try:
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None:
            return False
        return _rt._lib.acx_flight_dump(None) == 0
    except Exception:  # pragma: no cover — diagnostics must never raise
        return False


class RollingSLO:
    """Sliding-window serving SLOs for the live telemetry plane
    (docs/DESIGN.md §13): TTFT and inter-token-latency samples kept in a
    time-bounded window (default 30 s) plus point-in-time queue-depth and
    slot-occupancy gauges. ``live_slos()`` returns the rolling p50/p99 —
    the numbers an operator watching acx_top needs mid-run, as opposed to
    ServingMetrics' whole-batch aggregates computed at the end."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = float(window_s)
        self._ttft: deque = deque()  # (monotonic t, seconds)
        self._itl: deque = deque()
        self.queue_depth = 0
        self.slot_occupancy = 0.0
        # Lifecycle counters for the live "app" fragment: cumulative over
        # the serve call (not windowed — a rejection burst 40 s ago still
        # matters to an operator triaging "why is goodput down"). acx_top
        # renders the per-reason breakdown from these, live, instead of
        # waiting for the end-of-batch ServingMetrics totals.
        self.rejects: Dict[str, int] = {}
        self.preemptions = 0
        self.resumes = 0

    def note_reject(self, reason: str) -> None:
        self.rejects[reason] = self.rejects.get(reason, 0) + 1

    def note_preempt(self) -> None:
        self.preemptions += 1

    def note_resume(self) -> None:
        self.resumes += 1

    def _trim(self, dq: deque, now: float) -> None:
        cutoff = now - self.window_s
        while dq and dq[0][0] < cutoff:
            dq.popleft()

    def note_ttft(self, seconds: float) -> None:
        now = time.monotonic()
        self._ttft.append((now, float(seconds)))
        self._trim(self._ttft, now)

    def note_itl(self, seconds: float) -> None:
        now = time.monotonic()
        self._itl.append((now, float(seconds)))
        self._trim(self._itl, now)

    def note_gauges(self, queue_depth: int, slot_occupancy: float) -> None:
        self.queue_depth = int(queue_depth)
        self.slot_occupancy = float(slot_occupancy)

    def live_slos(self) -> dict:
        """Rolling-window percentiles + live gauges, JSON-ready."""
        now = time.monotonic()
        self._trim(self._ttft, now)
        self._trim(self._itl, now)
        ttft = [v for _, v in self._ttft]
        itl = [v for _, v in self._itl]
        return {
            "window_s": self.window_s,
            "ttft_p50_s": _pct(ttft, 0.50),
            "ttft_p99_s": _pct(ttft, 0.99),
            "ttft_n": len(ttft),
            "itl_p50_s": _pct(itl, 0.50),
            "itl_p99_s": _pct(itl, 0.99),
            "itl_n": len(itl),
            "queue_depth": self.queue_depth,
            "slot_occupancy": self.slot_occupancy,
            "rejections": sum(self.rejects.values()),
            "rejects": dict(self.rejects),
            "preemptions": self.preemptions,
            "resumes": self.resumes,
        }


def _tseries_annotate_best_effort(fragment: dict) -> bool:
    """Publish ``fragment`` to the native telemetry sampler (it rides along
    under ``"app"`` in every subsequent ACX_TSERIES sample) — but only if
    the native runtime is already loaded AND sampling is armed: same
    no-build/no-load discipline as ``_flight_dump_best_effort``, plus the
    JSON encode is skipped entirely when nobody is sampling. Returns True
    iff the fragment was handed to the sampler."""
    if not os.environ.get("ACX_TSERIES"):
        return False
    try:
        import json as _json
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None or not _rt._lib.acx_tseries_enabled():
            return False
        _rt._lib.acx_tseries_annotate(
            _json.dumps(fragment, separators=(",", ":")).encode())
        return True
    except Exception:  # pragma: no cover — diagnostics must never raise
        return False


def _span_app_begin_best_effort(request_id: int) -> bool:
    """Bracket-open for causal tracing (docs/DESIGN.md §14): ties every
    native op enqueued until the matching end-call to ``request_id``, so
    an offline acx_critpath.py run splits this request's TTFT into queue
    vs compute vs wire. Same no-build/no-load discipline as the
    annotate helper: only if the native runtime is ALREADY loaded and
    tracing is armed (ACX_TRACE). The id is offset by 1 — request ids
    start at 0 and span id 0 means "unspanned" on the native side.
    Returns True iff the bracket was opened (the caller must then close
    it)."""
    if not os.environ.get("ACX_TRACE"):
        return False
    try:
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None:
            return False
        _rt._lib.acx_span_app_begin(ctypes.c_uint64(request_id + 1))
        return True
    except Exception:  # pragma: no cover — diagnostics must never raise
        return False


def _span_app_end_best_effort() -> None:
    try:
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is not None:
            _rt._lib.acx_span_app_end()
    except Exception:  # pragma: no cover — diagnostics must never raise
        pass


def _bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def make_server_fns(params, cfg, family, chunk: int = 1,
                    kv_int8: bool = False, sample_cfg=None):
    """Compile-once closures for the serve loop: returns (prefill_fn,
    step_fn, scatter_fn, chunk, kv_int8, sample_cfg) — the trailing
    values let serve_greedy/serve_sample verify a reused tuple matches
    the call (chunk is baked into step_fn's scan length, so a tuple
    built for chunk=8 silently mis-serves a chunk=1 call otherwise).
    ``family`` is the model module (models.transformer, models.llama,
    or models.moe_transformer — anything exposing
    prefill/decode_step/init_kv_cache with the shared cache layout).

    ``chunk`` > 1 runs that many decode steps per host call as one
    jitted lax.scan returning the [chunk, B] token block — the
    scheduler then reacts every chunk tokens instead of every token,
    amortizing the host->device dispatch (through a tunneled chip that
    round trip is ~75 ms, dwarfing the ~2 ms step; even host-local it
    is the difference between a driver-bound and a device-bound
    server). The tokens are bit-identical to stepwise decoding; the
    cost is scheduling granularity — a finished slot idles until the
    chunk boundary.

    ``sample_cfg`` = (temperature, top_k, top_p) switches the step from
    greedy argmax to stochastic sampling: the step then carries a [B]
    per-slot key array and each slot draws with ITS OWN key per step,
    split exactly as decoding.sample_generate splits its single key —
    that discipline is what makes serve_sample's outputs equal the solo
    sampled runs."""
    prefill_cache: Dict[int, object] = {}

    def prefill_fn(tokens, last):
        """[1, S_bucket], traced last index -> (logits [1,1,vocab],
        cache). The unembedding runs on the real prompt's final row
        alone (``last_index``): the full-bucket [1, S, vocab] logits —
        ~1/3 of prefill FLOPs at GPT-2 vocab — are never computed."""
        S = tokens.shape[1]
        if S not in prefill_cache:
            prefill_cache[S] = jax.jit(
                lambda t, li, S=S: family.prefill(params, cfg, t, S,
                                                  kv_int8=kv_int8,
                                                  last_index=li))
        return prefill_cache[S](tokens, last)

    if sample_cfg is None:
        def pick(logits, keys):      # greedy: keys unused, pass-through
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), keys
    else:
        from mpi_acx_tpu.models.decoding import sample_logits
        temperature, top_k, top_p = sample_cfg

        def pick(logits, keys):
            # Mirror sample_generate: key, sub = split(key); draw with
            # sub — per slot, so slot b's stream equals the solo run's.
            splits = jax.vmap(jax.random.split)(keys)
            keys, subs = splits[:, 0], splits[:, 1]
            tok = jax.vmap(
                lambda lg, k: sample_logits(lg[None].astype(jnp.float32),
                                            k, temperature, top_k,
                                            top_p)[0])(logits, subs)
            return tok.astype(jnp.int32), keys

    # Donated carries: the loop always proceeds with the returned
    # cache, so XLA may update the slot buffers in place (on CPU the
    # donation is ignored, harmlessly).
    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(cache, tok, keys):
        def one(carry, _):
            cache, tok, keys = carry
            logits, cache = family.decode_step(params, cfg, cache, tok)
            nxt, keys = pick(logits, keys)
            return (cache, nxt, keys), nxt
        (cache, _, keys), toks = lax.scan(one, (cache, tok, keys), None,
                                          length=chunk)
        return cache, toks, keys                     # toks [chunk, B]

    @partial(jax.jit, donate_argnums=(0,))
    def scatter_fn(slots, one, slot_idx, new_pos):
        """Land a freshly prefilled single-request cache (``one``, B=1,
        bucket-length max_len) into slot ``slot_idx`` of the slot
        cache; rows past the bucket keep the slot's old contents (never
        attended: they lie beyond ``new_pos`` until decode overwrites
        them). Int8 slot caches carry their scale buffers ('ks'/'vs')
        through the same per-key scatter."""
        for key in [k for k in ("k", "v", "ks", "vs") if k in slots]:
            src = one[key][:, 0]                    # [L, S_bucket, H, D]
            dst = lax.dynamic_index_in_dim(
                slots[key], slot_idx, 1, keepdims=False)  # [L, max_len,...]
            dst = lax.dynamic_update_slice(
                dst, src, (0, 0, 0, 0))
            slots[key] = lax.dynamic_update_index_in_dim(
                slots[key], dst, slot_idx, 1)
        slots["pos"] = slots["pos"].at[slot_idx].set(new_pos)
        return slots

    # chunk/kv_int8/sample_cfg ride along so the serve entry points can
    # reject a mismatched reuse (e.g. int8 slots + bf16-prefill
    # closures, a step scanning a different chunk length, or a step
    # jitted with different sampling params, fail deep in a trace — or
    # worse, silently — otherwise).
    return prefill_fn, step_fn, scatter_fn, chunk, kv_int8, sample_cfg


def _serve(params, cfg, prompts, n_new, n_slots, max_len, family, eos,
           chunk, server_fns, kv_int8, sample_cfg, key,
           max_request_retries=2):
    """The scheduler shared by serve_greedy and serve_sample — queue,
    slot ownership, chunk-block consumption, retire/refill. Sampling
    only changes (a) how the step picks tokens (make_server_fns
    sample_cfg) and (b) the first token at refill, drawn on the host
    with request rid's own key stream fold_in(key, rid), split exactly
    as decoding.sample_generate splits.

    Degrades gracefully under step/prefill failure (the serving face of
    the runtime's retry plane): a request whose device step raised is
    re-queued from scratch — emitted tokens discarded, so the restart
    replays the same greedy/sampled path bit for bit — up to
    ``max_request_retries`` times before the failure is re-raised with
    the request id attached."""
    if family is None:
        from mpi_acx_tpu.models import transformer as family  # noqa: N813
    assert prompts, "no requests"
    assert all(len(p) > 0 for p in prompts), \
        "zero-length prompt (prefill needs at least one token to attend)"
    n_new = ([int(n_new)] * len(prompts) if np.ndim(n_new) == 0
             else [int(n) for n in n_new])
    assert len(n_new) == len(prompts), (len(n_new), len(prompts))
    assert all(n >= 1 for n in n_new), \
        "n_new >= 1 per request (the prefill itself emits the first token)"

    # Typed admission: an oversized request degrades to a
    # RequestRejected at its output index instead of an assert killing
    # the server for everyone else in the batch.
    rejected: Dict[int, RequestRejected] = {}
    for rid, (p, n) in enumerate(zip(prompts, n_new)):
        rej = _admission_check(rid, p, n, chunk, max_len, cfg.max_seq)
        if rej is not None:
            rejected[rid] = rej
            reqlog.emit("reject", rid, reason=rej.reason)
        else:
            reqlog.emit("admit", rid, prompt_len=len(p), n_new=n)

    if server_fns is None:
        server_fns = make_server_fns(params, cfg, family, chunk=chunk,
                                     kv_int8=kv_int8,
                                     sample_cfg=sample_cfg)
    (prefill_fn, step_fn, scatter_fn, fns_chunk, fns_int8,
     fns_sample) = server_fns
    assert fns_chunk == chunk, \
        (f"server_fns built for chunk={fns_chunk}, this call uses "
         f"chunk={chunk} (the scan length is baked into step_fn)")
    assert fns_int8 == kv_int8, \
        "server_fns built with a different kv_int8 than this call"
    assert fns_sample == sample_cfg, \
        ("server_fns built for different sampling settings "
         f"({fns_sample} vs {sample_cfg})")

    slots = family.init_kv_cache(cfg, n_slots, max_len, kv_int8=kv_int8)
    slots["pos"] = jnp.zeros((n_slots,), jnp.int32)

    queue = deque((rid, np.asarray(p, np.int32))
                  for rid, p in enumerate(prompts) if rid not in rejected)
    for depth, (rid, _p) in enumerate(queue):
        reqlog.emit("queue", rid, depth=depth)
    # Request id per slot; -1 = idle, -2 = shed (capacity retired after a
    # peer loss — never refilled, skipped by every owner[b] >= 0 loop).
    owner = [-1] * n_slots
    emitted: List[List[int]] = [[] for _ in prompts]
    done: List[Optional[object]] = [None] * len(prompts)
    for rid, rej in rejected.items():
        done[rid] = rej
    last_tok = np.zeros((n_slots,), np.int32)
    # Per-slot key streams (greedy: dummies the step passes through).
    keys = jax.random.split(key if key is not None else jax.random.key(0),
                            n_slots)

    # Per-request failure budget: a request whose prefill or decode step
    # raised restarts from scratch this many times before the error
    # propagates (0 = fail fast).
    attempts = [0] * len(prompts)

    # Telemetry (RequestTelemetry/ServingMetrics above). All requests
    # "arrive" at entry, so per-request clocks start at t0 — queue wait
    # counts toward TTFT and latency.
    t0 = time.perf_counter()
    ttft = [None] * len(prompts)      # type: List[Optional[float]]
    finish = [None] * len(prompts)    # type: List[Optional[float]]
    # Rolling-window SLOs for the live telemetry plane: fed alongside the
    # whole-batch lists below, published to the ACX_TSERIES sampler once
    # per scheduler iteration (a no-op unless sampling is armed).
    slo = RollingSLO()
    for rej in rejected.values():
        slo.note_reject(rej.reason)
    itl_samples: List[float] = []
    qd_samples: List[int] = []
    occ_samples: List[float] = []
    n_steps = 0
    n_prefills = 0
    n_requeues = 0
    n_peer_requeues = 0
    n_shed = 0
    n_revived = 0
    n_hang_dumps = 0
    # Fleet-elastic capacity (docs/DESIGN.md §12): remember how many rank
    # slots were ACTIVE at entry; a later rise (a replacement joined)
    # revives shed serving slots so queued requests rebalance onto the
    # restored capacity. None = no native runtime loaded, feature dormant.
    fleet_active_seen = _fleet_active()

    def _requeue(rid, prompt, exc, charge=True):
        """Put a failed request back on the queue for a bit-equal
        restart (emitted tokens discarded; refill replays the same
        greedy/per-rid-key path), or re-raise past the retry budget.
        ``charge=False`` (peer loss) requeues without spending the
        request's retry budget: losing a rank is not the request's
        fault, and a long recovery must not burn victims out of the
        server."""
        nonlocal n_requeues, n_peer_requeues
        if charge:
            attempts[rid] += 1
            if attempts[rid] > max_request_retries:
                raise RuntimeError(
                    f"request {rid} failed {attempts[rid]} time(s), past "
                    f"max_request_retries={max_request_retries}") from exc
        else:
            n_peer_requeues += 1
        emitted[rid] = []
        ttft[rid] = None   # the replayed attempt re-earns its first token
        n_requeues += 1
        reqlog.emit("requeue", rid, charged=bool(charge))
        queue.append((rid, prompt))

    def _check_fleet_rejoin():
        """Revive shed slots when the fleet view shows capacity back: a
        joined replacement returns the serving width a peer loss took
        away. Returns the revived slot indices so the caller rebalances
        queued requests onto exactly those — the rest of the schedule is
        untouched. A drop in ACTIVE slots just lowers the watermark, so
        the NEXT join (not the leave that preceded it) triggers revival."""
        nonlocal fleet_active_seen, n_revived
        if fleet_active_seen is None:
            return []
        act = _fleet_active()
        if act is None:
            return []
        revived = []
        if act > fleet_active_seen:
            for b in range(n_slots):
                if owner[b] == -2:
                    owner[b] = -1
                    revived.append(b)
            n_revived += len(revived)
        fleet_active_seen = act
        return revived

    def _shed_slot():
        """Retire one idle slot for good (owner -2): a lost rank shrank
        the job's capacity, so the batch shrinks with it instead of
        hammering the survivors at the old width. Always keeps at least
        one slot alive — a server with zero slots is just an outage."""
        nonlocal n_shed
        alive = [b for b in range(n_slots) if owner[b] != -2]
        idle = [b for b in alive if owner[b] == -1]
        if len(alive) <= 1 or not idle:
            return
        owner[max(idle)] = -2
        n_shed += 1

    def refill(b):
        """Returns True iff slot b now owns a request; a failed prefill
        re-queues the request instead of killing the server."""
        nonlocal slots, keys, n_prefills
        rid, prompt = queue.popleft()
        S = len(prompt)
        # Bucket for the prefill compile cache, capped at max_len so
        # the scatter's update always fits the slot buffer, and at the
        # model's position ceiling (prefill asserts padded S <= max_seq).
        padded = np.zeros((1, min(_bucket(S), max_len, cfg.max_seq)),
                          np.int32)
        padded[0, :S] = prompt
        # Causal-tracing bracket: any native op the prefill triggers
        # (multihost sharded serving pushes activations through MPIX
        # enqueues) is span-tagged with this request's id, so the
        # request's TTFT decomposes offline (acx_critpath.py).
        spanned = _span_app_begin_best_effort(rid)
        reqlog.emit("prefill_start", rid, prompt_len=S, bucket=padded.shape[1])
        try:
            logits, one = prefill_fn(jnp.asarray(padded), S - 1)
            if sample_cfg is None:
                first = int(jnp.argmax(logits[0, 0]))
            else:
                from mpi_acx_tpu.models.decoding import sample_logits
                rkey, sub = jax.random.split(jax.random.fold_in(key, rid))
                first = int(sample_logits(
                    logits[0, 0][None].astype(jnp.float32), sub,
                    *sample_cfg)[0])
                keys = keys.at[b].set(rkey)
            slots = scatter_fn(slots, one, b, S)
        except Exception as exc:  # noqa: BLE001 — any device failure
            _requeue(rid, prompt, exc, charge=not _peer_dead(exc))
            return False
        finally:
            if spanned:
                _span_app_end_best_effort()
        owner[b] = rid
        emitted[rid].append(first)
        last_tok[b] = first
        n_prefills += 1
        reqlog.emit("prefill_end", rid, first_token=first)
        reqlog.emit("seat", rid, slot=b, pos=S)
        ttft[rid] = time.perf_counter() - t0  # prefill emitted token one
        slo.note_ttft(ttft[rid])
        reqlog.emit("stream", rid, n=1, ttft_s=ttft[rid])
        return True

    def retire(b):
        nonlocal slots
        rid = owner[b]
        done[rid] = np.concatenate(
            [np.asarray(prompts[rid], np.int32),
             np.asarray(emitted[rid], np.int32)])
        finish[rid] = time.perf_counter() - t0
        reqlog.emit("finish", rid, new_tokens=len(emitted[rid]),
                    latency_s=finish[rid])
        owner[b] = -1
        # Park the freed slot at pos 0: an idle slot keeps stepping in
        # the batch, and a stale pos walks toward max_len where the
        # decode write would land out of bounds on a long-idle slot.
        slots["pos"] = slots["pos"].at[b].set(0)

    def slot_finished(b):
        rid = owner[b]
        return (len(emitted[rid]) >= n_new[rid]
                or (eos is not None and emitted[rid]
                    and emitted[rid][-1] == eos))

    # Seed the slots, retiring 1-token requests on the spot so a slot
    # never enters the decode loop already finished.
    qd_samples.append(len(queue))
    while queue and any(o == -1 for o in owner):
        b = owner.index(-1)
        if refill(b) and slot_finished(b):
            retire(b)

    while any(o >= 0 for o in owner) or queue:
        qd_samples.append(len(queue))
        occ_samples.append(sum(o >= 0 for o in owner) / n_slots)
        slo.note_gauges(qd_samples[-1], occ_samples[-1])
        _tseries_annotate_best_effort(slo.live_slos())
        if queue:
            # Capacity may have returned (a replacement rank joined):
            # revive shed slots and rebalance the backlog onto them.
            for b in _check_fleet_rejoin():
                if queue and refill(b) and slot_finished(b):
                    retire(b)
        if not any(o >= 0 for o in owner):
            # All slots idle with requests still queued: only reachable
            # after a failure re-queued them — reseed and keep serving.
            while queue and any(o == -1 for o in owner):
                b = owner.index(-1)
                if refill(b) and slot_finished(b):
                    retire(b)
            continue
        step_t0 = time.perf_counter()
        try:
            slots, toks, keys = step_fn(slots, jnp.asarray(last_tok), keys)
        except Exception as exc:  # noqa: BLE001 — any device failure
            # step_fn donates the slot cache, so after a failed dispatch
            # its buffers cannot be trusted. Re-queue every active
            # request (bit-equal restart, bounded per request by
            # max_request_retries), rebuild the cache, and continue —
            # the queued-but-unstarted requests are unaffected. A
            # peer-loss failure additionally sheds a slot (the job's
            # capacity shrank with the lost rank) and does NOT charge
            # the victims' retry budget.
            lost_peer = _peer_dead(exc)
            # Snapshot the comm plane before touching anything: the flight
            # dump captures the wedged op/link state as the failure left it.
            if _flight_dump_best_effort():
                n_hang_dumps += 1
            for b in range(n_slots):
                if owner[b] >= 0:
                    rid = owner[b]
                    owner[b] = -1
                    _requeue(rid, np.asarray(prompts[rid], np.int32), exc,
                             charge=not lost_peer)
            if lost_peer:
                _shed_slot()
            slots = family.init_kv_cache(cfg, n_slots, max_len,
                                         kv_int8=kv_int8)
            slots["pos"] = jnp.zeros((n_slots,), jnp.int32)
            keys = jax.random.split(
                key if key is not None else jax.random.key(0), n_slots)
            last_tok = np.zeros((n_slots,), np.int32)
            continue
        block = np.asarray(toks, np.int32)           # [chunk, B]
        # np.asarray forced the device sync, so this dt covers the real
        # device step; each of the chunk tokens shares it evenly — the
        # per-token cadence a streaming client would see.
        step_dt = time.perf_counter() - step_t0
        n_steps += 1
        reqlog.emit("decode_step", step=n_steps, dt_s=step_dt,
                    active=sum(o >= 0 for o in owner))
        for b in range(n_slots):
            last_tok[b] = block[-1, b]
            if owner[b] < 0:
                continue
            got = 0
            for c in range(block.shape[0]):
                # A slot that finishes mid-chunk idles (its further
                # tokens are valid continuations past the request's
                # end — dropped); retire/refill happens only at chunk
                # boundaries, the granularity ``chunk`` buys.
                if slot_finished(b):
                    break
                emitted[owner[b]].append(int(block[c, b]))
                itl_samples.append(step_dt / chunk)
                slo.note_itl(step_dt / chunk)
                got += 1
            if got:
                reqlog.emit("stream", owner[b], n=got, itl_s=step_dt / chunk)
        for b in range(n_slots):
            while owner[b] >= 0 and slot_finished(b):
                retire(b)
                if queue:
                    refill(b)

    assert all(d is not None for d in done)
    wall = time.perf_counter() - t0
    per_request = []
    total_new = 0
    for rid in range(len(prompts)):
        if rid in rejected:
            continue            # never ran — no telemetry to report
        nt = len(emitted[rid])
        total_new += nt
        lat = finish[rid] if finish[rid] is not None else wall
        per_request.append(RequestTelemetry(
            rid=rid,
            ttft_s=ttft[rid] if ttft[rid] is not None else lat,
            latency_s=lat,
            new_tokens=nt,
            tokens_per_s=nt / lat if lat > 0 else 0.0,
            retries=attempts[rid]))
    metrics = ServingMetrics(
        requests=len(prompts),
        wall_s=wall,
        new_tokens=total_new,
        tokens_per_s=total_new / wall if wall > 0 else 0.0,
        steps=n_steps,
        prefills=n_prefills,
        requeues=n_requeues,
        peer_requeues=n_peer_requeues,
        slots_shed=n_shed,
        slots_revived=n_revived,
        hang_dumps=n_hang_dumps,
        rejections=len(rejected),
        rejection_reasons=_count_reasons(rejected.values()),
        ttft_p50_s=_pct([r.ttft_s for r in per_request], 0.50),
        ttft_p99_s=_pct([r.ttft_s for r in per_request], 0.99),
        itl_p50_s=_pct(itl_samples, 0.50),
        itl_p99_s=_pct(itl_samples, 0.99),
        queue_depth_max=max(qd_samples) if qd_samples else 0,
        queue_depth_mean=(sum(qd_samples) / len(qd_samples)
                          if qd_samples else 0.0),
        slot_occupancy_mean=(sum(occ_samples) / len(occ_samples)
                             if occ_samples else 1.0),
        per_request=per_request)
    return ServedBatch(done, metrics)


def serve_greedy(params, cfg, prompts: Sequence[np.ndarray], n_new,
                 n_slots: int, max_len: int, family=None,
                 eos: Optional[int] = None, chunk: int = 1,
                 server_fns=None,
                 kv_int8: bool = False,
                 max_request_retries: int = 2) -> ServedBatch:
    """Serve ``prompts`` (1-D int arrays, any lengths) through
    ``n_slots`` continuously-batched cache slots; each request decodes
    greedily for ``n_new`` tokens (an int, or one per request — the
    mixed-output-length workload is where continuous batching beats a
    static batch) or until ``eos``. Returns, per request, ``prompt +
    generated`` — bit-equal to that request's solo ``family.generate``
    run (per-slot positions, see module docstring). ``chunk`` trades
    scheduling granularity for host-dispatch amortization (see
    make_server_fns); outputs are identical for any chunk. Pass
    ``server_fns`` (a make_server_fns result for the same
    params/cfg/family/chunk/kv_int8 — the flags are checked) to reuse
    compiled programs across calls — a fresh call otherwise rebuilds
    its jit closures and re-traces.
    ``kv_int8`` serves from int8 slot caches (ops/kvquant.py) — the
    long-context regime where the cache stream dominates; outputs then
    equal the solo ``generate(..., kv_int8=True)`` runs bit for bit
    (same codes, same scales, same scale-on-scores read).
    ``max_request_retries`` bounds per-request restarts after a failed
    prefill/step (see _serve) — a transient device fault costs the
    failed requests a replay, not the server.

    The returned list is a ``ServedBatch``: a plain list of outputs
    carrying batch telemetry as ``.metrics`` (per-request TTFT and
    tokens/sec, inter-token latency percentiles, queue depth, slot
    occupancy, requeue counts — see ServingMetrics).
    """
    return _serve(params, cfg, prompts, n_new, n_slots, max_len, family,
                  eos, chunk, server_fns, kv_int8, None, None,
                  max_request_retries=max_request_retries)


def serve_sample(params, cfg, prompts: Sequence[np.ndarray], n_new,
                 n_slots: int, max_len: int, key, family=None,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos: Optional[int] = None, chunk: int = 1,
                 server_fns=None,
                 kv_int8: bool = False,
                 max_request_retries: int = 2) -> ServedBatch:
    """Stochastic continuous batching (temperature / top-k / top-p).

    Request ``rid`` draws from its own key stream
    ``jax.random.fold_in(key, rid)`` with exactly
    decoding.sample_generate's split discipline, so each output equals
    the solo ``family.generate_sample(prompt, n,
    key=jax.random.fold_in(key, rid), ...)`` run bit for bit — the
    scheduler (slot assignment, refill order, chunking) cannot perturb
    any request's sample path. All other parameters (and the
    ``ServedBatch``/telemetry return) as serve_greedy.
    """
    return _serve(params, cfg, prompts, n_new, n_slots, max_len, family,
                  eos, chunk, server_fns, kv_int8,
                  (temperature, top_k, top_p), key,
                  max_request_retries=max_request_retries)


def _slo_admit_targets(slo_admit) -> tuple:
    """Resolve the SLO admission gate: ``slo_admit`` is (ttft_s, itl_s)
    rolling-p50 targets (either may be None), or None to read the
    ``ACX_SERVE_ADMIT_TTFT_MS`` / ``ACX_SERVE_ADMIT_ITL_MS`` knobs
    (unset/0 = gate off — the default, which keeps paged schedules
    identical to the fixed-slot path's)."""
    if slo_admit is not None:
        ttft_t, itl_t = slo_admit
        return (float(ttft_t) if ttft_t else None,
                float(itl_t) if itl_t else None)
    ttft_ms = float(os.environ.get("ACX_SERVE_ADMIT_TTFT_MS", "0") or 0)
    itl_ms = float(os.environ.get("ACX_SERVE_ADMIT_ITL_MS", "0") or 0)
    return (ttft_ms / 1e3 if ttft_ms > 0 else None,
            itl_ms / 1e3 if itl_ms > 0 else None)


def serve_paged_greedy(params, cfg, prompts: Sequence[np.ndarray], n_new,
                       n_slots: int, max_len: int, family=None,
                       eos: Optional[int] = None, chunk: int = 1,
                       kv_int8: bool = False,
                       page_tokens: Optional[int] = None,
                       n_pages: Optional[int] = None,
                       prefix_cache: bool = False,
                       slo_admit=None,
                       on_token=None,
                       max_request_retries: int = 2,
                       return_paged_state: bool = False) -> ServedBatch:
    """Greedy continuous batching over a PAGED KV cache
    (models/kvpage.py): slots share a pool of ``page_tokens``-sized
    pages through per-slot block tables, so HBM-resident KV bytes
    scale with LIVE tokens, not ``n_slots * max_len``. On identical
    schedules (the defaults: no prefix cache, no SLO gate, enough
    pages) outputs are BIT-EQUAL to the fixed-slot ``serve_greedy`` —
    the paged attend gathers each slot's pages into the exact
    ``[B, max_len]`` layout the dense reference attends, and the paged
    decode step is the fixed step with table-routed writes (tested in
    tests/test_paged.py for bf16 and int8 caches alike).

    Beyond the fixed path it adds:

    * **Typed admission** — a request that cannot fit ``max_len``,
      ``cfg.max_seq``, or the page budget degrades to a
      :class:`RequestRejected` at its output index (and a
      ``rejections`` count in metrics) instead of an assert.
    * **Lazy page growth + preemption** — a slot owns only the pages
      its live tokens need; growth happens at chunk boundaries, and
      when the pool runs dry the LOWEST-priority request (highest rid
      = latest arrival) is preempted: its pages are freed and it
      requeues UNCHARGED (the PR 3 peer-loss rule — pressure is the
      server's fault, not the request's), replaying bit-equal when
      reseated.
    * **Radix prefix sharing** (``prefix_cache=True``) — full-page
      prompt prefixes are cached in a refcounted radix trie; a hit
      seats the shared pages (stored ONCE, never rewritten) and
      prefills only the suffix. Hit-path prefills use different tensor
      shapes than cold ones, so a hit's outputs are deterministic but
      not bitwise-pinned to the cold path (docs/DESIGN.md §19) — which
      is why the feature is opt-in.
    * **SLO-aware batch formation** — ``slo_admit=(ttft_s, itl_s)``
      (or the ``ACX_SERVE_ADMIT_*_MS`` knobs) defers REFILLS while the
      RollingSLO window's p50 violates a target and at least one
      request is in flight: trading queue wait (cheap, visible) for
      inter-token latency (the SLO a streaming client feels).
    * **Streaming output** — ``on_token(rid, token)`` fires for every
      token as it is consumed, first (prefill) token included.
      At-least-once semantics: a preempted or requeued request replays
      its stream from the start when re-served.

    ``page_tokens`` defaults to $ACX_KV_PAGE_TOKENS (128 — the
    flash-decode block granularity) stepped down to divide ``max_len``;
    ``n_pages`` defaults to ``n_slots * max_len / page_tokens``
    (capacity parity with the fixed-slot cache). The returned
    ServedBatch carries the paged counters (preemptions, prefix_hits,
    prefix_evictions, prefix_pages_reused, pages_hwm) in ``.metrics``;
    ``return_paged_state=True`` additionally exposes the live
    :class:`~mpi_acx_tpu.models.kvpage.PagedKV` as ``.paged_state``
    (tests and benches inspect allocator occupancy through it)."""
    from mpi_acx_tpu.models import kvpage

    if family is None:
        from mpi_acx_tpu.models import transformer as family  # noqa: N813
    assert prompts, "no requests"
    assert all(len(p) > 0 for p in prompts), \
        "zero-length prompt (prefill needs at least one token to attend)"
    n_new = ([int(n_new)] * len(prompts) if np.ndim(n_new) == 0
             else [int(n) for n in n_new])
    assert len(n_new) == len(prompts), (len(n_new), len(prompts))
    assert all(n >= 1 for n in n_new), \
        "n_new >= 1 per request (the prefill itself emits the first token)"

    pt = page_tokens or kvpage.default_page_tokens(max_len)
    assert max_len % pt == 0, \
        f"page_tokens={pt} must divide max_len={max_len}"
    max_pages = max_len // pt
    if n_pages is None:
        n_pages = n_slots * max_pages
    ttft_target, itl_target = _slo_admit_targets(slo_admit)

    rejected: Dict[int, RequestRejected] = {}
    for rid, (p, n) in enumerate(zip(prompts, n_new)):
        rej = _admission_check(rid, p, n, chunk, max_len, cfg.max_seq,
                               page_budget=n_pages, page_tokens=pt)
        if rej is not None:
            rejected[rid] = rej
            reqlog.emit("reject", rid, reason=rej.reason)
        else:
            reqlog.emit("admit", rid, prompt_len=len(p), n_new=n)

    pkv = kvpage.PagedKV(cfg, family, n_slots, max_len, pt, n_pages,
                         kv_int8=kv_int8, prefix_cache=prefix_cache)

    prefill_cache: Dict[int, object] = {}

    def prefill_fn(tokens, last):
        S = tokens.shape[1]
        if S not in prefill_cache:
            prefill_cache[S] = jax.jit(
                lambda t, li, S=S: family.prefill(params, cfg, t, S,
                                                  kv_int8=kv_int8,
                                                  last_index=li))
        return prefill_cache[S](tokens, last)

    suffix_cache: Dict[tuple, object] = {}

    def suffix_prefill_fn(suffix, hk, hv, last):
        ck = (suffix.shape[1], hk.shape[1])
        if ck not in suffix_cache:
            suffix_cache[ck] = jax.jit(
                lambda s, k, v, li: kvpage.prefill_with_history(
                    params, cfg, s, k, v, li))
        return suffix_cache[ck](suffix, hk, hv, last)

    step_fn = kvpage.make_paged_step_fn(params, cfg, family, chunk, pt)

    queue = deque((rid, np.asarray(p, np.int32))
                  for rid, p in enumerate(prompts) if rid not in rejected)
    for depth, (rid, _p) in enumerate(queue):
        reqlog.emit("queue", rid, depth=depth)
    owner = [-1] * n_slots          # -1 idle, -2 shed (as _serve)
    emitted: List[List[int]] = [[] for _ in prompts]
    done: List[Optional[object]] = [None] * len(prompts)
    for rid, rej in rejected.items():
        done[rid] = rej
    last_tok = np.zeros((n_slots,), np.int32)
    keys = jax.random.split(jax.random.key(0), n_slots)  # greedy dummies
    attempts = [0] * len(prompts)

    t0 = time.perf_counter()
    ttft = [None] * len(prompts)      # type: List[Optional[float]]
    finish = [None] * len(prompts)    # type: List[Optional[float]]
    slo = RollingSLO()
    for rej in rejected.values():
        slo.note_reject(rej.reason)
    itl_samples: List[float] = []
    qd_samples: List[int] = []
    occ_samples: List[float] = []
    n_steps = n_prefills = n_requeues = n_peer_requeues = 0
    n_shed = n_revived = n_hang_dumps = n_preempts = n_slo_defer = 0
    # Requests currently evicted by page pressure: membership here turns
    # the next successful seat into a journey "resume" event.
    preempted_rids: set = set()
    fleet_active_seen = _fleet_active()

    def _requeue(rid, prompt, exc, charge=True):
        nonlocal n_requeues, n_peer_requeues
        if charge:
            attempts[rid] += 1
            if attempts[rid] > max_request_retries:
                raise RuntimeError(
                    f"request {rid} failed {attempts[rid]} time(s), past "
                    f"max_request_retries={max_request_retries}") from exc
        else:
            n_peer_requeues += 1
        emitted[rid] = []
        ttft[rid] = None
        n_requeues += 1
        reqlog.emit("requeue", rid, charged=bool(charge))
        queue.append((rid, prompt))

    def _check_fleet_rejoin():
        nonlocal fleet_active_seen, n_revived
        if fleet_active_seen is None:
            return []
        act = _fleet_active()
        if act is None:
            return []
        revived = []
        if act > fleet_active_seen:
            for b in range(n_slots):
                if owner[b] == -2:
                    owner[b] = -1
                    revived.append(b)
            n_revived += len(revived)
        fleet_active_seen = act
        return revived

    def _shed_slot():
        nonlocal n_shed
        alive = [b for b in range(n_slots) if owner[b] != -2]
        idle = [b for b in alive if owner[b] == -1]
        if len(alive) <= 1 or not idle:
            return
        owner[max(idle)] = -2
        n_shed += 1

    def _slo_defers() -> bool:
        """SLO-aware batch formation: with a target set, a violating
        rolling window defers refills while work is in flight —
        admitting another prompt would push the ITL every live stream
        sees further past target for queue wait nobody measures."""
        if ttft_target is None and itl_target is None:
            return False
        if not any(o >= 0 for o in owner):
            return False            # an empty server always admits
        live = slo.live_slos()
        if (itl_target is not None and live["itl_n"]
                and live["itl_p50_s"] > itl_target):
            return True
        return (ttft_target is not None and live["ttft_n"]
                and live["ttft_p50_s"] > ttft_target)

    def refill(b):
        """Seat the queue head in slot b. Returns True iff the slot
        now owns a request; False covers three distinct paths: the SLO
        gate deferred (request left at the queue head), the pool could
        not cover the prompt (ditto — a retire will free pages), or
        the prefill failed (request re-queued via the retry rules)."""
        nonlocal n_prefills, n_slo_defer
        if _slo_defers():
            n_slo_defer += 1
            return False
        rid, prompt = queue.popleft()
        S = len(prompt)
        hit_pages = (pkv.prefix.match(prompt)
                     if pkv.prefix is not None else [])
        if hit_pages:
            reqlog.emit("prefix_hit", rid, pages=len(hit_pages))
        n_fresh = kvpage.pages_needed(S, pt) - len(hit_pages)
        fresh = pkv.alloc_evicting(n_fresh)
        if fresh is None:
            # Page pressure at admission: put the request BACK at the
            # head (arrival order preserved) and release the trie refs
            # the failed match took; a later retire frees pages.
            for p in hit_pages:
                pkv.alloc.decref(p)
            queue.appendleft((rid, prompt))
            return False
        spanned = _span_app_begin_best_effort(rid)
        reqlog.emit("prefill_start", rid, prompt_len=S,
                    hit_pages=len(hit_pages), fresh_pages=len(fresh))
        try:
            if hit_pages:
                # Radix hit: prefill ONLY the suffix against the
                # cached pages' gathered history.
                P = len(hit_pages) * pt
                suffix = prompt[P:]
                Sb = min(_bucket(len(suffix)), max_len - P,
                         cfg.max_seq - P)
                padded = np.zeros((1, Sb), np.int32)
                padded[0, :len(suffix)] = suffix
                hk, hv = pkv.gather_history(hit_pages)
                logits, sk, sv = suffix_prefill_fn(
                    jnp.asarray(padded), hk, hv, len(suffix) - 1)
                one = {"k": sk, "v": sv}
                if kv_int8:
                    from mpi_acx_tpu.ops.kvquant import kv_quant
                    one["k"], one["ks"] = kv_quant(sk)
                    one["v"], one["vs"] = kv_quant(sv)
                first = int(jnp.argmax(logits[0, 0]))
                pkv.scatter_prompt(one, fresh)
            else:
                padded = np.zeros(
                    (1, min(_bucket(S), max_len, cfg.max_seq)), np.int32)
                padded[0, :S] = prompt
                logits, one = prefill_fn(jnp.asarray(padded), S - 1)
                first = int(jnp.argmax(logits[0, 0]))
                pkv.scatter_prompt(
                    {k: v for k, v in one.items() if k != "pos"}, fresh)
        except Exception as exc:  # noqa: BLE001 — any device failure
            for p in hit_pages + fresh:
                pkv.alloc.decref(p)
            _requeue(rid, prompt, exc, charge=not _peer_dead(exc))
            return False
        finally:
            if spanned:
                _span_app_end_best_effort()
        reqlog.emit("prefill_end", rid, first_token=first)
        pkv.seat(b, hit_pages, fresh, S, rid=rid)
        if pkv.prefix is not None:
            pkv.prefix.insert(prompt, pkv.pages[b])
        owner[b] = rid
        if rid in preempted_rids:
            preempted_rids.discard(rid)
            slo.note_resume()
            reqlog.emit("resume", rid, slot=b)
        emitted[rid].append(first)
        if on_token is not None:
            on_token(rid, first)
        last_tok[b] = first
        n_prefills += 1
        ttft[rid] = time.perf_counter() - t0
        slo.note_ttft(ttft[rid])
        reqlog.emit("stream", rid, n=1, ttft_s=ttft[rid])
        return True

    def retire(b):
        rid = owner[b]
        done[rid] = np.concatenate(
            [np.asarray(prompts[rid], np.int32),
             np.asarray(emitted[rid], np.int32)])
        finish[rid] = time.perf_counter() - t0
        reqlog.emit("finish", rid, new_tokens=len(emitted[rid]),
                    latency_s=finish[rid])
        owner[b] = -1
        pkv.release(b)              # pages back to the pool, slot parked

    def preempt(b):
        """Page-pressure eviction: requeue slot b's request UNCHARGED
        (server pressure is not the request's fault — the peer-loss
        rule) with its pages freed; the replay is bit-equal."""
        nonlocal n_preempts
        rid = owner[b]
        owner[b] = -1
        pkv.release(b)
        emitted[rid] = []
        ttft[rid] = None
        queue.append((rid, np.asarray(prompts[rid], np.int32)))
        n_preempts += 1
        pkv.preemptions += 1
        preempted_rids.add(rid)
        slo.note_preempt()
        reqlog.emit("preempt", rid, slot=b)

    def grow_for_chunk():
        """Before each step: every active slot's table must cover this
        chunk's writes (positions pos..pos+chunk-1). Pool dry even
        after trie eviction -> preempt the latest arrival and rescan;
        admission guarantees a LONE request always fits, so the loop
        terminates (each preemption strictly shrinks the active set)."""
        while True:
            for b in range(n_slots):
                if owner[b] < 0:
                    continue
                need = (int(pkv.pos[b]) + chunk - 1) // pt + 1
                if not pkv.grow(b, need):
                    victims = [s for s in range(n_slots) if owner[s] >= 0]
                    if len(victims) <= 1:
                        raise RuntimeError(
                            "page pool dry for a lone request — "
                            "admission should have rejected it")
                    preempt(max(victims, key=lambda s: owner[s]))
                    break
            else:
                return

    def slot_finished(b):
        rid = owner[b]
        return (len(emitted[rid]) >= n_new[rid]
                or (eos is not None and emitted[rid]
                    and emitted[rid][-1] == eos))

    def _publish():
        kvpage.publish_page_stats_best_effort(
            pkv.alloc.free_count, pkv.alloc.shared_count(),
            pkv.prefix.hits if pkv.prefix else 0,
            pkv.prefix.evictions if pkv.prefix else 0,
            pkv.preemptions)

    qd_samples.append(len(queue))
    while queue and any(o == -1 for o in owner):
        b = owner.index(-1)
        if refill(b):
            if slot_finished(b):
                retire(b)
        else:
            break                   # deferred/short on pages: stop seeding

    stalls = 0
    while any(o >= 0 for o in owner) or queue:
        qd_samples.append(len(queue))
        occ_samples.append(sum(o >= 0 for o in owner) / n_slots)
        slo.note_gauges(qd_samples[-1], occ_samples[-1])
        _tseries_annotate_best_effort(slo.live_slos())
        _publish()
        if queue:
            for b in _check_fleet_rejoin():
                if queue and refill(b) and slot_finished(b):
                    retire(b)
        if not any(o >= 0 for o in owner):
            # All slots idle with requests queued (failure requeues, a
            # deferred seed, or total preemption): reseed. The SLO gate
            # never defers an empty server and admission bounds every
            # queued request, so a stall here means a real bug — bound
            # it instead of spinning.
            progressed = False
            while queue and any(o == -1 for o in owner):
                b = owner.index(-1)
                if refill(b):
                    progressed = True
                    if slot_finished(b):
                        retire(b)
                else:
                    break
            stalls = 0 if progressed else stalls + 1
            if stalls > len(prompts) + n_slots + 2:
                raise RuntimeError(
                    "paged scheduler stalled: queue non-empty, no slot "
                    "seatable (pool exhausted below a single request?)")
            continue
        stalls = 0
        grow_for_chunk()
        if not any(o >= 0 for o in owner):
            continue                # grow_for_chunk preempted everyone
        # COW guard (unreachable under the radix policy — defensive):
        # the pages this chunk writes must be privately owned.
        for b in range(n_slots):
            if owner[b] < 0:
                continue
            for j in range(int(pkv.pos[b]) // pt,
                           (int(pkv.pos[b]) + chunk - 1) // pt + 1):
                if j < len(pkv.pages[b]):
                    pkv.ensure_writable(b, j)
        step_t0 = time.perf_counter()
        state = pkv.device_state()
        try:
            state, toks, keys = step_fn(state, jnp.asarray(last_tok),
                                        keys)
            pkv.absorb(state)
        except Exception as exc:  # noqa: BLE001 — any device failure
            lost_peer = _peer_dead(exc)
            if _flight_dump_best_effort():
                n_hang_dumps += 1
            for b in range(n_slots):
                if owner[b] >= 0:
                    rid = owner[b]
                    owner[b] = -1
                    _requeue(rid, np.asarray(prompts[rid], np.int32),
                             exc, charge=not lost_peer)
            if lost_peer:
                _shed_slot()
            # The step donated the pool buffers: rebuild from zeros and
            # drop every reference (prefix cache included — its pages
            # lived in the donated pool).
            pkv.reset_pool()
            last_tok = np.zeros((n_slots,), np.int32)
            continue
        block = np.asarray(toks, np.int32)           # [chunk, B]
        step_dt = time.perf_counter() - step_t0
        n_steps += 1
        reqlog.emit("decode_step", step=n_steps, dt_s=step_dt,
                    active=sum(o >= 0 for o in owner))
        for b in range(n_slots):
            last_tok[b] = block[-1, b]
            if owner[b] < 0:
                continue
            got = 0
            for c in range(block.shape[0]):
                if slot_finished(b):
                    break
                tok = int(block[c, b])
                emitted[owner[b]].append(tok)
                if on_token is not None:
                    on_token(owner[b], tok)
                itl_samples.append(step_dt / chunk)
                slo.note_itl(step_dt / chunk)
                got += 1
            if got:
                reqlog.emit("stream", owner[b], n=got, itl_s=step_dt / chunk)
        for b in range(n_slots):
            while owner[b] >= 0 and slot_finished(b):
                retire(b)
                if queue:
                    refill(b)

    _publish()
    assert all(d is not None for d in done)
    wall = time.perf_counter() - t0
    per_request = []
    total_new = 0
    for rid in range(len(prompts)):
        if rid in rejected:
            continue
        nt = len(emitted[rid])
        total_new += nt
        lat = finish[rid] if finish[rid] is not None else wall
        per_request.append(RequestTelemetry(
            rid=rid,
            ttft_s=ttft[rid] if ttft[rid] is not None else lat,
            latency_s=lat,
            new_tokens=nt,
            tokens_per_s=nt / lat if lat > 0 else 0.0,
            retries=attempts[rid]))
    metrics = ServingMetrics(
        requests=len(prompts),
        wall_s=wall,
        new_tokens=total_new,
        tokens_per_s=total_new / wall if wall > 0 else 0.0,
        steps=n_steps,
        prefills=n_prefills,
        requeues=n_requeues,
        peer_requeues=n_peer_requeues,
        slots_shed=n_shed,
        slots_revived=n_revived,
        hang_dumps=n_hang_dumps,
        rejections=len(rejected),
        rejection_reasons=_count_reasons(rejected.values()),
        preemptions=n_preempts,
        prefix_hits=pkv.prefix.hits if pkv.prefix else 0,
        prefix_evictions=pkv.prefix.evictions if pkv.prefix else 0,
        prefix_pages_reused=(pkv.prefix.pages_reused if pkv.prefix
                             else 0),
        pages_hwm=pkv.pages_hwm,
        slo_deferrals=n_slo_defer,
        ttft_p50_s=_pct([r.ttft_s for r in per_request], 0.50),
        ttft_p99_s=_pct([r.ttft_s for r in per_request], 0.99),
        itl_p50_s=_pct(itl_samples, 0.50),
        itl_p99_s=_pct(itl_samples, 0.99),
        queue_depth_max=max(qd_samples) if qd_samples else 0,
        queue_depth_mean=(sum(qd_samples) / len(qd_samples)
                          if qd_samples else 0.0),
        slot_occupancy_mean=(sum(occ_samples) / len(occ_samples)
                             if occ_samples else 1.0),
        per_request=per_request)
    batch = ServedBatch(done, metrics)
    if return_paged_state:
        batch.paged_state = pkv
    return batch
