"""Continuous-batching serving loop (single device).

The reference has no serving stack at all (SURVEY.md §0); this module
is the framework-goal tier above models/decoding.py. A static-batch
server leaves slots idle from the moment their request finishes until
the whole batch drains — at B slots and mixed output lengths that is a
bubble of up to (B-1)/B of the work. Here B cache slots decode in
lockstep as ONE jitted step while a host-side scheduler swaps finished
requests out and queued prompts in mid-stream, so the device never
waits for the slowest request.

The mechanism is per-slot positions: decode_layer_scan's vector-pos
mode writes each slot's fresh K/V at its own ``pos[b]`` and
grouped_decode_attend masks each slot at ``cols <= pos[b]`` — every
slot's math is exactly its solo run's (no left-padding, no shared
clock), so greedy outputs are bit-equal to per-request generate()
(tested). Prompts are right-padded to a power-of-two bucket for the
prefill compile cache; pad rows are never attended (they sit past
``pos[b]`` until overwritten by decode writes).

Static shapes throughout: one compiled prefill per bucket length, one
compiled decode step, one compiled slot-scatter — the host loop only
schedules.
"""

from __future__ import annotations

import ctypes
import math
import os
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _pct(samples: List[float], p: float) -> float:
    """Nearest-rank percentile, StepTimer's convention (profiling.py):
    the ceil(p*n)-th smallest sample, no interpolation."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[max(0, math.ceil(p * len(s)) - 1)]


@dataclass
class RequestTelemetry:
    """Per-request serving telemetry (times from the batch's arrival at
    _serve entry, so queue wait is included — the number a caller of a
    serving system actually experiences)."""

    rid: int
    ttft_s: float        # time to first token (prefill emits it)
    latency_s: float     # arrival -> retire
    new_tokens: int
    tokens_per_s: float  # new_tokens / latency_s
    retries: int         # failed attempts that re-queued this request


@dataclass
class ServingMetrics:
    """Batch-level serving telemetry returned on ServedBatch.metrics."""

    requests: int = 0
    wall_s: float = 0.0
    new_tokens: int = 0
    tokens_per_s: float = 0.0     # aggregate: new_tokens / wall_s
    steps: int = 0                # decode step_fn dispatches
    prefills: int = 0             # successful refills
    requeues: int = 0             # failure-path restarts
    peer_requeues: int = 0        # requeues from peer loss (uncharged)
    slots_shed: int = 0           # slots retired to match lost capacity
    slots_revived: int = 0        # shed slots returned after a fleet join
    hang_dumps: int = 0           # flight dumps written on step failure
    ttft_p50_s: float = 0.0
    ttft_p99_s: float = 0.0
    itl_p50_s: float = 0.0        # inter-token latency (per decoded token)
    itl_p99_s: float = 0.0
    queue_depth_max: int = 0
    queue_depth_mean: float = 0.0
    slot_occupancy_mean: float = 0.0  # fraction of slots owned per step
    per_request: List[RequestTelemetry] = field(default_factory=list)


class ServedBatch(list):
    """serve_greedy/serve_sample result: a plain list of per-request
    ``prompt + generated`` arrays (full backward compatibility — index,
    iterate, len as before) carrying the batch telemetry as
    ``.metrics``."""

    def __init__(self, outputs, metrics: ServingMetrics):
        super().__init__(outputs)
        self.metrics = metrics


def _peer_dead(exc: BaseException) -> bool:
    """True iff ``exc`` is peer-loss shaped: the runtime's typed
    AcxPeerDeadError, anything carrying ``error == ERR_PEER_DEAD``
    (a multi-host collective that failed on a dead rank), or an error
    message naming the condition. Peer loss is an infrastructure event,
    not the request's fault — the scheduler requeues its victims without
    charging their retry budget (docs/DESIGN.md "Survivable links")."""
    try:
        from mpi_acx_tpu.runtime import ERR_PEER_DEAD, AcxPeerDeadError
    except Exception:  # pragma: no cover — runtime layer unavailable
        AcxPeerDeadError, ERR_PEER_DEAD = (), 20
    if isinstance(exc, AcxPeerDeadError):
        return True
    if getattr(exc, "error", None) == ERR_PEER_DEAD:
        return True
    msg = str(exc).lower()
    return "peer dead" in msg or "peer_dead" in msg


def _fleet_active() -> Optional[int]:
    """Best-effort count of ACTIVE rank slots in this process's fleet view
    (docs/DESIGN.md §12), or None when the native runtime isn't loaded —
    same no-build/no-load discipline as ``_flight_dump_best_effort``. The
    serving loop polls this to notice capacity RETURNING: a replacement
    rank joining raises the count, and shed slots come back."""
    try:
        import ctypes
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None:
            return None
        out = (ctypes.c_uint64 * 5)()
        _rt._lib.acx_fleet_stats(out)
        return int(out[4])
    except Exception:  # pragma: no cover — diagnostics must never raise
        return None


def _flight_dump_best_effort() -> bool:
    """Write this rank's flight-recorder dump if the operator opted in
    ($ACX_FLIGHT names a prefix — same gate as the fatal-signal dump, so
    deliberate failure-path tests don't litter the cwd) and the native
    runtime is already loaded (never build or load the library just for a
    dump — the serving loop must keep making progress). A failed step
    usually means a comm op wedged underneath XLA; the dump plus
    tools/acx_doctor.py turns 'the batch hung' into 'rank R never sent
    tag T'. Returns True iff a dump file was written."""
    if not os.environ.get("ACX_FLIGHT"):
        return False
    try:
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None:
            return False
        return _rt._lib.acx_flight_dump(None) == 0
    except Exception:  # pragma: no cover — diagnostics must never raise
        return False


class RollingSLO:
    """Sliding-window serving SLOs for the live telemetry plane
    (docs/DESIGN.md §13): TTFT and inter-token-latency samples kept in a
    time-bounded window (default 30 s) plus point-in-time queue-depth and
    slot-occupancy gauges. ``live_slos()`` returns the rolling p50/p99 —
    the numbers an operator watching acx_top needs mid-run, as opposed to
    ServingMetrics' whole-batch aggregates computed at the end."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = float(window_s)
        self._ttft: deque = deque()  # (monotonic t, seconds)
        self._itl: deque = deque()
        self.queue_depth = 0
        self.slot_occupancy = 0.0

    def _trim(self, dq: deque, now: float) -> None:
        cutoff = now - self.window_s
        while dq and dq[0][0] < cutoff:
            dq.popleft()

    def note_ttft(self, seconds: float) -> None:
        now = time.monotonic()
        self._ttft.append((now, float(seconds)))
        self._trim(self._ttft, now)

    def note_itl(self, seconds: float) -> None:
        now = time.monotonic()
        self._itl.append((now, float(seconds)))
        self._trim(self._itl, now)

    def note_gauges(self, queue_depth: int, slot_occupancy: float) -> None:
        self.queue_depth = int(queue_depth)
        self.slot_occupancy = float(slot_occupancy)

    def live_slos(self) -> dict:
        """Rolling-window percentiles + live gauges, JSON-ready."""
        now = time.monotonic()
        self._trim(self._ttft, now)
        self._trim(self._itl, now)
        ttft = [v for _, v in self._ttft]
        itl = [v for _, v in self._itl]
        return {
            "window_s": self.window_s,
            "ttft_p50_s": _pct(ttft, 0.50),
            "ttft_p99_s": _pct(ttft, 0.99),
            "ttft_n": len(ttft),
            "itl_p50_s": _pct(itl, 0.50),
            "itl_p99_s": _pct(itl, 0.99),
            "itl_n": len(itl),
            "queue_depth": self.queue_depth,
            "slot_occupancy": self.slot_occupancy,
        }


def _tseries_annotate_best_effort(fragment: dict) -> bool:
    """Publish ``fragment`` to the native telemetry sampler (it rides along
    under ``"app"`` in every subsequent ACX_TSERIES sample) — but only if
    the native runtime is already loaded AND sampling is armed: same
    no-build/no-load discipline as ``_flight_dump_best_effort``, plus the
    JSON encode is skipped entirely when nobody is sampling. Returns True
    iff the fragment was handed to the sampler."""
    if not os.environ.get("ACX_TSERIES"):
        return False
    try:
        import json as _json
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None or not _rt._lib.acx_tseries_enabled():
            return False
        _rt._lib.acx_tseries_annotate(
            _json.dumps(fragment, separators=(",", ":")).encode())
        return True
    except Exception:  # pragma: no cover — diagnostics must never raise
        return False


def _span_app_begin_best_effort(request_id: int) -> bool:
    """Bracket-open for causal tracing (docs/DESIGN.md §14): ties every
    native op enqueued until the matching end-call to ``request_id``, so
    an offline acx_critpath.py run splits this request's TTFT into queue
    vs compute vs wire. Same no-build/no-load discipline as the
    annotate helper: only if the native runtime is ALREADY loaded and
    tracing is armed (ACX_TRACE). The id is offset by 1 — request ids
    start at 0 and span id 0 means "unspanned" on the native side.
    Returns True iff the bracket was opened (the caller must then close
    it)."""
    if not os.environ.get("ACX_TRACE"):
        return False
    try:
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is None:
            return False
        _rt._lib.acx_span_app_begin(ctypes.c_uint64(request_id + 1))
        return True
    except Exception:  # pragma: no cover — diagnostics must never raise
        return False


def _span_app_end_best_effort() -> None:
    try:
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is not None:
            _rt._lib.acx_span_app_end()
    except Exception:  # pragma: no cover — diagnostics must never raise
        pass


def _bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def make_server_fns(params, cfg, family, chunk: int = 1,
                    kv_int8: bool = False, sample_cfg=None):
    """Compile-once closures for the serve loop: returns (prefill_fn,
    step_fn, scatter_fn, chunk, kv_int8, sample_cfg) — the trailing
    values let serve_greedy/serve_sample verify a reused tuple matches
    the call (chunk is baked into step_fn's scan length, so a tuple
    built for chunk=8 silently mis-serves a chunk=1 call otherwise).
    ``family`` is the model module (models.transformer, models.llama,
    or models.moe_transformer — anything exposing
    prefill/decode_step/init_kv_cache with the shared cache layout).

    ``chunk`` > 1 runs that many decode steps per host call as one
    jitted lax.scan returning the [chunk, B] token block — the
    scheduler then reacts every chunk tokens instead of every token,
    amortizing the host->device dispatch (through a tunneled chip that
    round trip is ~75 ms, dwarfing the ~2 ms step; even host-local it
    is the difference between a driver-bound and a device-bound
    server). The tokens are bit-identical to stepwise decoding; the
    cost is scheduling granularity — a finished slot idles until the
    chunk boundary.

    ``sample_cfg`` = (temperature, top_k, top_p) switches the step from
    greedy argmax to stochastic sampling: the step then carries a [B]
    per-slot key array and each slot draws with ITS OWN key per step,
    split exactly as decoding.sample_generate splits its single key —
    that discipline is what makes serve_sample's outputs equal the solo
    sampled runs."""
    prefill_cache: Dict[int, object] = {}

    def prefill_fn(tokens, last):
        """[1, S_bucket], traced last index -> (logits [1,1,vocab],
        cache). The unembedding runs on the real prompt's final row
        alone (``last_index``): the full-bucket [1, S, vocab] logits —
        ~1/3 of prefill FLOPs at GPT-2 vocab — are never computed."""
        S = tokens.shape[1]
        if S not in prefill_cache:
            prefill_cache[S] = jax.jit(
                lambda t, li, S=S: family.prefill(params, cfg, t, S,
                                                  kv_int8=kv_int8,
                                                  last_index=li))
        return prefill_cache[S](tokens, last)

    if sample_cfg is None:
        def pick(logits, keys):      # greedy: keys unused, pass-through
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), keys
    else:
        from mpi_acx_tpu.models.decoding import sample_logits
        temperature, top_k, top_p = sample_cfg

        def pick(logits, keys):
            # Mirror sample_generate: key, sub = split(key); draw with
            # sub — per slot, so slot b's stream equals the solo run's.
            splits = jax.vmap(jax.random.split)(keys)
            keys, subs = splits[:, 0], splits[:, 1]
            tok = jax.vmap(
                lambda lg, k: sample_logits(lg[None].astype(jnp.float32),
                                            k, temperature, top_k,
                                            top_p)[0])(logits, subs)
            return tok.astype(jnp.int32), keys

    # Donated carries: the loop always proceeds with the returned
    # cache, so XLA may update the slot buffers in place (on CPU the
    # donation is ignored, harmlessly).
    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(cache, tok, keys):
        def one(carry, _):
            cache, tok, keys = carry
            logits, cache = family.decode_step(params, cfg, cache, tok)
            nxt, keys = pick(logits, keys)
            return (cache, nxt, keys), nxt
        (cache, _, keys), toks = lax.scan(one, (cache, tok, keys), None,
                                          length=chunk)
        return cache, toks, keys                     # toks [chunk, B]

    @partial(jax.jit, donate_argnums=(0,))
    def scatter_fn(slots, one, slot_idx, new_pos):
        """Land a freshly prefilled single-request cache (``one``, B=1,
        bucket-length max_len) into slot ``slot_idx`` of the slot
        cache; rows past the bucket keep the slot's old contents (never
        attended: they lie beyond ``new_pos`` until decode overwrites
        them). Int8 slot caches carry their scale buffers ('ks'/'vs')
        through the same per-key scatter."""
        for key in [k for k in ("k", "v", "ks", "vs") if k in slots]:
            src = one[key][:, 0]                    # [L, S_bucket, H, D]
            dst = lax.dynamic_index_in_dim(
                slots[key], slot_idx, 1, keepdims=False)  # [L, max_len,...]
            dst = lax.dynamic_update_slice(
                dst, src, (0, 0, 0, 0))
            slots[key] = lax.dynamic_update_index_in_dim(
                slots[key], dst, slot_idx, 1)
        slots["pos"] = slots["pos"].at[slot_idx].set(new_pos)
        return slots

    # chunk/kv_int8/sample_cfg ride along so the serve entry points can
    # reject a mismatched reuse (e.g. int8 slots + bf16-prefill
    # closures, a step scanning a different chunk length, or a step
    # jitted with different sampling params, fail deep in a trace — or
    # worse, silently — otherwise).
    return prefill_fn, step_fn, scatter_fn, chunk, kv_int8, sample_cfg


def _serve(params, cfg, prompts, n_new, n_slots, max_len, family, eos,
           chunk, server_fns, kv_int8, sample_cfg, key,
           max_request_retries=2):
    """The scheduler shared by serve_greedy and serve_sample — queue,
    slot ownership, chunk-block consumption, retire/refill. Sampling
    only changes (a) how the step picks tokens (make_server_fns
    sample_cfg) and (b) the first token at refill, drawn on the host
    with request rid's own key stream fold_in(key, rid), split exactly
    as decoding.sample_generate splits.

    Degrades gracefully under step/prefill failure (the serving face of
    the runtime's retry plane): a request whose device step raised is
    re-queued from scratch — emitted tokens discarded, so the restart
    replays the same greedy/sampled path bit for bit — up to
    ``max_request_retries`` times before the failure is re-raised with
    the request id attached."""
    if family is None:
        from mpi_acx_tpu.models import transformer as family  # noqa: N813
    assert prompts, "no requests"
    assert all(len(p) > 0 for p in prompts), \
        "zero-length prompt (prefill needs at least one token to attend)"
    n_new = ([int(n_new)] * len(prompts) if np.ndim(n_new) == 0
             else [int(n) for n in n_new])
    assert len(n_new) == len(prompts), (len(n_new), len(prompts))
    assert all(n >= 1 for n in n_new), \
        "n_new >= 1 per request (the prefill itself emits the first token)"
    assert all(len(p) + n + chunk <= max_len
               for p, n in zip(prompts, n_new)), \
        "request (+ chunk overrun) exceeds max_len"
    assert all(len(p) + n + chunk <= cfg.max_seq
               for p, n in zip(prompts, n_new)), \
        "request (+ chunk overrun) exceeds the model's position ceiling"

    if server_fns is None:
        server_fns = make_server_fns(params, cfg, family, chunk=chunk,
                                     kv_int8=kv_int8,
                                     sample_cfg=sample_cfg)
    (prefill_fn, step_fn, scatter_fn, fns_chunk, fns_int8,
     fns_sample) = server_fns
    assert fns_chunk == chunk, \
        (f"server_fns built for chunk={fns_chunk}, this call uses "
         f"chunk={chunk} (the scan length is baked into step_fn)")
    assert fns_int8 == kv_int8, \
        "server_fns built with a different kv_int8 than this call"
    assert fns_sample == sample_cfg, \
        ("server_fns built for different sampling settings "
         f"({fns_sample} vs {sample_cfg})")

    slots = family.init_kv_cache(cfg, n_slots, max_len, kv_int8=kv_int8)
    slots["pos"] = jnp.zeros((n_slots,), jnp.int32)

    queue = deque(enumerate(np.asarray(p, np.int32) for p in prompts))
    # Request id per slot; -1 = idle, -2 = shed (capacity retired after a
    # peer loss — never refilled, skipped by every owner[b] >= 0 loop).
    owner = [-1] * n_slots
    emitted: List[List[int]] = [[] for _ in prompts]
    done: List[Optional[np.ndarray]] = [None] * len(prompts)
    last_tok = np.zeros((n_slots,), np.int32)
    # Per-slot key streams (greedy: dummies the step passes through).
    keys = jax.random.split(key if key is not None else jax.random.key(0),
                            n_slots)

    # Per-request failure budget: a request whose prefill or decode step
    # raised restarts from scratch this many times before the error
    # propagates (0 = fail fast).
    attempts = [0] * len(prompts)

    # Telemetry (RequestTelemetry/ServingMetrics above). All requests
    # "arrive" at entry, so per-request clocks start at t0 — queue wait
    # counts toward TTFT and latency.
    t0 = time.perf_counter()
    ttft = [None] * len(prompts)      # type: List[Optional[float]]
    finish = [None] * len(prompts)    # type: List[Optional[float]]
    # Rolling-window SLOs for the live telemetry plane: fed alongside the
    # whole-batch lists below, published to the ACX_TSERIES sampler once
    # per scheduler iteration (a no-op unless sampling is armed).
    slo = RollingSLO()
    itl_samples: List[float] = []
    qd_samples: List[int] = []
    occ_samples: List[float] = []
    n_steps = 0
    n_prefills = 0
    n_requeues = 0
    n_peer_requeues = 0
    n_shed = 0
    n_revived = 0
    n_hang_dumps = 0
    # Fleet-elastic capacity (docs/DESIGN.md §12): remember how many rank
    # slots were ACTIVE at entry; a later rise (a replacement joined)
    # revives shed serving slots so queued requests rebalance onto the
    # restored capacity. None = no native runtime loaded, feature dormant.
    fleet_active_seen = _fleet_active()

    def _requeue(rid, prompt, exc, charge=True):
        """Put a failed request back on the queue for a bit-equal
        restart (emitted tokens discarded; refill replays the same
        greedy/per-rid-key path), or re-raise past the retry budget.
        ``charge=False`` (peer loss) requeues without spending the
        request's retry budget: losing a rank is not the request's
        fault, and a long recovery must not burn victims out of the
        server."""
        nonlocal n_requeues, n_peer_requeues
        if charge:
            attempts[rid] += 1
            if attempts[rid] > max_request_retries:
                raise RuntimeError(
                    f"request {rid} failed {attempts[rid]} time(s), past "
                    f"max_request_retries={max_request_retries}") from exc
        else:
            n_peer_requeues += 1
        emitted[rid] = []
        ttft[rid] = None   # the replayed attempt re-earns its first token
        n_requeues += 1
        queue.append((rid, prompt))

    def _check_fleet_rejoin():
        """Revive shed slots when the fleet view shows capacity back: a
        joined replacement returns the serving width a peer loss took
        away. Returns the revived slot indices so the caller rebalances
        queued requests onto exactly those — the rest of the schedule is
        untouched. A drop in ACTIVE slots just lowers the watermark, so
        the NEXT join (not the leave that preceded it) triggers revival."""
        nonlocal fleet_active_seen, n_revived
        if fleet_active_seen is None:
            return []
        act = _fleet_active()
        if act is None:
            return []
        revived = []
        if act > fleet_active_seen:
            for b in range(n_slots):
                if owner[b] == -2:
                    owner[b] = -1
                    revived.append(b)
            n_revived += len(revived)
        fleet_active_seen = act
        return revived

    def _shed_slot():
        """Retire one idle slot for good (owner -2): a lost rank shrank
        the job's capacity, so the batch shrinks with it instead of
        hammering the survivors at the old width. Always keeps at least
        one slot alive — a server with zero slots is just an outage."""
        nonlocal n_shed
        alive = [b for b in range(n_slots) if owner[b] != -2]
        idle = [b for b in alive if owner[b] == -1]
        if len(alive) <= 1 or not idle:
            return
        owner[max(idle)] = -2
        n_shed += 1

    def refill(b):
        """Returns True iff slot b now owns a request; a failed prefill
        re-queues the request instead of killing the server."""
        nonlocal slots, keys, n_prefills
        rid, prompt = queue.popleft()
        S = len(prompt)
        # Bucket for the prefill compile cache, capped at max_len so
        # the scatter's update always fits the slot buffer, and at the
        # model's position ceiling (prefill asserts padded S <= max_seq).
        padded = np.zeros((1, min(_bucket(S), max_len, cfg.max_seq)),
                          np.int32)
        padded[0, :S] = prompt
        # Causal-tracing bracket: any native op the prefill triggers
        # (multihost sharded serving pushes activations through MPIX
        # enqueues) is span-tagged with this request's id, so the
        # request's TTFT decomposes offline (acx_critpath.py).
        spanned = _span_app_begin_best_effort(rid)
        try:
            logits, one = prefill_fn(jnp.asarray(padded), S - 1)
            if sample_cfg is None:
                first = int(jnp.argmax(logits[0, 0]))
            else:
                from mpi_acx_tpu.models.decoding import sample_logits
                rkey, sub = jax.random.split(jax.random.fold_in(key, rid))
                first = int(sample_logits(
                    logits[0, 0][None].astype(jnp.float32), sub,
                    *sample_cfg)[0])
                keys = keys.at[b].set(rkey)
            slots = scatter_fn(slots, one, b, S)
        except Exception as exc:  # noqa: BLE001 — any device failure
            _requeue(rid, prompt, exc, charge=not _peer_dead(exc))
            return False
        finally:
            if spanned:
                _span_app_end_best_effort()
        owner[b] = rid
        emitted[rid].append(first)
        last_tok[b] = first
        n_prefills += 1
        ttft[rid] = time.perf_counter() - t0  # prefill emitted token one
        slo.note_ttft(ttft[rid])
        return True

    def retire(b):
        nonlocal slots
        rid = owner[b]
        done[rid] = np.concatenate(
            [np.asarray(prompts[rid], np.int32),
             np.asarray(emitted[rid], np.int32)])
        finish[rid] = time.perf_counter() - t0
        owner[b] = -1
        # Park the freed slot at pos 0: an idle slot keeps stepping in
        # the batch, and a stale pos walks toward max_len where the
        # decode write would land out of bounds on a long-idle slot.
        slots["pos"] = slots["pos"].at[b].set(0)

    def slot_finished(b):
        rid = owner[b]
        return (len(emitted[rid]) >= n_new[rid]
                or (eos is not None and emitted[rid]
                    and emitted[rid][-1] == eos))

    # Seed the slots, retiring 1-token requests on the spot so a slot
    # never enters the decode loop already finished.
    qd_samples.append(len(queue))
    while queue and any(o == -1 for o in owner):
        b = owner.index(-1)
        if refill(b) and slot_finished(b):
            retire(b)

    while any(o >= 0 for o in owner) or queue:
        qd_samples.append(len(queue))
        occ_samples.append(sum(o >= 0 for o in owner) / n_slots)
        slo.note_gauges(qd_samples[-1], occ_samples[-1])
        _tseries_annotate_best_effort(slo.live_slos())
        if queue:
            # Capacity may have returned (a replacement rank joined):
            # revive shed slots and rebalance the backlog onto them.
            for b in _check_fleet_rejoin():
                if queue and refill(b) and slot_finished(b):
                    retire(b)
        if not any(o >= 0 for o in owner):
            # All slots idle with requests still queued: only reachable
            # after a failure re-queued them — reseed and keep serving.
            while queue and any(o == -1 for o in owner):
                b = owner.index(-1)
                if refill(b) and slot_finished(b):
                    retire(b)
            continue
        step_t0 = time.perf_counter()
        try:
            slots, toks, keys = step_fn(slots, jnp.asarray(last_tok), keys)
        except Exception as exc:  # noqa: BLE001 — any device failure
            # step_fn donates the slot cache, so after a failed dispatch
            # its buffers cannot be trusted. Re-queue every active
            # request (bit-equal restart, bounded per request by
            # max_request_retries), rebuild the cache, and continue —
            # the queued-but-unstarted requests are unaffected. A
            # peer-loss failure additionally sheds a slot (the job's
            # capacity shrank with the lost rank) and does NOT charge
            # the victims' retry budget.
            lost_peer = _peer_dead(exc)
            # Snapshot the comm plane before touching anything: the flight
            # dump captures the wedged op/link state as the failure left it.
            if _flight_dump_best_effort():
                n_hang_dumps += 1
            for b in range(n_slots):
                if owner[b] >= 0:
                    rid = owner[b]
                    owner[b] = -1
                    _requeue(rid, np.asarray(prompts[rid], np.int32), exc,
                             charge=not lost_peer)
            if lost_peer:
                _shed_slot()
            slots = family.init_kv_cache(cfg, n_slots, max_len,
                                         kv_int8=kv_int8)
            slots["pos"] = jnp.zeros((n_slots,), jnp.int32)
            keys = jax.random.split(
                key if key is not None else jax.random.key(0), n_slots)
            last_tok = np.zeros((n_slots,), np.int32)
            continue
        block = np.asarray(toks, np.int32)           # [chunk, B]
        # np.asarray forced the device sync, so this dt covers the real
        # device step; each of the chunk tokens shares it evenly — the
        # per-token cadence a streaming client would see.
        step_dt = time.perf_counter() - step_t0
        n_steps += 1
        for b in range(n_slots):
            last_tok[b] = block[-1, b]
            if owner[b] < 0:
                continue
            for c in range(block.shape[0]):
                # A slot that finishes mid-chunk idles (its further
                # tokens are valid continuations past the request's
                # end — dropped); retire/refill happens only at chunk
                # boundaries, the granularity ``chunk`` buys.
                if slot_finished(b):
                    break
                emitted[owner[b]].append(int(block[c, b]))
                itl_samples.append(step_dt / chunk)
                slo.note_itl(step_dt / chunk)
        for b in range(n_slots):
            while owner[b] >= 0 and slot_finished(b):
                retire(b)
                if queue:
                    refill(b)

    assert all(d is not None for d in done)
    wall = time.perf_counter() - t0
    per_request = []
    total_new = 0
    for rid in range(len(prompts)):
        nt = len(emitted[rid])
        total_new += nt
        lat = finish[rid] if finish[rid] is not None else wall
        per_request.append(RequestTelemetry(
            rid=rid,
            ttft_s=ttft[rid] if ttft[rid] is not None else lat,
            latency_s=lat,
            new_tokens=nt,
            tokens_per_s=nt / lat if lat > 0 else 0.0,
            retries=attempts[rid]))
    metrics = ServingMetrics(
        requests=len(prompts),
        wall_s=wall,
        new_tokens=total_new,
        tokens_per_s=total_new / wall if wall > 0 else 0.0,
        steps=n_steps,
        prefills=n_prefills,
        requeues=n_requeues,
        peer_requeues=n_peer_requeues,
        slots_shed=n_shed,
        slots_revived=n_revived,
        hang_dumps=n_hang_dumps,
        ttft_p50_s=_pct([r.ttft_s for r in per_request], 0.50),
        ttft_p99_s=_pct([r.ttft_s for r in per_request], 0.99),
        itl_p50_s=_pct(itl_samples, 0.50),
        itl_p99_s=_pct(itl_samples, 0.99),
        queue_depth_max=max(qd_samples) if qd_samples else 0,
        queue_depth_mean=(sum(qd_samples) / len(qd_samples)
                          if qd_samples else 0.0),
        slot_occupancy_mean=(sum(occ_samples) / len(occ_samples)
                             if occ_samples else 1.0),
        per_request=per_request)
    return ServedBatch(done, metrics)


def serve_greedy(params, cfg, prompts: Sequence[np.ndarray], n_new,
                 n_slots: int, max_len: int, family=None,
                 eos: Optional[int] = None, chunk: int = 1,
                 server_fns=None,
                 kv_int8: bool = False,
                 max_request_retries: int = 2) -> ServedBatch:
    """Serve ``prompts`` (1-D int arrays, any lengths) through
    ``n_slots`` continuously-batched cache slots; each request decodes
    greedily for ``n_new`` tokens (an int, or one per request — the
    mixed-output-length workload is where continuous batching beats a
    static batch) or until ``eos``. Returns, per request, ``prompt +
    generated`` — bit-equal to that request's solo ``family.generate``
    run (per-slot positions, see module docstring). ``chunk`` trades
    scheduling granularity for host-dispatch amortization (see
    make_server_fns); outputs are identical for any chunk. Pass
    ``server_fns`` (a make_server_fns result for the same
    params/cfg/family/chunk/kv_int8 — the flags are checked) to reuse
    compiled programs across calls — a fresh call otherwise rebuilds
    its jit closures and re-traces.
    ``kv_int8`` serves from int8 slot caches (ops/kvquant.py) — the
    long-context regime where the cache stream dominates; outputs then
    equal the solo ``generate(..., kv_int8=True)`` runs bit for bit
    (same codes, same scales, same scale-on-scores read).
    ``max_request_retries`` bounds per-request restarts after a failed
    prefill/step (see _serve) — a transient device fault costs the
    failed requests a replay, not the server.

    The returned list is a ``ServedBatch``: a plain list of outputs
    carrying batch telemetry as ``.metrics`` (per-request TTFT and
    tokens/sec, inter-token latency percentiles, queue depth, slot
    occupancy, requeue counts — see ServingMetrics).
    """
    return _serve(params, cfg, prompts, n_new, n_slots, max_len, family,
                  eos, chunk, server_fns, kv_int8, None, None,
                  max_request_retries=max_request_retries)


def serve_sample(params, cfg, prompts: Sequence[np.ndarray], n_new,
                 n_slots: int, max_len: int, key, family=None,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos: Optional[int] = None, chunk: int = 1,
                 server_fns=None,
                 kv_int8: bool = False,
                 max_request_retries: int = 2) -> ServedBatch:
    """Stochastic continuous batching (temperature / top-k / top-p).

    Request ``rid`` draws from its own key stream
    ``jax.random.fold_in(key, rid)`` with exactly
    decoding.sample_generate's split discipline, so each output equals
    the solo ``family.generate_sample(prompt, n,
    key=jax.random.fold_in(key, rid), ...)`` run bit for bit — the
    scheduler (slot assignment, refill order, chunking) cannot perturb
    any request's sample path. All other parameters (and the
    ``ServedBatch``/telemetry return) as serve_greedy.
    """
    return _serve(params, cfg, prompts, n_new, n_slots, max_len, family,
                  eos, chunk, server_fns, kv_int8,
                  (temperature, top_k, top_p), key,
                  max_request_retries=max_request_retries)
