"""Speculative decoding: a small draft model proposes, the target model
verifies k tokens in ONE forward pass.

Autoregressive decode is latency-bound — every token costs a full pass
whose time is dominated by streaming the target's weights. Speculative
decoding amortizes that stream: the cheap draft model proposes k-1
tokens with sequential cached steps, then the target scores the pending
token plus all proposals in a single k-wide cached window pass (one
weight stream for up to k emitted tokens). The wall-clock win requires
the weight-streaming-bound regime (a real-size target on HBM) and a
draft the target usually agrees with; the mechanism — R window passes
instead of n_new sequential steps — is asserted directly in the tests
(6.0x fewer target passes at full acceptance, k=6). Greedy acceptance
keeps the output equal to the target-only greedy decode up to
floating-point argmax ties: the window pass and sequential decode use
differently-ordered contractions (~1e-8 apart in f32), so a position
whose top-2 logits tie within that noise — or within bf16 rounding
under bf16 compute — can break the equality; the draft can never
otherwise change which tokens appear, only how fast
(tests/test_speculative.py asserts token equality against
transformer.generate for arbitrary draft/target pairs in f32).

TPU-first construction: the whole loop is one jitted ``lax.while_loop``
with static shapes — a fixed-k draft scan, a fixed-width target window
pass, and a token buffer sized S + n_new + k for the final-round
overshoot. Cache rollback is free by design: both KV caches keep their
stale entries for rejected positions, which are always overwritten by
the pass that next occupies those positions before any query can attend
to them (queries at position p attend only to entries <= p, and every
position is re-written in order).

The reference has no serving stack at all (SURVEY.md §0); this sits on
the same decode substrate as the other families
(decoding.decode_layer_scan, grouped_decode_attend).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi_acx_tpu.models import llama as lm
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.decoding import (decode_layer_scan,
                                          grouped_decode_attend)


def _window_pass_llama(params, cfg, cache, tokens):
    """Llama counterpart of :func:`_window_pass`: RoPE at the window's
    absolute positions and grouped-query attention against the
    un-repeated GQA cache (the W-token generalization of
    decoding.grouped_decode_attend)."""
    W = tokens.shape[1]
    pos = cache["pos"]
    max_len = cache["k"].shape[2]
    n_rep = cfg.n_heads // cfg.n_kv_heads
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = pos + jnp.arange(W)

    def qkv_fn(lp, x, _pos):
        return lm._qkv(cfg, lp, x, positions)

    def attend_fn(lp, x, q, kc, vc, _pos):
        o = grouped_decode_attend(q, kc, vc, pos, max_len, n_rep)
        return lm._mlp(cfg, lp, x + o @ lp["wo"].astype(x.dtype))

    x, ks, vs = decode_layer_scan(params["layers"], x, cache["k"],
                                  cache["v"], pos, qkv_fn, attend_fn)
    x = lm.rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["unembed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": ks, "v": vs, "pos": pos + W}


def _family_ops(cfg):
    """(prefill, decode_step, window_pass) for a config's model family."""
    if isinstance(cfg, lm.LlamaConfig):
        return lm.prefill, lm.decode_step, _window_pass_llama
    if isinstance(cfg, tfm.TransformerConfig):
        return tfm.prefill, tfm.decode_step, _window_pass
    raise TypeError(
        f"speculative decoding supports the GPT-2 and Llama families; "
        f"got {type(cfg).__name__}")


def _window_pass(params, cfg, cache, tokens):
    """Process a W-token window against the cache: tokens [1, W] occupy
    positions pos..pos+W-1; returns (logits [1, W, vocab] f32, cache with
    pos advanced by W). Row w attends cache entries <= pos+w (the entries
    for this window are written before the attention reads them)."""
    W = tokens.shape[1]
    pos = cache["pos"]
    max_len = cache["k"].shape[2]
    x = (params["embed"][tokens]
         + lax.dynamic_slice_in_dim(params["pos"], pos, W, 0)[None]
         ).astype(cfg.dtype)

    def qkv_fn(lp, x, pos):
        return tfm._qkv(cfg, lp, x)                    # [1, W, H, Dh]

    def attend_fn(lp, x, q, kc, vc, pos):
        o = grouped_decode_attend(q, kc, vc, pos, max_len, n_rep=1)
        return tfm._mlp(cfg, lp, x + o @ lp["wo"].astype(x.dtype))

    x, ks, vs = decode_layer_scan(params["layers"], x, cache["k"],
                                  cache["v"], pos, qkv_fn, attend_fn)
    x = tfm.layernorm(x, params["lnf_g"], params["lnf_b"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": ks, "v": vs, "pos": pos + W}


@functools.lru_cache(maxsize=64)
def _build(draft_cfg, cfg, S: int, n_new: int, k: int):
    """One compiled speculative loop per (configs, shapes) — configs are
    frozen dataclasses, so they key the cache; repeat calls to
    :func:`speculative_generate` reuse the jitted program instead of
    re-tracing (a fresh inner jit per call costs seconds of compile)."""
    cap = S + n_new + k                      # overshoot slack, last round
    assert cap <= cfg.max_seq and cap <= draft_cfg.max_seq, (
        cap, cfg.max_seq, draft_cfg.max_seq)
    t_prefill, _t_decode, t_window = _family_ops(cfg)
    d_prefill, d_decode, _ = _family_ops(draft_cfg)

    @jax.jit
    def run(draft_params, params, prompt):
        t_logits, t_cache = t_prefill(params, cfg, prompt, cap,
                                      last_only=True)
        _, d_cache = d_prefill(draft_params, draft_cfg, prompt, cap,
                               last_only=True)
        pending = jnp.argmax(t_logits[:, -1], -1).astype(prompt.dtype)

        buf = jnp.zeros((1, cap), prompt.dtype)
        buf = lax.dynamic_update_slice(buf, prompt, (0, 0))
        buf = lax.dynamic_update_slice(buf, pending[:, None], (0, S))

        # State: (n_emitted_after_prompt, pending token, caches, buf,
        # rounds, accepted). `pending` sits at position S+n-1... by the
        # decode convention the pending token occupies pos and is not in
        # any cache yet.
        def cond(state):
            n, *_ = state
            return n < n_new

        def body(state):
            n, pending, d_cache, t_cache, buf, rounds, acc = state

            # -- draft: k cached greedy steps; the first k-1 outputs are
            # the proposals. The k-th step exists to WRITE the draft's
            # cache entry for position P+k-1 (the last proposal's seat):
            # at full acceptance the next round starts past it and would
            # otherwise leave a permanent zero hole the draft attends to
            # forever. At partial acceptance the extra entry is stale but
            # sits at >= the rolled-back pos, so later rounds rewrite it
            # before any query can see it.
            def dstep(carry, _):
                cache, tok = carry
                lg, cache = d_decode(draft_params, draft_cfg, cache, tok)
                nxt = jnp.argmax(lg, -1).astype(tok.dtype)
                return (cache, nxt), nxt

            (d_cache, _), props = lax.scan(
                dstep, (d_cache, pending), None, length=k)
            props = props[:k - 1, 0]                     # [k-1]

            # -- target: one window pass over [pending, props] ----------
            window = jnp.concatenate([pending, props])[None]   # [1, k]
            t_logits, t_cache = t_window(params, cfg, t_cache, window)
            targets = jnp.argmax(t_logits[0], -1).astype(
                prompt.dtype)                            # [k]
            # targets[i] = target's token for position pos+i+1.

            # -- accept the longest matching prefix ---------------------
            matches = props == targets[:k - 1]           # [k-1]
            m = jnp.argmin(
                jnp.concatenate([matches, jnp.zeros((1,), bool)]))
            m = m.astype(jnp.int32)                      # 0..k-1 accepted
            bonus = targets[m]
            # The emitted tokens for positions P+1..P+m+1 are exactly
            # targets[0..m] (accepted proposals equal the target chain,
            # and targets[m] is the bonus/correction). Write the whole
            # window — entries past m are garbage that the next round
            # overwrites before the final trim can expose them.
            buf = lax.dynamic_update_slice(buf, targets[None], (0, S + n))

            emitted = m + 1
            n = n + emitted
            # Roll both caches to the new pending position: pending now
            # sits at S + n - 1... i.e. cache pos = S + n - 1.
            newpos = jnp.asarray(S, jnp.int32) + n - 1
            d_cache = dict(d_cache, pos=newpos)
            t_cache = dict(t_cache, pos=newpos)
            pending = bonus[None]
            return (n, pending, d_cache, t_cache, buf, rounds + 1,
                    acc + m)

        state = (jnp.asarray(1, jnp.int32), pending, d_cache, t_cache,
                 buf, jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
        n, pending, d_cache, t_cache, buf, rounds, acc = lax.while_loop(
            cond, body, state)
        return buf[:, :S + n_new], rounds, acc

    return run


def speculative_generate(
    draft_params, draft_cfg, params, cfg,
    prompt: jax.Array, n_new: int, k: int = 4,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Greedy speculative decode (B=1 — it is a latency technique).

    cfg/draft_cfg select the model family per config type (GPT-2
    TransformerConfig or LlamaConfig; the families may even be mixed, but
    the vocabularies must match — asserted). Returns ``(tokens
    [1, S + n_new], stats)`` where tokens equal the target family's
    ``generate(params, cfg, prompt, n_new)`` (up to fp argmax ties, see
    module docstring) and stats counts
    ``{"rounds": R, "drafted_accepted": A}`` — the target ran R window
    passes (vs n_new sequential steps for plain decode), and A of the
    R*(k-1) drafted tokens were accepted.

    Each round: the draft runs ``k-1`` cached greedy steps from the
    pending token; the target scores the pending token plus the k-1
    proposals in one k-wide window pass; the longest prefix of proposals
    matching the target's own argmax chain is emitted, plus the target's
    next token (the "bonus" — also the correction when a proposal is
    rejected). A round therefore emits 1..k tokens at the cost of ONE
    target pass + k-1 draft steps.

    The compiled loop is cached per (configs, prompt length, n_new, k),
    so repeat calls with the same shapes are trace-free.
    """
    B, S = prompt.shape
    assert B == 1, "speculative decoding is per-sequence (B=1)"
    assert k >= 2, k
    assert draft_cfg.vocab == cfg.vocab, (
        f"draft/target vocabularies differ ({draft_cfg.vocab} vs "
        f"{cfg.vocab}) — acceptance would be meaningless")
    run = _build(draft_cfg, cfg, S, n_new, k)
    tokens, rounds, acc = run(draft_params, params, prompt)
    return tokens, {"rounds": rounds, "drafted_accepted": acc}
