"""Speculative decoding: a small draft model proposes, the target model
verifies k tokens in ONE forward pass.

Autoregressive decode is latency-bound — every token costs a full pass
whose time is dominated by streaming the target's weights. Speculative
decoding amortizes that stream: the cheap draft model proposes k-1
tokens with sequential cached steps, then the target scores the pending
token plus all proposals in a single k-wide cached window pass (one
weight stream for up to k emitted tokens). The wall-clock win requires
the weight-streaming-bound regime (a real-size target on HBM) and a
draft the target usually agrees with; the mechanism — R window passes
instead of n_new sequential steps — is asserted directly in the tests
(6.0x fewer target passes at full acceptance, k=6). Greedy acceptance
keeps the output equal to the target-only greedy decode up to
floating-point argmax ties: the window pass and sequential decode use
differently-ordered contractions (~1e-8 apart in f32), so a position
whose top-2 logits tie within that noise — or within bf16 rounding
under bf16 compute — can break the equality; the draft can never
otherwise change which tokens appear, only how fast
(tests/test_speculative.py asserts token equality against
transformer.generate for arbitrary draft/target pairs in f32).

TPU-first construction: the whole loop is one jitted ``lax.while_loop``
with static shapes — a fixed-k draft scan, a fixed-width target window
pass, and a token buffer sized S + n_new + k for the final-round
overshoot. Cache rollback is free by design: both KV caches keep their
stale entries for rejected positions, which are always overwritten by
the pass that next occupies those positions before any query can attend
to them (queries at position p attend only to entries <= p, and every
position is re-written in order).

The reference has no serving stack at all (SURVEY.md §0); this sits on
the same decode substrate as the other families
(decoding.decode_layer_scan, grouped_decode_attend).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi_acx_tpu.ops.wquant import wread

from mpi_acx_tpu.models import llama as lm
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.decoding import (decode_layer_scan,
                                          grouped_decode_attend)


def _window_pass_llama(params, cfg, cache, tokens):
    """Llama counterpart of :func:`_window_pass`: RoPE at the window's
    absolute positions and grouped-query attention against the
    un-repeated GQA cache (the W-token generalization of
    decoding.grouped_decode_attend)."""
    W = tokens.shape[1]
    pos = cache["pos"]
    max_len = cache["k"].shape[2]
    n_rep = cfg.n_heads // cfg.n_kv_heads
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = pos + jnp.arange(W)

    def qkv_fn(lp, x, _pos):
        return lm._qkv(cfg, lp, x, positions)

    def attend_fn(lp, x, q, kc, vc, _pos):
        o = grouped_decode_attend(q, kc, vc, pos, max_len, n_rep,
                                  flash=cfg.decode_flash)
        return lm._mlp(cfg, lp, x + o @ wread(lp, "wo", x.dtype))

    x, ks, vs = decode_layer_scan(params["layers"], x, cache["k"],
                                  cache["v"], pos, qkv_fn, attend_fn)
    x = lm.rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["unembed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": ks, "v": vs, "pos": pos + W}


def _family_ops(cfg):
    """(prefill, decode_step, window_pass) for a config's model family."""
    from mpi_acx_tpu.models.moe_transformer import (MoeTransformerConfig,
                                                    _moe_ffn)

    if isinstance(cfg, lm.LlamaConfig):
        return lm.prefill, lm.decode_step, _window_pass_llama
    if isinstance(cfg, MoeTransformerConfig):
        # The MoE family rides the GPT-2 scaffold with its routed FFN
        # plugged into every pass (same hook as prefill/decode_step).
        return (functools.partial(tfm.prefill, ffn=_moe_ffn),
                functools.partial(tfm.decode_step, ffn=_moe_ffn),
                functools.partial(_window_pass, ffn=_moe_ffn))
    if isinstance(cfg, tfm.TransformerConfig):
        return tfm.prefill, tfm.decode_step, _window_pass
    raise TypeError(
        f"speculative decoding supports the GPT-2, Llama, and "
        f"MoE-transformer families; got {type(cfg).__name__}")


def _window_pass(params, cfg, cache, tokens, ffn=None):
    """Process a W-token window against the cache: tokens [1, W] occupy
    positions pos..pos+W-1; returns (logits [1, W, vocab] f32, cache with
    pos advanced by W). Row w attends cache entries <= pos+w (the entries
    for this window are written before the attention reads them).
    ``ffn(cfg, lp, x) -> x`` overrides the feed-forward half, exactly as
    on tfm.prefill/decode_step — the MoE family plugs in its routed FFN.
    """
    ffn = ffn or tfm._mlp
    W = tokens.shape[1]
    pos = cache["pos"]
    max_len = cache["k"].shape[2]
    x = (params["embed"][tokens]
         + lax.dynamic_slice_in_dim(params["pos"], pos, W, 0)[None]
         ).astype(cfg.dtype)

    def qkv_fn(lp, x, pos):
        return tfm._qkv(cfg, lp, x)                    # [1, W, H, Dh]

    def attend_fn(lp, x, q, kc, vc, pos):
        o = grouped_decode_attend(q, kc, vc, pos, max_len, n_rep=1,
                                  flash=cfg.decode_flash)
        return ffn(cfg, lp, x + o @ wread(lp, "wo", x.dtype))

    x, ks, vs = decode_layer_scan(params["layers"], x, cache["k"],
                                  cache["v"], pos, qkv_fn, attend_fn)
    x = tfm.layernorm(x, params["lnf_g"], params["lnf_b"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": ks, "v": vs, "pos": pos + W}

def _make_run(draft_cfg, cfg, S, n_new, k, pick0, draft_pick, decide,
              ops=None):
    """The ONE speculative round skeleton (prefill, draft scan with the
    k-th cache-seat step, window pass, buffer/cache bookkeeping,
    while_loop) shared by the greedy and stochastic variants, which
    differ only through three hooks:

    pick0(logits [1,V], key) -> pending [1]      (first token)
    draft_pick(lg [1,V], key) -> nxt [1]         (proposal choice)
    decide(props [k-1], q_logits [k-1,V], p_logits [k,V], key)
        -> (emit [k], m, pending [1])            (accept + finalize)

    ``ops`` overrides the model-family operations as a tuple
    ``(t_prefill, t_window, d_prefill, d_decode)`` with the family
    signatures (params first) — the tensor-parallel speculative builder
    injects per-shard TP variants here; None selects the single-device
    family ops by config type. Returns the RAW traceable ``run`` —
    callers jit it themselves (or embed it in an outer shard_map/jit).

    Cache invariants (identical for both variants): the draft runs k
    steps so full-acceptance rounds leave no unwritten cache seat; stale
    entries sit at >= the rolled-back pos and are rewritten before any
    query can see them; buffer garbage past slot m is overwritten next
    round or trimmed by the final ``buf[:, :S + n_new]``.
    """
    cap = S + n_new + k                      # overshoot slack, last round
    assert cap <= cfg.max_seq and cap <= draft_cfg.max_seq, (
        cap, cfg.max_seq, draft_cfg.max_seq)
    if ops is None:
        t_prefill, _t_decode, t_window = _family_ops(cfg)
        d_prefill, d_decode, _ = _family_ops(draft_cfg)
    else:
        t_prefill, t_window, d_prefill, d_decode = ops

    def run(draft_params, params, prompt, key):
        t_logits, t_cache = t_prefill(params, cfg, prompt, cap,
                                      last_only=True)
        _, d_cache = d_prefill(draft_params, draft_cfg, prompt, cap,
                               last_only=True)
        key, k0 = jax.random.split(key)
        pending = pick0(t_logits[:, -1], k0).astype(prompt.dtype)

        buf = jnp.zeros((1, cap), prompt.dtype)
        buf = lax.dynamic_update_slice(buf, prompt, (0, 0))
        buf = lax.dynamic_update_slice(buf, pending[:, None], (0, S))

        # State: (n_emitted_after_prompt, pending token, caches, buf,
        # key, rounds, accepted). By the decode convention the pending
        # token occupies cache pos = S + n - 1 and is in no cache yet.
        def cond(state):
            n, *_ = state
            return n < n_new

        def body(state):
            n, pending, d_cache, t_cache, buf, key, rounds, acc = state
            key, kd, kdec = jax.random.split(key, 3)

            def dstep(carry, skey):
                cache, tok = carry
                lg, cache = d_decode(draft_params, draft_cfg, cache, tok)
                nxt = draft_pick(lg, skey).astype(tok.dtype)
                return (cache, nxt), (nxt, lg[0])

            (d_cache, _), (props_all, q_logits) = lax.scan(
                dstep, (d_cache, pending), jax.random.split(kd, k))
            props = props_all[:k - 1, 0]                   # [k-1]

            window = jnp.concatenate([pending, props])[None]   # [1, k]
            t_logits, t_cache = t_window(params, cfg, t_cache, window)

            emit, m, pending = decide(props, q_logits[:k - 1],
                                      t_logits[0], kdec)
            emit = emit.astype(prompt.dtype)
            pending = pending.astype(prompt.dtype)
            # Entries of emit past slot m are garbage the next round
            # overwrites before the final trim can expose them.
            buf = lax.dynamic_update_slice(buf, emit[None], (0, S + n))

            n = n + m + 1
            # Roll both caches to the new pending position S + n - 1.
            newpos = jnp.asarray(S, jnp.int32) + n - 1
            d_cache = dict(d_cache, pos=newpos)
            t_cache = dict(t_cache, pos=newpos)
            return (n, pending, d_cache, t_cache, buf, key, rounds + 1,
                    acc + m)

        state = (jnp.asarray(1, jnp.int32), pending, d_cache, t_cache,
                 buf, key, jnp.asarray(0, jnp.int32),
                 jnp.asarray(0, jnp.int32))
        n, pending, d_cache, t_cache, buf, key, rounds, acc = \
            lax.while_loop(cond, body, state)
        return buf[:, :S + n_new], rounds, acc

    return run


def _greedy_hooks(k: int):
    """(pick0, draft_pick, decide) for GREEDY speculation: argmax
    proposals, the longest prefix matching the target's argmax chain
    accepted, the target's argmax as the bonus/correction. The hooks
    ignore their key arguments."""
    def pick0(logits, key):
        return jnp.argmax(logits, -1)

    def draft_pick(lg, key):
        return jnp.argmax(lg, -1)

    def decide(props, q_logits, t_logits, key):
        targets = jnp.argmax(t_logits, -1).astype(props.dtype)   # [k]
        matches = props == targets[:k - 1]
        m = jnp.argmin(jnp.concatenate([matches, jnp.zeros((1,), bool)]))
        m = m.astype(jnp.int32)
        # Emitted tokens are exactly targets[0..m] (accepted proposals
        # equal the target chain; targets[m] is the bonus/correction).
        return targets, m, targets[m][None]

    return pick0, draft_pick, decide


@functools.lru_cache(maxsize=64)
def _build(draft_cfg, cfg, S: int, n_new: int, k: int):
    """Compiled GREEDY speculative loop (hooks: _greedy_hooks). One
    compiled program per (configs, shapes) — the configs are frozen
    dataclasses, so they key the lru_cache and repeat calls are
    trace-free. The public wrapper passes a dummy key."""
    run = _make_run(draft_cfg, cfg, S, n_new, k, *_greedy_hooks(k))
    return jax.jit(run)


def _sample_hooks(k: int, temperature: float):
    """(pick0, draft_pick, decide) for STOCHASTIC speculation (the
    Leviathan/Chen accept/resample algorithm): proposals are SAMPLED
    from the draft at ``temperature``, each accepted with probability
    min(1, p(x)/q(x)) under the target's distribution p and the
    draft's q; on rejection the token is resampled from
    normalize(max(p - q, 0)). Every emitted token is therefore
    distributed EXACTLY as target-only sampling at the same temperature
    (the algorithm's defining guarantee — tests/test_speculative.py
    checks the two-token joint distribution against exact
    teacher-forced target probabilities)."""
    assert temperature > 0.0, temperature
    inv_t = 1.0 / temperature

    def pick0(logits, key):
        return jax.random.categorical(key, logits * inv_t, axis=-1)

    def draft_pick(lg, key):
        return jax.random.categorical(key, lg * inv_t, axis=-1)

    def decide(props, q_logits, t_logits, key):
        ka, kr = jax.random.split(key)
        q = jax.nn.softmax(q_logits * inv_t, -1)       # [k-1, V]
        p = jax.nn.softmax(t_logits * inv_t, -1)       # [k, V]
        # Accept x_i with prob min(1, p_i(x_i)/q_i(x_i)).
        idx = props.astype(jnp.int32)
        p_x = jnp.take_along_axis(p[:k - 1], idx[:, None], 1)[:, 0]
        q_x = jnp.take_along_axis(q, idx[:, None], 1)[:, 0]
        u = jax.random.uniform(ka, (k - 1,))
        accept = u * q_x < p_x                         # [k-1]
        m = jnp.argmin(jnp.concatenate([accept, jnp.zeros((1,), bool)]))
        m = m.astype(jnp.int32)                        # accepted count
        # Final token: on rejection at slot m, resample from the
        # residual (p_m - q_m)^+; at full acceptance, a free sample
        # from p_{k-1}.
        p_m = p[m]
        q_m = q[jnp.minimum(m, k - 2)]
        residual = jnp.where(m < k - 1,
                             jnp.maximum(p_m - q_m, 0.0), p_m)
        # All-zero residual (p <= q everywhere, numerically) falls back
        # to p_m — distribution-correct when p == q.
        residual = jnp.where(residual.sum() > 0, residual, p_m)
        y = jax.random.categorical(kr, jnp.log(residual + 1e-30))
        emit = jnp.concatenate([props, jnp.zeros((1,), props.dtype)])
        emit = lax.dynamic_update_slice(
            emit, y[None].astype(props.dtype), (m,))
        return emit, m, y[None]

    return pick0, draft_pick, decide


@functools.lru_cache(maxsize=64)
def _build_sample(draft_cfg, cfg, S: int, n_new: int, k: int,
                  temperature: float):
    """Compiled STOCHASTIC speculative loop (hooks: _sample_hooks);
    cached per (configs, shapes, temperature) like :func:`_build`."""
    run = _make_run(draft_cfg, cfg, S, n_new, k,
                    *_sample_hooks(k, temperature))
    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _build_batched(draft_cfg, cfg, S: int, n_new: int, k: int,
                   temperature):
    """Batched speculative loop as ``vmap`` of the single-sequence
    program (temperature None = greedy, else stochastic).

    Rows advance INDEPENDENTLY: each row is the complete B=1
    ``lax.while_loop`` round loop, and JAX's while_loop batching rule
    lifts the batch to ONE loop that runs while any row is active,
    select-guarding every row's carry by its own predicate — a finished
    row's buffer, caches, and stats stop changing while the stragglers
    run on. Per-row cache positions, buffer offsets, and acceptance
    counts fall out of the same rule (the scalar ``pos`` becomes a [B]
    vector, the dynamic updates become scatters). This is the TPU-first
    answer to per-row speculative state that CUDA serving stacks
    hand-schedule: the transform, not the kernel, carries the
    bookkeeping. Masked work on finished rows is the usual batched-
    speculation cost and is bounded by the slowest row's round count.
    """
    if temperature is None:
        run = _build(draft_cfg, cfg, S, n_new, k)
    else:
        run = _build_sample(draft_cfg, cfg, S, n_new, k, temperature)

    @jax.jit
    def runb(draft_params, params, prompts, keys):
        tokens, rounds, acc = jax.vmap(
            lambda row, kk: run(draft_params, params, row[None], kk)
        )(prompts, keys)
        return tokens[:, 0], rounds, acc

    return runb


def _check_moe_target(cfg):
    """An MoE TARGET must be in the drop-free capacity regime: the window
    pass routes k tokens as ONE dispatch group while plain decode routes
    1, so with tight capacity a popular expert could drop tokens in one
    pass and not the other — silently breaking the exactness guarantees.
    capacity_factor >= n_experts makes every group drop-free (each
    expert can seat every token). A MoE DRAFT needs no guard: it only
    shapes acceptance, never the emitted distribution."""
    from mpi_acx_tpu.models.moe_transformer import MoeTransformerConfig
    if isinstance(cfg, MoeTransformerConfig):
        assert cfg.capacity_factor >= cfg.n_experts, (
            f"MoE speculative target needs drop-free routing "
            f"(capacity_factor {cfg.capacity_factor} < n_experts "
            f"{cfg.n_experts}); see moe_transformer.decode_step")


def speculative_sample(
    draft_params, draft_cfg, params, cfg,
    prompt: jax.Array, n_new: int, key: jax.Array, k: int = 4,
    temperature: float = 1.0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Stochastic speculative decode: same round structure as
    :func:`speculative_generate` but with SAMPLED proposals and the
    accept/resample rule, so every emitted token follows the target's
    sampling distribution at ``temperature`` exactly — the draft changes
    only latency, never the distribution. Returns ``(tokens, stats)``
    like the greedy variant; at B > 1 each row samples under its own
    fold of ``key`` and the stats are per-row (see
    :func:`speculative_generate`)."""
    B, S = prompt.shape
    assert k >= 2, k
    assert draft_cfg.vocab == cfg.vocab, (draft_cfg.vocab, cfg.vocab)
    _check_moe_target(cfg)
    if B == 1:
        run = _build_sample(draft_cfg, cfg, S, n_new, k,
                            float(temperature))
        tokens, rounds, acc = run(draft_params, params, prompt, key)
    else:
        runb = _build_batched(draft_cfg, cfg, S, n_new, k,
                              float(temperature))
        tokens, rounds, acc = runb(draft_params, params, prompt,
                                   jax.random.split(key, B))
    return tokens, {"rounds": rounds, "drafted_accepted": acc}


def speculative_generate(
    draft_params, draft_cfg, params, cfg,
    prompt: jax.Array, n_new: int, k: int = 4,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Greedy speculative decode.

    cfg/draft_cfg select the model family per config type (GPT-2
    TransformerConfig, LlamaConfig, or MoeTransformerConfig — an MoE
    target additionally requires drop-free capacity, see
    _check_moe_target; the families may be mixed freely, but the
    vocabularies must match — asserted). Returns ``(tokens
    [B, S + n_new], stats)`` where each row of tokens equals the target
    family's ``generate(params, cfg, prompt, n_new)`` on that row (up to
    fp argmax ties, see module docstring) and stats counts
    ``{"rounds": R, "drafted_accepted": A}`` — the target ran R window
    passes (vs n_new sequential steps for plain decode), and A of the
    R*(k-1) drafted tokens were accepted. At B == 1 both stats are
    scalars; at B > 1 they are per-row [B] vectors and rows advance
    independently through the vmap-lifted loop (see
    :func:`_build_batched`) — each row's output and stats are those of
    its own B=1 run, while wall-clock is bounded by the slowest row
    (finished rows ride along masked until the batch drains).

    Each round: the draft runs ``k-1`` cached greedy steps from the
    pending token; the target scores the pending token plus the k-1
    proposals in one k-wide window pass; the longest prefix of proposals
    matching the target's own argmax chain is emitted, plus the target's
    next token (the "bonus" — also the correction when a proposal is
    rejected). A round therefore emits 1..k tokens at the cost of ONE
    target pass + k-1 draft steps.

    The compiled loop is cached per (configs, prompt length, n_new, k),
    so repeat calls with the same shapes are trace-free.
    """
    B, S = prompt.shape
    assert k >= 2, k
    assert draft_cfg.vocab == cfg.vocab, (
        f"draft/target vocabularies differ ({draft_cfg.vocab} vs "
        f"{cfg.vocab}) — acceptance would be meaningless")
    _check_moe_target(cfg)
    if B == 1:
        run = _build(draft_cfg, cfg, S, n_new, k)
        tokens, rounds, acc = run(draft_params, params, prompt,
                                  jax.random.key(0))   # hooks ignore it
    else:
        runb = _build_batched(draft_cfg, cfg, S, n_new, k, None)
        tokens, rounds, acc = runb(
            draft_params, params, prompt,
            jax.random.split(jax.random.key(0), B))    # hooks ignore it
    return tokens, {"rounds": rounds, "drafted_accepted": acc}
