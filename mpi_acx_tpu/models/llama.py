"""Llama-family decoder (RMSNorm, RoPE, SwiGLU, grouped-query attention),
pure functional JAX.

Second model family next to the GPT-2 transformer (models/transformer.py);
the workload behind BASELINE.json configs[4] ("Llama-3-8B activation/grad
pipeline exchange"). Same TPU-first construction: stacked-layer params
scanned with ``lax.scan`` (stage-sliceable for pipeline parallelism with
:func:`mpi_acx_tpu.models.transformer.stage_slice`-style reshapes), bf16
compute with f32 norms/softmax, static shapes, and the shared flash/dense
attention policy (GQA K/V heads are broadcast to query heads before the
kernel — the cache still stores only ``n_kv_heads``, which is GQA's
inference memory win).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from mpi_acx_tpu.ops.wquant import wread


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 128256
    d_model: int = 4096
    n_heads: int = 32
    n_kv_heads: int = 8          # GQA: queries share K/V head groups
    n_layers: int = 32
    d_ff: int = 14336            # SwiGLU hidden
    max_seq: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    use_flash: Optional[bool] = None  # None = shared auto policy
    decode_flash: Optional[bool] = None  # decode kernel; None = auto

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def llama3_8b() -> LlamaConfig:
    """Llama-3-8B geometry (BASELINE.json configs[4])."""
    return LlamaConfig()


def tiny_llama(vocab: int = 256, d_model: int = 64, n_heads: int = 4,
               n_kv_heads: int = 2, n_layers: int = 2, d_ff: int = 128,
               max_seq: int = 64) -> LlamaConfig:
    """Small config for tests and virtual-mesh dryruns."""
    return LlamaConfig(vocab=vocab, d_model=d_model, n_heads=n_heads,
                       n_kv_heads=n_kv_heads, n_layers=n_layers, d_ff=d_ff,
                       max_seq=max_seq, rope_theta=10000.0)


Params = Dict[str, Any]


def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    """Stacked-layer parameter pytree ([n_layers] leading axis per leaf)."""
    k = jax.random.split(key, 8)
    L, d, ff = cfg.n_layers, cfg.d_model, cfg.d_ff
    dh, hq, hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    s = 0.02

    def nrm(key, *shape, scale=s):
        return jax.random.normal(key, shape, jnp.float32) * scale

    return {
        "embed": nrm(k[0], cfg.vocab, d),
        "layers": {
            "attn_norm": jnp.ones((L, d)),
            "wq": nrm(k[1], L, d, hq * dh),
            "wk": nrm(k[2], L, d, hkv * dh),
            "wv": nrm(k[3], L, d, hkv * dh),
            "wo": nrm(k[4], L, hq * dh, d, scale=s / (2 * L) ** 0.5),
            "mlp_norm": jnp.ones((L, d)),
            "w_gate": nrm(k[5], L, d, ff),
            "w_up": nrm(k[6], L, d, ff),
            "w_down": nrm(k[7], L, ff, d, scale=s / (2 * L) ** 0.5),
        },
        "final_norm": jnp.ones((d,)),
        # Untied output head (Llama style).
        "unembed": nrm(jax.random.fold_in(key, 99), cfg.vocab, d),
    }


def rmsnorm(x, g, eps=1e-5):
    x32 = x.astype(jnp.float32)
    rms = lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms * g).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x [..., S, H, D], positions [S] (or [..., S])."""
    D = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    ang = positions[..., None].astype(jnp.float32) * freqs       # [..., S, D/2]
    cos = jnp.cos(ang)[..., None, :]                             # [..., S, 1, D/2]
    sin = jnp.sin(ang)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D]: broadcast K/V head groups
    to the query heads (GQA -> MHA view for the attention kernel)."""
    if n_rep == 1:
        return x
    B, S, H, D = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :],
                            (B, S, H, n_rep, D)).reshape(B, S, H * n_rep, D)


def _qkv(cfg: LlamaConfig, lp: Params, x: jax.Array, positions: jax.Array):
    B, S, _ = x.shape
    h = rmsnorm(x, lp["attn_norm"])
    q = (h @ wread(lp, "wq", x.dtype)).reshape(B, S, cfg.n_heads,
                                               cfg.head_dim)
    k = (h @ wread(lp, "wk", x.dtype)).reshape(B, S, cfg.n_kv_heads,
                                               cfg.head_dim)
    v = (h @ wread(lp, "wv", x.dtype)).reshape(B, S, cfg.n_kv_heads,
                                               cfg.head_dim)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    return q, k, v


def _attend(cfg: LlamaConfig, q, k, v):
    """Post-RoPE attention with K/V broadcast to query heads; the kernel
    choice delegates to the shared flash/dense policy."""
    from mpi_acx_tpu.ops.attention import select_attention
    n_rep = cfg.n_heads // cfg.n_kv_heads
    k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
    o = select_attention(cfg.use_flash)(q, k, v)
    B, S = q.shape[:2]
    return o.reshape(B, S, cfg.n_heads * cfg.head_dim)


def _mlp(cfg: LlamaConfig, lp: Params, x: jax.Array):
    h = rmsnorm(x, lp["mlp_norm"])
    gate = jax.nn.silu(h @ wread(lp, "w_gate", x.dtype))
    up = h @ wread(lp, "w_up", x.dtype)
    return x + (gate * up) @ wread(lp, "w_down", x.dtype)


def block(cfg: LlamaConfig, lp: Params, x: jax.Array,
          positions: jax.Array) -> jax.Array:
    q, k, v = _qkv(cfg, lp, x, positions)
    x = x + _attend(cfg, q, k, v) @ wread(lp, "wo", x.dtype)
    return _mlp(cfg, lp, x)


def _hidden(params: Params, cfg: LlamaConfig,
            tokens: jax.Array) -> jax.Array:
    """The model trunk: tokens [B, S] -> final-rmsnormed hidden states
    [B, S, d]. Shared by :func:`forward` and the chunked-CE loss path so
    dtype policy / block wiring can never diverge between them."""
    S = tokens.shape[1]
    assert S <= cfg.max_seq, (S, cfg.max_seq)
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.arange(S)

    def body(x, lp):
        return block(cfg, lp, x, positions), None

    x, _ = lax.scan(body, x, params["layers"])
    return rmsnorm(x, params["final_norm"])


def forward(params: Params, cfg: LlamaConfig,
            tokens: jax.Array) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, vocab] f32."""
    x = _hidden(params, cfg, tokens)
    return jnp.einsum("bsd,vd->bsv", x, params["unembed"].astype(x.dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params: Params, cfg: LlamaConfig, tokens: jax.Array,
            targets: jax.Array,
            xent_chunk: int | None = None) -> jax.Array:
    """Mean next-token cross-entropy; ``xent_chunk`` selects the
    memory-bounded chunked-vocab CE (ops/xent.py — the [B, S, vocab]
    logits never materialize; same values/grads to fp summation
    order)."""
    if xent_chunk is not None:
        from mpi_acx_tpu.ops.xent import chunked_xent_ll
        B, S = tokens.shape
        x = _hidden(params, cfg, tokens)
        ll = chunked_xent_ll(x.reshape(B * S, -1), params["unembed"],
                             targets.reshape(-1), xent_chunk)
        return -jnp.mean(ll)
    logits = forward(params, cfg, tokens)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


# -- KV-cache decode (GQA: the cache stores only n_kv_heads) ---------------


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int,
                  kv_int8: bool = False):
    """GQA cache (n_kv_heads, the memory win); ``kv_int8=True`` stores
    int8 codes + per-(position, head) f32 scales (ops/kvquant.py)."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    cache = {
        "k": jnp.zeros(shape, jnp.int8 if kv_int8 else cfg.dtype),
        "v": jnp.zeros(shape, jnp.int8 if kv_int8 else cfg.dtype),
        "pos": jnp.zeros((), jnp.int32),
    }
    if kv_int8:
        cache["ks"] = jnp.zeros(shape[:-1] + (1,), jnp.float32)
        cache["vs"] = jnp.zeros(shape[:-1] + (1,), jnp.float32)
    return cache


def prefill(params: Params, cfg: LlamaConfig, tokens: jax.Array,
            max_len: int, last_only: bool = False,
            kv_int8: bool = False, last_index=None):
    """Prompt pass filling a fresh KV cache (layout: init_kv_cache).
    Prefill attention runs on the exact bf16 K/V; with ``kv_int8`` only
    the CACHE entries are quantized. ``last_index`` (traced scalar):
    unembed position ``last_index`` alone — bucket-padded serving
    prompts (see transformer.prefill)."""
    B, S = tokens.shape
    assert S <= max_len and S <= cfg.max_seq, (S, max_len, cfg.max_seq)
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.arange(S)

    def body(x, lp):
        q, k, v = _qkv(cfg, lp, x, positions)
        x = x + _attend(cfg, q, k, v) @ wread(lp, "wo", x.dtype)
        x = _mlp(cfg, lp, x)
        return x, (k, v)

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    x = rmsnorm(x, params["final_norm"])
    if last_index is not None:
        x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
    elif last_only:
        x = x[:, -1:]
    logits = jnp.einsum("bsd,vd->bsv", x, params["unembed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
    from mpi_acx_tpu.models.decoding import fill_kv_cache
    cache = fill_kv_cache(init_kv_cache(cfg, B, max_len,
                                        kv_int8=kv_int8), ks, vs, S)
    return logits, cache


from mpi_acx_tpu.models.decoding import (  # noqa: F401  (re-export)
    grouped_decode_attend,
)


def decode_step(params: Params, cfg: LlamaConfig, cache,
                token: jax.Array):
    """One autoregressive step; token [B] -> (logits [B, vocab] f32,
    updated cache). Fixed shapes: jit once per generation.

    The cache update runs through the shared carry-scan
    (decoding.decode_layer_scan): in-place updates, 1.9x faster decode
    on v5e than scan-ys stacking."""
    pos = jnp.asarray(cache["pos"])
    max_len = cache["k"].shape[2]
    n_rep = cfg.n_heads // cfg.n_kv_heads
    x = params["embed"][token][:, None, :].astype(cfg.dtype)
    # Scalar pos -> shared position [1]; per-slot pos [B] (serving) ->
    # [B, 1] so each slot's RoPE rotates by its own position.
    positions = pos[:, None] if pos.ndim else jnp.full((1,), pos)

    def qkv_fn(lp, x, pos):
        return _qkv(cfg, lp, x, positions)               # k,v [B,1,Hkv,D]

    def attend_fn(lp, x, q, kc, vc, pos):
        o = grouped_decode_attend(q, kc, vc, pos, max_len, n_rep,
                                  flash=cfg.decode_flash)
        return _mlp(cfg, lp, x + o @ wread(lp, "wo", x.dtype))

    from mpi_acx_tpu.models.decoding import run_decode_layers
    x, out_cache = run_decode_layers(params["layers"], x, cache,
                                     qkv_fn, attend_fn)
    x = rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["unembed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)[:, 0]
    return logits, out_cache


def generate(params: Params, cfg: LlamaConfig, prompt: jax.Array,
             n_new: int, max_len: Optional[int] = None,
             kv_int8: bool = False) -> jax.Array:
    """Greedy decode: prompt [B, S] -> [B, S + n_new]. ``kv_int8``
    selects the quantized KV cache (ops/kvquant.py)."""
    from mpi_acx_tpu.models.decoding import greedy_generate
    return greedy_generate(
        lambda t, ml, lo: prefill(params, cfg, t, ml, last_only=lo,
                                  kv_int8=kv_int8),
        lambda c, t: decode_step(params, cfg, c, t),
        prompt, n_new, cfg.max_seq, max_len)


def generate_sample(params: Params, cfg: LlamaConfig, prompt: jax.Array,
                    n_new: int, key: jax.Array, temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    max_len: Optional[int] = None,
                    kv_int8: bool = False) -> jax.Array:
    """Stochastic decode (temperature / top-k / top-p nucleus)."""
    from mpi_acx_tpu.models.decoding import sample_generate
    return sample_generate(
        lambda t, ml, lo: prefill(params, cfg, t, ml, last_only=lo,
                                  kv_int8=kv_int8),
        lambda c, t: decode_step(params, cfg, c, t),
        prompt, n_new, cfg.max_seq, key, temperature, top_k, top_p, max_len)
