"""Decoder-only transformer (GPT-2 family), pure functional JAX.

TPU-first choices:
* parameters live in float32, compute casts to bfloat16 so every matmul
  lands on the MXU at full rate;
* attention/MLP shapes are [*, d_model] x [d_model, big] einsums — large,
  batched, static — exactly what XLA tiles well;
* no Python control flow depends on data; the layer stack is a
  ``lax.scan`` over stacked layer parameters (single compiled layer body,
  fast compiles at depth);
* the head dim and FFN dim are the tensor-parallel shardable axes, and the
  sequence axis is the ring-attention/sequence-parallel axis — the
  distributed train step in mpi_acx_tpu.train slices these with shard_map.

GPT-2 125M (BASELINE.json configs[3]) is `gpt2_small()`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 50257
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16  # compute dtype (params stay f32)
    # Pallas blockwise flash-attention kernel (ops/attention.py) instead of
    # dense-mask attention: O(S) memory, causal-skipped FLOPs. None = auto:
    # flash on TPU for S >= 1024 (measured v5e crossover: dense wins below —
    # kernel grid overhead; flash 1.4x at 2048, 5.3x at 4096), dense
    # elsewhere. Flash requires S % 128 == 0 (block sizes self-fit to S).
    use_flash: Optional[bool] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def gpt2_small() -> TransformerConfig:
    """GPT-2 124M: 12L / 768d / 12H / 3072ff (BASELINE.json configs[3])."""
    return TransformerConfig()


def tiny_config(vocab: int = 512, d_model: int = 128, n_heads: int = 4,
                n_layers: int = 4, d_ff: int = 512,
                max_seq: int = 128) -> TransformerConfig:
    """Small config for tests and virtual-mesh dryruns."""
    return TransformerConfig(vocab=vocab, d_model=d_model, n_heads=n_heads,
                             n_layers=n_layers, d_ff=d_ff, max_seq=max_seq)


Params = Dict[str, Any]


def init_params(key: jax.Array, cfg: TransformerConfig) -> Params:
    """Stacked-layer parameter pytree: every per-layer tensor has a leading
    [n_layers] axis (scanned in forward; sliceable into pipeline stages)."""
    k = jax.random.split(key, 8)
    L, d, ff = cfg.n_layers, cfg.d_model, cfg.d_ff
    s = 0.02

    def nrm(key, *shape, scale=s):
        return (jax.random.normal(key, shape, jnp.float32) * scale)

    return {
        "embed": nrm(k[0], cfg.vocab, d),
        "pos": nrm(k[1], cfg.max_seq, d),
        "layers": {
            "ln1_g": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
            "wqkv": nrm(k[2], L, d, 3 * d),
            "wo": nrm(k[3], L, d, d, scale=s / jnp.sqrt(2 * L).item()),
            "ln2_g": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
            "w1": nrm(k[4], L, d, ff), "b1": jnp.zeros((L, ff)),
            "w2": nrm(k[5], L, ff, d, scale=s / jnp.sqrt(2 * L).item()),
            "b2": jnp.zeros((L, d)),
        },
        "lnf_g": jnp.ones((d,)), "lnf_b": jnp.zeros((d,)),
    }


def layernorm(x, g, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * g + b).astype(x.dtype)


def _causal_attention(q, k, v):
    """q,k,v: [B, S, H, Dh] -> [B, S, H, Dh], causal, f32 softmax."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(d)
    s = q.shape[1]
    mask = jnp.tril(jnp.ones((s, s), bool))
    logits = jnp.where(mask[None, None], logits, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def block(cfg: TransformerConfig, lp: Params, x: jax.Array) -> jax.Array:
    """One transformer block; x [B, S, d] in compute dtype."""
    B, S, d = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim

    h = layernorm(x, lp["ln1_g"], lp["ln1_b"])
    qkv = h @ lp["wqkv"].astype(x.dtype)                      # [B,S,3d]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, H, Dh)
    k = k.reshape(B, S, H, Dh)
    v = v.reshape(B, S, H, Dh)
    use_flash = cfg.use_flash
    if use_flash is None:
        use_flash = (jax.default_backend() == "tpu" and S >= 1024
                     and S % 128 == 0)
    if use_flash:
        from mpi_acx_tpu.ops.attention import flash_attention
        o = flash_attention(q, k, v).reshape(B, S, d)
    else:
        o = _causal_attention(q, k, v).reshape(B, S, d)
    x = x + o @ lp["wo"].astype(x.dtype)

    h = layernorm(x, lp["ln2_g"], lp["ln2_b"])
    y = jax.nn.gelu(h @ lp["w1"].astype(x.dtype) + lp["b1"].astype(x.dtype))
    return x + y @ lp["w2"].astype(x.dtype) + lp["b2"].astype(x.dtype)


def forward(params: Params, cfg: TransformerConfig,
            tokens: jax.Array) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, vocab] (f32)."""
    B, S = tokens.shape
    x = (params["embed"][tokens] + params["pos"][:S]).astype(cfg.dtype)

    def body(x, lp):
        return block(cfg, lp, x), None

    x, _ = lax.scan(body, x, params["layers"])
    x = layernorm(x, params["lnf_g"], params["lnf_b"])
    # Tied unembedding (GPT-2 style): bf16 operands, f32 accumulation —
    # this matmul is ~1/3 of forward FLOPs and must ride the MXU at full
    # rate (f32 operands here cost 1.45x whole-model latency on v5e).
    return jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params: Params, cfg: TransformerConfig, tokens: jax.Array,
            targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy."""
    logits = forward(params, cfg, tokens)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def stage_slice(params: Params, n_stages: int) -> Params:
    """Reshape stacked layers [L, ...] -> [n_stages, L/n_stages, ...] so a
    shard_map P('pp') spec hands each pipeline stage its own layer block."""
    L = params["layers"]["ln1_g"].shape[0]
    assert L % n_stages == 0, (L, n_stages)
    per = L // n_stages

    def rs(p):
        return p.reshape((n_stages, per) + p.shape[1:])

    out = dict(params)
    out["layers"] = jax.tree.map(rs, params["layers"])
    return out
