"""Decoder-only transformer (GPT-2 family), pure functional JAX.

TPU-first choices:
* parameters live in float32, compute casts to bfloat16 so every matmul
  lands on the MXU at full rate;
* attention/MLP shapes are [*, d_model] x [d_model, big] einsums — large,
  batched, static — exactly what XLA tiles well;
* no Python control flow depends on data; the layer stack is a
  ``lax.scan`` over stacked layer parameters (single compiled layer body,
  fast compiles at depth);
* the head dim and FFN dim are the tensor-parallel shardable axes, and the
  sequence axis is the ring-attention/sequence-parallel axis — the
  distributed train step in mpi_acx_tpu.train slices these with shard_map.

GPT-2 125M (BASELINE.json configs[3]) is `gpt2_small()`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from mpi_acx_tpu.ops.wquant import wread

from mpi_acx_tpu.models.decoding import grouped_decode_attend


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 50257
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16  # compute dtype (params stay f32)
    # Pallas blockwise flash-attention kernel (ops/attention.py) instead of
    # dense-mask attention: O(S) memory, causal-skipped FLOPs. None = auto:
    # flash on TPU for S >= 1024 (measured v5e crossover: dense wins below —
    # kernel grid overhead; flash 1.4x at 2048, 5.3x at 4096), dense
    # elsewhere. Flash requires S % 128 == 0 (block sizes self-fit to S).
    use_flash: Optional[bool] = None
    # Decode-attention backend (ops/flash_decode.py): None = auto (the
    # length-aware Pallas kernel on TPU for long 128-aligned caches),
    # True = always the kernel (interpret mode off-TPU), False = dense.
    decode_flash: Optional[bool] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def gpt2_small() -> TransformerConfig:
    """GPT-2 124M: 12L / 768d / 12H / 3072ff (BASELINE.json configs[3])."""
    return TransformerConfig()


def tiny_config(vocab: int = 512, d_model: int = 128, n_heads: int = 4,
                n_layers: int = 4, d_ff: int = 512,
                max_seq: int = 128) -> TransformerConfig:
    """Small config for tests and virtual-mesh dryruns."""
    return TransformerConfig(vocab=vocab, d_model=d_model, n_heads=n_heads,
                             n_layers=n_layers, d_ff=d_ff, max_seq=max_seq)


Params = Dict[str, Any]


def init_params(key: jax.Array, cfg: TransformerConfig) -> Params:
    """Stacked-layer parameter pytree: every per-layer tensor has a leading
    [n_layers] axis (scanned in forward; sliceable into pipeline stages)."""
    k = jax.random.split(key, 8)
    L, d, ff = cfg.n_layers, cfg.d_model, cfg.d_ff
    s = 0.02

    def nrm(key, *shape, scale=s):
        return (jax.random.normal(key, shape, jnp.float32) * scale)

    return {
        "embed": nrm(k[0], cfg.vocab, d),
        "pos": nrm(k[1], cfg.max_seq, d),
        "layers": {
            "ln1_g": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
            "wqkv": nrm(k[2], L, d, 3 * d),
            "wo": nrm(k[3], L, d, d, scale=s / jnp.sqrt(2 * L).item()),
            "ln2_g": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
            "w1": nrm(k[4], L, d, ff), "b1": jnp.zeros((L, ff)),
            "w2": nrm(k[5], L, ff, d, scale=s / jnp.sqrt(2 * L).item()),
            "b2": jnp.zeros((L, d)),
        },
        "lnf_g": jnp.ones((d,)), "lnf_b": jnp.zeros((d,)),
    }


def layernorm(x, g, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * g + b).astype(x.dtype)


def _attend(cfg: TransformerConfig, q, k, v):
    """Causal attention with the per-shape kernel choice (flash vs dense);
    [B, S, H, Dh] -> [B, S, d]."""
    B, S = q.shape[:2]
    from mpi_acx_tpu.ops.attention import select_attention
    o = select_attention(cfg.use_flash)(q, k, v)
    return o.reshape(B, S, cfg.d_model)


def block(cfg: TransformerConfig, lp: Params, x: jax.Array) -> jax.Array:
    """One transformer block; x [B, S, d] in compute dtype."""
    q, k, v = _qkv(cfg, lp, x)
    x = x + _attend(cfg, q, k, v) @ wread(lp, "wo", x.dtype)
    return _mlp(cfg, lp, x)


def _hidden(params: Params, cfg: TransformerConfig,
            tokens: jax.Array) -> jax.Array:
    """The model trunk: tokens [B, S] -> final-layernormed hidden states
    [B, S, d]. Shared by :func:`forward` and the chunked-CE loss path so
    dtype policy / block wiring can never diverge between them."""
    S = tokens.shape[1]
    x = (params["embed"][tokens] + params["pos"][:S]).astype(cfg.dtype)

    def body(x, lp):
        return block(cfg, lp, x), None

    x, _ = lax.scan(body, x, params["layers"])
    return layernorm(x, params["lnf_g"], params["lnf_b"])


def forward(params: Params, cfg: TransformerConfig,
            tokens: jax.Array) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, vocab] (f32)."""
    x = _hidden(params, cfg, tokens)
    # Tied unembedding (GPT-2 style): bf16 operands, f32 accumulation —
    # this matmul is ~1/3 of forward FLOPs and must ride the MXU at full
    # rate (f32 operands here cost 1.45x whole-model latency on v5e).
    return jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params: Params, cfg: TransformerConfig, tokens: jax.Array,
            targets: jax.Array,
            xent_chunk: int | None = None) -> jax.Array:
    """Mean next-token cross-entropy.

    ``xent_chunk`` selects the memory-bounded chunked-vocab CE
    (ops/xent.py): the [B, S, vocab] logits never materialize — the
    hidden states go straight into the online-logsumexp scan, and the
    custom VJP recomputes logit tiles in the backward. Same values and
    gradients up to fp summation order; the win is HBM (the logits are
    the largest tensor in a training step at GPT-2 vocab)."""
    if xent_chunk is not None:
        from mpi_acx_tpu.ops.xent import chunked_xent_ll
        B, S = tokens.shape
        x = _hidden(params, cfg, tokens)
        ll = chunked_xent_ll(x.reshape(B * S, -1), params["embed"],
                             targets.reshape(-1), xent_chunk)
        return -jnp.mean(ll)
    logits = forward(params, cfg, tokens)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def cast_params(params: Params, dtype=jnp.bfloat16) -> Params:
    """Cast the whole parameter tree for inference. Decode steps are
    HBM-bandwidth-bound on re-reading the parameters every token; bf16
    weights halve that traffic (measured 1.4x decode throughput on v5e).
    Training should keep f32 master weights."""
    return jax.tree.map(lambda p: p.astype(dtype), params)


# -- KV-cache decode -------------------------------------------------------
#
# Static-shape autoregressive inference: the cache holds [L, B, max_len, H,
# Dh] for k and v; every decode step attends over the full cache width with
# an iota<=pos mask, so the jitted step has one shape for the whole
# generation (no recompiles, MXU-friendly).


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int,
                  kv_int8: bool = False):
    """Zeroed cache pytree: {'k','v': [L, B, max_len, H, Dh], 'pos':
    int32}. ``kv_int8=True`` stores int8 codes plus per-(position,
    head) f32 scale buffers 'ks'/'vs' (ops/kvquant.py) — half the
    cache-read bandwidth, the binding term at long max_len."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_heads, cfg.head_dim)
    cache = {
        "k": jnp.zeros(shape, jnp.int8 if kv_int8 else cfg.dtype),
        "v": jnp.zeros(shape, jnp.int8 if kv_int8 else cfg.dtype),
        "pos": jnp.zeros((), jnp.int32),
    }
    if kv_int8:
        cache["ks"] = jnp.zeros(shape[:-1] + (1,), jnp.float32)
        cache["vs"] = jnp.zeros(shape[:-1] + (1,), jnp.float32)
    return cache


def _qkv(cfg: TransformerConfig, lp: Params, x: jax.Array):
    B, S, _ = x.shape
    h = layernorm(x, lp["ln1_g"], lp["ln1_b"])
    qkv = h @ wread(lp, "wqkv", x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    rs = lambda t: t.reshape(B, S, cfg.n_heads, cfg.head_dim)
    return rs(q), rs(k), rs(v)


def _mlp(cfg: TransformerConfig, lp: Params, x: jax.Array):
    h = layernorm(x, lp["ln2_g"], lp["ln2_b"])
    y = jax.nn.gelu(h @ wread(lp, "w1", x.dtype) + lp["b1"].astype(x.dtype))
    return x + y @ wread(lp, "w2", x.dtype) + lp["b2"].astype(x.dtype)


def prefill(params: Params, cfg: TransformerConfig, tokens: jax.Array,
            max_len: int, last_only: bool = False, ffn=None,
            kv_int8: bool = False, last_index=None):
    """Run the prompt through the model, filling a fresh KV cache.

    tokens [B, S] -> (logits [B, S, vocab] f32, cache with pos=S).
    With ``last_only`` the unembedding runs on the final position alone
    (logits [B, 1, vocab]) — for generation, which discards the rest,
    this skips ~1/3 of prefill FLOPs and the [B, S, vocab] materialization.
    ``last_index`` (traced scalar) generalizes it to "the unembedding
    runs on position ``last_index`` alone" — for bucket-padded prompts
    (models/serving.py) whose real last token is not the last row.

    ``ffn(cfg, lp, x) -> x`` overrides the block's feed-forward half
    (default :func:`_mlp`); the MoE family reuses this whole scaffold
    with its routed FFN (models/moe_transformer.py) — the cache layout,
    scan wiring, and guards live only here. ``kv_int8`` selects the
    quantized cache (init_kv_cache); prefill attention itself runs on
    the exact bf16 K/V — only the CACHE entries are quantized.
    """
    ffn = ffn or _mlp
    B, S = tokens.shape
    assert S <= max_len, (S, max_len)
    assert S <= cfg.max_seq, (S, cfg.max_seq)
    x = (params["embed"][tokens] + params["pos"][:S]).astype(cfg.dtype)

    def body(x, lp):
        q, k, v = _qkv(cfg, lp, x)
        x = x + _attend(cfg, q, k, v) @ wread(lp, "wo", x.dtype)
        x = ffn(cfg, lp, x)
        return x, (k, v)

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    x = layernorm(x, params["lnf_g"], params["lnf_b"])
    if last_index is not None:
        x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
    elif last_only:
        x = x[:, -1:]
    logits = jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
    # One cache-layout definition: init_kv_cache allocates,
    # decoding.fill_kv_cache fills (quantizing when int8).
    from mpi_acx_tpu.models.decoding import fill_kv_cache
    cache = fill_kv_cache(init_kv_cache(cfg, B, max_len,
                                        kv_int8=kv_int8), ks, vs, S)
    return logits, cache


def decode_step(params: Params, cfg: TransformerConfig, cache,
                token: jax.Array, ffn=None):
    """One autoregressive step. token [B] int32 -> (logits [B, vocab] f32,
    updated cache). Fixed shapes: jit once, run for the whole generation.
    ``ffn`` overrides the feed-forward half as in :func:`prefill`.

    The cache update runs through the shared carry-scan
    (decoding.decode_layer_scan) so XLA updates it in place — 1.9x
    faster decode on v5e than the scan-xs/ys structure."""
    ffn = ffn or _mlp
    pos = jnp.asarray(cache["pos"])
    max_len = cache["k"].shape[2]
    # Scalar pos: one learned position row for the whole batch; [B]
    # pos (continuous-batching serving): each slot reads its own row.
    pe = (params["pos"][pos][:, None, :] if pos.ndim
          else params["pos"][pos][None, None, :])
    x = (params["embed"][token][:, None, :] + pe).astype(cfg.dtype)

    def qkv_fn(lp, x, pos):
        return _qkv(cfg, lp, x)                        # [B, 1, H, Dh]

    def attend_fn(lp, x, q, kc, vc, pos):
        o = grouped_decode_attend(q, kc, vc, pos, max_len, n_rep=1,
                                  flash=cfg.decode_flash)
        return ffn(cfg, lp, x + o @ wread(lp, "wo", x.dtype))

    from mpi_acx_tpu.models.decoding import run_decode_layers
    x, out_cache = run_decode_layers(params["layers"], x, cache,
                                     qkv_fn, attend_fn)
    x = layernorm(x, params["lnf_g"], params["lnf_b"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype),
                        preferred_element_type=jnp.float32)[:, 0]
    return logits, out_cache


def generate(params: Params, cfg: TransformerConfig, prompt: jax.Array,
             n_new: int, max_len: Optional[int] = None,
             kv_int8: bool = False) -> jax.Array:
    """Greedy decode: prompt [B, S] -> [B, S + n_new] (jit-compatible;
    the decode loop is a lax.scan of n_new fixed-shape steps).
    ``kv_int8`` selects the quantized KV cache (ops/kvquant.py) — half
    the cache bandwidth, the binding stream at long max_len."""
    from mpi_acx_tpu.models.decoding import greedy_generate
    return greedy_generate(
        lambda t, ml, lo: prefill(params, cfg, t, ml, last_only=lo,
                                  kv_int8=kv_int8),
        lambda c, t: decode_step(params, cfg, c, t),
        prompt, n_new, cfg.max_seq, max_len)


def generate_sample(params: Params, cfg: TransformerConfig,
                    prompt: jax.Array, n_new: int, key: jax.Array,
                    temperature: float = 1.0, top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    max_len: Optional[int] = None,
                    kv_int8: bool = False) -> jax.Array:
    """Stochastic decode (temperature / top-k / top-p nucleus)."""
    from mpi_acx_tpu.models.decoding import sample_generate
    return sample_generate(
        lambda t, ml, lo: prefill(params, cfg, t, ml, last_only=lo,
                                  kv_int8=kv_int8),
        lambda c, t: decode_step(params, cfg, c, t),
        prompt, n_new, cfg.max_seq, key, temperature, top_k, top_p, max_len)


def stage_slice(params: Params, n_stages: int) -> Params:
    """Reshape stacked layers [L, ...] -> [n_stages, L/n_stages, ...] so a
    shard_map P('pp') spec hands each pipeline stage its own layer block.
    Works on any family's params dict with a stacked 'layers' subtree
    (GPT-2 and Llama both)."""
    L = jax.tree.leaves(params["layers"])[0].shape[0]
    assert L % n_stages == 0, (L, n_stages)
    per = L // n_stages

    def rs(p):
        return p.reshape((n_stages, per) + p.shape[1:])

    out = dict(params)
    out["layers"] = jax.tree.map(rs, params["layers"])
    return out


def stage_slice_interleaved(params: Params, n_stages: int,
                            n_virtual: int) -> Params:
    """Reshape stacked layers [L, ...] -> [pp, v, L/(pp*v), ...] for the
    interleaved pipeline schedule: global stage g = j*pp + s lands at
    [s, j] (device s, chunk j), so consecutive layer blocks snake over
    the devices n_virtual times."""
    L = jax.tree.leaves(params["layers"])[0].shape[0]
    G = n_stages * n_virtual
    assert L % G == 0, (L, n_stages, n_virtual)
    per = L // G

    def rs(p):
        q = p.reshape((n_virtual, n_stages, per) + p.shape[1:])
        return jnp.swapaxes(q, 0, 1)                # [pp, v, per, ...]

    out = dict(params)
    out["layers"] = jax.tree.map(rs, params["layers"])
    return out
