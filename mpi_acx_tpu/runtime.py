"""ctypes bindings to the native tpu-acx runtime (build/libtpuacx.so).

Python face of the host plane: MPIX_Init/Finalize, enqueued sends/recvs on
the host execution queue, host waits, partitioned channels, and proxy
statistics. The C surface is the same 17-function API the C tests use
(include/mpi-acx.h; parity with reference include/mpi-acx.h:48-104), so
behavior is identical across languages.

Multi-process usage mirrors the C side: run under ``build/acxrun -np N
python my_script.py`` and the transport picks up ACX_RANK/ACX_SIZE/ACX_FDS.
Single-process usage gets the loopback transport (rank 0 of 1).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(_REPO_ROOT, "build", "libtpuacx.so")

_lib: Optional[ctypes.CDLL] = None

# Status.error values surfaced by the resilience plane (include/acx/state.h).
# ERR_TRUNCATE stays a Status-level condition (MPI semantics); the three
# below are raised as typed exceptions by wait().
ERR_TRUNCATE = 17
ERR_TIMEOUT = 19
ERR_PEER_DEAD = 20
ERR_INJECTED = 21


class AcxError(RuntimeError):
    """A host-plane operation completed with a resilience-plane error."""

    def __init__(self, message: str, error: int, source: int, tag: int):
        super().__init__(message)
        self.error = error
        self.source = source
        self.tag = tag


class AcxTimeoutError(AcxError):
    """Op deadline expired or retries exhausted (MPIX_ERR_TIMEOUT)."""


class AcxPeerDeadError(AcxError):
    """Peer declared dead — EOF or heartbeat timeout (MPIX_ERR_PEER_DEAD)."""


def _build_lib() -> None:
    subprocess.run(["make", "-C", _REPO_ROOT, "lib", "tools"], check=True,
                   capture_output=True)


def lib() -> ctypes.CDLL:
    """Load (building if necessary) the native runtime library."""
    global _lib
    if _lib is None:
        if not os.path.exists(_LIB_PATH):
            _build_lib()
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.MPIX_Init.restype = ctypes.c_int
        _lib.MPIX_Finalize.restype = ctypes.c_int
        _lib.acx_proxy_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
        _lib.acx_flags_publish.restype = ctypes.c_int
        _lib.acx_flags_publish.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int]
        _lib.acx_flags_fetch.restype = ctypes.c_int
        _lib.acx_flags_fetch.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int]
        _lib.acx_request_partition_slots.restype = ctypes.c_int
        _lib.acx_request_partition_slots.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        _lib.acx_resilience_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
        _lib.acx_recovery_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
        _lib.acx_drain.restype = ctypes.c_int
        _lib.acx_drain.argtypes = [ctypes.c_double]
        _lib.MPIX_Set_deadline.restype = ctypes.c_int
        _lib.MPIX_Set_deadline.argtypes = [ctypes.c_double]
        _lib.MPIX_Get_deadline.restype = ctypes.c_int
        _lib.MPIX_Get_deadline.argtypes = [ctypes.POINTER(ctypes.c_double)]
        _lib.MPIX_Op_status.restype = ctypes.c_int
        _lib.MPIX_Op_status.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        _lib.acx_metrics_enabled.restype = ctypes.c_int
        _lib.acx_metrics_snapshot.restype = ctypes.c_int
        _lib.acx_metrics_snapshot.argtypes = [ctypes.c_char_p, ctypes.c_int]
        _lib.acx_metrics_prom.restype = ctypes.c_int
        _lib.acx_metrics_prom.argtypes = [ctypes.c_char_p, ctypes.c_int]
        _lib.acx_now_since_start_ns.restype = ctypes.c_uint64
        _lib.acx_now_since_start_ns.argtypes = []
        _lib.acx_metrics_dump_json.restype = ctypes.c_int
        _lib.acx_metrics_dump_json.argtypes = [ctypes.c_char_p]
        _lib.acx_flight_dump.restype = ctypes.c_int
        _lib.acx_flight_dump.argtypes = [ctypes.c_char_p]
        _lib.acx_flight_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
        _lib.MPIX_Dump_state.restype = ctypes.c_int
        _lib.MPIX_Fleet_epoch.restype = ctypes.c_uint64
        _lib.MPIX_Fleet_view.restype = ctypes.c_int
        _lib.MPIX_Fleet_view.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        _lib.MPIX_Fleet_leave.restype = ctypes.c_int
        _lib.MPIX_Fleet_leave.argtypes = [ctypes.c_double]
        _lib.acx_fleet_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
        _lib.acx_tseries_enabled.restype = ctypes.c_int
        _lib.acx_tseries_sample_now.restype = ctypes.c_int
        _lib.acx_tseries_live_json.restype = ctypes.c_int
        _lib.acx_tseries_live_json.argtypes = [ctypes.c_char_p, ctypes.c_int]
        _lib.acx_tseries_annotate.restype = None
        _lib.acx_tseries_annotate.argtypes = [ctypes.c_char_p]
        _lib.acx_serving_page_stats.restype = None
        _lib.acx_serving_page_stats.argtypes = [
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64]
        _lib.acx_span_app_begin.restype = None
        _lib.acx_span_app_begin.argtypes = [ctypes.c_uint64]
        _lib.acx_span_app_end.restype = None
        _lib.acx_span_app_end.argtypes = []
        _lib.acx_rank.restype = ctypes.c_int
        _lib.acx_rank.argtypes = []
        _lib.acx_size.restype = ctypes.c_int
        _lib.acx_size.argtypes = []
        _lib.acx_nflags.restype = ctypes.c_uint64
        _lib.acx_nflags.argtypes = []
    return _lib


def acxrun_path() -> str:
    p = os.path.join(_REPO_ROOT, "build", "acxrun")
    if not os.path.exists(p):
        _build_lib()
    return p


class Status(ctypes.Structure):
    """Mirror of the compat MPI_Status (include/compat/mpi.h)."""

    _fields_ = [
        ("MPI_SOURCE", ctypes.c_int),
        ("MPI_TAG", ctypes.c_int),
        ("MPI_ERROR", ctypes.c_int),
        ("acx_bytes", ctypes.c_size_t),
    ]


_DTYPE_TO_MPI = {
    np.dtype(np.int8): 1,     # MPI_CHAR
    np.dtype(np.uint8): 2,    # MPI_BYTE
    np.dtype(np.int32): 3,    # MPI_INT
    np.dtype(np.float32): 4,  # MPI_FLOAT
    np.dtype(np.float64): 5,  # MPI_DOUBLE
    np.dtype(np.int64): 6,    # MPI_INT64_T
}

QUEUE_STREAM = 0
QUEUE_GRAPH = 1

# MemberState values of the fleet membership table (include/acx/membership.h;
# MPIX_FLEET_* in include/mpi-acx.h). Indices are the C enum — do not reorder.
FLEET_STATE_NAMES = ("unknown", "joining", "active", "draining", "left",
                     "dead")


class Runtime:
    """One process's handle on the native runtime.

    Wraps MPI_Init_thread + MPIX_Init and exposes enqueued/partitioned
    operations on numpy buffers. Buffers must stay alive until their op
    completes (same rule as the C API).
    """

    def __init__(self) -> None:
        L = lib()
        provided = ctypes.c_int(0)
        L.MPI_Init_thread(None, None, 3, ctypes.byref(provided))
        if L.MPIX_Init() != 0:
            raise RuntimeError("MPIX_Init failed")
        self._lib = L
        rank = ctypes.c_int(0)
        size = ctypes.c_int(0)
        L.MPI_Comm_rank(0, ctypes.byref(rank))
        L.MPI_Comm_size(0, ctypes.byref(size))
        self.rank = rank.value
        self.size = size.value
        self._open = True

    # -- enqueued ops (default queue) --------------------------------------

    def isend_enqueue(self, buf: np.ndarray, dest: int, tag: int = 0):
        """MPIX_Isend_enqueue on the default host queue; returns a request."""
        req = ctypes.c_void_p(None)
        stream = ctypes.c_void_p(None)  # NULL handle = default queue
        mpitype = _DTYPE_TO_MPI[buf.dtype]
        rc = self._lib.MPIX_Isend_enqueue(
            buf.ctypes.data_as(ctypes.c_void_p), buf.size, mpitype, dest, tag,
            0, ctypes.byref(req), QUEUE_STREAM, ctypes.byref(stream))
        if rc != 0:
            raise RuntimeError("MPIX_Isend_enqueue failed")
        return req

    def irecv_enqueue(self, buf: np.ndarray, source: int, tag: int = 0):
        req = ctypes.c_void_p(None)
        stream = ctypes.c_void_p(None)
        mpitype = _DTYPE_TO_MPI[buf.dtype]
        rc = self._lib.MPIX_Irecv_enqueue(
            buf.ctypes.data_as(ctypes.c_void_p), buf.size, mpitype, source,
            tag, 0, ctypes.byref(req), QUEUE_STREAM, ctypes.byref(stream))
        if rc != 0:
            raise RuntimeError("MPIX_Irecv_enqueue failed")
        return req

    def wait(self, req) -> Status:
        """Block until the request completes. Resilience-plane failures
        (op deadline expired / retries exhausted / peer dead / injected
        fault) surface as typed exceptions; ERR_TRUNCATE stays in the
        returned Status (MPI semantics)."""
        st = Status()
        rc = self._lib.MPIX_Wait(ctypes.byref(req), ctypes.byref(st))
        if rc != 0:
            raise RuntimeError("MPIX_Wait failed")
        err = st.MPI_ERROR
        if err in (ERR_TIMEOUT, ERR_PEER_DEAD, ERR_INJECTED):
            cls = {ERR_TIMEOUT: AcxTimeoutError,
                   ERR_PEER_DEAD: AcxPeerDeadError,
                   ERR_INJECTED: AcxError}[err]
            name = {ERR_TIMEOUT: "op timed out",
                    ERR_PEER_DEAD: "peer dead",
                    ERR_INJECTED: "injected fault"}[err]
            raise cls(f"tpu-acx: {name} (error={err}, "
                      f"source={st.MPI_SOURCE}, tag={st.MPI_TAG})",
                      err, st.MPI_SOURCE, st.MPI_TAG)
        return st

    def stream_sync(self) -> None:
        self._lib.cudaStreamSynchronize(None)

    # -- partitioned ops ----------------------------------------------------

    def psend_init(self, buf: np.ndarray, partitions: int, dest: int,
                   tag: int = 0):
        assert buf.size % partitions == 0
        req = ctypes.c_void_p(None)
        mpitype = _DTYPE_TO_MPI[buf.dtype]
        rc = self._lib.MPIX_Psend_init(
            buf.ctypes.data_as(ctypes.c_void_p), partitions,
            ctypes.c_longlong(buf.size // partitions), mpitype, dest, tag, 0,
            0, ctypes.byref(req))
        if rc != 0:
            raise RuntimeError("MPIX_Psend_init failed")
        return req

    def precv_init(self, buf: np.ndarray, partitions: int, source: int,
                   tag: int = 0):
        assert buf.size % partitions == 0
        req = ctypes.c_void_p(None)
        mpitype = _DTYPE_TO_MPI[buf.dtype]
        rc = self._lib.MPIX_Precv_init(
            buf.ctypes.data_as(ctypes.c_void_p), partitions,
            ctypes.c_longlong(buf.size // partitions), mpitype, source, tag,
            0, 0, ctypes.byref(req))
        if rc != 0:
            raise RuntimeError("MPIX_Precv_init failed")
        return req

    def start(self, req) -> None:
        if self._lib.MPIX_Start(ctypes.byref(req)) != 0:
            raise RuntimeError("MPIX_Start failed")

    def pready(self, partition: int, req) -> None:
        if self._lib.MPIX_Pready(partition, ctypes.byref(req)) != 0:
            raise RuntimeError("MPIX_Pready failed")

    def parrived(self, req, partition: int) -> bool:
        flag = ctypes.c_int(0)
        if self._lib.MPIX_Parrived(ctypes.byref(req), partition,
                                   ctypes.byref(flag)) != 0:
            raise RuntimeError("MPIX_Parrived failed")
        return bool(flag.value)

    def wait_partitioned(self, req) -> Status:
        return self.wait(req)

    def request_free(self, req) -> None:
        if self._lib.MPIX_Request_free(ctypes.byref(req)) != 0:
            raise RuntimeError("MPIX_Request_free failed")

    # -- device<->proxy flag bridge ----------------------------------------
    # The TPU-native form of the reference's kernel-writes-host-flag-page
    # coupling (partitioned.cu:200-212 -> init.cpp:82-115): a Pallas kernel
    # mutates a per-partition device flag buffer (mpi_acx_tpu.ops.flags);
    # these calls mirror it into / out of the native table the proxy polls.

    def partition_slots(self, req) -> np.ndarray:
        """Native flag-table slot index of each partition of `req` (the
        idx array of the reference's device mirror)."""
        # The C call writes up to cap entries but returns the full count:
        # probe with cap=0, then fetch exactly n (never truncate silently).
        n = self._lib.acx_request_partition_slots(req, None, 0)
        if n < 0:
            raise RuntimeError("not a partitioned request")
        out = np.zeros(max(n, 1), dtype=np.int64)
        got = self._lib.acx_request_partition_slots(
            req, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n)
        if got != n:
            raise RuntimeError(f"partition count changed ({n} -> {got})")
        return out[:n].copy()

    def publish_partition_flags(self, req, device_flags: np.ndarray) -> int:
        """Mirror a device flag buffer (one int32 word per partition, the
        protocol constants of ops.flags) into the native table: every
        partition the kernel marked PENDING is published to the proxy
        exactly like a host MPIX_Pready. Idempotent per partition (CAS in
        the native layer). Returns how many partitions were newly
        published."""
        slots = self.partition_slots(req)
        vals = np.ascontiguousarray(
            device_flags[:len(slots)], dtype=np.int32)
        n = self._lib.acx_flags_publish(
            slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(slots))
        if n < 0:
            raise RuntimeError("acx_flags_publish failed")
        return n

    def fetch_partition_flags(self, req) -> np.ndarray:
        """Snapshot the native flag word of each partition (COMPLETED once
        the proxy observed arrival) for lifting into the device flag
        buffer a Pallas parrived kernel polls."""
        slots = self.partition_slots(req)
        out = np.zeros(len(slots), dtype=np.int32)
        if self._lib.acx_flags_fetch(
                slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                len(slots)) != 0:
            raise RuntimeError("acx_flags_fetch failed")
        return out

    # -- collectives / lifecycle -------------------------------------------

    def barrier(self) -> None:
        self._lib.MPI_Barrier(0)

    def allreduce_max(self, value: int) -> int:
        buf = np.array([value], dtype=np.int32)
        inplace = ctypes.c_void_p(-1 & (2**64 - 1))  # MPI_IN_PLACE
        self._lib.MPI_Allreduce(inplace, buf.ctypes.data_as(ctypes.c_void_p),
                                1, 3, 0, 0)
        return int(buf[0])

    def proxy_stats(self) -> dict:
        out = (ctypes.c_uint64 * 4)()
        self._lib.acx_proxy_stats(out)
        stats = {
            "sweeps": out[0],
            "ops_issued": out[1],
            "ops_completed": out[2],
            "slots_reclaimed": out[3],
        }
        stats.update(self.resilience_stats())
        stats.update(self.recovery_stats())
        return stats

    # -- resilience plane ---------------------------------------------------

    def set_deadline(self, timeout_ms: float) -> None:
        """Per-op deadline for every subsequently issued op (0 disables).
        An op past its deadline completes with ERR_TIMEOUT instead of
        blocking forever — the bound that keeps wait() from hanging on a
        dead or wedged peer."""
        if self._lib.MPIX_Set_deadline(float(timeout_ms)) != 0:
            raise ValueError(f"bad deadline {timeout_ms!r} (must be >= 0)")

    def get_deadline(self) -> float:
        out = ctypes.c_double(0.0)
        if self._lib.MPIX_Get_deadline(ctypes.byref(out)) != 0:
            raise RuntimeError("MPIX_Get_deadline failed")
        return out.value

    def op_status(self, req) -> dict:
        """Nonblocking probe of a request: lifecycle state (the Flag
        enum value), first error, and issue attempts (> 1 means the
        retry path fired)."""
        st = ctypes.c_int(0)
        err = ctypes.c_int(0)
        att = ctypes.c_int(0)
        if self._lib.MPIX_Op_status(req, ctypes.byref(st), ctypes.byref(err),
                                    ctypes.byref(att)) != 0:
            raise RuntimeError("MPIX_Op_status: not a live request")
        return {"state": st.value, "error": err.value,
                "attempts": att.value}

    def resilience_stats(self) -> dict:
        """Process-wide resilience counters: proxy retries/timeouts,
        injected-fault hits, and transport heartbeat/dead-peer state."""
        out = (ctypes.c_uint64 * 8)()
        self._lib.acx_resilience_stats(out)
        return {
            "retries": out[0],
            "timeouts": out[1],
            "fault_drops": out[2],
            "fault_delays": out[3],
            "fault_fails": out[4],
            "hb_sent": out[5],
            "hb_recv": out[6],
            "peers_dead": out[7],
        }

    # -- survivable links (docs/DESIGN.md "Survivable links") ---------------

    def drain(self, timeout_ms: float = 1000.0) -> int:
        """Graceful drain (MPIX_Drain): wait up to ``timeout_ms`` for every
        in-flight op — including ops parked while a peer's link reconnects —
        then cancel the stragglers with a typed error (``AcxPeerDeadError``
        for unhealthy peers, ``AcxTimeoutError`` otherwise) so every waiter
        unblocks in bounded time. Returns the number of ops cancelled
        (0 = clean drain). Survivors of a peer loss call this to shed the
        dead rank's traffic and keep serving."""
        n = self._lib.acx_drain(float(timeout_ms))
        if n < 0:
            raise RuntimeError("acx_drain: runtime not initialized")
        return n

    def recovery_stats(self) -> dict:
        """Process-wide survivable-link counters: link reconnects, frames
        replayed from the resend buffer, CRC-rejected frames, NAKs sent,
        ops cancelled by drain, links currently mid-reconnect, and links
        whose replay buffer has evicted an unacked frame (still moving
        data, but their next link loss is terminal — the early warning to
        raise ACX_REPLAY_BUF_BYTES)."""
        out = (ctypes.c_uint64 * 7)()
        self._lib.acx_recovery_stats(out)
        return {
            "reconnects": out[0],
            "replayed_frames": out[1],
            "crc_rejects": out[2],
            "naks_sent": out[3],
            "drained_slots": out[4],
            "links_recovering": out[5],
            "replay_broken_links": out[6],
        }

    # -- fleet membership (docs/DESIGN.md §12) ------------------------------

    def fleet_epoch(self) -> int:
        """Monotonically increasing fleet epoch: bumps on every membership
        verdict this rank adopts (a join, a graceful leave, a death).
        Epochs are per-rank views that converge by max-merge — compare for
        ordering on one rank, not for equality across ranks."""
        return int(self._lib.MPIX_Fleet_epoch())

    def fleet_view(self) -> list:
        """This rank's membership view, one state name per rank slot
        (``FLEET_STATE_NAMES``): ``"active"``, ``"draining"``, ``"left"``,
        ``"dead"``, ... A replaced rank's slot returns to ``"active"`` when
        this rank adopts the new incarnation."""
        states = (ctypes.c_int32 * max(self.size, 1))()
        n = self._lib.MPIX_Fleet_view(states, self.size)
        return [FLEET_STATE_NAMES[states[i]]
                if 0 <= states[i] < len(FLEET_STATE_NAMES) else "unknown"
                for i in range(max(n, 0))]

    def fleet_stats(self) -> dict:
        """Membership counters: current epoch, joins/leaves/deaths adopted
        into this rank's view, and slots currently ACTIVE."""
        out = (ctypes.c_uint64 * 5)()
        self._lib.acx_fleet_stats(out)
        return {"epoch": out[0], "joins": out[1], "leaves": out[2],
                "deaths": out[3], "active": out[4]}

    def fleet_leave(self, timeout_ms: float = 2000.0) -> int:
        """Leave the fleet gracefully: drain in-flight work (up to
        ``timeout_ms``), announce LEFT to every peer, and surrender the
        rendezvous listener so a replacement can take this slot. Returns
        the number of ops the drain had to cancel (0 = clean departure).
        After leaving, ``finalize()`` skips the MPI_Finalize barrier —
        this rank is no longer part of the rank set it would sync with."""
        n = self._lib.MPIX_Fleet_leave(float(timeout_ms))
        if n < 0:
            raise RuntimeError("MPIX_Fleet_leave: runtime not initialized")
        self._left = True
        return n

    # -- metrics plane ------------------------------------------------------

    def metrics_enabled(self) -> bool:
        """True iff ACX_METRICS was set when the native library loaded."""
        return bool(self._lib.acx_metrics_enabled())

    def metrics(self) -> dict:
        """Snapshot of the native metrics registry (src/core/metrics.cc):
        ``{"enabled": bool, "counters": {...}, "histograms": {name:
        {"unit","count","sum","buckets"}}}``. Counters derived from runtime
        stats (proxy sweeps, heartbeats, fault injections, slot watermark)
        are refreshed at snapshot time. With ACX_METRICS unset the registry
        is off and counters read zero."""
        import json as _json
        # The snapshot length can grow between the size probe and the
        # fill (live counters gain digits under the proxy thread), so
        # retry with slack until the fill fits its buffer.
        n = self._lib.acx_metrics_snapshot(None, 0)
        while True:
            cap = n + 256
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.acx_metrics_snapshot(buf, cap)
            if n < cap:
                return _json.loads(buf.value.decode())

    def metrics_prom(self) -> str:
        """The registry in Prometheus text exposition format (0.0.4):
        every counter/gauge as ``acx_<name>`` with a ``# TYPE`` line,
        histograms as cumulative ``_bucket{le=...}``/``_sum``/``_count``
        series on the native power-of-two bucket edges. Runtime-derived
        counters are refreshed at scrape time — this is the payload a
        Prometheus scraper (or ``acx_top.py --prom``) serves verbatim."""
        # Same retry-sizing discipline as metrics(): counters gain digits
        # under the proxy thread between the size probe and the fill.
        n = self._lib.acx_metrics_prom(None, 0)
        while True:
            cap = n + 256
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.acx_metrics_prom(buf, cap)
            if n < cap:
                return buf.value.decode()

    def metrics_dump(self, path: str) -> None:
        """Write the registry snapshot to ``path`` as JSON."""
        if self._lib.acx_metrics_dump_json(path.encode()) != 0:
            raise RuntimeError(f"acx_metrics_dump_json({path!r}) failed")

    # -- live telemetry plane (ACX_TSERIES, docs/DESIGN.md §13) -------------

    def tseries_enabled(self) -> bool:
        """True iff ACX_TSERIES periodic sampling is armed."""
        return bool(self._lib.acx_tseries_enabled())

    def live_metrics(self) -> dict:
        """Take a fresh telemetry sample and return it as a dict — the same
        delta-encoded record the sampler appends to the per-rank
        ``.tseries.jsonl`` (counter deltas since the previous sample, gauge
        absolutes, interval proxy utilization, per-link wire scope, and the
        last ``tseries_annotate`` fragment under ``"app"``). Readable
        mid-run from any thread. Returns ``{"enabled": False}`` when
        ACX_TSERIES is unset."""
        import json as _json
        if self._lib.acx_tseries_sample_now() < 0:
            return {"enabled": False}
        # Same retry-sizing discipline as metrics(): the live line can be
        # replaced by a bigger sample between the probe and the fill.
        n = self._lib.acx_tseries_live_json(None, 0)
        while True:
            cap = n + 256
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.acx_tseries_live_json(buf, cap)
            if n < cap:
                return _json.loads(buf.value.decode()) if n else {}

    def tseries_annotate(self, fragment: dict) -> None:
        """Attach an application-level JSON fragment (e.g. serving SLOs) to
        subsequent telemetry samples under ``"app"``. No-op when sampling
        is disabled; fragments over 8 KiB are ignored by the native side."""
        import json as _json
        self._lib.acx_tseries_annotate(
            _json.dumps(fragment, separators=(",", ":")).encode())

    # -- causal tracing (docs/DESIGN.md §14) --------------------------------

    def span_app_begin(self, request_id: int) -> None:
        """Open an application span bracket: every op enqueued until
        ``span_app_end`` emits a ``req_op`` trace event tying the op's
        native causal span to ``request_id``, so tools/acx_critpath.py
        can split the request's latency into queue vs compute vs wire.
        Latest begin wins (no nesting); ``request_id`` must be nonzero."""
        self._lib.acx_span_app_begin(ctypes.c_uint64(request_id))

    def span_app_end(self) -> None:
        """Close the application span bracket opened by span_app_begin."""
        self._lib.acx_span_app_end()

    # -- flight recorder ----------------------------------------------------

    def hang_report(self, path: Optional[str] = None) -> str:
        """Write this rank's flight dump — recent op-lifecycle events, live
        slot table, per-peer link clocks — for tools/acx_doctor.py.

        ``path`` is the file *prefix*; the dump lands at
        ``<prefix>.rank<r>.flight.json`` (default prefix: $ACX_FLIGHT,
        then "acx"). Returns the written filename."""
        prefix = path if path is not None else os.environ.get(
            "ACX_FLIGHT", "acx")
        arg = path.encode() if path is not None else None
        if self._lib.acx_flight_dump(arg) != 0:
            raise RuntimeError(f"acx_flight_dump({path!r}) failed")
        return f"{prefix}.rank{self.rank}.flight.json"

    def flight_stats(self) -> dict:
        """Flight-recorder counters: events recorded (lifetime), ring
        capacity (0 = disabled via ACX_FLIGHT_EVENTS=0), stall warnings,
        watchdog hang dumps, and dump files written."""
        out = (ctypes.c_uint64 * 5)()
        self._lib.acx_flight_stats(out)
        return {
            "recorded": out[0],
            "capacity": out[1],
            "stall_warns": out[2],
            "hang_dumps": out[3],
            "dumps_written": out[4],
        }

    def finalize(self) -> None:
        if self._open:
            undrained = getattr(self, "_inprogram_sends", [])
            if undrained:
                # Mirrors the native finalize's leaked-slot diagnostic:
                # in-program sends were triggered but never waited
                # (xla_triggers.drain_sends) — their host buffers and
                # slots are about to be torn down under them.
                import sys
                print(f"tpu-acx: finalize: {len(undrained)} in-program "
                      f"send(s) never drained (xla_triggers.drain_sends)",
                      file=sys.stderr)
            self._lib.MPIX_Finalize()
            if not getattr(self, "_left", False):
                # MPI_Finalize barriers with the full rank set; a rank that
                # announced LEFT is no longer in it and must not sync.
                self._lib.MPI_Finalize()
            self._open = False
