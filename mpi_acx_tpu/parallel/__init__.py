"""TPU data plane: mesh-based collectives, partitioned exchange, ring
attention, and the microbatch pipeline.

This package is the ICI half of the framework (the native C++ runtime in
``src/`` is the host half): the reference's CUDA/MPI primitives re-expressed
as JAX/XLA collectives over a ``jax.sharding.Mesh``, per the SURVEY.md §7.1
mapping table. Everything here is jit-compatible, static-shaped, and runs
identically on a real TPU slice and on a virtual CPU mesh.

The ICI-plane modules need a jax with top-level ``jax.shard_map``; on an
older jax their re-exports are skipped so the host-plane modules that
live beside them (parallel.kv_ship, parallel.multihost — numpy + the
native runtime only) stay importable. Importing a skipped name raises
the usual ImportError at use, not at package import.
"""

try:
    from mpi_acx_tpu.parallel.mesh import (  # noqa: F401
        make_mesh,
        mesh_from_devices,
    )
    from mpi_acx_tpu.parallel.collective import (  # noqa: F401
        ring_shift,
        neighbor_exchange,
        halo_exchange_1d,
        halo_exchange_2d,
        all_to_all_seq,
    )
    from mpi_acx_tpu.parallel.partitioned import (  # noqa: F401
        partitioned_ring_exchange,
        partitioned_pipeline,
    )
    from mpi_acx_tpu.parallel.ring_attention import (  # noqa: F401
        ring_attention,
        ring_attention_batched,
        ring_attention_sharded,
        blockwise_attention_reference,
    )
    from mpi_acx_tpu.parallel.pipeline import (  # noqa: F401
        pipeline_1f1b_loss_and_grads,
        pipeline_forward,
        pipeline_forward_interleaved,
        pipeline_loss,
    )
    from mpi_acx_tpu.parallel.ulysses import (  # noqa: F401
        ulysses_attention,
        ulysses_attention_sharded,
    )
    from mpi_acx_tpu.parallel.quantized import (  # noqa: F401
        quantized_pmean,
        quantized_psum,
        ring_psum,
    )
    from mpi_acx_tpu.parallel.tp_inference import (  # noqa: F401
        make_tp_generate,
        make_tp_generate_llama,
        make_tp_generate_moe,
        make_tp_speculative_generate,
        tp_param_specs,
        tp_param_specs_llama,
        tp_param_specs_moe,
        tp_shard_params,
        tp_shard_params_llama,
        tp_shard_params_moe,
    )
except ImportError:  # pragma: no cover — jax without jax.shard_map
    pass
from mpi_acx_tpu.parallel import multihost  # noqa: F401
