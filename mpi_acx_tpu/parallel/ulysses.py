"""Ulysses (all-to-all) sequence parallelism.

The second canonical long-context strategy next to ring attention
(parallel/ring_attention.py). Ring attention keeps the sequence sharded
and rotates K/V around the mesh — n ppermute steps, O(S/n) peak memory.
Ulysses instead **re-shards**: one all-to-all turns the sequence-sharded
layout into a head-sharded layout, each device runs full-sequence
attention for its H/n heads with any single-device kernel (the Pallas
flash kernel rides along for free), and a second all-to-all restores
sequence sharding. Two collectives total, so it wins when attention
FLOPs dominate and H >= mesh size; ring wins when S is extreme and
memory is the constraint. Both ride ICI.

The primitive underneath is collective.all_to_all_seq — a single
lax.all_to_all per direction.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpi_acx_tpu.parallel.collective import all_to_all_seq


def _default_local_attn(q, k, v, causal: bool):
    """Full-sequence attention for the local heads, [S, H_loc, D];
    flash/dense choice delegated to the shared policy."""
    from mpi_acx_tpu.ops.attention import auto_attention
    return auto_attention(q[None], k[None], v[None], causal=causal)[0]


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = True,
                      attn_fn: Optional[Callable] = None) -> jax.Array:
    """Exact attention under Ulysses sequence parallelism.

    Per-shard shapes: q, k, v = [seq_shard, heads, head_dim]; the global
    sequence is the shard concatenation in mesh order. heads must divide
    by the axis size. Returns the local Q block's output, same shape.

    attn_fn(q, k, v, causal) runs on [S_global, heads/n, head_dim]; the
    default picks flash/dense like the model layer.
    """
    n = lax.axis_size(axis_name)
    assert q.ndim == 3, f"expected [seq_shard, heads, head_dim], got {q.shape}"
    H = q.shape[1]
    assert H % n == 0, f"heads {H} not divisible by axis size {n}"
    if attn_fn is None:
        attn_fn = _default_local_attn

    # seq-sharded -> head-sharded: scatter heads, gather sequence. q/k/v
    # stack into ONE all-to-all so the reshard is a single ICI collective.
    x = jnp.stack([q, k, v])                       # [3, sq, H, D]
    xh = all_to_all_seq(x, axis_name, split_axis=2, concat_axis=1)
    qh, kh, vh = xh[0], xh[1], xh[2]               # [S_global, H/n, D]
    oh = attn_fn(qh, kh, vh, causal)
    # head-sharded -> seq-sharded.
    return all_to_all_seq(oh, axis_name, split_axis=0, concat_axis=1)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, axis_name: str = "x",
                              causal: bool = True):
    """Array-level wrapper: q/k/v sharded on the sequence (leading) axis."""
    spec = P(axis_name)
    f = shard_map(
        functools.partial(ulysses_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)
