"""Ring attention: exact attention over a sequence sharded across devices.

The long-context capability of the framework (first-class per the build
goals): each device holds a sequence block of Q, K, V; K/V blocks rotate
around the ring (collective-permute over ICI) while each device
accumulates its Q-block's attention over every K/V block using the
numerically stable logsumexp merge (flash-attention style). After `n`
steps every Q block has attended to the full sequence, with peak memory
O(seq/n) and the K/V transfer of step k overlapping the attention compute
of step k-1 — the same produce/transmit overlap the reference's
partitioned primitive provides on the host plane (SURVEY.md §5.7 maps
partitioned comm to exactly this pipelined exchange).

Each ring step's block-pair attention runs the Pallas flash kernel
(:func:`mpi_acx_tpu.ops.attention.flash_attention_lse`) when profitable —
the kernel returns (normalized output, row logsumexp), exactly the merge
state the ring needs, so the sequence-parallel path keeps the single-chip
flash advantage. A K/V block is, per the causal structure, entirely
visible (source block before this device's block: unmasked flash call),
entirely masked (source after: skipped — no FLOPs at all), or diagonal
(the standard causal flash call); the three cases dispatch by
``lax.switch`` on the rotating source index.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from mpi_acx_tpu.parallel.collective import _ring_perm

_NEG = float(jnp.finfo(jnp.float32).min)

# Flash engages automatically only when the PER-SHARD Q block is at
# least this long (and 128-aligned): below it the kernel's grid/launch
# overhead loses to one fused dense block on the measured v5e crossover.
# NOTE the cliff when choosing tp: the shard is S/tp, so e.g. S=2048 at
# tp=8 gives 256-long shards and the SP path runs the (exact,
# identical-math) dense blocks — pass use_flash=True to force the
# kernel, or keep S/tp >= this threshold for the flash win at scale.
FLASH_MIN_SHARD = 1024


def _dense_block(q32, kk, vv, mask):
    """One Q-block x K-block dense attention: returns (normalized_out
    [mb, Sq, H, D] f32, lse [mb, H, Sq] f32). Fully-masked rows get
    lse = finfo.min (an additive identity for the logaddexp merge)."""
    d = q32.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q32, kk.astype(jnp.float32))
    logits = logits / jnp.sqrt(d)
    logits = jnp.where(mask, logits, _NEG)
    m = jnp.max(logits, axis=-1)                      # [mb, H, Sq]
    p = jnp.exp(logits - m[..., None])
    p = jnp.where(mask, p, 0.0)                       # kill fully-masked rows
    l = jnp.sum(p, axis=-1)                           # [mb, H, Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, vv.astype(jnp.float32))
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-37)), _NEG)
    o = o / jnp.moveaxis(jnp.maximum(l, 1e-37), 1, 2)[..., None]
    return o, lse


def ring_attention_batched(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str, causal: bool = True,
                           use_flash: bool | None = None,
                           kv_repeat: int = 1) -> jax.Array:
    """Exact (optionally causal) attention with K/V rotating on the ring.

    Per-shard shapes: q = [mb, seq_shard, heads, head_dim]; k, v =
    [mb, seq_shard, heads/kv_repeat, head_dim]; the global sequence is the
    concatenation of shards in mesh order. Returns the attention output
    for the local Q block, same shape as q.

    kv_repeat > 1 is grouped-query attention: the ring rotates the
    UN-expanded K/V heads (kv_repeat x less ICI traffic per ppermute —
    the bandwidth GQA exists to save) and each block broadcasts them to
    the query heads locally, where XLA fuses the broadcast into the dots.

    use_flash: None -> auto (Pallas kernel on TPU when the PER-SHARD
    length reaches :data:`FLASH_MIN_SHARD` and is 128-aligned — note
    the shard is the global sequence over the tp/sp degree, so high tp
    can silently drop the auto path below the crossover; see
    FLASH_MIN_SHARD), True/False -> force. The dense and flash paths
    produce identical math; both yield (normalized block output, lse) and
    merge with logaddexp, so switching kernels never changes numerics
    beyond float roundoff.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    mb, sq, h, dh = q.shape
    assert k.shape[2] * kv_repeat == h, (k.shape, h, kv_repeat)
    if use_flash is None:
        use_flash = (jax.default_backend() == "tpu"
                     and sq >= FLASH_MIN_SHARD and sq % 128 == 0)

    def expand(x):
        # kv-head g serves query heads [g*kv_repeat, (g+1)*kv_repeat) —
        # the same layout as the model families' _repeat_kv.
        if kv_repeat == 1:
            return x
        hkv = x.shape[2]
        return jnp.broadcast_to(
            x[:, :, :, None, :],
            (mb, x.shape[1], hkv, kv_repeat, dh)).reshape(
                mb, x.shape[1], h, dh)

    if use_flash:
        from mpi_acx_tpu.ops.attention import flash_attention_lse

        def full_fn(q_, kk, vv):
            o, lse = flash_attention_lse(q_, expand(kk), expand(vv),
                                         causal=False)
            return o.astype(jnp.float32), lse

        def diag_fn(q_, kk, vv):
            o, lse = flash_attention_lse(q_, expand(kk), expand(vv),
                                         causal=True)
            return o.astype(jnp.float32), lse

        def skip_fn(q_, kk, vv):
            return (jnp.zeros((mb, sq, h, dh), jnp.float32),
                    jnp.full((mb, h, sq), _NEG, jnp.float32))

        def block_fn(q_, kk, vv, src):
            if not causal:
                return full_fn(q_, kk, vv)
            idx = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            return lax.switch(idx, (full_fn, diag_fn, skip_fn), q_, kk, vv)

        q_in = q
    else:
        def block_fn(q_, kk, vv, src):
            if causal:
                qpos = my * sq + jnp.arange(sq)[:, None]            # [Sq,1]
                kpos = src * sq + jnp.arange(kk.shape[1])[None, :]  # [1,Sk]
                mask = (kpos <= qpos)[None, None]              # [1,1,Sq,Sk]
            else:
                mask = jnp.ones((1, 1, sq, kk.shape[1]), bool)
            return _dense_block(q_, expand(kk), expand(vv), mask)

        q_in = q.astype(jnp.float32)

    # Accumulators are device-varying from step 0 (they mix in rotated K/V);
    # mark them so the scan carry type is stable under shard_map's vma check.
    o0 = lax.pcast(jnp.zeros(q.shape, jnp.float32), axis_name, to="varying")
    lse0 = lax.pcast(jnp.full((mb, h, sq), _NEG, jnp.float32), axis_name,
                     to="varying")

    def step(carry, t):
        o_acc, lse_acc, kk, vv = carry
        # K/V block currently held arrived from `t` ring steps back.
        src = (my - t) % n
        o_b, lse_b = block_fn(q_in, kk, vv, src)
        # logaddexp merge. finfo.min sentinels stay finite, so the weights
        # are well-defined with no NaN guard: a finfo.min-vs-finfo.min
        # merge gives weight 1 on a zero block output.
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        wa = jnp.exp(lse_acc - lse_new)                   # [mb, H, Sq]
        wb = jnp.exp(lse_b - lse_new)
        o_new = (o_acc * jnp.moveaxis(wa, 1, 2)[..., None]
                 + o_b * jnp.moveaxis(wb, 1, 2)[..., None])
        # Rotate K/V to the right neighbor for the next step; XLA overlaps
        # this transfer with the next iteration's compute.
        kk = lax.ppermute(kk, axis_name, perm=_ring_perm(n, 1))
        vv = lax.ppermute(vv, axis_name, perm=_ring_perm(n, 1))
        return (o_new, lse_new, kk, vv), None

    (o, _, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = True,
                   use_flash: bool | None = None) -> jax.Array:
    """3-D per-shard form: q, k, v = [seq_shard, heads, head_dim]."""
    return ring_attention_batched(q[None], k[None], v[None], axis_name,
                                  causal=causal, use_flash=use_flash)[0]


def blockwise_attention_reference(q, k, v, causal=True):
    """Single-device reference attention (for tests): [S, H, D] inputs."""
    d = q.shape[-1]
    logits = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / jnp.sqrt(d)
    if causal:
        s = q.shape[0]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None], logits, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32)).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = "x",
                           causal: bool = True,
                           use_flash: bool | None = None):
    """Array-level wrapper: q/k/v sharded on the sequence (leading) axis."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(axis_name)
    # check_vma=False: the Pallas interpreter (CPU path) can't yet mix
    # varying and non-varying operands inside its internal dynamic_slice
    # ("Primitive dynamic_slice requires varying manual axes to match ...
    # as a temporary workaround pass check_vma=False"); the distributed
    # train step (train.py) runs the same per-shard function with
    # check_vma=False as well.
    f = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal,
                          use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return f(q, k, v)
