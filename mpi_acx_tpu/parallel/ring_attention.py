"""Ring attention: exact attention over a sequence sharded across devices.

The long-context capability of the framework (first-class per the build
goals): each device holds a sequence block of Q, K, V; K/V blocks rotate
around the ring (collective-permute over ICI) while each device
accumulates its Q-block's attention over every K/V block using the
numerically stable running-max/log-sum-exp merge (flash-attention style).
After `n` steps every Q block has attended to the full sequence, with peak
memory O(seq/n) and the K/V transfer of step k overlapping the attention
compute of step k-1 — the same produce/transmit overlap the reference's
partitioned primitive provides on the host plane (SURVEY.md §5.7 maps
partitioned comm to exactly this pipelined exchange).

Causal masking uses static block indices (device index is static under
shard_map with a full ring permutation), so XLA sees static control flow.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from mpi_acx_tpu.parallel.collective import _ring_perm


def _block_attend(q, k, v, mask):
    """One Q-block x K-block attention: returns (unnorm_out, row_max,
    row_sumexp) for LSE merging. Shapes: q [Sq, H, D], k/v [Sk, H, D]."""
    d = q.shape[-1]
    # [H, Sq, Sk]
    logits = jnp.einsum("qhd,khd->hqk", q, k) / jnp.sqrt(d).astype(q.dtype)
    logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    m = jnp.max(logits, axis=-1)                      # [H, Sq]
    p = jnp.exp(logits - m[..., None])
    p = jnp.where(mask, p, 0.0)                       # kill fully-masked rows
    l = jnp.sum(p, axis=-1)                           # [H, Sq]
    o = jnp.einsum("hqk,khd->qhd", p, v)              # unnormalized
    return o, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = True) -> jax.Array:
    """Exact (optionally causal) attention with K/V rotating on the ring.

    Per-shard shapes: q, k, v = [seq_shard, heads, head_dim]; the global
    sequence is the concatenation of shards in mesh order. Returns the
    attention output for the local Q block, [seq_shard, heads, head_dim].
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    sq = q.shape[0]
    h = q.shape[1]

    neg = jnp.finfo(jnp.float32).min
    # Accumulators are device-varying from step 0 (they mix in rotated K/V);
    # mark them so the scan carry type is stable under shard_map's vma check.
    o0 = lax.pcast(jnp.zeros(q.shape, jnp.float32), axis_name, to="varying")
    m0 = lax.pcast(jnp.full((h, sq), neg, jnp.float32), axis_name,
                   to="varying")
    l0 = lax.pcast(jnp.zeros((h, sq), jnp.float32), axis_name, to="varying")

    q32 = q.astype(jnp.float32)

    def step(carry, t):
        o_acc, m_acc, l_acc, kk, vv = carry
        # K/V block currently held arrived from `t` ring steps back.
        src = (my - t) % n
        if causal:
            qpos = my * sq + jnp.arange(sq)[:, None]          # [Sq, 1]
            kpos = src * sq + jnp.arange(kk.shape[0])[None, :]  # [1, Sk]
            mask = (kpos <= qpos)[None]                        # [1, Sq, Sk]
        else:
            mask = jnp.ones((1, sq, kk.shape[0]), bool)
        o, m, l = _block_attend(q32, kk.astype(jnp.float32),
                                vv.astype(jnp.float32), mask)
        # LSE merge of (o_acc, m_acc, l_acc) with the new block.
        m_new = jnp.maximum(m_acc, m)
        a = jnp.exp(m_acc - m_new)      # rescale old accumulator
        b = jnp.exp(m - m_new)          # rescale new block
        l_new = l_acc * a + l * b
        o_new = (o_acc * a.transpose(1, 0)[:, :, None]
                 + o * b.transpose(1, 0)[:, :, None])
        # Rotate K/V to the right neighbor for the next step; XLA overlaps
        # this transfer with the next iteration's compute.
        kk = lax.ppermute(kk, axis_name, perm=_ring_perm(n, 1))
        vv = lax.ppermute(vv, axis_name, perm=_ring_perm(n, 1))
        return (o_new, m_new, l_new, kk, vv), None

    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    # Normalize; fully-masked rows (none in causal self-attention) guard.
    denom = jnp.maximum(l, 1e-20).transpose(1, 0)[:, :, None]
    return (o / denom).astype(q.dtype)


def blockwise_attention_reference(q, k, v, causal=True):
    """Single-device reference attention (for tests): [S, H, D] inputs."""
    d = q.shape[-1]
    logits = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / jnp.sqrt(d)
    if causal:
        s = q.shape[0]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None], logits, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32)).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = "x",
                           causal: bool = True):
    """Array-level wrapper: q/k/v sharded on the sequence (leading) axis."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(axis_name)
    f = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)
