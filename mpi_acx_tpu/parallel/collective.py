"""Point-to-point-shaped collectives over a mesh axis.

The TPU-native form of the reference's enqueued Isend/Irecv ring exchange
(reference test/src/ring.c:78-90): inside ``shard_map``, a
``lax.ppermute`` IS "send to right neighbor / receive from left neighbor",
compiled by XLA into a collective-permute that rides ICI — the device
itself reaches the op in its execution stream, which is exactly the
"enqueued" property the reference builds a proxy thread to get. No host
round-trip, no flag table: on the ICI plane the hardware gives us the
semantics the host plane has to emulate.

All functions are per-shard functions: call them inside ``shard_map`` (or
use the ``*_sharded`` convenience wrappers that do it for you).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def _ring_perm(n: int, shift: int = 1) -> list[tuple[int, int]]:
    return [(i, (i + shift) % n) for i in range(n)]


def ring_shift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Rotate shards around the ring: each device sends its shard `shift`
    steps to the right and receives from the left. The enqueued-sendrecv
    primitive of the ICI plane."""
    n = lax.axis_size(axis_name)
    return lax.ppermute(x, axis_name, perm=_ring_perm(n, shift))


def neighbor_exchange(right_going: jax.Array, left_going: jax.Array,
                      axis_name: str) -> tuple[jax.Array, jax.Array]:
    """Bidirectional neighbor exchange: returns (from_left, from_right).

    Two opposite collective-permutes, which XLA schedules onto both ICI
    directions concurrently (full-duplex links).
    """
    n = lax.axis_size(axis_name)
    from_left = lax.ppermute(right_going, axis_name, perm=_ring_perm(n, 1))
    from_right = lax.ppermute(left_going, axis_name, perm=_ring_perm(n, -1))
    return from_left, from_right


def halo_exchange_1d(x: jax.Array, axis_name: str, halo: int) -> jax.Array:
    """1D halo exchange (periodic): pads shard `x` (leading axis) with
    `halo` rows from both ring neighbors.

    TPU-native counterpart of the reference's partitioned halo use-case
    (BASELINE.json configs[1]): the neighbor's boundary block arrives as
    one fused collective-permute instead of per-partition MPI messages.
    """
    top = x[:halo]          # my first rows -> left neighbor's bottom halo
    bottom = x[-halo:]      # my last rows  -> right neighbor's top halo
    from_left, from_right = neighbor_exchange(bottom, top, axis_name)
    return jnp.concatenate([from_left, x, from_right], axis=0)


def halo_exchange_2d(x: jax.Array, row_axis: str, col_axis: str,
                     halo: int) -> jax.Array:
    """2D halo exchange (periodic) over a 2D mesh (BASELINE.json
    configs[2]): rows first, then columns of the already-padded block — so
    edge halos carry the 4 axis neighbors and corner cells carry the
    DIAGONAL neighbors' corners (sufficient for 9-point as well as 5-point
    stencils).

    `x` is the local [H, W] block; returns [H+2h, W+2h].
    """
    x = halo_exchange_1d(x, row_axis, halo)                # pad rows
    left = x[:, :halo]
    right = x[:, -halo:]
    from_left, from_right = neighbor_exchange(right, left, col_axis)
    return jnp.concatenate([from_left, x, from_right], axis=1)


def all_to_all_seq(x: jax.Array, axis_name: str, split_axis: int,
                   concat_axis: int) -> jax.Array:
    """All-to-all reshard (the Ulysses sequence-parallelism primitive):
    redistributes a [.., seq_shard, .., heads, ..] layout between
    sequence-sharded and head-sharded, in one ICI all-to-all."""
    n = lax.axis_size(axis_name)
    parts = jnp.split(x, n, axis=split_axis)
    stacked = jnp.stack(parts, axis=0)  # [n, ...]
    swapped = lax.all_to_all(stacked, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)
    return jnp.concatenate([swapped[i] for i in range(n)], axis=concat_axis)


# ---- array-level wrappers (shard_map plumbing) ---------------------------


def ring_shift_sharded(arr: jax.Array, mesh: Mesh, axis_name: str = "x",
                       shift: int = 1) -> jax.Array:
    """Array-level ring shift: `arr` sharded on its leading dim over
    `axis_name`; every shard moves one ring step."""
    spec = P(axis_name)
    f = shard_map(
        functools.partial(ring_shift, axis_name=axis_name, shift=shift),
        mesh=mesh, in_specs=(spec,), out_specs=spec)
    return f(arr)


def halo_exchange_1d_sharded(arr: jax.Array, mesh: Mesh, halo: int,
                             axis_name: str = "x") -> jax.Array:
    """Array-level 1D halo exchange; returns the per-shard padded blocks
    stacked on a new leading axis (shape [n_shards, shard+2*halo, ...])."""
    spec = P(axis_name)
    out_spec = P(axis_name)

    def body(x):
        padded = halo_exchange_1d(x, axis_name, halo)
        return padded[None]  # add shard axis so out stays shardable

    f = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=out_spec)
    return f(arr)
