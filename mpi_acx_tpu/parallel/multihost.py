"""Multi-host (multi-process) distributed runtime.

The reference scales across hosts with `mpiexec -np N` + MPI as the wire
(SURVEY.md §4: multi-node is "tested" the way any MPI program is — by the
launcher). The TPU-native equivalent is JAX's distributed runtime: one
process per host, `jax.distributed.initialize` for rank bootstrap (the
`MPI_Init` analogue), a global device mesh whose inner axes ride ICI and
whose outer axis rides DCN, and `multihost_utils` for host-local <->
global array movement. This module packages that recipe behind an
acxrun-style env-var interface so the same worker code runs single-host
(no-op initialize) or multi-host (ACX_COORDINATOR/ACX_NPROCS/ACX_PROC_ID).
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               timeout_s: Optional[float] = None) -> None:
    """Bootstrap the distributed runtime (MPIX_Init's process-level half).

    Arguments fall back to ACX_COORDINATOR / ACX_NPROCS / ACX_PROC_ID, so
    a launcher exports three env vars and workers call ``initialize()``
    bare. Single-process (no coordinator configured) is a no-op, letting
    the same worker script run standalone. Idempotent.

    ``timeout_s`` (fallback: ACX_INIT_TIMEOUT_S) bounds the coordinator
    rendezvous where the JAX build supports it — a dead coordinator or a
    peer that never starts then raises instead of hanging the job, the
    process-bootstrap face of the runtime's op deadlines. Failures raise
    RuntimeError naming the coordinator/nprocs/proc triple so the
    launcher log says WHICH rank failed to join, not just "init failed".
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "ACX_COORDINATOR")
    if coordinator_address is None:
        return  # single-process mode
    if num_processes is None:
        # ACX_NPROCS/ACX_PROC_ID primary; fall back to the native
        # launcher's ACX_SIZE/ACX_RANK so a worker under acxrun only
        # needs ACX_COORDINATOR exported on top.
        e = os.environ.get("ACX_NPROCS") or os.environ.get("ACX_SIZE")
        if e is None:
            raise ValueError(
                "ACX_COORDINATOR is set but the process count isn't: "
                "export ACX_NPROCS (or run under acxrun, which sets "
                "ACX_SIZE) — defaulting to 1 would silently split the job")
        num_processes = int(e)
    if process_id is None:
        e = os.environ.get("ACX_PROC_ID") or os.environ.get("ACX_RANK")
        if e is None:
            raise ValueError(
                "ACX_COORDINATOR is set but the process id isn't: export "
                "ACX_PROC_ID (or run under acxrun, which sets ACX_RANK)")
        process_id = int(e)
    # Multi-process CPU (the test topology) needs a cross-process
    # collectives backend; gloo is the in-tree one. Harmless if the
    # platform is TPU (ICI collectives don't use it).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    if timeout_s is None:
        e = os.environ.get("ACX_INIT_TIMEOUT_S")
        timeout_s = float(e) if e else None
    kwargs = {}
    if timeout_s is not None:
        # Older jax.distributed.initialize has no timeout kwarg; a bounded
        # init is best-effort there rather than a hard version floor.
        import inspect
        sig = inspect.signature(jax.distributed.initialize)
        if "initialization_timeout" in sig.parameters:
            kwargs["initialization_timeout"] = int(timeout_s)
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kwargs)
    except Exception as e:
        raise RuntimeError(
            f"tpu-acx: multihost initialize failed (coordinator="
            f"{coordinator_address}, nprocs={num_processes}, "
            f"proc={process_id}): {e}") from e
    _initialized = True


def recovery_budget_s(max_attempts: Optional[int] = None,
                      backoff_ms: Optional[float] = None,
                      cap_ms: float = 2000.0,
                      margin_s: float = 1.0) -> float:
    """Worst-case seconds a lost link may spend in RECOVERING before the
    native transport gives up and declares the peer dead (docs/DESIGN.md
    "Survivable links"). Computed from the same knobs the transport reads
    — ACX_RECONNECT_MAX dial attempts with exponential backoff starting
    at ACX_RECONNECT_BACKOFF_MS, each wait capped at ``cap_ms`` — plus a
    fixed ``margin_s`` for the handshake itself.

    This is the number multi-host callers size their patience with: a
    coordinator waiting on a wedged worker (or a serving loop deciding
    when a requeued batch is definitely not coming back) should wait at
    least this long before treating recovery as failed — any shorter and
    it races the transport's own verdict; much longer only delays the
    inevitable."""
    if max_attempts is None:
        max_attempts = int(os.environ.get("ACX_RECONNECT_MAX", "5"))
    if backoff_ms is None:
        backoff_ms = float(os.environ.get("ACX_RECONNECT_BACKOFF_MS", "50"))
    total_ms = 0.0
    for attempt in range(1, max(0, max_attempts)):
        # Mirror of the native DialBackoffMs ladder: the wait AFTER
        # attempt k is backoff * 2^(k-1), capped.
        total_ms += min(backoff_ms * (2.0 ** (attempt - 1)), cap_ms)
    return total_ms / 1000.0 + margin_s


def fleet_join_budget_s(timeout_ms: Optional[float] = None,
                        margin_s: float = 1.0) -> float:
    """Worst-case seconds a joining replacement rank may spend dialing the
    fleet's rendezvous listeners before the native transport gives up
    (exit 13) — ACX_FLEET_JOIN_TIMEOUT_MS plus a fixed ``margin_s`` for
    the per-peer JOIN handshakes. The rolling-restart counterpart of
    ``recovery_budget_s``: a coordinator (or serving loop) replacing a
    rank should wait at least this long for the new incarnation's slot to
    come back ACTIVE before escalating to the hang doctor."""
    if timeout_ms is None:
        timeout_ms = float(os.environ.get("ACX_FLEET_JOIN_TIMEOUT_MS",
                                          "10000"))
    return timeout_ms / 1000.0 + margin_s


def fleet_snapshot(runtime) -> dict:
    """One-call fleet summary off a ``Runtime``: ``{"epoch", "view",
    "stats"}`` (docs/DESIGN.md §12). The view is THIS process's — epochs
    converge by max-merge, so treat it as a local observation, not a
    global agreement."""
    return {"epoch": runtime.fleet_epoch(),
            "view": runtime.fleet_view(),
            "stats": runtime.fleet_stats()}


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_mesh(axis_sizes: Mapping[str, int]) -> Mesh:
    """Mesh over ALL processes' devices with named axes (dict order =
    major-to-minor). Put the cross-host axis FIRST so consecutive devices
    (same host, ICI-connected) land in the innermost axes — collectives
    over inner axes then ride ICI, the outer axis rides DCN.

    Example (2 hosts x 4 chips): ``global_mesh({"dcn": 2, "ici": 4})``;
    dp-over-hosts + tp-within-host: ``global_mesh({"dp": 2, "tp": 4})``.
    """
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    devices = jax.devices()
    n = int(np.prod(tuple(axis_sizes.values())))
    if n != len(devices):
        raise ValueError(f"mesh {dict(axis_sizes)} needs {n} devices, the "
                         f"job has {len(devices)}")
    return mesh_from_devices(axis_sizes, devices)


def hybrid_mesh(ici_axes: Mapping[str, int],
                dcn_axis: str = "dcn") -> Mesh:
    """ICI x DCN mesh: one outer axis spanning processes (DCN), the given
    inner axes within each process's devices (ICI). The standard layout
    for data-parallel-across-hosts, model-parallel-within-host."""
    n_proc = jax.process_count()
    local = len(jax.local_devices())
    sizes = tuple(ici_axes.values())
    if int(np.prod(sizes)) != local:
        raise ValueError(f"ici axes {dict(ici_axes)} need {np.prod(sizes)} "
                         f"local devices, have {local}")
    return global_mesh({dcn_axis: n_proc, **ici_axes})


def host_local_to_global(x, mesh: Mesh, pspec: P):
    """Assemble per-process shards into one global jax.Array (the moral
    inverse of scattering an MPI-rank-local buffer)."""
    from jax.experimental import multihost_utils
    return multihost_utils.host_local_array_to_global_array(x, mesh, pspec)


def global_to_host_local(x, mesh: Mesh, pspec: P):
    from jax.experimental import multihost_utils
    return multihost_utils.global_array_to_host_local_array(x, mesh, pspec)


def broadcast_from_host0(x):
    """Replicate host 0's pytree to every process (param init pattern:
    init once, broadcast, avoid divergent RNG)."""
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(x)


def sync(name: str = "acx_sync") -> None:
    """Cross-process barrier (MPI_Barrier analogue)."""
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def replicated(mesh: Mesh):
    """Sharding for fully-replicated values on the mesh."""
    return NamedSharding(mesh, P())
