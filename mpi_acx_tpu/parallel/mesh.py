"""Mesh construction helpers.

The reference's process topology is `mpiexec -np N` ranks (SURVEY.md §4);
the TPU-native topology is a named device mesh over which pjit/shard_map
place collectives on ICI. These helpers build the standard meshes the rest
of the package expects.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def mesh_from_devices(axis_sizes: Mapping[str, int],
                      devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Builds a Mesh with the given axis names/sizes from available devices.

    Axis order follows dict order; the product must equal the device count
    used. Example: ``mesh_from_devices({"dp": 2, "tp": 4})`` on 8 devices.
    """
    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    if len(devices) < n:
        raise ValueError(f"need {n} devices for mesh {dict(axis_sizes)}, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(arr, names)


def make_mesh(n_devices: int | None = None) -> Mesh:
    """Default mesh for n devices: a 1D "x" axis (ring).

    The ring is the canonical topology for the reference's tests (every
    test/src program is a ring exchange) and maps directly onto an ICI ring.
    """
    devices = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    return Mesh(np.asarray(devices), ("x",))
