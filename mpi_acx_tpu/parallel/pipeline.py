"""Pipeline parallelism: microbatch exchange over a 'pp' mesh axis.

The reference positions partitioned P2P as the substrate for
pipeline-parallel microbatch exchange (SURVEY.md §2 "Parallelism
strategies"; BASELINE.json configs[3,4]). This module is that application,
TPU-native: a GPipe-style schedule where each pipeline stage is one slice
of the mesh's 'pp' axis, activations travel stage->stage+1 by
collective-permute on ICI, and the whole schedule is a single
``lax.scan`` inside ``shard_map`` — one compiled program, no host in the
loop. Autodiff through the scan gives the backward pipeline (reverse
permutes) for free.

Schedule: T = n_micro + n_stages - 1 ticks; stage s computes microbatch m
at tick t = s + m (the classic GPipe timetable; bubbles are masked
compute).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_forward(
    stage_fn: Callable,
    stage_params,
    xs: jax.Array,
    axis_name: str,
    with_aux: bool = False,
):
    """Runs xs ([n_micro, micro_batch, ...], replicated) through the
    pipeline; returns the last stage's outputs [n_micro, micro_batch, ...]
    (replicated via psum).

    Per-shard function: call inside shard_map with `stage_params` sharded
    P(axis_name) on a stacked leading stage axis (shard_map hands each
    device its own stage's slice, leading dim 1 — squeezed here).

    stage_fn(params, x) -> y with y.shape == x.shape (inter-stage
    activations must be shape-stable so the wire format is fixed).

    ``with_aux=True``: stage_fn returns ``(y, aux)`` with aux a pytree of
    f32 scalars (e.g. MoE router losses), and the function returns
    ``(ys, aux_sum)`` where aux_sum is THIS stage's aux summed over its
    valid (non-bubble) ticks only — i.e. over every (layer-of-this-stage,
    microbatch) pair, exactly once. Aux never rides the inter-stage wire
    (it is additive, so a per-stage local sum + one caller-side psum over
    the pp axis assembles the total); bubble ticks compute clamped
    garbage whose aux is masked out here, keeping autodiff exact.
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n_stages - 1

    params = jax.tree.map(lambda p: p[0], stage_params)  # drop stage axis

    # stage s -> s+1 (no wraparound: stage 0 receives zeros = bubble).
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        from_left, aux_acc = carry
        m = jnp.clip(t, 0, n_micro - 1)
        first_in = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
        x = jnp.where(stage == 0, first_in, from_left)
        if with_aux:
            y, aux = stage_fn(params, x)
            # Stage s computes microbatch t-s at tick t; valid iff that
            # index is a real microbatch (everything else is bubble).
            valid = jnp.logical_and(t >= stage, t - stage < n_micro)
            aux_acc = jax.tree.map(
                lambda a, b: a + jnp.where(valid, b, 0.0), aux_acc, aux)
        else:
            y = stage_fn(params, x)
        send = lax.ppermute(y, axis_name, perm=fwd_perm)
        return (send, aux_acc), y

    # Carry is device-varying (each stage holds a different activation).
    init = lax.pcast(jnp.zeros_like(xs[0]), axis_name, to="varying")
    aux0 = None
    if with_aux:
        probe = jax.eval_shape(stage_fn, params, jax.ShapeDtypeStruct(
            xs.shape[1:], xs.dtype))[1]
        aux0 = jax.tree.map(
            lambda s: lax.pcast(jnp.zeros(s.shape, s.dtype), axis_name,
                                to="varying"), probe)
    (_, aux_sum), ys = lax.scan(tick, (init, aux0), jnp.arange(ticks))

    # The last stage's valid outputs live at ticks [n_stages-1, ticks).
    tail = lax.dynamic_slice_in_dim(ys, n_stages - 1, n_micro, 0)
    contrib = jnp.where(stage == n_stages - 1, tail, jnp.zeros_like(tail))
    out = lax.psum(contrib, axis_name)
    return (out, aux_sum) if with_aux else out


def pipeline_forward_interleaved(
    stage_fn: Callable,
    stage_params,
    xs: jax.Array,
    axis_name: str,
    n_virtual: int,
    with_aux: bool = False,
):
    """Interleaved virtual-stage pipeline (the Megatron-LM interleaved
    schedule's forward): device s holds ``v = n_virtual`` chunks, chunk j
    being global stage ``j*pp + s``. A time slot is ONE chunk application
    per device — microbatches flow in groups of ``pp`` through chunk 0,
    then the same group through chunk 1, etc. — so the whole forward
    takes ``v*n_micro + pp - 1`` chunk-slots per device, of which only
    ``pp - 1`` are fill/drain. GPipe over the same ``v*pp``-stage model
    (v layers folded per stage, :func:`pipeline_forward`) wastes
    ``v*(pp-1)`` chunk-slots; interleaving divides the bubble by ``v``
    at the price of ``v`` x more ICI hops per activation (cheap).

    Per-shard function (use inside shard_map). stage_params' leading axes
    are [pp, n_virtual, ...] (shard P(axis_name) on the first). xs:
    [n_micro, micro_batch, ...] replicated, with ``n_micro % pp == 0``
    (the schedule's group size — the standard Megatron constraint);
    returns the final global stage's outputs, replicated.

    Schedule formula: device s at slot t computes, with u = t - s,
    b = u // pp, chunk j = b % v, microbatch m = (b // v)*pp + u % pp.
    Every hop (s -> s+1 same-chunk, and pp-1 -> 0 advancing to chunk
    j+1) is consumed exactly one slot after production, so the carry is
    a single activation buffer. Fill/drain slots compute clamped garbage
    that is never collected (the masked-compute construction of
    :func:`pipeline_forward`, so autodiff through the scan stays exact).

    ``with_aux=True`` follows :func:`pipeline_forward`'s contract:
    stage_fn returns ``(y, aux)``; returns ``(ys, aux_sum)`` with
    aux_sum this device's aux over its valid slots — each of its v
    chunks applied to each microbatch exactly once (``v * n_micro``
    contributions; fill/drain slots masked out).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    v = n_virtual
    n_micro = xs.shape[0]
    if n_micro % n_stages != 0:
        raise ValueError(
            f"interleaved schedule needs n_micro ({n_micro}) % pp "
            f"({n_stages}) == 0")
    ticks = v * n_micro + n_stages - 1

    params = jax.tree.map(lambda p: p[0], stage_params)  # [v, per, ...]

    # One CIRCULAR permute per slot: s -> s+1 is the same-chunk hop and
    # pp-1 -> 0 is the wrap that advances to the next chunk.
    ring_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # Microbatch m's final output leaves device pp-1's chunk v-1 at slot
    # (v*(m//pp) + v - 1)*pp + m%pp + (pp-1); slot -> m lookup (-1 = not
    # a collection slot), so outputs accumulate into an [n_micro, ...]
    # buffer instead of stacking every tick (~v x less activation memory).
    slot_to_m = [-1] * ticks
    for m in range(n_micro):
        tau = ((v * (m // n_stages) + v - 1) * n_stages + m % n_stages
               + n_stages - 1)
        slot_to_m[tau] = m
    slot_to_m = jnp.asarray(slot_to_m)

    def tick(carry, t):
        buf, acc, aux_acc = carry
        u = jnp.maximum(t - stage, 0)
        b = u // n_stages
        j = b % v
        m = jnp.clip((b // v) * n_stages + u % n_stages, 0, n_micro - 1)
        fresh = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
        # Device 0 starts a chunk-0 slot from a fresh microbatch; every
        # other slot consumes last slot's routed activation.
        x = jnp.where(jnp.logical_and(stage == 0, j == 0), fresh, buf)
        pj = jax.tree.map(
            lambda q: lax.dynamic_index_in_dim(q, j, 0, keepdims=False),
            params)
        if with_aux:
            y, aux = stage_fn(pj, x)
            # Device s's valid slots are u = t - stage in [0, v*n_micro):
            # each (chunk, microbatch) pair exactly once.
            valid = jnp.logical_and(t >= stage, t - stage < v * n_micro)
            aux_acc = jax.tree.map(
                lambda a, bb: a + jnp.where(valid, bb, 0.0), aux_acc, aux)
        else:
            y = stage_fn(pj, x)
        mm = slot_to_m[t]
        upd = lax.dynamic_update_slice_in_dim(
            acc, y[None], jnp.clip(mm, 0, n_micro - 1), axis=0)
        acc = jnp.where(
            jnp.logical_and(mm >= 0, stage == n_stages - 1), upd, acc)
        nxt = lax.ppermute(y, axis_name, perm=ring_perm)
        return (nxt, acc, aux_acc), None

    init = lax.pcast(jnp.zeros(xs.shape[1:], xs.dtype), axis_name,
                     to="varying")
    acc0 = lax.pcast(jnp.zeros_like(xs), axis_name, to="varying")
    aux0 = None
    if with_aux:
        p0 = jax.tree.map(
            lambda q: lax.dynamic_index_in_dim(q, 0, 0, keepdims=False),
            params)
        probe = jax.eval_shape(stage_fn, p0, jax.ShapeDtypeStruct(
            xs.shape[1:], xs.dtype))[1]
        aux0 = jax.tree.map(
            lambda s: lax.pcast(jnp.zeros(s.shape, s.dtype), axis_name,
                                to="varying"), probe)
    (_, acc, aux_sum), _ = lax.scan(tick, (init, acc0, aux0),
                                    jnp.arange(ticks))
    out = lax.psum(acc, axis_name)
    return (out, aux_sum) if with_aux else out


def pipeline_loss(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    xs: jax.Array,
    targets: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Mean loss over microbatches through the pipeline (differentiable;
    jax.grad of this per-shard function yields the 1F1B-equivalent backward
    schedule as the scan's transpose)."""
    ys = pipeline_forward(stage_fn, stage_params, xs, axis_name)
    return loss_fn(ys, targets)


# -- 1F1B: the memory-bounded schedule --------------------------------------


def _schedule_1f1b(P: int, M: int):
    """Static 1F1B timetable (Python ints, computed at trace time).

    Slot grid: each slot holds at most ONE op per stage (a forward or a
    backward of one microbatch). Stage s runs its warmup forwards at
    slots ``s + m`` (m < P - s), steady-state forwards at ``2m + s``,
    and backwards at ``2P - 1 - s + 2m`` — the classic Megatron-LM
    non-interleaved 1F1B: after warmup each backward's freed activation
    is immediately refilled by one forward, so at most ``P - s``
    microbatches are ever in flight at stage s (O(pp), independent of
    n_micro — GPipe's O(n_micro) is the round-3 verdict item this
    closes).

    Returns ``(T, fwd, bwd, arr, K)``: total slots; [P, T] int arrays
    with the microbatch forwarded/backwarded by stage s at slot t (-1 =
    idle); arrivals ``arr[s][t]`` = microbatch whose activation reaches
    stage s at slot t (sent by s-1 one slot earlier; -1 = none); and K,
    the input-buffer depth = max microbatch activations simultaneously
    alive (arrival..backward) at any stage. Every constraint (one op
    per slot, producer-before-consumer, tight cotangent chain, in-flight
    bound) is asserted here, so a schedule bug fails loudly at build
    time, not as silent garbage."""
    f_slot = {}
    b_slot = {}
    for s in range(P):
        for m in range(M):
            f_slot[(s, m)] = s + m if m <= P - 1 - s else 2 * m + s
            b_slot[(s, m)] = 2 * P - 1 - s + 2 * m
    T = max(b_slot.values()) + 1

    import numpy as np
    fwd = np.full((P, T), -1, np.int32)
    bwd = np.full((P, T), -1, np.int32)
    arr = np.full((P, T), -1, np.int32)
    for (s, m), t in f_slot.items():
        assert fwd[s, t] == -1 and bwd[s, t] == -1, (s, t)
        fwd[s, t] = m
    for (s, m), t in b_slot.items():
        assert fwd[s, t] == -1 and bwd[s, t] == -1, (s, t)
        bwd[s, t] = m
    for s in range(1, P):
        for m in range(M):
            t_arr = f_slot[(s - 1, m)] + 1
            assert t_arr <= f_slot[(s, m)], (s, m)   # arrives before use
            arr[s, t_arr] = m
    for s in range(P - 1):
        for m in range(M):
            # dx from stage s+1 lands exactly on stage s's backward slot.
            assert b_slot[(s + 1, m)] + 1 == b_slot[(s, m)], (s, m)
    for m in range(M):
        assert b_slot[(P - 1, m)] == f_slot[(P - 1, m)] + 1, m

    K = 0
    for s in range(P):
        births = {m: (f_slot[(s - 1, m)] + 1 if s else f_slot[(s, m)])
                  for m in range(M)}
        for t in range(T):
            live = sum(1 for m in range(M)
                       if births[m] <= t <= b_slot[(s, m)])
            K = max(K, live)
    return T, fwd, bwd, arr, K


def pipeline_1f1b_loss_and_grads(
    stage_fn: Callable,
    per_micro_loss: Callable,
    stage_params,
    xs: jax.Array,
    targets,
    axis_name: str,
):
    """Pipeline loss AND gradients under the 1F1B schedule (per-shard
    function; call inside shard_map exactly like :func:`pipeline_forward`
    — stage_params sharded P(axis_name), xs/targets
    [n_micro, micro_batch, ...] replicated).

    Returns ``(loss, stage_grads)``: the mean of
    ``per_micro_loss(y_m, targets[m])`` over microbatches (replicated),
    and THIS stage's parameter gradients with the leading stage axis
    restored (same pytree structure as stage_params), exactly equal to
    ``jax.grad`` of :func:`pipeline_loss` up to fp summation order
    (asserted by tests/test_pipeline_1f1b.py).

    Memory contract — the point of the schedule: autodiff is never
    applied across the slot scan. The backward of each microbatch is an
    explicit ``jax.vjp`` inside the scan body, re-running the stage
    forward from its STORED INPUT (per-stage remat), so peak activation
    residency is the K-deep input ring buffer with K <= pp + 1 —
    O(pp), not GPipe's O(n_micro) scan residuals. Verified against
    XLA's compiled memory analysis in the tests.

    Caveats: ``stage_fn`` must be collective-free (forward and backward
    run under per-device ``lax.cond`` — stages genuinely take different
    branches each slot, so a collective inside would desynchronize);
    ``per_micro_loss(y, tgt) -> scalar`` is evaluated on the LAST
    stage's outputs only. Embedding / head parameters outside
    stage_params are the caller's to handle (the flagship train step
    keeps them outside the pipeline)."""
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = xs.shape[0]

    # Static timetable (axis_size is a Python int inside shard_map).
    P_static = int(n_stages)
    T, fwd_np, bwd_np, arr_np, K = _schedule_1f1b(P_static, n_micro)
    fwd_tab = jnp.asarray(fwd_np)
    bwd_tab = jnp.asarray(bwd_np)
    arr_tab = jnp.asarray(arr_np)

    params = jax.tree.map(lambda p: p[0], stage_params)  # drop stage axis

    fwd_perm = [(i, i + 1) for i in range(P_static - 1)]
    bwd_perm = [(i, i - 1) for i in range(1, P_static)]

    mb_shape = xs.shape[1:]
    zero_act = jnp.zeros(mb_shape, xs.dtype)
    last = P_static - 1

    def slot(carry, t):
        ib, fwd_msg, bwd_msg, gacc, lacc = carry

        # 1) Bank an arriving activation (sent by stage-1 last slot).
        am = arr_tab[stage, t]
        ib = lax.cond(
            am >= 0,
            lambda ib: lax.dynamic_update_index_in_dim(
                ib, fwd_msg, jnp.maximum(am, 0) % K, 0),
            lambda ib: ib, ib)

        # 2) Forward, if scheduled this slot.
        mf = fwd_tab[stage, t]

        def do_fwd(ib):
            mfc = jnp.maximum(mf, 0)
            fresh = lax.dynamic_index_in_dim(xs, mfc, 0, keepdims=False)
            x = jnp.where(stage == 0, fresh,
                          lax.dynamic_index_in_dim(ib, mfc % K, 0,
                                                   keepdims=False))
            # Stage 0 banks its input too — the backward recomputes
            # from the ring buffer uniformly.
            ib = lax.dynamic_update_index_in_dim(ib, x, mfc % K, 0)
            return ib, stage_fn(params, x)

        ib, y_out = lax.cond(mf >= 0, do_fwd,
                             lambda ib: (ib, zero_act), ib)

        # 3) Backward, if scheduled: recompute from the stored input
        # (remat), seed with the loss cotangent (last stage) or the
        # neighbor's dx (everyone else), accumulate param grads.
        mb = bwd_tab[stage, t]

        def do_bwd(operand):
            ib, gacc, lacc = operand
            mbc = jnp.maximum(mb, 0)
            x = lax.dynamic_index_in_dim(ib, mbc % K, 0, keepdims=False)
            y, vjp_fn = jax.vjp(stage_fn, params, x)

            def seed_from_loss(y):
                tgt = jax.tree.map(
                    lambda tg: lax.dynamic_index_in_dim(tg, mbc, 0,
                                                        keepdims=False),
                    targets)
                lval, loss_vjp = jax.vjp(
                    lambda yy: per_micro_loss(yy, tgt), y)
                (dy,) = loss_vjp(jnp.ones((), lval.dtype))
                return lval.astype(jnp.float32), dy.astype(y.dtype)

            # Only the last stage pays for the loss evaluation; the
            # rest seed from the neighbor's cotangent.
            lval, dy = lax.cond(
                stage == last, seed_from_loss,
                lambda y: (jnp.zeros((), jnp.float32),
                           bwd_msg.astype(y.dtype)), y)
            dp, dx = vjp_fn(dy)
            gacc = jax.tree.map(jnp.add, gacc, dp)
            return (ib, gacc, lacc + lval), dx

        (ib, gacc, lacc), dx_out = lax.cond(
            mb >= 0, do_bwd,
            lambda op: (op, zero_act), (ib, gacc, lacc))

        # 4) Lockstep exchanges: activations ride right, cotangents left.
        fwd_msg = lax.ppermute(y_out, axis_name, perm=fwd_perm)
        bwd_msg = lax.ppermute(dx_out, axis_name, perm=bwd_perm)
        return (ib, fwd_msg, bwd_msg, gacc, lacc), None

    varying = lambda a: lax.pcast(a, axis_name, to="varying")  # noqa: E731
    init = (
        varying(jnp.zeros((K,) + mb_shape, xs.dtype)),
        varying(zero_act),
        varying(zero_act),
        jax.tree.map(lambda p: varying(jnp.zeros_like(p)), params),
        varying(jnp.zeros((), jnp.float32)),
    )
    (ib, fwd_msg, bwd_msg, gacc, lacc), _ = lax.scan(
        slot, init, jnp.arange(T))

    loss = lax.psum(lacc, axis_name) / n_micro
    # Loss is mean-over-micro: scale the summed per-micro cotangents.
    grads = jax.tree.map(lambda g: (g / n_micro)[None], gacc)
    return loss, grads


def run_pipeline(mesh, stage_fn, stacked_params, xs, axis_name: str = "pp"):
    """Array-level convenience: stacked_params' leading axis = stage."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    f = shard_map(
        functools.partial(pipeline_forward, stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    return f(stacked_params, xs)
