"""Pipeline parallelism: microbatch exchange over a 'pp' mesh axis.

The reference positions partitioned P2P as the substrate for
pipeline-parallel microbatch exchange (SURVEY.md §2 "Parallelism
strategies"; BASELINE.json configs[3,4]). This module is that application,
TPU-native: a GPipe-style schedule where each pipeline stage is one slice
of the mesh's 'pp' axis, activations travel stage->stage+1 by
collective-permute on ICI, and the whole schedule is a single
``lax.scan`` inside ``shard_map`` — one compiled program, no host in the
loop. Autodiff through the scan gives the backward pipeline (reverse
permutes) for free.

Schedule: T = n_micro + n_stages - 1 ticks; stage s computes microbatch m
at tick t = s + m (the classic GPipe timetable; bubbles are masked
compute).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_forward(
    stage_fn: Callable,
    stage_params,
    xs: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Runs xs ([n_micro, micro_batch, ...], replicated) through the
    pipeline; returns the last stage's outputs [n_micro, micro_batch, ...]
    (replicated via psum).

    Per-shard function: call inside shard_map with `stage_params` sharded
    P(axis_name) on a stacked leading stage axis (shard_map hands each
    device its own stage's slice, leading dim 1 — squeezed here).

    stage_fn(params, x) -> y with y.shape == x.shape (inter-stage
    activations must be shape-stable so the wire format is fixed).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n_stages - 1

    params = jax.tree.map(lambda p: p[0], stage_params)  # drop stage axis

    # stage s -> s+1 (no wraparound: stage 0 receives zeros = bubble).
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        from_left = carry
        m = jnp.clip(t, 0, n_micro - 1)
        first_in = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
        x = jnp.where(stage == 0, first_in, from_left)
        y = stage_fn(params, x)
        send = lax.ppermute(y, axis_name, perm=fwd_perm)
        return send, y

    # Carry is device-varying (each stage holds a different activation).
    init = lax.pcast(jnp.zeros_like(xs[0]), axis_name, to="varying")
    _, ys = lax.scan(tick, init, jnp.arange(ticks))

    # The last stage's valid outputs live at ticks [n_stages-1, ticks).
    tail = lax.dynamic_slice_in_dim(ys, n_stages - 1, n_micro, 0)
    contrib = jnp.where(stage == n_stages - 1, tail, jnp.zeros_like(tail))
    return lax.psum(contrib, axis_name)


def pipeline_forward_interleaved(
    stage_fn: Callable,
    stage_params,
    xs: jax.Array,
    axis_name: str,
    n_virtual: int,
) -> jax.Array:
    """Interleaved virtual-stage pipeline (the Megatron-LM interleaved
    schedule's forward): device s holds ``v = n_virtual`` chunks, chunk j
    being global stage ``j*pp + s``. A time slot is ONE chunk application
    per device — microbatches flow in groups of ``pp`` through chunk 0,
    then the same group through chunk 1, etc. — so the whole forward
    takes ``v*n_micro + pp - 1`` chunk-slots per device, of which only
    ``pp - 1`` are fill/drain. GPipe over the same ``v*pp``-stage model
    (v layers folded per stage, :func:`pipeline_forward`) wastes
    ``v*(pp-1)`` chunk-slots; interleaving divides the bubble by ``v``
    at the price of ``v`` x more ICI hops per activation (cheap).

    Per-shard function (use inside shard_map). stage_params' leading axes
    are [pp, n_virtual, ...] (shard P(axis_name) on the first). xs:
    [n_micro, micro_batch, ...] replicated, with ``n_micro % pp == 0``
    (the schedule's group size — the standard Megatron constraint);
    returns the final global stage's outputs, replicated.

    Schedule formula: device s at slot t computes, with u = t - s,
    b = u // pp, chunk j = b % v, microbatch m = (b // v)*pp + u % pp.
    Every hop (s -> s+1 same-chunk, and pp-1 -> 0 advancing to chunk
    j+1) is consumed exactly one slot after production, so the carry is
    a single activation buffer. Fill/drain slots compute clamped garbage
    that is never collected (the masked-compute construction of
    :func:`pipeline_forward`, so autodiff through the scan stays exact).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    v = n_virtual
    n_micro = xs.shape[0]
    if n_micro % n_stages != 0:
        raise ValueError(
            f"interleaved schedule needs n_micro ({n_micro}) % pp "
            f"({n_stages}) == 0")
    ticks = v * n_micro + n_stages - 1

    params = jax.tree.map(lambda p: p[0], stage_params)  # [v, per, ...]

    # One CIRCULAR permute per slot: s -> s+1 is the same-chunk hop and
    # pp-1 -> 0 is the wrap that advances to the next chunk.
    ring_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # Microbatch m's final output leaves device pp-1's chunk v-1 at slot
    # (v*(m//pp) + v - 1)*pp + m%pp + (pp-1); slot -> m lookup (-1 = not
    # a collection slot), so outputs accumulate into an [n_micro, ...]
    # buffer instead of stacking every tick (~v x less activation memory).
    slot_to_m = [-1] * ticks
    for m in range(n_micro):
        tau = ((v * (m // n_stages) + v - 1) * n_stages + m % n_stages
               + n_stages - 1)
        slot_to_m[tau] = m
    slot_to_m = jnp.asarray(slot_to_m)

    def tick(carry, t):
        buf, acc = carry
        u = jnp.maximum(t - stage, 0)
        b = u // n_stages
        j = b % v
        m = jnp.clip((b // v) * n_stages + u % n_stages, 0, n_micro - 1)
        fresh = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
        # Device 0 starts a chunk-0 slot from a fresh microbatch; every
        # other slot consumes last slot's routed activation.
        x = jnp.where(jnp.logical_and(stage == 0, j == 0), fresh, buf)
        pj = jax.tree.map(
            lambda q: lax.dynamic_index_in_dim(q, j, 0, keepdims=False),
            params)
        y = stage_fn(pj, x)
        mm = slot_to_m[t]
        upd = lax.dynamic_update_slice_in_dim(
            acc, y[None], jnp.clip(mm, 0, n_micro - 1), axis=0)
        acc = jnp.where(
            jnp.logical_and(mm >= 0, stage == n_stages - 1), upd, acc)
        nxt = lax.ppermute(y, axis_name, perm=ring_perm)
        return (nxt, acc), None

    init = lax.pcast(jnp.zeros(xs.shape[1:], xs.dtype), axis_name,
                     to="varying")
    acc0 = lax.pcast(jnp.zeros_like(xs), axis_name, to="varying")
    (_, acc), _ = lax.scan(tick, (init, acc0), jnp.arange(ticks))
    return lax.psum(acc, axis_name)


def pipeline_loss(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    xs: jax.Array,
    targets: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Mean loss over microbatches through the pipeline (differentiable;
    jax.grad of this per-shard function yields the 1F1B-equivalent backward
    schedule as the scan's transpose)."""
    ys = pipeline_forward(stage_fn, stage_params, xs, axis_name)
    return loss_fn(ys, targets)


def run_pipeline(mesh, stage_fn, stacked_params, xs, axis_name: str = "pp"):
    """Array-level convenience: stacked_params' leading axis = stage."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    f = shard_map(
        functools.partial(pipeline_forward, stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    return f(stacked_params, xs)
