"""Pipeline parallelism: microbatch exchange over a 'pp' mesh axis.

The reference positions partitioned P2P as the substrate for
pipeline-parallel microbatch exchange (SURVEY.md §2 "Parallelism
strategies"; BASELINE.json configs[3,4]). This module is that application,
TPU-native: a GPipe-style schedule where each pipeline stage is one slice
of the mesh's 'pp' axis, activations travel stage->stage+1 by
collective-permute on ICI, and the whole schedule is a single
``lax.scan`` inside ``shard_map`` — one compiled program, no host in the
loop. Autodiff through the scan gives the backward pipeline (reverse
permutes) for free.

Schedule: T = n_micro + n_stages - 1 ticks; stage s computes microbatch m
at tick t = s + m (the classic GPipe timetable; bubbles are masked
compute).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_forward(
    stage_fn: Callable,
    stage_params,
    xs: jax.Array,
    axis_name: str,
    with_aux: bool = False,
):
    """Runs xs ([n_micro, micro_batch, ...], replicated) through the
    pipeline; returns the last stage's outputs [n_micro, micro_batch, ...]
    (replicated via psum).

    Per-shard function: call inside shard_map with `stage_params` sharded
    P(axis_name) on a stacked leading stage axis (shard_map hands each
    device its own stage's slice, leading dim 1 — squeezed here).

    stage_fn(params, x) -> y with y.shape == x.shape (inter-stage
    activations must be shape-stable so the wire format is fixed).

    ``with_aux=True``: stage_fn returns ``(y, aux)`` with aux a pytree of
    f32 scalars (e.g. MoE router losses), and the function returns
    ``(ys, aux_sum)`` where aux_sum is THIS stage's aux summed over its
    valid (non-bubble) ticks only — i.e. over every (layer-of-this-stage,
    microbatch) pair, exactly once. Aux never rides the inter-stage wire
    (it is additive, so a per-stage local sum + one caller-side psum over
    the pp axis assembles the total); bubble ticks compute clamped
    garbage whose aux is masked out here, keeping autodiff exact.
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n_stages - 1

    params = jax.tree.map(lambda p: p[0], stage_params)  # drop stage axis

    # stage s -> s+1 (no wraparound: stage 0 receives zeros = bubble).
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        from_left, aux_acc = carry
        m = jnp.clip(t, 0, n_micro - 1)
        first_in = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
        x = jnp.where(stage == 0, first_in, from_left)
        if with_aux:
            y, aux = stage_fn(params, x)
            # Stage s computes microbatch t-s at tick t; valid iff that
            # index is a real microbatch (everything else is bubble).
            valid = jnp.logical_and(t >= stage, t - stage < n_micro)
            aux_acc = jax.tree.map(
                lambda a, b: a + jnp.where(valid, b, 0.0), aux_acc, aux)
        else:
            y = stage_fn(params, x)
        send = lax.ppermute(y, axis_name, perm=fwd_perm)
        return (send, aux_acc), y

    # Carry is device-varying (each stage holds a different activation).
    init = lax.pcast(jnp.zeros_like(xs[0]), axis_name, to="varying")
    aux0 = None
    if with_aux:
        probe = jax.eval_shape(stage_fn, params, jax.ShapeDtypeStruct(
            xs.shape[1:], xs.dtype))[1]
        aux0 = jax.tree.map(
            lambda s: lax.pcast(jnp.zeros(s.shape, s.dtype), axis_name,
                                to="varying"), probe)
    (_, aux_sum), ys = lax.scan(tick, (init, aux0), jnp.arange(ticks))

    # The last stage's valid outputs live at ticks [n_stages-1, ticks).
    tail = lax.dynamic_slice_in_dim(ys, n_stages - 1, n_micro, 0)
    contrib = jnp.where(stage == n_stages - 1, tail, jnp.zeros_like(tail))
    out = lax.psum(contrib, axis_name)
    return (out, aux_sum) if with_aux else out


def pipeline_forward_interleaved(
    stage_fn: Callable,
    stage_params,
    xs: jax.Array,
    axis_name: str,
    n_virtual: int,
    with_aux: bool = False,
):
    """Interleaved virtual-stage pipeline (the Megatron-LM interleaved
    schedule's forward): device s holds ``v = n_virtual`` chunks, chunk j
    being global stage ``j*pp + s``. A time slot is ONE chunk application
    per device — microbatches flow in groups of ``pp`` through chunk 0,
    then the same group through chunk 1, etc. — so the whole forward
    takes ``v*n_micro + pp - 1`` chunk-slots per device, of which only
    ``pp - 1`` are fill/drain. GPipe over the same ``v*pp``-stage model
    (v layers folded per stage, :func:`pipeline_forward`) wastes
    ``v*(pp-1)`` chunk-slots; interleaving divides the bubble by ``v``
    at the price of ``v`` x more ICI hops per activation (cheap).

    Per-shard function (use inside shard_map). stage_params' leading axes
    are [pp, n_virtual, ...] (shard P(axis_name) on the first). xs:
    [n_micro, micro_batch, ...] replicated, with ``n_micro % pp == 0``
    (the schedule's group size — the standard Megatron constraint);
    returns the final global stage's outputs, replicated.

    Schedule formula: device s at slot t computes, with u = t - s,
    b = u // pp, chunk j = b % v, microbatch m = (b // v)*pp + u % pp.
    Every hop (s -> s+1 same-chunk, and pp-1 -> 0 advancing to chunk
    j+1) is consumed exactly one slot after production, so the carry is
    a single activation buffer. Fill/drain slots compute clamped garbage
    that is never collected (the masked-compute construction of
    :func:`pipeline_forward`, so autodiff through the scan stays exact).

    ``with_aux=True`` follows :func:`pipeline_forward`'s contract:
    stage_fn returns ``(y, aux)``; returns ``(ys, aux_sum)`` with
    aux_sum this device's aux over its valid slots — each of its v
    chunks applied to each microbatch exactly once (``v * n_micro``
    contributions; fill/drain slots masked out).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    v = n_virtual
    n_micro = xs.shape[0]
    if n_micro % n_stages != 0:
        raise ValueError(
            f"interleaved schedule needs n_micro ({n_micro}) % pp "
            f"({n_stages}) == 0")
    ticks = v * n_micro + n_stages - 1

    params = jax.tree.map(lambda p: p[0], stage_params)  # [v, per, ...]

    # One CIRCULAR permute per slot: s -> s+1 is the same-chunk hop and
    # pp-1 -> 0 is the wrap that advances to the next chunk.
    ring_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # Microbatch m's final output leaves device pp-1's chunk v-1 at slot
    # (v*(m//pp) + v - 1)*pp + m%pp + (pp-1); slot -> m lookup (-1 = not
    # a collection slot), so outputs accumulate into an [n_micro, ...]
    # buffer instead of stacking every tick (~v x less activation memory).
    slot_to_m = [-1] * ticks
    for m in range(n_micro):
        tau = ((v * (m // n_stages) + v - 1) * n_stages + m % n_stages
               + n_stages - 1)
        slot_to_m[tau] = m
    slot_to_m = jnp.asarray(slot_to_m)

    def tick(carry, t):
        buf, acc, aux_acc = carry
        u = jnp.maximum(t - stage, 0)
        b = u // n_stages
        j = b % v
        m = jnp.clip((b // v) * n_stages + u % n_stages, 0, n_micro - 1)
        fresh = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
        # Device 0 starts a chunk-0 slot from a fresh microbatch; every
        # other slot consumes last slot's routed activation.
        x = jnp.where(jnp.logical_and(stage == 0, j == 0), fresh, buf)
        pj = jax.tree.map(
            lambda q: lax.dynamic_index_in_dim(q, j, 0, keepdims=False),
            params)
        if with_aux:
            y, aux = stage_fn(pj, x)
            # Device s's valid slots are u = t - stage in [0, v*n_micro):
            # each (chunk, microbatch) pair exactly once.
            valid = jnp.logical_and(t >= stage, t - stage < v * n_micro)
            aux_acc = jax.tree.map(
                lambda a, bb: a + jnp.where(valid, bb, 0.0), aux_acc, aux)
        else:
            y = stage_fn(pj, x)
        mm = slot_to_m[t]
        upd = lax.dynamic_update_slice_in_dim(
            acc, y[None], jnp.clip(mm, 0, n_micro - 1), axis=0)
        acc = jnp.where(
            jnp.logical_and(mm >= 0, stage == n_stages - 1), upd, acc)
        nxt = lax.ppermute(y, axis_name, perm=ring_perm)
        return (nxt, acc, aux_acc), None

    init = lax.pcast(jnp.zeros(xs.shape[1:], xs.dtype), axis_name,
                     to="varying")
    acc0 = lax.pcast(jnp.zeros_like(xs), axis_name, to="varying")
    aux0 = None
    if with_aux:
        p0 = jax.tree.map(
            lambda q: lax.dynamic_index_in_dim(q, 0, 0, keepdims=False),
            params)
        probe = jax.eval_shape(stage_fn, p0, jax.ShapeDtypeStruct(
            xs.shape[1:], xs.dtype))[1]
        aux0 = jax.tree.map(
            lambda s: lax.pcast(jnp.zeros(s.shape, s.dtype), axis_name,
                                to="varying"), probe)
    (_, acc, aux_sum), _ = lax.scan(tick, (init, acc0, aux0),
                                    jnp.arange(ticks))
    out = lax.psum(acc, axis_name)
    return (out, aux_sum) if with_aux else out


def pipeline_loss(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    xs: jax.Array,
    targets: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Mean loss over microbatches through the pipeline (differentiable;
    jax.grad of this per-shard function yields the 1F1B-equivalent backward
    schedule as the scan's transpose)."""
    ys = pipeline_forward(stage_fn, stage_params, xs, axis_name)
    return loss_fn(ys, targets)


# -- 1F1B: the memory-bounded schedule --------------------------------------


class _Sched1F1B:
    """Static (interleaved) 1F1B timetable: numpy tables indexed
    [device, slot], built once at trace time by :func:`_sched_1f1b_tables`
    and verified by replay before use. All entries are -1 where no op /
    arrival happens.

    * ``f_m, f_j, f_cell``: microbatch, chunk, and input-buffer cell of
      the forward this device runs at each slot.
    * ``a_cell``: input-buffer cell into which this slot's incoming
      activation (the fwd ring message) is banked.
    * ``b_m, b_j, b_cell``: the backward op (its input cell = the
      forward's, re-read for the per-stage remat vjp).
    * ``d_arr, d_use``: dx-buffer cell into which this slot's incoming
      cotangent (the bwd ring message) is banked / from which this
      slot's backward seeds (-1 at the global last stage, which seeds
      from the loss in-slot).
    * ``K, D``: input/dx buffer depths (interval-colored; K is O(v*pp),
      independent of n_micro — the schedule's memory claim).
    * ``T``: total slots.
    """

    def __init__(self, P, V, T, K, D, f_m, f_j, f_cell, a_cell,
                 b_m, b_j, b_cell, d_arr, d_use):
        self.P, self.V, self.T, self.K, self.D = P, V, T, K, D
        self.f_m, self.f_j, self.f_cell = f_m, f_j, f_cell
        self.a_cell = a_cell
        self.b_m, self.b_j, self.b_cell = b_m, b_j, b_cell
        self.d_arr, self.d_use = d_arr, d_use


def _sched_1f1b_tables(P: int, M: int, V: int = 1) -> _Sched1F1B:
    """Builds the (interleaved) 1F1B timetable by greedy simulation.

    Device s owns chunks j = 0..V-1, chunk j being global stage
    ``j*P + s`` of a V*P-deep virtual pipeline (the Megatron-LM
    interleaved mapping). Each device executes its units in the
    standard 1F1B order — warmup forwards, then strict
    forward/backward alternation, then cooldown backwards — with the
    interleaved unit sequence: the k-th forward is (chunk (k//P) % V,
    microbatch (k//(P*V))*P + k%P) and the k-th backward mirrors it
    with chunks reversed. The timetable is then the unique greedy
    slot assignment: at every slot each device runs its next unit iff
    its data dependency has arrived (one-slot ICI hop per ring
    message), else idles.

    Bubble accounting: every device is busy 2*M*V slots; the schedule
    ends at T = 2*M*V + 2*(P-1) (asserted) — the same 2*(P-1)-slot
    fill/drain bubble as non-interleaved 1F1B, but an interleaved slot
    is ONE chunk (1/V of a folded stage), so the bubble fraction
    drops from 2(P-1)/(2M) of a step to 2(P-1)/(2MV): the Megatron
    divide-the-bubble-by-V result. The price is V x more ring hops
    per microbatch (cheap on ICI) and an input buffer that grows from
    O(P) to O(V*P).

    V = 1 reproduces the classic non-interleaved timetable exactly
    (asserted against the closed form below). V > 1 requires
    ``M % P == 0`` (the standard Megatron constraint).

    Every structural invariant — single op per device-slot, every unit
    scheduled exactly once, producer-before-consumer with the one-slot
    hop, buffer-cell exclusivity — is checked by a full symbolic
    REPLAY of the tables at build time, so a schedule bug fails
    loudly at trace time, never as silent gradient corruption.
    """
    import numpy as np

    VP = V * P
    if V > 1 and M % P != 0:
        raise ValueError(
            f"interleaved 1F1B needs n_micro ({M}) % pp ({P}) == 0")
    total = M * V

    def f_unit(k):   # k-th forward on a device -> (m, j)
        return (k // (P * V)) * P + k % P, (k // P) % V

    def b_unit(k):   # k-th backward: chunks in reverse order
        return (k // (P * V)) * P + k % P, V - 1 - (k // P) % V

    def device_order(s):
        if V == 1:
            warm = min(total, P - 1 - s)
        else:
            warm = min(total, (V - 1) * P + 2 * (P - 1 - s))
        seq = [("f",) + f_unit(k) for k in range(warm)]
        fi, bi = warm, 0
        while fi < total:
            seq.append(("f",) + f_unit(fi))
            seq.append(("b",) + b_unit(bi))
            fi, bi = fi + 1, bi + 1
        seq.extend(("b",) + b_unit(k) for k in range(bi, total))
        return seq

    orders = [device_order(s) for s in range(P)]
    ptr = [0] * P
    fs, bs = {}, {}          # (g, m) -> completion slot
    t = 0
    guard = 4 * (VP + total) + 16
    while any(ptr[s] < len(orders[s]) for s in range(P)):
        assert t < guard, f"1F1B schedule deadlock at P={P} M={M} V={V}"
        for s in range(P):
            if ptr[s] >= len(orders[s]):
                continue
            kind, m, j = orders[s][ptr[s]]
            g = j * P + s
            if kind == "f":
                ready = g == 0 or (g - 1, m) in fs and fs[(g - 1, m)] + 1 <= t
                if ready:
                    fs[(g, m)] = t
                    ptr[s] += 1
            else:
                if g == VP - 1:
                    ready = (g, m) in fs and fs[(g, m)] + 1 <= t
                else:
                    ready = (g + 1, m) in bs and bs[(g + 1, m)] + 1 <= t
                if ready:
                    bs[(g, m)] = t
                    ptr[s] += 1
        t += 1
    T = t

    # Busy/bubble accounting (documented above; the equality is load-
    # bearing for the bubble claim, so assert it).
    assert T == 2 * total + 2 * (P - 1), (P, M, V, T)

    if V == 1:
        # The greedy sim must reproduce the classic closed form —
        # _schedule_1f1b below is the executable spec (also what the
        # structural tests check), so the timetable exists ONCE.
        _, fwd_cf, bwd_cf, _, _ = _schedule_1f1b(P, M)
        for s in range(P):
            for m in range(M):
                assert fwd_cf[s][fs[(s, m)]] == m, (P, M, s, m)
                assert bwd_cf[s][bs[(s, m)]] == m, (P, M, s, m)

    # Interval-color buffer cells per device. Reuse rule: a cell read
    # (death) at slot t is free for a new banking at t+1 — the slot
    # body banks arrivals BEFORE the backward reads, so same-slot
    # reuse would overwrite a live value.
    def color(intervals):
        """intervals: {unit: (birth, death)} -> ({unit: cell}, depth)."""
        cells = {}
        free, used_until = [], {}
        depth = 0
        for u, (b, d) in sorted(intervals.items(), key=lambda kv: kv[1]):
            got = None
            for c in list(free):
                if used_until[c] < b:
                    got = c
                    free.remove(c)
                    break
            if got is None:
                got = depth
                depth += 1
            cells[u] = got
            used_until[got] = d
            free.append(got)
        return cells, depth

    tabs = {name: np.full((P, T), -1, np.int32)
            for name in ("f_m", "f_j", "f_cell", "a_cell",
                         "b_m", "b_j", "b_cell", "d_arr", "d_use")}
    K = D = 1
    for s in range(P):
        ivals, divals = {}, {}
        for j in range(V):
            g = j * P + s
            for m in range(M):
                birth = fs[(g, m)] if g == 0 else fs[(g - 1, m)] + 1
                ivals[(j, m)] = (birth, bs[(g, m)])
                if g < VP - 1:
                    divals[(j, m)] = (bs[(g + 1, m)] + 1, bs[(g, m)])
        cells, k = color(ivals)
        dcells, d = color(divals)
        K, D = max(K, k), max(D, d)
        for j in range(V):
            g = j * P + s
            for m in range(M):
                tf, tb = fs[(g, m)], bs[(g, m)]
                assert tabs["f_m"][s, tf] == -1 and \
                    tabs["b_m"][s, tf] == -1, (s, tf)
                assert tabs["f_m"][s, tb] == -1 and \
                    tabs["b_m"][s, tb] == -1, (s, tb)
                tabs["f_m"][s, tf] = m
                tabs["f_j"][s, tf] = j
                tabs["f_cell"][s, tf] = cells[(j, m)]
                tabs["b_m"][s, tb] = m
                tabs["b_j"][s, tb] = j
                tabs["b_cell"][s, tb] = cells[(j, m)]
                if g > 0:
                    tabs["a_cell"][s, fs[(g - 1, m)] + 1] = cells[(j, m)]
                if g < VP - 1:
                    tabs["d_arr"][s, bs[(g + 1, m)] + 1] = dcells[(j, m)]
                    tabs["d_use"][s, tb] = dcells[(j, m)]

    sched = _Sched1F1B(P, V, T, K, D, **tabs)
    _replay_check(sched, M)
    return sched


def _replay_check(sc: _Sched1F1B, M: int):
    """Symbolic replay of the tables against the exact slot-body
    semantics of the engine (bank arrivals, fwd, bwd, ring permutes):
    verifies every forward consumes the right microbatch/chunk input,
    every backward re-reads the same cell and seeds from the right
    cotangent, and no live buffer cell is ever overwritten."""
    P, V, T = sc.P, sc.V, sc.T
    VP = V * P
    ib = [dict() for _ in range(P)]       # device -> cell -> tag
    db = [dict() for _ in range(P)]
    fmsg = [None] * P                     # in flight toward device s
    bmsg = [None] * P
    done_f, done_b = set(), set()
    for t in range(T):
        sent_f, sent_b = [None] * P, [None] * P
        for s in range(P):
            ac = sc.a_cell[s, t]
            if ac >= 0:
                assert fmsg[s] is not None, (s, t)
                ib[s][ac] = fmsg[s]
            dc = sc.d_arr[s, t]
            if dc >= 0:
                assert bmsg[s] is not None, (s, t)
                db[s][dc] = bmsg[s]
            mf = sc.f_m[s, t]
            if mf >= 0:
                j = sc.f_j[s, t]
                g = j * P + s
                if g == 0:
                    ib[s][sc.f_cell[s, t]] = ("act", 0, mf)
                tag = ib[s].get(sc.f_cell[s, t])
                assert tag == ("act", g, mf), (s, t, tag, g, mf)
                sent_f[s] = ("act", g + 1, mf)   # consumed by stage g+1
                done_f.add((g, mf))
            mb = sc.b_m[s, t]
            if mb >= 0:
                j = sc.b_j[s, t]
                g = j * P + s
                tag = ib[s].get(sc.b_cell[s, t])
                assert tag == ("act", g, mb), (s, t, tag, g, mb)
                if g == VP - 1:
                    assert (g, mb) in done_f, (s, t)
                else:
                    dtag = db[s].get(sc.d_use[s, t])
                    assert dtag == ("cot", g, mb), (s, t, dtag, g, mb)
                done_b.add((g, mb))
                sent_b[s] = ("cot", g - 1, mb)
        # Ring hops: fwd s -> s+1 (wrap advances the chunk), bwd reverse.
        fmsg = [sent_f[(s - 1) % P] for s in range(P)]
        bmsg = [sent_b[(s + 1) % P] for s in range(P)]
        # Re-tag wrap messages for the chunk advance: stage g's output
        # keeps its global-stage destination, nothing to change — tags
        # already carry g+1 / g-1.
    assert done_f == {(g, m) for g in range(VP) for m in range(M)}
    assert done_b == done_f


def _schedule_1f1b(P: int, M: int):
    """Static non-interleaved 1F1B timetable in closed form — the
    EXECUTABLE SPEC: :func:`_sched_1f1b_tables` (the builder the engine
    actually runs) asserts its V=1 greedy simulation reproduces these
    slots exactly, and the structural tests check invariants here, so
    the classic timetable is written down once.

    Slot grid: each slot holds at most ONE op per stage (a forward or a
    backward of one microbatch). Stage s runs its warmup forwards at
    slots ``s + m`` (m < P - s), steady-state forwards at ``2m + s``,
    and backwards at ``2P - 1 - s + 2m`` — the classic Megatron-LM
    non-interleaved 1F1B: after warmup each backward's freed activation
    is immediately refilled by one forward, so at most ``P - s``
    microbatches are ever in flight at stage s (O(pp), independent of
    n_micro — GPipe's O(n_micro) is the round-3 verdict item this
    closes).

    Returns ``(T, fwd, bwd, arr, K)``: total slots; [P, T] int arrays
    with the microbatch forwarded/backwarded by stage s at slot t (-1 =
    idle); arrivals ``arr[s][t]`` = microbatch whose activation reaches
    stage s at slot t (sent by s-1 one slot earlier; -1 = none); and K,
    the input-buffer depth = max microbatch activations simultaneously
    alive (arrival..backward) at any stage. Every constraint (one op
    per slot, producer-before-consumer, tight cotangent chain, in-flight
    bound) is asserted here, so a schedule bug fails loudly at build
    time, not as silent garbage."""
    f_slot = {}
    b_slot = {}
    for s in range(P):
        for m in range(M):
            f_slot[(s, m)] = s + m if m <= P - 1 - s else 2 * m + s
            b_slot[(s, m)] = 2 * P - 1 - s + 2 * m
    T = max(b_slot.values()) + 1

    import numpy as np
    fwd = np.full((P, T), -1, np.int32)
    bwd = np.full((P, T), -1, np.int32)
    arr = np.full((P, T), -1, np.int32)
    for (s, m), t in f_slot.items():
        assert fwd[s, t] == -1 and bwd[s, t] == -1, (s, t)
        fwd[s, t] = m
    for (s, m), t in b_slot.items():
        assert fwd[s, t] == -1 and bwd[s, t] == -1, (s, t)
        bwd[s, t] = m
    for s in range(1, P):
        for m in range(M):
            t_arr = f_slot[(s - 1, m)] + 1
            assert t_arr <= f_slot[(s, m)], (s, m)   # arrives before use
            arr[s, t_arr] = m
    for s in range(P - 1):
        for m in range(M):
            # dx from stage s+1 lands exactly on stage s's backward slot.
            assert b_slot[(s + 1, m)] + 1 == b_slot[(s, m)], (s, m)
    for m in range(M):
        assert b_slot[(P - 1, m)] == f_slot[(P - 1, m)] + 1, m

    K = 0
    for s in range(P):
        births = {m: (f_slot[(s - 1, m)] + 1 if s else f_slot[(s, m)])
                  for m in range(M)}
        for t in range(T):
            live = sum(1 for m in range(M)
                       if births[m] <= t <= b_slot[(s, m)])
            K = max(K, live)
    return T, fwd, bwd, arr, K


def _pipeline_1f1b_engine(
    stage_fn: Callable,
    chunk_params,
    xs: jax.Array,
    axis_name: str,
    n_virtual: int,
    *,
    loss_side: Callable,
    zero_head,
    embed_side: Callable | None = None,
    aux_seed=None,
    aux_gate=None,
    lockstep: bool = False,
):
    """THE 1F1B slot engine — the single place the timetable, ring
    buffers, and lockstep exchanges live (round-4 verdict item #5: the
    generic pipeline API and the flagship train step previously each
    carried a copy). Per-shard function; call inside shard_map.

    * ``chunk_params``: this device's chunks, leading axis
      ``n_virtual`` (lift v=1 params with ``[None]``).
    * ``xs`` [n_micro, micro_batch, ...]: global-stage-0 inputs.
    * ``loss_side(y, m) -> (lval, head_grads, dy)``: evaluated (under
      ``lax.cond``) at the global LAST stage's backward — returns the
      per-microbatch loss value, gradients for any head/tail params it
      closed over (``zero_head``-shaped; pass ``{}`` if none), and the
      cotangent seeding the backward. Must be collective-free.
    * ``embed_side(dx, m) -> head_grads``: optional, evaluated (under
      ``lax.cond``) at the global FIRST stage's backward with the
      input cotangent — the embedding's gradient path. Collective-free.
    * ``aux_seed`` / ``aux_gate``: when ``stage_fn`` returns
      ``(y, aux)``, the cotangent seed for aux in each backward and a
      boolean gating which ranks accumulate the aux VALUES (exclusive
      cotangent-path rule; see train.py).
    * ``lockstep=False``: forward/backward run under per-device
      ``lax.cond`` — stage_fn must then be collective-free. ``True``:
      every rank computes every slot body and masks the accumulations,
      so stage_fn MAY contain collectives (tp psums) — they execute in
      lockstep across ranks (~2x op count; the win is memory).

    Returns raw accumulators ``(lacc, aux_acc, chunk_grads,
    head_grads)`` — callers own normalization and cross-axis reduction.

    Memory contract: autodiff never crosses the slot scan. Each
    backward is an explicit ``jax.vjp`` re-running the chunk forward
    from its STORED INPUT (per-stage remat), so peak residency is the
    K-deep input buffer, K = O(n_virtual * pp) and flat in n_micro
    (interval-colored by :func:`_sched_1f1b_tables`, which also replay-
    verifies the timetable at build time)."""
    P = int(lax.axis_size(axis_name))
    stage = lax.axis_index(axis_name)
    V = n_virtual
    M = xs.shape[0]
    sc = _sched_1f1b_tables(P, M, V)
    tb = {k: jnp.asarray(getattr(sc, k))
          for k in ("f_m", "f_j", "f_cell", "a_cell",
                    "b_m", "b_j", "b_cell", "d_arr", "d_use")}
    K, D, T = sc.K, sc.D, sc.T
    has_aux = aux_seed is not None
    last = P - 1

    # Ring permutes BOTH directions. The wrap hop exists only to
    # advance the chunk (device P-1's chunk-j output feeds device 0's
    # chunk j+1, and device 0's cotangent feeds device P-1's chunk
    # j-1) — at V=1 nothing is ever banked off it, so OMIT the wrap
    # pair entirely rather than ship a dead microbatch-sized ICI
    # transfer per direction every slot.
    if V > 1:
        fwd_perm = [(i, (i + 1) % P) for i in range(P)]
        bwd_perm = [(i, (i - 1) % P) for i in range(P)]
    else:
        fwd_perm = [(i, i + 1) for i in range(P - 1)]
        bwd_perm = [(i, i - 1) for i in range(1, P)]

    mb_shape = xs.shape[1:]
    zero_act = jnp.zeros(mb_shape, xs.dtype)

    def chunk_p(j):
        return jax.tree.map(
            lambda q: lax.dynamic_index_in_dim(q, j, 0, keepdims=False),
            chunk_params)

    def bank(buf, msg, cell):
        return lax.dynamic_update_index_in_dim(buf, msg, cell, 0)

    def slot(carry, t):
        ib, dxb, fmsg, bmsg, gl, gh, lacc, aux_acc = carry

        # 1) Bank arrivals (messages sent by the neighbors last slot).
        ac = tb["a_cell"][stage, t]
        ib = jnp.where(ac >= 0, bank(ib, fmsg, jnp.maximum(ac, 0)), ib)
        dc = tb["d_arr"][stage, t]
        dxb = jnp.where(dc >= 0, bank(dxb, bmsg, jnp.maximum(dc, 0)),
                        dxb)

        # 2) Forward.
        mf = tb["f_m"][stage, t]
        jf = jnp.maximum(tb["f_j"][stage, t], 0)
        cf = jnp.maximum(tb["f_cell"][stage, t], 0)
        is_g0 = jnp.logical_and(stage == 0, tb["f_j"][stage, t] == 0)

        def fwd_body(ib):
            mfc = jnp.maximum(mf, 0)
            fresh = lax.dynamic_index_in_dim(xs, mfc, 0, keepdims=False)
            x = jnp.where(is_g0, fresh,
                          lax.dynamic_index_in_dim(ib, cf, 0,
                                                   keepdims=False))
            # Bank the input (global stage 0 has no arrival; everyone
            # re-banks the same value) — backward recomputes from the
            # buffer uniformly.
            ib = bank(ib, x, cf)
            out = stage_fn(chunk_p(jf), x)
            y = out[0] if has_aux else out
            return ib, y

        if lockstep:
            ib2, y_f = fwd_body(ib)
            ib = jnp.where(mf >= 0, ib2, ib)
            y_f = jnp.where(mf >= 0, y_f, zero_act)
        else:
            ib, y_f = lax.cond(mf >= 0, fwd_body,
                               lambda ib: (ib, zero_act), ib)

        # 3) Backward: recompute from the banked input (remat); seed
        # from the loss (global last stage) or the banked dx.
        mb_ = tb["b_m"][stage, t]
        jb = jnp.maximum(tb["b_j"][stage, t], 0)
        cb = jnp.maximum(tb["b_cell"][stage, t], 0)
        du = jnp.maximum(tb["d_use"][stage, t], 0)
        is_last = jnp.logical_and(stage == last,
                                  tb["b_j"][stage, t] == V - 1)
        is_first = jnp.logical_and(stage == 0,
                                   tb["b_j"][stage, t] == 0)

        def bwd_body(operand):
            ib, dxb, gl, gh, lacc, aux_acc = operand
            mbc = jnp.maximum(mb_, 0)
            x = lax.dynamic_index_in_dim(ib, cb, 0, keepdims=False)
            pj = chunk_p(jb)
            out_b, vjp_fn = jax.vjp(stage_fn, pj, x)
            y_b = out_b[0] if has_aux else out_b

            lval, d_head, dy_loss = lax.cond(
                is_last, lambda y: loss_side(y, mbc),
                lambda y: (jnp.zeros((), jnp.float32),
                           jax.tree.map(jnp.zeros_like, zero_head),
                           jnp.zeros_like(y)), y_b)
            dy = jnp.where(
                is_last, dy_loss,
                lax.dynamic_index_in_dim(dxb, du, 0,
                                         keepdims=False).astype(y_b.dtype))
            seed = (dy, aux_seed) if has_aux else dy
            d_chunk, dx = vjp_fn(seed)

            bmask = mb_ >= 0
            # Scatter-add this chunk's grads at jb.
            gl = jax.tree.map(
                lambda a, d: lax.dynamic_update_index_in_dim(
                    a,
                    lax.dynamic_index_in_dim(a, jb, 0, keepdims=False)
                    + jnp.where(bmask, d, 0), jb, 0),
                gl, d_chunk)
            lastmask = jnp.logical_and(bmask, is_last)
            gh = jax.tree.map(
                lambda a, d: a + jnp.where(lastmask, d, 0), gh, d_head)
            lacc = lacc + jnp.where(lastmask, lval, 0.0)
            if embed_side is not None:
                d_emb = lax.cond(
                    is_first, lambda dxx: embed_side(dxx, mbc),
                    lambda dxx: jax.tree.map(jnp.zeros_like, zero_head),
                    dx)
                emask = jnp.logical_and(bmask, is_first)
                gh = jax.tree.map(
                    lambda a, d: a + jnp.where(emask, d, 0), gh, d_emb)
            if has_aux:
                amask = jnp.logical_and(bmask, aux_gate)
                aux_acc = jax.tree.map(
                    lambda a, v: a + jnp.where(amask, v, 0.0),
                    aux_acc, out_b[1])
            return (ib, dxb, gl, gh, lacc, aux_acc), dx

        if lockstep:
            (_, _, gl, gh, lacc, aux_acc), dx_out = bwd_body(
                (ib, dxb, gl, gh, lacc, aux_acc))
            dx_out = jnp.where(mb_ >= 0, dx_out, zero_act)
        else:
            (ib, dxb, gl, gh, lacc, aux_acc), dx_out = lax.cond(
                mb_ >= 0, bwd_body,
                lambda op: (op, zero_act),
                (ib, dxb, gl, gh, lacc, aux_acc))

        # 4) Lockstep exchanges: activations ride the ring rightward,
        # cotangents leftward.
        fmsg = lax.ppermute(y_f, axis_name, perm=fwd_perm)
        bmsg = lax.ppermute(dx_out, axis_name, perm=bwd_perm)
        return (ib, dxb, fmsg, bmsg, gl, gh, lacc, aux_acc), None

    varying = lambda a: lax.pcast(a, axis_name, to="varying")  # noqa: E731
    if has_aux:
        p0 = chunk_p(0)
        probe = jax.eval_shape(stage_fn, p0, jax.ShapeDtypeStruct(
            mb_shape, xs.dtype))[1]
        aux0 = jax.tree.map(
            lambda s: varying(jnp.zeros(s.shape, s.dtype)), probe)
    else:
        aux0 = None
    init = (
        varying(jnp.zeros((K,) + mb_shape, xs.dtype)),
        varying(jnp.zeros((D,) + mb_shape, xs.dtype)),
        varying(zero_act), varying(zero_act),
        jax.tree.map(lambda p: varying(jnp.zeros_like(p)), chunk_params),
        jax.tree.map(lambda p: varying(jnp.zeros_like(p)), zero_head),
        varying(jnp.zeros((), jnp.float32)),
        aux0,
    )
    (ib, dxb, fmsg, bmsg, gl, gh, lacc, aux_acc), _ = lax.scan(
        slot, init, jnp.arange(T))
    return lacc, aux_acc, gl, gh


def pipeline_1f1b_loss_and_grads(
    stage_fn: Callable,
    per_micro_loss: Callable,
    stage_params,
    xs: jax.Array,
    targets,
    axis_name: str,
    n_virtual: int = 1,
):
    """Pipeline loss AND gradients under the 1F1B schedule (per-shard
    function; call inside shard_map exactly like :func:`pipeline_forward`
    — stage_params sharded P(axis_name), xs/targets
    [n_micro, micro_batch, ...] replicated).

    Returns ``(loss, stage_grads)``: the mean of
    ``per_micro_loss(y_m, targets[m])`` over microbatches (replicated),
    and THIS stage's parameter gradients with the leading stage axis
    restored (same pytree structure as stage_params), exactly equal to
    ``jax.grad`` of :func:`pipeline_loss` up to fp summation order
    (asserted by tests/test_pipeline_1f1b.py).

    ``n_virtual > 1`` selects the INTERLEAVED 1F1B schedule (Megatron):
    stage_params' leading axes become [pp, n_virtual, ...] (chunk j on
    device s is global stage j*pp + s), ``n_micro % pp == 0`` is
    required, and the fill/drain bubble drops from 2(pp-1) folded-stage
    slots to 2(pp-1) chunk slots — a factor-of-v reduction — at the
    price of an O(v*pp) input buffer and v x more ring hops. Gradient
    parity with the GPipe interleaved forward is asserted in tests.

    Memory contract — the point of the schedule: autodiff is never
    applied across the slot scan (see :func:`_pipeline_1f1b_engine`);
    peak activation residency is the interval-colored input buffer,
    O(n_virtual * pp), not GPipe's O(n_micro) scan residuals. Verified
    against XLA's compiled memory analysis in the tests.

    Caveats: ``stage_fn`` must be collective-free (forward and backward
    run under per-device ``lax.cond`` — stages genuinely take different
    branches each slot, so a collective inside would desynchronize; the
    flagship train step uses the engine's ``lockstep`` mode instead —
    see train.py); ``per_micro_loss(y, tgt) -> scalar`` is evaluated on
    the LAST global stage's outputs only. Embedding / head parameters
    outside stage_params are the caller's to handle."""
    n_micro = xs.shape[0]

    params = jax.tree.map(lambda p: p[0], stage_params)  # drop stage axis
    if n_virtual == 1:
        params = jax.tree.map(lambda p: p[None], params)  # lift chunk axis

    def loss_side(y, m):
        tgt = jax.tree.map(
            lambda tg: lax.dynamic_index_in_dim(tg, m, 0, keepdims=False),
            targets)
        lval, loss_vjp = jax.vjp(lambda yy: per_micro_loss(yy, tgt), y)
        (dy,) = loss_vjp(jnp.ones((), lval.dtype))
        return lval.astype(jnp.float32), {}, dy.astype(y.dtype)

    lacc, _, gl, _ = _pipeline_1f1b_engine(
        stage_fn, params, xs, axis_name, n_virtual,
        loss_side=loss_side, zero_head={})

    loss = lax.psum(lacc, axis_name) / n_micro
    # Loss is mean-over-micro: scale the summed per-micro cotangents.
    if n_virtual == 1:
        gl = jax.tree.map(lambda g: g[0], gl)     # drop chunk axis
    grads = jax.tree.map(lambda g: (g / n_micro)[None], gl)
    return loss, grads


def run_pipeline(mesh, stage_fn, stacked_params, xs, axis_name: str = "pp"):
    """Array-level convenience: stacked_params' leading axis = stage."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    f = shard_map(
        functools.partial(pipeline_forward, stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    return f(stacked_params, xs)
