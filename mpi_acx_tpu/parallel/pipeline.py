"""Pipeline parallelism: microbatch exchange over a 'pp' mesh axis.

The reference positions partitioned P2P as the substrate for
pipeline-parallel microbatch exchange (SURVEY.md §2 "Parallelism
strategies"; BASELINE.json configs[3,4]). This module is that application,
TPU-native: a GPipe-style schedule where each pipeline stage is one slice
of the mesh's 'pp' axis, activations travel stage->stage+1 by
collective-permute on ICI, and the whole schedule is a single
``lax.scan`` inside ``shard_map`` — one compiled program, no host in the
loop. Autodiff through the scan gives the backward pipeline (reverse
permutes) for free.

Schedule: T = n_micro + n_stages - 1 ticks; stage s computes microbatch m
at tick t = s + m (the classic GPipe timetable; bubbles are masked
compute).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_forward(
    stage_fn: Callable,
    stage_params,
    xs: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Runs xs ([n_micro, micro_batch, ...], replicated) through the
    pipeline; returns the last stage's outputs [n_micro, micro_batch, ...]
    (replicated via psum).

    Per-shard function: call inside shard_map with `stage_params` sharded
    P(axis_name) on a stacked leading stage axis (shard_map hands each
    device its own stage's slice, leading dim 1 — squeezed here).

    stage_fn(params, x) -> y with y.shape == x.shape (inter-stage
    activations must be shape-stable so the wire format is fixed).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n_stages - 1

    params = jax.tree.map(lambda p: p[0], stage_params)  # drop stage axis

    # stage s -> s+1 (no wraparound: stage 0 receives zeros = bubble).
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        from_left = carry
        m = jnp.clip(t, 0, n_micro - 1)
        first_in = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
        x = jnp.where(stage == 0, first_in, from_left)
        y = stage_fn(params, x)
        send = lax.ppermute(y, axis_name, perm=fwd_perm)
        return send, y

    # Carry is device-varying (each stage holds a different activation).
    init = lax.pcast(jnp.zeros_like(xs[0]), axis_name, to="varying")
    _, ys = lax.scan(tick, init, jnp.arange(ticks))

    # The last stage's valid outputs live at ticks [n_stages-1, ticks).
    tail = lax.dynamic_slice_in_dim(ys, n_stages - 1, n_micro, 0)
    contrib = jnp.where(stage == n_stages - 1, tail, jnp.zeros_like(tail))
    return lax.psum(contrib, axis_name)


def pipeline_loss(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    xs: jax.Array,
    targets: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Mean loss over microbatches through the pipeline (differentiable;
    jax.grad of this per-shard function yields the 1F1B-equivalent backward
    schedule as the scan's transpose)."""
    ys = pipeline_forward(stage_fn, stage_params, xs, axis_name)
    return loss_fn(ys, targets)


def run_pipeline(mesh, stage_fn, stacked_params, xs, axis_name: str = "pp"):
    """Array-level convenience: stacked_params' leading axis = stage."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    f = shard_map(
        functools.partial(pipeline_forward, stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    return f(stacked_params, xs)
