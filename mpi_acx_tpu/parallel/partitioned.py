"""Partitioned (per-partition-ready) exchange on the ICI plane.

The reference's partitioned communication (MPI_Psend_init + device-side
MPIX_Pready/Parrived, reference partitioned.cu:36-231) exists to overlap a
kernel's *production* of message fragments with their *transmission*. On
TPU, XLA programs are static, so "the kernel marks partition p ready" is
expressed structurally instead of dynamically: a ``lax.scan`` whose steps
interleave (a) computing/consuming one partition with (b) transmitting
another via collective-permute. XLA overlaps the ppermute of step k with
the compute of step k (async collective start/done), giving the same
pipelining the reference gets from its proxy thread — without a proxy.

Two shapes are provided:

* :func:`partitioned_ring_exchange` — fixed-size partitioned neighbor
  exchange with a per-partition consumer, the analogue of
  ring-partitioned.cu's mark_ready/wait_until_arrived pair.
* :func:`partitioned_pipeline` — produce-send-consume: a producer makes
  partition k while partition k-1 is in flight, the exact overlap pattern
  pipeline-parallel microbatch exchange needs (BASELINE.json configs[3,4]).

Host-plane partitioned channels (real out-of-order Pready across process
boundaries) live in the native runtime: mpi_acx_tpu.runtime.psend_init.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from mpi_acx_tpu.parallel.collective import _ring_perm


def partitioned_ring_exchange(
    x: jax.Array,
    axis_name: str,
    partitions: int,
    consume: Callable[[jax.Array], jax.Array] | None = None,
) -> jax.Array:
    """Send local shard `x` one ring step in `partitions` chunks, applying
    `consume` to each arriving chunk as it lands.

    Per scan step, chunk k is on the wire while chunk k-1 is being
    consumed — compute/comm overlap per partition, the property the
    reference's per-partition flags exist to provide.

    `x`'s leading dim must divide into `partitions`. Returns the received
    shard with `consume` applied chunkwise (identity if None).
    """
    n = lax.axis_size(axis_name)
    chunks = x.reshape((partitions, -1) + x.shape[1:])

    def step(_, chunk):
        arrived = lax.ppermute(chunk, axis_name, perm=_ring_perm(n, 1))
        out = arrived if consume is None else consume(arrived)
        return None, out

    _, received = lax.scan(step, None, chunks)
    return received.reshape((-1,) + x.shape[1:])


def partitioned_pipeline(
    produce: Callable[[jax.Array | int], jax.Array],
    consume: Callable[[jax.Array, jax.Array], jax.Array],
    init_acc: jax.Array,
    partitions: int,
    axis_name: str,
) -> jax.Array:
    """Produce partition k, transmit it right, consume on arrival — with
    production of k+1 overlapping transmission of k (software-pipelined by
    one step, matching "Pready fires as soon as a partition is produced",
    reference README.md:105-115).

    produce(k) -> partition payload (same shape each k)
    consume(acc, payload) -> acc
    Returns the final accumulator of arrivals from the left neighbor.
    """
    n = lax.axis_size(axis_name)

    def step(acc, k):
        payload = produce(k)
        arrived = lax.ppermute(payload, axis_name, perm=_ring_perm(n, 1))
        return consume(acc, arrived), None

    # The accumulator becomes device-varying after the first arrival; mark
    # the initial value varying so the scan carry type is stable.
    init_acc = lax.pcast(init_acc, axis_name, to="varying")
    acc, _ = lax.scan(step, init_acc, jnp.arange(partitions))
    return acc
