"""Per-layer KV shipping over host-plane partitioned channels.

The disaggregated-serving handoff (models/disagg.py): a prefill rank
maps ONE request's quantized KV cache — [L, prompt_bucket, H, D] int8
codes plus their f32 scales — onto ONE partitioned send with L
partitions, one per transformer layer. The prefill publishes partition
l with MPIX_Pready the moment layer l's K/V leave the device, while
layers l+1..L-1 are still computing — the reference's
produce-partition/Pready overlap (partitioned.cu:36-231) applied to
the serving plane's prompt-cache transfer instead of a kernel's
fragment stream. The decode rank polls MPIX_Parrived per layer and
splices arrivals into its slot cache without waiting for the tail of
the prompt pass.

Wire form (the EQuARX rule, PAPERS.md): quantized codes + scales are
the ONLY form KV ever takes on the wire — a bf16-cached prefill
quantizes before packing, never after. Per layer the partition packs
``[k codes | v codes | k scales | v scales]`` contiguously; codes are
int8 [bucket, H, D], scales f32 [bucket, H, 1] (ops/kvquant.py's
per-(position, head) layout), so every partition has identical size
and the partitioned channel's equal-partition contract holds for any
layer count.

Channels are persistent (MPIX_Psend_init once per (peer, bucket
geometry), restarted per request with MPIX_Start) — the compile-once
discipline of models/serving.py applied to the wire: the handoff of
request N+1 reuses request N's channel, staging buffer, and flag
slots. docs/MIGRATION.md records the layer-partition layout as a
contract: partition index == layer index, in-partition packing as
above.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

# Host-plane tag space for the disagg handoff. KV rounds take one tag
# per prompt bucket (log2-indexed: channels for different buckets are
# distinct persistent requests and must not share a (peer, tag)
# message stream); descriptor tags live beside them.
KV_TAG_BASE = 7100
DESC_HDR_TAG = 7001
DESC_FIN_TAG = 7002


def kv_tag(bucket: int) -> int:
    """Per-bucket wire tag of the KV partitioned channel."""
    assert bucket > 0 and (bucket & (bucket - 1)) == 0, bucket
    return KV_TAG_BASE + bucket.bit_length()


def layer_part_bytes(bucket: int, heads: int, head_dim: int) -> int:
    """Bytes of one layer partition: k+v int8 codes plus k+v f32
    per-(position, head) scales."""
    codes = bucket * heads * head_dim      # int8, 1 byte each
    scales = bucket * heads * 4            # f32 [bucket, H, 1]
    return 2 * codes + 2 * scales


def pack_layer(row: np.ndarray, kq, ks, vq, vs) -> None:
    """Pack one layer's quantized K/V into staging row ``row`` (uint8,
    layer_part_bytes long). Enforces the wire rule: codes must already
    be int8 and scales f32 — a bf16 tensor here is a bug upstream, not
    something to quantize quietly at the wire."""
    kq = np.ascontiguousarray(kq)
    vq = np.ascontiguousarray(vq)
    ks = np.ascontiguousarray(ks)
    vs = np.ascontiguousarray(vs)
    assert kq.dtype == np.int8 and vq.dtype == np.int8, \
        (kq.dtype, vq.dtype, "wire form is int8 codes (EQuARX rule)")
    assert ks.dtype == np.float32 and vs.dtype == np.float32, \
        (ks.dtype, vs.dtype, "wire form is f32 scales (EQuARX rule)")
    o = 0
    for arr in (kq, vq, ks, vs):
        b = arr.reshape(-1).view(np.uint8)
        row[o:o + b.size] = b
        o += b.size
    assert o == row.size, (o, row.size)


def unpack_layer(row: np.ndarray, bucket: int, heads: int,
                 head_dim: int) -> Tuple[np.ndarray, np.ndarray,
                                         np.ndarray, np.ndarray]:
    """Inverse of :func:`pack_layer`: staging row -> (kq, ks, vq, vs)
    with the shapes scatter_fn's per-slot splice expects (B=1 axis
    added by the caller when assembling the [L, 1, bucket, ...] cache).
    Returns copies — the staging row is reused by the next round."""
    nc = bucket * heads * head_dim
    ns = bucket * heads * 4
    o = 0
    kq = row[o:o + nc].view(np.int8).reshape(bucket, heads,
                                             head_dim).copy()
    o += nc
    vq = row[o:o + nc].view(np.int8).reshape(bucket, heads,
                                             head_dim).copy()
    o += nc
    ks = row[o:o + ns].view(np.float32).reshape(bucket, heads, 1).copy()
    o += ns
    vs = row[o:o + ns].view(np.float32).reshape(bucket, heads, 1).copy()
    return kq, ks, vq, vs


@dataclass(frozen=True)
class ChannelGeom:
    """One persistent channel's shape key: everything that fixes the
    partition size and count."""

    peer: int
    bucket: int
    n_layers: int
    heads: int
    head_dim: int

    @property
    def part_bytes(self) -> int:
        return layer_part_bytes(self.bucket, self.heads, self.head_dim)


class _SendChannel:
    """One persistent L-partition send channel to one decode peer."""

    def __init__(self, rt, geom: ChannelGeom):
        self.rt = rt
        self.geom = geom
        self.staging = np.zeros((geom.n_layers, geom.part_bytes),
                                np.uint8)
        self.req = rt.psend_init(self.staging.reshape(-1),
                                 geom.n_layers, dest=geom.peer,
                                 tag=kv_tag(geom.bucket))
        self.open_round = False
        self.published = 0

    def begin(self) -> None:
        assert not self.open_round, "previous handoff round still open"
        self.rt.start(self.req)
        self.open_round = True
        self.published = 0

    def publish(self, layer: int, kq, ks, vq, vs) -> None:
        """Stage layer ``layer``'s quantized K/V and Pready its
        partition — called the moment the layer's prefill compute is
        done, while later layers still run."""
        pack_layer(self.staging[layer], kq, ks, vq, vs)
        self.rt.pready(layer, self.req)
        self.published += 1

    def abort_fill(self) -> None:
        """Publish every not-yet-published partition with whatever the
        staging rows hold (stale bytes — the receiver discards the
        handoff). Completing the round is what keeps the persistent
        channel restartable after a mid-handoff failure: a round with
        unpublished partitions would wedge both ends' FinishRound."""
        for layer in range(self.published, self.geom.n_layers):
            self.rt.pready(layer, self.req)
        self.published = self.geom.n_layers

    def finish(self):
        st = None
        try:
            st = self.rt.wait_partitioned(self.req)
        finally:
            self.open_round = False
        return st

    def close(self) -> None:
        self.rt.request_free(self.req)


class _RecvChannel:
    """One persistent L-partition recv channel from the prefill peer."""

    def __init__(self, rt, geom: ChannelGeom):
        self.rt = rt
        self.geom = geom
        self.staging = np.zeros((geom.n_layers, geom.part_bytes),
                                np.uint8)
        self.req = rt.precv_init(self.staging.reshape(-1),
                                 geom.n_layers, source=geom.peer,
                                 tag=kv_tag(geom.bucket))
        self.open_round = False

    def begin(self) -> None:
        assert not self.open_round, "previous handoff round still open"
        self.rt.start(self.req)
        self.open_round = True

    def poll(self, layer: int) -> bool:
        """MPIX_Parrived on partition ``layer``; an error-completed
        partition (peer died mid-ship) also reads arrived — the error
        surfaces in :meth:`finish`, where the caller's requeue path
        picks it up."""
        return self.rt.parrived(self.req, layer)

    def take(self, layer: int):
        """Unpack an arrived layer into (kq, ks, vq, vs) host arrays."""
        g = self.geom
        return unpack_layer(self.staging[layer], g.bucket, g.heads,
                            g.head_dim)

    def finish(self):
        st = None
        try:
            st = self.rt.wait_partitioned(self.req)
        finally:
            self.open_round = False
        return st

    def close(self) -> None:
        self.rt.request_free(self.req)


class KvShipper:
    """Prefill side: persistent per-(peer, bucket) send channels."""

    def __init__(self, rt, n_layers: int, heads: int, head_dim: int):
        self.rt = rt
        self.n_layers = n_layers
        self.heads = heads
        self.head_dim = head_dim
        self._chans: Dict[Tuple[int, int], _SendChannel] = {}

    def channel(self, peer: int, bucket: int) -> _SendChannel:
        key = (peer, bucket)
        if key not in self._chans:
            self._chans[key] = _SendChannel(
                self.rt, ChannelGeom(peer, bucket, self.n_layers,
                                     self.heads, self.head_dim))
        return self._chans[key]

    def close(self) -> None:
        for ch in self._chans.values():
            ch.close()
        self._chans.clear()


class KvReceiver:
    """Decode side: persistent per-(peer, bucket) recv channels."""

    def __init__(self, rt, n_layers: int, heads: int, head_dim: int):
        self.rt = rt
        self.n_layers = n_layers
        self.heads = heads
        self.head_dim = head_dim
        self._chans: Dict[Tuple[int, int], _RecvChannel] = {}

    def channel(self, peer: int, bucket: int) -> _RecvChannel:
        key = (peer, bucket)
        if key not in self._chans:
            self._chans[key] = _RecvChannel(
                self.rt, ChannelGeom(peer, bucket, self.n_layers,
                                     self.heads, self.head_dim))
        return self._chans[key]

    def close(self) -> None:
        for ch in self._chans.values():
            ch.close()
        self._chans.clear()
