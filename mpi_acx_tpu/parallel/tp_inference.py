"""Tensor-parallel (Megatron-style) inference for the GPT-2 family.

Decoding is latency-bound — each autoregressive step is a skinny
[B, 1, *] pass that one chip's HBM bandwidth gates. Head-parallel
attention + column/row-parallel MLP split every weight matrix (and the KV
cache) over a 'tp' mesh axis so each step streams 1/tp of the weights per
chip, at the cost of two ``psum``s per layer (the classic Megatron
residual-boundary all-reduces) riding ICI.

The whole generation — prefill, KV cache, the ``lax.scan`` decode loop,
greedy or temperature/top-k/top-p sampling — runs inside ONE ``shard_map``
program: the cache never leaves its shard, XLA sees the full schedule, and
every rank computes identical logits (each psum replicates them), so the
emitted tokens agree rank-to-rank by construction.

Weight layout: :func:`tp_shard_params` reshapes the stacked GPT-2 pytree
so the head axis (attention) and FFN axis (MLP) are explicit, and
:func:`tp_param_specs` shards exactly those axes; everything else
replicates. Numerics match models.transformer.generate exactly up to
matmul-split summation order (tests/test_tp_inference.py asserts token
equality vs the single-device path).

The reference has no serving stack (SURVEY.md §0: "not a training
framework" — and not an inference one either); this is the
application-layer counterpart of train.py's tensor parallelism, built on
the same mesh/collective substrate.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.decoding import sample_logits
from mpi_acx_tpu.ops.attention import select_attention


def tp_shard_params(params, cfg: tfm.TransformerConfig):
    """Re-layout the stacked GPT-2 pytree for head/FFN sharding: wqkv
    [L, d, 3d] -> [L, d, 3, H, Dh] (the head axis becomes shardable
    without splitting the packed q/k/v thirds) and wo [L, d, d] ->
    [L, H, Dh, d] (row-parallel by head)."""
    L, d = cfg.n_layers, cfg.d_model
    H, Dh = cfg.n_heads, cfg.head_dim
    lay = params["layers"]
    out = dict(params)
    out["layers"] = dict(
        lay,
        wqkv=lay["wqkv"].reshape(L, d, 3, H, Dh),
        wo=lay["wo"].reshape(L, H, Dh, d),
    )
    return out


def tp_param_specs(axis: str = "tp"):
    """PartitionSpecs matching :func:`tp_shard_params` output: attention
    sharded on the head axis, MLP on the FFN axis, the rest replicated."""
    return {
        "embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
        "layers": {
            "ln1_g": P(), "ln1_b": P(),
            "wqkv": P(None, None, None, axis, None),
            "wo": P(None, axis),
            "ln2_g": P(), "ln2_b": P(),
            "w1": P(None, None, axis), "b1": P(None, axis),
            "w2": P(None, axis), "b2": P(),
        },
    }


def make_tp_generate(cfg: tfm.TransformerConfig, mesh: Mesh, n_new: int,
                     axis: str = "tp", temperature: float = 0.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None):
    """Builds a jitted tensor-parallel ``generate(params, prompt, key) ->
    tokens [B, S + n_new]`` over the mesh's ``axis``.

    params is the ORDINARY transformer pytree (tfm.init_params /
    cast_params output) — the TP re-layout happens inside the jit.
    ``temperature=0`` is greedy (key unused but still required, so the
    signature is stable across sampling configs).
    """
    tp = mesh.shape[axis]
    H, Dh, d = cfg.n_heads, cfg.head_dim, cfg.d_model
    assert H % tp == 0, (H, tp)
    Hl = H // tp
    attend = select_attention(cfg.use_flash)

    def attn_prefill(lp, x):
        """[B, S, d] -> (psummed attention output, local k, v)."""
        B, S, _ = x.shape
        h = tfm.layernorm(x, lp["ln1_g"], lp["ln1_b"])
        qkv = h @ lp["wqkv"].reshape(d, 3 * Hl * Dh).astype(x.dtype)
        q, k, v = (t.reshape(B, S, Hl, Dh)
                   for t in jnp.split(qkv, 3, axis=-1))
        o = attend(q, k, v)
        part = o.reshape(B, S, Hl * Dh) @ lp["wo"].reshape(
            Hl * Dh, d).astype(x.dtype)
        return lax.psum(part, axis), k, v

    def mlp(lp, x):
        h = tfm.layernorm(x, lp["ln2_g"], lp["ln2_b"])
        y = jax.nn.gelu(h @ lp["w1"].astype(x.dtype)
                        + lp["b1"].astype(x.dtype))
        part = y @ lp["w2"].astype(x.dtype)
        return x + lax.psum(part, axis) + lp["b2"].astype(x.dtype)

    def unembed(params, x):
        x = tfm.layernorm(x, params["lnf_g"], params["lnf_b"])
        return jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype),
                          preferred_element_type=jnp.float32)

    def per_shard(params, prompt, key):
        B, S = prompt.shape
        max_len = S + n_new
        assert max_len <= cfg.max_seq, (max_len, cfg.max_seq)

        # -- prefill: fill the local-head KV cache ----------------------
        x = (params["embed"][prompt] + params["pos"][:S]).astype(cfg.dtype)

        def pf_body(x, lp):
            attn, k, v = attn_prefill(lp, x)
            return mlp(lp, x + attn), (k, v)

        x, (ks, vs) = lax.scan(pf_body, x, params["layers"])
        logits0 = unembed(params, x[:, -1:])[:, 0]      # [B, vocab] f32

        kc = jnp.zeros((cfg.n_layers, B, max_len, Hl, Dh), cfg.dtype)
        vc = jnp.zeros_like(kc)
        kc = lax.dynamic_update_slice(kc, ks, (0,) * 5)
        vc = lax.dynamic_update_slice(vc, vs, (0,) * 5)

        def pick(logits, k):
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
            return sample_logits(logits, k, temperature, top_k,
                                 top_p).astype(prompt.dtype)

        # -- decode loop: one fixed-shape step per new token ------------
        def dec_body(carry, step_key):
            kc, vc, pos, tok = carry
            x = (params["embed"][tok][:, None, :]
                 + params["pos"][pos][None, None, :]).astype(cfg.dtype)

            def body(x, layer):
                lp, kcl, vcl = layer
                h = tfm.layernorm(x, lp["ln1_g"], lp["ln1_b"])
                qkv = h @ lp["wqkv"].reshape(d, 3 * Hl * Dh).astype(x.dtype)
                q, k, v = (t.reshape(B, 1, Hl, Dh)
                           for t in jnp.split(qkv, 3, axis=-1))
                kcl = lax.dynamic_update_slice(kcl, k, (0, pos, 0, 0))
                vcl = lax.dynamic_update_slice(vcl, v, (0, pos, 0, 0))
                s = jnp.einsum("bqhd,bkhd->bhqk", q, kcl).astype(
                    jnp.float32) / jnp.sqrt(Dh)
                mask = jnp.arange(max_len) <= pos
                s = jnp.where(mask[None, None, None], s,
                              jnp.finfo(jnp.float32).min)
                p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
                o = jnp.einsum("bhqk,bkhd->bqhd", p, vcl)
                part = o.reshape(B, 1, Hl * Dh) @ lp["wo"].reshape(
                    Hl * Dh, d).astype(x.dtype)
                x = x + lax.psum(part, axis)
                return mlp(lp, x), (kcl, vcl)

            x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
            logits = unembed(params, x)[:, 0]
            nxt = pick(logits, step_key)
            return (kc, vc, pos + 1, nxt), tok

        first = pick(logits0, key)
        keys = jax.random.split(jax.random.fold_in(key, 1), n_new)
        (_, _, _, _), toks = lax.scan(
            dec_body, (kc, vc, jnp.asarray(S, jnp.int32), first), keys)
        return jnp.concatenate([prompt, jnp.moveaxis(toks, 0, 1)], axis=1)

    specs = tp_param_specs(axis)
    inner = shard_map(per_shard, mesh=mesh,
                      in_specs=(specs, P(), P()),
                      out_specs=P(), check_vma=False)

    @jax.jit
    def generate(params, prompt, key):
        return inner(tp_shard_params(params, cfg), prompt, key)

    return generate
