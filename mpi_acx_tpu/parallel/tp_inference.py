"""Tensor-parallel (Megatron-style) inference for the model families.

Decoding is latency-bound — each autoregressive step is a skinny
[B, 1, *] pass that one chip's HBM bandwidth gates. Head-parallel
attention + column/row-parallel MLP split every weight matrix (and the KV
cache) over a 'tp' mesh axis so each step streams 1/tp of the weights per
chip, at the cost of two ``psum``s per layer (the classic Megatron
residual-boundary all-reduces) riding ICI.

The whole generation — prefill, KV cache, the ``lax.scan`` decode loop,
greedy or temperature/top-k/top-p sampling — runs inside ONE ``shard_map``
program (:func:`_run_generation`, shared by the families): the cache never
leaves its shard, XLA sees the full schedule, and every rank computes
identical logits (each psum replicates them), so the emitted tokens agree
rank-to-rank by construction.

GPT-2 (:func:`make_tp_generate`) shards the packed qkv by attention head;
Llama (:func:`make_tp_generate_llama`) shards by KV-HEAD GROUP — each rank
holds ``n_kv_heads/tp`` K/V heads plus their ``n_rep`` query heads, so the
per-rank cache keeps GQA's bandwidth win and grouped-query attention runs
against the un-repeated local cache. Greedy output matches the
single-device generate paths exactly (tests/test_tp_inference.py).

The reference has no serving stack (SURVEY.md §0: "not a training
framework" — and not an inference one either); this is the
application-layer counterpart of train.py's tensor parallelism, built on
the same mesh/collective substrate.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpi_acx_tpu.models import llama as lm
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.decoding import (decode_layer_scan,
                                         grouped_decode_attend,
                                         sample_logits)
from mpi_acx_tpu.ops.attention import select_attention
from mpi_acx_tpu.ops.wquant import wread


def _run_generation(hooks, layers, prompt, key, n_new, *, pick):
    """The family-independent TP generation loop (per-shard code).

    hooks: embed(tokens [B,S]) -> x; embed_tok(tok [B], pos) -> x [B,1,d];
    prefill_layer(x, lp) -> (x, (k, v));
    decode_qkv(lp, x, pos) -> (q, k, v) (k/v [B, 1, H_local, D]);
    decode_attend(lp, x, q, kc, vc, pos, max_len) -> x (kc/vc are the
    layer's updated cache slices); finish(x) -> logits [B, S, vocab] f32.

    The decode loop owns the cache writes through the shared carry-scan
    (models.decoding.decode_layer_scan): in-place per-layer updates,
    1.9x faster decode on v5e than scan-ys stacking.
    """
    B, S = prompt.shape
    max_len = S + n_new

    x = hooks["embed"](prompt)
    x, (ks, vs) = lax.scan(hooks["prefill_layer"], x, layers)
    logits0 = hooks["finish"](x[:, -1:])[:, 0]            # [B, vocab]

    # Cache layout follows the prefill outputs ([L, B, S, H?, D] local).
    kc, vc = _init_kv_from_prefill(ks, vs, max_len)

    def dec_body(carry, step_key):
        kc, vc, pos, tok = carry
        x = hooks["embed_tok"](tok, pos)
        x, kc, vc = decode_layer_scan(
            layers, x, kc, vc, pos, hooks["decode_qkv"],
            lambda lp, x, q, kc_l, vc_l, pos: hooks["decode_attend"](
                lp, x, q, kc_l, vc_l, pos, max_len))
        nxt = pick(hooks["finish"](x)[:, 0], step_key)
        return (kc, vc, pos + 1, nxt), tok

    first = pick(logits0, key)
    keys = jax.random.split(jax.random.fold_in(key, 1), n_new)
    (_, _, _, _), toks = lax.scan(
        dec_body, (kc, vc, jnp.asarray(S, jnp.int32), first), keys)
    return jnp.concatenate([prompt, jnp.moveaxis(toks, 0, 1)], axis=1)


def _make_pick(temperature, top_k, top_p, out_dtype):
    def pick(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(out_dtype)
        return sample_logits(logits, k, temperature, top_k,
                             top_p).astype(out_dtype)
    return pick


# -- GPT-2 family ----------------------------------------------------------


def _scale_keys(params) -> frozenset:
    """The int8 weight-only scale companions present in a checkpoint
    (ops/wquant.py). TP serving supports them for the dense matmul
    weights: the shard fns re-layout each companion alongside its
    weight, the spec trees gain matching entries (_specs_with_scales),
    and every weight read goes through ops.wquant.wread."""
    return frozenset(k for k in params["layers"] if k.endswith("_scale"))


def _specs_with_scales(specs, scale_keys: frozenset, scale_specs: dict,
                       where: str):
    """Extend a family's layer spec tree with entries for the scale
    companions actually present. Unknown companions (e.g. quantized MoE
    expert weights) raise LOUDLY — the alternative is multiplying raw
    int8 codes without their scales."""
    unknown = [k for k in scale_keys if k not in scale_specs]
    if unknown:
        raise ValueError(
            f"{where} does not support int8 quantization of {unknown} "
            f"(supported: {sorted(scale_specs)}); see ops/wquant.py")
    if not scale_keys:
        return specs
    out = dict(specs)
    out["layers"] = dict(specs["layers"],
                         **{k: scale_specs[k] for k in scale_keys})
    return out


def _tp_program_cache(mesh, per_shard, param_slots, data_specs,
                      out_specs, donate_argnums=()):
    """THE scale-keyed program cache every TP builder uses: one
    compiled shard_map program per tuple of int8 scale-key sets, so
    quantized and plain checkpoints (whose pytrees differ) share the
    per-shard code but get matching spec trees.

    ``param_slots``: one (base_specs, scale_specs, shard_fn, cfg,
    where) per leading parameter-tree argument of ``per_shard``; the
    remaining arguments use ``data_specs``. ``donate_argnums`` (indices
    into the combined ``(*param_trees, *data)`` argument list) lets a
    carry-style caller donate its buffers — TP serving donates the slot
    caches so each chunk updates them in place. Returns a plain
    callable ``fn(*param_trees, *data)``."""
    n = len(param_slots)
    cache: dict = {}

    def call(*args):
        key = tuple(_scale_keys(p) for p in args[:n])
        fn = cache.get(key)
        if fn is None:
            in_specs = tuple(
                _specs_with_scales(bs, sk, ss, where)
                for (bs, ss, _, _, where), sk in zip(param_slots, key)
            ) + tuple(data_specs)
            inner = shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)

            def run(*a, _inner=inner):
                pt = tuple(slot[2](p, slot[3])
                           for slot, p in zip(param_slots, a[:n]))
                return _inner(*pt, *a[n:])

            fn = cache[key] = jax.jit(run,
                                      donate_argnums=donate_argnums)
        return fn(*args)

    return call


def tp_shard_params(params, cfg: tfm.TransformerConfig):
    """Re-layout the stacked GPT-2 pytree for head/FFN sharding: wqkv
    [L, d, 3d] -> [L, d, 3, H, Dh] (the head axis becomes shardable
    without splitting the packed q/k/v thirds) and wo [L, d, d] ->
    [L, H, Dh, d] (row-parallel by head). Int8 scale companions are
    re-laid-out alongside their weights (w1/w2 scales broadcast as-is)."""
    L, d = cfg.n_layers, cfg.d_model
    H, Dh = cfg.n_heads, cfg.head_dim
    lay = params["layers"]
    out = dict(params)
    out["layers"] = dict(
        lay,
        wqkv=lay["wqkv"].reshape(L, d, 3, H, Dh),
        wo=lay["wo"].reshape(L, H, Dh, d),
    )
    if "wqkv_scale" in lay:
        out["layers"]["wqkv_scale"] = lay["wqkv_scale"].reshape(
            L, 1, 3, H, Dh)
    if "wo_scale" in lay:
        out["layers"]["wo_scale"] = lay["wo_scale"].reshape(L, 1, 1, d)
    return out


def _gpt2_scale_specs(axis: str):
    """Spec entries for GPT-2 scale companions after tp_shard_params:
    per-OUTPUT-channel scales shard with their weight's output axis
    (wqkv: heads; w1: ffn) and replicate when the weight shards on its
    input side (wo, w2)."""
    return {
        "wqkv_scale": P(None, None, None, axis, None),
        "wo_scale": P(),
        "w1_scale": P(None, None, axis),
        "w2_scale": P(),
    }


def _moe_scale_specs(axis: str):
    """MoE TP serving supports int8 on the ATTENTION weights only (they
    ride the shared GPT-2 ops); expert-weight companions are absent
    here so _specs_with_scales rejects them loudly. One definition for
    plain AND speculative MoE TP serving."""
    gs = _gpt2_scale_specs(axis)
    return {k: gs[k] for k in ("wqkv_scale", "wo_scale")}


def tp_param_specs(axis: str = "tp"):
    """PartitionSpecs matching :func:`tp_shard_params` output: attention
    sharded on the head axis, MLP on the FFN axis, the rest replicated."""
    return {
        "embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
        "layers": {
            "ln1_g": P(), "ln1_b": P(),
            "wqkv": P(None, None, None, axis, None),
            "wo": P(None, axis),
            "ln2_g": P(), "ln2_b": P(),
            "w1": P(None, None, axis), "b1": P(None, axis),
            "w2": P(None, axis), "b2": P(),
        },
    }


def _gpt2_embed(params, cfg, tokens):
    """Token + learned-position embedding (replicated leaves)."""
    S = tokens.shape[1]
    return (params["embed"][tokens] + params["pos"][:S]).astype(cfg.dtype)


def _gpt2_finish(params, cfg, x):
    """Final layernorm + tied unembedding -> f32 logits."""
    x = tfm.layernorm(x, params["lnf_g"], params["lnf_b"])
    return jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype),
                      preferred_element_type=jnp.float32)


def _init_kv_from_prefill(ks, vs, cap):
    """Allocate [L, B, cap, H_local, D] caches and land the prefill
    K/V at positions [0, S)."""
    kc = jnp.zeros(ks.shape[:2] + (cap,) + ks.shape[3:], ks.dtype)
    vc = jnp.zeros_like(kc)
    kc = lax.dynamic_update_slice(kc, ks, (0,) * kc.ndim)
    vc = lax.dynamic_update_slice(vc, vs, (0,) * vc.ndim)
    return kc, vc


def _gpt2_tp_layer_ops(cfg, tp: int, axis: str):
    """The head/FFN-split per-layer primitives shared by TP generation
    and TP speculative decoding: (local_qkv, out_proj, dense_mlp) over
    this rank's Hl = n_heads/tp head slice (two psums per layer at the
    residual boundaries — the classic Megatron split)."""
    H, Dh, d = cfg.n_heads, cfg.head_dim, cfg.d_model
    assert H % tp == 0, (H, tp)
    Hl = H // tp

    def local_qkv(lp, x):
        B, S, _ = x.shape
        h = tfm.layernorm(x, lp["ln1_g"], lp["ln1_b"])
        qkv = h @ wread(lp, "wqkv", x.dtype).reshape(d, 3 * Hl * Dh)
        return (t.reshape(B, S, Hl, Dh) for t in jnp.split(qkv, 3, -1))

    def out_proj(lp, o, x):
        B, S = o.shape[:2]
        part = o.reshape(B, S, Hl * Dh) @ wread(lp, "wo",
                                                x.dtype).reshape(
            Hl * Dh, d)
        return x + lax.psum(part, axis)

    def dense_mlp(lp, x):
        h = tfm.layernorm(x, lp["ln2_g"], lp["ln2_b"])
        y = jax.nn.gelu(h @ wread(lp, "w1", x.dtype)
                        + lp["b1"].astype(x.dtype))
        part = y @ wread(lp, "w2", x.dtype)
        return x + lax.psum(part, axis) + lp["b2"].astype(x.dtype)

    return local_qkv, out_proj, dense_mlp


def make_tp_generate(cfg, mesh: Mesh, n_new: int,
                     axis: str = "tp", temperature: float = 0.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     ffn=None, specs=None, shard_params=None,
                     scale_specs=None):
    """Builds a jitted tensor-parallel ``generate(params, prompt, key) ->
    tokens [B, S + n_new]`` over the mesh's ``axis``.

    params is the ORDINARY transformer pytree (tfm.init_params /
    cast_params output) — the TP re-layout happens inside the jit.
    ``temperature=0`` is greedy (key unused but still required, so the
    signature is stable across sampling configs).

    ``ffn(lp, x) -> x`` overrides the per-layer feed-forward half (the
    dense column/row-parallel MLP by default), with ``specs``/
    ``shard_params`` overriding the weight layout to match — the GPT-2-
    attention MoE family plugs in its expert-parallel FFN this way
    (:func:`make_tp_generate_moe`), mirroring the single-device ffn hook
    on tfm.prefill/decode_step.
    """
    tp = mesh.shape[axis]
    local_qkv, out_proj, dense_mlp = _gpt2_tp_layer_ops(cfg, tp, axis)
    mlp = ffn or dense_mlp
    shard_params_fn = shard_params or tp_shard_params
    specs = specs or tp_param_specs(axis)
    if scale_specs is None:
        scale_specs = _gpt2_scale_specs(axis)

    def per_shard(params, prompt, key):
        assert prompt.shape[1] + n_new <= cfg.max_seq

        def embed(tokens):
            return _gpt2_embed(params, cfg, tokens)

        def embed_tok(tok, pos):
            return (params["embed"][tok][:, None, :]
                    + params["pos"][pos][None, None, :]).astype(cfg.dtype)

        def prefill_layer(x, lp):
            q, k, v = local_qkv(lp, x)
            o = select_attention(cfg.use_flash)(q, k, v)
            return mlp(lp, out_proj(lp, o, x)), (k, v)

        def decode_qkv(lp, x, pos):
            return tuple(local_qkv(lp, x))

        def decode_attend(lp, x, q, kcl, vcl, pos, max_len):
            # Shared MHA decode attention (GQA construction, n_rep=1).
            o = grouped_decode_attend(q, kcl, vcl, pos, max_len, n_rep=1,
                                      flash=cfg.decode_flash)
            return mlp(lp, out_proj(lp, o, x))

        def finish(x):
            return _gpt2_finish(params, cfg, x)

        hooks = {"embed": embed, "embed_tok": embed_tok,
                 "prefill_layer": prefill_layer,
                 "decode_qkv": decode_qkv,
                 "decode_attend": decode_attend, "finish": finish}
        return _run_generation(
            hooks, params["layers"], prompt, key, n_new,
            pick=_make_pick(temperature, top_k, top_p, prompt.dtype))

    return _tp_program_cache(
        mesh, per_shard,
        [(specs, scale_specs, shard_params_fn, cfg,
          "TP GPT-2/MoE serving")],
        (P(), P()), P())


# -- MoE family (attention by head, experts over the same axis) ------------


# Attention re-layout is exactly the dense family's (cfg duck-types);
# expert tensors keep their layout — the [n_experts] dim shards directly.
tp_shard_params_moe = tp_shard_params


def tp_param_specs_moe(axis: str = "tp"):
    return {
        "embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
        "layers": {
            "ln1_g": P(), "ln1_b": P(),
            "wqkv": P(None, None, None, axis, None),
            "wo": P(None, axis),
            "ln2_g": P(), "ln2_b": P(), "gate": P(),
            "w1": P(None, axis), "w2": P(None, axis),
        },
    }


def _ep_dispatch_mode(mode: str, tokens: int, ep: int) -> str:
    """Resolve the effective EP dispatch for ONE routed call moving
    ``tokens`` tokens over an ``ep``-way axis. ``"auto"`` picks
    ``"sharded"`` when the token count divides the axis — in the
    drop-free serving regime the two paths are token-identical, so
    divisibility is the only real constraint — and falls back to
    ``"replicated"`` otherwise (B=1 latency decode, k-wide speculative
    verify windows). Shapes are static under jit, so the choice is
    made at trace time, per call site: a prefill can dispatch sharded
    while the same program's decode runs replicated."""
    if mode == "auto":
        return "sharded" if tokens % ep == 0 else "replicated"
    return mode


def _make_moe_ffn(cfg, tp: int, axis: str, ep_dispatch: str):
    """THE MoE expert-FFN hook every TP builder shares (generate,
    speculative, serving): validates the expert split, resolves
    ``ep_dispatch`` per call site (_ep_dispatch_mode), and applies the
    drop-free degrade — outside ``capacity_factor >= n_experts``,
    sharded dispatch forms different capacity groups than the
    single-device run, so "auto" degrades to replicated (bit-equal at
    any capacity) to keep the exact-parity contract; an EXPLICIT
    "sharded" request is honored as-is."""
    from mpi_acx_tpu.models.moe_transformer import _moe_ffn

    assert cfg.n_experts % tp == 0, (cfg.n_experts, tp)
    assert ep_dispatch in ("auto", "sharded", "replicated"), ep_dispatch
    side = ep_dispatch
    if side == "auto" and cfg.capacity_factor < cfg.n_experts:
        side = "replicated"

    def moe_ffn(lp, x):
        mode = _ep_dispatch_mode(side, x.shape[0] * x.shape[1], tp)
        return _moe_ffn(cfg, lp, x, ep_axis=axis,
                        replicated=mode == "replicated",
                        sharded_dispatch=mode == "sharded")

    return moe_ffn


def make_tp_generate_moe(cfg, mesh: Mesh, n_new: int, axis: str = "tp",
                         temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None,
                         ep_dispatch: str = "auto"):
    """Tensor-parallel MoE-transformer generation: the dense GPT-2
    builder with the expert-parallel routed FFN plugged into its ffn
    hook. Attention splits by head (two psums per layer); each rank
    hosts ``n_experts/tp`` experts.

    ``ep_dispatch`` selects how tokens reach their experts:

    * ``"auto"`` (default) — per call site (trace-time, shapes are
      static): ``"sharded"`` whenever the call's token count divides
      tp, ``"replicated"`` otherwise. Prefill (B*S tokens) and
      batch-serving decode get real EP scaling; B=1 latency decode
      falls back to replicated instead of raising. Outside the
      drop-free regime (capacity_factor < n_experts) auto degrades to
      replicated entirely — sharded capacity groups differ from the
      single-device run's there (_make_moe_ffn holds the rule).
    * ``"sharded"`` — REAL expert-parallel dispatch
      (moe.moe_layer_sharded_dispatch): each rank routes only its
      exclusive 1/tp token slice and the capacity-bounded
      ``all_to_all`` of the training EP path carries tokens to their
      expert's rank and back, then one all_gather re-replicates.
      Router + dispatch work per rank genuinely scales as 1/tp —
      this is the path that scales past small tp. Requires every
      routed call's token count to divide tp (decode routes B tokens
      per step; raises at trace time).
    * ``"replicated"`` — every rank routes ALL tokens, local expert
      block + one psum (moe.moe_layer_replicated_ep): only the expert
      FLOPs shard, but any batch size works and routing is bit-equal
      to the single-device dispatch at any capacity.

    In the drop-free regime (``capacity_factor >= n_experts``, the
    serving guard — see moe_transformer.decode_step) all paths emit
    tokens identical to the single-device ``generate``
    (tests/test_tp_inference.py covers tp=4 and tp=8, plus the auto
    fallback at an indivisible batch)."""
    moe_ffn = _make_moe_ffn(cfg, mesh.shape[axis], axis, ep_dispatch)

    return make_tp_generate(cfg, mesh, n_new, axis=axis,
                            temperature=temperature, top_k=top_k,
                            top_p=top_p, ffn=moe_ffn,
                            specs=tp_param_specs_moe(axis),
                            shard_params=tp_shard_params_moe,
                            scale_specs=_moe_scale_specs(axis))


# -- Llama family (GQA: shard by KV-head group) ----------------------------


def tp_shard_params_llama(params, cfg: lm.LlamaConfig):
    """Head-axis re-layout for the Llama pytree: wq [L, d, Hq*Dh] ->
    [L, d, Hq, Dh], wk/wv -> [L, d, Hkv, Dh], wo -> [L, Hq, Dh, d].
    Contiguous head chunks keep each KV group's query heads on the same
    rank as their K/V head (query head h belongs to group h // n_rep).
    Int8 scale companions are re-laid-out alongside their weights."""
    L, d = cfg.n_layers, cfg.d_model
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    lay = params["layers"]
    out = dict(params)
    out["layers"] = dict(
        lay,
        wq=lay["wq"].reshape(L, d, Hq, Dh),
        wk=lay["wk"].reshape(L, d, Hkv, Dh),
        wv=lay["wv"].reshape(L, d, Hkv, Dh),
        wo=lay["wo"].reshape(L, Hq, Dh, d),
    )
    for name, shp in (("wq", (L, 1, Hq, Dh)), ("wk", (L, 1, Hkv, Dh)),
                      ("wv", (L, 1, Hkv, Dh)), ("wo", (L, 1, 1, d))):
        if name + "_scale" in lay:
            out["layers"][name + "_scale"] = \
                lay[name + "_scale"].reshape(shp)
    return out


def _llama_scale_specs(axis: str):
    """Spec entries for Llama scale companions after
    tp_shard_params_llama (output-side scales shard with their heads /
    ffn axis; input-side-sharded weights get replicated scales)."""
    return {
        "wq_scale": P(None, None, axis, None),
        "wk_scale": P(None, None, axis, None),
        "wv_scale": P(None, None, axis, None),
        "wo_scale": P(),
        "w_gate_scale": P(None, None, axis),
        "w_up_scale": P(None, None, axis),
        "w_down_scale": P(),
    }


def tp_param_specs_llama(axis: str = "tp"):
    return {
        "embed": P(), "final_norm": P(), "unembed": P(),
        "layers": {
            "attn_norm": P(), "mlp_norm": P(),
            "wq": P(None, None, axis, None),
            "wk": P(None, None, axis, None),
            "wv": P(None, None, axis, None),
            "wo": P(None, axis),
            "w_gate": P(None, None, axis),
            "w_up": P(None, None, axis),
            "w_down": P(None, axis),
        },
    }


def _llama_tp_layer_ops(cfg, tp: int, axis: str):
    """Llama per-layer primitives for TP generation AND TP speculative
    decoding, sharded by KV-HEAD GROUP: (local_qkv, out_proj, mlp,
    n_rep). Each rank holds Hkv/tp K/V heads plus their n_rep query
    heads, so the local cache stays un-repeated (GQA's bandwidth win
    survives the split)."""
    Hq, Hkv, Dh, d = (cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
                      cfg.d_model)
    assert Hkv % tp == 0, (Hkv, tp)
    n_rep = Hq // Hkv
    Hkv_l, Hq_l = Hkv // tp, (Hkv // tp) * n_rep

    def mlp(lp, x):
        h = lm.rmsnorm(x, lp["mlp_norm"])
        gate = jax.nn.silu(h @ wread(lp, "w_gate", x.dtype))
        up = h @ wread(lp, "w_up", x.dtype)
        part = (gate * up) @ wread(lp, "w_down", x.dtype)
        return x + lax.psum(part, axis)

    def local_qkv(lp, x, positions):
        B, S, _ = x.shape
        h = lm.rmsnorm(x, lp["attn_norm"])
        q = (h @ wread(lp, "wq", x.dtype).reshape(
            d, Hq_l * Dh)).reshape(B, S, Hq_l, Dh)
        k = (h @ wread(lp, "wk", x.dtype).reshape(
            d, Hkv_l * Dh)).reshape(B, S, Hkv_l, Dh)
        v = (h @ wread(lp, "wv", x.dtype).reshape(
            d, Hkv_l * Dh)).reshape(B, S, Hkv_l, Dh)
        q = lm.rope(q, positions, cfg.rope_theta)
        k = lm.rope(k, positions, cfg.rope_theta)
        return q, k, v

    def out_proj(lp, o, x):
        B, S = o.shape[:2]
        part = o.reshape(B, S, Hq_l * Dh) @ wread(lp, "wo",
                                                  x.dtype).reshape(
            Hq_l * Dh, d)
        return x + lax.psum(part, axis)

    return local_qkv, out_proj, mlp, n_rep


def make_tp_generate_llama(cfg: lm.LlamaConfig, mesh: Mesh, n_new: int,
                           axis: str = "tp", temperature: float = 0.0,
                           top_k: Optional[int] = None,
                           top_p: Optional[float] = None):
    """Tensor-parallel Llama generation: ``tp`` must divide
    ``n_kv_heads``; each rank serves ``n_kv_heads/tp`` KV groups and their
    query heads, so the local cache stays un-repeated (GQA's bandwidth
    win per rank) and grouped-query decode runs exactly as the
    single-device path (llama.decode_step), just on the group slice.
    """
    tp = mesh.shape[axis]
    local_qkv, out_proj, mlp, n_rep = _llama_tp_layer_ops(cfg, tp, axis)

    def per_shard(params, prompt, key):
        assert prompt.shape[1] + n_new <= cfg.max_seq

        def embed(tokens):
            return params["embed"][tokens].astype(cfg.dtype)

        def embed_tok(tok, pos):
            return params["embed"][tok][:, None, :].astype(cfg.dtype)

        def prefill_layer(x, lp):
            S = x.shape[1]
            q, k, v = local_qkv(lp, x, jnp.arange(S))
            kr, vr = lm._repeat_kv(k, n_rep), lm._repeat_kv(v, n_rep)
            o = select_attention(cfg.use_flash)(q, kr, vr)
            return mlp(lp, out_proj(lp, o, x)), (k, v)

        def decode_qkv(lp, x, pos):
            return local_qkv(lp, x, jnp.full((1,), pos))

        def decode_attend(lp, x, q, kcl, vcl, pos, max_len):
            # The shared grouped-GQA construction, on this rank's slice;
            # its flat [B, 1, Hq_l*Dh] output feeds out_proj directly.
            o = grouped_decode_attend(q, kcl, vcl, pos, max_len, n_rep,
                                      flash=cfg.decode_flash)
            return mlp(lp, out_proj(lp, o, x))

        def finish(x):
            x = lm.rmsnorm(x, params["final_norm"])
            return jnp.einsum("bsd,vd->bsv", x,
                              params["unembed"].astype(x.dtype),
                              preferred_element_type=jnp.float32)

        hooks = {"embed": embed, "embed_tok": embed_tok,
                 "prefill_layer": prefill_layer,
                 "decode_qkv": decode_qkv,
                 "decode_attend": decode_attend, "finish": finish}
        return _run_generation(
            hooks, params["layers"], prompt, key, n_new,
            pick=_make_pick(temperature, top_k, top_p, prompt.dtype))

    return _tp_program_cache(
        mesh, per_shard,
        [(tp_param_specs_llama(axis), _llama_scale_specs(axis),
          tp_shard_params_llama, cfg, "TP Llama serving")],
        (P(), P()), P())


# -- Tensor-parallel SPECULATIVE decoding ----------------------------------


def _pack_prefill_cache(ks, vs, cap, kv_int8):
    """Allocate a cap-length per-shard cache and land the prefill K/V
    through decoding.fill_kv_cache — the single definition of the
    (int8) cache layout, so the TP serving path cannot drift from the
    single-device one."""
    from mpi_acx_tpu.models.decoding import fill_kv_cache
    L, B = ks.shape[:2]
    H, D = ks.shape[3], ks.shape[4]
    if kv_int8:
        cache = {"k": jnp.zeros((L, B, cap, H, D), jnp.int8),
                 "v": jnp.zeros((L, B, cap, H, D), jnp.int8),
                 "ks": jnp.zeros((L, B, cap, H, 1), jnp.float32),
                 "vs": jnp.zeros((L, B, cap, H, 1), jnp.float32)}
    else:
        cache = {"k": jnp.zeros((L, B, cap, H, D), ks.dtype),
                 "v": jnp.zeros((L, B, cap, H, D), vs.dtype)}
    return fill_kv_cache(cache, ks, vs, ks.shape[2])


def _tp_family_ops(cfg, tp: int, axis: str, ffn=None,
                   kv_int8: bool = False):
    """GPT-2-scaffold ops with the speculative-core signatures
    (models.speculative._make_run ``ops``), tensor-parallel per shard:
    (prefill, window, decode). Each rank holds its Hl-head slice of the
    weights and KV cache; logits are assembled replicated by the
    per-layer psums, so the speculative accept/roll-back control flow —
    argmax chains, acceptance counts, while_loop conditions — computes
    identically on every rank by construction. ``ffn(lp, x) -> x``
    overrides the feed-forward half (the MoE family plugs in its
    replicated-EP routed FFN, exactly as on make_tp_generate)."""
    local_qkv, out_proj, dense_mlp = _gpt2_tp_layer_ops(cfg, tp, axis)
    mlp = ffn or dense_mlp

    embed = lambda params, tokens: _gpt2_embed(params, cfg, tokens)  # noqa: E731
    finish = lambda params, x: _gpt2_finish(params, cfg, x)  # noqa: E731

    def qkv_fn(lp, x, pos):
        return tuple(local_qkv(lp, x))

    def make_attend(max_len):
        def attend_fn(lp, x, q, kcl, vcl, pos):
            o = grouped_decode_attend(q, kcl, vcl, pos, max_len, n_rep=1,
                                      flash=cfg.decode_flash)
            return mlp(lp, out_proj(lp, o, x))
        return attend_fn

    def prefill(params, _cfg, tokens, cap, last_only=True,
                last_index=None):
        x = embed(params, tokens)

        def pl(x, lp):
            q, k_, v_ = local_qkv(lp, x)
            o = select_attention(cfg.use_flash)(q, k_, v_)
            return mlp(lp, out_proj(lp, o, x)), (k_, v_)

        x, (ks, vs) = lax.scan(pl, x, params["layers"])
        if last_index is not None:     # traced: bucket-padded serving
            x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
        elif last_only:
            x = x[:, -1:]
        logits = finish(params, x)
        # Per-(position, local-head) int8 when enabled: each rank
        # quantizes its own head slice — no cross-shard state.
        return logits, _pack_prefill_cache(ks, vs, cap, kv_int8)

    def decode(params, _cfg, cache, tok):
        pos = jnp.asarray(cache["pos"])
        max_len = cache["k"].shape[2]
        # Scalar pos (generation/speculative) or [B] per-slot positions
        # (continuous-batching serving) — as transformer.decode_step.
        pe = params["pos"][pos]
        x = (params["embed"][tok][:, None, :]
             + (pe[:, None, :] if pos.ndim else pe[None, None, :])
             ).astype(cfg.dtype)
        from mpi_acx_tpu.models.decoding import run_decode_layers
        x, out_cache = run_decode_layers(params["layers"], x, cache,
                                         qkv_fn, make_attend(max_len))
        return finish(params, x)[:, 0], out_cache

    def window(params, _cfg, cache, tokens):
        W = tokens.shape[1]
        pos = cache["pos"]
        max_len = cache["k"].shape[2]
        x = (params["embed"][tokens]
             + lax.dynamic_slice_in_dim(params["pos"], pos, W, 0)[None]
             ).astype(cfg.dtype)
        x, kc, vc = decode_layer_scan(
            params["layers"], x, cache["k"], cache["v"], pos, qkv_fn,
            make_attend(max_len))
        logits = finish(params, x)                        # [1, W, vocab]
        return logits, {"k": kc, "v": vc, "pos": pos + W}

    return prefill, window, decode


def _llama_tp_family_ops(cfg, tp: int, axis: str,
                         kv_int8: bool = False):
    """Llama counterpart of :func:`_tp_family_ops` (speculative-core
    signatures, KV-group-sharded): RoPE at absolute positions, grouped
    decode/window attention against the un-repeated local cache."""
    local_qkv, out_proj, mlp, n_rep = _llama_tp_layer_ops(cfg, tp, axis)

    def embed(params, tokens):
        return params["embed"][tokens].astype(cfg.dtype)

    def finish(params, x):
        x = lm.rmsnorm(x, params["final_norm"])
        return jnp.einsum("bsd,vd->bsv", x,
                          params["unembed"].astype(x.dtype),
                          preferred_element_type=jnp.float32)

    def make_attend(max_len):
        def attend_fn(lp, x, q, kcl, vcl, pos):
            o = grouped_decode_attend(q, kcl, vcl, pos, max_len, n_rep,
                                      flash=cfg.decode_flash)
            return mlp(lp, out_proj(lp, o, x))
        return attend_fn

    def prefill(params, _cfg, tokens, cap, last_only=True,
                last_index=None):
        x = embed(params, tokens)
        S = tokens.shape[1]

        def pl(x, lp):
            q, k_, v_ = local_qkv(lp, x, jnp.arange(S))
            kr, vr = lm._repeat_kv(k_, n_rep), lm._repeat_kv(v_, n_rep)
            o = select_attention(cfg.use_flash)(q, kr, vr)
            return mlp(lp, out_proj(lp, o, x)), (k_, v_)

        x, (ks, vs) = lax.scan(pl, x, params["layers"])
        if last_index is not None:     # traced: bucket-padded serving
            x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
        elif last_only:
            x = x[:, -1:]
        logits = finish(params, x)
        return logits, _pack_prefill_cache(ks, vs, cap, kv_int8)

    def decode(params, _cfg, cache, tok):
        pos = jnp.asarray(cache["pos"])
        max_len = cache["k"].shape[2]
        x = params["embed"][tok][:, None, :].astype(cfg.dtype)

        def qkv_fn(lp, x, pos):
            # Scalar pos -> shared position [1]; [B] per-slot pos
            # (serving) -> [B, 1] so RoPE rotates per slot.
            p = pos[:, None] if pos.ndim else jnp.full((1,), pos)
            return local_qkv(lp, x, p)

        from mpi_acx_tpu.models.decoding import run_decode_layers
        x, out_cache = run_decode_layers(params["layers"], x, cache,
                                         qkv_fn, make_attend(max_len))
        return finish(params, x)[:, 0], out_cache

    def window(params, _cfg, cache, tokens):
        W = tokens.shape[1]
        pos = cache["pos"]
        max_len = cache["k"].shape[2]
        x = params["embed"][tokens].astype(cfg.dtype)

        def qkv_fn(lp, x, pos):
            return local_qkv(lp, x, pos + jnp.arange(W))

        x, kc, vc = decode_layer_scan(
            params["layers"], x, cache["k"], cache["v"], pos, qkv_fn,
            make_attend(max_len))
        logits = finish(params, x)
        return logits, {"k": kc, "v": vc, "pos": pos + W}

    return prefill, window, decode


def make_tp_speculative_generate(draft_cfg, cfg, mesh: Mesh, n_new: int,
                                 k: int = 4, axis: str = "tp",
                                 temperature: float = 0.0,
                                 ep_dispatch: str = "auto"):
    """Tensor-parallel SPECULATIVE decoding: draft proposes, target
    verifies k tokens per window pass — with BOTH models Megatron-split
    over the mesh's ``axis`` inside one shard_map program (per-rank
    head slices of weights and KV caches, two psums per layer). The
    latency technique and the weight-streaming split compose: each
    draft step and each k-wide target window streams 1/tp of the
    weights per chip.

    GPT-2 and Llama families, freely mixed between draft and target
    (config type selects each side's ops; vocabularies must match).
    ``temperature=0`` is greedy: output
    tokens equal the single-device ``speculative_generate`` AND the
    target-only greedy decode (tests/test_tp_inference.py asserts both
    at tp=2/4); otherwise the stochastic accept/resample hooks run with
    the replicated key, every rank drawing identical samples.

    ``ep_dispatch`` (MoE sides only) follows make_tp_generate_moe's
    contract: ``"auto"`` (default) resolves PER CALL SITE by
    divisibility — the prompt prefill, and the k+1-wide verify window
    when ``B*(k+1)`` happens to divide tp, dispatch sharded; calls
    with indivisible token counts (single-token draft steps, most
    window geometries) fall back to replicated EP instead of raising.
    Parity exception: on an MoE side OUTSIDE the drop-free capacity
    regime, ``"auto"`` resolves to replicated for EVERY call — sharded
    dispatch forms different capacity groups than the single-device
    run, and this builder's contract is exact equality with
    ``speculative_generate`` (only the TARGET is required drop-free by
    ``_check_moe_target``; a tight-capacity DRAFT is legal, so its
    dispatch must stay bit-equal). Forcing ``"sharded"`` raises at
    trace time when any call's token count is indivisible (same rule
    as plain TP MoE serving); a compiled FLOP/wire comparison of the
    modes is recorded in BASELINE.md.

    Returns a jitted ``generate(draft_params, params, prompt, key) ->
    (tokens [1, S+n_new], stats)`` with stats as in
    ``speculative_generate``.
    """
    from mpi_acx_tpu.models.speculative import (_greedy_hooks,
                                                _make_run, _sample_hooks)

    from mpi_acx_tpu.models.moe_transformer import (MoeTransformerConfig,
                                                    _moe_ffn)
    from mpi_acx_tpu.models.speculative import _check_moe_target

    def fam(c):
        """One dispatch per family: (speculative ops, specs, shard fn,
        scale_specs for int8 weight-only companions)."""
        if type(c) is lm.LlamaConfig:
            return (_llama_tp_family_ops(c, tp, axis),
                    tp_param_specs_llama(axis), tp_shard_params_llama,
                    _llama_scale_specs(axis))
        if type(c) is MoeTransformerConfig:
            moe_ffn = _make_moe_ffn(c, tp, axis, ep_dispatch)
            return (_tp_family_ops(c, tp, axis, ffn=moe_ffn),
                    tp_param_specs_moe(axis), tp_shard_params,
                    _moe_scale_specs(axis))
        if type(c) is tfm.TransformerConfig:
            return (_tp_family_ops(c, tp, axis), tp_param_specs(axis),
                    tp_shard_params, _gpt2_scale_specs(axis))
        raise TypeError(
            "TP speculative decoding supports the GPT-2, Llama, and "
            f"MoE-transformer families; got {type(c).__name__}")

    assert draft_cfg.vocab == cfg.vocab, (draft_cfg.vocab, cfg.vocab)
    assert k >= 2, k
    assert ep_dispatch in ("auto", "sharded", "replicated"), ep_dispatch
    # An MoE TARGET must be drop-free so the k-wide verify window
    # routes exactly like plain decode (same rule as the
    # single-device speculative API).
    _check_moe_target(cfg)
    tp = mesh.shape[axis]
    t_ops, specs_t, shard_t, sspecs_t = fam(cfg)
    d_ops, specs_d, shard_d, sspecs_d = fam(draft_cfg)
    hooks = (_greedy_hooks(k) if temperature == 0.0
             else _sample_hooks(k, float(temperature)))

    def per_shard(dparams, params, prompt, key):
        B, S = prompt.shape        # static at trace time
        run = _make_run(draft_cfg, cfg, S, n_new, k, *hooks,
                        ops=(t_ops[0], t_ops[1], d_ops[0], d_ops[2]))
        if B == 1:
            return run(dparams, params, prompt, key)
        # Batched: vmap the single-sequence loop over rows INSIDE the
        # shard (the same lift as models.speculative._build_batched).
        # The per-layer psums batch elementwise across ranks, so each
        # row's replicated-logits invariant — and therefore its
        # independent pacing — survives the composition.
        toks, rounds, acc = jax.vmap(
            lambda row, kk: run(dparams, params, row[None], kk)
        )(prompt, jax.random.split(key, B))
        return toks[:, 0], rounds, acc

    run = _tp_program_cache(
        mesh, per_shard,
        [(specs_d, sspecs_d, shard_d, draft_cfg,
          "TP speculative draft"),
         (specs_t, sspecs_t, shard_t, cfg, "TP speculative target")],
        (P(), P()), (P(), P(), P()))

    def generate(draft_params, params, prompt, key):
        toks, rounds, acc = run(draft_params, params, prompt, key)
        return toks, {"rounds": rounds, "drafted_accepted": acc}

    return generate


# -- Tensor-parallel CONTINUOUS BATCHING (models/serving.py contract) ------


def make_tp_server_fns(params, cfg, mesh: Mesh, chunk: int = 1,
                       axis: str = "tp", family: str = "gpt2",
                       ep_dispatch: str = "auto",
                       kv_int8: bool = False):
    """Server-fns tuple for models.serving._serve whose three programs
    run tensor-parallel over the mesh: continuous batching composes
    with the Megatron weight split. Each slot's KV cache shards by
    attention head (the same [L, B, max_len, H, D] layout with H on
    ``axis``); per-slot positions ride the shared decode scaffold's
    vector-pos mode unchanged, so outputs equal the single-device
    serve_greedy's token for token up to the matmul split's summation
    reorder (exact in f32; bf16 can flip near-tied argmaxes — the same
    caveat as every TP-vs-single-device comparison here, see
    tests/test_tp_inference.py), while every decode step streams 1/tp
    of the weights per rank.

    ``family``: "gpt2" (dense), "moe" (GPT-2 attention + the routed
    expert FFN through _tp_family_ops' ffn hook; each rank hosts
    n_experts/tp experts, ``ep_dispatch`` as make_tp_generate_moe —
    "auto" gives batch-serving decode the sharded all_to_all path and
    falls back per call site when the token count doesn't divide tp),
    or "llama" (GQA: slots hold the un-repeated KV-head-group cache,
    sharded by group). Greedy. ``kv_int8`` serves from int8 slot
    caches (gpt2/llama): each rank quantizes its own head slice on
    write and the shared scale-on-scores read keeps the codes as the
    attention operands — the long-context composition where cache
    bytes dominate even after the 1/tp weight split. Use::

        fns = make_tp_server_fns(params, cfg, mesh, chunk=8)
        outs = serving.serve_greedy(params, cfg, prompts, n_new,
                                    n_slots, max_len, family=tfm,
                                    chunk=8, server_fns=fns)

    int8 WEIGHT checkpoints work (wread + the sharded scale
    companions, exactly as make_tp_generate). The weight tree is
    re-laid-out and sharded ONCE here — the serve loop dispatches
    step programs every chunk, and re-sharding the full tree per
    dispatch (the one-shot generate builders' pattern) would double
    weight traffic in the latency-bound hot loop.
    """
    tp = mesh.shape[axis]
    # Reuse the speculative core's per-shard family ops — prefill with
    # a traced last_index, decode with vector pos — so the TP layer
    # wiring lives once per family (_tp_family_ops /
    # _llama_tp_family_ops), not per builder.
    if family == "gpt2":
        ops_prefill, _, ops_decode = _tp_family_ops(cfg, tp, axis,
                                                    kv_int8=kv_int8)
        specs = tp_param_specs(axis)
        scale_specs = _gpt2_scale_specs(axis)
        shard_fn = tp_shard_params
    elif family == "moe":
        if kv_int8:
            raise ValueError(
                "int8 KV slot caches: gpt2/llama only for now")
        moe_ffn = _make_moe_ffn(cfg, tp, axis, ep_dispatch)
        ops_prefill, _, ops_decode = _tp_family_ops(cfg, tp, axis,
                                                    ffn=moe_ffn)
        specs = tp_param_specs_moe(axis)
        scale_specs = _moe_scale_specs(axis)
        shard_fn = tp_shard_params_moe
    elif family == "llama":
        ops_prefill, _, ops_decode = _llama_tp_family_ops(
            cfg, tp, axis, kv_int8=kv_int8)
        specs = tp_param_specs_llama(axis)
        scale_specs = _llama_scale_specs(axis)
        shard_fn = tp_shard_params_llama
    else:
        raise ValueError(f"unknown family {family!r}")
    cspec = P(None, None, None, axis, None)
    cache_spec = {"k": cspec, "v": cspec, "pos": P()}
    if kv_int8:
        cache_spec.update(ks=cspec, vs=cspec)   # scales shard by head

    # Pre-shard the weights eagerly (once per server, not per call).
    sspecs = _specs_with_scales(specs, _scale_keys(params), scale_specs,
                                "TP serving")
    shardings = jax.tree.map(
        lambda sp: jax.sharding.NamedSharding(mesh, sp), sspecs,
        is_leaf=lambda x: isinstance(x, P))
    sharded = jax.jit(lambda p: shard_fn(p, cfg),
                      out_shardings=shardings)(params)

    def per_shard_prefill(params, tokens, last):
        # The 'one' cache is bucket-length: the scatter lands rows
        # [0, S_bucket) into the slot (serving.scatter_fn contract);
        # its pos entry is dropped (the scatter sets the slot's).
        logits, cache = ops_prefill(params, cfg, tokens,
                                    cap=tokens.shape[1],
                                    last_index=last)
        cache.pop("pos")
        return logits, cache

    one_spec = dict(cache_spec)
    one_spec.pop("pos")
    prefill_prog = jax.jit(shard_map(
        per_shard_prefill, mesh=mesh, in_specs=(sspecs, P(), P()),
        out_specs=(P(), one_spec), check_vma=False))

    def per_shard_step(params, cache, tok):
        def one(carry, _):
            cache, tok = carry
            logits, cache = ops_decode(params, cfg, cache, tok)
            nxt = jnp.argmax(logits, axis=-1).astype(tok.dtype)
            return (cache, nxt), nxt

        (cache, _), toks = lax.scan(one, (cache, tok), None,
                                    length=chunk)
        return cache, toks

    # Donate the slot caches: the host loop always proceeds with the
    # returned slots, and a non-donated [L, B, max_len, H, D] pair
    # would cost a full-cache copy per chunk on top of doubled peak
    # memory.
    step_prog = jax.jit(shard_map(
        per_shard_step, mesh=mesh,
        in_specs=(sspecs, cache_spec, P()),
        out_specs=(cache_spec, P()), check_vma=False),
        donate_argnums=(1,))

    def per_shard_scatter(slots, one, slot_idx, new_pos):
        def land(cache, src):
            dst = lax.dynamic_index_in_dim(cache, slot_idx, 1,
                                           keepdims=False)
            dst = lax.dynamic_update_slice(
                dst, src[:, 0], (0,) * dst.ndim)
            return lax.dynamic_update_index_in_dim(cache, dst,
                                                   slot_idx, 1)
        out = {k: land(slots[k], one[k]) for k in one}
        out["pos"] = slots["pos"].at[slot_idx].set(new_pos)
        return out

    scatter_prog = jax.jit(shard_map(
        per_shard_scatter, mesh=mesh,
        in_specs=(cache_spec, one_spec, P(), P()),
        out_specs=cache_spec, check_vma=False),
        donate_argnums=(0,))

    def prefill_fn(tokens, last):
        return prefill_prog(sharded, tokens, last)

    def step_fn(slots, tok, keys):
        slots, toks = step_prog(sharded, slots, tok)
        return slots, toks, keys

    def scatter_fn(slots, one, slot_idx, new_pos):
        return scatter_prog(slots, one, slot_idx, new_pos)

    return prefill_fn, step_fn, scatter_fn, chunk, kv_int8, None
