"""Quantized all-reduce over a mesh axis — bandwidth-compressed gradient
synchronization (after EQuARX, arXiv:2506.17615; see PAPERS.md).

Data-parallel gradient sync moves full f32 gradients over the wire every
step. This module implements the all-reduce as the standard ring
reduce-scatter + all-gather decomposition, but QUANTIZES every hop's
payload to int8 (symmetric, one f32 max-abs scale per 128-lane block) —
~4x less ICI/DCN traffic, at a bounded relative error: each hop
re-quantizes the partial sum at ~1/254 of its block max, so worst-case
elementwise error grows linearly in ring length (measured ~1.5% of the
result's max-norm on an 8-ring) while the mean error stays an order of
magnitude tighter (tests/test_quantized.py pins max < 2.5%, mean < 0.6%
— ~0.2% measured on 1024-element tensors).

Everything is SPMD inside ``shard_map``: the ring is ``lax.ppermute``
steps (int8 chunk + f32 scale riding together), chunk bookkeeping is
static Python over the (static) axis size, and the per-rank chunk index
is the only traced scalar — XLA sees a fixed schedule of n-1 sends per
phase, exactly like its native all-reduce, just narrower.

Use :func:`quantized_pmean` as a drop-in for ``lax.pmean`` on gradient
leaves when the dp axis rides a slow link (DCN cross-slice sync is the
EQuARX target); keep exact pmean when ICI is not the bottleneck. The
distributed train step exposes this as ``dp_quant_bits``
(mpi_acx_tpu.train.make_loss_and_grads).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


_BLOCK = 128   # lanes per quantization block (one f32 scale per block,
               # ~3% wire overhead; block-wise scales localize outliers —
               # the EQuARX design choice that keeps per-hop error tight)


def _quant(x: jax.Array, qmax: float):
    """Symmetric block-wise max-abs quantization: f32 [C] (C a multiple
    of _BLOCK) -> (int8 [C//B, B], f32 scales [C//B, 1])."""
    xb = x.reshape(-1, _BLOCK)
    s = jnp.max(jnp.abs(xb), axis=1, keepdims=True) / qmax
    s = jnp.where(s > 0, s, 1.0)
    q = jnp.clip(jnp.round(xb / s), -qmax, qmax).astype(jnp.int8)
    return q, s


def ring_psum(x: jax.Array, axis_name: str,
              quantize: bool = True) -> jax.Array:
    """Ring reduce-scatter + all-gather all-reduce (per-shard function).

    ``quantize=True`` sends every hop as int8 + per-block f32 scales
    (~4x less wire traffic, the EQuARX scheme); ``quantize=False`` sends
    raw f32 — the EXACT all-reduce on the IDENTICAL hop schedule, which
    is what bench.py's wire-byte comparison measures against (one
    skeleton, so the two variants cannot silently diverge).
    """
    n = lax.axis_size(axis_name)
    qmax = 127.0
    r = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % n) for i in range(n)]

    shape, size = x.shape, x.size
    c = -(-size // n)                                   # ceil chunk size
    c = -(-c // _BLOCK) * _BLOCK                        # round to blocks
    flat = jnp.zeros((n * c,), jnp.float32).at[:size].set(
        x.astype(jnp.float32).reshape(-1))
    acc = flat.reshape(n, c)

    def hop(chunk):
        """Encode, permute one step along the ring, decode -> [c] f32."""
        if not quantize:
            return lax.ppermute(chunk, axis_name, ring)
        q, s = _quant(chunk, qmax)
        q = lax.ppermute(q, axis_name, ring)
        s = lax.ppermute(s, axis_name, ring)
        return (q * s).reshape(-1)

    # -- reduce-scatter: n-1 hops; after step t, the chunk each rank
    # just accumulated holds t+2 ranks' contributions. Rank r ends
    # owning the fully reduced chunk (r + 1) mod n.
    for t in range(n - 1):
        si = (r - t) % n                                # traced index
        chunk = lax.dynamic_slice_in_dim(acc, si, 1, 0)[0]
        got = hop(chunk)
        ri = (r - t - 1) % n
        upd = lax.dynamic_slice_in_dim(acc, ri, 1, 0)[0] + got
        acc = lax.dynamic_update_slice_in_dim(acc, upd[None], ri, 0)

    owned = (r + 1) % n
    reduced = lax.dynamic_slice_in_dim(acc, owned, 1, 0)[0]

    # -- all-gather: every rank broadcasts its reduced chunk around the
    # ring, encoded ONCE (the owner also keeps the decode-of-encode
    # value so all ranks hold bit-identical results).
    if quantize:
        q, s = _quant(reduced, qmax)
        cur = (q * s).reshape(-1)
    else:
        q = s = None
        cur = reduced
    out = jnp.zeros((n, c), jnp.float32)
    out = lax.dynamic_update_slice_in_dim(out, cur[None], owned, 0)
    for t in range(1, n):
        if quantize:
            q = lax.ppermute(q, axis_name, ring)
            s = lax.ppermute(s, axis_name, ring)
            cur = (q * s).reshape(-1)
        else:
            cur = lax.ppermute(cur, axis_name, ring)
        idx = (r - t + 1) % n
        out = lax.dynamic_update_slice_in_dim(out, cur[None], idx, 0)

    return out.reshape(-1)[:size].reshape(shape)


def quantized_psum(x: jax.Array, axis_name: str, bits: int = 8) -> jax.Array:
    """All-reduce-sum of ``x`` over ``axis_name`` with int8-quantized ring
    hops (per-shard function — call inside shard_map). Returns f32 of
    ``x``'s shape, identical on every rank.

    bits: only 8 currently (int8 wire dtype); the parameter documents the
    knob the EQuARX design space exposes.
    """
    assert bits == 8, "int8 is the implemented wire format"
    n = lax.axis_size(axis_name)
    if n == 1:
        return x.astype(jnp.float32)
    if x.size < n * _BLOCK:
        # Small leaves (norm gains, biases): block padding + 2(n-1)
        # serialized hops would move MORE bytes at MORE latency than the
        # exact all-reduce — fall back to it (also exact, a bonus).
        return lax.psum(x.astype(jnp.float32), axis_name)
    return ring_psum(x, axis_name, quantize=True)


def quantized_pmean(x: jax.Array, axis_name: str, bits: int = 8):
    """Mean-reducing sibling of :func:`quantized_psum` (the gradient-sync
    drop-in for ``lax.pmean``)."""
    return quantized_psum(x, axis_name, bits) / lax.axis_size(axis_name)
