"""mpi_acx_tpu — a TPU-native accelerator-triggered communication framework.

A ground-up rebuild of the capabilities of NVIDIA/mpi-acx (stream/graph-
triggered MPI point-to-point and kernel-triggered partitioned communication;
reference README.md:1-7) designed for TPU:

* The **host plane** is the native C++ runtime in ``src/``: an atomic
  flag-slot table, a progress (proxy) thread, a socket data plane, a host
  execution-queue runtime, and the 17-function ``MPIX_*`` C API — reachable
  from Python through :mod:`mpi_acx_tpu.runtime` (ctypes).
* The **ICI plane** is pure JAX/XLA: collectives over a
  ``jax.sharding.Mesh`` (:mod:`mpi_acx_tpu.parallel`), partitioned
  (pipelined, per-partition-ready) exchanges, ring attention for sequence
  parallelism, and a collective-permute microbatch pipeline — the idiomatic
  TPU forms of the reference's enqueued and partitioned primitives
  (SURVEY.md §7.1 mapping table).
* :mod:`mpi_acx_tpu.models` provides transformer model families wired for
  dp/tp/pp/sp/ep execution on top of those primitives.
"""

from mpi_acx_tpu.version import __version__  # noqa: F401


def __getattr__(name):
    # Lazy subpackage imports: the host-plane path (`runtime`, pure
    # ctypes/numpy) must not pay for — or depend on — the JAX stack, which
    # matters when acxrun spawns N Python ranks.
    if name in ("parallel", "models", "runtime", "train", "checkpoint"):
        import importlib

        return importlib.import_module(f"mpi_acx_tpu.{name}")
    raise AttributeError(f"module 'mpi_acx_tpu' has no attribute '{name}'")
