"""Request-journey event log (ACX_REQLOG, docs/DESIGN.md §20).

The op-level planes (metrics registry, tseries, causal spans, flight
recorder) explain any single native op; this plane explains a
*request*: every serving loop (models/serving.py, models/disagg.py,
models/kvpage.py) appends one JSON line per lifecycle event —
admit/reject, queue, prefill, per-partition KV ship, page seat,
decode steps, preempt/resume, prefix hit, stream, finish — to
``<$ACX_REQLOG>.rank<r>.reqlog.jsonl``, keyed by request id and by
the PR-8 app span id (``span = rid + 1``, the same offset the serving
loops pass to ``acx_span_app_begin``), so tools/acx_request.py can
join journeys against trace ``req_op`` events.

Line schema (one JSON object per line, torn-tolerant like tseries):

  init line   {"init":true,"rank":r,"pid":...,"role":"...",
               "clock":"native"|"mono","schema":1,
               "t_mono_ns":...,"t_wall_ms":...}
  event line  {"k":<kind>,"t_mono_ns":...,"rid":...,"span":rid+1,
               ...kind-specific fields}

``t_mono_ns`` is trace::NowSinceStartNs (via acx_now_since_start_ns)
when the native runtime is loaded — the SAME per-rank timeline traces
and tseries stamp, so acx_trace_merge's barrier-anchored skew
correction applies verbatim. Without the native library (pure-Python
unit tests) it falls back to a process-local monotonic zero; the init
line's paired (t_mono_ns, t_wall_ms) reading then gives the offline
merge a wall-clock fallback anchor. The clock source is latched at
the first emit and recorded in the init line — one file never mixes
timelines.

Crash-tail survival mirrors src/core/tseries.cc: every line is
flushed as it is written, so the journey of a request in flight when
a rank dies survives up to (at most) one torn final line, which
readers skip and count.

Discipline: emitting must NEVER raise and never build or load the
native library (the ``_flight_dump_best_effort`` rule) — an
observability plane that can take the server down is worse than no
plane. With ACX_REQLOG unset, ``emit`` is one dict lookup and a
falsy return.
"""

from __future__ import annotations

import json
import os
import threading
import time

# The journey event-kind vocabulary. tools/acx_audit.py's
# ``journey_kinds`` rule pins three tables together: the literal kinds
# emitted by serving.py/disagg.py/kvpage.py, this set, and the decode
# table in tools/acx_request.py (KINDS) must agree exactly — an event
# kind the offline tool cannot decode is a schema bug, caught at lint
# time, not in a 3 a.m. incident merge.
KINDS = frozenset({
    "admit",          # request accepted by typed admission
    "reject",         # typed admission rejection (reason field)
    "queue",          # request enqueued on the scheduler queue
    "prefill_start",  # prompt pass begins (bucket field)
    "prefill_layer",  # one layer of a layerwise (disagg) prefill done
    "prefill_end",    # prompt pass done, first token known
    "ship_hdr",       # KV handoff descriptor header sent/received
    "ship_pready",    # one KV partition published to the wire
    "ship_fin",       # KV handoff FIN descriptor sent/received
    "seat",           # request seated in a cache slot (pages/scatter)
    "prefix_hit",     # radix prefix-cache prompt match
    "decode_step",    # one batched decode step (rid-less, batch-wide)
    "stream",         # tokens streamed to the request this step
    "preempt",        # request evicted by page pressure (requeued)
    "resume",         # a previously preempted request re-seated
    "requeue",        # failure-path restart (charged flag)
    "finish",         # request retired; terminal journey event
})

_SCHEMA = 1

_lock = threading.Lock()
_state = None        # None = unprobed, False = disabled, file = armed
_clock_native = False
_mono_zero = 0


def _now_ns() -> int:
    if _clock_native:
        try:
            import mpi_acx_tpu.runtime as _rt
            return int(_rt._lib.acx_now_since_start_ns())
        except Exception:
            pass
    return time.monotonic_ns() - _mono_zero


def _probe_clock() -> str:
    """Latch the timeline source for this process's reqlog: the native
    trace clock when the library is ALREADY loaded (never load it for
    telemetry), else a process-local monotonic zero."""
    global _clock_native, _mono_zero
    try:
        import mpi_acx_tpu.runtime as _rt
        if _rt._lib is not None:
            _clock_native = True
            return "native"
    except Exception:
        pass
    _mono_zero = time.monotonic_ns()
    return "mono"


def _rank() -> int:
    try:
        return int(os.environ.get("ACX_RANK", "0") or 0)
    except ValueError:
        return 0


def _armed():
    """Open (once) the per-rank journey file, or latch disabled."""
    global _state
    if _state is not None:
        return _state
    with _lock:
        if _state is not None:
            return _state
        prefix = os.environ.get("ACX_REQLOG", "").strip()
        if not prefix:
            _state = False
            return _state
        clock = _probe_clock()
        try:
            f = open(f"{prefix}.rank{_rank()}.reqlog.jsonl", "a")
            f.write(json.dumps({
                "init": True, "schema": _SCHEMA, "rank": _rank(),
                "pid": os.getpid(),
                "role": os.environ.get("ACX_ROLE", ""),
                "clock": clock, "t_mono_ns": _now_ns(),
                "t_wall_ms": int(time.time() * 1e3),
            }, separators=(",", ":")) + "\n")
            f.flush()
            _state = f
        except OSError:
            _state = False
    return _state


def enabled() -> bool:
    """True iff journey logging is armed for this process."""
    return bool(_armed())


def emit(kind: str, rid: int = -1, **fields) -> bool:
    """Append one journey event; returns True iff a line was written.
    Never raises (an unwritable line is dropped, not fatal) and
    flushes per line so a crashed rank's tail survives."""
    f = _armed()
    if not f:
        return False
    try:
        rec = {"k": kind, "t_mono_ns": _now_ns()}
        if rid >= 0:
            rec["rid"] = int(rid)
            rec["span"] = int(rid) + 1   # the PR-8 app span id offset
        rec.update(fields)
        with _lock:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
        return True
    except Exception:  # pragma: no cover — diagnostics must never raise
        return False


def _reset_for_tests() -> None:
    """Drop the armed/disabled latch so a test can re-point ACX_REQLOG.
    Test-only; production code never re-arms."""
    global _state, _clock_native, _mono_zero
    with _lock:
        if _state not in (None, False):
            try:
                _state.close()
            except Exception:
                pass
        _state = None
        _clock_native = False
        _mono_zero = 0
