"""Blockwise-causal flash attention as a Pallas TPU kernel.

The MXU hot op of every model family in :mod:`mpi_acx_tpu.models`. Online
softmax over key/value blocks (never materializes the [S, S] score matrix),
f32 accumulators, bf16-friendly matmuls with ``preferred_element_type`` so
both dots land on the MXU at full rate. Causal blocks above the diagonal
are skipped entirely (the inner loop's trip count is ``i + 1``), so the
kernel does ~half the FLOPs of the dense-mask reference implementation and
O(S) memory instead of O(S^2).

Differentiable: a ``jax.custom_vjp`` backward recomputes everything
blockwise from (q, k, v, o) in pure JAX — one streaming pass rebuilds the
row logsumexp, a second applies the standard flash-backward formulas
(dS = P * (dP - rowsum(dO*O))) — O(S * block_k) peak memory, so training
(e.g. make_train_step on long sequences) differentiates straight through
the Pallas call. (For plain :func:`flash_attention` the lse is recomputed
rather than emitted because multi-output pallas_call hangs the axon
remote-compile path; the extra QK sweep costs ~1/5 of the backward's
FLOPs and keeps the inference forward at zero overhead.)

:func:`flash_attention_lse` is the variant that DOES emit the row
logsumexp — packed into one extra lane column of a single output, so the
single-output constraint holds — and its backward reuses the emitted lse
and folds the lse cotangent into dS. It is the single-chip building block
of :func:`mpi_acx_tpu.parallel.ring_attention.ring_attention`: ring
attention rotates K/V shards around the mesh while each step runs exactly
this kernel on the resident shard and merges blocks by logaddexp.

Runs compiled on TPU; falls back to Pallas interpret mode elsewhere (the
CPU test mesh), same code path.

Two kernel variants share the math:
* resident (default below S=16384): whole K/V in VMEM per (batch, head)
  program — fastest at moderate S, but VMEM caps it near S=8192 on v5e;
* streaming (``streaming=True`` / auto at S>=16384): a fourth,
  sequential grid dimension feeds ONE double-buffered K/V tile per step
  with the online-softmax state in VMEM scratch — bit-identical output
  (verified on-chip), bounded by HBM instead of VMEM (S=32768 measured
  at 60 ms on v5e where the resident kernel cannot compile).
Beyond one chip, the sequence-parallel strategies (ring attention /
Ulysses) shard S across devices and call these kernels per shard.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax<0.5 exposes this as TPUCompilerParams; newer releases renamed it.
_CompilerParams = (getattr(pltpu, "CompilerParams", None)
                   or getattr(pltpu, "TPUCompilerParams"))

_NEG_INF = -1e30


def attention_reference(q, k, v, causal: bool = True):
    """Dense-mask reference attention, [B, S, H, D] layout; f32 softmax.
    Ground truth for the kernel's numerics tests."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(d)
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def auto_attention(q, k, v, causal: bool = True):
    """[B, S, H, D] attention with the kernel picked per shape: the Pallas
    flash kernel past its measured v5e crossover (S >= 1024; dense wins
    below — grid overhead), dense elsewhere. THE single definition of the
    flash/dense policy — the model layer and the sequence-parallel
    strategies all route through here."""
    S = q.shape[1]
    if jax.default_backend() == "tpu" and S >= 1024 and S % 128 == 0:
        return flash_attention(q, k, v, causal=causal)
    return attention_reference(q, k, v, causal=causal)


def select_attention(use_flash):
    """THE single flash/dense dispatch for a ``use_flash`` config field
    (both model families route here so the policy can't drift):
    ``None`` -> per-shape auto policy, ``True`` -> Pallas flash kernel,
    ``False`` -> dense reference. All returned callables take
    ``(q, k, v, causal=True)`` on [B, S, H, D]."""
    if use_flash is None:
        return auto_attention
    return flash_attention if use_flash else attention_reference


def _online_softmax_step(q, kb, vb, m, l, acc, row0, col0, masked, prec,
                         rows=None):
    """One flash block update, shared by the resident and streaming
    kernels AND the decode kernel in ops/flash_decode.py (BASELINE.md's
    bit-identical claim rests on this being THE single definition):
    scaled-q x K^T logits, optional causal mask with absolute row/col
    offsets, and the rescale-and-accumulate of the online-softmax state.
    Callers whose row positions are not affine in the row index (the
    decode kernel's ``pos + i // n_rep``) pass absolute ``rows``
    (broadcastable to ``s``) directly instead of ``row0``. Returns
    (m, l, acc)."""
    s = jax.lax.dot_general(
        q, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)  # [BQ, BK] f32
    if masked:
        if rows is None:
            rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = corr * acc + jax.lax.dot_general(
        p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)
    return m_new, l_new, acc_new


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k,
                  scale, causal, emit_lse=False):
    """One (batch, head, q-block) program: online softmax over k blocks.

    Causal masking is only evaluated on the blocks that straddle the
    diagonal; the (majority) fully-below-diagonal blocks run the unmasked
    fast loop. Dots run in the input dtype with f32 accumulation; for f32
    inputs the MXU is asked for HIGHEST precision (its default f32 path is
    bf16-pass multiplication, ~1e-2 absolute error — measured on v5e).

    With ``emit_lse`` the out block is f32 [block_q, D+1]: the normalized
    output in lanes [0, D) and the row logsumexp in lane D. Packing into
    ONE output keeps the kernel single-output (multi-output pallas_call
    hangs the axon remote-compile path; see module docstring)."""
    i = pl.program_id(2)
    prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    # Pre-scale q once instead of scaling every [BQ, BK] logit block.
    q = (q_ref[0, 0].astype(jnp.float32) * scale).astype(q_ref.dtype)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    def step(j, carry, masked):
        m, l, acc = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        return _online_softmax_step(q, kb, vb, m, l, acc, i * block_q,
                                    j * block_k, masked, prec)

    if causal:
        # K/V blocks [0, n_full) lie strictly below the diagonal for every
        # row of this q block; blocks [n_full, n_diag) straddle it.
        q_end = (i + 1) * block_q                        # first masked col
        n_full = i * block_q // block_k
        n_diag = (q_end + block_k - 1) // block_k
        carry = jax.lax.fori_loop(
            0, n_full, lambda j, c: step(j, c, masked=False), (m0, l0, acc0))
        m, l, acc = jax.lax.fori_loop(
            n_full, n_diag, lambda j, c: step(j, c, masked=True), carry)
    else:
        n_kv = k_ref.shape[2] // block_k
        m, l, acc = jax.lax.fori_loop(
            0, n_kv, lambda j, c: step(j, c, masked=False), (m0, l0, acc0))
    if emit_lse:
        lse = m + jnp.log(l)                             # [BQ, 1] f32
        o_ref[0, 0] = jnp.concatenate([acc / l, lse], axis=-1).astype(
            o_ref.dtype)
    else:
        o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct for a pallas_call output, carrying the union of the
    operands' varying-mesh-axes so the kernel can run inside a shard_map
    with check_vma=True (e.g. as ring attention's block primitive)."""
    try:
        vma = frozenset().union(*(jax.typeof(x).vma for x in operands))
    except Exception:
        vma = frozenset()
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _fit_blocks(S, block_q, block_k):
    """Shrink the requested block sizes to divisors of S. Returns
    ``(block_q, block_k)``, or ``None`` when S has no usable 128-multiple
    divisor (e.g. S=648) — callers fall back to the dense reference
    instead of crashing the model call."""
    def fit(block):
        b = min(block, S)
        while b > 128 and S % b:
            b -= 128
        return b

    bq, bk = fit(block_q), fit(block_k)
    if S % bq or S % bk:
        return None
    return bq, bk


_fallback_warned: set = set()


def _warn_dense_fallback(S, Sk):
    """One-time (per shape) warning that the flash kernel can't tile this
    sequence length and the dense reference is used instead."""
    key = (S, Sk)
    if key not in _fallback_warned:
        _fallback_warned.add(key)
        import warnings

        warnings.warn(
            f"flash_attention: no block size divides S={S}/Sk={Sk}; "
            "falling back to the dense reference for this shape",
            RuntimeWarning, stacklevel=3)


def _reference_lse(q, k, v, causal: bool = True):
    """Dense fallback for :func:`flash_attention_lse`: same contract
    (o [B, S, H, D], lse [B, H, S] f32), materialized logits."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(d)
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)   # [B, H, S] f32
    p = jnp.exp(logits - lse[..., None]).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v), lse


def _flash_fwd_impl(qt, kt, vt, causal, block_q, block_k):
    """Raw pallas call on [B, H, Sq, D] / [B, H, Sk, D] operands ->
    o [B, H, Sq, D] (Sk may differ from Sq in the non-causal case)."""
    B, H, S, D = qt.shape
    Sk = kt.shape[2]
    assert not causal or S == Sk, (S, Sk)
    scale = 1.0 / (D ** 0.5)
    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, scale=scale, causal=causal)
    return pl.pallas_call(
        kernel,
        grid=(B, H, S // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Sk, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Sk, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((B, H, S, D), qt.dtype, qt, kt, vt),
        interpret=jax.default_backend() != "tpu",
    )(qt, kt, vt)


def _flash_stream_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                         *, block_q, block_k, scale, causal, n_k):
    """One (batch, head, q-block, K-BLOCK) grid step of the STREAMING
    kernel: K/V arrive one [block_k, D] tile per step (Mosaic
    double-buffers the tile DMA against compute), and the online-softmax
    state (m, l, acc) lives in VMEM scratch across the sequential k
    dimension. Unlike _flash_kernel, VMEM holds only one K/V tile — no
    whole-sequence residency, so S is bounded by HBM, not VMEM."""
    i = pl.program_id(2)
    j = pl.program_id(3)
    prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: K blocks strictly above the diagonal contribute nothing —
    # skip their FLOPs entirely (their DMA is pipelined regardless).
    visible = (jnp.bool_(True) if not causal
               else j * block_k <= i * block_q + block_q - 1)

    @pl.when(visible)
    def _compute():
        q = (q_ref[0, 0].astype(jnp.float32) * scale).astype(q_ref.dtype)
        m_new, l_new, acc_new = _online_softmax_step(
            q, k_ref[0, 0], v_ref[0, 0], m_scr[:], l_scr[:], acc_scr[:],
            i * block_q, j * block_k, causal, prec)
        m_scr[:] = m_new
        l_scr[:] = l_new
        acc_scr[:] = acc_new

    @pl.when(j == n_k - 1)
    def _finish():
        o_ref[0, 0] = (acc_scr[:] / l_scr[:]).astype(o_ref.dtype)


def _flash_stream_fwd_impl(qt, kt, vt, causal, block_q, block_k):
    """Raw streaming pallas call on [B, H, S, D] operands."""
    B, H, S, D = qt.shape
    Sk = kt.shape[2]
    assert not causal or S == Sk, (S, Sk)
    n_k = Sk // block_k
    scale = 1.0 / (D ** 0.5)
    kernel = functools.partial(_flash_stream_kernel, block_q=block_q,
                               block_k=block_k, scale=scale, causal=causal,
                               n_k=n_k)
    return pl.pallas_call(
        kernel,
        grid=(B, H, S // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((B, H, S, D), qt.dtype, qt, kt, vt),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=jax.default_backend() != "tpu",
    )(qt, kt, vt)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(qt, kt, vt, causal, block_q, block_k, streaming=False):
    if streaming:
        return _flash_stream_fwd_impl(qt, kt, vt, causal, block_q, block_k)
    return _flash_fwd_impl(qt, kt, vt, causal, block_q, block_k)


def _flash_vjp_fwd(qt, kt, vt, causal, block_q, block_k, streaming=False):
    o = _flash(qt, kt, vt, causal, block_q, block_k, streaming)
    return o, (qt, kt, vt, o)


def _flash_bwd_blockwise(qt, kt, vt, o, do, causal, block_q, block_k,
                         lse=None, dlse=None):
    """Blockwise flash backward in pure JAX ([B, H, S, D] operands).

    Outer scan over q blocks; for each, an inner fori_loop over exactly
    the k blocks at-or-below the diagonal (causal skips the rest, like the
    forward kernel) first rebuilds that q block's row logsumexp (skipped
    when the forward emitted ``lse`` [B, H, S]), then applies the standard
    flash-backward formulas:
      dV_j += P_j^T dO;  dP_j = dO V_j^T;  D = rowsum(dO * O)
      dS_j = P_j * (dP_j - D + dLSE) * scale;  dQ += dS_j K_j;  dK_j += dS_j^T Q
    (the dLSE term is the cotangent of an emitted lse output: d lse_i /
    d s_ij = P_ij). Peak extra memory is [B, H, block_q, block_k] per step.
    """
    B, H, S, Dh = qt.shape
    Sk = kt.shape[2]
    scale = 1.0 / (Dh ** 0.5)
    k32 = kt.astype(jnp.float32)
    v32 = vt.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    Drow = jnp.sum(do32 * o.astype(jnp.float32), axis=-1)      # [B,H,S]

    def qblock(carry, i):
        dk_acc, dv_acc = carry
        q0 = i * block_q
        qb = jax.lax.dynamic_slice_in_dim(
            qt, q0, block_q, axis=2).astype(jnp.float32)       # [B,H,bq,D]
        dob = jax.lax.dynamic_slice_in_dim(do32, q0, block_q, axis=2)
        Db = jax.lax.dynamic_slice_in_dim(Drow, q0, block_q, axis=2)
        rows = q0 + jnp.arange(block_q)[:, None]               # [bq, 1]
        if causal:
            # k blocks [0, n_kv) contain at least one unmasked column for
            # this q block (same bound as the forward kernel's n_diag).
            n_kv = (q0 + block_q + block_k - 1) // block_k
        else:
            n_kv = Sk // block_k

        def logits(j):
            kb = jax.lax.dynamic_slice_in_dim(k32, j * block_k, block_k,
                                              axis=2)
            s = jnp.einsum("bhqd,bhkd->bhqk", qb, kb) * scale
            if causal:
                cols = j * block_k + jnp.arange(block_k)[None, :]
                s = jnp.where((rows >= cols)[None, None], s, _NEG_INF)
            return s, kb

        if lse is not None:
            lse_b = jax.lax.dynamic_slice_in_dim(lse, q0, block_q, axis=2)
        else:
            def lse_step(j, carry):
                m, l = carry
                s, _ = logits(j)
                m_new = jnp.maximum(m, jnp.max(s, axis=-1))
                l = l * jnp.exp(m - m_new) + jnp.sum(
                    jnp.exp(s - m_new[..., None]), axis=-1)
                return m_new, l

            m0 = jnp.full((B, H, block_q), _NEG_INF, jnp.float32)
            m, l = jax.lax.fori_loop(0, n_kv, lse_step,
                                     (m0, jnp.zeros_like(m0)))
            lse_b = m + jnp.log(l)                             # [B,H,bq]

        rowterm = Db[..., None]
        if dlse is not None:
            dlse_b = jax.lax.dynamic_slice_in_dim(
                dlse.astype(jnp.float32), q0, block_q, axis=2)
            rowterm = rowterm - dlse_b[..., None]

        def grad_step(j, carry):
            dq_b, dk_acc, dv_acc = carry
            s, kb = logits(j)
            p = jnp.exp(s - lse_b[..., None])                  # [B,H,bq,bk]
            vb = jax.lax.dynamic_slice_in_dim(v32, j * block_k, block_k,
                                              axis=2)
            dv_j = jnp.einsum("bhqk,bhqd->bhkd", p, dob)
            dp = jnp.einsum("bhqd,bhkd->bhqk", dob, vb)
            ds = p * (dp - rowterm) * scale
            dq_b = dq_b + jnp.einsum("bhqk,bhkd->bhqd", ds, kb)
            dk_j = jnp.einsum("bhqk,bhqd->bhkd", ds, qb)

            def acc(a, upd, j=j):
                cur = jax.lax.dynamic_slice_in_dim(a, j * block_k, block_k,
                                                   axis=2)
                return jax.lax.dynamic_update_slice_in_dim(
                    a, cur + upd, j * block_k, axis=2)

            return dq_b, acc(dk_acc, dk_j), acc(dv_acc, dv_j)

        dq_b0 = jnp.zeros((B, H, block_q, Dh), jnp.float32)
        dq_b, dk_acc, dv_acc = jax.lax.fori_loop(
            0, n_kv, grad_step, (dq_b0, dk_acc, dv_acc))
        return (dk_acc, dv_acc), dq_b

    zeros = jnp.zeros((B, H, Sk, Dh), jnp.float32)
    (dk, dv), dq_blocks = jax.lax.scan(qblock, (zeros, zeros),
                                       jnp.arange(S // block_q))
    # [n_q, B, H, bq, D] -> [B, H, S, D]
    dq = jnp.moveaxis(dq_blocks, 0, 2).reshape(B, H, S, Dh)
    return dq.astype(qt.dtype), dk.astype(kt.dtype), dv.astype(vt.dtype)


def _flash_vjp_bwd(causal, block_q, block_k, streaming, res, do):
    # The blockwise backward is kernel-independent (pure JAX recompute),
    # so resident and streaming forwards share it.
    qt, kt, vt, o = res
    return _flash_bwd_blockwise(qt, kt, vt, o, do, causal, block_q, block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# -- LSE-emitting variant (the building block of ring attention) -----------


def _flash_lse_fwd_impl(qt, kt, vt, causal, block_q, block_k):
    """Packed pallas call on [B, H, Sq, D] / [B, H, Sk, D] operands ->
    f32 [B, H, Sq, D+1] (normalized output ‖ row logsumexp). Sk may differ
    from Sq in the non-causal case (ring/cross blocks). Single output on
    purpose — see _flash_kernel."""
    B, H, S, D = qt.shape
    Sk = kt.shape[2]
    assert not causal or S == Sk, (S, Sk)
    scale = 1.0 / (D ** 0.5)
    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, scale=scale, causal=causal,
                               emit_lse=True)
    packed = pl.pallas_call(
        kernel,
        grid=(B, H, S // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Sk, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Sk, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D + 1),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((B, H, S, D + 1), jnp.float32, qt, kt, vt),
        interpret=jax.default_backend() != "tpu",
    )(qt, kt, vt)
    return packed[..., :D].astype(qt.dtype), packed[..., D]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_lse(qt, kt, vt, causal, block_q, block_k):
    return _flash_lse_fwd_impl(qt, kt, vt, causal, block_q, block_k)


def _flash_lse_vjp_fwd(qt, kt, vt, causal, block_q, block_k):
    o, lse = _flash_lse_fwd_impl(qt, kt, vt, causal, block_q, block_k)
    return (o, lse), (qt, kt, vt, o, lse)


def _flash_lse_vjp_bwd(causal, block_q, block_k, res, cts):
    do, dlse = cts
    qt, kt, vt, o, lse = res
    return _flash_bwd_blockwise(qt, kt, vt, o, do, causal, block_q, block_k,
                                lse=lse, dlse=dlse)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "streaming"))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512, streaming: bool | None = None):
    """Flash attention, [B, S, H, D] in / [B, S, H, D] out. Differentiable
    (custom VJP; see module docstring).

    D rides the lane dimension as-is (Mosaic handles sub-128 lane widths;
    padding to 128 would double both FLOPs and HBM traffic for the common
    D=64). Block sizes shrink to the largest divisor of S when S isn't a
    multiple of the requested block. On a real TPU, S must be a multiple
    of 128 (Mosaic tiling; ``auto_attention`` guards this) — interpret
    mode (any non-TPU backend) accepts any S that divides by 8.

    ``streaming`` selects the k-grid kernel that holds only ONE K/V tile
    in VMEM (double-buffered DMA) instead of the whole K/V — required
    past the resident kernel's ~S=8k VMEM ceiling. ``None`` -> auto: on
    for S >= 16384.
    """
    B, S, H, D = q.shape
    Sk = k.shape[1]
    if streaming is None:
        streaming = Sk >= 16384
    fit_q = _fit_blocks(S, block_q, block_k)
    fit_k = _fit_blocks(Sk, block_q, block_k)
    if fit_q is None or fit_k is None:
        _warn_dense_fallback(S, Sk)
        return attention_reference(q, k, v, causal=causal)
    block_q, block_k = fit_q[0], fit_k[1]

    def to_bhsd(x):
        return jnp.transpose(x, (0, 2, 1, 3))            # [B, H, S, D]

    out = _flash(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal, block_q,
                 block_k, streaming)
    return jnp.transpose(out, (0, 2, 1, 3))              # [B, S, H, D]


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def flash_attention_lse(q, k, v, causal: bool = True, block_q: int = 512,
                        block_k: int = 512):
    """Flash attention that also returns the row logsumexp — the merge
    state sequence-parallel strategies need. [B, S, H, D] in; returns
    ``(o [B, S, H, D], lse [B, H, S] f32)`` where ``lse[b,h,s] =
    log sum_k exp(q·k/sqrt(D))`` over the visible keys. Two partial
    results merge exactly:
      ``lse = logaddexp(lse1, lse2); o = o1*exp(lse1-lse) + o2*exp(lse2-lse)``
    Differentiable in both outputs (custom VJP; the backward reuses the
    emitted lse instead of recomputing it, and folds the lse cotangent
    into dS — see _flash_bwd_blockwise). Same shape rules as
    :func:`flash_attention`, except K/V sequence length may differ from
    Q's in the non-causal case (ring/cross attention blocks).
    """
    B, S, H, D = q.shape
    Sk = k.shape[1]
    fit_q = _fit_blocks(S, block_q, block_k)
    fit_k = _fit_blocks(Sk, block_q, block_k)
    if fit_q is None or fit_k is None:
        _warn_dense_fallback(S, Sk)
        return _reference_lse(q, k, v, causal=causal)
    block_q, block_k = fit_q[0], fit_k[1]

    def to_bhsd(x):
        return jnp.transpose(x, (0, 2, 1, 3))            # [B, H, S, D]

    o, lse = _flash_lse(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal,
                        block_q, block_k)
    return jnp.transpose(o, (0, 2, 1, 3)), lse           # [B, S, H, D]
