"""Blockwise-causal flash attention as a Pallas TPU kernel.

The MXU hot op of every model family in :mod:`mpi_acx_tpu.models`. Online
softmax over key/value blocks (never materializes the [S, S] score matrix),
f32 accumulators, bf16-friendly matmuls with ``preferred_element_type`` so
both dots land on the MXU at full rate. Causal blocks above the diagonal
are skipped entirely (the inner loop's trip count is ``i + 1``), so the
kernel does ~half the FLOPs of the dense-mask reference implementation and
O(S) memory instead of O(S^2).

This is also the single-chip building block of
:func:`mpi_acx_tpu.parallel.ring_attention.ring_attention`: ring attention
rotates K/V shards around the mesh while each step runs exactly this
blockwise inner kernel on the resident shard.

Runs compiled on TPU; falls back to Pallas interpret mode elsewhere (the
CPU test mesh), same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def attention_reference(q, k, v, causal: bool = True):
    """Dense-mask reference attention, [B, S, H, D] layout; f32 softmax.
    Ground truth for the kernel's numerics tests."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(d)
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def auto_attention(q, k, v, causal: bool = True):
    """[B, S, H, D] attention with the kernel picked per shape: the Pallas
    flash kernel past its measured v5e crossover (S >= 1024; dense wins
    below — grid overhead), dense elsewhere. THE single definition of the
    flash/dense policy — the model layer and the sequence-parallel
    strategies all route through here."""
    S = q.shape[1]
    if jax.default_backend() == "tpu" and S >= 1024 and S % 128 == 0:
        return flash_attention(q, k, v, causal=causal)
    return attention_reference(q, k, v, causal=causal)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k, scale,
                  causal):
    """One (batch, head, q-block) program: online softmax over k blocks.

    Causal masking is only evaluated on the blocks that straddle the
    diagonal; the (majority) fully-below-diagonal blocks run the unmasked
    fast loop. Dots run in the input dtype with f32 accumulation; for f32
    inputs the MXU is asked for HIGHEST precision (its default f32 path is
    bf16-pass multiplication, ~1e-2 absolute error — measured on v5e)."""
    i = pl.program_id(2)
    prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    # Pre-scale q once instead of scaling every [BQ, BK] logit block.
    q = (q_ref[0, 0].astype(jnp.float32) * scale).astype(q_ref.dtype)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    def step(j, carry, masked):
        m, l, acc = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                              # [BQ, BK] f32
        if masked:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = corr * acc + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)
        return m_new, l_new, acc_new

    if causal:
        # K/V blocks [0, n_full) lie strictly below the diagonal for every
        # row of this q block; blocks [n_full, n_diag) straddle it.
        q_end = (i + 1) * block_q                        # first masked col
        n_full = i * block_q // block_k
        n_diag = (q_end + block_k - 1) // block_k
        carry = jax.lax.fori_loop(
            0, n_full, lambda j, c: step(j, c, masked=False), (m0, l0, acc0))
        m, l, acc = jax.lax.fori_loop(
            n_full, n_diag, lambda j, c: step(j, c, masked=True), carry)
    else:
        n_kv = k_ref.shape[2] // block_k
        m, l, acc = jax.lax.fori_loop(
            0, n_kv, lambda j, c: step(j, c, masked=False), (m0, l0, acc0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512):
    """Flash attention, [B, S, H, D] in / [B, S, H, D] out.

    D rides the lane dimension as-is (Mosaic handles sub-128 lane widths;
    padding to 128 would double both FLOPs and HBM traffic for the common
    D=64). Block sizes shrink to the largest divisor of S when S isn't a
    multiple of the requested block (S itself must divide by 128, or be
    smaller than 128 entirely).
    """
    B, S, H, D = q.shape

    def fit(block):
        b = min(block, S)
        while b > 128 and S % b:
            b -= 128
        return b

    block_q, block_k = fit(block_q), fit(block_k)
    assert S % block_q == 0 and S % block_k == 0, (S, block_q, block_k)
    scale = 1.0 / (D ** 0.5)

    def to_bhsd(x):
        return jnp.transpose(x, (0, 2, 1, 3))            # [B, H, S, D]

    qt, kt, vt = to_bhsd(q), to_bhsd(k), to_bhsd(v)

    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, scale=scale, causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(B, H, S // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=jax.default_backend() != "tpu",
    )(qt, kt, vt)

    return jnp.transpose(out, (0, 2, 1, 3))              # [B, S, H, D]
