"""Int8 KV-cache quantization for long-context decode.

The decode step streams two tensors from HBM every token: the weights
(halved by ops/wquant.py) and the KV cache. At short max_len the
weights dominate, but the cache grows linearly with context — at
GPT-2-125M geometry, B=8 x max_len=4096 is ~1.2 GB bf16, several times
the weight stream — so long-context serving is KV-bandwidth-bound and
int8 codes halve the dominant term.

Scheme: symmetric per-(position, head) scales — each cached K/V vector
[head_dim] gets one f32 scale (amax/127), stored in a parallel
[..., 1] buffer. Quantization happens at WRITE time (one new vector
per step; the prompt bulk at prefill). At READ time the codes are NOT
dequantized to HBM — there are two read paths, both keeping int8 as
the only HBM-resident form. The dense path
(decoding.dense_decode_attend) keeps the int8 buffers as the attention
einsums' operands and applies K's scales to the logits and V's to the
probabilities (scale-on-scores factoring). The flash path
(ops/flash_decode.py, the default on TPU for long caches) DMAs each
live int8 block into VMEM and dequantizes IN REGISTER against the
per-position scales before the dot — algebraically the same factoring
(sum_d q_d*(K_kd*s_k) == (sum_d q_d*K_kd)*s_k), with the added
length-aware win that dead blocks never cross the wire at all. What
is never done: dequantizing the full cache slice before attending.
The first design did, betting XLA would fuse the convert+mul into the
einsum's operand read the way it does for int8 weights (wquant.py) —
the r05 chip A/B measured that at 0.73x the bf16 baseline (XLA
materializes the dequantized [B, S, H, D] tensor in HBM: int8 read +
bf16 write + bf16 read).

Integration: decoding.decode_layer_scan carries the scale buffers and
the per-family caches gain "ks"/"vs" entries (transformer.init_kv_cache
/ llama.init_kv_cache with ``kv_int8=True``); attend_fns receive
``(codes, scales)`` tuples that grouped_decode_attend consumes. The
reference has no serving stack (SURVEY.md SS0); this serves the
framework goal's perf axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def kv_quant(x: jax.Array):
    """[..., D] -> (int8 codes [..., D], f32 scales [..., 1]):
    symmetric per-vector quantization over the feature axis."""
    a = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    s = jnp.maximum(a, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s.astype(jnp.float32)


def kv_dequant(q: jax.Array, s: jax.Array, dtype) -> jax.Array:
    """Reconstruct [..., D] in compute dtype. NOT on the decode hot
    path (see module docstring — materializing this tensor was the
    0.73x regression); kept as the scheme's reference reconstruction
    for tests and offline use."""
    return (q.astype(jnp.float32) * s).astype(dtype)
