"""Memory-bounded cross-entropy: online-logsumexp over vocab chunks.

The [tokens, vocab] logits tensor is the largest single allocation in a
training step (GPT-2 bench shape B=8 S=512, V=50257: ~0.8 GB in f32 —
bigger than the model). This op computes the per-token target
log-likelihood ``logit[target] - logsumexp(logits)`` WITHOUT ever
materializing the full logits:

* forward — one ``lax.scan`` over vocab chunks carrying the running
  (max, sumexp, target-logit) triple; peak extra memory is one
  [T, chunk] tile.
* backward — ``jax.custom_vjp``: residuals are just (h, head, lse, g)
  — O(T) beyond the inputs — and a second scan recomputes each chunk's
  logits to form the softmax cotangents tile by tile. This is the
  flash-attention trade applied to the unembedding: recompute a tile
  instead of storing the O(T·V) intermediate.

Exact up to float summation order (tests pin values and gradients
against the naive log_softmax path at 1e-6 and the compiled temp
memory at a fraction of the naive step's).

The reference has no model stack at all (SURVEY.md §0); this is TPU
framework territory — the same trick "How to Scale Your Model"-style
recipes assume for large-vocab training.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG = float(jnp.finfo(jnp.float32).min)


def _pad_head(head: jax.Array, chunk: int):
    V = head.shape[0]
    pad = (-V) % chunk
    if pad:
        head = jnp.pad(head, ((0, pad), (0, 0)))
    return head, V, (V + pad) // chunk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_xent_ll(h: jax.Array, head: jax.Array, targets: jax.Array,
                    chunk: int = 8192) -> jax.Array:
    """Per-token target log-likelihood, vocab processed ``chunk`` rows
    at a time. ``h`` [T, d] (any float dtype; compute in f32),
    ``head`` [V, d], ``targets`` [T] int. Returns [T] f32 equal to
    ``log_softmax(h @ head.T)[t, targets[t]]``."""
    ll, _ = _xent_fwd_scan(h, head, targets, chunk)
    return ll


def _xent_fwd_scan(h, head, targets, chunk):
    T = h.shape[0]
    h32 = h.astype(jnp.float32)
    headp, V, n_chunks = _pad_head(head, chunk)
    lanes = jnp.arange(chunk)

    def body(carry, c0):
        m, s, tl = carry
        head_c = lax.dynamic_slice_in_dim(headp, c0, chunk, 0)
        lg = h32 @ head_c.astype(jnp.float32).T            # [T, chunk]
        lg = jnp.where((c0 + lanes)[None, :] < V, lg, _NEG)
        cm = jnp.max(lg, axis=-1)
        nm = jnp.maximum(m, cm)
        s = s * jnp.exp(m - nm) + jnp.sum(jnp.exp(lg - nm[:, None]), -1)
        idx = targets - c0
        inb = jnp.logical_and(idx >= 0, idx < chunk)
        got = jnp.take_along_axis(
            lg, jnp.clip(idx, 0, chunk - 1)[:, None], 1)[:, 0]
        tl = tl + jnp.where(inb, got, 0.0)
        return (nm, s, tl), None

    init = (jnp.full((T,), _NEG, jnp.float32),
            jnp.zeros((T,), jnp.float32), jnp.zeros((T,), jnp.float32))
    (m, s, tl), _ = lax.scan(
        body, init, jnp.arange(n_chunks, dtype=jnp.int32) * chunk)
    lse = m + jnp.log(s)
    return tl - lse, lse


def _xent_fwd(h, head, targets, chunk):
    ll, lse = _xent_fwd_scan(h, head, targets, chunk)
    return ll, (h, head, targets, lse)


def _xent_bwd(chunk, res, g):
    h, head, targets, lse = res
    T, d = h.shape
    h32 = h.astype(jnp.float32)
    headp, V, n_chunks = _pad_head(head, chunk)
    lanes = jnp.arange(chunk)

    def body(carry, c0):
        dh, dheadp = carry
        head_c = lax.dynamic_slice_in_dim(headp, c0, chunk, 0)
        lg = h32 @ head_c.astype(jnp.float32).T            # recompute tile
        valid = (c0 + lanes)[None, :] < V
        p = jnp.where(valid, jnp.exp(lg - lse[:, None]), 0.0)
        idx = targets - c0
        onehot = jnp.logical_and(idx[:, None] == lanes[None, :],
                                 valid).astype(jnp.float32)
        coef = (onehot - p) * g[:, None]                   # [T, chunk]
        dh = dh + coef @ head_c.astype(jnp.float32)
        # coef is already zero on padded lanes, so dhead rows past V
        # stay zero and the final [:V] trim is exact.
        dhead_c = coef.T @ h32                             # [chunk, d]
        dheadp = lax.dynamic_update_slice_in_dim(dheadp, dhead_c, c0, 0)
        return (dh, dheadp), None

    init = (jnp.zeros((T, d), jnp.float32),
            jnp.zeros_like(headp, dtype=jnp.float32))
    (dh, dheadp), _ = lax.scan(
        body, init, jnp.arange(n_chunks, dtype=jnp.int32) * chunk)
    return (dh.astype(h.dtype), dheadp[:V].astype(head.dtype), None)


chunked_xent_ll.defvjp(_xent_fwd, _xent_bwd)
