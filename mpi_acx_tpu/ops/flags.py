"""Device-side partition signaling as Pallas TPU kernels.

The reference lets a *running CUDA kernel* participate in the partitioned
communication state machine through two ``__host__ __device__`` functions:

* ``MPIX_Pready(p, req)`` — store ``PENDING`` into the flag word for
  partition ``p`` (reference partitioned.cu:200-212, a raw store into
  host-mapped memory: ``preq->flags[preq->idx[p]] = MPIACX_OP_STATE_PENDING``);
* ``MPIX_Parrived(req, p, &flag)`` — read the flag word, true iff
  ``COMPLETED`` (partitioned.cu:215-231).

TPU kernels cannot dereference host pointers, so the TPU-native form keeps
the flag table in an **HBM int32 buffer** and expresses both operations as
Pallas kernels over it (SURVEY.md §7.1: "device side: Pallas kernel doing a
DMA store to / copy-poll of a flag buffer"). The state values are the
shared protocol constants of the whole framework (include/acx/state.h,
reference mpi-acx-internal.h:196-203), so a flag buffer produced here can
be mirrored to the host page the native proxy polls.

Functional form: every mutator returns the updated flag buffer (donated /
aliased, so XLA performs the update in place in HBM). ``jit``-compatible,
static-shaped; runs compiled on TPU and interpreted on CPU meshes.

The deadlock rule from the reference (README.md:152-159: a single kernel
that both marks partitions ready and polls arrivals can deadlock) is
preserved structurally: ``pready*`` and ``parrived*`` are separate kernels,
and ``parrived`` is a non-blocking poll — there is no blocking wait
primitive on purpose.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Op states — the wire protocol shared with the native runtime
# (include/acx/state.h; reference mpi-acx-internal.h:196-203).
AVAILABLE = 0
RESERVED = 1
PENDING = 2
ISSUED = 3
COMPLETED = 4
CLEANUP = 5

_LANE = 128
_MIN_ROWS = 8  # int32 min tile is (8, 128)


def _interpret() -> bool:
    # Compiled Mosaic kernels need a real TPU; everywhere else (the CPU
    # test mesh, the driver's virtual-device dryrun) use interpret mode.
    return jax.default_backend() != "tpu"


def _padded(flags: jax.Array):
    """Reshape a 1-D int32 flag table to the 2-D (rows, 128) layout the VPU
    wants, padding to the (8, 128) int32 min tile. Returns (2-D array, n)."""
    n = flags.shape[0]
    rows = max(_MIN_ROWS, -(-n // _LANE))
    pad = rows * _LANE - n
    if pad:
        flags = jnp.pad(flags, (0, pad))
    return flags.reshape(rows, _LANE), n


def _linear_ids(shape):
    r = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    c = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return r * _LANE + c


def _pready_kernel(idx_ref, flags_ref, out_ref):
    lin = _linear_ids(flags_ref.shape)
    out_ref[:] = jnp.where(lin == idx_ref[0, 0], PENDING, flags_ref[:])


def pready(flags: jax.Array, idx: jax.Array | int) -> jax.Array:
    """Mark the flag slot `idx` PENDING from device code.

    TPU-native ``MPIX_Pready`` (reference partitioned.cu:200-212): the
    whole-table masked select compiles to one VPU pass over the table —
    no scalar scatter, no host round trip. Returns the updated table
    (input donated: in-place in HBM under jit).
    """
    f2, n = _padded(flags)
    idx = jnp.asarray(idx, jnp.int32).reshape(1, 1)
    out = pl.pallas_call(
        _pready_kernel,
        out_shape=jax.ShapeDtypeStruct(f2.shape, jnp.int32),
        in_specs=[
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        input_output_aliases={1: 0},
        interpret=_interpret(),
    )(idx, f2)
    return out.reshape(-1)[:n]


def _pready_many_kernel(idxs_ref, flags_ref, out_ref):
    lin = _linear_ids(flags_ref.shape)
    k = idxs_ref.shape[1]

    def body(i, cur):
        return jnp.where(lin == idxs_ref[0, i], PENDING, cur)

    out_ref[:] = jax.lax.fori_loop(0, k, body, flags_ref[:])


def pready_many(flags: jax.Array, idxs: jax.Array) -> jax.Array:
    """Mark several slots PENDING in one kernel (the ``mark_ready<<<1,N>>>``
    launch of reference ring-partitioned.cu:38-40, collapsed into a single
    vector pass)."""
    f2, n = _padded(flags)
    idxs = jnp.asarray(idxs, jnp.int32).reshape(1, -1)
    out = pl.pallas_call(
        _pready_many_kernel,
        out_shape=jax.ShapeDtypeStruct(f2.shape, jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        input_output_aliases={1: 0},
        interpret=_interpret(),
    )(idxs, f2)
    return out.reshape(-1)[:n]


def _parrived_kernel(idx_ref, flags_ref, out_ref):
    lin = _linear_ids(flags_ref.shape)
    word = jnp.sum(jnp.where(lin == idx_ref[0, 0], flags_ref[:], 0))
    out_ref[0, 0] = (word == COMPLETED).astype(jnp.int32)


def parrived(flags: jax.Array, idx: jax.Array | int) -> jax.Array:
    """Non-blocking poll: is slot `idx` COMPLETED? Returns a 0/1 int32
    scalar (TPU-native ``MPIX_Parrived``, reference partitioned.cu:215-231)."""
    f2, _ = _padded(flags)
    idx = jnp.asarray(idx, jnp.int32).reshape(1, 1)
    out = pl.pallas_call(
        _parrived_kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        in_specs=[
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=_interpret(),
    )(idx, f2)
    return out[0, 0]


def _parrived_all_kernel(idxs_ref, flags_ref, out_ref):
    lin = _linear_ids(flags_ref.shape)
    k = idxs_ref.shape[1]

    def body(i, acc):
        word = jnp.sum(jnp.where(lin == idxs_ref[0, i], flags_ref[:], 0))
        return jnp.logical_and(acc, word == COMPLETED)

    done = jax.lax.fori_loop(0, k, body, jnp.bool_(True))
    out_ref[0, 0] = done.astype(jnp.int32)


def parrived_all(flags: jax.Array, idxs: jax.Array) -> jax.Array:
    """Poll a set of slots; 1 iff every one is COMPLETED (the condition the
    ``wait_until_arrived`` spin of ring-partitioned.cu:42-47 waits for —
    exposed as a poll, never a device-side spin: see module docstring)."""
    f2, _ = _padded(flags)
    idxs = jnp.asarray(idxs, jnp.int32).reshape(1, -1)
    out = pl.pallas_call(
        _parrived_all_kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=_interpret(),
    )(idxs, f2)
    return out[0, 0]


def produce_and_pready(
    produce: Callable[[jax.Array], jax.Array],
    x: jax.Array,
    flags: jax.Array,
    idx: jax.Array | int,
) -> tuple[jax.Array, jax.Array]:
    """Fused produce-then-signal: one kernel computes a partition's payload
    and marks its flag PENDING — the pattern the reference's partitioned
    API exists for ("a kernel marks partitions of a message ready as it
    produces them", reference README.md:60-66). The flag store is in the
    same kernel as the payload store, so readiness is published with the
    data, with no separate launch between them.

    ``produce`` is any shape-preserving traced function of the payload
    block (runs on VPU/MXU in VMEM). ``x`` must be 2-D and tile-aligned.
    Returns ``(payload, updated_flags)``.
    """
    f2, n = _padded(flags)
    idx = jnp.asarray(idx, jnp.int32).reshape(1, 1)

    def kernel(idx_ref, x_ref, flags_ref, payload_ref, fout_ref):
        payload_ref[:] = produce(x_ref[:])
        lin = _linear_ids(flags_ref.shape)
        fout_ref[:] = jnp.where(lin == idx_ref[0, 0], PENDING, flags_ref[:])

    payload, fout = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct(f2.shape, jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        input_output_aliases={2: 1},
        interpret=_interpret(),
    )(idx, x, f2)
    return payload, fout.reshape(-1)[:n]
