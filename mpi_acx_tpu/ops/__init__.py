"""Pallas TPU kernels — the device-side half of the framework.

The reference exposes two `__device__` functions a CUDA kernel can call
mid-execution: ``MPIX_Pready`` (store PENDING into a host-mapped flag word,
reference partitioned.cu:200-212) and ``MPIX_Parrived`` (poll a flag word
for COMPLETED, partitioned.cu:215-231). On TPU the analogue is a Pallas
kernel operating on an in-HBM flag buffer: :mod:`mpi_acx_tpu.ops.flags`
provides ``pready`` / ``parrived`` / fused produce-and-signal kernels with
identical state-machine semantics (state values shared with the native
runtime, include/acx/state.h).

:mod:`mpi_acx_tpu.ops.attention` provides the blockwise-causal flash
attention kernel used by the model families — the MXU hot op.
"""

from mpi_acx_tpu.ops.flags import (  # noqa: F401
    AVAILABLE,
    RESERVED,
    PENDING,
    ISSUED,
    COMPLETED,
    CLEANUP,
    pready,
    pready_many,
    parrived,
    parrived_all,
    produce_and_pready,
)
from mpi_acx_tpu.ops.attention import (  # noqa: F401
    attention_reference,
    auto_attention,
    flash_attention,
    flash_attention_lse,
    select_attention,
)
