"""Int8 weight-only quantization for serving.

Decode is HBM-bandwidth-bound on RE-READING THE WEIGHTS every token
(BASELINE.md decode roofline: at B=8/GPT-2-125M the weight stream is
~40x the KV stream), so halving weight bytes — bf16 -> int8 + one f32
scale per output channel — roughly doubles the bandwidth roofline at a
small, measured quality cost. This is the serving-side counterpart of
the int8 gradient ring (parallel/quantized.py): same symmetric
per-block scheme, applied to the static weights instead of the wire.

Design: :func:`quantize_weights_int8` keeps the parameter pytree's
SHAPE — each quantized leaf w is replaced by its int8 quantization and
a broadcast-ready ``w + "_scale"`` companion leaf is added beside it.
The model blocks read every matmul weight through :func:`wread`, which
transparently dequantizes when a scale is present (XLA fuses the
int8->bf16 convert + multiply into the matmul's operand read, so HBM
traffic is the int8 bytes). Unquantized checkpoints hit the
``scale is None`` fast path, which is exactly the old
``lp[name].astype(dtype)``.

The embedding / unembedding stay bf16: the tied logits matmul sets
output quality directly and is one tensor, not a per-layer stream.

Supported entry points: the single-device serving stack — forward /
prefill / decode_step / generate for GPT-2 and Llama, speculative
decoding over them — AND tensor-parallel serving for both dense
families (plain and speculative): the TP shard fns re-layout each
``_scale`` companion alongside its weight, the spec trees gain
matching entries, and the TP layer ops read through :func:`wread`
(tp_inference). The MoE expert einsums have no wread path and REJECT
quantized expert weights loudly (moe_transformer
._reject_quantized_experts) rather than cast raw int8 codes without
their scales; MoE *attention* weights may be quantized (they ride the
shared GPT-2 ops).

The reference has no inference stack at all (SURVEY.md SS0); this
module exists for the framework goal's serving-perf axis.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable

import jax
import jax.numpy as jnp

# Per-family matmul weights worth streaming as int8 (contracted axis is
# second-to-last at every call site: y = x @ w).
GPT2_WEIGHTS = ("wqkv", "wo", "w1", "w2")
LLAMA_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def wread(lp: Dict[str, Any], name: str, dtype) -> jax.Array:
    """Read matmul weight ``name`` in compute ``dtype``, transparently
    dequantizing int8 weight-only quantization (``name + "_scale"``
    present -> q * s). The one read path every block uses, so a
    quantized and an unquantized checkpoint run the same code."""
    w = lp[name]
    s = lp.get(name + "_scale")
    if s is None:
        return w.astype(dtype)
    # Dequantize in f32 (the scale's dtype) BEFORE casting to compute
    # dtype: a bf16 scale would add ~0.4% error on top of the int8
    # rounding. XLA fuses convert+mul into the matmul's operand read.
    return (w.astype(jnp.float32) * s).astype(dtype)


def _quant_leaf(w: jax.Array):
    """Symmetric per-output-channel int8: scale = amax over the
    CONTRACTED axis (second-to-last; every call site computes x @ w),
    keepdims so the companion broadcasts in ``wread`` unchanged."""
    a = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    s = jnp.maximum(a, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s.astype(jnp.float32)


def quantize_weights_int8(params: Dict[str, Any],
                          names: Iterable[str]) -> Dict[str, Any]:
    """Quantize the named ``params["layers"]`` matmul weights to int8,
    adding ``<name>_scale`` companion leaves (leading layer axis
    preserved, so the decode layer scans carry them like any other
    leaf). Everything else — biases, norms, embeddings — is untouched.

    Use GPT2_WEIGHTS / LLAMA_WEIGHTS for ``names``, or any subset."""
    lay = dict(params["layers"])
    for name in names:
        q, s = _quant_leaf(lay[name])
        lay[name] = q
        lay[name + "_scale"] = s
    return dict(params, layers=lay)


def weight_bytes(params: Dict[str, Any]) -> int:
    """Total parameter bytes as stored — the numerator of the decode
    bandwidth roofline (bench.py uses this so the int8 row's roofline
    reflects the actual quantized stream)."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree.leaves(params))
